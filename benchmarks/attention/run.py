"""Attention kernel benchmark grid (`make bench-attn`).

The measurement behind ``ops.attention.ATTN_CROSSOVER_S``: fwd+bwd step time
for every (impl × seq × dtype × sparsity) cell, reported as µs/token and as
achieved FLOP/s against the chip's roofline (``telemetry/perf.py`` peaks).
Sparsity legs (dense / causal / sliding-window) matter because the in-tree
flash kernel's block lattice SKIPS fully-masked tiles — its useful-FLOP rate
holds while the einsum path still materializes (and masks) every score.

A second leg times the fp8-vs-bf16 llama train step (``dtype_recipe="fp8"``
routing QKV/O + MLP through ``ops.fp8.fp8_dot``) — the "kernel-dominated
train step" claim needs both the attention kernel AND the matmul recipe
measured on the same chip. Step-time wins only materialize on fp8-capable
MXUs (v5p+); on CPU/v5e the leg is a parity + plumbing check and the ratio
reads > 1.

Emits one JSON line (bench.py conventions). The ``guarded`` block feeds
``telemetry/regress.py`` (``*attn_kernel*`` / ``*fp8*step*`` lower-is-better,
``*mfu*`` higher-is-better specs).

```bash
python benchmarks/attention/run.py --steps 5
```
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from _common import detect_backend, emit


def _band_fraction(s: int, window) -> float:
    """Fraction of the S×S score matrix a mask leaves active."""
    if window is None:
        return 1.0
    w = min(window, s)
    return (w * s - w * (w - 1) / 2) / float(s * s)


def _attention_flops(b, h, s, d, active_fraction: float) -> float:
    """Useful fwd+bwd attention FLOPs per step: fwd = QKᵀ + PV (4·B·H·S²·D),
    bwd re-forms scores and produces dQ/dK/dV (≈2.5× fwd)."""
    return 3.5 * 4.0 * b * h * s * s * d * active_fraction


def _time_loop(fn, args, steps: int) -> float:
    import jax

    out = fn(*args)  # compile + warm
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / steps


def run_bench_attention(on_tpu: bool, steps: int = None) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu.ops.attention import dot_product_attention
    from accelerate_tpu.telemetry.perf import peaks_for_device

    if on_tpu:
        b, h, hkv, d = 8, 12, 6, 64
        seqs = (512, 1024, 2048)
        dtypes = (("bf16", jnp.bfloat16), ("f32", jnp.float32))
        impls = ("xla", "flash")
        steps = steps or 10
    else:
        # CPU-shaped: the xla path only (the Pallas interpreter is a
        # correctness tool, ~1000× off any perf signal) — the grid still
        # exercises every sparsity leg so regressions in the einsum path and
        # the mask plumbing are caught per-environment
        b, h, hkv, d = 2, 4, 2, 64
        seqs = (256, 512)
        dtypes = (("f32", jnp.float32),)
        impls = ("xla",)
        steps = steps or 3

    peaks = peaks_for_device()
    sparsities = lambda s: (
        ("dense", False, None),
        ("causal", True, None),
        ("window", True, max(s // 4, 128)),
    )

    def make_step(impl, causal, window):
        def loss(q, k, v):
            out = dot_product_attention(
                q, k, v, causal=causal, window=window, impl=impl
            )
            return jnp.sum(out.astype(jnp.float32) ** 2)

        return jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))

    grid = []
    for s in seqs:
        keys = jax.random.split(jax.random.PRNGKey(0), 3)
        for dname, dtype in dtypes:
            q = jax.random.normal(keys[0], (b, s, h, d), dtype)
            k = jax.random.normal(keys[1], (b, s, hkv, d), dtype)
            v = jax.random.normal(keys[2], (b, s, hkv, d), dtype)
            for sname, causal, window in sparsities(s):
                for impl in impls:
                    entry = {
                        "impl": impl,
                        "seq": s,
                        "dtype": dname,
                        "sparsity": sname,
                    }
                    try:
                        sec = _time_loop(
                            make_step(impl, causal, window), (q, k, v), steps
                        )
                    except Exception as e:
                        entry["error"] = f"{type(e).__name__}: {str(e)[:120]}"
                        grid.append(entry)
                        continue
                    frac = _band_fraction(s, window) * (
                        (s + 1) / (2.0 * s) if causal and window is None else 1.0
                    )
                    flops = _attention_flops(b, h, s, d, frac)
                    entry["us_per_token"] = round(sec / (b * s) * 1e6, 3)
                    entry["achieved_tflops"] = round(flops / sec / 1e12, 4)
                    entry["fraction_of_peak"] = round(flops / sec / peaks.flops, 4)
                    grid.append(entry)

    ok = [g for g in grid if "us_per_token" in g]
    if not ok:
        raise RuntimeError(f"every attention grid cell failed: {grid}")
    # the headline cell: best impl at the largest causal leg, bench dtype
    # (bf16 on TPU, f32 on CPU) — the regime training actually runs in
    s_top = max(g["seq"] for g in ok)
    head_pool = [
        g for g in ok
        if g["seq"] == s_top and g["sparsity"] == "causal" and g["dtype"] == dtypes[0][0]
    ] or ok
    best = min(head_pool, key=lambda g: g["us_per_token"])
    best_mfu = max(g["fraction_of_peak"] for g in ok)

    fp8_leg = _fp8_train_step_leg(on_tpu)

    out = {
        "metric": f"attention fwd+bwd µs/token (seq {best['seq']}, {best['impl']})",
        "value": best["us_per_token"],
        "unit": "us/token",
        "best": best,
        "grid": grid,
        "peak_flops": peaks.flops,
        "peak_nominal": peaks.nominal,
        "shape": {"batch": b, "heads": h, "kv_heads": hkv, "head_dim": d},
        "steps": steps,
        "fp8_train_step": fp8_leg,
        # regression-guarded (telemetry/regress.py: *attn_kernel* and
        # *fp8*step* lower-is-better, *mfu* higher-is-better)
        "guarded": {
            "attn_kernel_us_per_token": best["us_per_token"],
            "fp8_step_ms": fp8_leg["fp8_step_ms"],
            "attn_mfu_best_fraction": best_mfu,
        },
    }
    return out


def _fp8_train_step_leg(on_tpu: bool, steps: int = None) -> dict:
    """fp8-vs-bf16 llama train step: the ``dtype_recipe="fp8"`` knob routes
    QKV/O + MLP matmuls through ``fp8_dot``; the bf16 baseline runs the same
    step with bf16-cast params. Reports steady-state ms and final-loss
    relative delta (the parity envelope)."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu.models.transformer import LlamaConfig, init_llama, llama_loss
    from accelerate_tpu.ops.fp8 import make_fp8_optimizer

    if on_tpu:
        base = LlamaConfig(vocab_size=32000, dim=1024, n_layers=8, n_heads=16,
                           n_kv_heads=8, max_seq_len=1024, unroll_layers=False)
        bs, seq = 4, 1024
        steps = steps or 10
    else:
        base = LlamaConfig.tiny()
        bs, seq = 2, 128
        steps = steps or 3

    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, base.vocab_size, (bs, seq)), jnp.int32
    )
    batch = {"input_ids": ids}

    def run(recipe):
        cfg = dataclasses.replace(base, dtype_recipe=recipe)
        params = init_llama(cfg, jax.random.PRNGKey(0))
        if recipe is None:
            params = jax.tree_util.tree_map(
                lambda x: x.astype(jnp.bfloat16), params
            )
            tx = optax.sgd(1e-3)
        else:
            # meta leaves are replaced, not optimized (the same partition the
            # accelerator installs for mixed_precision="fp8")
            tx = make_fp8_optimizer(optax.sgd(1e-3), params)
        state = tx.init(params)

        @jax.jit
        def step(p, s, b):
            loss, grads = jax.value_and_grad(llama_loss)(p, b, cfg)
            updates, s = tx.update(grads, s, p)
            return optax.apply_updates(p, updates), s, loss

        sec = _time_loop(step, (params, state, batch), steps)
        _, _, loss = step(params, state, batch)
        return sec * 1e3, float(np.asarray(loss))

    bf16_ms, bf16_loss = run(None)
    fp8_ms, fp8_loss = run("fp8")
    return {
        "bf16_step_ms": round(bf16_ms, 3),
        "fp8_step_ms": round(fp8_ms, 3),
        "fp8_over_bf16": round(fp8_ms / bf16_ms, 3),
        "loss_rel_delta": round(abs(fp8_loss - bf16_loss) / max(abs(bf16_loss), 1e-9), 5),
        "seq": seq,
        "batch": bs,
    }


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=None,
                    help="timed iterations per grid cell (default 10 TPU / 3 CPU)")
    args = ap.parse_args()
    emit(run_bench_attention(on_tpu=detect_backend(), steps=args.steps))
