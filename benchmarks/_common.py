"""Shared plumbing for the user-runnable benchmark scripts: locate the repo,
decide TPU-vs-CPU honestly (killable probe), emit one JSON line."""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def detect_backend(probe_timeout: int = 120) -> bool:
    """True iff a real TPU answers (killable subprocess probe — a dead tunnel
    hangs inside backend init and must be killed from outside)."""
    from bench import _probe_backend_subprocess  # shared predicate

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
        return False
    ok, _ = _probe_backend_subprocess(probe_timeout)
    if not ok:
        import jax

        jax.config.update("jax_platforms", "cpu")
        print("TPU unreachable: running the CPU-shaped variant", file=sys.stderr)
    return ok


_FINGERPRINT = None


def env_fingerprint() -> dict:
    """THE environment fingerprint stamped into every bench payload (key
    ``env``): git sha, host, device kind/count, jax/jaxlib versions, python,
    nproc. The regression sentinel (``telemetry.regress``) groups payloads by
    this and REFUSES cross-environment comparisons — a v5 number vs a CPU
    number is not a regression, it is a different machine. Cached per
    process; device fields stay None until jax is already imported (probing
    here could hang on a dead TPU tunnel — ``detect_backend`` owns that)."""
    global _FINGERPRINT
    if _FINGERPRINT is None:
        import platform
        import subprocess

        fp = {
            "git_sha": None,
            "host": platform.node(),
            "python": platform.python_version(),
            "nproc": os.cpu_count(),
            "jax": None,
            "jaxlib": None,
            "device_kind": None,
            "device_count": None,
        }
        try:
            out = subprocess.run(
                ["git", "-C", REPO, "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=10,
            )
            fp["git_sha"] = out.stdout.strip() or None
        except Exception:
            pass
        if "jax" in sys.modules:
            try:
                import jax
                import jaxlib

                fp["jax"] = jax.__version__
                fp["jaxlib"] = getattr(jaxlib, "__version__", None)
                devices = jax.devices()
                fp["device_kind"] = devices[0].device_kind
                fp["device_count"] = len(devices)
            except Exception:
                pass
        _FINGERPRINT = fp
    return dict(_FINGERPRINT)


def emit(entry: dict) -> None:
    entry = dict(entry)
    entry.setdefault("env", env_fingerprint())
    print(json.dumps(entry), flush=True)


# THE percentile implementation lives in telemetry.metrics (nearest-rank,
# shared with the report CLI and the /metrics histogram plane) — the benches
# re-export it instead of carrying a private variant, so a bench's p99 and
# the report's p99 of the same numbers can never disagree.
from accelerate_tpu.telemetry.metrics import percentile  # noqa: E402,F401
