"""Shared plumbing for the user-runnable benchmark scripts: locate the repo,
decide TPU-vs-CPU honestly (killable probe), emit one JSON line."""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def detect_backend(probe_timeout: int = 120) -> bool:
    """True iff a real TPU answers (killable subprocess probe — a dead tunnel
    hangs inside backend init and must be killed from outside)."""
    from bench import _probe_backend_subprocess  # shared predicate

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
        return False
    ok, _ = _probe_backend_subprocess(probe_timeout)
    if not ok:
        import jax

        jax.config.update("jax_platforms", "cpu")
        print("TPU unreachable: running the CPU-shaped variant", file=sys.stderr)
    return ok


def emit(entry: dict) -> None:
    print(json.dumps(entry), flush=True)


# THE percentile implementation lives in telemetry.metrics (nearest-rank,
# shared with the report CLI and the /metrics histogram plane) — the benches
# re-export it instead of carrying a private variant, so a bench's p99 and
# the report's p99 of the same numbers can never disagree.
from accelerate_tpu.telemetry.metrics import percentile  # noqa: E402,F401
