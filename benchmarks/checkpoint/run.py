"""Checkpoint-stall microbench: step-time tax of periodic saves, sync vs async.

Runs a fixed-cadence "train" loop (per-step compute stand-in) over a params/
opt-state pytree of ``--mb`` megabytes and measures the p95 step time for
three variants:

- ``baseline``: no checkpointing at all,
- ``sync``:  ``save_state`` (blocking) every ``--every`` steps,
- ``async``: ``save_state(blocking=False)`` every ``--every`` steps.

The async writer hides the serialize+fsync+commit behind subsequent steps, so
its p95 should sit near the baseline while sync pays the full write on every
saving step. ``value`` is the exposed-stall ratio: how much of the sync
save's extra step time the async path still exposes (lower is better; the
acceptance bar in ISSUE 5 is < 0.20). Emits one JSON line per the bench.py
conventions.
"""

import argparse
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from _common import detect_backend, emit, percentile as _percentile


def _params(mb: float):
    import numpy as np

    n = max(1, int(mb * (1 << 20) / 4 / 2))  # two leaves
    return {
        "w": np.random.default_rng(0).standard_normal(n).astype(np.float32),
        "m": np.zeros(n, dtype=np.float32),
    }


def _measure(steps, compute_s, every, mode, mb):
    """One loop; returns per-step wall times and total save-call time."""
    from accelerate_tpu import Accelerator, CheckpointConfig
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    from accelerate_tpu.utils.dataclasses import ProjectConfiguration

    workdir = tempfile.mkdtemp(prefix=f"bench_ckpt_{mode}_")
    try:
        acc = Accelerator(
            project_config=ProjectConfiguration(
                project_dir=workdir, automatic_checkpoint_naming=True, total_limit=2
            ),
            checkpoint_config=CheckpointConfig(async_save=(mode == "async")),
        )
        params = _params(mb)
        acc.save_state(params=params, blocking=True)  # warmup: backend + first dirs
        step_times = []
        save_call_s = 0.0
        for step in range(steps):
            t0 = time.monotonic()
            time.sleep(compute_s)  # the jitted step the writer must hide under
            if mode != "baseline" and (step + 1) % every == 0:
                s0 = time.monotonic()
                acc.save_state(params=params, blocking=(mode == "sync"))
                save_call_s += time.monotonic() - s0
            step_times.append(time.monotonic() - t0)
        t0 = time.monotonic()
        acc.wait_for_checkpoint()
        drain_s = time.monotonic() - t0
        acc.end_training()
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    return {
        "p50_step_ms": round(_percentile(step_times, 50) * 1e3, 3),
        "p95_step_ms": round(_percentile(step_times, 95) * 1e3, 3),
        "max_step_ms": round(max(step_times) * 1e3, 3),
        "wall_s": round(sum(step_times), 4),
        "save_call_s": round(save_call_s, 4),
        "drain_s": round(drain_s, 4),
        "saves": (steps // every) if mode != "baseline" else 0,
    }


def run_bench_checkpoint(
    on_tpu: bool,
    steps: int = 75,
    compute_ms: float = 30.0,
    every: int = 25,
    mb: float = 16.0,
) -> dict:
    # note: hiding a write takes compute to hide under — the defaults keep
    # every*compute_ms above this box's fsync'd write time for `mb` MiB; a
    # cadence faster than disk throughput shows up as back-pressure stall in
    # BOTH the sync and async variants (and in the telemetry report)
    baseline = _measure(steps, compute_ms / 1e3, every, "baseline", mb)
    sync = _measure(steps, compute_ms / 1e3, every, "sync", mb)
    async_ = _measure(steps, compute_ms / 1e3, every, "async", mb)
    # exposed stall = extra whole-loop wall over baseline, charged to saving
    sync_stall = max(1e-9, sync["wall_s"] - baseline["wall_s"])
    async_stall = max(0.0, async_["wall_s"] - baseline["wall_s"])
    return {
        "bench": "checkpoint",
        "unit": "exposed_stall_ratio(async/sync)",
        "value": round(async_stall / sync_stall, 4),
        "baseline": baseline,
        "sync": sync,
        "async": async_,
        "p95_async_over_baseline": round(
            async_["p95_step_ms"] / max(baseline["p95_step_ms"], 1e-9), 3
        ),
        "steps": steps,
        "compute_ms": compute_ms,
        "save_every": every,
        "state_mb": mb,
        "on_tpu": on_tpu,
    }


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=75)
    ap.add_argument("--compute-ms", type=float, default=30.0,
                    help="per-step compute the async writer hides under")
    ap.add_argument("--every", type=int, default=25, help="save_state cadence in steps")
    ap.add_argument("--mb", type=float, default=16.0, help="params+opt-state size in MiB")
    args = ap.parse_args()
    emit(
        run_bench_checkpoint(
            on_tpu=detect_backend(),
            steps=args.steps,
            compute_ms=args.compute_ms,
            every=args.every,
            mb=args.mb,
        )
    )
