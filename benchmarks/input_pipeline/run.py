"""Input-pipeline microbench: synchronous vs prefetched iteration.

Measures end-to-end samples/sec of a ``DataLoaderShard`` loop whose dataset
charges a per-item host cost (tokenization/disk stand-in) while each step
pays a fixed compute cost — the exact shape the async prefetch pipeline
(``docs/data_pipeline.md``) is built to hide. Emits one JSON line matching
the bench.py conventions (``unit``/``value`` + per-variant detail), so the
driver can track the overlap win across rounds.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from _common import detect_backend, emit


class _SleepyDataset:
    def __init__(self, n, feat, delay_s):
        self.n = n
        self.feat = feat
        self.delay_s = delay_s

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        import numpy as np

        time.sleep(self.delay_s)
        return {"x": np.full((self.feat,), i, dtype=np.float32)}


def _measure(steps, batch_size, feat, item_delay_s, compute_s, depth):
    from accelerate_tpu.data_loader import DataLoader, DataLoaderShard

    dl = DataLoaderShard(
        DataLoader(_SleepyDataset(batch_size * steps, feat, item_delay_s), batch_size=batch_size),
        prefetch_depth=depth,
    )
    it = iter(dl)
    t0 = time.monotonic()
    for _ in range(steps):
        next(it)
        time.sleep(compute_s)  # the "jitted step" the pipeline hides under
    wall = time.monotonic() - t0
    it.close()
    return {
        "samples_per_s": round(batch_size * steps / wall, 2),
        "wall_s": round(wall, 4),
        "step_ms": round(wall / steps * 1e3, 3),
    }


def run_bench_input_pipeline(
    on_tpu: bool,
    steps: int = 30,
    batch_size: int = 16,
    feat: int = 64,
    item_delay_ms: float = 1.0,
    compute_ms: float = 10.0,
    depth: int = 2,
) -> dict:
    sync = _measure(steps, batch_size, feat, item_delay_ms / 1e3, compute_ms / 1e3, 0)
    prefetch = _measure(steps, batch_size, feat, item_delay_ms / 1e3, compute_ms / 1e3, depth)
    return {
        "bench": "input_pipeline",
        "unit": "speedup(prefetch/sync)",
        "value": round(prefetch["samples_per_s"] / max(sync["samples_per_s"], 1e-9), 3),
        "sync": sync,
        "prefetch": prefetch,
        "prefetch_depth": depth,
        "steps": steps,
        "batch_size": batch_size,
        "item_delay_ms": item_delay_ms,
        "compute_ms": compute_ms,
        "on_tpu": on_tpu,
    }


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--feat", type=int, default=64)
    ap.add_argument("--item-delay-ms", type=float, default=1.0,
                    help="per-item host cost the producer must hide")
    ap.add_argument("--compute-ms", type=float, default=10.0,
                    help="per-step compute the pipeline overlaps with")
    ap.add_argument("--depth", type=int, default=2, help="prefetch_depth for the async variant")
    args = ap.parse_args()
    emit(
        run_bench_input_pipeline(
            on_tpu=detect_backend(),
            steps=args.steps,
            batch_size=args.batch_size,
            feat=args.feat,
            item_delay_ms=args.item_delay_ms,
            compute_ms=args.compute_ms,
            depth=args.depth,
        )
    )
