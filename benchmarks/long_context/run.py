"""Long-context training benchmark (reference CP/ALST scaling claims,
``docs/source/concept_guides/{context,sequence}_parallelism.md``): decoder
train step at --seq tokens with the flash-attention ladder (flash+light remat
→ flash+full remat → einsum) — measures the best config that runs and
reports which one won, so flash-vs-einsum is decided by measurement."""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from _common import detect_backend, emit

if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=None,
                    help="sequence length (default: ACCELERATE_BENCH_LONGCTX_SEQ "
                         "env, else 8192 on TPU / 256 on CPU)")
    args = ap.parse_args()
    if args.seq is not None:
        # an explicit CLI value beats any ambient env setting
        os.environ["ACCELERATE_BENCH_LONGCTX_SEQ"] = str(args.seq)
    from bench import run_bench_longcontext

    emit(run_bench_longcontext(on_tpu=detect_backend()))
