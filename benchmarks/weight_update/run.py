"""Fused ZeRO-1 weight-update microbench (ISSUE 9 acceptance path, also
`make bench-zero1`).

Trains the same pure-DP model under ZeRO-1 twice on the same mesh:

- **unfused** — the annotation path (``ACCELERATE_ZERO1_FUSED=0``):
  ``zero1_state_specs`` shards the moment buffers, GSPMD partitions the update;
- **fused** — the bucketed path (``parallel/weight_update.py``): grads
  reduce-scattered per bucket, 1/N shard-local optimizer math, all-gathered
  params, all inside the jitted step.

Emits one JSON line (bench.py conventions, last line on stdout) with the
fused/unfused step-time ratio, optimizer-state bytes per replica for each leg,
and — when a trace window is armed (``--trace-every``) — the
``comms_overlap_ratio`` from the PR 7 trace summary: how much of the fused
step's collective time the latency-hiding scheduler buried under compute.
On the CPU backend the mesh is 8 virtual devices and the *ratio* fields are
the meaningful signal; on a real TPU slice the step times are, too.
"""

import argparse
import contextlib
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

VIRTUAL_DEVICES = 8


def _ensure_virtual_devices() -> None:
    """8 virtual CPU devices — must land in XLA_FLAGS before jax's backend
    initializes, so callers import this module before touching jax."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={VIRTUAL_DEVICES}"
        ).strip()


def _bytes_per_replica(tree) -> int:
    import jax

    dev0 = jax.devices()[0]
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        for shard in getattr(leaf, "addressable_shards", ()):
            if shard.device == dev0:
                total += shard.data.nbytes
    return total


def run_bench_weight_update(
    on_tpu: bool,
    steps: int = 20,
    dim: int = 512,
    layers: int = 4,
    trace_every: int = 0,
    keep_artifacts: bool = False,
) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu import (
        Accelerator,
        DeepSpeedPlugin,
        ParallelismConfig,
        telemetry,
    )
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils import patch_environment
    from accelerate_tpu.utils.dataclasses import ProfileConfig

    n = len(jax.devices())

    def make_params():
        rng = np.random.default_rng(0)
        return {
            f"layer{i}": {
                "w": jnp.asarray(rng.normal(size=(dim, dim)) * dim**-0.5, jnp.float32),
                "b": jnp.zeros((dim,), jnp.float32),
            }
            for i in range(layers)
        }

    def loss_fn(p, batch):
        x = batch["x"]
        for i in range(layers):
            x = jnp.tanh(x @ p[f"layer{i}"]["w"] + p[f"layer{i}"]["b"])
        return jnp.mean(x**2)

    batch = {
        "x": jnp.asarray(
            np.random.default_rng(1).normal(size=(max(16, 2 * n), dim)), jnp.float32
        )
    }

    workdir = tempfile.mkdtemp(prefix="bench_zero1_")

    def _null():
        return contextlib.nullcontext()

    def leg(fused: bool) -> dict:
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        env = {} if fused else {"ACCELERATE_ZERO1_FUSED": "0"}
        handlers = []
        if fused and trace_every:
            handlers.append(
                ProfileConfig(
                    trace_every=trace_every,
                    trace_steps=2,  # CPU 1-step windows can close before TraceMe flush
                    output_trace_dir=os.path.join(workdir, "trace"),
                )
            )
        with patch_environment(**env) if env else _null():
            acc = Accelerator(
                deepspeed_plugin=DeepSpeedPlugin(zero_stage=1),
                parallelism_config=ParallelismConfig(dp_replicate_size=n),
                rng_seed=0,
                kwargs_handlers=handlers or None,
            )
            params, opt = acc.prepare(make_params(), optax.adam(1e-3))
        step = acc.prepare_train_step(loss_fn, opt)
        state = opt.opt_state
        opt_bytes = _bytes_per_replica(state)
        opt_global = sum(
            getattr(leaf, "nbytes", 0) for leaf in jax.tree_util.tree_leaves(state)
        )
        # warmup: compile + one steady-state dispatch
        for _ in range(2):
            params, state, m = step(params, state, batch)
            float(np.asarray(m["loss"]))
        times = []
        for _ in range(steps):
            t0 = time.perf_counter()
            params, state, m = step(params, state, batch)
            # value fetch forces completion inside the timed window (and inside
            # any open trace window)
            loss = float(np.asarray(m["loss"]))
            times.append(time.perf_counter() - t0)
        acc.end_training()
        return {
            "fused": bool(opt.fused_zero1),
            "step_ms": round(float(np.median(times)) * 1e3, 3),
            "p95_step_ms": round(float(np.percentile(times, 95)) * 1e3, 3),
            "opt_state_bytes_per_replica": opt_bytes,
            # fraction of the full (replicated-equivalent) state one replica
            # holds — the ZeRO-1 memory claim; ~1/n_devices plus scalar leaves
            "opt_state_fraction": round(opt_bytes / max(opt_global, 1), 4),
            "final_loss": round(loss, 6),
        }

    telemetry_dir = os.path.join(workdir, "telemetry")
    overlap = None
    collective_bytes_per_step = None
    try:
        unfused = leg(fused=False)
        if trace_every:
            telemetry.enable(telemetry_dir)
        try:
            fused = leg(fused=True)
        finally:
            if trace_every:
                telemetry.disable()
        if trace_every:
            from accelerate_tpu.telemetry.report import build_report

            rep = build_report([telemetry_dir])
            trace = (rep.get("performance") or {}).get("trace") or {}
            overlap = trace.get("comms_overlap_ratio")
            comms = (rep.get("comms") or {}).get("by_op") or {}
            rs = comms.get("compiled:reduce_scatter") or {}
            if rs.get("calls"):
                collective_bytes_per_step = rs.get("bytes", 0) // rs["calls"]
    finally:
        if not keep_artifacts:
            shutil.rmtree(workdir, ignore_errors=True)

    n_params = layers * (dim * dim + dim)
    return {
        "bench": "weight_update",
        "unit": "step_time_ratio(fused/unfused)",
        "value": round(fused["step_ms"] / max(unfused["step_ms"], 1e-9), 4),
        "fused": fused,
        "unfused": unfused,
        "opt_state_ratio": round(
            fused["opt_state_bytes_per_replica"]
            / max(unfused["opt_state_bytes_per_replica"], 1),
            4,
        ),
        "overlap_ratio": overlap,
        "collective_bytes_per_step": collective_bytes_per_step,
        "n_devices": n,
        "n_params": n_params,
        "steps": steps,
        "on_tpu": on_tpu,
        **({"artifacts": workdir} if keep_artifacts else {}),
    }


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--trace-every", type=int, default=8,
                    help="arm a two-step jax.profiler window every N fused steps "
                         "(0 disables tracing and the overlap_ratio field)")
    ap.add_argument("--keep-artifacts", action="store_true")
    args = ap.parse_args()
    # decide backend BEFORE jax initializes: virtual devices only help the CPU
    # emulation; a real TPU slice brings its own chips
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        _ensure_virtual_devices()
        from _common import detect_backend, emit

        on_tpu = detect_backend()
    else:
        from _common import detect_backend, emit

        on_tpu = detect_backend()
        if not on_tpu:
            print(
                "warning: CPU fallback after backend init — virtual device "
                "count could not be raised; mesh may be 1-wide",
                file=sys.stderr,
            )
    emit(
        run_bench_weight_update(
            on_tpu=on_tpu,
            steps=args.steps,
            dim=args.dim,
            layers=args.layers,
            trace_every=args.trace_every,
            keep_artifacts=args.keep_artifacts,
        )
    )
