"""FSDP-scale LM training benchmark (reference ``benchmarks/fsdp2``):
GPT-2-large-scale (774M) decoder train step, adafactor + remat ladder.
Multi-chip FSDP sharding itself is validated by ``__graft_entry__.
dryrun_multichip``; this measures the per-chip building block."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from _common import detect_backend, emit

from bench import run_bench_fsdp_lm

if __name__ == "__main__":
    emit(run_bench_fsdp_lm(on_tpu=detect_backend()))
