"""Big-model inference benchmark (reference ``benchmarks/big_model_inference``
README table: load seconds + seconds/token): llama-1B-class kv-cache greedy
generation, bf16 resident weights."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from _common import detect_backend, emit

from bench import run_bench_inference

if __name__ == "__main__":
    emit(run_bench_inference(on_tpu=detect_backend()))
