"""Compile-time benches.

Default mode — **restart/boot cold vs warm** (`make bench-compile`): the
zero-cold-start recovery numbers the persistent compile cache
(``accelerate_tpu/compile_cache``) exists for. Two subprocess pairs against
one shared cache directory:

- ``train``: restart-to-first-step through the real Accelerator stack —
  generation 0 cold (compiles + exports), generation 1 warm (probes the
  cache before tracing and runs the deserialized executable);
- ``serve``: replica-boot-to-first-token through a ``ReplicaSpec``-built
  serving engine — cold warmup compiles the whole bucket lattice, warm
  warmup loads it.

The payload carries both wall times per leg plus the ``compile_cache``
telemetry counts (hit/miss/store/corrupt), so a "warm" leg that silently
recompiled is visible as miss>0 instead of a fake win.

``--regional`` keeps the original bench: regional (scan-over-layers) vs
fully unrolled compilation (reference ``benchmarks/torch.compile``), via
``bench.run_bench_compile_time``.
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from _common import detect_backend, emit

HERE = os.path.dirname(os.path.abspath(__file__))
CHILD = os.path.join(HERE, "restart_child.py")


def _cache_event_counts(telemetry_dir: str) -> dict:
    """Aggregate ``compile_cache`` record counts from one leg's telemetry."""
    counts: dict = {}
    try:
        names = os.listdir(telemetry_dir)
    except OSError:
        return counts
    for name in names:
        if not (name.startswith("events-rank") and name.endswith(".jsonl")):
            continue
        with open(os.path.join(telemetry_dir, name)) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("kind") != "compile_cache":
                    continue
                ev = rec.get("event")
                counts[ev] = counts.get(ev, 0) + 1
    return counts


def _run_leg(mode: str, cache_dir: str, telemetry_dir: str, generation: int,
             timeout: int = 300) -> dict:
    os.makedirs(telemetry_dir, exist_ok=True)
    res = subprocess.run(
        [
            sys.executable, CHILD, "--mode", mode,
            "--cache-dir", cache_dir,
            "--telemetry-dir", telemetry_dir,
            "--generation", str(generation),
        ],
        capture_output=True, text=True, timeout=timeout, env=dict(os.environ),
    )
    if res.returncode != 0:
        raise RuntimeError(
            f"restart bench child ({mode}, gen {generation}) failed "
            f"rc={res.returncode}\n{res.stderr[-2000:]}"
        )
    child = json.loads(res.stdout.strip().splitlines()[-1])
    child["compile_cache_events"] = _cache_event_counts(telemetry_dir)
    return child


def run_restart_bench(on_tpu: bool, root: str, modes: "tuple[str, ...]" = ("train", "serve")) -> dict:
    cache_dir = os.path.join(root, "cache")
    os.makedirs(cache_dir, exist_ok=True)
    legs = {}
    metrics = {"train": "restart_to_first_step_s", "serve": "boot_to_first_token_s"}
    for mode, metric in ((m, metrics[m]) for m in modes):
        cold = _run_leg(mode, cache_dir, os.path.join(root, f"tel-{mode}-cold"), 0)
        warm = _run_leg(mode, cache_dir, os.path.join(root, f"tel-{mode}-warm"), 1)
        legs[mode] = {
            "metric": metric,
            "cold_s": cold[metric],
            "warm_s": warm[metric],
            "speedup": round(cold[metric] / max(warm[metric], 1e-9), 3),
            "cold_cache_events": cold["compile_cache_events"],
            "warm_cache_events": warm["compile_cache_events"],
        }
        # bitwise sanity: the warm generation must produce the same first
        # result as the cold one (a wrong executable load would show here)
        if mode == "serve":
            legs[mode]["first_token_match"] = cold["first_token"] == warm["first_token"]
    first = next(iter(legs.values()))
    return {
        "bench": "compile_time_restart",
        "unit": "speedup(cold/warm restart-to-first-step)",
        "value": legs.get("train", first)["speedup"],
        "on_tpu": on_tpu,
        **legs,
    }


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--regional", action="store_true",
                        help="the original regional-vs-unrolled compile bench")
    parser.add_argument("--keep-dir", default=None,
                        help="run the restart bench under this dir (kept)")
    parser.add_argument("--modes", default="train,serve",
                        help="comma list of restart legs (train, serve)")
    args = parser.parse_args()
    if args.regional:
        from bench import run_bench_compile_time

        emit(run_bench_compile_time(on_tpu=detect_backend()))
    else:
        on_tpu = detect_backend()
        modes = tuple(m.strip() for m in args.modes.split(",") if m.strip())
        if args.keep_dir:
            os.makedirs(args.keep_dir, exist_ok=True)
            emit(run_restart_bench(on_tpu, args.keep_dir, modes))
        else:
            with tempfile.TemporaryDirectory() as tmp:
                emit(run_restart_bench(on_tpu, tmp, modes))
