"""Regional-vs-full compilation benchmark (reference ``benchmarks/
torch.compile`` README: 5-9x compile-time wins on Llama 1B-13B): scan-over-
stacked-layers (one layer body compiled once) vs fully unrolled, plus the
steady-state step time both ways — regional compilation must not cost
runtime."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from _common import detect_backend, emit

from bench import run_bench_compile_time

if __name__ == "__main__":
    emit(run_bench_compile_time(on_tpu=detect_backend()))
