"""One process generation of the restart bench: build the real stack, do the
first unit of useful work, report how long that took from process entry.

Two modes, matching the two recovery paths the compile cache exists for:

- ``train``: Accelerator + prepared jitted train step (the elastic
  supervisor's respawn path) — reports ``restart_to_first_step_s``, the
  wall time from entry to the first completed optimizer step;
- ``serve``: a ``ReplicaSpec``-built serving engine (the router's
  replacement-replica path) — reports ``boot_to_first_token_s``, entry to
  the first token of the first request (warmup included: a replica is not
  useful until its lattice is compiled).

The parent (``run.py``) runs each mode twice against the same cache
directory — generation 0 cold (populates), generation 1 warm (loads) — and
reads the ``compile_cache`` telemetry records to prove the warm leg actually
hit instead of quietly recompiling.
"""

import argparse
import json
import os
import sys
import time

_T_ENTRY = time.monotonic()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="restart_child")
    parser.add_argument("--mode", choices=("train", "serve"), required=True)
    parser.add_argument("--cache-dir", default="")
    parser.add_argument("--telemetry-dir", default="")
    parser.add_argument("--generation", type=int, default=0)
    args = parser.parse_args(argv)

    if args.cache_dir:
        os.environ["ACCELERATE_COMPILE_CACHE_DIR"] = args.cache_dir
    if args.generation:
        os.environ["ACCELERATE_RESTART_GENERATION"] = str(args.generation)
    if args.telemetry_dir:
        os.environ["ACCELERATE_TELEMETRY"] = "1"
        os.environ["ACCELERATE_TELEMETRY_DIR"] = args.telemetry_dir

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

    import jax  # noqa: E402  (env must be set before backends init)

    from accelerate_tpu.telemetry import events as tel

    # the serve path builds an engine without an Accelerator, which is what
    # normally honors the env kill switch — do it explicitly here so the
    # compile_cache records land in this leg's telemetry dir either way
    tel.maybe_enable_from_env()

    out = {"mode": args.mode, "generation": args.generation}
    if args.mode == "train":
        import numpy as np
        import optax

        import jax.numpy as jnp
        from accelerate_tpu import Accelerator

        acc = Accelerator()
        # a few chained matmuls so the step's XLA compile is a real cost the
        # warm leg visibly skips (a 2-matrix toy compiles in noise)
        params = {
            "w1": jnp.zeros((64, 128), jnp.float32),
            "w2": jnp.zeros((128, 128), jnp.float32),
            "w3": jnp.zeros((128, 8), jnp.float32),
        }
        params, opt = acc.prepare(params, optax.adam(1e-2))

        def loss_fn(p, batch):
            h = jnp.tanh(batch["x"] @ p["w1"])
            h = jnp.tanh(h @ p["w2"])
            return jnp.mean((h @ p["w3"]) ** 2)

        step = acc.prepare_train_step(loss_fn, opt)
        batch = {"x": jnp.asarray(np.ones((32, 64), np.float32))}
        params, opt_state, metrics = step(params, opt.opt_state, batch)
        jax.block_until_ready(params)
        out["restart_to_first_step_s"] = round(time.monotonic() - _T_ENTRY, 4)
        out["loss"] = float(metrics["loss"])
        acc.end_training()
    else:
        import numpy as np

        from accelerate_tpu.serving.replica import ReplicaSpec

        spec = ReplicaSpec(
            model=dict(
                vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
                ffn_dim=64, max_seq_len=128,
            ),
            num_blocks=17,
            block_size=8,
            max_slots=2,
            max_blocks_per_seq=4,
            slot_buckets=(1, 2),
            block_buckets=(4,),
            prefill_buckets=(16,),
            param_dtype="float32",
            compile_cache_dir=args.cache_dir or None,
        )
        engine = spec.build_engine()
        engine.warmup()
        req = engine.submit(np.arange(1, 9, dtype=np.int32), 3, rng_seed=0)
        while not req.generated:
            engine.step()
        out["boot_to_first_token_s"] = round(time.monotonic() - _T_ENTRY, 4)
        out["first_token"] = int(req.generated[0])
        out["cache_stats"] = engine.cache_stats

    tel.hard_flush()
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
