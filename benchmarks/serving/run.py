"""Serving microbench: continuous vs static batching under Poisson load.

Replays ONE seeded open-loop workload — Poisson arrivals (exponential
inter-arrival gaps measured in engine steps), uniformly random prompt and
completion lengths — through the :class:`~accelerate_tpu.serving.engine.
ServingEngine` twice:

- ``continuous``: in-flight batching — requests join the running batch at
  step granularity, finished slots are backfilled immediately;
- ``static``: gang admission — a batch is admitted only into an idle engine
  and drained to the LAST member's completion before the next forms (the
  classic serving baseline continuous batching exists to beat).

Both legs share the warmed bucket lattice, so every timed step runs
compiled code; the ratio isolates scheduling, not compilation. Reports
aggregate generated tok/s (wall), mean batch occupancy, and p50/p99
per-request latency + TTFT (arrival -> finish, wall). Emits one JSON line
per the bench.py conventions; ``make bench-serve`` runs it, and bench.py's
``serving`` config carries it in the round payload.

The **replicated leg** (ISSUE 12) drains the same seeded workload through
the ``ServingRouter`` over 1 and N thread-backed replicas (aggregate tok/s
scaling), then once more with one replica killed mid-load: zero requests
may be lost, the kill run's outputs must be bitwise-identical to the
unkilled run (token-exact failover resume), and the p99 shows the failover
latency tax.

The **disaggregated leg** (ISSUE 16) replays a long-prompt-heavy Poisson
ramp through a monolithic 1-replica router and through the 1-prefill +
1-decode ``DisaggRouter`` with the SLO autoscaler armed: ≥1 decode
scale-up must fire mid-load, the joiner must boot warm off the pre-shipped
compile cache (``join_compiles == 0``), outputs must stay bitwise-identical
to the monolith, and the payload carries the ttft/latency p99 across the
scale transition.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from _common import detect_backend, emit, percentile as _percentile


def build_workload(n_requests, seed, prompt_lens, new_tokens, rate, vocab_size,
                   shared_len=0):
    """Seeded open-loop arrival schedule: [(arrival_step, prompt, max_new)].
    ``rate`` is mean arrivals per engine step (Poisson: exponential gaps).
    ``shared_len > 0`` prepends one shared head of that many tokens to every
    prompt (``prompt_lens`` then sizes the private suffix) — the system-prompt
    workload shape automatic prefix caching exists to exploit."""
    import numpy as np

    rng = np.random.default_rng(seed)
    shared = rng.integers(0, vocab_size, (shared_len,)).astype(np.int32)
    t = 0.0
    workload = []
    for _ in range(n_requests):
        t += rng.exponential(1.0 / rate)
        suffix = rng.integers(0, vocab_size, (int(rng.integers(*prompt_lens)),))
        prompt = np.concatenate([shared, suffix.astype(np.int32)])
        workload.append((int(t), prompt, int(rng.integers(*new_tokens))))
    return workload


def _drive(engine, workload):
    """Open-loop drive: submit each request at its arrival step, step the
    engine while work is live, idle-tick otherwise. Returns (terminal
    requests partitioned FINISHED/other, wall seconds)."""
    from accelerate_tpu.serving import RequestStatus

    terminal = []
    next_req = 0
    step = 0
    t0 = time.monotonic()
    while next_req < len(workload) or not engine.scheduler.idle():
        while next_req < len(workload) and workload[next_req][0] <= step:
            _, prompt, max_new = workload[next_req]
            engine.submit(prompt, max_new, rng_seed=next_req)
            next_req += 1
        if engine.scheduler.idle():
            step += 1  # idle tick: nothing due yet, no device work
            continue
        terminal.extend(engine.step())
        step += 1
    wall = time.monotonic() - t0
    finished = [r for r in terminal if r.status is RequestStatus.FINISHED]
    other = [r for r in terminal if r.status is not RequestStatus.FINISHED]
    return finished, other, wall


def run_leg(params, config, workload, *, continuous, max_slots, num_blocks,
            block_size, lattice):
    """One scheduling policy over the shared workload; returns its metrics."""
    from accelerate_tpu.serving import ServingEngine

    engine = ServingEngine(
        params, config, num_blocks=num_blocks, block_size=block_size,
        max_slots=max_slots, lattice=lattice, continuous=continuous,
    )
    engine.warmup()  # all buckets compiled before the clock starts
    # step() also returns REJECTED requests (pool/lattice misconfiguration):
    # keep them out of the throughput/latency aggregates — and out of the
    # continuous/static comparison — but report them (a silently shrunken
    # workload would fake the ratio)
    completed, rejected, wall = _drive(engine, workload)
    tokens = sum(len(r.generated) for r in completed)
    latencies = [r.finish_t - r.arrival_t for r in completed]
    ttfts = [r.first_token_t - r.arrival_t for r in completed if r.first_token_t]
    stats = engine.stats()
    return {
        "completed": len(completed),
        "rejected": len(rejected),
        "tokens": tokens,
        "wall_s": round(wall, 4),
        "tokens_per_s": round(tokens / max(wall, 1e-9), 2),
        "engine_steps": stats["steps"],
        "mean_occupancy": stats["mean_occupancy"],
        "preemptions": stats["preemptions"],
        "p50_latency_ms": round(_percentile(latencies, 50) * 1e3, 2),
        "p99_latency_ms": round(_percentile(latencies, 99) * 1e3, 2),
        "p50_ttft_ms": round(_percentile(ttfts, 50) * 1e3, 2),
        "continuous": continuous,
    }


def _drain_through_router(spec, workload, *, n_replicas, kill_after=None,
                          health_timeout_s=10.0, traced=False):
    """Drain the whole workload as a backlog through a router over
    ``n_replicas`` thread-backed replicas; optionally SIGKILL-equivalent one
    replica after ``kill_after`` completions (abrupt: in-flight work is
    failed over with token-exact resume). Returns the leg metrics plus every
    request's output tokens so the kill leg can be parity-checked against
    the unkilled one.

    ``traced=True`` arms request-scoped tracing (telemetry/tracing.py) for
    the leg and reports ``span_trees_complete``: every FINISHED request must
    carry a gap-free admission→dispatch→prefill→decode span tree, failover
    hops included — the ISSUE 15 acceptance invariant, measured on the same
    workload the untraced legs time."""
    import time as _time

    from accelerate_tpu.serving import (
        AdmissionController,
        LocalReplica,
        RouterRequestStatus,
        ServingRouter,
    )
    from accelerate_tpu.telemetry import tracing as _tracing

    if traced:
        _tracing.arm(1.0)
    replicas = [LocalReplica(f"r{i}", spec) for i in range(n_replicas)]
    router = ServingRouter(
        replicas,
        # the whole workload is submitted as one backlog: size the queue so
        # the throughput legs never shed (shedding is admission.py's job and
        # has its own tests; here it would just shrink the measured work)
        admission=AdmissionController(max_queue=len(workload) + 1),
        health_timeout_s=health_timeout_s,
    )
    try:
        router.wait_ready()
        t0 = _time.monotonic()
        reqs = [
            router.submit(prompt, max_new, rng_seed=i)
            for i, (_, prompt, max_new) in enumerate(workload)
        ]
        killed = False
        # every-request-terminal, not a poll-return count: SHED finalizes at
        # submit time and never appears in poll()'s terminal list
        while not all(r.status.terminal for r in reqs):
            router.poll()
            finished = sum(
                1 for r in reqs if r.status is RouterRequestStatus.FINISHED
            )
            if kill_after is not None and not killed and finished >= kill_after:
                router.replicas["r0"].kill()
                killed = True
            _time.sleep(0.001)
            if _time.monotonic() - t0 > 600:
                raise RuntimeError("replicated leg wedged (>600s)")
        wall = _time.monotonic() - t0
        completed = [r for r in reqs if r.status is RouterRequestStatus.FINISHED]
        tokens = sum(len(r.generated) for r in completed)
        latencies = [r.finish_t - r.arrival_t for r in completed]
        leg = {
            "replicas": n_replicas,
            "completed": len(completed),
            "lost": len(reqs) - len(completed),
            "tokens": tokens,
            "wall_s": round(wall, 4),
            "tokens_per_s": round(tokens / max(wall, 1e-9), 2),
            "failovers": router.failovers,
            "p50_latency_ms": round(_percentile(latencies, 50) * 1e3, 2),
            "p99_latency_ms": round(_percentile(latencies, 99) * 1e3, 2),
            "outputs": [[int(t) for t in r.generated] for r in reqs],
        }
        if traced:
            broken = [
                r.rid for r in completed
                if _tracing.validate_span_tree(r.trace_spans)
            ]
            retried = [r for r in completed if r.retries > 0]
            lineage = all(
                sum(1 for s in r.trace_spans if s["name"] == "dispatch") >= 2
                for r in retried
            )
            leg["traced"] = True
            leg["span_trees_complete"] = not broken and lineage
            leg["broken_span_trees"] = len(broken)
        return leg
    finally:
        router.close()
        if traced:
            _tracing.disarm()


def run_bench_replicated(
    on_tpu: bool,
    requests: int = 16,
    seed: int = 0,
    n_replicas: int = 2,
    max_slots: int = 4,
    num_blocks: int = 49,
    block_size: int = 8,
) -> dict:
    """The router leg (ISSUE 12): the SAME seeded workload drained through 1
    replica, through ``n_replicas``, and through ``n_replicas`` with one
    replica killed mid-load. Reports aggregate tok/s scaling, the kill leg's
    p99 + failover count, and whether the kill leg's outputs are bitwise
    identical to the unkilled run (greedy decode is deterministic, so any
    difference means failover resume corrupted a stream)."""
    import dataclasses

    from accelerate_tpu.models import LlamaConfig
    from accelerate_tpu.serving import ReplicaSpec

    config = LlamaConfig.tiny()
    prompt_lens, new_tokens = (4, 24), (2, 40)
    max_len = prompt_lens[1] + new_tokens[1]
    # one coarse bucket per axis: replicated legs pay one decode + one
    # prefill compile per replica engine instead of the full lattice
    spec = ReplicaSpec(
        model=dataclasses.asdict(config),
        num_blocks=num_blocks,
        block_size=block_size,
        max_slots=max_slots,
        slot_buckets=(max_slots,),
        block_buckets=(-(-max_len // block_size) + 1,),
        prefill_buckets=(prompt_lens[1] + new_tokens[1],),
    )
    workload = build_workload(
        requests, seed, prompt_lens, new_tokens, 2.0, config.vocab_size
    )
    one = _drain_through_router(spec, workload, n_replicas=1)
    many = _drain_through_router(spec, workload, n_replicas=n_replicas)
    kill = _drain_through_router(
        spec, workload, n_replicas=n_replicas, kill_after=max(1, requests // 4)
    )
    # the ISSUE 15 leg: the SAME kill workload with tracing armed — outputs
    # must stay bitwise-identical, every completion must carry a gap-free
    # span tree (failover hops included), and the tok/s ratio against the
    # untraced kill leg reports the tracing tax honestly
    traced = _drain_through_router(
        spec, workload, n_replicas=n_replicas,
        kill_after=max(1, requests // 4), traced=True,
    )
    parity = kill["outputs"] == many["outputs"]
    traced_parity = traced["outputs"] == many["outputs"]
    for leg in (one, many, kill, traced):
        leg.pop("outputs")
    return {
        "bench": "serving_replicated",
        "unit": f"tokens_per_s_scaling({n_replicas}r/1r)",
        "value": round(many["tokens_per_s"] / max(one["tokens_per_s"], 1e-9), 3),
        "one_replica": one,
        "replicated": many,
        "replica_kill": kill,
        "replica_kill_traced": traced,
        "kill_outputs_match_unkilled": parity,
        "traced_outputs_match_unkilled": traced_parity,
        "tracing_tokens_per_s_ratio": round(
            traced["tokens_per_s"] / max(kill["tokens_per_s"], 1e-9), 3
        ),
        "requests": requests,
        "n_replicas": n_replicas,
        "on_tpu": on_tpu,
    }


def run_prefix_leg(params, config, workload, *, prefix_cache, max_slots,
                   num_blocks, block_size, lattice):
    """One prefix-cache setting over the shared-prefix workload; returns the
    leg metrics, every request's output tokens (for the cross-leg bitwise
    parity check) and the post-warmup recompile count (must be 0 — the cache
    introduces no new shapes). Rejected requests are reported, not silently
    dropped (a shrunken workload would fake the prefill-token reduction)."""
    from accelerate_tpu.serving import ServingEngine
    from accelerate_tpu.telemetry.step_profiler import RecompileWatcher

    engine = ServingEngine(
        params, config, num_blocks=num_blocks, block_size=block_size,
        max_slots=max_slots, lattice=lattice, prefix_cache=prefix_cache,
    )
    engine.warmup()
    watcher = RecompileWatcher()
    watcher.register("prefill", engine.prefill_fn)
    watcher.register("decode", engine.decode_fn)
    if prefix_cache:
        # the COW block copy is the one jit fn the cache adds: the
        # zero-recompile signal must watch it too
        watcher.register("cow", engine.cow_fn)
    completed, rejected, wall = _drive(engine, workload)
    tokens = sum(len(r.generated) for r in completed)
    ttfts = [r.first_token_t - r.arrival_t for r in completed if r.first_token_t]
    stats = engine.stats()
    outputs = {r.rid: [int(t) for t in r.output_ids()] for r in completed}
    return {
        "prefix_cache": prefix_cache,
        "completed": len(completed),
        "rejected": len(rejected),
        "tokens": tokens,
        "wall_s": round(wall, 4),
        "tokens_per_s": round(tokens / max(wall, 1e-9), 2),
        "p50_ttft_ms": round(_percentile(ttfts, 50) * 1e3, 2),
        "prefill_tokens": stats["prefill_tokens"],
        "prefix_hit_rate": stats.get("prefix_hit_rate", 0.0),
        "prefill_tokens_saved": stats.get("prefill_tokens_saved", 0),
        "cow_copies": stats.get("cow_copies", 0),
        "recompiles": sum(watcher.poll(emit=False).values()),
    }, [outputs[k] for k in sorted(outputs)]


def run_bench_prefix_cache(
    on_tpu: bool,
    requests: int = 24,
    rate: float = 2.0,
    seed: int = 0,
    max_slots: int = 4,
    num_blocks: int = 97,
    block_size: int = 8,
) -> dict:
    """The shared-prefix leg (ISSUE 14): ONE seeded Poisson workload whose
    prompts share a long system prompt, replayed with the prefix cache on
    and off. The cache-on leg must cut prefill tokens (the `value` is the
    measured reduction), improve tok/s and TTFT p50, produce bitwise
    -identical outputs per request, and stay recompile-free — the
    acceptance line `make bench-serve` holds."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.models import LlamaConfig, init_llama
    from accelerate_tpu.serving import BucketLattice

    if on_tpu:
        config = LlamaConfig(vocab_size=32000, dim=1024, n_layers=8, n_heads=16,
                             n_kv_heads=8, max_seq_len=512)
        shared_len, suffix_lens, new_tokens = 128, (8, 48), (8, 32)
        max_slots, num_blocks, block_size = max(max_slots, 8), 320, 16
    else:
        config = LlamaConfig.tiny()
        # a long shared system prompt vs short private suffixes: the
        # workload shape where prefix caching pays (most prompt tokens are
        # the shared head, so the cached leg's prefill runs a small bucket
        # instead of the largest)
        shared_len, suffix_lens, new_tokens = 64, (2, 14), (2, 20)
    params = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16), init_llama(config, jax.random.PRNGKey(0))
    )
    max_len = shared_len + suffix_lens[1] + new_tokens[1]
    lattice = BucketLattice.from_limits(
        max_slots, -(-max_len // block_size) + 1, shared_len + suffix_lens[1]
    )
    workload = build_workload(
        requests, seed, suffix_lens, new_tokens, rate, config.vocab_size,
        shared_len=shared_len,
    )
    kw = dict(max_slots=max_slots, num_blocks=num_blocks,
              block_size=block_size, lattice=lattice)
    cached, cached_out = run_prefix_leg(params, config, workload,
                                        prefix_cache=True, **kw)
    plain, plain_out = run_prefix_leg(params, config, workload,
                                      prefix_cache=False, **kw)
    reduction = (
        1.0 - cached["prefill_tokens"] / plain["prefill_tokens"]
        if plain["prefill_tokens"] else 0.0
    )
    return {
        "bench": "serving_prefix_cache",
        "unit": "prefill_token_reduction(cached vs off)",
        "value": round(reduction, 4),
        "cached": cached,
        "uncached": plain,
        "prefix_hit_rate": cached["prefix_hit_rate"],
        "prefill_tokens_saved": cached["prefill_tokens_saved"],
        "tokens_per_s_ratio": round(
            cached["tokens_per_s"] / max(plain["tokens_per_s"], 1e-9), 3
        ),
        "ttft_p50_ratio": round(
            cached["p50_ttft_ms"] / max(plain["p50_ttft_ms"], 1e-9), 3
        ),
        "outputs_match": cached_out == plain_out,
        "zero_recompiles": cached["recompiles"] == 0 and plain["recompiles"] == 0,
        "requests": requests,
        "shared_prefix_len": shared_len,
        "on_tpu": on_tpu,
    }


def _drain_through_disagg(pspec, dspec, workload, *, arrival_dt_s,
                          cache_root=None, timeout_s=600.0):
    """Drain the seeded Poisson workload through a 1-prefill + 1-decode
    DisaggRouter with the SLO autoscaler armed under an artificially tight
    ttft objective (threshold 1µs: the open-loop ramp is violating by
    construction, so ≥1 decode scale-up MUST fire mid-load). Arrival steps
    are replayed open-loop at ``arrival_dt_s`` wall seconds per step.
    Returns the leg metrics — per-request outputs for the monolith parity
    check, pre/post-transition ttft+latency percentiles, and the joiner's
    compile count (0 == the pre-ship made the join warm)."""
    import time as _time

    from accelerate_tpu.serving import (
        AdmissionController,
        AutoscalerPolicy,
        DisaggRouter,
        LocalReplica,
        RouterRequestStatus,
    )
    from accelerate_tpu.telemetry.slo import SLOMonitor, serving_slos

    autoscaler = AutoscalerPolicy(
        dspec,
        min_decode=1,
        max_decode=2,
        cooldown_s=30.0,
        idle_shrink_after_s=3600.0,  # this leg measures the scale-UP path
        source_cache_dir=(
            os.path.join(cache_root, "warm") if cache_root else None
        ),
        joiner_cache_dir=(
            (lambda name: os.path.join(cache_root, name)) if cache_root else None
        ),
    )
    router = DisaggRouter(
        [LocalReplica("p0", pspec)],
        [LocalReplica("d0", dspec)],
        admission=AdmissionController(max_queue=len(workload) + 1),
        health_timeout_s=30.0,
        # a 1µs ttft threshold saturates the burn windows as soon as
        # min_events completions land — the deterministic scale trigger
        slo_monitor=SLOMonitor(serving_slos(ttft_threshold_s=1e-6), min_events=4),
        slo_eval_interval_s=0.0,
        autoscaler=autoscaler,
    )
    try:
        router.wait_ready(timeout_s=300)
        t0 = _time.monotonic()
        reqs = []
        next_req = 0
        while next_req < len(workload) or not all(r.status.terminal for r in reqs):
            now = _time.monotonic()
            while (next_req < len(workload)
                   and workload[next_req][0] * arrival_dt_s <= now - t0):
                _, prompt, max_new = workload[next_req]
                reqs.append(router.submit(prompt, max_new, rng_seed=next_req))
                next_req += 1
            router.poll()
            _time.sleep(0.001)
            if now - t0 > timeout_s:
                raise RuntimeError(f"disagg leg wedged (>{timeout_s}s)")
        # let an in-flight join finish warming so its compile count lands
        while autoscaler.stats()["pending_joins"]:
            router.poll()
            _time.sleep(0.01)
            if _time.monotonic() - t0 > timeout_s:
                break
        wall = _time.monotonic() - t0
        completed = [r for r in reqs if r.status is RouterRequestStatus.FINISHED]
        tokens = sum(len(r.generated) for r in completed)
        scale_ups = [e for e in autoscaler.events if e["action"] == "scale_up"]
        joins = [e for e in autoscaler.events if e["action"] == "join_ready"]

        def _phase(rs):
            lat = [r.finish_t - r.arrival_t for r in rs]
            ttft = [r.first_token_t - r.arrival_t for r in rs if r.first_token_t]
            return {
                "completed": len(rs),
                "p50_latency_ms": round(_percentile(lat, 50) * 1e3, 2),
                "p99_latency_ms": round(_percentile(lat, 99) * 1e3, 2),
                "p50_ttft_ms": round(_percentile(ttft, 50) * 1e3, 2),
                "p99_ttft_ms": round(_percentile(ttft, 99) * 1e3, 2),
            }

        # the transition cut: requests finishing before the first scale-up
        # ran on the founding fleet; everything after shares the joiner
        t_scale = scale_ups[0]["t"] if scale_ups else None
        leg = {
            "completed": len(completed),
            "lost": len(reqs) - len(completed),
            "tokens": tokens,
            "wall_s": round(wall, 4),
            "tokens_per_s": round(tokens / max(wall, 1e-9), 2),
            "handoffs": router.handoffs,
            "handoff_corrupt": router.handoff_corrupt,
            "scale_events": len(autoscaler.events),
            "scale_ups": len(scale_ups),
            "first_scale_after_s": (
                round(t_scale - t0, 4) if t_scale is not None else None
            ),
            "join_compiles": sum(int(j.get("join_compiles", 0)) for j in joins),
            "warm_joins": sum(1 for j in joins if j.get("warm")),
            "joins": len(joins),
            "time_to_ready_s": [j.get("time_to_ready_s") for j in joins],
            "outputs": [[int(t) for t in r.generated] for r in reqs],
        }
        if t_scale is not None:
            pre = [r for r in completed if r.finish_t < t_scale]
            post = [r for r in completed if r.finish_t >= t_scale]
            leg["transition"] = {"pre_scale": _phase(pre), "post_scale": _phase(post)}
        return leg
    finally:
        router.close()


def run_bench_disagg(
    on_tpu: bool,
    requests: int = 16,
    seed: int = 0,
    max_slots: int = 2,
    num_blocks: int = 49,
    block_size: int = 8,
) -> dict:
    """The disaggregated leg (ISSUE 16): ONE seeded long-prompt-heavy Poisson
    ramp drained through a monolithic 1-replica router and through the
    1-prefill + 1-decode DisaggRouter with the SLO autoscaler armed. The
    tight ttft objective forces ≥1 decode scale-up mid-load; the payload
    reports the ttft/latency p99 across that transition, the monolith-vs
    -disagg comparison, bitwise output parity, zero lost requests, and the
    joiner's compile count (the pre-shipped join must be warm:
    ``join_compiles == 0``)."""
    import dataclasses
    import tempfile

    from accelerate_tpu.models import LlamaConfig
    from accelerate_tpu.serving import ReplicaSpec

    config = LlamaConfig.tiny()
    # long-prompt-heavy: most work is prefill, the mix disaggregation exists
    # to isolate from decode interference
    prompt_lens, new_tokens = (16, 48), (2, 12)
    max_len = prompt_lens[1] + new_tokens[1]
    spec = ReplicaSpec(
        model=dataclasses.asdict(config),
        num_blocks=num_blocks,
        block_size=block_size,
        max_slots=max_slots,
        slot_buckets=(max_slots,),
        block_buckets=(-(-max_len // block_size) + 1,),
        prefill_buckets=(prompt_lens[1] + new_tokens[1],),
    )
    workload = build_workload(
        requests, seed, prompt_lens, new_tokens, 2.0, config.vocab_size
    )
    mono = _drain_through_router(spec, workload, n_replicas=1)
    with tempfile.TemporaryDirectory(prefix="bench-disagg-cache-") as cache_root:
        # founding replicas warm into (and the joiner pre-ships from) a
        # shared source cache dir; each joiner gets its OWN dir so the
        # pre-ship is real file movement, not a shared-directory freebie
        warm_dir = os.path.join(cache_root, "warm")
        pspec = dataclasses.replace(spec, role="prefill",
                                    compile_cache_dir=warm_dir)
        dspec = dataclasses.replace(spec, role="decode",
                                    compile_cache_dir=warm_dir)
        disagg = _drain_through_disagg(
            pspec, dspec, workload, arrival_dt_s=0.02, cache_root=cache_root,
        )
    parity = disagg["outputs"] == mono["outputs"]
    for leg in (mono, disagg):
        leg.pop("outputs")
    return {
        "bench": "serving_disagg",
        "unit": "tokens_per_s_ratio(disagg/monolith)",
        "value": round(
            disagg["tokens_per_s"] / max(mono["tokens_per_s"], 1e-9), 3
        ),
        "monolith": mono,
        "disagg": disagg,
        "outputs_match_monolith": parity,
        "zero_lost": disagg["lost"] == 0,
        "scale_up_fired": disagg["scale_ups"] >= 1,
        "join_compiles": disagg["join_compiles"],
        "warm_join": disagg["joins"] > 0
        and disagg["warm_joins"] == disagg["joins"],
        "requests": requests,
        "prompt_lens": list(prompt_lens),
        "new_tokens": list(new_tokens),
        "on_tpu": on_tpu,
    }


def run_spec_leg(params, config, workload, *, spec_tokens, draft_layers,
                 max_slots, num_blocks, block_size, lattice):
    """One speculation setting over the shared workload; returns the leg
    metrics, every request's output tokens (for the cross-leg bitwise parity
    check — bitwise-accept means speculation may change HOW FAST tokens come
    out, never WHICH) and the post-warmup recompile count across all four
    jit functions (draft + verify are warmed at every decode point)."""
    from accelerate_tpu.serving import ServingEngine
    from accelerate_tpu.telemetry.step_profiler import RecompileWatcher

    kw = {}
    if spec_tokens:
        kw = dict(spec_tokens=spec_tokens, draft_layers=draft_layers)
    engine = ServingEngine(
        params, config, num_blocks=num_blocks, block_size=block_size,
        max_slots=max_slots, lattice=lattice, **kw,
    )
    engine.warmup()
    watcher = RecompileWatcher()
    watcher.register("prefill", engine.prefill_fn)
    watcher.register("decode", engine.decode_fn)
    if spec_tokens:
        watcher.register("draft", engine.draft_fn)
        watcher.register("verify", engine.verify_fn)
    completed, rejected, wall = _drive(engine, workload)
    tokens = sum(len(r.generated) for r in completed)
    # per-token decode latency: the metric speculation exists to cut —
    # first-token to finish divided by the tokens decoded in that span
    per_tok = [
        (r.finish_t - r.first_token_t) / max(len(r.generated) - 1, 1)
        for r in completed if r.first_token_t and len(r.generated) > 1
    ]
    stats = engine.stats()
    outputs = {r.rid: [int(t) for t in r.output_ids()] for r in completed}
    leg = {
        "spec_tokens": spec_tokens,
        "draft_layers": draft_layers if spec_tokens else None,
        "completed": len(completed),
        "rejected": len(rejected),
        "tokens": tokens,
        "wall_s": round(wall, 4),
        "tokens_per_s": round(tokens / max(wall, 1e-9), 2),
        "engine_steps": stats["steps"],
        "p50_per_token_ms": round(_percentile(per_tok, 50) * 1e3, 3),
        "p99_per_token_ms": round(_percentile(per_tok, 99) * 1e3, 3),
        "recompiles": sum(watcher.poll(emit=False).values()),
    }
    if spec_tokens:
        leg["draft_proposed_tokens"] = stats["draft_proposed_tokens"]
        leg["draft_accepted_tokens"] = stats["draft_accepted_tokens"]
        leg["spec_accept_rate"] = stats["spec_accept_rate"]
        leg["spec_accept_hist"] = stats["spec_accept_hist"]
    return leg, [outputs[k] for k in sorted(outputs)]


def _prefill_kernel_microbench(on_tpu: bool, *, iters: int = 20):
    """Paged-attention prefill chunk: XLA gather path vs the Pallas kernel.
    On TPU both run compiled and the ratio is the ISSUE 18 kernel win; on
    CPU the kernel only runs under the Pallas interpreter (a correctness
    vehicle, orders of magnitude slower by construction), so the kernel
    column is timed once and flagged — the gather column is still an honest
    CPU baseline for the chunk shape."""
    import numpy as np

    import jax.numpy as jnp

    from accelerate_tpu.ops.flash_attention import paged_attention_prefill
    from accelerate_tpu.serving.kv_pager import paged_attention as gather_ref

    if on_tpu:
        B, S, H, Hkv, D, bs, nb, W = 8, 64, 16, 8, 128, 16, 256, 24
    else:
        B, S, H, Hkv, D, bs, nb, W = 2, 8, 4, 2, 32, 8, 16, 4
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    k_pool = jnp.asarray(rng.standard_normal((nb, bs, Hkv, D)), jnp.float32)
    v_pool = jnp.asarray(rng.standard_normal((nb, bs, Hkv, D)), jnp.float32)
    tables = jnp.asarray(
        rng.permutation(np.arange(1, nb))[: B * W].reshape(B, W), jnp.int32
    )
    # the chunk sits at the very end of the table: every earlier block is
    # landed-prefix KV, the max-work shape for a chunk of S queries
    qpos = jnp.asarray(
        (W * bs - S) + np.arange(S)[None, :] + np.zeros((B, 1), np.int32),
        jnp.int32,
    )
    n_tok = B * S

    def _time(fn, reps):
        fn().block_until_ready()  # warm (compile / first trace)
        t0 = time.perf_counter()
        for _ in range(reps):
            fn().block_until_ready()
        return (time.perf_counter() - t0) / reps

    import jax

    gather_jit = jax.jit(gather_ref)
    gather_s = _time(lambda: gather_jit(q, k_pool, v_pool, tables, qpos), iters)
    if on_tpu:
        kernel_s = _time(
            lambda: paged_attention_prefill(q, k_pool, v_pool, tables, qpos),
            iters,
        )
        kernel_mode = "compiled"
    else:
        t0 = time.perf_counter()
        paged_attention_prefill(
            q, k_pool, v_pool, tables, qpos, interpret=True
        ).block_until_ready()
        kernel_s = time.perf_counter() - t0
        kernel_mode = "interpret"
    return {
        "shape": {"B": B, "S": S, "H": H, "Hkv": Hkv, "D": D,
                  "block_size": bs, "table_width": W},
        "gather_us_per_token": round(gather_s * 1e6 / n_tok, 3),
        "kernel_us_per_token": round(kernel_s * 1e6 / n_tok, 3),
        "kernel_mode": kernel_mode,
        # only meaningful when both columns are compiled (TPU)
        "kernel_speedup": (
            round(gather_s / max(kernel_s, 1e-12), 3) if on_tpu else None
        ),
    }


def run_bench_spec_decode(
    on_tpu: bool,
    requests: int = 12,
    rate: float = 2.0,
    seed: int = 0,
    spec_tokens: int = 3,
    draft_layers: int = 1,
    max_slots: int = 4,
    num_blocks: int = 49,
    block_size: int = 8,
) -> dict:
    """The speculative-decoding leg (ISSUE 18): ONE seeded Poisson workload
    replayed with speculation off and with a k-token truncated-layer
    self-draft on. Bitwise-accept makes the comparison exact: outputs must
    match token-for-token, so the legs differ only in steps taken. Reports
    the per-token latency improvement at the measured accept rate, the
    engine-step reduction, bitwise parity, and the zero-recompile line
    (draft + verify included); plus the prefill-kernel chunk microbench."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.models import LlamaConfig, init_llama
    from accelerate_tpu.serving import BucketLattice

    if on_tpu:
        config = LlamaConfig(vocab_size=32000, dim=1024, n_layers=8, n_heads=16,
                             n_kv_heads=8, max_seq_len=512)
        prompt_lens, new_tokens = (16, 96), (16, 64)
        max_slots, num_blocks, block_size = max(max_slots, 8), 160, 16
        draft_layers = max(draft_layers, 2)
    else:
        config = LlamaConfig.tiny()
        # decode-heavy: long completions are where accepted drafts compound
        prompt_lens, new_tokens = (4, 16), (8, 40)
    params = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16), init_llama(config, jax.random.PRNGKey(0))
    )
    max_len = prompt_lens[1] + new_tokens[1]
    lattice = BucketLattice.from_limits(
        max_slots, -(-max_len // block_size) + 1, prompt_lens[1]
    )
    workload = build_workload(
        requests, seed, prompt_lens, new_tokens, rate, config.vocab_size
    )
    kw = dict(max_slots=max_slots, num_blocks=num_blocks,
              block_size=block_size, lattice=lattice)
    spec, spec_out = run_spec_leg(params, config, workload,
                                  spec_tokens=spec_tokens,
                                  draft_layers=draft_layers, **kw)
    plain, plain_out = run_spec_leg(params, config, workload,
                                    spec_tokens=0, draft_layers=None, **kw)
    return {
        "bench": "serving_spec_decode",
        "unit": "per_token_latency_ratio(spec/off)",
        "value": round(
            spec["p50_per_token_ms"] / max(plain["p50_per_token_ms"], 1e-9), 3
        ),
        "speculative": spec,
        "baseline": plain,
        "spec_accept_rate": spec["spec_accept_rate"],
        "tokens_per_s_ratio": round(
            spec["tokens_per_s"] / max(plain["tokens_per_s"], 1e-9), 3
        ),
        "engine_step_ratio": round(
            spec["engine_steps"] / max(plain["engine_steps"], 1), 3
        ),
        "outputs_match": spec_out == plain_out,
        "zero_recompiles": spec["recompiles"] == 0 and plain["recompiles"] == 0,
        "prefill_kernel": _prefill_kernel_microbench(on_tpu),
        "requests": requests,
        "spec_tokens": spec_tokens,
        "draft_layers": draft_layers,
        "on_tpu": on_tpu,
    }


def run_bench_serving(
    on_tpu: bool,
    requests: int = 32,
    rate: float = 2.0,
    seed: int = 0,
    max_slots: int = 4,
    num_blocks: int = 49,
    block_size: int = 8,
) -> dict:
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.models import LlamaConfig, init_llama
    from accelerate_tpu.serving import BucketLattice

    if on_tpu:
        config = LlamaConfig(vocab_size=32000, dim=1024, n_layers=8, n_heads=16,
                             n_kv_heads=8, max_seq_len=512)
        prompt_lens, new_tokens = (16, 96), (8, 64)
        max_slots, num_blocks, block_size = max(max_slots, 8), 160, 16
    else:
        config = LlamaConfig.tiny()
        # heterogeneous completion lengths are the whole point: static
        # batching drains every gang to its slowest member while continuous
        # backfills the freed slots at step granularity
        prompt_lens, new_tokens = (4, 24), (2, 40)
    params = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16), init_llama(config, jax.random.PRNGKey(0))
    )
    max_len = prompt_lens[1] + new_tokens[1]
    lattice = BucketLattice.from_limits(
        max_slots, -(-max_len // block_size) + 1, prompt_lens[1] + new_tokens[1]
    )
    workload = build_workload(
        requests, seed, prompt_lens, new_tokens, rate, config.vocab_size
    )
    kw = dict(max_slots=max_slots, num_blocks=num_blocks, block_size=block_size,
              lattice=lattice)
    continuous = run_leg(params, config, workload, continuous=True, **kw)
    static = run_leg(params, config, workload, continuous=False, **kw)
    return {
        "bench": "serving",
        "unit": "throughput_ratio(continuous/static)",
        "value": round(
            continuous["tokens_per_s"] / max(static["tokens_per_s"], 1e-9), 3
        ),
        "continuous": continuous,
        "static": static,
        "p99_latency_ms": continuous["p99_latency_ms"],
        "requests": requests,
        "arrival_rate_per_step": rate,
        "prompt_lens": list(prompt_lens),
        "new_tokens": list(new_tokens),
        "max_slots": max_slots,
        "num_blocks": num_blocks,
        "block_size": block_size,
        "on_tpu": on_tpu,
    }


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--rate", type=float, default=2.0,
                    help="mean Poisson arrivals per engine step (open loop)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-slots", type=int, default=4)
    ap.add_argument("--num-blocks", type=int, default=49)
    ap.add_argument("--block-size", type=int, default=8)
    ap.add_argument("--replicated-requests", type=int, default=16,
                    help="workload size for the router leg (0 skips it)")
    ap.add_argument("--n-replicas", type=int, default=2)
    ap.add_argument("--prefix-requests", type=int, default=24,
                    help="workload size for the shared-prefix leg (0 skips it)")
    ap.add_argument("--disagg-requests", type=int, default=16,
                    help="workload size for the disaggregated leg (0 skips it)")
    ap.add_argument("--spec-requests", type=int, default=12,
                    help="workload size for the spec-decode leg (0 skips it)")
    ap.add_argument("--spec-tokens", type=int, default=3)
    ap.add_argument("--draft-layers", type=int, default=1)
    args = ap.parse_args()
    on_tpu = detect_backend()
    out = run_bench_serving(
        on_tpu=on_tpu,
        requests=args.requests,
        rate=args.rate,
        seed=args.seed,
        max_slots=args.max_slots,
        num_blocks=args.num_blocks,
        block_size=args.block_size,
    )
    if args.replicated_requests > 0:
        out["replicated"] = run_bench_replicated(
            on_tpu=on_tpu,
            requests=args.replicated_requests,
            seed=args.seed,
            n_replicas=args.n_replicas,
            max_slots=args.max_slots,
            num_blocks=args.num_blocks,
            block_size=args.block_size,
        )
    if args.prefix_requests > 0:
        out["prefix_cache"] = run_bench_prefix_cache(
            on_tpu=on_tpu,
            requests=args.prefix_requests,
            rate=args.rate,
            seed=args.seed,
        )
    if args.disagg_requests > 0:
        out["disagg"] = run_bench_disagg(
            on_tpu=on_tpu,
            requests=args.disagg_requests,
            seed=args.seed,
        )
    if args.spec_requests > 0:
        out["spec_decode"] = run_bench_spec_decode(
            on_tpu=on_tpu,
            requests=args.spec_requests,
            rate=args.rate,
            seed=args.seed,
            spec_tokens=args.spec_tokens,
            draft_layers=args.draft_layers,
        )
    emit(out)
