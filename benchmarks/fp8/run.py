"""FP8 vs bf16 benchmark (reference ``benchmarks/fp8/{te,torchao,ms_amp}``:
loss-parity comparison scripts): train the same MLP stack on the same data in
bf16 and in fp8 (delayed-scaling ``fp8_dot``, ``ops/fp8.py``), report final-
loss relative delta and steady-state step times.

On CPU XLA emulates the fp8 dtypes, so the parity leg is meaningful
everywhere; the step-time ratio is only meaningful on fp8-capable hardware.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from _common import detect_backend, emit


def build(depth: int, dim: int, fp8: bool, key):
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.ops.fp8 import fp8_dense_apply, fp8_dense_init

    keys = jax.random.split(key, depth)
    if fp8:
        # standard recipe: first and last layers stay bf16, middles are fp8
        # (the policy filter_first_and_last_linear_layers encodes; the
        # reference's TE benchmarks do the same) — edge layers see the rawest
        # activations/cotangents and dominate quantization error
        def init_one(k, i):
            if i in (0, depth - 1):
                return {"kernel": jax.random.normal(k, (dim, dim)) / jnp.sqrt(dim),
                        "bias": jnp.zeros((dim,))}
            return fp8_dense_init(k, dim, dim)

        params = [init_one(k, i) for i, k in enumerate(keys)]

        def forward(ps, x):
            h = x
            for i, p in enumerate(ps):
                if i in (0, depth - 1):
                    h = jax.nn.gelu(
                        (h.astype(jnp.bfloat16) @ p["kernel"].astype(jnp.bfloat16)
                         + p["bias"].astype(jnp.bfloat16)).astype(jnp.float32))
                else:
                    h = jax.nn.gelu(fp8_dense_apply(p, h))
            return h
    else:
        params = [
            {"kernel": jax.random.normal(k, (dim, dim)) / jnp.sqrt(dim),
             "bias": jnp.zeros((dim,))}
            for k in keys
        ]

        def forward(ps, x):
            h = x
            for p in ps:
                h = jax.nn.gelu(h.astype(jnp.bfloat16) @ p["kernel"].astype(jnp.bfloat16)
                                + p["bias"].astype(jnp.bfloat16)).astype(jnp.float32)
            return h
    return params, forward


def train(fp8: bool, depth: int, dim: int, batch: int, steps: int):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu.ops.fp8 import make_fp8_optimizer

    params, forward = build(depth, dim, fp8, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.normal(size=(batch, dim)), jnp.float32)
    # learnable target (random linear teacher): a memorize-pure-noise target
    # would measure quantization noise on an unlearnable task, not training
    # parity — the reference's fp8 benchmarks also train a real objective
    W_t = jnp.asarray(rng.normal(size=(dim, dim)) / np.sqrt(dim), jnp.float32)
    Y = jnp.tanh(X @ W_t)

    def loss_fn(ps):
        return jnp.mean((forward(ps, X) - Y) ** 2)

    inner = optax.adam(1e-3)
    opt = make_fp8_optimizer(inner, params) if fp8 else inner
    opt_state = opt.init(params)

    @jax.jit
    def step(ps, s):
        loss, grads = jax.value_and_grad(loss_fn)(ps)
        updates, s = opt.update(grads, s, ps)
        return optax.apply_updates(ps, updates), s, loss

    params, opt_state, loss = step(params, opt_state)  # compile
    float(np.asarray(loss))
    t0 = time.time()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state)
    final = float(np.asarray(loss))
    elapsed = time.time() - t0
    return final, elapsed / steps * 1e3


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    args = ap.parse_args()
    on_tpu = detect_backend()
    depth, dim, batch = (8, 2048, 512) if on_tpu else (3, 128, 64)
    bf16_loss, bf16_ms = train(False, depth, dim, batch, args.steps)
    fp8_loss, fp8_ms = train(True, depth, dim, batch, args.steps)
    rel = abs(fp8_loss - bf16_loss) / max(abs(bf16_loss), 1e-9)
    emit({
        "metric": "fp8 vs bf16 train (loss parity + step time)",
        "value": round(rel, 4),
        "unit": "relative final-loss delta (lower is better)",
        "bf16_final_loss": round(bf16_loss, 5),
        "fp8_final_loss": round(fp8_loss, 5),
        "bf16_step_ms": round(bf16_ms, 2),
        "fp8_step_ms": round(fp8_ms, 2),
        "depth": depth, "dim": dim, "batch": batch, "steps": args.steps,
    })
