"""Performance-observatory microbench: the bench train step under full
attribution (ISSUE 7 acceptance path, also `make profile`).

Runs the headline bench's BERT train step (same model/loss/prepare path as
``bench.py``) for a handful of steps with telemetry, cost-analysis capture and
an automatic trace window enabled, then prints:

- the telemetry report's **performance** section (per-step MFU, roofline
  bucket, top-k ops, comms-overlap ratio) — human-readable, to stdout;
- one JSON line (bench.py conventions, last line on stdout) with the same
  fields for drivers/tests.

On a dev box this exercises the whole observatory on the CPU backend (MFU is
*relative* there — nominal peaks, see docs/performance.md); on a TPU it is a
real utilization reading of the bench step.
"""

import argparse
import dataclasses
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from _common import detect_backend, emit


def run_bench_perf(
    on_tpu: bool,
    steps: int = 8,
    trace_every: int = 3,
    keep_artifacts: bool = False,
) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from accelerate_tpu import Accelerator, telemetry
    from accelerate_tpu.models import BertConfig, bert_loss, bert_shard_rules, init_bert
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.telemetry.report import build_report, format_performance_section
    from accelerate_tpu.utils.dataclasses import ProfileConfig

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    if on_tpu:
        config, batch_size, seq_len = BertConfig.base(), 64, 128
    else:
        config, batch_size, seq_len = BertConfig.tiny(), 8, 32
    config = dataclasses.replace(config, max_seq_len=seq_len)

    workdir = tempfile.mkdtemp(prefix="bench_perf_")
    telemetry.enable(os.path.join(workdir, "telemetry"))
    try:
        accelerator = Accelerator(
            mixed_precision="bf16",
            rng_seed=0,
            kwargs_handlers=[
                ProfileConfig(
                    trace_every=trace_every,
                    # 2-step windows: on the CPU backend a 1-step window can
                    # close before the XLA pool threads flush their TraceMe
                    # buffers into the session (observed ~1-in-3 empty); the
                    # second step's events force the first step's to land
                    trace_steps=2,
                    output_trace_dir=os.path.join(workdir, "trace"),
                )
            ],
        )
        params = init_bert(config, jax.random.PRNGKey(0))
        params, opt = accelerator.prepare(
            params, optax.adamw(2e-5), shard_rules=bert_shard_rules()
        )
        step = accelerator.prepare_train_step(lambda p, b: bert_loss(p, b, config), opt)
        rng = np.random.default_rng(0)
        batch = {
            "input_ids": jnp.asarray(
                rng.integers(0, config.vocab_size, (batch_size, seq_len)), jnp.int32
            ),
            "attention_mask": jnp.ones((batch_size, seq_len), jnp.int32),
            "token_type_ids": jnp.zeros((batch_size, seq_len), jnp.int32),
            "labels": jnp.asarray(rng.integers(0, 2, (batch_size,)), jnp.int32),
        }
        opt_state = opt.opt_state
        for _ in range(steps):
            params, opt_state, metrics = step(params, opt_state, batch)
            # force completion INSIDE the step (and inside any open trace
            # window): under async dispatch the thunks would otherwise
            # execute after stop_trace and the window would read empty
            final_loss = float(np.asarray(metrics["loss"]))
        accelerator.end_training()
        telemetry.disable()

        report = build_report([os.path.join(workdir, "telemetry")])
        perf = report.get("performance") or {}
        print(format_performance_section(perf) if perf else "no performance records")
        mfu = perf.get("mfu") or {}
        fn = (perf.get("by_fn") or {}).get("train_step") or {}
        trace = perf.get("trace") or {}
        return {
            "bench": "perf",
            "unit": "mfu(p50)",
            "value": mfu.get("p50", 0.0),
            "mfu": {k: mfu.get(k) for k in ("p50", "mean", "max") if k in mfu},
            "roofline": fn.get("roofline"),
            "arithmetic_intensity": fn.get("arithmetic_intensity"),
            "flops_per_step": fn.get("flops"),
            "peak_source": fn.get("peak_source"),
            "overlap_ratio": trace.get("comms_overlap_ratio"),
            "trace_windows": trace.get("windows", 0),
            "top_ops": (trace.get("top_ops") or [])[:3],
            "steps": steps,
            "final_loss": round(final_loss, 4),
            "on_tpu": on_tpu,
            **({"artifacts": workdir} if keep_artifacts else {}),
        }
    finally:
        telemetry.disable()
        if not keep_artifacts:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--trace-every", type=int, default=3,
                    help="open a two-step jax.profiler window every N steps")
    ap.add_argument("--keep-artifacts", action="store_true",
                    help="keep the telemetry dir + raw traces instead of deleting")
    args = ap.parse_args()
    emit(
        run_bench_perf(
            on_tpu=detect_backend(),
            steps=args.steps,
            trace_every=args.trace_every,
            keep_artifacts=args.keep_artifacts,
        )
    )
