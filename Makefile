# Test/benchmark targets (reference Makefile:23-58 split: core vs cli vs
# big-modeling vs examples, for CI sharding).

.PHONY: test test_smoke test_core test_cli test_big_modeling test_examples \
        test_models test_multihost test_checkpoint quality bench

PYTEST := python -m pytest -q

test:
	$(PYTEST) tests/

# <60s cross-subsystem signal: one marked test per subsystem (mesh, collectives,
# data loader, train step, bridge incl. CV, models, long-context, quantization,
# checkpointing, tracking, CLI, native C++, memory, utils)
test_smoke:
	$(PYTEST) tests/ -m smoke

test_core:
	$(PYTEST) tests/ --ignore=tests/test_big_modeling.py \
	  --ignore=tests/test_examples.py --ignore=tests/test_cli.py \
	  --ignore=tests/test_multiprocess.py --ignore=tests/test_models.py \
	  --ignore=tests/test_t5.py --ignore=tests/test_convert.py \
	  --ignore=tests/test_bridge.py --ignore=tests/test_sharded_checkpoint.py \
	  --ignore=tests/test_native.py

test_cli:
	$(PYTEST) tests/test_cli.py

test_big_modeling:
	$(PYTEST) tests/test_big_modeling.py

test_examples:
	$(PYTEST) tests/test_examples.py

test_models:
	$(PYTEST) tests/test_models.py tests/test_t5.py tests/test_convert.py \
	  tests/test_bridge.py tests/test_bridge_cv.py

test_multihost:
	$(PYTEST) tests/test_multiprocess.py

test_checkpoint:
	$(PYTEST) tests/test_sharded_checkpoint.py tests/test_native.py

quality:
	python -m compileall -q accelerate_tpu

bench:
	python bench.py
