# Test/benchmark targets (reference Makefile:23-58 split: core vs cli vs
# big-modeling vs examples, for CI sharding; reference test_utils/testing.py
# @slow discipline: long-running tests carry -m slow and run in their own
# shard so the core signal stays fast).
#
# Approximate shard wall-times (virtual 8-device CPU mesh, this container):
#   test_smoke       ~1 min
#   test_core        ~4 min   (slow-marked tests excluded)
#   test_slow        ~3 min   (the excluded heavy MoE/decode/quant tests)
#   test_cli         ~3 min
#   test_big_modeling~2 min
#   test_models      ~7 min
#   test_checkpoint  ~2 min
#   test_multihost   ~4 min   (real OS processes)
#   test_examples    ~12 min  (30 example scripts end-to-end)
# Run shards SEQUENTIALLY: concurrent shards starve each other on this
# box (observed round 4).

.PHONY: test test_smoke test_core test_slow test_cli test_big_modeling \
        test_examples test_models test_multihost test_checkpoint quality bench \
        bench-input bench-ckpt bench-zero1 bench-serve bench-compile \
        bench-attn bench-check doctor lint profile chaos

PYTEST := python -m pytest -q

test:
	$(PYTEST) tests/

# <60s cross-subsystem signal: one marked test per subsystem (mesh, collectives,
# data loader, train step, bridge incl. CV, models, long-context, quantization,
# checkpointing, tracking, CLI, native C++, memory, utils)
test_smoke:
	$(PYTEST) tests/ -m smoke

test_core:
	$(PYTEST) tests/ -m "not slow" --ignore=tests/test_big_modeling.py \
	  --ignore=tests/test_examples.py --ignore=tests/test_cli.py \
	  --ignore=tests/test_multiprocess.py --ignore=tests/test_models.py \
	  --ignore=tests/test_t5.py --ignore=tests/test_convert.py \
	  --ignore=tests/test_bridge.py --ignore=tests/test_sharded_checkpoint.py \
	  --ignore=tests/test_native.py

# the slow-marked complement of test_core (heavy MoE/sharded-decode/quant
# end-to-end parity tests) — run in CI's long lane, like the reference's @slow.
# Same ignore list as test_core: slow tests living in the cli/models/etc
# shards already run there, and running them twice would double-bill the lane.
test_slow:
	$(PYTEST) tests/ -m slow --ignore=tests/test_big_modeling.py \
	  --ignore=tests/test_examples.py --ignore=tests/test_cli.py \
	  --ignore=tests/test_multiprocess.py --ignore=tests/test_models.py \
	  --ignore=tests/test_t5.py --ignore=tests/test_convert.py \
	  --ignore=tests/test_bridge.py --ignore=tests/test_sharded_checkpoint.py \
	  --ignore=tests/test_native.py

test_cli:
	$(PYTEST) tests/test_cli.py

test_big_modeling:
	$(PYTEST) tests/test_big_modeling.py

test_examples:
	$(PYTEST) tests/test_examples.py

test_models:
	$(PYTEST) tests/test_models.py tests/test_t5.py tests/test_convert.py \
	  tests/test_bridge.py tests/test_bridge_cv.py

test_multihost:
	$(PYTEST) tests/test_multiprocess.py

test_checkpoint:
	$(PYTEST) tests/test_sharded_checkpoint.py tests/test_native.py

quality:
	python -m compileall -q accelerate_tpu

# jaxlint: traced-code static analysis (host syncs, recompile hazards,
# donation bugs, rank-divergent collectives, trace-time nondeterminism).
# Exit 0 iff no findings beyond jaxlint-baseline.json and inline disables.
lint:
	JAX_PLATFORMS=cpu python -m accelerate_tpu.analysis lint accelerate_tpu/

bench:
	python bench.py

# sync-vs-prefetch input pipeline microbench (benchmarks/input_pipeline)
bench-input:
	python benchmarks/input_pipeline/run.py

# sync-vs-async checkpoint stall microbench (benchmarks/checkpoint)
bench-ckpt:
	python benchmarks/checkpoint/run.py

# fused-vs-annotation ZeRO-1 weight update (benchmarks/weight_update):
# step time, opt-state bytes/replica, comms-overlap ratio
bench-zero1:
	python benchmarks/weight_update/run.py

# continuous-vs-static batching through the paged-KV serving engine under a
# seeded Poisson open-loop load (aggregate tok/s ratio, batch occupancy,
# p50/p99 per-request latency), plus the replicated-router leg: tok/s
# scaling over N replicas and no-lost-requests + output parity under a
# replica kill — re-run once with tracing armed (gap-free span trees for
# every completion incl. failover hops, tracing tok/s tax reported) —
# plus the shared-prefix leg: prefix cache on/off over one seeded
# system-prompt workload (prefill-token reduction, hit rate, bitwise
# output parity, zero recompiles) (benchmarks/serving)
bench-serve:
	python benchmarks/serving/run.py

# attention kernel grid (benchmarks/attention): fwd+bwd µs/token and
# fraction-of-roofline over impl × seq × dtype × sparsity — the measurement
# behind ops.attention.ATTN_CROSSOVER_S — plus the fp8-vs-bf16 llama
# train-step leg (dtype_recipe="fp8" through fp8_dot)
bench-attn:
	python benchmarks/attention/run.py

# zero-cold-start recovery (benchmarks/compile_time, compile_cache/):
# restart-to-first-step and replica-boot-to-first-token, cold vs warm
# through the persistent AOT executable cache, with hit/miss counts from
# the compile_cache telemetry records in the payload
bench-compile:
	python benchmarks/compile_time/run.py

# perf-regression sentinel (telemetry/regress.py): compare the two newest
# comparable BENCH_*.json payloads in BENCH_DIR (default: repo root) against
# the per-metric tolerance registry. Exit 1 on regression, 2 when the
# environments' fingerprints differ (refusal, not a verdict).
BENCH_DIR ?= .
bench-check:
	JAX_PLATFORMS=cpu python -m accelerate_tpu.telemetry regress --scan $(BENCH_DIR)

# self-check: flight-recorder dump, watchdog stall detection, straggler
# report, collective-divergence detection, the jaxlint engine, perf cost
# capture, xplane trace parsing, the performance report section, fused
# ZeRO-1, elastic auto-resume, the serving engine, the replicated
# serving router (2 replicas, one chaos-killed mid-load, exactly-once +
# bitwise parity), the persistent compile cache (subprocess restart
# hits with zero recompiles; poisoned entry quarantined + clean fallback),
# the prefix cache + COW, the observability plane (traced 2-replica
# router under an injected kill: gap-free span trees, /metrics scrape
# matching the report, slo_violation under a tight objective), and the
# disaggregated prefill/decode tier (2+2 fleet with a corrupted and a
# dropped KV handoff: exactly-once + bitwise parity across the handoff),
# and the goodput ledger (a supervised chaos run whose injected SIGKILL
# and slow-data badput the ledger must attribute to cause, <5% of
# wall-clock unattributed), and the live observability plane (a
# supervised restart tailed live across a torn line with exactly one
# anomaly episode, a seeded canary corruption drained with the
# mismatching token named, and `top --once` rendering the post-hoc
# report's sections string-exact), and fp8 through fused ZeRO-1 (an fp8
# train step on 8 virtual devices keeping the fused bucketed path engaged
# with fp8 metadata as passthrough slots, 1/N opt-state sharding, stage-0
# loss parity, and a frozen jit cache) against synthetic inputs
# (telemetry/report.py run_doctor)
doctor:
	JAX_PLATFORMS=cpu python -m accelerate_tpu.telemetry doctor

# performance observatory: a few traced bench train steps on the CPU backend
# -> printed "performance" report section (MFU, roofline, top ops, overlap)
profile:
	JAX_PLATFORMS=cpu python benchmarks/perf/run.py

# chaos e2e (resilience/chaos.py): fault-free reference run, then the same
# toy training run supervised under a seeded SIGKILL schedule — the
# supervisor must auto-resume from the last committed checkpoint and finish
# with BITWISE-identical final params. CPU-only, tier-1-safe.
chaos:
	JAX_PLATFORMS=cpu python -m accelerate_tpu.resilience.chaos
