"""Live observability plane tests (ISSUE 15): request-scoped distributed
tracing, the streaming metrics exporter, and SLO burn-rate monitoring.

The acceptance lines these tests hold:

- one request = ONE coherent span tree across router → replica → engine
  (admission/queue wait, dispatch, per-chunk prefill, batched decode steps,
  completion), across BOTH replica transports, with failover retry lineage
  (a chaos-killed replica's request shows two dispatch spans under one
  trace_id) — and ZERO cost when tracing is disarmed;
- the /metrics endpoint serves parseable Prometheus text whose histograms
  agree with the report CLI (same fixed-bucket math — the repo's ONE
  histogram/percentile implementation, ratcheted);
- SLO burn rates fire exactly one violation record per episode over the
  fast/slow window pair (synthetic clock).
"""

import dataclasses
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.models import LlamaConfig, init_llama
from accelerate_tpu.serving import (
    AdmissionController,
    BucketLattice,
    LocalReplica,
    ProcessReplica,
    ReplicaSpec,
    RouterRequestStatus,
    ServingEngine,
    ServingRouter,
)
from accelerate_tpu.telemetry import events as tel
from accelerate_tpu.telemetry import metrics, slo, tracing

CONFIG = LlamaConfig.tiny()


@pytest.fixture(scope="module")
def params():
    return jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16), init_llama(CONFIG, jax.random.PRNGKey(0))
    )


@pytest.fixture(autouse=True)
def _clean_observability_state():
    """Every test starts and ends with the plane disarmed (module-level
    singletons, same discipline as the events tests)."""
    tracing.disarm()
    metrics.disable()
    tel.disable()
    yield
    tracing.disarm()
    metrics.disable()
    tel.disable()


def _replica_spec(**overrides) -> ReplicaSpec:
    kw = dict(
        model=dataclasses.asdict(CONFIG), num_blocks=33, block_size=8,
        max_slots=2, slot_buckets=(2,), block_buckets=(6,), prefill_buckets=(16,),
    )
    kw.update(overrides)
    return ReplicaSpec(**kw)


# ---------------------------------------------------------------------------
# histogram / percentile math (the shared implementation)


class TestHistogram:
    def test_bucket_counts_are_cumulative_with_inf_overflow(self):
        h = metrics.Histogram("h", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.01, 0.05, 0.5, 7.0):
            h.observe(v)
        # le is inclusive: 0.01 lands in its own bucket, 7.0 only in +Inf
        assert h.cumulative_counts() == [2, 3, 4]
        assert h.count == 5 and h.max == 7.0
        assert h.sum == pytest.approx(7.565)

    def test_quantile_interpolates_within_the_covering_bucket(self):
        h = metrics.Histogram("h", buckets=(1.0, 2.0, 4.0))
        h.observe_many([0.5] * 2 + [1.5] * 2)  # cumulative [2, 4, 4]
        # rank 2 sits exactly at the first bound; rank 3 is halfway into
        # (1, 2]
        assert h.quantile(0.5) == pytest.approx(1.0)
        assert h.quantile(0.75) == pytest.approx(1.5)
        # past the last finite bound: the honest answer is that bound
        h2 = metrics.Histogram("h2", buckets=(1.0,))
        h2.observe(5.0)
        assert h2.quantile(0.99) == 1.0

    def test_dict_roundtrip_preserves_quantiles(self):
        h = metrics.Histogram("h")
        h.observe_many([0.004, 0.03, 0.03, 0.4, 2.0, 80.0])
        rt = metrics.Histogram.from_dict("h", h.to_dict())
        assert rt.cumulative_counts() == h.cumulative_counts()
        for q in (0.5, 0.9, 0.99):
            assert rt.quantile(q) == pytest.approx(h.quantile(q))

    def test_hist_dist_matches_a_scrape_of_the_same_values(self):
        """The report-vs-scrape agreement in miniature: hist_dist (the
        serving/router report sections) and a parsed /metrics scrape of the
        same observations must compute identical percentiles."""
        values = [0.004, 0.031, 0.032, 0.41, 0.09, 0.02]
        reg = metrics.MetricsRegistry()
        reg.histogram("accelerate_x_seconds").observe_many(values)
        scraped = metrics.histogram_from_scrape(
            metrics.parse_prometheus_text(reg.render()), "accelerate_x_seconds"
        )
        dist = metrics.hist_dist(values)
        assert scraped.count == dist["count"]
        assert scraped.quantile(0.5) == pytest.approx(dist["p50"], abs=1e-9)
        assert scraped.quantile(0.99) == pytest.approx(dist["p99"], abs=1e-9)

    def test_percentile_is_nearest_rank(self):
        assert metrics.percentile([], 50) == 0.0
        assert metrics.percentile([3.0, 1.0, 2.0], 50) == 2.0
        assert metrics.percentile([1.0, 2.0, 3.0, 4.0], 99) == 4.0

    def test_no_private_percentile_helpers_remain(self):
        """ISSUE 15 ratchet (the PR 7 peak-registry pattern): the repo has
        exactly ONE percentile/histogram implementation —
        telemetry/metrics.py. A reintroduced private `def percentile` /
        `def _percentile` anywhere in shipped code is a regression."""
        import os
        import re

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        pattern = re.compile(r"^\s*def\s+_?percentile\s*\(", re.M)
        offenders = []
        roots = ["accelerate_tpu", "benchmarks", "tools", "bench.py"]
        for root in roots:
            root_path = os.path.join(repo, root)
            files = (
                [root_path] if root_path.endswith(".py")
                else [
                    os.path.join(dirpath, f)
                    for dirpath, _, names in os.walk(root_path)
                    for f in names
                    if f.endswith(".py")
                ]
            )
            for path in files:
                if path.endswith(os.path.join("telemetry", "metrics.py")):
                    continue
                with open(path) as fh:
                    if pattern.search(fh.read()):
                        offenders.append(os.path.relpath(path, repo))
        assert offenders == [], (
            f"private percentile helpers reintroduced: {offenders} — "
            "import telemetry.metrics.percentile instead"
        )


# ---------------------------------------------------------------------------
# metrics registry + exporter


class TestMetricsExporter:
    def test_prometheus_text_format_golden(self):
        """The exposition format is a wire contract — hold it to a golden."""
        reg = metrics.MetricsRegistry()
        reg.counter("accelerate_requests_total").inc(3, outcome="finished")
        reg.counter("accelerate_requests_total").inc(1, outcome="shed")
        reg.gauge("accelerate_queue_depth").set(4)
        reg.histogram("accelerate_ttft_seconds", buckets=(0.1, 1.0)).observe_many(
            [0.05, 0.5, 0.5]
        )
        assert reg.render() == (
            "# HELP accelerate_queue_depth \n"
            "# TYPE accelerate_queue_depth gauge\n"
            "accelerate_queue_depth 4\n"
            "# HELP accelerate_requests_total \n"
            "# TYPE accelerate_requests_total counter\n"
            'accelerate_requests_total{outcome="finished"} 3\n'
            'accelerate_requests_total{outcome="shed"} 1\n'
            "# HELP accelerate_ttft_seconds \n"
            "# TYPE accelerate_ttft_seconds histogram\n"
            'accelerate_ttft_seconds_bucket{le="0.1"} 1\n'
            'accelerate_ttft_seconds_bucket{le="1"} 3\n'
            'accelerate_ttft_seconds_bucket{le="+Inf"} 3\n'
            "accelerate_ttft_seconds_sum 1.05\n"
            "accelerate_ttft_seconds_count 3\n"
        )

    def test_http_endpoint_serves_and_parses(self):
        metrics.enable()
        metrics.observe("accelerate_ttft_seconds", 0.02)
        metrics.inc("accelerate_requests_total", outcome="finished")
        try:
            metrics.serve(0)
            port = metrics.server_port()
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ).read().decode()
            families = metrics.parse_prometheus_text(body)
            assert families["accelerate_requests_total"]["type"] == "counter"
            hist = metrics.histogram_from_scrape(families, "accelerate_ttft_seconds")
            assert hist is not None and hist.count == 1
            # non-metrics paths 404
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(f"http://127.0.0.1:{port}/nope", timeout=10)
        finally:
            metrics.disable()
        assert metrics.server_port() is None

    def test_healthz_readiness_endpoint(self):
        """``/healthz`` answers 200 while the exporter is live and 503 the
        moment shutdown begins — the readiness flag flips BEFORE the socket
        dies, so a probe racing stop_server() sees not-ready instead of a
        connection reset, and a re-serve() re-arms readiness."""
        metrics.enable()
        try:
            metrics.serve(0)
            port = metrics.server_port()
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10
            )
            assert resp.status == 200 and resp.read() == b"ok\n"
            # the shutdown window: readiness flips first, socket still up
            metrics._SHUTTING_DOWN = True
            try:
                with pytest.raises(urllib.error.HTTPError) as excinfo:
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz", timeout=10
                    )
                assert excinfo.value.code == 503
                assert excinfo.value.read() == b"shutting down\n"
            finally:
                metrics._SHUTTING_DOWN = False
            metrics.stop_server()
            assert metrics.server_port() is None
            # a fresh serve() must not inherit the stale shutdown flag
            metrics.serve(0)
            port = metrics.server_port()
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10
            )
            assert resp.status == 200
        finally:
            metrics.disable()

    def test_snapshot_record_lands_in_telemetry(self, tmp_path):
        tel.enable(out_dir=str(tmp_path), run_id="m")
        metrics.enable()
        metrics.inc("accelerate_decode_tokens_total", 7)
        metrics.observe("accelerate_ttft_seconds", 0.2)
        metrics.snapshot_now()
        tel.disable()
        recs = [json.loads(l) for l in open(tmp_path / "events-rank0.jsonl")]
        snaps = [r for r in recs if r["kind"] == "metrics"]
        assert len(snaps) == 1
        payload = snaps[0]["metrics"]
        assert payload["accelerate_decode_tokens_total"]["value"] == 7
        assert payload["accelerate_ttft_seconds"]["count"] == 1
        # a persisted histogram rebuilds into the same quantile math
        h = metrics.Histogram.from_dict(
            "accelerate_ttft_seconds", payload["accelerate_ttft_seconds"]
        )
        assert h.quantile(0.5) > 0

    def test_maybe_snapshot_is_throttled(self, tmp_path, monkeypatch):
        monkeypatch.setenv(metrics.METRICS_SNAPSHOT_ENV_VAR, "3600")
        tel.enable(out_dir=str(tmp_path), run_id="m")
        metrics.enable()
        metrics.inc("x_total")
        assert metrics.maybe_snapshot() is True
        assert metrics.maybe_snapshot() is False  # inside the interval
        tel.disable()
        recs = [json.loads(l) for l in open(tmp_path / "events-rank0.jsonl")]
        assert sum(1 for r in recs if r["kind"] == "metrics") == 1

    def test_port_env_arms_registry_and_server(self, monkeypatch):
        monkeypatch.setenv(metrics.METRICS_PORT_ENV_VAR, "0")
        try:
            assert metrics.maybe_enable_from_env() is not None
            assert metrics.server_port() is not None
        finally:
            metrics.disable()

    def test_label_values_escape_and_roundtrip(self):
        """Label values are user-controlled (replica names): quotes, commas,
        backslashes and newlines must render as valid exposition and parse
        back to the original value."""
        reg = metrics.MetricsRegistry()
        hostile = 'r"0,\\weird\nname'
        reg.counter("accelerate_replica_deaths_total").inc(2, replica=hostile)
        text = reg.render()
        sample_lines = [l for l in text.splitlines() if not l.startswith("#")]
        assert len(sample_lines) == 1  # the raw newline was escaped, not emitted
        fams = metrics.parse_prometheus_text(text)
        samples = fams["accelerate_replica_deaths_total"]["samples"]
        (name, labels, value), = samples
        assert labels == {"replica": hostile} and value == 2

    def test_serve_never_crashes_on_bind_conflict_or_port_change(self):
        """A bind failure (a child inheriting the parent's fixed port) must
        degrade to registry-only with a warning, and a second serve() on a
        different port must warn instead of silently lying about where the
        exporter listens."""
        import socket

        blocker = socket.socket()
        blocker.bind(("127.0.0.1", 0))
        taken = blocker.getsockname()[1]
        try:
            with pytest.warns(UserWarning, match="could not bind"):
                assert metrics.serve(taken) is None
            assert metrics.get_registry() is not None  # armed despite the miss
            assert metrics.server_port() is None
            first = metrics.serve(0)
            assert first is not None
            with pytest.warns(UserWarning, match="already bound"):
                assert metrics.serve(taken) is first  # kept, loudly
        finally:
            blocker.close()
            metrics.disable()

    def test_process_replica_child_env_drops_the_metrics_port(self, monkeypatch):
        """ProcessReplica children must NOT inherit ACCELERATE_METRICS_PORT:
        the router host owns the scrape endpoint, and N children racing one
        fixed port would each degrade to a warning serving nobody."""
        import io

        from accelerate_tpu.serving import replica as replica_mod

        captured = {}

        class _FakeProc:
            stdout = io.StringIO("")

            def __init__(self, cmd, env=None, **kw):
                captured["env"] = env

            stdin = io.StringIO()

            def poll(self):
                return None

            def kill(self):
                pass

        monkeypatch.setattr(
            replica_mod.subprocess, "Popen", lambda *a, **kw: _FakeProc(a, **kw)
        )
        monkeypatch.setenv(metrics.METRICS_PORT_ENV_VAR, "9102")
        ProcessReplica("p", _replica_spec())
        assert metrics.METRICS_PORT_ENV_VAR not in captured["env"]
        assert replica_mod.REPLICA_SPEC_ENV_VAR in captured["env"]


# ---------------------------------------------------------------------------
# tracing: span model + propagation


class TestTracing:
    def test_span_tree_validation_catches_gaps(self):
        tracing.arm(1.0)
        ctx = tracing.new_trace()
        root = tracing.span_open(ctx, "request")
        child = tracing.span_open(ctx, "work", parent_id=root["span_id"])
        tracing.span_close(child)
        tracing.span_close(root)
        assert tracing.validate_span_tree([root, child]) == []
        # orphan parent
        orphan = dict(child, parent_id="deadbeef", span_id="f00d")
        assert any("orphaned" in p for p in tracing.validate_span_tree([root, orphan]))
        # two roots
        root2 = tracing.span_close(tracing.span_open(ctx, "request2"))
        assert any("root" in p for p in tracing.validate_span_tree([root, root2]))
        # never closed
        open_span = tracing.span_open(ctx, "hang", parent_id=root["span_id"])
        assert any("never closed" in p
                   for p in tracing.validate_span_tree([root, open_span]))

    def test_sampling_is_deterministic_per_trace_and_forced_emit_wins(self, tmp_path):
        tracing.arm(0.5)
        kept = [tracing.new_trace().sampled for _ in range(400)]
        assert 0.35 < sum(kept) / len(kept) < 0.65
        # an unsampled trace still emits when forced (the SHED/FAILED path)
        tel.enable(out_dir=str(tmp_path), run_id="t")
        ctx = tracing.new_trace(sampled=False)
        span = tracing.span_close(tracing.span_open(ctx, "request"))
        assert tracing.finish_trace(ctx, [span]) is False
        assert tracing.finish_trace(ctx, [span], forced=True) is True
        tel.disable()
        recs = [json.loads(l) for l in open(tmp_path / "events-rank0.jsonl")]
        assert sum(1 for r in recs if r["kind"] == "span") == 1

    def test_arm_from_env(self, monkeypatch):
        monkeypatch.setenv(tracing.TRACE_SAMPLE_ENV_VAR, "0.25")
        assert tracing.maybe_arm_from_env() == 0.25
        tracing.disarm()
        monkeypatch.setenv(tracing.TRACE_SAMPLE_ENV_VAR, "garbage")
        assert tracing.maybe_arm_from_env() is None
        monkeypatch.setenv(tracing.TRACE_SAMPLE_ENV_VAR, "1")
        assert tracing.maybe_arm_from_env() == 1.0

    def test_chrome_trace_export_shape(self):
        tracing.arm(1.0)
        ctx = tracing.new_trace()
        root = tracing.span_close(tracing.span_open(ctx, "request", component="router"))
        out = tracing.chrome_trace([root])
        events = [e for e in out["traceEvents"] if e["ph"] == "X"]
        assert events[0]["name"] == "request" and events[0]["ts"] >= 0
        assert any(e["ph"] == "M" for e in out["traceEvents"])  # lane names


class TestEngineTracing:
    def test_engine_spans_cover_queue_prefill_chunks_and_decode(self, params, tmp_path):
        tel.enable(out_dir=str(tmp_path), run_id="eng")
        tracing.arm(1.0)
        engine = ServingEngine(
            params, CONFIG, num_blocks=33, block_size=8, max_slots=4,
            lattice=BucketLattice(slot_buckets=(2, 4), block_buckets=(8,),
                                  prefill_buckets=(16, 32)),
        )
        engine.warmup()
        req = engine.submit(np.arange(1, 40, dtype=np.int32), 5)  # chunks past 32
        engine.run()
        tel.disable()
        assert req._trace_owner
        assert tracing.validate_span_tree(req.trace_spans) == []
        names = [s["name"] for s in req.trace_spans]
        assert names.count("prefill_chunk") == 2  # 32-bucket chunk + 16-bucket tail
        assert names.count("decode_step") == 4  # 5 tokens, first from prefill
        chunk_buckets = [
            s["attrs"]["bucket"] for s in req.trace_spans if s["name"] == "prefill_chunk"
        ]
        assert chunk_buckets == [32, 16]
        # the engine owned the trace: every span is in the event stream
        recs = [json.loads(l) for l in open(tmp_path / "events-rank0.jsonl")]
        assert sum(1 for r in recs if r["kind"] == "span") == len(req.trace_spans)

    def test_prefix_cache_annotations_ride_the_prefill_span(self, params):
        tracing.arm(1.0)
        engine = ServingEngine(
            params, CONFIG, num_blocks=65, block_size=8, max_slots=4,
            lattice=BucketLattice(slot_buckets=(2, 4), block_buckets=(8,),
                                  prefill_buckets=(32,)),
            prefix_cache=True,
        )
        engine.warmup()
        rng = np.random.default_rng(3)
        shared = rng.integers(0, CONFIG.vocab_size, (24,)).astype(np.int32)
        a = engine.submit(np.concatenate([shared, np.arange(5, dtype=np.int32)]), 4,
                          rng_seed=0)
        engine.step()
        b = engine.submit(np.concatenate([shared, np.arange(9, dtype=np.int32)]), 4,
                          rng_seed=1)
        engine.run()
        prefill_a = next(s for s in a.trace_spans if s["name"] == "prefill")
        prefill_b = next(s for s in b.trace_spans if s["name"] == "prefill")
        assert prefill_a["attrs"]["cached_tokens"] == 0
        assert prefill_b["attrs"]["cached_tokens"] == 24  # the shared 3 blocks

    def test_unsampled_trace_skips_per_token_spans(self, params):
        """The sampling knob bounds RECORDING cost, not just emission: an
        unsampled context keeps only the cheap structural spans (root/queue/
        prefill) — no decode_step dict per generated token."""
        tracing.arm(1.0)
        engine = ServingEngine(
            params, CONFIG, num_blocks=17, block_size=8, max_slots=2,
            lattice=BucketLattice(slot_buckets=(2,), block_buckets=(4,),
                                  prefill_buckets=(16,)),
        )
        engine.warmup()
        ctx = tracing.new_trace(sampled=False)
        req = engine.submit(np.arange(1, 6, dtype=np.int32), 6, trace=dict(ctx))
        engine.run()
        names = [s["name"] for s in req.trace_spans]
        assert "decode_step" not in names
        assert "prefill" in names and "engine_request" in names
        # a sampled ctx on the same engine records the full detail
        req2 = engine.submit(
            np.arange(1, 6, dtype=np.int32), 6,
            trace=dict(tracing.new_trace(sampled=True)),
        )
        engine.run()
        assert [s["name"] for s in req2.trace_spans].count("decode_step") == 5

    def test_disabled_path_zero_cost(self, params, tmp_path, monkeypatch):
        """Tracing/metrics disarmed: no context, no spans, no registry, no
        exporter thread, no files — the hot-path additions are one branch
        (the PR 4/7 smoke pattern)."""
        monkeypatch.chdir(tmp_path)
        before = {t.name for t in threading.enumerate()}
        engine = ServingEngine(
            params, CONFIG, num_blocks=17, block_size=8, max_slots=2,
            lattice=BucketLattice(slot_buckets=(2,), block_buckets=(4,),
                                  prefill_buckets=(16,)),
        )
        engine.warmup()
        req = engine.submit(np.arange(1, 6, dtype=np.int32), 3)
        engine.run()
        assert req.trace is None and req.trace_spans == []
        assert req._span_root is None and not req._trace_owner
        assert metrics.get_registry() is None
        assert metrics.server_port() is None
        assert not tracing.is_armed()
        after = {t.name for t in threading.enumerate()}
        assert "accelerate-tpu-metrics" not in after - before
        assert not list(tmp_path.iterdir())  # no artifacts anywhere


# ---------------------------------------------------------------------------
# cross-transport propagation + failover continuity


class TestRouterTracing:
    def test_local_replica_failover_keeps_one_trace_with_two_dispatch_spans(self):
        """Trace continuity through an abrupt replica death (thread
        transport): the retried request's tree stays gap-free and shows its
        retry lineage — two dispatch spans, one trace_id, the first closed
        ``failover`` and the last ``finished``."""
        tracing.arm(1.0)
        router = ServingRouter(
            [LocalReplica(f"r{i}", _replica_spec()) for i in range(2)],
            admission=AdmissionController(max_queue=16),
            health_timeout_s=5.0,
        )
        try:
            router.wait_ready(timeout_s=300)
            rng = np.random.default_rng(0)
            reqs = [
                router.submit(
                    rng.integers(0, CONFIG.vocab_size, (8,)).astype(np.int32),
                    24, rng_seed=i,
                )
                for i in range(4)
            ]
            deadline = time.monotonic() + 120
            while not any(len(r.generated) >= 2 for r in reqs):
                router.poll()
                time.sleep(0.002)
                assert time.monotonic() < deadline, "no tokens flowed"
            router.replicas["r0"].kill()
            router.run(timeout_s=300)
        finally:
            router.close()
        assert router.failovers >= 1
        assert all(r.status is RouterRequestStatus.FINISHED for r in reqs)
        for r in reqs:
            assert tracing.validate_span_tree(r.trace_spans) == []
        retried = [r for r in reqs if r.retries > 0]
        assert retried
        for r in retried:
            assert len({s["trace_id"] for s in r.trace_spans}) == 1
            dispatches = [s for s in r.trace_spans if s["name"] == "dispatch"]
            assert len(dispatches) >= 2
            outcomes = [s["attrs"].get("outcome") for s in dispatches]
            assert "failover" in outcomes and outcomes[-1] == "finished"
            assert [s["attrs"]["attempt"] for s in dispatches] == list(
                range(len(dispatches))
            )

    def test_process_replica_propagates_context_and_ships_spans(self):
        """The JSON-lines transport carries the context out and the spans
        back: a ProcessReplica child (its own OS process) parents its engine
        spans under the router's dispatch span."""
        tracing.arm(1.0)
        router = ServingRouter(
            [ProcessReplica("p0", _replica_spec(), env=dict(
                __import__("os").environ, JAX_PLATFORMS="cpu"
            ))],
            admission=AdmissionController(max_queue=8),
            health_timeout_s=120.0,
        )
        try:
            router.wait_ready(timeout_s=300)
            req = router.submit(np.arange(1, 9, dtype=np.int32), 4, rng_seed=0)
            router.run(timeout_s=300)
        finally:
            router.close()
        assert req.status is RouterRequestStatus.FINISHED
        assert tracing.validate_span_tree(req.trace_spans) == []
        names = [s["name"] for s in req.trace_spans]
        for want in ("request", "admission", "dispatch", "engine_request",
                     "queue_wait", "prefill", "decode_step"):
            assert want in names, (want, names)
        dispatch = next(s for s in req.trace_spans if s["name"] == "dispatch")
        engine_root = next(s for s in req.trace_spans if s["name"] == "engine_request")
        assert engine_root["parent_id"] == dispatch["span_id"]
        assert engine_root["trace_id"] == dispatch["trace_id"]

    @pytest.mark.slow  # real SIGKILL needs a second warmed child process
    def test_process_replica_sigkill_failover_trace_continuity(self):
        """The ISSUE 15 tier: a seeded chaos SIGKILL takes a ProcessReplica
        down mid-decode; the survivor finishes the work and the retried
        request's trace shows both dispatch hops under one trace_id."""
        import os

        from accelerate_tpu.resilience.chaos import ChaosSchedule, Fault

        tracing.arm(1.0)
        schedule = ChaosSchedule(
            faults=[Fault(kind="sigkill", point="serving_decode", step=6)]
        ).to_json()
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        router = ServingRouter(
            [
                ProcessReplica("k0", _replica_spec(), chaos_schedule=schedule, env=env),
                ProcessReplica("k1", _replica_spec(), env=env),
            ],
            admission=AdmissionController(max_queue=16),
            health_timeout_s=120.0,
        )
        try:
            router.wait_ready(timeout_s=600)
            rng = np.random.default_rng(1)
            reqs = [
                router.submit(
                    rng.integers(0, CONFIG.vocab_size, (8,)).astype(np.int32),
                    16, rng_seed=i,
                )
                for i in range(4)
            ]
            router.run(timeout_s=600)
        finally:
            router.close()
        assert router.failovers >= 1
        assert all(r.status is RouterRequestStatus.FINISHED for r in reqs)
        retried = [r for r in reqs if r.retries > 0]
        assert retried
        for r in retried:
            assert tracing.validate_span_tree(r.trace_spans) == []
            assert len({s["trace_id"] for s in r.trace_spans}) == 1
            assert sum(1 for s in r.trace_spans if s["name"] == "dispatch") >= 2

    def test_shed_request_trace_is_force_emitted(self, tmp_path):
        """SHED/FAILED traces are kept even when unsampled — the requests an
        operator is guaranteed to ask about."""
        tel.enable(out_dir=str(tmp_path), run_id="shed")
        tracing.arm(0.000001)  # nothing would survive sampling
        router = ServingRouter(
            [LocalReplica("r0", _replica_spec())],
            admission=AdmissionController(max_queue=1),
            health_timeout_s=30.0,
        )
        try:
            router.wait_ready(timeout_s=300)
            small = np.arange(4, dtype=np.int32) + 1
            keep = [router.submit(small, 4, rng_seed=i) for i in range(3)]
            shed = [r for r in keep if r.status is RouterRequestStatus.SHED]
            assert shed  # queue bound 1: the overflow shed at submit
            router.run(timeout_s=300)
        finally:
            router.close()
        tel.disable()
        recs = [json.loads(l) for l in open(tmp_path / "events-rank0.jsonl")]
        spans = [r for r in recs if r["kind"] == "span"]
        shed_roots = [
            s for s in spans
            if not s.get("parent_id") and s.get("attrs", {}).get("outcome") == "shed"
        ]
        assert len(shed_roots) == len(shed)

    def test_router_disabled_path_zero_cost(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        router = ServingRouter(
            [LocalReplica("r0", _replica_spec())],
            admission=AdmissionController(max_queue=8),
        )
        try:
            router.wait_ready(timeout_s=300)
            req = router.submit(np.arange(1, 6, dtype=np.int32), 3, rng_seed=0)
            router.run(timeout_s=300)
        finally:
            router.close()
        assert req.status is RouterRequestStatus.FINISHED
        assert req.trace is None and req.trace_spans == []
        assert metrics.get_registry() is None
        assert not list(tmp_path.iterdir())


# ---------------------------------------------------------------------------
# SLO burn rates (synthetic clock)


class TestSLO:
    def _monitor(self, clock, **kw):
        objective = slo.SLObjective(
            name="ttft", kind="latency", threshold_s=0.1, target=0.99,
            fast_window_s=300.0, slow_window_s=3600.0, burn_threshold=14.4,
        )
        return slo.SLOMonitor([objective], clock=clock, **kw)

    def test_violation_needs_both_windows_and_min_events(self):
        clock = [0.0]
        mon = self._monitor(lambda: clock[0], min_events=10)
        # below min_events: even 100% bad must not page
        for _ in range(5):
            clock[0] += 1
            mon.observe("ttft", value=9.0)
        assert not mon.evaluate(emit=False)[0]["violating"]
        for _ in range(10):
            clock[0] += 1
            mon.observe("ttft", value=9.0)
        rec = mon.evaluate(emit=False)[0]
        assert rec["violating"] and rec["fast_burn"] >= 14.4 <= rec["slow_burn"]

    def test_one_record_per_episode_with_fast_window_recovery(self, tmp_path):
        clock = [0.0]
        mon = self._monitor(lambda: clock[0], min_events=5)
        tel.enable(out_dir=str(tmp_path), run_id="slo")
        for _ in range(10):
            clock[0] += 1
            mon.observe("ttft", value=9.0)
        mon.evaluate()
        mon.evaluate()  # still burning: same episode, no second record
        assert mon.stats()["ttft"]["violations"] == 1
        # fast window ages the bad events out under good traffic -> re-arm
        for _ in range(40):
            clock[0] += 15
            mon.observe("ttft", value=0.01)
        assert not mon.evaluate()[0]["violating"]
        for _ in range(10):
            clock[0] += 1
            mon.observe("ttft", value=9.0)
        mon.evaluate()
        assert mon.stats()["ttft"]["violations"] == 2
        tel.disable()
        recs = [json.loads(l) for l in open(tmp_path / "events-rank0.jsonl")]
        violations = [r for r in recs if r["kind"] == "slo_violation"]
        assert len(violations) == 2
        assert violations[0]["slo"] == "ttft" and violations[0]["fast_burn"] > 14.4

    def test_fast_blip_alone_does_not_violate_slow_window(self):
        """The multi-window point: a burst that saturates the fast window
        but is diluted across the slow one must not page."""
        clock = [0.0]
        mon = self._monitor(lambda: clock[0], min_events=10)
        # 3000 good events spread over 50 minutes
        for _ in range(3000):
            clock[0] += 1
            mon.observe("ttft", value=0.01)
        # a 60-event bad blip at the end: ~20% of the fast window is bad
        # (burn 20x), but the slow window still holds the 3000 good events
        # (burn ~2x) — no page
        for _ in range(60):
            clock[0] += 1
            mon.observe("ttft", value=9.0)
        rec = mon.evaluate(emit=False)[0]
        assert rec["fast_burn"] >= 14.4
        assert rec["slow_burn"] < 14.4
        assert not rec["violating"]

    def test_burning_sources_attributes_the_bad_replica(self):
        clock = [0.0]
        mon = self._monitor(lambda: clock[0], min_events=5)
        for _ in range(10):
            clock[0] += 1
            mon.observe("ttft", value=0.01, source="r0")
            mon.observe("ttft", value=9.0, source="r1")
        assert mon.burning_sources("ttft") == ["r1"]

    def test_router_deprioritizes_burning_replica(self):
        """The DRAINING-pressure hook: with r0 burning its ttft window, new
        dispatch prefers r1 even when r0 has fewer outstanding tokens."""
        monitor = slo.SLOMonitor(
            slo.serving_slos(ttft_threshold_s=0.1), min_events=2,
        )
        router = ServingRouter(
            [LocalReplica(f"r{i}", _replica_spec()) for i in range(2)],
            admission=AdmissionController(max_queue=8),
            slo_monitor=monitor,
            slo_eval_interval_s=0.0,
        )
        try:
            router.wait_ready(timeout_s=300)
            for _ in range(6):
                monitor.observe("ttft", value=9.0, source="r0")
            router.poll()
            assert router._burning_replicas == {"r0"}
            req = router.submit(np.arange(1, 6, dtype=np.int32), 3, rng_seed=0)
            router.poll()
            assert req.replica == "r1"
            router.run(timeout_s=300)
        finally:
            router.close()

    def test_failover_survivor_is_not_blamed_for_inflated_ttft(self):
        """A failed-over request's ttft was inflated by the DEAD replica
        (death detection + re-prefill); attributing it to the survivor
        would drain exactly the replica that absorbed the work. Retried
        requests count toward the global burn only (source=None)."""
        from accelerate_tpu.serving.router import RouterRequest, RouterRequestStatus

        monitor = slo.SLOMonitor(slo.serving_slos(ttft_threshold_s=0.1), min_events=2)
        router = ServingRouter(
            [LocalReplica("r1", _replica_spec())],
            admission=AdmissionController(max_queue=4),
            slo_monitor=monitor,
        )
        try:
            router.wait_ready(timeout_s=300)
            req = RouterRequest(prompt=np.arange(1, 5, dtype=np.int32), max_new_tokens=4)
            req.replica = "r1"       # the SURVIVOR that finished the work
            req.retries = 1          # ...after a failover
            req.first_token_t = 9.0  # inflated by the dead replica's hop
            req.arrival_t = 0.0
            for _ in range(4):
                router._observe_slo(req, RouterRequestStatus.FINISHED, now=9.5)
            assert monitor.burning_sources("ttft", now=9.5) == []  # r1 not blamed
            # same events on an UN-retried request DO attribute
            req.retries = 0
            for _ in range(4):
                router._observe_slo(req, RouterRequestStatus.FINISHED, now=9.5)
            assert monitor.burning_sources("ttft", now=9.5) == ["r1"]
        finally:
            router.close()

    def test_stock_serving_slos_env_tuning(self, monkeypatch):
        monkeypatch.setenv(slo.SLO_TTFT_ENV_VAR, "0.25")
        monkeypatch.setenv(slo.SLO_AVAILABILITY_TARGET_ENV_VAR, "0.95")
        objectives = {o.name: o for o in slo.serving_slos()}
        assert objectives["ttft"].threshold_s == 0.25
        assert objectives["availability"].target == 0.95

    def test_accelerator_arms_step_latency_slo_from_env(self, monkeypatch):
        """ACCELERATE_SLO_STEP_LATENCY_S arms the Accelerator's step monitor
        (observe-per-step, evaluate-per-second); unset leaves the hot path a
        None-check. The end-to-end violation firing is proven by the
        supervisor test below (same monitor machinery)."""
        from accelerate_tpu import Accelerator

        acc = Accelerator()
        assert acc._step_slo_monitor is None
        monkeypatch.setenv(slo.SLO_STEP_LATENCY_ENV_VAR, "0.5")
        acc2 = Accelerator()
        mon = acc2._step_slo_monitor
        assert mon is not None and "step_latency" in mon.objectives
        assert mon.objectives["step_latency"].threshold_s == 0.5
        monkeypatch.setenv(slo.SLO_STEP_LATENCY_ENV_VAR, "garbage")
        assert Accelerator()._step_slo_monitor is None

    def test_supervisor_restart_downtime_slo_record(self, tmp_path, monkeypatch):
        """Training-side: a supervised child that dies once emits a restart
        record; with the downtime objective armed (tight threshold), the
        supervisor writes an slo_violation next to it."""
        import sys

        from accelerate_tpu.resilience.supervisor import RestartPolicy, Supervisor

        monkeypatch.setenv(slo.SLO_RESTART_DOWNTIME_ENV_VAR, "0.000001")
        done = tmp_path / "DONE"
        child = (
            "import os, signal\n"
            "if os.environ.get('ACCELERATE_RESTART_GENERATION', '0') == '0':\n"
            "    os.kill(os.getpid(), signal.SIGKILL)\n"
            f"open({str(done)!r}, 'w').write('ok')\n"
        )
        sup = Supervisor(
            [[sys.executable, "-c", child]],
            policy=RestartPolicy(max_restarts=2, backoff_base_s=0.05,
                                 grace_period_s=1.0),
            telemetry_dir=str(tmp_path),
        )
        assert sup.run() == 0
        recs = [
            json.loads(l) for l in open(tmp_path / "events-supervisor.jsonl")
        ]
        violations = [r for r in recs if r["kind"] == "slo_violation"]
        assert len(violations) == 1
        assert violations[0]["slo"] == "restart_downtime"
        assert violations[0]["generation"] == 1


# ---------------------------------------------------------------------------
# report CLI: SLO section, --request timeline, --trace-out


class TestReportIntegration:
    def _traced_run(self, params, out_dir):
        tel.enable(out_dir=str(out_dir), run_id="rep")
        tracing.arm(1.0)
        metrics.enable()
        engine = ServingEngine(
            params, CONFIG, num_blocks=33, block_size=8, max_slots=4,
            lattice=BucketLattice(slot_buckets=(2, 4), block_buckets=(8,),
                                  prefill_buckets=(32,)),
        )
        engine.warmup()
        reqs = [
            engine.submit(np.arange(1, 8 + i, dtype=np.int32), 4 + i, rng_seed=i)
            for i in range(2)
        ]
        engine.run()
        metrics.snapshot_now()
        tel.disable()
        return engine, reqs

    def test_request_timeline_and_chrome_export(self, params, tmp_path, capsys):
        from accelerate_tpu.telemetry.report import main as report_main

        _, reqs = self._traced_run(params, tmp_path)
        rid = reqs[0].rid
        trace_out = tmp_path / "t.json"
        assert report_main([
            "report", str(tmp_path), "--request", str(rid),
            "--trace-out", str(trace_out),
        ]) == 0
        out = capsys.readouterr().out
        assert f"request {rid}" in out
        for stage in ("engine_request", "queue_wait", "prefill", "decode_step"):
            assert stage in out
        assert "WARNING" not in out  # the tree is gap-free
        chrome = json.loads(trace_out.read_text())
        assert chrome["traceEvents"] and any(
            e.get("name") == "prefill" for e in chrome["traceEvents"]
        )
        # unknown rid: helpful failure naming what IS traced
        assert report_main(["report", str(tmp_path), "--request", "nope"]) == 1
        assert "no trace found" in capsys.readouterr().out

    def test_report_serving_ttft_matches_registry_histogram(self, params, tmp_path):
        """The scrape-vs-report acceptance line at unit scale: the serving
        section's ttft percentiles equal the registry histogram's quantiles
        over the same run (both are the shared fixed-bucket math)."""
        from accelerate_tpu.telemetry.report import build_report

        engine, reqs = self._traced_run(params, tmp_path)
        hist = metrics.get_registry().histogram("accelerate_engine_ttft_seconds")
        report = build_report([str(tmp_path)])
        ttft = report["serving"]["requests"]["ttft_s"]
        assert hist.count == ttft["count"] == len(reqs)
        # records round at 1e-6: agree to that precision
        assert hist.quantile(0.50) == pytest.approx(ttft["p50"], abs=2e-6)
        assert hist.quantile(0.99) == pytest.approx(ttft["p99"], abs=2e-6)

    def test_slo_section_renders(self, tmp_path):
        from accelerate_tpu.telemetry.report import build_report, format_report

        (tmp_path / "events-rank0.jsonl").write_text(
            json.dumps({"kind": "meta", "schema": 1, "run_id": "s",
                        "process_index": 0, "num_processes": 1}) + "\n"
            + json.dumps({
                "kind": "slo_violation", "t": 1.0, "slo": "ttft",
                "slo_kind": "latency", "target": 0.99, "threshold_s": 0.25,
                "fast_burn": 33.0, "slow_burn": 20.0, "fast_window_s": 300.0,
                "slow_window_s": 3600.0, "burn_threshold": 14.4,
                "violating": True,
            }) + "\n"
        )
        report = build_report([str(tmp_path)])
        section = report["slo"]
        assert section["violations"] == 1
        assert section["by_slo"]["ttft"]["worst_fast_burn"] == 33.0
        text = format_report(report)
        assert "SLO: 1 violation episode(s)" in text
        assert "ttft: 1 episode(s)" in text and "99.00% good @ 250ms" in text

    def test_report_without_slo_or_spans_omits_sections(self, tmp_path):
        from accelerate_tpu.telemetry.report import build_report, format_report

        (tmp_path / "events-rank0.jsonl").write_text(
            '{"kind": "meta", "schema": 1, "run_id": "r", "process_index": 0, '
            '"num_processes": 1}\n'
            # a legacy EventLog.span TIMING record (no trace_id) must not
            # read as a request trace
            '{"kind": "span", "t": 1.0, "name": "my_region", "dur_s": 0.5}\n'
        )
        report = build_report([str(tmp_path)])
        assert report["slo"] is None and report["traces"] == 0
        text = format_report(report)
        assert "SLO:" not in text and "traces:" not in text
