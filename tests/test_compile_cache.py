"""The persistent AOT executable cache (accelerate_tpu/compile_cache/):
crash-safe commits, defensive reads, quarantine-on-corruption, eviction
semantics, the kill switch, and the warm-restart consumers (ISSUE 13).

The invariants under test: a poisoned/torn/mismatched entry must NEVER crash
a restart or load the wrong executable (fallback compile + quarantine,
always); a kill -9 at any point of a store leaves only committed entries;
the cache key is stable across processes (or there is no warm restart); and
``ACCELERATE_COMPILE_CACHE=0`` is byte-identical to an uncached build.
"""

import json
import os
import pickle
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import compile_cache as cc
from accelerate_tpu.compile_cache.cache import CompileCache

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _child_env():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = os.pathsep.join(p for p in (REPO, env.get("PYTHONPATH")) if p)
    return env


@pytest.fixture(scope="module")
def step_fn():
    def step(p, x):
        return {"w": p["w"] - 0.1 * (p["w"] @ x)[:, None] * x[None, :]}

    return jax.jit(step)


@pytest.fixture(scope="module")
def step_args():
    return ({"w": jnp.ones((8, 8))}, jnp.ones((8,)))


def _populate(cache_dir, step_fn, step_args, name="step"):
    executable, outcome = cc.aot_compile(name, step_fn, step_args, directory=str(cache_dir))
    assert executable is not None
    return outcome


# ---------------------------------------------------------------------------
# store/load roundtrip + commit protocol


def test_miss_store_hit_roundtrip(tmp_path, step_fn, step_args):
    assert _populate(tmp_path, step_fn, step_args) == "miss"
    executable, outcome = cc.aot_compile("step", step_fn, step_args, directory=str(tmp_path))
    assert outcome == "hit"
    ref = step_fn(*step_args)
    got = executable(*step_args)
    np.testing.assert_array_equal(np.asarray(ref["w"]), np.asarray(got["w"]))
    cache = CompileCache(str(tmp_path))
    assert cache.stats()["entries"] == 1
    entry = cache.entries()[0]
    manifest = json.load(open(os.path.join(entry, cc.MANIFEST_NAME)))
    assert manifest["schema"] == cc.SCHEMA_VERSION
    assert manifest["payload"]["bytes"] == os.path.getsize(
        os.path.join(entry, cc.PAYLOAD_NAME)
    )


def test_load_only_probe_never_compiles(tmp_path, step_fn, step_args):
    from accelerate_tpu.telemetry import step_profiler as sp

    sp.install_compile_listener()
    loaded, key = cc.maybe_load_executable("step", step_fn, step_args, directory=str(tmp_path))
    assert loaded is None  # empty cache: miss, and load-only must NOT compile
    _populate(tmp_path, step_fn, step_args)
    c0 = sp.raw_compile_snapshot()[0]
    loaded, key = cc.maybe_load_executable("step", step_fn, step_args, directory=str(tmp_path))
    assert loaded is not None and key is not None
    got = loaded(*step_args)
    assert sp.raw_compile_snapshot()[0] == c0  # zero backend compiles on the warm path
    np.testing.assert_array_equal(
        np.asarray(step_fn(*step_args)["w"]), np.asarray(got["w"])
    )


def test_key_changes_with_fingerprint_and_identity_fields(step_fn, step_args):
    lowered = step_fn.lower(*step_args)
    k1 = cc.key_from_lowered("step", lowered)
    k2 = cc.key_from_lowered("renamed", lowered)
    assert k1.entry_id == k2.entry_id  # fn name is informational, not identity
    other = jax.jit(lambda p, x: {"w": p["w"] + x.sum()}).lower(*step_args)
    assert cc.key_from_lowered("step", other).entry_id != k1.entry_id
    import dataclasses

    bumped = dataclasses.replace(k1, jaxlib_version="9.9.9")
    assert bumped.entry_id != k1.entry_id
    retopo = dataclasses.replace(k1, mesh_axes=(("dp", 4),))
    assert retopo.entry_id != k1.entry_id


# ---------------------------------------------------------------------------
# defensive reads: corrupt / truncated / version / topology / swapped


def _entry(cache_dir):
    cache = CompileCache(str(cache_dir))
    entries = cache.entries()
    assert entries, "no committed entry"
    return cache, entries[0]


def _assert_fallback(tmp_path, step_fn, step_args, expect_reason_substr):
    """The poisoned load must report corrupt (never an executable), the entry
    must be quarantined, and the fallback compile must still be correct."""
    executable, outcome = cc.aot_compile("step", step_fn, step_args, directory=str(tmp_path))
    assert outcome == "corrupt"
    assert executable is not None  # the FALLBACK compile, not a cache load
    np.testing.assert_array_equal(
        np.asarray(step_fn(*step_args)["w"]), np.asarray(executable(*step_args)["w"])
    )
    cache = CompileCache(str(tmp_path))
    assert cache.stats()["quarantined"] >= 1
    qdir = cache.quarantine_dir()
    reasons = ""
    for q in os.listdir(qdir):
        reason_file = os.path.join(qdir, q, "QUARANTINE_REASON")
        if os.path.isfile(reason_file):
            reasons += open(reason_file).read()
    assert expect_reason_substr in reasons


def test_bitflipped_payload_quarantined_and_fallback(tmp_path, step_fn, step_args):
    _populate(tmp_path, step_fn, step_args)
    _, entry = _entry(tmp_path)
    payload = os.path.join(entry, cc.PAYLOAD_NAME)
    blob = bytearray(open(payload, "rb").read())
    blob[len(blob) // 3] ^= 0xFF
    open(payload, "wb").write(bytes(blob))
    _assert_fallback(tmp_path, step_fn, step_args, "CRC32 mismatch")


def test_truncated_payload_quarantined(tmp_path, step_fn, step_args):
    _populate(tmp_path, step_fn, step_args)
    _, entry = _entry(tmp_path)
    payload = os.path.join(entry, cc.PAYLOAD_NAME)
    blob = open(payload, "rb").read()
    open(payload, "wb").write(blob[: len(blob) // 2])
    _assert_fallback(tmp_path, step_fn, step_args, "truncated")


def test_version_mismatch_never_loads(tmp_path, step_fn, step_args):
    """A manifest claiming a different jaxlib under OUR entry id can only be
    tampering/corruption (an honest version difference hashes elsewhere) —
    quarantine + fallback, never a load."""
    _populate(tmp_path, step_fn, step_args)
    _, entry = _entry(tmp_path)
    mpath = os.path.join(entry, cc.MANIFEST_NAME)
    manifest = json.load(open(mpath))
    manifest["key"]["jaxlib_version"] = "0.0.1"
    json.dump(manifest, open(mpath, "w"))
    _assert_fallback(tmp_path, step_fn, step_args, "jaxlib_version")


def test_topology_mismatch_never_loads(tmp_path, step_fn, step_args):
    _populate(tmp_path, step_fn, step_args)
    _, entry = _entry(tmp_path)
    mpath = os.path.join(entry, cc.MANIFEST_NAME)
    manifest = json.load(open(mpath))
    manifest["key"]["num_devices"] = 4096
    manifest["key"]["mesh_axes"] = [["dp", 4096]]
    json.dump(manifest, open(mpath, "w"))
    _assert_fallback(tmp_path, step_fn, step_args, "mismatch")


def test_unparseable_manifest_quarantined(tmp_path, step_fn, step_args):
    _populate(tmp_path, step_fn, step_args)
    _, entry = _entry(tmp_path)
    open(os.path.join(entry, cc.MANIFEST_NAME), "w").write("{torn json")
    _assert_fallback(tmp_path, step_fn, step_args, "unparseable")


def test_swapped_manifests_both_refused(tmp_path, step_args):
    """The chaos 'swap manifests' case: two committed entries whose manifests
    are exchanged must BOTH fail key verification — neither may load the
    other's executable."""
    f1 = jax.jit(lambda p, x: {"w": p["w"] * 2.0})
    f2 = jax.jit(lambda p, x: {"w": p["w"] + x.sum()})
    _populate(tmp_path, f1, step_args, name="f1")
    _populate(tmp_path, f2, step_args, name="f2")
    cache = CompileCache(str(tmp_path))
    e1, e2 = cache.entries()
    m1, m2 = (os.path.join(e, cc.MANIFEST_NAME) for e in (e1, e2))
    blob1, blob2 = open(m1).read(), open(m2).read()
    open(m1, "w").write(blob2)
    open(m2, "w").write(blob1)
    for fn, name in ((f1, "f1"), (f2, "f2")):
        executable, outcome = cc.aot_compile(name, fn, step_args, directory=str(tmp_path))
        assert outcome == "corrupt"
        np.testing.assert_array_equal(
            np.asarray(fn(*step_args)["w"]), np.asarray(executable(*step_args)["w"])
        )


def test_corrupt_pickle_payload_with_valid_crc(tmp_path, step_fn, step_args):
    """A payload whose CRC *matches* (manifest rewritten consistently) but
    whose pickled content is garbage must still fall back — the deserialize
    failure path, not the CRC path."""
    import zlib

    _populate(tmp_path, step_fn, step_args)
    _, entry = _entry(tmp_path)
    payload_path = os.path.join(entry, cc.PAYLOAD_NAME)
    garbage = pickle.dumps(("not", "an", "executable"))
    open(payload_path, "wb").write(garbage)
    mpath = os.path.join(entry, cc.MANIFEST_NAME)
    manifest = json.load(open(mpath))
    manifest["payload"]["bytes"] = len(garbage)
    manifest["payload"]["crc32"] = zlib.crc32(garbage) & 0xFFFFFFFF
    json.dump(manifest, open(mpath, "w"))
    _assert_fallback(tmp_path, step_fn, step_args, "deserialize")


# ---------------------------------------------------------------------------
# crash consistency + writer races


@pytest.mark.slow  # subprocess pays a jax import
def test_kill9_mid_write_leaves_only_committed_entries(tmp_path, step_fn, step_args):
    """A seeded SIGKILL at the compile_cache_store chaos point (payload
    written, manifest NOT committed) must leave zero committed entries — only
    an orphaned staging dir, which the next store sweeps."""
    cache_dir = tmp_path / "cache"
    child = (
        "import os, json\n"
        "import jax, jax.numpy as jnp\n"
        "from accelerate_tpu.resilience.chaos import ChaosSchedule, Fault, arm\n"
        "from accelerate_tpu import compile_cache as cc\n"
        "arm(ChaosSchedule(faults=[Fault(kind='sigkill', point='compile_cache_store')]))\n"
        "f = jax.jit(lambda p, x: {'w': p['w'] - 0.1 * (p['w'] @ x)[:, None] * x[None, :]})\n"
        f"cc.aot_compile('step', f, ({{'w': jnp.ones((8, 8))}}, jnp.ones((8,))), directory={str(cache_dir)!r})\n"
        "print('UNREACHABLE')\n"
    )
    res = subprocess.run(
        [sys.executable, "-c", child], env=_child_env(), capture_output=True,
        text=True, timeout=240,
    )
    assert res.returncode == -9, (res.returncode, res.stderr[-500:])
    assert "UNREACHABLE" not in res.stdout
    cache = CompileCache(str(cache_dir))
    assert cache.entries() == []  # nothing committed
    staging = [n for n in os.listdir(cache_dir) if ".tmp-" in n]
    assert staging  # the torn write is visible as staging, not as an entry
    # the next writer sweeps the orphan (age floor zeroed for the test) and
    # commits a real entry
    cache._sweep_stale_staging(max_age_s=0.0)
    assert [n for n in os.listdir(cache_dir) if ".tmp-" in n] == []
    assert _populate(cache_dir, step_fn, step_args) == "miss"
    assert len(cache.entries()) == 1


def test_concurrent_writers_race_benignly(tmp_path, step_fn, step_args):
    """First rename wins; the second writer discards its staging and reports
    `raced` — the committed entry stays valid either way."""
    lowered = step_fn.lower(*step_args)
    key = cc.key_from_lowered("step", lowered)
    compiled = lowered.compile()
    cache = CompileCache(str(tmp_path))
    r1 = cache.store(key, compiled)
    r2 = cache.store(key, compiled)
    assert r1.outcome == "stored" and r2.outcome == "raced"
    assert cache.load(key).outcome == "hit"
    assert [n for n in os.listdir(tmp_path) if ".tmp-" in n] == []


def test_true_rename_race_loser_discards(tmp_path, step_fn, step_args, monkeypatch):
    """Two stagings for the same key racing through os.rename: the loser's
    rename targets an existing non-empty dir, fails, and is discarded."""
    lowered = step_fn.lower(*step_args)
    key = cc.key_from_lowered("step", lowered)
    compiled = lowered.compile()
    cache = CompileCache(str(tmp_path))
    real_rename = os.rename
    committed_first = {}

    def racing_rename(src, dst):
        # the other writer commits between our manifest write and our rename
        if not committed_first and ".tmp-" in src:
            committed_first["done"] = True
            CompileCache(str(tmp_path)).store(key, compiled)
        return real_rename(src, dst)

    monkeypatch.setattr(os, "rename", racing_rename)
    res = cache.store(key, compiled)
    monkeypatch.undo()
    assert res.outcome == "raced"
    assert cache.load(key).outcome == "hit"
    assert [n for n in os.listdir(tmp_path) if ".tmp-" in n] == []


# ---------------------------------------------------------------------------
# eviction


def _fake_entry(cache_dir, key_id, nbytes=1024, mtime=None, fn=None):
    """Hand-built committed entry (content is irrelevant to eviction);
    ``fn`` labels the manifest for the per-function quota grouping."""
    import zlib

    entry = os.path.join(str(cache_dir), key_id)
    os.makedirs(entry)
    payload = os.urandom(nbytes)
    open(os.path.join(entry, cc.PAYLOAD_NAME), "wb").write(payload)
    json.dump(
        {"schema": cc.SCHEMA_VERSION, "key": {}, "fn": fn or key_id,
         "payload": {"file": cc.PAYLOAD_NAME, "bytes": nbytes,
                     "crc32": zlib.crc32(payload) & 0xFFFFFFFF}},
        open(os.path.join(entry, cc.MANIFEST_NAME), "w"),
    )
    if mtime is not None:
        os.utime(entry, (mtime, mtime))
    return entry


def test_eviction_oldest_first_under_cap(tmp_path):
    old = _fake_entry(tmp_path, "a" * 24, nbytes=600 * 1024, mtime=1_000)
    new = _fake_entry(tmp_path, "b" * 24, nbytes=600 * 1024, mtime=2_000)
    cache = CompileCache(str(tmp_path))
    evicted = cache.evict(max_mb=1.0)
    assert evicted == [old]
    assert os.path.isdir(new) and not os.path.isdir(old)


def test_eviction_skips_entry_open_for_read(tmp_path):
    import fcntl

    victim = _fake_entry(tmp_path, "a" * 24, nbytes=600 * 1024, mtime=1_000)
    other = _fake_entry(tmp_path, "b" * 24, nbytes=600 * 1024, mtime=2_000)
    cache = CompileCache(str(tmp_path))
    reader = open(os.path.join(victim, cc.MANIFEST_NAME), "rb")
    try:
        fcntl.flock(reader.fileno(), fcntl.LOCK_SH)  # a load in flight
        evicted = cache.evict(max_mb=0.0)
        # the reader-held entry survives even under a zero cap; the idle one
        # goes
        assert victim not in evicted and os.path.isdir(victim)
        assert other in evicted and not os.path.isdir(other)
    finally:
        reader.close()
    assert cache.evict(max_mb=0.0) == [victim]  # released: now evictable


def test_eviction_hit_refreshes_recency(tmp_path, step_fn, step_args):
    """GC is LRU-by-last-HIT, not oldest-write: a load stamps the entry's
    recency, so the executable a fleet actually reloads outlives a
    never-read entry written later (ISSUE 14 compile-cache GC upgrade)."""
    assert _populate(tmp_path, step_fn, step_args) == "miss"
    cache = CompileCache(str(tmp_path))
    (hot,) = cache.entries()
    os.utime(hot, (1_000, 1_000))  # backdate: oldest-write would evict it
    stale = _fake_entry(tmp_path, "b" * 24, nbytes=600 * 1024, mtime=2_000)
    key = cc.key_from_lowered("step", step_fn.lower(*step_args))
    assert cache.load(key).outcome == "hit"  # stamps LAST_HIT on `hot`
    assert os.path.isfile(os.path.join(hot, cc.LAST_HIT_NAME))
    assert cache.entries() == [stale, hot]  # recency order flipped
    evicted = cache.evict(max_mb=0.55)
    assert stale in evicted and hot not in evicted and os.path.isdir(hot)
    # and the hot entry still loads after the GC pass
    assert cache.load(key).outcome == "hit"


def test_eviction_fn_quota_spares_other_fns(tmp_path):
    """Per-fn quota: a function over its share sheds its OWN least-recently
    -hit entries; another function's globally-older entry is untouched."""
    a1 = _fake_entry(tmp_path, "a" * 24, nbytes=500 * 1024, mtime=1_000, fn="lattice")
    a2 = _fake_entry(tmp_path, "b" * 24, nbytes=500 * 1024, mtime=2_000, fn="lattice")
    a3 = _fake_entry(tmp_path, "c" * 24, nbytes=500 * 1024, mtime=3_000, fn="lattice")
    b1 = _fake_entry(tmp_path, "d" * 24, nbytes=500 * 1024, mtime=1_500, fn="train_step")
    cache = CompileCache(str(tmp_path), fn_quota_mb=1.0)
    evicted = cache.evict()  # quota enforcement needs no global cap
    # lattice holds 1.5MB against a 1MB share: its LRU entry goes; train_step
    # is under quota, so its OLDER entry survives a pass that oldest-write
    # eviction would have taken it in
    assert evicted == [a1]
    assert os.path.isdir(b1) and os.path.isdir(a2) and os.path.isdir(a3)
    assert not os.path.isdir(a1)


def test_eviction_fn_quota_env_knob_then_global_cap(tmp_path, monkeypatch):
    """The env knob wires the quota, and the global cap still applies after
    the quota pass — across functions, least-recently-hit first."""
    a1 = _fake_entry(tmp_path, "a" * 24, nbytes=400 * 1024, mtime=1_000, fn="lattice")
    a2 = _fake_entry(tmp_path, "b" * 24, nbytes=400 * 1024, mtime=3_000, fn="lattice")
    b1 = _fake_entry(tmp_path, "c" * 24, nbytes=400 * 1024, mtime=2_000, fn="train_step")
    monkeypatch.setenv(cc.CACHE_FN_QUOTA_MB_ENV_VAR, "0.5")
    cache = CompileCache(str(tmp_path))
    evicted = cache.evict(max_mb=0.5)
    # quota pass: lattice (800KB > 512KB) drops a1; cap pass: 800KB total
    # still > 512KB, so the globally least-recently-hit survivor (b1) goes
    assert evicted == [a1, b1]
    assert os.path.isdir(a2)


def test_store_applies_env_cap_but_protects_fresh_entry(tmp_path, step_fn, step_args, monkeypatch):
    _fake_entry(tmp_path, "a" * 24, nbytes=900 * 1024, mtime=1_000)
    monkeypatch.setenv(cc.CACHE_MAX_MB_ENV_VAR, "0.2")
    executable, outcome = cc.aot_compile("step", step_fn, step_args, directory=str(tmp_path))
    assert outcome == "miss" and executable is not None
    cache = CompileCache(str(tmp_path))
    # the old oversize entry was evicted; the JUST-written one is protected
    # even though the cap is smaller than it
    assert len(cache.entries()) == 1
    assert cache.load(cc.key_from_lowered("step", step_fn.lower(*step_args))).outcome == "hit"


# ---------------------------------------------------------------------------
# kill switch + pretouch


def test_kill_switch_is_byte_identical_to_uncached(tmp_path, step_fn, step_args, monkeypatch):
    monkeypatch.setenv(cc.CACHE_ENV_VAR, "0")
    monkeypatch.setenv(cc.CACHE_DIR_ENV_VAR, str(tmp_path / "cache"))
    assert not cc.cache_enabled()
    assert cc.get_cache() is None
    executable, outcome = cc.aot_compile("step", step_fn, step_args)
    assert outcome == "uncached" and executable is not None
    loaded, key = cc.maybe_load_executable("step", step_fn, step_args)
    assert loaded is None and key is None
    assert cc.pretouch() == {"status": "disabled", "dir": None}
    # byte-identical: the configured dir was never even created
    assert not os.path.exists(tmp_path / "cache")
    np.testing.assert_array_equal(
        np.asarray(step_fn(*step_args)["w"]), np.asarray(executable(*step_args)["w"])
    )


def test_unconfigured_cache_is_inert(step_fn, step_args, monkeypatch):
    monkeypatch.delenv(cc.CACHE_DIR_ENV_VAR, raising=False)
    monkeypatch.delenv(cc.CACHE_ENV_VAR, raising=False)
    assert cc.get_cache() is None
    assert cc.pretouch() == {"status": "unconfigured", "dir": None}
    loaded, key = cc.maybe_load_executable("step", step_fn, step_args)
    assert loaded is None


def test_pretouch_statuses(tmp_path, monkeypatch):
    target = tmp_path / "cache"
    monkeypatch.setenv(cc.CACHE_DIR_ENV_VAR, str(target))
    info = cc.pretouch()
    assert info["status"] == "ok" and os.path.isdir(target)  # created = available
    # a FILE squatting on the path: cannot create the dir -> missing (visible
    # cold start), never an exception
    squatted = tmp_path / "squat"
    open(squatted, "w").write("x")
    assert cc.pretouch(directory=str(squatted))["status"] in ("missing", "readonly")
    # env-dict form (the supervisor probes the CHILD env, not its own)
    assert cc.pretouch(env={cc.CACHE_DIR_ENV_VAR: str(target)})["status"] == "ok"
    assert cc.pretouch(env={})["status"] == "unconfigured"
    assert cc.pretouch(env={cc.CACHE_ENV_VAR: "0"})["status"] == "disabled"


# ---------------------------------------------------------------------------
# cross-process key stability (the property warm restart rests on)


@pytest.mark.slow  # two subprocesses, each pays a jax import
def test_key_is_stable_across_processes():
    child = (
        "import jax, jax.numpy as jnp\n"
        "from accelerate_tpu import compile_cache as cc\n"
        "f = jax.jit(lambda p, x: {'w': p['w'] - 0.1 * (p['w'] @ x)[:, None] * x[None, :]})\n"
        "lowered = f.lower({'w': jnp.ones((8, 8))}, jnp.ones((8,)))\n"
        "print(cc.key_from_lowered('step', lowered).entry_id)\n"
    )
    ids = []
    for _ in range(2):
        res = subprocess.run(
            [sys.executable, "-c", child], env=_child_env(), capture_output=True,
            text=True, timeout=240,
        )
        assert res.returncode == 0, res.stderr[-800:]
        ids.append(res.stdout.strip().splitlines()[-1])
    assert ids[0] == ids[1] and len(ids[0]) == 24


# ---------------------------------------------------------------------------
# telemetry records + report section


def test_cache_outcomes_emit_telemetry_and_report_section(tmp_path, step_fn, step_args):
    from accelerate_tpu.telemetry import events as tel
    from accelerate_tpu.telemetry.report import (
        build_report,
        format_compile_cache_section,
        format_report,
    )

    tel_dir = tmp_path / "telemetry"
    cache_dir = tmp_path / "cache"
    tel.enable(out_dir=str(tel_dir), run_id="ccache-test")
    try:
        cc.aot_compile("step", step_fn, step_args, directory=str(cache_dir))  # miss+store
        cc.aot_compile("step", step_fn, step_args, directory=str(cache_dir))  # hit
        cache = CompileCache(str(cache_dir))
        payload = os.path.join(cache.entries()[0], cc.PAYLOAD_NAME)
        blob = bytearray(open(payload, "rb").read())
        blob[1] ^= 0xFF
        open(payload, "wb").write(bytes(blob))
        cc.aot_compile("step", step_fn, step_args, directory=str(cache_dir))  # corrupt+fallback+store
    finally:
        tel.disable()
    events = [
        json.loads(line)
        for line in open(tel_dir / "events-rank0.jsonl")
        if json.loads(line).get("kind") == "compile_cache"
    ]
    by_event = {}
    for e in events:
        by_event[e["event"]] = by_event.get(e["event"], 0) + 1
    assert by_event["miss"] == 1 and by_event["hit"] == 1
    assert by_event["corrupt"] == 1 and by_event["fallback"] == 1
    assert by_event["store"] == 2
    hit = next(e for e in events if e["event"] == "hit")
    assert hit["bytes"] > 0 and hit["load_s"] >= 0 and hit["key"]
    corrupt = next(e for e in events if e["event"] == "corrupt")
    assert "CRC32" in corrupt["reason"] and corrupt["quarantined_to"]

    report = build_report([str(tel_dir)])
    section = report["compile_cache"]
    assert section["hits"] == 1 and section["misses"] == 1
    assert section["corrupt"] == 1 and section["fallbacks"] == 1
    assert section["bytes_loaded"] > 0 and section["quarantined"]
    text = format_report(report)
    assert "compile cache:" in text and "quarantined" in text
    assert "WARNING: 1 corrupt" in format_compile_cache_section(section)


def test_disabled_telemetry_emits_nothing(tmp_path, step_fn, step_args):
    from accelerate_tpu.telemetry import events as tel

    assert not tel.is_enabled()
    cc.aot_compile("step", step_fn, step_args, directory=str(tmp_path))
    cc.aot_compile("step", step_fn, step_args, directory=str(tmp_path))
    # no telemetry dir appears anywhere under the cache dir; cache still works
    assert CompileCache(str(tmp_path)).stats()["entries"] == 1


# ---------------------------------------------------------------------------
# consumers: serving warm boot + Accelerator restart probe


@pytest.fixture(scope="module")
def tiny_engine_parts():
    from accelerate_tpu.models import init_llama
    from accelerate_tpu.models.transformer import LlamaConfig

    config = LlamaConfig(
        vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=64, max_seq_len=128,
    )
    params = jax.tree_util.tree_map(
        lambda x: x.astype(np.float32), init_llama(config, jax.random.PRNGKey(0))
    )
    return config, params


def test_serving_warmup_loads_full_lattice_from_cache(tmp_path, tiny_engine_parts):
    from accelerate_tpu.serving import BucketLattice, ServingEngine

    config, params = tiny_engine_parts
    lattice = BucketLattice(slot_buckets=(1, 2), block_buckets=(4,), prefill_buckets=(16,))

    def boot():
        engine = ServingEngine(
            params, config, num_blocks=17, block_size=8, max_slots=2,
            max_blocks_per_seq=4, lattice=lattice,
            compile_cache_dir=str(tmp_path),
        )
        counts = engine.warmup()
        return engine, counts

    cold, counts_cold = boot()
    # the prefix-cache COW copy is one more warmed point (ISSUE 14)
    points = lattice.warmup_points(prefix_cache=True)
    assert cold.cache_stats["miss"] == points and cold.cache_stats["hit"] == 0
    warm, counts_warm = boot()
    # the FULL lattice loaded: every point a hit, zero compiles
    assert warm.cache_stats["hit"] == points and warm.cache_stats["miss"] == 0
    assert counts_cold == counts_warm == {
        "prefill_compiles": len(lattice.prefill_points()),
        "decode_compiles": len(lattice.decode_points()),
        "cow_compiles": 1,
    }
    # bitwise: the warm replica serves exactly what the cold one does, and
    # exactly what an uncached engine does
    prompt = (np.arange(1, 11) % 63).astype(np.int32)
    outs = []
    uncached = ServingEngine(
        params, config, num_blocks=17, block_size=8, max_slots=2,
        max_blocks_per_seq=4, lattice=lattice,
    )
    uncached.warmup()
    for engine in (cold, warm, uncached):
        req = engine.submit(prompt, 5, rng_seed=3)
        engine.run()
        outs.append(req.output_ids())
    np.testing.assert_array_equal(outs[0], outs[1])
    np.testing.assert_array_equal(outs[0], outs[2])
    # churn after a cache-loaded warmup still never grows the caches
    assert warm.jit_cache_sizes() == counts_warm


def test_serving_warmup_with_poisoned_cache_falls_back(tmp_path, tiny_engine_parts):
    from accelerate_tpu.serving import BucketLattice, ServingEngine

    config, params = tiny_engine_parts
    lattice = BucketLattice(slot_buckets=(1,), block_buckets=(4,), prefill_buckets=(16,))

    def boot():
        engine = ServingEngine(
            params, config, num_blocks=9, block_size=8, max_slots=1,
            max_blocks_per_seq=4, lattice=lattice,
            compile_cache_dir=str(tmp_path),
        )
        engine.warmup()
        return engine

    boot()
    cache = CompileCache(str(tmp_path))
    for entry in cache.entries():
        payload = os.path.join(entry, cc.PAYLOAD_NAME)
        blob = bytearray(open(payload, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        open(payload, "wb").write(bytes(blob))
    engine = boot()  # must not crash; compiles fresh
    assert engine.cache_stats["corrupt"] == lattice.warmup_points(prefix_cache=True)
    assert cache.stats()["quarantined"] >= lattice.size()
    prompt = (np.arange(1, 9) % 63).astype(np.int32)
    req = engine.submit(prompt, 4, rng_seed=1)
    engine.run()
    assert len(req.generated) == 4


@pytest.mark.slow  # two subprocess generations, each pays a jax import + compile
def test_accelerator_restart_probe_hits_with_zero_recompiles(tmp_path):
    """The elastic-restart e2e: generation 0 trains one step (exporting via
    the perf capture), generation 1 probes the cache before tracing, runs the
    DESERIALIZED executable with zero training compiles, and produces
    bitwise-identical step output."""
    cache_dir = tmp_path / "cache"
    child = (
        "import json, os, sys\n"
        "import numpy as np\n"
        "import jax, jax.numpy as jnp\n"
        "import optax\n"
        "from accelerate_tpu import Accelerator\n"
        "from accelerate_tpu.telemetry import step_profiler as sp\n"
        "acc = Accelerator()\n"
        "params = {'w': jnp.zeros((16, 4), jnp.float32)}\n"
        "params, opt = acc.prepare(params, optax.adam(1e-2))\n"
        "def loss_fn(p, batch):\n"
        "    return jnp.mean((batch['x'] @ p['w']) ** 2)\n"
        "step = acc.prepare_train_step(loss_fn, opt)\n"
        "batch = {'x': jnp.asarray(np.ones((8, 16), np.float32))}\n"
        "c0 = sp.compile_snapshot()[0]\n"
        "params, opt_state, metrics = step(params, opt.opt_state, batch)\n"
        "params, opt_state, metrics = step(params, opt_state, batch)\n"
        "compiles = sp.compile_snapshot()[0] - c0\n"
        "print(json.dumps({'w0': float(params['w'][0, 0]), 'loss': float(metrics['loss']),\n"
        "                  'training_compiles': compiles}))\n"
        "acc.end_training()\n"
    )

    def _gen(generation):
        env = _child_env()
        env["ACCELERATE_TELEMETRY"] = "1"
        env["ACCELERATE_TELEMETRY_DIR"] = str(tmp_path / f"tel-{generation}")
        env["ACCELERATE_COMPILE_CACHE_DIR"] = str(cache_dir)
        if generation:
            env["ACCELERATE_RESTART_GENERATION"] = str(generation)
        res = subprocess.run(
            [sys.executable, "-c", child], env=env, capture_output=True,
            text=True, timeout=300,
        )
        assert res.returncode == 0, res.stderr[-1500:]
        out = json.loads(res.stdout.strip().splitlines()[-1])
        events = []
        tel_file = tmp_path / f"tel-{generation}" / "events-rank0.jsonl"
        if tel_file.exists():
            events = [json.loads(line) for line in open(tel_file)]
        out["cache_events"] = [e["event"] for e in events if e.get("kind") == "compile_cache"]
        return out

    cold = _gen(0)
    warm = _gen(1)
    assert "store" in cold["cache_events"] and "hit" not in cold["cache_events"]
    assert warm["cache_events"].count("hit") == 1
    # gen 1 ran the deserialized executable: ZERO compiles charged to training
    assert cold["training_compiles"] >= 1
    assert warm["training_compiles"] == 0
    # and the math is bitwise-identical
    assert warm["w0"] == cold["w0"] and warm["loss"] == cold["loss"]


def test_report_section_surfaces_degraded_pretouch_only(tmp_path):
    """A healthy/unconfigured supervisor pre-touch alone must NOT grow the
    report; a degraded one (missing/readonly) must render as a WARNING."""
    from accelerate_tpu.telemetry.report import build_report, format_report

    def _write(records):
        with open(tmp_path / "events-supervisor.jsonl", "w") as f:
            f.write(json.dumps({"kind": "meta", "schema": 1, "run_id": "p"}) + "\n")
            for r in records:
                f.write(json.dumps(dict(r, t=0.0)) + "\n")

    _write([{"kind": "compile_cache", "status": "unconfigured", "generation": 0}])
    report = build_report([str(tmp_path)])
    assert report["compile_cache"] is None

    _write([
        {"kind": "compile_cache", "status": "ok", "generation": 0},
        {"kind": "compile_cache", "status": "readonly", "generation": 1,
         "dir": "/shared/cache"},
    ])
    report = build_report([str(tmp_path)])
    section = report["compile_cache"]
    assert section["pretouch"] == {"ok": 1, "readonly": 1}
    text = format_report(report)
    assert "pre-touch found the cache readonly x1" in text
    assert "cold-started" in text
