"""Big-model inference layer tests.

Mirrors the reference's ``tests/test_big_modeling.py`` /
``test_modeling_utils.py`` / ``test_offload.py`` / ``test_hooks.py`` strategy
(tiny models, behavioral equality between dispatched and plain execution).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.big_modeling import (
    DispatchedParams,
    cpu_offload,
    disk_offload,
    dispatch_params,
    init_empty_weights,
    load_checkpoint_and_dispatch,
)
from accelerate_tpu.hooks import (
    AlignDevicesHook,
    LayerwiseCastingHook,
    ModelHook,
    SequentialHook,
    add_hook_to_fn,
    remove_hook_from_fn,
)
from accelerate_tpu.utils.modeling import (
    abstract_params,
    clean_device_map,
    compute_module_sizes,
    convert_file_size_to_int,
    dtype_byte_size,
    find_tied_parameters,
    get_balanced_memory,
    get_max_memory,
    infer_auto_device_map,
    load_checkpoint_in_params,
    lookup_device,
    named_parameters,
    retie_parameters,
    total_byte_size,
    unflatten_parameters,
)
from accelerate_tpu.utils.offload import (
    OffloadedWeightsLoader,
    PrefixedDataset,
    load_offloaded_weight,
    offload_state_dict,
    offload_weight,
    save_offload_index,
)


def tiny_mlp_params(key=None, d=8):
    key = key if key is not None else jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "layer1": {"w": jax.random.normal(k1, (d, d)), "b": jnp.zeros((d,))},
        "layer2": {"w": jax.random.normal(k2, (d, d)), "b": jnp.zeros((d,))},
        "head": {"w": jax.random.normal(k3, (d, 2)), "b": jnp.zeros((2,))},
    }


def mlp_stages():
    def layer(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    def head(p, x):
        return x @ p["w"] + p["b"]

    return [("layer1", layer), ("layer2", layer), ("head", head)]


def run_plain(params, x):
    for name, fn in mlp_stages():
        x = fn(params[name], x)
    return x


# ------------------------------------------------------------------- sizing --
class TestSizes:
    def test_dtype_byte_size(self):
        assert dtype_byte_size(np.float32) == 4
        assert dtype_byte_size("bfloat16") == 2
        assert dtype_byte_size(np.int8) == 1
        assert dtype_byte_size("int4") == 0.5
        assert dtype_byte_size(np.float64) == 8

    def test_convert_file_size(self):
        assert convert_file_size_to_int("1KB") == 1000
        assert convert_file_size_to_int("1KiB") == 1024
        assert convert_file_size_to_int("2GB") == 2 * 10**9
        assert convert_file_size_to_int(512) == 512
        with pytest.raises(ValueError):
            convert_file_size_to_int("lots")

    def test_module_sizes(self):
        params = tiny_mlp_params(d=8)
        sizes = compute_module_sizes(params)
        assert sizes["layer1/w"] == 8 * 8 * 4
        assert sizes["layer1"] == 8 * 8 * 4 + 8 * 4
        assert sizes[""] == total_byte_size(params)

    def test_module_sizes_dtype_override_never_upcasts(self):
        params = {"a": {"w": jnp.zeros((4, 4), dtype=jnp.bfloat16)}}
        # asking for fp32 must not double the accounted storage
        assert compute_module_sizes(params, dtype=np.float32)["a/w"] == 4 * 4 * 2
        assert compute_module_sizes(params, dtype="bfloat16")["a/w"] == 4 * 4 * 2

    def test_named_roundtrip(self):
        params = tiny_mlp_params()
        flat = named_parameters(params)
        assert set(flat) == {
            "layer1/w", "layer1/b", "layer2/w", "layer2/b", "head/w", "head/b",
        }
        rebuilt = unflatten_parameters(flat)
        assert jax.tree_util.tree_structure(rebuilt) == jax.tree_util.tree_structure(params)

    def test_abstract_params_allocates_nothing(self):
        def init():
            return {"w": jnp.zeros((1024, 1024))}

        tree = abstract_params(init)
        leaf = tree["w"]
        assert isinstance(leaf, jax.ShapeDtypeStruct)
        assert total_byte_size(tree) == 1024 * 1024 * 4


class TestTiedParams:
    def test_find_and_retie(self):
        emb = jnp.ones((16, 8))
        params = {"embed": {"w": emb}, "lm_head": {"w": emb}, "other": {"w": jnp.zeros((2, 2))}}
        groups = find_tied_parameters(params)
        assert groups == [["embed/w", "lm_head/w"]]
        flat = named_parameters(params)
        flat["lm_head/w"] = None
        broken = unflatten_parameters(flat)
        fixed = retie_parameters(broken, groups)
        assert fixed["lm_head/w" .split("/")[0]]["w"] is fixed["embed"]["w"]


# --------------------------------------------------------------- device map --
class TestDeviceMap:
    def test_all_fits_on_device_zero(self):
        params = tiny_mlp_params()
        dm = infer_auto_device_map(params, max_memory={0: "1GB", "cpu": "1GB"})
        assert set(dm.values()) == {0}

    def test_spills_to_cpu_then_disk(self):
        params = tiny_mlp_params(d=8)
        sizes = compute_module_sizes(params)
        budget0 = sizes["layer1"] * 2 + 8  # layer1 + largest-layer reserve
        dm = infer_auto_device_map(
            params, max_memory={0: budget0, "cpu": sizes["layer2"] + 8}
        )
        values = [lookup_device(dm, p) for p in ("layer1/w", "layer2/w", "head/w")]
        assert values[0] == 0
        assert "cpu" in values or "disk" in values
        assert values[2] in ("cpu", "disk")

    def test_no_split_advances_device(self):
        params = tiny_mlp_params(d=8)
        sizes = compute_module_sizes(params)
        dm = infer_auto_device_map(
            params,
            max_memory={0: sizes["layer1"] // 2, "cpu": 10**9},
            no_split_module_patterns=["layer1", "layer2", "head"],
        )
        # nothing fits on device 0 → everything moves over intact
        assert all(v == "cpu" for v in dm.values())

    def test_tied_modules_placed_together(self):
        emb = jnp.ones((64, 32))
        params = {
            "embed": {"w": emb},
            "mid": {"w": jnp.ones((64, 64))},
            "lm_head": {"w": emb},
        }
        dm = infer_auto_device_map(params, max_memory={0: 10**9, "cpu": 10**9})
        assert lookup_device(dm, "embed/w") == lookup_device(dm, "lm_head/w")

    def test_clean_device_map_collapses(self):
        dm = clean_device_map(
            {"a/x": 0, "a/y": 0, "b/x": 0, "b/y": "cpu"},
        )
        assert dm["a"] == 0
        assert dm["b/x"] == 0 and dm["b/y"] == "cpu"

    def test_max_memory_probe_and_override(self):
        mm = get_max_memory()
        assert "cpu" in mm and mm["cpu"] > 0
        mm2 = get_max_memory({0: "1MB", "cpu": 2048})
        assert mm2[0] == 10**6 and mm2["cpu"] == 2048

    def test_balanced_memory_caps_devices(self):
        params = tiny_mlp_params(d=16)
        total = total_byte_size(params)
        mm = get_balanced_memory(params, {0: 10**9, 1: 10**9, "cpu": 10**9})
        assert mm[0] < 10**9 and mm[1] < 10**9
        assert mm[0] + mm[1] >= total


# ------------------------------------------------------------------ offload --
class TestOffload:
    def test_offload_roundtrip(self, tmp_path):
        w = np.random.randn(5, 3).astype(np.float32)
        index = offload_weight(w, "w", str(tmp_path))
        save_offload_index(index, str(tmp_path))
        back = load_offloaded_weight(str(tmp_path / "w.dat"), index["w"])
        np.testing.assert_array_equal(w, back)

    def test_offload_bfloat16(self, tmp_path):
        w = jnp.arange(6, dtype=jnp.bfloat16).reshape(2, 3)
        index = offload_weight(np.asarray(w), "w", str(tmp_path))
        back = load_offloaded_weight(str(tmp_path / "w.dat"), index["w"])
        assert str(back.dtype) == "bfloat16"
        np.testing.assert_array_equal(np.asarray(w, dtype=np.float32), np.asarray(back, dtype=np.float32))

    def test_offload_scalar(self, tmp_path):
        index = offload_weight(np.float32(3.5), "s", str(tmp_path))
        back = load_offloaded_weight(str(tmp_path / "s.dat"), index["s"])
        assert float(back) == 3.5

    def test_state_dict_loader(self, tmp_path):
        sd = {"a": np.ones((2, 2), np.float32), "b": np.zeros((3,), np.float32)}
        offload_state_dict(str(tmp_path), sd)
        loader = OffloadedWeightsLoader(save_folder=str(tmp_path))
        assert set(loader) == {"a", "b"}
        np.testing.assert_array_equal(loader["a"], sd["a"])

    def test_prefixed_dataset(self):
        ds = {"pre.a": 1, "pre.b": 2, "other": 3}
        pd = PrefixedDataset(ds, "pre.")
        assert pd["a"] == 1 and len(pd) == 2


# -------------------------------------------------------------------- hooks --
class TestHooks:
    def test_sequential_and_remove(self):
        calls = []

        class H(ModelHook):
            def __init__(self, tag):
                self.tag = tag

            def pre_forward(self, params, *args, **kwargs):
                calls.append(f"pre{self.tag}")
                return params, args, kwargs

            def post_forward(self, params, output):
                calls.append(f"post{self.tag}")
                return output

        fn = lambda p, x: x * p
        hooked = add_hook_to_fn(fn, H(1))
        hooked = add_hook_to_fn(hooked, H(2))
        assert hooked(2.0, 3.0) == 6.0
        assert calls == ["pre1", "pre2", "post1", "post2"]
        assert remove_hook_from_fn(hooked)(2.0, 3.0) == 6.0

    def test_align_devices_hook_loads_missing(self):
        weights = {"w": np.full((2, 2), 7.0, np.float32)}
        hook = AlignDevicesHook(weights_map=weights)
        fn = add_hook_to_fn(lambda p, x: x @ p["w"], hook)
        out = fn({"w": None}, jnp.eye(2))
        np.testing.assert_allclose(np.asarray(out), weights["w"])

    def test_layerwise_casting(self):
        hook = LayerwiseCastingHook(jnp.bfloat16, jnp.float32)
        params = hook.init_hook("s", {"w": jnp.ones((2, 2), jnp.float32)})
        assert params["w"].dtype == jnp.bfloat16
        cast, _, _ = hook.pre_forward(params)
        assert cast["w"].dtype == jnp.float32


# ----------------------------------------------------------------- dispatch --
class TestDispatch:
    def test_dispatch_all_resident_matches_plain(self):
        params = tiny_mlp_params()
        x = jnp.ones((4, 8))
        expected = run_plain(params, x)
        dp = dispatch_params(params, device_map={"": 0})
        out = dp.run(mlp_stages(), x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-6)

    def test_cpu_offload_matches_plain(self):
        params = tiny_mlp_params()
        x = jnp.ones((4, 8))
        expected = run_plain(params, x)
        dp = cpu_offload(params)
        out = dp.run(mlp_stages(), x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-6)
        assert len(dp._paged_cache) == 0  # released after run

    def test_disk_offload_matches_plain(self, tmp_path):
        params = tiny_mlp_params()
        x = jnp.ones((4, 8))
        expected = run_plain(params, x)
        dp = disk_offload(params, str(tmp_path))
        assert os.path.exists(tmp_path / "index.json")
        out = dp.run(mlp_stages(), x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-6)

    def test_mixed_map(self, tmp_path):
        params = tiny_mlp_params()
        x = jnp.ones((4, 8))
        expected = run_plain(params, x)
        dp = dispatch_params(
            params,
            device_map={"layer1": 0, "layer2": "cpu", "head": "disk"},
            offload_folder=str(tmp_path),
        )
        out = dp.run(mlp_stages(), x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-6)

    def test_auto_map_runs(self):
        params = tiny_mlp_params()
        dp = dispatch_params(params, device_map="auto")
        out = dp.run(mlp_stages(), jnp.ones((2, 8)))
        assert out.shape == (2, 2)

    def test_materialize(self):
        params = tiny_mlp_params()
        dp = cpu_offload(params)
        full = dp.materialize()
        np.testing.assert_allclose(
            np.asarray(full["layer1"]["w"]), np.asarray(params["layer1"]["w"])
        )


class TestLoadCheckpointAndDispatch:
    def _save_ckpt(self, params, path):
        from safetensors.numpy import save_file

        flat = {k: np.asarray(v) for k, v in named_parameters(params).items()}
        save_file(flat, str(path))

    def test_roundtrip_single_file(self, tmp_path):
        params = tiny_mlp_params()
        ckpt = tmp_path / "model.safetensors"
        self._save_ckpt(params, ckpt)

        abstract = jax.eval_shape(lambda: params)
        dp = load_checkpoint_and_dispatch(abstract, str(ckpt), device_map={"": 0})
        x = jnp.ones((4, 8))
        np.testing.assert_allclose(
            np.asarray(dp.run(mlp_stages(), x)), np.asarray(run_plain(params, x)), rtol=1e-6
        )

    def test_roundtrip_sharded_with_disk(self, tmp_path):
        from safetensors.numpy import save_file

        params = tiny_mlp_params()
        flat = {k: np.asarray(v) for k, v in named_parameters(params).items()}
        keys = sorted(flat)
        half = len(keys) // 2
        save_file({k: flat[k] for k in keys[:half]}, str(tmp_path / "shard-1.safetensors"))
        save_file({k: flat[k] for k in keys[half:]}, str(tmp_path / "shard-2.safetensors"))
        index = {"weight_map": {k: ("shard-1.safetensors" if k in keys[:half] else "shard-2.safetensors") for k in keys}}
        with open(tmp_path / "model.safetensors.index.json", "w") as f:
            json.dump(index, f)

        abstract = jax.eval_shape(lambda: params)
        offload = tmp_path / "offload"
        dp = load_checkpoint_and_dispatch(
            abstract,
            str(tmp_path),
            device_map={"layer1": 0, "layer2": "cpu", "head": "disk"},
            offload_folder=str(offload),
        )
        x = jnp.ones((4, 8))
        np.testing.assert_allclose(
            np.asarray(dp.run(mlp_stages(), x)), np.asarray(run_plain(params, x)), rtol=1e-6
        )

    def test_missing_tensor_raises(self, tmp_path):
        params = tiny_mlp_params()
        ckpt = tmp_path / "model.safetensors"
        self._save_ckpt({"layer1": params["layer1"]}, ckpt)
        abstract = jax.eval_shape(lambda: params)
        with pytest.raises(KeyError):
            load_checkpoint_and_dispatch(abstract, str(ckpt), device_map={"": 0})
