"""Hang/crash forensics: flight-recorder ring + dumps, watchdog stall
detection (heartbeat sources and blocked phases), collective annotations,
signal post-mortems, cross-rank straggler reporting, the bench probe's
flight artifact, and the zero-cost disabled path."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import jax.numpy as jnp
import pytest

from accelerate_tpu import Accelerator, DataLoader, telemetry as tel
from accelerate_tpu.telemetry import events as tel_events
from accelerate_tpu.telemetry import flight_recorder, watchdog
from accelerate_tpu.telemetry.report import build_report, format_report, main as report_main
from accelerate_tpu.utils import operations as ops

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")


@pytest.fixture(autouse=True)
def _forensics_clean(monkeypatch):
    for var in (
        "ACCELERATE_TELEMETRY",
        "ACCELERATE_TELEMETRY_DIR",
        "ACCELERATE_WATCHDOG_TIMEOUT",
        "ACCELERATE_WATCHDOG_INTERVAL",
        "ACCELERATE_WATCHDOG_ABORT",
        "ACCELERATE_FLIGHT",
        "ACCELERATE_FLIGHT_DIR",
        "ACCELERATE_RUN_ID",
    ):
        monkeypatch.delenv(var, raising=False)
    yield
    watchdog.stop()
    flight_recorder.uninstall()
    rec = flight_recorder.get_recorder()
    rec.events.clear()
    rec.step = None
    rec.out_dir = None
    tel.disable()


def _subprocess_env():
    return {**os.environ, "JAX_PLATFORMS": "cpu", "ACCELERATE_TELEMETRY": "",
            "ACCELERATE_WATCHDOG_TIMEOUT": ""}


# ------------------------------------------------------------ flight recorder


def test_flight_ring_keeps_last_n_and_dump_has_stacks(tmp_path):
    rec = flight_recorder.FlightRecorder(capacity=8)
    for i in range(20):
        rec.record("tick", i=i)
    assert [e["i"] for e in rec.snapshot()] == list(range(12, 20))
    rec.step = 41
    rec.record("with_step")
    assert rec.snapshot()[-1]["step"] == 41
    path = rec.dump("unit test", out_dir=str(tmp_path))
    assert path == str(tmp_path / "flight-rank0.json")
    data = json.load(open(path))
    assert data["reason"] == "unit test" and data["schema"] == 1
    assert data["step"] == 41
    assert data["meta"]["pid"] == os.getpid() and "hostname" in data["meta"]
    # this test's own frame must appear in the all-thread stacks
    assert any(
        "test_flight_ring_keeps_last_n_and_dump_has_stacks" in "".join(t["stack"])
        for t in data["threads"]
    )
    assert data["memory"] is None or "host_rss_bytes" in data["memory"]


def test_flight_phase_nesting_and_current_phases():
    rec = flight_recorder.get_recorder()
    rec.events.clear()
    with flight_recorder.phase("outer"):
        with flight_recorder.phase("collective:gather", op="gather"):
            phases = flight_recorder.current_phases()
            me = phases[threading.current_thread().name]
            assert me["phase"] == "collective:gather" and me["op"] == "gather"
            assert me["age_s"] >= 0
    assert flight_recorder.current_phases() == {}
    kinds = [(e["kind"], e.get("name")) for e in rec.snapshot()]
    assert kinds == [
        ("phase_enter", "outer"),
        ("phase_enter", "collective:gather"),
        ("phase_exit", "collective:gather"),
        ("phase_exit", "outer"),
    ]


def test_collectives_are_phase_annotated():
    rec = flight_recorder.get_recorder()
    rec.events.clear()
    ops.gather(jnp.ones((4,)))
    ops.reduce(jnp.ones((4,)), "mean")
    names = [e.get("name") for e in rec.snapshot() if e["kind"] == "phase_enter"]
    assert "collective:gather" in names and "collective:reduce" in names
    exits = [e for e in rec.snapshot() if e["kind"] == "phase_exit"]
    assert all(e["dur_s"] >= 0 for e in exits)


def test_sigterm_dump_subprocess(tmp_path):
    out = str(tmp_path)
    # a real file (not -c) so the dumped stacks carry source lines
    script = tmp_path / "victim.py"
    script.write_text(
        "import os, signal, sys, time\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from accelerate_tpu.telemetry import flight_recorder\n"
        f"flight_recorder.install(out_dir={out!r})\n"
        "for i in range(5):\n"
        "    flight_recorder.record('work', i=i)\n"
        "os.kill(os.getpid(), signal.SIGTERM)\n"
        "time.sleep(10)\n"  # not reached: the handler chains to SIG_DFL
    )
    res = subprocess.run(
        [sys.executable, str(script)], capture_output=True, text=True,
        timeout=60, env=_subprocess_env(),
    )
    assert res.returncode == -signal.SIGTERM, (res.returncode, res.stderr[-2000:])
    data = json.load(open(tmp_path / "flight-rank0.json"))
    assert data["reason"] == "signal SIGTERM"
    assert [e["i"] for e in data["events"] if e["kind"] == "work"] == list(range(5))
    assert data["threads"] and any("os.kill" in "".join(t["stack"]) for t in data["threads"])


def test_hard_flush_survives_held_event_log_lock(tmp_path):
    """A SIGTERM can interrupt a frame that holds the EventLog lock (emit
    flushes every 64 events); the crash-path flush must time out and let the
    process die with its dump instead of deadlocking on itself."""
    log = tel_events.EventLog(str(tmp_path))
    log.emit("before")
    with log._lock:  # simulate the interrupted lock-holding frame
        t0 = time.monotonic()
        log.hard_flush()  # must return (bounded acquire), not deadlock
        assert time.monotonic() - t0 < 10
    log.hard_flush()  # lock free again: the buffered event lands, fsynced
    records = [json.loads(l) for l in open(tmp_path / "events-rank0.jsonl")]
    assert [r["kind"] for r in records] == ["meta", "before"]
    log.close()


# ------------------------------------------------------------------ watchdog


def test_watchdog_dumps_when_heartbeat_source_stalls(tmp_path):
    wd = watchdog.start(timeout=0.4, interval=0.1, out_dir=str(tmp_path))
    wd.register("fake_producer", depth=2)
    wd.beat("fake_producer", batch=3)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not wd.dump_paths:
        time.sleep(0.05)
    assert wd.dump_paths, "no stall dump within 5s"
    data = json.load(open(wd.dump_paths[0]))
    assert "source 'fake_producer' stalled" in data["reason"]
    assert data["watchdog"]["stalls"][0]["batch"] == 3
    # one dump per stall episode, not one per tick
    count = wd.stall_count
    time.sleep(0.4)
    assert wd.stall_count == count
    # a beat ends the episode and re-arms detection
    wd.beat("fake_producer", batch=4)
    while time.monotonic() < deadline and wd.stall_count == count:
        time.sleep(0.05)
    assert wd.stall_count == count + 1


def test_watchdog_names_the_phase_a_thread_is_stuck_in(tmp_path):
    wd = watchdog.start(timeout=0.3, interval=0.1, out_dir=str(tmp_path))
    release = threading.Event()

    def _stuck():
        with flight_recorder.phase("collective:fake_gather", op="gather"):
            release.wait(8.0)

    worker = threading.Thread(target=_stuck, name="stuck-worker", daemon=True)
    worker.start()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not wd.dump_paths:
        time.sleep(0.05)
    release.set()
    worker.join()
    assert wd.dump_paths
    data = json.load(open(wd.dump_paths[0]))
    assert "phase 'collective:fake_gather' stalled" in data["reason"]
    assert "stuck-worker" in data["reason"]
    assert data["phases"]["stuck-worker"]["phase"] == "collective:fake_gather"
    assert any("release.wait" in "".join(t["stack"]) for t in data["threads"])


def test_hang_inside_fake_collective_end_to_end(tmp_path):
    """Acceptance: an injected hang inside a fake collective produces
    flight-rank0.json naming the stuck collective, with all-thread stacks,
    within the watchdog timeout — and the hard-flushed JSONL stream carries
    the heartbeat/stall records for the by-rank report."""
    out = str(tmp_path)
    script = tmp_path / "hang.py"  # a real file so stacks carry source lines
    script.write_text(
        "import os, sys, time\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from accelerate_tpu.telemetry import events, flight_recorder, watchdog\n"
        f"events.enable({out!r})\n"
        "events.emit('custom', note='pre-hang')\n"
        f"flight_recorder.install(out_dir={out!r})\n"
        f"watchdog.start(timeout=1.0, interval=0.2, abort_on_stall=True, out_dir={out!r})\n"
        "flight_recorder.set_step(7)\n"
        "with flight_recorder.phase('collective:gather', op='gather'):\n"
        "    time.sleep(60)\n"
    )
    t0 = time.monotonic()
    res = subprocess.run(
        [sys.executable, str(script)], capture_output=True, text=True,
        timeout=45, env=_subprocess_env(),
    )
    wall = time.monotonic() - t0
    assert res.returncode == watchdog.ABORT_EXIT_CODE, (res.returncode, res.stderr[-2000:])
    assert wall < 40, f"abort took {wall:.1f}s"
    data = json.load(open(tmp_path / "flight-rank0.json"))
    assert "phase 'collective:gather' stalled" in data["reason"]
    assert data["step"] == 7
    assert data["phases"]["MainThread"]["phase"] == "collective:gather"
    assert data["phases"]["MainThread"]["op"] == "gather"
    stacks = ["".join(t["stack"]) for t in data["threads"]]
    assert any("time.sleep" in s for s in stacks)  # the hung main thread
    assert len(data["threads"]) >= 2  # ... and the watchdog thread itself
    # the EventLog was hard-flushed by the dump: nothing buffered was lost
    records = [json.loads(l) for l in open(tmp_path / "events-rank0.jsonl")]
    kinds = {r["kind"] for r in records}
    assert {"custom", "heartbeat", "watchdog_stall"} <= kinds
    stall = [r for r in records if r["kind"] == "watchdog_stall"][-1]
    assert "collective:gather" in stall["reason"]
    # and the report merges the flight record into the by-rank view
    report = build_report([out], by_rank=True)
    flights = report["ranks"]["flight_records"]
    assert flights and "collective:gather" in flights[0]["reason"]


def test_watchdog_env_seeding(tmp_path, monkeypatch):
    from accelerate_tpu.utils.dataclasses import WatchdogConfig

    assert not WatchdogConfig().enabled
    monkeypatch.setenv("ACCELERATE_WATCHDOG_TIMEOUT", "150")
    monkeypatch.setenv("ACCELERATE_WATCHDOG_ABORT", "1")
    cfg = WatchdogConfig()
    assert cfg.enabled and cfg.timeout == 150.0 and cfg.abort_on_stall
    monkeypatch.setenv("ACCELERATE_WATCHDOG_TIMEOUT", "not-a-number")
    assert not WatchdogConfig().enabled  # malformed env never crashes startup
    assert watchdog.env_timeout() == 0.0


def test_accelerator_starts_and_stops_watchdog(tmp_path, monkeypatch):
    monkeypatch.setenv("ACCELERATE_WATCHDOG_TIMEOUT", "60")
    monkeypatch.setenv("ACCELERATE_FLIGHT_DIR", str(tmp_path))
    acc = Accelerator()
    wd = watchdog.get_watchdog()
    assert wd is not None and wd.running and wd.timeout == 60.0
    assert flight_recorder.installed()
    acc.end_training()
    assert watchdog.get_watchdog() is None


# --------------------------------------------------------- disabled-path cost


@pytest.mark.smoke
def test_forensics_disabled_path_no_thread_no_file(tmp_path, monkeypatch):
    """Default runs pay nothing: no watchdog thread, no handler, no file —
    the hot-path helpers are a single flag check."""
    monkeypatch.chdir(tmp_path)
    before = {t.name for t in threading.enumerate()}
    assert watchdog.maybe_start_from_env() is None
    acc = Accelerator()
    assert watchdog.get_watchdog() is None
    assert not flight_recorder.installed()
    watchdog.beat("anything", step=1)  # no-ops, no registration anywhere
    watchdog.register("anything")
    watchdog.unregister("anything")
    after = {t.name for t in threading.enumerate()}
    assert "accelerate-tpu-watchdog" not in after - before
    # nothing opened a file: no telemetry/flight/watchdog artifacts in cwd
    assert not list(tmp_path.iterdir())
    del acc


# ------------------------------------------------------------------- report


def test_report_header_surfaces_per_rank_counts_and_dropped(tmp_path):
    (tmp_path / "events-rank0.jsonl").write_text(
        json.dumps({"kind": "meta", "schema": 1, "run_id": "r", "process_index": 0}) + "\n"
        + json.dumps({"kind": "step", "step": 0, "dur_s": 0.01}) + "\n"
    )
    (tmp_path / "events-rank1.jsonl").write_text(
        json.dumps({"kind": "meta", "schema": 1, "run_id": "r", "process_index": 1}) + "\n"
        + json.dumps({"kind": "dropped", "count": 42}) + "\n"
    )
    report = build_report([str(tmp_path)])
    assert report["per_rank_events"] == {
        "0": {"events": 2, "dropped": 0},
        "1": {"events": 2, "dropped": 42},
    }
    assert report["dropped_events"] == 42
    text = format_report(report)
    assert "events by rank: rank0=2, rank1=2" in text
    assert "WARNING: 42 event(s) DROPPED" in text and "rank1=42" in text


def _write_straggler_streams(out_dir: str) -> None:
    """Synthetic two-rank run: rank 1 is 3x slower on every step and has a
    3s heartbeat gap; its flight record names a stuck gather. Timestamps are
    fixed so the rendered report is byte-deterministic (golden file)."""
    for rank, scale, beat_ts in ((0, 1.0, [0, 1, 2, 3, 4]), (1, 3.0, [0, 1, 4])):
        lines = [
            json.dumps({"kind": "meta", "schema": 1, "run_id": "straggle",
                        "process_index": rank, "num_processes": 2})
        ]
        for s in range(10):
            lines.append(json.dumps({"kind": "step", "step": s, "t": float(s),
                                     "dur_s": round(0.010 * scale, 6)}))
        for t in beat_ts:
            lines.append(json.dumps({"kind": "heartbeat", "t": float(t),
                                     "sources": {"train_step": 0.1}}))
        with open(os.path.join(out_dir, f"events-rank{rank}.jsonl"), "w") as f:
            f.write("\n".join(lines) + "\n")
    with open(os.path.join(out_dir, "flight-rank1.json"), "w") as f:
        json.dump(
            {
                "kind": "flight_record",
                "schema": 1,
                "reason": "watchdog: phase 'collective:gather' stalled for 12.0s "
                          "in thread MainThread (timeout 5s)",
                "step": 7,
                "meta": {"process_index": 1},
                "phases": {"MainThread": {"phase": "collective:gather", "age_s": 12.0}},
                "events": [],
                "threads": [],
            },
            f,
        )


def test_by_rank_report_identifies_straggler(tmp_path):
    _write_straggler_streams(str(tmp_path))
    report = build_report([str(tmp_path)], by_rank=True)
    ranks = report["ranks"]
    assert ranks["steps_compared"] == 10
    assert ranks["straggler"] == {
        "rank": 1, "slowest_steps": 10, "steps_compared": 10, "mean_excess_s": 0.02,
    }
    assert ranks["skew_s"]["p50"] == 0.02 and ranks["skew_s"]["count"] == 10
    assert ranks["slowest_counts"] == {"1": 10}
    assert ranks["per_rank"]["0"]["steps"] == 10
    assert ranks["per_rank"]["1"]["wall_s"]["p50"] == 0.03
    assert ranks["heartbeat_gaps"]["0"]["max_gap_s"] == 1.0
    assert ranks["heartbeat_gaps"]["1"]["max_gap_s"] == 3.0
    flights = ranks["flight_records"]
    assert flights[0]["rank"] == 1 and flights[0]["step"] == 7
    assert flights[0]["phases"]["MainThread"]["phase"] == "collective:gather"


def test_by_rank_report_matches_golden(tmp_path, capsys):
    """Golden-file test over the synthetic straggler scenario: the rendered
    per-rank section is byte-stable. Regenerate after an intentional format
    change with: python tests/test_forensics.py regen"""
    _write_straggler_streams(str(tmp_path))
    assert report_main(["report", str(tmp_path), "--by-rank"]) == 0
    out = capsys.readouterr().out
    section = out[out.index("per-rank stragglers:"):]
    golden = open(os.path.join(GOLDEN, "straggler_report.txt")).read()
    assert section == golden


def test_report_cli_json_includes_ranks(tmp_path, capsys):
    _write_straggler_streams(str(tmp_path))
    assert report_main(["report", str(tmp_path), "--json", "--by-rank"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["ranks"]["straggler"]["rank"] == 1
    # without the flag the section is absent (and the report stays driver-stable)
    assert report_main(["report", str(tmp_path), "--json"]) == 0
    assert "ranks" not in json.loads(capsys.readouterr().out)


@pytest.mark.slow  # the full doctor is minutes of subprocess e2e on a small
# box (fused-zero1 8-device compile child, elastic supervisor children, two
# serving engines + two router replicas, all warmed); `make doctor` runs the
# same thing as its own CI lane, so the timed tier-1 window doesn't pay twice
def test_doctor_self_checks(capsys):
    from accelerate_tpu.telemetry.report import run_doctor

    assert run_doctor() == 0
    out = capsys.readouterr().out
    # dump + stall + straggler + collective divergence + jaxlint
    # + perf cost capture + xplane trace parse + performance report (ISSUE 7)
    # + fused zero1 lint/compiled-collectives (ISSUE 9)
    # + elastic auto-resume (ISSUE 10)
    # + serving engine (ISSUE 11)
    # + replicated serving router (ISSUE 12)
    # + persistent compile cache (ISSUE 13)
    # + prefix cache + COW (ISSUE 14 — the count was left at 14 when that
    #   check landed; fixed here)
    # + observability plane (ISSUE 15)
    # + disaggregated serving (ISSUE 16)
    # + goodput ledger (ISSUE 17)
    # + speculative decoding (ISSUE 18)
    # + live observability plane (ISSUE 19)
    # + fp8 fused zero1 train step (ISSUE 20)
    assert out.count("PASS") == 21 and "FAIL" not in out
    assert "static analyzer (jaxlint)" in out and "collective divergence" in out
    assert "goodput ledger" in out
    assert "speculative decoding" in out
    assert "perf cost capture" in out and "xplane trace parse" in out
    assert "serving engine" in out
    assert "replicated serving router" in out
    assert "fused zero1 compiled collectives" in out
    assert "performance report section" in out
    assert "elastic auto-resume" in out
    assert "persistent compile cache" in out
    assert "prefix cache + COW" in out
    assert "observability plane" in out
    assert "live observability plane" in out
    assert "fp8 fused zero1 train step" in out


# ------------------------------------------------------- integration hookups


@pytest.mark.slow  # pays a full loader-prepare compile (~4s); test_slow shard
def test_prefetch_producer_registers_and_unregisters(tmp_path):
    import numpy as np

    wd = watchdog.start(timeout=60, interval=0.05, out_dir=str(tmp_path))
    acc = Accelerator()
    # enough batches that the bounded queue (depth 2) keeps the producer
    # alive — and registered — while the consumer holds the first batch; a
    # 3-batch epoch let the producer finish and unregister (from its own
    # exit path, by design) before the assertion below could observe it
    data = [{"x": np.ones((4,), np.float32)} for _ in range(240)]
    dl = acc.prepare(DataLoader(data, batch_size=8))
    it = iter(dl)
    next(it)
    sources = wd.sources()
    producer = [s for s in sources if s.startswith("prefetch_producer@")]
    assert producer, sources
    assert "batch" in sources[producer[0]] or "depth" in sources[producer[0]]
    it.close()  # clean shutdown must unregister (not a stall)
    assert not [s for s in wd.sources() if s.startswith("prefetch_producer@")]


def test_train_step_beats_watchdog(tmp_path):
    import numpy as np
    import optax

    wd = watchdog.start(timeout=60, interval=10, out_dir=str(tmp_path))
    acc = Accelerator()
    params = {"w": jnp.ones((4,))}
    optimizer = optax.sgd(1e-2)
    params, optimizer = acc.prepare(params, optimizer)
    step = acc.prepare_train_step(lambda p, b: jnp.mean((b["x"] @ p["w"]) ** 2), optimizer)
    batch = {"x": jnp.ones((8, 4))}
    params, opt_state, _ = step(params, optimizer.opt_state, batch)
    assert wd.sources()["train_step"]["step"] == 0
    assert flight_recorder.get_recorder().step == 0
    params, opt_state, _ = step(params, opt_state, batch)
    assert wd.sources()["train_step"]["step"] == 1


def test_bench_probe_hang_leaves_flight_record(tmp_path, monkeypatch):
    import bench

    monkeypatch.setattr(bench, "_PROBE_FLIGHT_DIR", str(tmp_path / "probe"))
    ok, detail = bench._probe_backend_subprocess(
        3, init_stmt="import time; time.sleep(120)"
    )
    assert not ok
    assert "flight record:" in detail
    paths = list((tmp_path / "probe").glob("attempt-*/flight-rank0.json"))
    assert len(paths) == 1
    data = json.load(open(paths[0]))
    assert "phase 'backend_init' stalled" in data["reason"]
    assert data["phases"]["MainThread"]["op"] == "jax.devices"
    assert bench._FLIGHT_RECORDS and bench._FLIGHT_RECORDS[-1] == str(paths[0])
    # a second (retry) probe must not destroy the first attempt's evidence
    ok2, _ = bench._probe_backend_subprocess(
        3, init_stmt="import time; time.sleep(120)"
    )
    assert not ok2 and paths[0].exists()
    assert len(set(bench._FLIGHT_RECORDS[-2:])) == 2


def test_bench_probe_success_path_unchanged(tmp_path, monkeypatch):
    import bench

    monkeypatch.setattr(bench, "_PROBE_FLIGHT_DIR", str(tmp_path / "probe"))
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    ok, detail = bench._probe_backend_subprocess(120)
    assert ok and detail == "ok"
    assert not list((tmp_path / "probe").glob("attempt-*/flight-rank0.json"))


if __name__ == "__main__" and "regen" in sys.argv:
    # regenerate the golden straggler report after an intentional format change
    import io
    import tempfile
    from contextlib import redirect_stdout

    with tempfile.TemporaryDirectory() as tmp:
        _write_straggler_streams(tmp)
        buf = io.StringIO()
        with redirect_stdout(buf):
            report_main(["report", tmp, "--by-rank"])
        out = buf.getvalue()
        os.makedirs(GOLDEN, exist_ok=True)
        with open(os.path.join(GOLDEN, "straggler_report.txt"), "w") as f:
            f.write(out[out.index("per-rank stragglers:"):])
    print("regenerated", os.path.join(GOLDEN, "straggler_report.txt"))
