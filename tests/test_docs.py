"""Docs-corpus checks: generated API reference freshness, breadth, and the
documented SageMaker exclusion (VERDICT r04 items 8 and 10)."""

import os
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
DOCS = REPO / "docs"


def test_api_reference_is_fresh(tmp_path):
    """Regenerating the package_reference pages produces exactly what is
    committed — docstring edits must be followed by `python
    tools/gen_api_docs.py` (the pages can never silently drift from code)."""
    res = subprocess.run(
        [sys.executable, str(REPO / "tools" / "gen_api_docs.py"), str(tmp_path)],
        capture_output=True, text=True, timeout=300,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    committed = DOCS / "package_reference"
    fresh_files = sorted(p.name for p in tmp_path.glob("*.md"))
    committed_files = sorted(p.name for p in committed.glob("*.md"))
    assert fresh_files == committed_files
    stale = [
        name for name in fresh_files
        if (tmp_path / name).read_text() != (committed / name).read_text()
    ]
    assert not stale, (
        f"stale generated docs {stale}: run `python tools/gen_api_docs.py`"
    )


def test_docs_corpus_breadth():
    """The corpus stays at reference-shaped breadth: flat guides +
    concept_guides/ + generated package_reference/ ≥ 25 files."""
    md_files = list(DOCS.rglob("*.md"))
    assert len(md_files) >= 25, sorted(str(p.relative_to(DOCS)) for p in md_files)
    assert (DOCS / "concept_guides").is_dir()
    assert (DOCS / "package_reference").is_dir()


def test_sagemaker_config_is_rejected_with_pointer(tmp_path):
    """The SageMaker launch route is a DOCUMENTED exclusion: a reference
    SageMaker config must fail loudly with the rationale, not be half-read
    as a cluster config (docs/launching.md)."""
    cfg = tmp_path / "sagemaker.yaml"
    cfg.write_text(
        "compute_environment: AMAZON_SAGEMAKER\n"
        "mixed_precision: 'no'\n"
    )
    from accelerate_tpu.commands.config import ClusterConfig

    with pytest.raises(ValueError, match="SageMaker.*docs/launching.md"):
        ClusterConfig.load(str(cfg))
    assert "SageMaker" in (DOCS / "launching.md").read_text()
