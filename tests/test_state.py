"""Tests for state singletons + mesh construction (reference: tests exercise
PartialState via scripts, SURVEY.md §4)."""

import numpy as np
import pytest

from accelerate_tpu import (
    AcceleratorState,
    DistributedType,
    GradientState,
    ParallelismConfig,
    PartialState,
)
from accelerate_tpu.parallelism_config import MESH_AXIS_NAMES
from accelerate_tpu.utils import patch_environment


def test_partial_state_singleton():
    a = PartialState()
    b = PartialState()
    assert a.__dict__ is b.__dict__
    assert a.num_devices == 8
    assert a.process_index == 0
    assert a.is_main_process
    assert a.distributed_type == DistributedType.SPMD


def test_split_between_processes_single_process():
    state = PartialState()
    with state.split_between_processes([1, 2, 3]) as chunk:
        assert chunk == [1, 2, 3]


def test_parallelism_config_validation():
    with pytest.raises(ValueError):
        ParallelismConfig(tp_size=0)
    with pytest.raises(ValueError):
        ParallelismConfig(cp_size=2, sp_size=2)
    with pytest.raises(ValueError):
        ParallelismConfig(dp_replicate_size=3).mesh_shape(8)


def test_parallelism_config_infer_dp_shard():
    pc = ParallelismConfig(dp_shard_size=-1, tp_size=2)
    assert pc.infer_dp_shard(8) == 4
    assert pc.mesh_shape(8) == (1, 1, 4, 1, 1, 2, 1)
    assert pc.fsdp_enabled and pc.tp_enabled and not pc.cp_enabled


@pytest.mark.smoke
def test_build_mesh_axes():
    pc = ParallelismConfig(dp_replicate_size=2, dp_shard_size=2, tp_size=2)
    mesh = pc.build_mesh()
    assert mesh.axis_names == MESH_AXIS_NAMES
    assert mesh.shape["dp_replicate"] == 2
    assert mesh.shape["dp_shard"] == 2
    assert mesh.shape["tp"] == 2
    assert np.prod(list(mesh.shape.values())) == 8


from accelerate_tpu.test_utils import fake_slice_devices as _fake_slice_devices


class TestMultiSliceMesh:
    """DCN-aware hybrid mesh construction (VERDICT r03 item 3; reference
    multi-node analogue ``state.py:753-812``)."""

    def test_dcn_factors_land_on_dp_replicate(self):
        pc = ParallelismConfig(dp_replicate_size=2, dp_shard_size=2, tp_size=2)
        per_slice, dcn = pc.dcn_mesh_shapes(8, num_slices=2)
        assert dcn == (1, 2, 1, 1, 1, 1, 1)  # dp_replicate across DCN
        assert per_slice == (1, 1, 2, 1, 1, 2, 1)

    def test_dcn_factors_prefer_pp_then_dp_replicate(self):
        pc = ParallelismConfig(pp_size=2, dp_replicate_size=2, dp_shard_size=2)
        per_slice, dcn = pc.dcn_mesh_shapes(8, num_slices=4)
        assert dcn == (2, 2, 1, 1, 1, 1, 1)
        assert per_slice == (1, 1, 2, 1, 1, 1, 1)

    def test_unfactorable_slice_count_raises_with_guidance(self):
        pc = ParallelismConfig(dp_shard_size=8)  # no outer axis to absorb slices
        with pytest.raises(ValueError, match="ACCELERATE_DCN_MESH_SHAPE"):
            pc.dcn_mesh_shapes(8, num_slices=2)

    def test_explicit_dcn_shape_env_override(self):
        pc = ParallelismConfig(dp_shard_size=8)
        with patch_environment(ACCELERATE_DCN_MESH_SHAPE="1,1,2,1,1,1,1"):
            per_slice, dcn = pc.dcn_mesh_shapes(8, num_slices=2)
        assert dcn == (1, 1, 2, 1, 1, 1, 1)  # user chose dp_shard across DCN
        assert per_slice == (1, 1, 4, 1, 1, 1, 1)

    def test_build_mesh_two_fake_slices_places_dp_replicate_across_dcn(self):
        devices = _fake_slice_devices(8, num_slices=2)
        pc = ParallelismConfig(dp_replicate_size=2, dp_shard_size=4)
        mesh = pc.build_mesh(devices=devices)
        assert mesh.shape["dp_replicate"] == 2 and mesh.shape["dp_shard"] == 4
        arr = mesh.devices  # (pp, dp_replicate, dp_shard, cp, sp, tp, ep)
        # each dp_replicate row must live entirely inside ONE slice...
        for rep in range(2):
            slices = {d.slice_index for d in arr[0, rep].flat}
            assert len(slices) == 1, f"dp_replicate row {rep} spans slices {slices}"
        # ...and the two rows on DIFFERENT slices (the allreduce crosses DCN
        # once; everything else stays on ICI)
        assert {d.slice_index for d in arr[0, 0].flat} != {
            d.slice_index for d in arr[0, 1].flat
        }

    def test_build_mesh_multislice_never_silently_flattens(self):
        # 2 slices but a config whose outer axes cannot absorb them: must
        # raise, not fall back to a DCN-oblivious reshape
        devices = _fake_slice_devices(8, num_slices=2)
        pc = ParallelismConfig(dp_shard_size=8)
        with pytest.raises(ValueError):
            pc.build_mesh(devices=devices)

    def test_single_slice_path_unchanged(self):
        pc = ParallelismConfig(dp_replicate_size=2, dp_shard_size=4)
        mesh = pc.build_mesh()  # real (virtual CPU) devices, no slice_index
        assert mesh.shape["dp_replicate"] == 2


def test_parallelism_config_env_round_trip():
    pc = ParallelismConfig(dp_shard_size=4, tp_size=2, cp_rotate_method="ring")
    with patch_environment(**pc.to_env()):
        loaded = ParallelismConfig.from_env()
    assert loaded == pc


def test_accelerator_state_mesh_default_dp():
    state = AcceleratorState()
    assert state.mesh.shape["dp_replicate"] == 8
    assert state.num_devices == 8
    assert str(state.mixed_precision) == "no"


def test_accelerator_state_env_parallelism():
    with patch_environment(PARALLELISM_CONFIG_DP_SHARD_SIZE=8, PARALLELISM_CONFIG_DP_REPLICATE_SIZE=1):
        state = AcceleratorState(mixed_precision="bf16")
        assert state.mesh.shape["dp_shard"] == 8
        assert str(state.mixed_precision) == "bf16"


def test_gradient_state():
    gs = GradientState()
    assert gs.sync_gradients
    assert gs.num_steps == 1
    assert not gs.in_dataloader
    assert gs.remainder == -1


def test_on_main_process_decorator():
    state = PartialState()
    calls = []

    @state.on_main_process
    def fn(x):
        calls.append(x)
        return x

    assert fn(3) == 3
    assert calls == [3]


def test_main_process_first_noop_single():
    state = PartialState()
    with state.main_process_first():
        pass
