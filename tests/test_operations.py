"""Tests for host-level collectives (reference: test_utils/scripts/test_ops.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from accelerate_tpu import ParallelismConfig
from accelerate_tpu.utils.operations import (
    broadcast,
    broadcast_object_list,
    concatenate,
    find_batch_size,
    gather,
    gather_object,
    get_data_structure,
    initialize_tensors,
    pad_input_tensors,
    recursively_apply,
    reduce,
    send_to_device,
    slice_tensors,
)


def test_recursively_apply_preserves_structure():
    data = {"a": np.ones(3), "b": [np.zeros(2), "keep"], "c": (np.ones(1),)}
    out = recursively_apply(lambda x: x + 1, data)
    assert out["b"][1] == "keep"
    assert isinstance(out["c"], tuple)
    np.testing.assert_array_equal(out["a"], np.full(3, 2.0))


@pytest.mark.smoke
def test_gather_replicates_sharded_array():
    mesh = ParallelismConfig(dp_shard_size=8).build_mesh()
    x = jax.device_put(jnp.arange(16.0).reshape(16, 1), NamedSharding(mesh, P("dp_shard")))
    out = gather({"x": x})["x"]
    assert out.sharding.spec == P()
    np.testing.assert_array_equal(np.asarray(out), np.arange(16.0).reshape(16, 1))


def test_gather_object_single_process():
    assert gather_object({"rank": 0}) == [{"rank": 0}]


def test_broadcast_single_process_identity():
    data = {"x": np.ones(2)}
    out = broadcast(data)
    np.testing.assert_array_equal(out["x"], data["x"])
    objs = broadcast_object_list([1, "a"])
    assert objs == [1, "a"]


def test_reduce_mean_on_replicated():
    mesh = ParallelismConfig(dp_replicate_size=8).build_mesh()
    x = jax.device_put(jnp.full((4,), 3.0), NamedSharding(mesh, P()))
    out = reduce({"x": x}, reduction="mean")["x"]
    np.testing.assert_allclose(np.asarray(out), np.full((4,), 3.0))


def test_reduce_invalid_reduction():
    with pytest.raises(ValueError):
        reduce(np.ones(2), reduction="max")


def test_pad_input_tensors():
    batch = {"x": np.arange(10).reshape(5, 2)}
    out = pad_input_tensors(batch, batch_size=5, num_processes=4)
    assert out["x"].shape == (8, 2)
    np.testing.assert_array_equal(out["x"][5], out["x"][4])
    even = pad_input_tensors({"x": np.ones((8, 2))}, batch_size=8, num_processes=4)
    assert even["x"].shape == (8, 2)


def test_slice_and_concatenate_and_batch_size():
    data = [{"x": np.arange(6).reshape(6, 1)}, {"x": np.arange(6, 12).reshape(6, 1)}]
    sliced = slice_tensors(data[0], slice(0, 2))
    assert sliced["x"].shape == (2, 1)
    cat = concatenate(data)
    assert cat["x"].shape == (12, 1)
    assert find_batch_size(data[0]) == 6
    assert find_batch_size({"a": "str", "b": np.ones((3, 2))}) == 3


def test_structure_round_trip():
    data = {"x": np.ones((2, 3), dtype=np.float32), "y": [np.zeros(4, dtype=np.int32)]}
    skeleton = get_data_structure(data)
    rebuilt = initialize_tensors(skeleton)
    assert rebuilt["x"].shape == (2, 3) and rebuilt["x"].dtype == np.float32
    assert rebuilt["y"][0].shape == (4,) and rebuilt["y"][0].dtype == np.int32


def test_send_to_device_with_sharding():
    mesh = ParallelismConfig(dp_shard_size=8).build_mesh()
    sharding = NamedSharding(mesh, P("dp_shard"))
    out = send_to_device({"x": np.zeros((8, 2)), "skip": np.ones(1)}, sharding, skip_keys="skip")
    assert isinstance(out["x"], jax.Array)
    assert out["x"].sharding == sharding
    assert isinstance(out["skip"], np.ndarray)


def test_rng_set_seed_and_capture():
    from accelerate_tpu.utils.random import (
        capture_rng_states,
        next_rng_key,
        restore_rng_states,
        set_seed,
    )

    set_seed(42)
    a = np.random.rand(3)
    k1 = next_rng_key()
    states = capture_rng_states()
    b = np.random.rand(3)
    k2 = next_rng_key()
    restore_rng_states(states)
    np.testing.assert_array_equal(np.random.rand(3), b)
    np.testing.assert_array_equal(np.asarray(next_rng_key()), np.asarray(k2))
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))


def test_tensor_information_round_trip():
    from accelerate_tpu.utils.operations import TensorInformation, is_tensor_information

    info = TensorInformation((2, 3), "float32")
    assert is_tensor_information(info)
    skel = get_data_structure({"a": np.ones((2, 3), np.float32)})
    assert is_tensor_information(skel["a"])
    zeros = initialize_tensors(skel)
    assert zeros["a"].shape == (2, 3) and float(zeros["a"].sum()) == 0.0


def test_dp_group_ops_single_process():
    from accelerate_tpu.utils.operations import (
        avg_losses_across_data_parallel_group,
        gather_across_data_parallel_groups,
        ignorant_find_batch_size,
    )

    losses = [np.float32(1.0), np.float32(3.0)]
    avg = np.asarray(avg_losses_across_data_parallel_group(losses))
    np.testing.assert_allclose(avg, [1.0, 3.0])  # single process: per-entry identity
    g = gather_across_data_parallel_groups({"x": np.ones((2,))})
    assert np.asarray(g["x"]).shape[0] >= 2
    assert ignorant_find_batch_size(object()) is None
