"""Asynchronous zero-stall checkpointing: snapshot/background-writer split,
crash-consistent commit protocol (staging + fsync + ``_COMMITTED`` marker +
atomic rename), back-pressure, post-commit rotation, and corruption
detection on load."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax
import optax

from accelerate_tpu import Accelerator, CheckpointConfig, ParallelismConfig
from accelerate_tpu.checkpointing import (
    COMMITTED_MARKER,
    CheckpointCorruptError,
    find_latest_checkpoint,
    is_committed_checkpoint,
)
from accelerate_tpu.data_loader import DataLoader, prepare_data_loader
from accelerate_tpu.state import AcceleratorState
from accelerate_tpu.utils.dataclasses import ProjectConfiguration

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _auto_acc(tmp_path, total_limit=None, **ckpt_kwargs):
    return Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(tmp_path), automatic_checkpoint_naming=True, total_limit=total_limit
        ),
        checkpoint_config=CheckpointConfig(**ckpt_kwargs) if ckpt_kwargs else None,
    )


def _params(value=1.0):
    return {"w": np.full((32, 4), value, np.float32), "b": np.zeros(4, np.float32)}


# ---------------------------------------------------------------------------
# async semantics


@pytest.mark.smoke
def test_async_save_roundtrip_and_commit_marker(tmp_path):
    acc = _auto_acc(tmp_path)
    out = acc.save_state(params=_params(3.0), blocking=False)
    acc.wait_for_checkpoint()
    assert is_committed_checkpoint(out)
    manifest = json.load(open(os.path.join(out, COMMITTED_MARKER)))
    assert manifest["schema"] == 1 and manifest["files"]
    # every listed file exists with the recorded size
    for name, rec in manifest["files"].items():
        assert os.path.getsize(os.path.join(out, name)) == rec["bytes"]
    restored = acc.load_state(out, params=_params(0.0))
    np.testing.assert_allclose(np.asarray(restored["w"]), 3.0)
    acc.end_training()


def test_async_save_returns_before_write_finishes(tmp_path, monkeypatch):
    """The zero-stall property: save_state(blocking=False) returns after the
    snapshot; a deliberately slowed writer runs in the background."""
    from accelerate_tpu import checkpointing

    real = checkpointing.write_and_commit
    started = threading.Event()

    def slow(snap, heartbeat=None):
        started.set()
        time.sleep(0.5)
        return real(snap, heartbeat=heartbeat)

    monkeypatch.setattr(checkpointing, "write_and_commit", slow)
    acc = _auto_acc(tmp_path)
    t0 = time.monotonic()
    out = acc.save_state(params=_params(), blocking=False)
    returned_after = time.monotonic() - t0
    assert started.wait(5.0)
    assert returned_after < 0.5  # did not wait out the 0.5s writer
    assert not is_committed_checkpoint(out)  # still in flight
    acc.wait_for_checkpoint()
    assert is_committed_checkpoint(out)
    acc.end_training()


def test_backpressure_blocks_second_save_until_commit(tmp_path, monkeypatch):
    """max_in_flight=1: a second async save_state blocks until the first
    commits (bounding host RAM to one extra state copy), then proceeds."""
    from accelerate_tpu import checkpointing

    real = checkpointing.write_and_commit
    delay = 0.4

    def slow(snap, heartbeat=None):
        time.sleep(delay)
        return real(snap, heartbeat=heartbeat)

    monkeypatch.setattr(checkpointing, "write_and_commit", slow)
    acc = _auto_acc(tmp_path, max_in_flight=1)
    out1 = acc.save_state(params=_params(1.0), blocking=False)
    t0 = time.monotonic()
    out2 = acc.save_state(params=_params(2.0), blocking=False)
    blocked = time.monotonic() - t0
    # the second call waited out (most of) the first write
    assert blocked > delay * 0.5
    assert is_committed_checkpoint(out1)  # first committed before second ran
    acc.wait_for_checkpoint()
    assert is_committed_checkpoint(out2)
    acc.end_training()


def test_blocking_save_drains_pending_async_saves(tmp_path, monkeypatch):
    from accelerate_tpu import checkpointing

    real = checkpointing.write_and_commit

    def slow(snap, heartbeat=None):
        time.sleep(0.3)
        return real(snap, heartbeat=heartbeat)

    monkeypatch.setattr(checkpointing, "write_and_commit", slow)
    acc = _auto_acc(tmp_path)
    out1 = acc.save_state(params=_params(1.0), blocking=False)
    out2 = acc.save_state(params=_params(2.0), blocking=True)
    # call order == commit order, both durable when the blocking call returns
    assert is_committed_checkpoint(out1) and is_committed_checkpoint(out2)
    acc.end_training()


def test_writer_error_surfaces_on_wait(tmp_path, monkeypatch):
    from accelerate_tpu import checkpointing

    def boom(snap, heartbeat=None):
        raise OSError("disk on fire")

    monkeypatch.setattr(checkpointing, "write_and_commit", boom)
    acc = _auto_acc(tmp_path)
    acc.save_state(params=_params(), blocking=False)
    with pytest.raises(RuntimeError, match="background checkpoint save") as exc:
        acc.wait_for_checkpoint()
    assert isinstance(exc.value.__cause__, OSError)
    # manager is usable again afterwards
    monkeypatch.undo()
    out = acc.save_state(params=_params(5.0), blocking=False)
    acc.wait_for_checkpoint()
    assert is_committed_checkpoint(out)
    acc.end_training()


def test_writer_error_does_not_leak_backpressure_slot(tmp_path, monkeypatch):
    """A parked writer error raised out of save_state must give the
    back-pressure slot back — with max_in_flight=1 a leaked slot deadlocks
    every later async save."""
    from accelerate_tpu import checkpointing

    real = checkpointing.write_and_commit

    def boom(snap, heartbeat=None):
        raise OSError("disk on fire")

    monkeypatch.setattr(checkpointing, "write_and_commit", boom)
    acc = _auto_acc(tmp_path, max_in_flight=1)
    acc.save_state(params=_params(), blocking=False)
    # wait for the failure to park, then the error surfaces from save_state
    deadline = time.monotonic() + 5.0
    while acc._checkpoint_manager.pending() and time.monotonic() < deadline:
        time.sleep(0.01)
    with pytest.raises(RuntimeError, match="background checkpoint save"):
        acc.save_state(params=_params(2.0), blocking=False)
    # the slot came back: a healthy writer saves without blocking forever
    monkeypatch.setattr(checkpointing, "write_and_commit", real)
    out = acc.save_state(params=_params(3.0), blocking=False)
    acc.wait_for_checkpoint(timeout=10.0)
    assert is_committed_checkpoint(out)
    acc.end_training()


def test_end_training_drains_inflight_save(tmp_path, monkeypatch):
    from accelerate_tpu import checkpointing

    real = checkpointing.write_and_commit

    def slow(snap, heartbeat=None):
        time.sleep(0.3)
        return real(snap, heartbeat=heartbeat)

    monkeypatch.setattr(checkpointing, "write_and_commit", slow)
    acc = _auto_acc(tmp_path)
    out = acc.save_state(params=_params(), blocking=False)
    acc.end_training()
    assert is_committed_checkpoint(out)


def test_async_mid_epoch_resume_matches_sync(tmp_path):
    """An async save at step k must reproduce the exact batch stream a sync
    save at step k reproduces: the dataloader snapshot is taken at call time,
    not at write time."""

    class RangeDS:
        def __len__(self):
            return 1024  # 8 global steps on the 8-way mesh

        def __getitem__(self, i):
            return {"x": np.full((4,), i, np.float32)}

    def run(blocking):
        AcceleratorState._reset_state()
        acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=8))
        dl = acc.prepare(DataLoader(RangeDS(), batch_size=16, shuffle=True, seed=11))
        it = iter(dl)
        for _ in range(3):
            next(it)
        out = acc.save_state(
            str(tmp_path / f"ck_{blocking}"), params=_params(), blocking=blocking
        )
        acc.wait_for_checkpoint()
        tail_live = [np.asarray(b["x"]).copy() for b in it]
        acc.end_training()
        # fresh process-alike: new accelerator + loader, restore, replay
        AcceleratorState._reset_state()
        acc2 = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=8))
        dl2 = acc2.prepare(DataLoader(RangeDS(), batch_size=16, shuffle=True, seed=11))
        acc2.load_state(out, params=_params())
        tail_resumed = [np.asarray(b["x"]).copy() for b in dl2]
        acc2.end_training()
        return tail_live, tail_resumed

    sync_live, sync_resumed = run(blocking=True)
    async_live, async_resumed = run(blocking=False)
    assert len(sync_resumed) == len(async_resumed) == len(sync_live)
    for a, b in zip(sync_resumed, async_resumed):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(async_live, async_resumed):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# rotation


def test_rotation_runs_post_commit_and_skips_staging(tmp_path):
    acc = _auto_acc(tmp_path, total_limit=2, async_save=True)
    root = tmp_path / "checkpoints"
    # a leftover staging dir from a crashed run must neither count toward the
    # limit nor survive the next save (it is torn, uncommitted garbage)
    (root / "checkpoint_90.tmp").mkdir(parents=True)
    (root / "checkpoint_90.tmp" / "model.npz").write_bytes(b"torn")
    for i in range(4):
        acc.save_state(params=_params(float(i)))
    acc.wait_for_checkpoint()
    acc.end_training()
    assert sorted(os.listdir(root)) == ["checkpoint_2", "checkpoint_3"]


def test_rotation_never_deletes_last_committed(tmp_path):
    acc = _auto_acc(tmp_path, total_limit=1)
    root = tmp_path / "checkpoints"
    acc.save_state(params=_params(1.0))
    acc.save_state(params=_params(2.0))
    # simulate checkpoint_1 torn post-commit (marker gone): rotation for the
    # next save must still keep the newest COMMITTED dir available
    os.remove(root / "checkpoint_1" / COMMITTED_MARKER)
    acc.save_state(params=_params(3.0))
    survivors = sorted(os.listdir(root))
    assert "checkpoint_2" in survivors
    assert is_committed_checkpoint(str(root / "checkpoint_2"))
    acc.end_training()


# ---------------------------------------------------------------------------
# crash consistency


def test_load_ignores_uncommitted_newest_dir(tmp_path):
    acc = _auto_acc(tmp_path)
    acc.save_state(params=_params(1.0))
    out2 = acc.save_state(params=_params(2.0))
    os.remove(os.path.join(out2, COMMITTED_MARKER))  # torn newest
    restored = acc.load_state(params=_params(0.0))
    np.testing.assert_allclose(np.asarray(restored["w"]), 1.0)
    acc.end_training()


_CRASH_SCRIPT = """
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from accelerate_tpu import Accelerator, CheckpointConfig
from accelerate_tpu.utils.dataclasses import ProjectConfiguration

d = sys.argv[1]
acc = Accelerator(
    project_config=ProjectConfiguration(project_dir=d, automatic_checkpoint_naming=True),
)
params = {"w": np.full((64, 8), 1.0, np.float32)}
acc.save_state(params=params)  # checkpoint_0: committed
os.environ["ACCELERATE_CKPT_CRASH_POINT"] = sys.argv[2]
acc.save_state(params={"w": np.full((64, 8), 2.0, np.float32)}, blocking=False)
acc.wait_for_checkpoint()  # killed before this returns
print("UNREACHABLE")
"""


def _run_crash_child(tmp_path, point):
    script = tmp_path / "crash_child.py"
    script.write_text(_CRASH_SCRIPT)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("ACCELERATE_CKPT_CRASH_POINT", None)
    res = subprocess.run(
        [sys.executable, str(script), str(tmp_path), point],
        capture_output=True, text=True, timeout=240, env=env,
    )
    assert res.returncode == -9, (res.returncode, res.stdout, res.stderr[-2000:])
    assert "UNREACHABLE" not in res.stdout


def test_kill9_mid_write_resumes_from_previous_commit(tmp_path):
    """kill -9 while the background writer is mid-file: the torn save is
    invisible to load_state (resumes from the previous committed dir) and the
    partial .tmp staging dir is cleaned up by the next save."""
    _run_crash_child(tmp_path, "mid_write")
    root = tmp_path / "checkpoints"
    assert (root / "checkpoint_1.tmp").is_dir()  # partial staging left behind
    assert not (root / "checkpoint_1.tmp" / COMMITTED_MARKER).exists()

    acc = _auto_acc(tmp_path)
    restored = acc.load_state(params={"w": np.zeros((64, 8), np.float32)})
    np.testing.assert_allclose(np.asarray(restored["w"]), 1.0)  # checkpoint_0
    # next save sweeps the torn staging dir
    acc.save_state(params={"w": np.full((64, 8), 3.0, np.float32)})
    assert not (root / "checkpoint_1.tmp").exists()
    acc.end_training()


def test_kill9_between_marker_and_rename_repairs_on_load(tmp_path):
    """kill -9 after the _COMMITTED manifest but before the atomic rename:
    the staging dir is fully durable — the next load finishes the rename and
    resumes from the NEW checkpoint."""
    _run_crash_child(tmp_path, "before_replace")
    root = tmp_path / "checkpoints"
    assert (root / "checkpoint_1.tmp" / COMMITTED_MARKER).exists()

    acc = _auto_acc(tmp_path)
    restored = acc.load_state(params={"w": np.zeros((64, 8), np.float32)})
    np.testing.assert_allclose(np.asarray(restored["w"]), 2.0)  # repaired ckpt_1
    assert (root / "checkpoint_1").is_dir()
    assert not (root / "checkpoint_1.tmp").exists()
    acc.end_training()


# ---------------------------------------------------------------------------
# corruption detection


def test_corrupt_bin_chunk_raises_with_filename(tmp_path):
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.sharding import Mesh

    acc = Accelerator()
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("fsdp",))
    params = {
        "w": jax.device_put(
            np.arange(64, dtype=np.float32).reshape(16, 4), NamedSharding(mesh, P("fsdp"))
        )
    }
    out = acc.save_state(str(tmp_path / "ck"), params=params, sharded=True)
    index_file = next(
        os.path.join(out, n)
        for n in os.listdir(out)
        if n.startswith("model-shard-") and n.endswith(".index.json")
    )
    index = json.load(open(index_file))
    chunk = max(
        (c for meta in index["leaves"].values() for c in meta["chunks"]),
        key=lambda c: c["nbytes"],
    )
    bin_file = index_file[: -len(".index.json")] + ".bin"
    # flip a byte INSIDE a recorded chunk (not alignment padding)
    with open(bin_file, "r+b") as f:
        f.seek(chunk["offset"] + 1)
        byte = f.read(1)
        f.seek(chunk["offset"] + 1)
        f.write(bytes([byte[0] ^ 0xFF]))
    with pytest.raises(CheckpointCorruptError) as exc:
        acc.load_state(out, params=params)
    assert exc.value.path == bin_file
    acc.end_training()


def test_torn_npz_raises_corrupt_error(tmp_path):
    acc = Accelerator()
    out = acc.save_state(str(tmp_path / "ck"), params=_params(1.0))
    npz = os.path.join(out, "model.npz")
    size = os.path.getsize(npz)
    # torn write: same-length zeros over the tail (manifest size still matches)
    with open(npz, "r+b") as f:
        f.seek(size // 2)
        f.write(b"\x00" * (size - size // 2))
    with pytest.raises(CheckpointCorruptError) as exc:
        acc.load_state(out, params=_params(0.0))
    assert exc.value.path == npz
    acc.end_training()


def test_manifest_size_mismatch_raises(tmp_path):
    acc = Accelerator()
    out = acc.save_state(str(tmp_path / "ck"), params=_params(1.0))
    npz = os.path.join(out, "model.npz")
    with open(npz, "ab") as f:
        f.write(b"junk")  # post-commit truncation/append tampering
    with pytest.raises(CheckpointCorruptError):
        acc.load_state(out, params=_params(0.0))
    acc.end_training()


def test_find_latest_checkpoint_repairs_and_prefers_committed(tmp_path):
    acc = _auto_acc(tmp_path)
    out0 = acc.save_state(params=_params(1.0))
    # fabricate an interrupted commit for checkpoint_1: committed staging dir
    root = str(tmp_path / "checkpoints")
    import shutil

    shutil.copytree(out0, os.path.join(root, "checkpoint_1.tmp"))
    latest = find_latest_checkpoint(root)
    assert latest == os.path.join(root, "checkpoint_1")  # repair finished it
    assert is_committed_checkpoint(latest)
    acc.end_training()
