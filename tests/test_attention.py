"""Attention masking: segment-id semantics vs explicit padding masks, and
flash-vs-xla parity (the TPU-gated case pins the Pallas kernel against the
einsum reference under a padding mask — round-2 verdict item 2)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.ops.attention import (
    dot_product_attention,
    make_padding_mask,
)
from accelerate_tpu.ops.flash_attention import flash_attention
from accelerate_tpu.ops.fused_attention import fused_attention, fused_supported
from accelerate_tpu.test_utils.testing import require_tpu


def _qkv(b=2, s=32, h=4, d=16, seed=0):
    keys = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, s, h, d)) for k in keys)


class TestSegmentIds:
    def test_segment_ids_match_padding_mask_on_valid_rows(self):
        """At valid query positions, segment-id masking must equal the
        key-padding-mask einsum path (padded queries differ by design: they
        attend only other pads under segment semantics)."""
        q, k, v = _qkv()
        valid = 20
        attn_mask = np.zeros((2, 32), np.int32)
        attn_mask[:, :valid] = 1

        out_seg = dot_product_attention(
            q, k, v, segment_ids=jnp.asarray(attn_mask), impl="xla"
        )
        out_mask = dot_product_attention(
            q, k, v, mask=make_padding_mask(jnp.asarray(attn_mask), 32), impl="xla"
        )
        np.testing.assert_allclose(
            np.asarray(out_seg[:, :valid]), np.asarray(out_mask[:, :valid]), atol=1e-6
        )

    def test_packed_segments_do_not_cross_attend(self):
        """Two packed documents: tokens of doc A must be unaffected by doc B's
        content (the packing use case of segment ids)."""
        q, k, v = _qkv()
        seg = np.ones((2, 32), np.int32)
        seg[:, 16:] = 2
        out = dot_product_attention(q, k, v, segment_ids=jnp.asarray(seg), impl="xla")

        k2 = k.at[:, 16:].set(jax.random.normal(jax.random.PRNGKey(9), (2, 16, 4, 16)))
        v2 = v.at[:, 16:].set(jax.random.normal(jax.random.PRNGKey(10), (2, 16, 4, 16)))
        out2 = dot_product_attention(q, k2, v2, segment_ids=jnp.asarray(seg), impl="xla")
        np.testing.assert_allclose(
            np.asarray(out[:, :16]), np.asarray(out2[:, :16]), atol=1e-6
        )

    def test_segment_ids_with_causal(self):
        q, k, v = _qkv()
        seg = np.ones((2, 32), np.int32)
        seg[:, 24:] = 0
        out = dot_product_attention(
            q, k, v, causal=True, segment_ids=jnp.asarray(seg), impl="xla"
        )
        assert np.all(np.isfinite(np.asarray(out)))

    def test_flash_wrapper_falls_back_with_segments_off_tpu(self):
        q, k, v = _qkv()
        seg = np.ones((2, 32), np.int32)
        seg[:, 24:] = 0
        out_flash = flash_attention(q, k, v, segment_ids=jnp.asarray(seg))
        out_xla = dot_product_attention(q, k, v, segment_ids=jnp.asarray(seg), impl="xla")
        np.testing.assert_allclose(np.asarray(out_flash), np.asarray(out_xla), atol=1e-5)

    def test_arbitrary_mask_rejects_flash(self):
        q, k, v = _qkv()
        with pytest.raises(ValueError):
            dot_product_attention(
                q, k, v, mask=jnp.ones((2, 1, 32, 32), bool), impl="flash"
            )


class TestFusedKernel:
    def test_supported_shapes(self):
        q = jnp.zeros((4, 128, 12, 64))
        k = jnp.zeros((4, 128, 12, 64))
        assert fused_supported(q, k)
        assert fused_supported(q, jnp.zeros((4, 128, 4, 64)))  # GQA
        assert not fused_supported(q, jnp.zeros((4, 256, 12, 64)))  # cross-len
        assert not fused_supported(jnp.zeros((4, 96, 12, 64)), jnp.zeros((4, 96, 12, 64)))

    def test_fused_impl_dispatch_and_fallback(self):
        """impl='fused' routes through fused_attention; off-TPU it must equal
        the xla path exactly (same mask construction)."""
        q, k, v = _qkv()
        seg = np.ones((2, 32), np.int32)
        seg[:, 24:] = 0
        out_fused = dot_product_attention(q, k, v, segment_ids=jnp.asarray(seg), impl="fused")
        out_xla = dot_product_attention(q, k, v, segment_ids=jnp.asarray(seg), impl="xla")
        np.testing.assert_allclose(np.asarray(out_fused), np.asarray(out_xla), atol=1e-6)

    def test_fused_rejects_arbitrary_mask(self):
        q, k, v = _qkv()
        with pytest.raises(ValueError):
            dot_product_attention(q, k, v, mask=jnp.ones((2, 1, 32, 32), bool), impl="fused")


@require_tpu
class TestFusedParityTPU:
    """Single-pass Pallas kernel vs einsum reference on real TPU hardware."""

    def test_fused_matches_xla_under_padding(self):
        b, s, h, d = 4, 128, 12, 64
        keys = jax.random.split(jax.random.PRNGKey(2), 3)
        q, k, v = (jax.random.normal(kk, (b, s, h, d), jnp.float32) for kk in keys)
        seg = np.ones((b, s), np.int32)
        seg[:, 100:] = 0
        seg = jnp.asarray(seg)
        out_fused = dot_product_attention(q, k, v, segment_ids=seg, impl="fused")
        out_xla = dot_product_attention(q, k, v, segment_ids=seg, impl="xla")
        np.testing.assert_allclose(
            np.asarray(out_fused[:, :100]), np.asarray(out_xla[:, :100]), atol=1e-2
        )

    def test_fused_grads_match_xla(self):
        b, s, h, d = 4, 128, 12, 64
        keys = jax.random.split(jax.random.PRNGKey(3), 3)
        q, k, v = (jax.random.normal(kk, (b, s, h, d), jnp.float32) for kk in keys)
        seg = np.ones((b, s), np.int32)
        seg[:, 96:] = 0
        seg = jnp.asarray(seg)

        def loss(impl, q, k, v):
            out = dot_product_attention(q, k, v, segment_ids=seg, impl=impl)
            return jnp.sum(out[:, :96] ** 2)

        gf = jax.grad(lambda *a: loss("fused", *a), argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(lambda *a: loss("xla", *a), argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(gf, gx):
            rel = float(jnp.abs(a - b_).max() / (jnp.abs(b_).max() + 1e-9))
            assert rel < 2e-2, rel

    def test_fused_causal_gqa(self):
        b, s, h, d = 4, 128, 8, 64
        keys = jax.random.split(jax.random.PRNGKey(4), 3)
        q = jax.random.normal(keys[0], (b, s, h, d), jnp.float32)
        k = jax.random.normal(keys[1], (b, s, 2, d), jnp.float32)
        v = jax.random.normal(keys[2], (b, s, 2, d), jnp.float32)
        out_fused = dot_product_attention(q, k, v, causal=True, impl="fused")
        out_xla = dot_product_attention(q, k, v, causal=True, impl="xla")
        np.testing.assert_allclose(np.asarray(out_fused), np.asarray(out_xla), atol=1e-2)


@require_tpu
class TestFlashParityTPU:
    """Pallas kernel vs einsum reference on real TPU hardware."""

    def test_flash_matches_xla_under_padding(self):
        b, s, h, d = 2, 256, 4, 64
        keys = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(kk, (b, s, h, d), jnp.bfloat16) for kk in keys)
        seg = np.ones((b, s), np.int32)
        seg[:, 200:] = 0
        seg = jnp.asarray(seg)
        out_flash = dot_product_attention(q, k, v, segment_ids=seg, impl="flash")
        out_xla = dot_product_attention(q, k, v, segment_ids=seg, impl="xla")
        np.testing.assert_allclose(
            np.asarray(out_flash[:, :200], dtype=np.float32),
            np.asarray(out_xla[:, :200], dtype=np.float32),
            atol=2e-2,
        )

    def test_flash_grads_match_xla_under_padding(self):
        b, s, h, d = 2, 256, 4, 64
        keys = jax.random.split(jax.random.PRNGKey(1), 3)
        q, k, v = (jax.random.normal(kk, (b, s, h, d), jnp.float32) for kk in keys)
        seg = np.ones((b, s), np.int32)
        seg[:, 192:] = 0
        seg = jnp.asarray(seg)

        def loss(impl, q, k, v):
            out = dot_product_attention(q, k, v, segment_ids=seg, impl=impl)
            return jnp.sum(out[:, :192] ** 2)

        gf = jax.grad(lambda *a: loss("flash", *a), argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(lambda *a: loss("xla", *a), argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(gf, gx):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=5e-2, rtol=1e-2)


class TestAttnImplConfigKnob:
    """`LlamaConfig.attn_impl` (ISSUE 18 satellite): the config knob feeds
    `llama_forward`'s default attention implementation, and an explicit
    `attention_impl=` argument still wins over the config."""

    def _setup(self):
        from dataclasses import replace

        from accelerate_tpu.models import LlamaConfig, init_llama, llama_forward

        cfg = LlamaConfig.tiny()
        params = init_llama(cfg, jax.random.PRNGKey(0))
        ids = jnp.asarray(np.arange(2 * 16).reshape(2, 16) % cfg.vocab_size)
        return replace, cfg, params, ids, llama_forward

    def test_config_default_is_auto_and_round_trips(self):
        replace, cfg, _, _, _ = self._setup()
        assert cfg.attn_impl == "auto"
        assert replace(cfg, attn_impl="fused").attn_impl == "fused"
        assert cfg.attn_impl == "auto"  # frozen original untouched

    def test_fused_knob_matches_xla_off_tpu(self):
        """impl='fused' falls back to the xla mask path off TPU, so wiring
        the knob through the config must reproduce attn_impl='xla' exactly."""
        replace, cfg, params, ids, llama_forward = self._setup()
        out_fused = llama_forward(params, ids, replace(cfg, attn_impl="fused"))
        out_xla = llama_forward(params, ids, replace(cfg, attn_impl="xla"))
        np.testing.assert_allclose(
            np.asarray(out_fused), np.asarray(out_xla), atol=1e-6
        )

    def test_explicit_argument_overrides_config(self):
        replace, cfg, params, ids, llama_forward = self._setup()
        out_arg = llama_forward(
            params, ids, replace(cfg, attn_impl="fused"), attention_impl="xla"
        )
        out_xla = llama_forward(params, ids, replace(cfg, attn_impl="xla"))
        assert np.array_equal(np.asarray(out_arg), np.asarray(out_xla))
