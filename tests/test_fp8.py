"""FP8 delayed-scaling tests: quantized-dot accuracy, gradient fidelity, meta
(amax history) threading through the optimizer partition, end-to-end training
convergence in fp8 (reference fp8 benchmarks compare loss parity vs bf16)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from accelerate_tpu.ops.fp8 import (
    E4M3_MAX,
    META_KEY,
    FP8Recipe,
    fp8_dense_apply,
    fp8_dense_init,
    fp8_dot,
    fp8_param_labels,
    has_fp8_meta,
    init_fp8_meta,
    make_fp8_optimizer,
)


def _rand(shape, seed=0, scale=1.0):
    return jax.random.normal(jax.random.PRNGKey(seed), shape) * scale


class TestFp8Dot:
    def test_forward_close_to_dense(self):
        x, w = _rand((16, 64), 0), _rand((64, 32), 1)
        meta = init_fp8_meta()
        # histories start empty → first-step scale uses fp8_max fallback;
        # prime them with one grad step for realistic scales
        out = fp8_dot(x, w, meta)
        ref = x @ w
        rel = float(jnp.linalg.norm(out - ref) / jnp.linalg.norm(ref))
        assert rel < 0.06, rel

    def test_batched_input(self):
        x, w = _rand((4, 8, 64)), _rand((64, 16), 1)
        out = fp8_dot(x, w, init_fp8_meta())
        assert out.shape == (4, 8, 16)

    def test_gradients_close_to_dense(self):
        x, w = _rand((16, 64), 2), _rand((64, 32), 3)
        meta = init_fp8_meta()

        def loss_fp8(x, w, meta):
            return jnp.sum(fp8_dot(x, w, meta) ** 2)

        def loss_dense(x, w):
            return jnp.sum((x @ w) ** 2)

        gx, gw, gmeta = jax.grad(loss_fp8, argnums=(0, 1, 2))(x, w, meta)
        rx, rw = jax.grad(loss_dense, argnums=(0, 1))(x, w)
        assert float(jnp.linalg.norm(gx - rx) / jnp.linalg.norm(rx)) < 0.15
        assert float(jnp.linalg.norm(gw - rw) / jnp.linalg.norm(rw)) < 0.15
        # meta cotangent is the UPDATED history: slot 0 holds this step's amax
        np.testing.assert_allclose(float(gmeta["x_hist"][0]),
                                   float(jnp.max(jnp.abs(x))), rtol=1e-5)
        np.testing.assert_allclose(float(gmeta["w_hist"][0]),
                                   float(jnp.max(jnp.abs(w))), rtol=1e-5)
        assert float(gmeta["g_hist"][0]) > 0

    def test_scale_uses_history(self):
        """After priming, quantization uses the recorded amax (better accuracy
        for small-magnitude tensors than the fp8_max fallback)."""
        x, w = _rand((16, 64), 4) * 0.01, _rand((64, 32), 5) * 0.01
        meta = init_fp8_meta()
        cold = fp8_dot(x, w, meta)
        primed = {
            "x_hist": meta["x_hist"].at[0].set(jnp.max(jnp.abs(x))),
            "w_hist": meta["w_hist"].at[0].set(jnp.max(jnp.abs(w))),
            "g_hist": meta["g_hist"],
        }
        warm = fp8_dot(x, w, primed)
        ref = x @ w
        err_cold = float(jnp.linalg.norm(cold - ref))
        err_warm = float(jnp.linalg.norm(warm - ref))
        assert err_warm < err_cold

    def test_most_recent_algo_and_e4m3_format(self):
        recipe = FP8Recipe(amax_compute_algo="most_recent", fp8_format="E4M3")
        x, w = _rand((8, 32)), _rand((32, 8), 1)
        out = fp8_dot(x, w, init_fp8_meta(recipe), recipe)
        assert out.shape == (8, 8)
        with pytest.raises(ValueError):
            FP8Recipe(amax_compute_algo="bogus")


class TestMetaThreading:
    def test_labels(self):
        params = {"dense": fp8_dense_init(jax.random.PRNGKey(0), 8, 4),
                  "head": {"kernel": _rand((4, 2))}}
        labels = fp8_param_labels(params)
        assert labels["dense"][META_KEY]["x_hist"] == "fp8_meta"
        assert labels["dense"]["kernel"] == "default"
        assert labels["head"]["kernel"] == "default"
        assert has_fp8_meta(params) and not has_fp8_meta({"a": 1})

    def test_training_updates_meta_and_converges(self):
        """End-to-end: 2-layer fp8 MLP regression; meta histories fill up;
        loss reaches near-dense quality."""
        k = jax.random.split(jax.random.PRNGKey(0), 4)
        params = {
            "l1": fp8_dense_init(k[0], 16, 32),
            "l2": fp8_dense_init(k[1], 32, 1),
        }
        W = _rand((16, 1), 7)
        X = _rand((256, 16), 8)
        Y = X @ W

        def loss_fn(p, x, y):
            h = jax.nn.relu(fp8_dense_apply(p["l1"], x))
            pred = fp8_dense_apply(p["l2"], h)
            return jnp.mean((pred - y) ** 2)

        opt = make_fp8_optimizer(optax.adam(1e-2), params)
        opt_state = opt.init(params)

        @jax.jit
        def step(p, s, x, y):
            loss, grads = jax.value_and_grad(loss_fn)(p, x, y)
            updates, s = opt.update(grads, s, p)
            return optax.apply_updates(p, updates), s, loss

        first = None
        for i in range(200):
            params, opt_state, loss = step(params, opt_state, X, Y)
            if first is None:
                first = float(loss)
        assert float(loss) < first * 0.05, (first, float(loss))
        # histories actually recorded amax values
        assert float(jnp.max(params["l1"][META_KEY]["x_hist"])) > 0
        assert float(jnp.max(params["l1"][META_KEY]["g_hist"])) > 0
        # meta was REPLACED, not optimized: histories hold real amax magnitudes
        amax_x = float(params["l1"][META_KEY]["x_hist"][0])
        np.testing.assert_allclose(amax_x, float(jnp.max(jnp.abs(X))), rtol=0.5)

    def test_meta_under_scan(self):
        """Stacked fp8 layers scanned with lax.scan — the stacked-meta case."""
        L, D = 3, 16
        keys = jax.random.split(jax.random.PRNGKey(1), L)
        stacked = {
            "kernel": jnp.stack([_rand((D, D), i) for i in range(L)]),
            META_KEY: jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *[init_fp8_meta() for _ in range(L)]
            ),
        }

        def layer(h, p):
            return jax.nn.relu(fp8_dot(h, p["kernel"], p[META_KEY])), None

        def loss_fn(p, x):
            h, _ = jax.lax.scan(layer, x, p)
            return jnp.sum(h ** 2)

        x = _rand((4, D), 9)
        loss, grads = jax.value_and_grad(loss_fn)(stacked, x)
        assert np.isfinite(float(loss))
        assert grads[META_KEY]["x_hist"].shape == stacked[META_KEY]["x_hist"].shape


class TestAcceleratorIntegration:
    def test_fp8_mixed_precision_training(self):
        """mixed_precision='fp8' + fp8 params: the optimizer is auto-partitioned
        and the jitted step trains while threading amax histories."""
        from accelerate_tpu import Accelerator

        acc = Accelerator(mixed_precision="fp8", cpu=True)
        k = jax.random.split(jax.random.PRNGKey(0), 2)
        params = {"l1": fp8_dense_init(k[0], 16, 32), "l2": fp8_dense_init(k[1], 32, 1)}
        opt = optax.adam(1e-2)
        params, opt = acc.prepare(params, opt)

        W = _rand((16, 1), 7)
        X = _rand((256, 16), 8)
        Y = X @ W

        def loss_fn(p, batch):
            h = jax.nn.relu(fp8_dense_apply(p["l1"], batch["x"]))
            return jnp.mean((fp8_dense_apply(p["l2"], h) - batch["y"]) ** 2)

        step = acc.prepare_train_step(loss_fn, opt)
        opt_state = opt.opt_state
        batch = {"x": X, "y": Y}
        first = None
        for _ in range(150):
            params, opt_state, m = step(params, opt_state, batch)
            if first is None:
                first = float(m["loss"])
        assert float(m["loss"]) < first * 0.1, (first, float(m["loss"]))
        # meta histories filled AND stayed f32 through the bf16 compute cast
        meta = params["l1"][META_KEY]
        assert meta["x_hist"].dtype == jnp.float32
        assert float(jnp.max(meta["x_hist"])) > 0
        assert float(jnp.max(meta["g_hist"])) > 0


def test_fp8_wrap_when_optimizer_prepared_first():
    """prepare(optimizer, model) order must still partition the fp8 meta."""
    from accelerate_tpu import Accelerator

    acc = Accelerator(mixed_precision="fp8", cpu=True)
    params = {"l1": fp8_dense_init(jax.random.PRNGKey(0), 16, 8)}
    opt, params = acc.prepare(optax.adam(1e-2), params)

    def loss_fn(p, b):
        return jnp.mean(fp8_dense_apply(p["l1"], b) ** 2)

    step = acc.prepare_train_step(loss_fn, opt)
    s = opt.opt_state
    x = _rand((32, 16))
    p1, s, _ = step(params, s, x)
    # meta history slot 0 must hold this step's amax (replacement semantics),
    # not an adam-mangled value
    np.testing.assert_allclose(float(p1["l1"][META_KEY]["x_hist"][0]),
                               float(jnp.max(jnp.abs(x))), rtol=1e-3)


class TestFp8GradAccumulation:
    """amax histories must roll EVERY micro-step while real params update only
    on accumulation boundaries (round-2 verdict item: MultiSteps around the
    whole partition would average/delay the delayed-scaling statistics)."""

    def _setup(self, accum):
        from accelerate_tpu import Accelerator

        acc = Accelerator(
            mixed_precision="fp8", cpu=True, gradient_accumulation_steps=accum
        )
        params = {"l1": fp8_dense_init(jax.random.PRNGKey(0), 16, 8)}
        opt = optax.sgd(1e-2)
        params, opt = acc.prepare(params, opt)

        def loss_fn(p, b):
            return jnp.mean(fp8_dense_apply(p["l1"], b["x"]) ** 2)

        step = acc.prepare_train_step(loss_fn, opt, donate=False)
        return acc, params, opt, step

    def test_meta_rolls_every_microstep_params_on_boundary(self):
        acc, params, opt, step = self._setup(accum=2)
        opt_state = opt.opt_state
        kernel0 = np.asarray(params["l1"]["kernel"]).copy()

        batches = [
            {"x": _rand((8, 16), seed) * (seed + 1.0)} for seed in range(4)
        ]
        hists = [np.asarray(params["l1"][META_KEY]["x_hist"]).copy()]
        kernels = [kernel0]
        for b in batches:
            params, opt_state, _ = step(params, opt_state, b)
            hists.append(np.asarray(params["l1"][META_KEY]["x_hist"]).copy())
            kernels.append(np.asarray(params["l1"]["kernel"]).copy())

        # histories differ after EVERY micro-step (slot 0 = that step's amax)
        for i in range(1, len(hists)):
            assert not np.array_equal(hists[i], hists[i - 1]), f"history stale at step {i}"
            # and slot0 holds the *current* batch amax, not an average
            # bf16 compute cast → compare with bf16-level tolerance
            expected_amax = float(np.max(np.abs(np.asarray(batches[i - 1]["x"]))))
            assert abs(float(hists[i][0]) - expected_amax) < 1e-2 * expected_amax, (
                i, hists[i][0], expected_amax,
            )

        # params: unchanged after micro-step 1 and 3, changed on boundaries 2 and 4
        assert np.array_equal(kernels[1], kernels[0]), "params moved mid-accumulation"
        assert not np.array_equal(kernels[2], kernels[1]), "no update on boundary"
        assert np.array_equal(kernels[3], kernels[2]), "params moved mid-accumulation"
        assert not np.array_equal(kernels[4], kernels[3]), "no update on boundary"

    def test_boundary_bookkeeping_with_nested_multisteps(self):
        acc, params, opt, step = self._setup(accum=2)
        opt_state = opt.opt_state
        assert opt.is_accumulation_boundary  # fresh state: mini_step == 0
        params, opt_state, _ = step(params, opt_state, {"x": _rand((8, 16), 1)})
        assert not opt.is_accumulation_boundary
        assert opt.step_count == 0
        params, opt_state, _ = step(params, opt_state, {"x": _rand((8, 16), 2)})
        assert opt.is_accumulation_boundary
        assert opt.step_count == 1
