"""Tracker suite tests (reference ``tests/test_tracking.py`` — 870 LoC of
dummy/offline trackers + log-file parsing; here the always-available JSONL
tracker plays the offline role and the 9 integration classes are validated
structurally, since their libraries are not installed in this image)."""

import json

from accelerate_tpu.tracking import (
    _AVAILABILITY,
    LOGGER_TYPE_TO_CLASS,
    GeneralTracker,
    JSONLTracker,
    filter_trackers,
)


def test_registry_covers_reference_integrations():
    """The reference ships 9 integrations (tracking.py:182-1226); all must have
    a counterpart class + availability probe here."""
    expected = {
        "tensorboard", "wandb", "mlflow", "comet_ml", "aim", "clearml",
        "dvclive", "swanlab", "trackio",
    }
    assert expected <= set(LOGGER_TYPE_TO_CLASS)
    assert expected <= set(_AVAILABILITY)
    for name, cls in LOGGER_TYPE_TO_CLASS.items():
        assert issubclass(cls, GeneralTracker)
        assert cls.name == name
        # the full API surface (reference GeneralTracker:143-181)
        for method in ("store_init_configuration", "log", "finish"):
            assert callable(getattr(cls, method)), (name, method)


def test_filter_trackers_skips_unavailable(caplog):
    # none of the heavy integrations are installed in this image — requesting
    # one must warn-and-skip, not raise (reference filter_trackers:1262)
    unavailable = [n for n in LOGGER_TYPE_TO_CLASS if not _AVAILABILITY[n]()]
    if not unavailable:  # pragma: no cover - all libs present
        return
    got = filter_trackers([unavailable[0]], project_name="run")
    assert got == []


def test_filter_trackers_unknown_name_raises(tmp_path):
    import pytest

    with pytest.raises(ValueError):
        filter_trackers(["definitely_not_a_tracker"], project_name="run")


def test_jsonl_tracker_roundtrip(tmp_path):
    tracker = JSONLTracker("run", logging_dir=str(tmp_path))
    tracker.store_init_configuration({"lr": 1e-3, "nested": {"bs": 8}})
    tracker.log({"loss": 1.5}, step=0)
    tracker.log({"loss": 0.5}, step=1)
    tracker.finish()
    lines = [json.loads(line) for line in (tmp_path / "run.jsonl").read_text().splitlines()]
    assert lines[0]["_type"] == "config" and lines[0]["lr"] == 1e-3
    assert [entry["loss"] for entry in lines[1:]] == [1.5, 0.5]
    assert [entry["step"] for entry in lines[1:]] == [0, 1]


def test_all_resolves_to_available_only():
    from accelerate_tpu.utils.dataclasses import LoggerType

    got = filter_trackers(LoggerType.ALL, project_name="run", logging_dir="/tmp")
    names = {t.name for t in got}
    assert "jsonl" in names
    for t in got:
        t.finish()
    for name in names:
        assert _AVAILABILITY[name]()
