"""Tracker suite tests (reference ``tests/test_tracking.py`` — 870 LoC of
dummy/offline trackers + log-file parsing; here the always-available JSONL
tracker plays the offline role and the 9 integration classes are validated
structurally, since their libraries are not installed in this image)."""

import json

import pytest

from accelerate_tpu.tracking import (
    _AVAILABILITY,
    LOGGER_TYPE_TO_CLASS,
    GeneralTracker,
    JSONLTracker,
    filter_trackers,
)


def test_registry_covers_reference_integrations():
    """The reference ships 9 integrations (tracking.py:182-1226); all must have
    a counterpart class + availability probe here."""
    expected = {
        "tensorboard", "wandb", "mlflow", "comet_ml", "aim", "clearml",
        "dvclive", "swanlab", "trackio",
    }
    assert expected <= set(LOGGER_TYPE_TO_CLASS)
    assert expected <= set(_AVAILABILITY)
    for name, cls in LOGGER_TYPE_TO_CLASS.items():
        assert issubclass(cls, GeneralTracker)
        assert cls.name == name
        # the full API surface (reference GeneralTracker:143-181)
        for method in ("store_init_configuration", "log", "finish"):
            assert callable(getattr(cls, method)), (name, method)


def test_filter_trackers_skips_unavailable(caplog):
    # none of the heavy integrations are installed in this image — requesting
    # one must warn-and-skip, not raise (reference filter_trackers:1262)
    unavailable = [n for n in LOGGER_TYPE_TO_CLASS if not _AVAILABILITY[n]()]
    if not unavailable:  # pragma: no cover - all libs present
        return
    got = filter_trackers([unavailable[0]], project_name="run")
    assert got == []


def test_filter_trackers_unknown_name_raises(tmp_path):
    import pytest

    with pytest.raises(ValueError):
        filter_trackers(["definitely_not_a_tracker"], project_name="run")


@pytest.mark.smoke
def test_jsonl_tracker_roundtrip(tmp_path):
    tracker = JSONLTracker("run", logging_dir=str(tmp_path))
    tracker.store_init_configuration({"lr": 1e-3, "nested": {"bs": 8}})
    tracker.log({"loss": 1.5}, step=0)
    tracker.log({"loss": 0.5}, step=1)
    tracker.finish()
    lines = [json.loads(line) for line in (tmp_path / "run.jsonl").read_text().splitlines()]
    assert lines[0]["_type"] == "config" and lines[0]["lr"] == 1e-3
    assert [entry["loss"] for entry in lines[1:]] == [1.5, 0.5]
    assert [entry["step"] for entry in lines[1:]] == [0, 1]


def test_deferred_start_lifecycle(tmp_path):
    """Two-phase init (reference GeneralTracker.start tracking.py:142):
    construction is side-effect free; start() creates the run; logging before
    start() lazily starts."""
    tracker = JSONLTracker("run", logging_dir=str(tmp_path))
    assert not (tmp_path / "run.jsonl").exists()  # __init__ wrote nothing
    tracker.start()
    assert (tmp_path / "run.jsonl").exists()
    tracker.start()  # idempotent
    tracker.log({"a": 1}, step=0)
    tracker.finish()
    # lazy-start path: no explicit start() before log
    lazy = JSONLTracker("lazy", logging_dir=str(tmp_path))
    lazy.log({"b": 2})
    lazy.finish()
    assert (tmp_path / "lazy.jsonl").exists()
    # finish() on a never-started tracker is a harmless no-op
    JSONLTracker("unused", logging_dir=str(tmp_path)).finish()
    assert not (tmp_path / "unused.jsonl").exists()


def test_api_surface_includes_media_methods():
    for name, cls in LOGGER_TYPE_TO_CLASS.items():
        for method in ("start", "log_images", "log_table"):
            assert callable(getattr(cls, method)), (name, method)


def test_jsonl_log_images_writes_sidecars(tmp_path):
    import numpy as np

    tracker = JSONLTracker("run", logging_dir=str(tmp_path))
    imgs = [np.zeros((4, 4, 3), np.uint8), np.ones((4, 4, 3), np.uint8)]
    tracker.log_images({"samples": imgs}, step=3)
    tracker.finish()
    lines = [json.loads(line) for line in (tmp_path / "run.jsonl").read_text().splitlines()]
    entry = next(e for e in lines if e["_type"] == "images")
    assert entry["step"] == 3 and len(entry["samples"]) == 2
    back = np.load(entry["samples"][1]["path"])
    np.testing.assert_array_equal(back, imgs[1])


def test_jsonl_log_table_rows_and_dataframe(tmp_path):
    tracker = JSONLTracker("run", logging_dir=str(tmp_path))
    tracker.log_table("preds", columns=["text", "label"],
                      data=[["a", 0], ["b", 1]], step=1)
    tracker.finish()
    lines = [json.loads(line) for line in (tmp_path / "run.jsonl").read_text().splitlines()]
    entry = next(e for e in lines if e["_type"] == "table")
    assert entry["name"] == "preds"
    assert entry["columns"] == ["text", "label"]
    assert entry["rows"] == [["a", 0], ["b", 1]]


def test_tensorboard_log_images(tmp_path):
    import numpy as np
    import pytest

    from accelerate_tpu.tracking import _AVAILABILITY, TensorBoardTracker

    if not _AVAILABILITY["tensorboard"]():
        pytest.skip("tensorboard unavailable")
    tracker = TensorBoardTracker("run", logging_dir=str(tmp_path))
    tracker.start()
    imgs = np.random.default_rng(0).integers(0, 255, (2, 8, 8, 3)).astype(np.uint8)
    tracker.log_images({"samples": imgs}, step=0)
    tracker.log({"loss": 1.0}, step=0)
    tracker.finish()
    event_files = list((tmp_path / "run").glob("events*"))
    assert event_files and event_files[0].stat().st_size > 0


def test_base_tracker_media_methods_warn_not_raise():
    t = GeneralTracker("run")
    t.start()
    t.log_images({"x": []})  # warns, must not raise
    t.log_table("t", columns=["a"], data=[[1]])


def test_accelerator_log_images_and_table(tmp_path):
    import numpy as np

    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    acc = Accelerator(log_with="jsonl", project_dir=str(tmp_path))
    acc.init_trackers("proj")
    acc.log_images({"img": [np.zeros((2, 2), np.uint8)]}, step=0)
    acc.log_table("tbl", columns=["k"], data=[["v"]], step=0)
    acc.end_training()
    text = (tmp_path / "proj.jsonl").read_text()
    assert '"_type": "images"' in text and '"_type": "table"' in text


def test_all_resolves_to_available_only():
    from accelerate_tpu.utils.dataclasses import LoggerType

    got = filter_trackers(LoggerType.ALL, project_name="run", logging_dir="/tmp")
    names = {t.name for t in got}
    assert "jsonl" in names
    for t in got:
        t.finish()
    for name in names:
        assert _AVAILABILITY[name]()
