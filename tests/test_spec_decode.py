"""Speculative decoding with bitwise-accept verification (ISSUE 18).

The engine's spec-decode path (``spec_tokens=k, draft_layers=m``) proposes
k tokens per slot-step from a truncated-layer self-draft (the first m
verifier layers, sharing the verifier's KV pool) and verifies them with ONE
batched S=k+1 forward whose acceptance rule is BITWISE: position j is
accepted only if the draft token equals the exact token the non-speculative
stream would have selected there (same fold_in(rng_seed, token_idx) key,
same select_one). So the output stream is identical to ``spec_tokens=0``
token-for-token in BOTH greedy and sampled modes — speculation may only
change how many steps it takes, never what comes out. These tests hold that
line end to end, plus the jit-cache freeze (draft + verify warmed at every
decode point), the accept-rate accounting, and the config surface.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.generation import greedy_generate
from accelerate_tpu.models import LlamaConfig, init_llama
from accelerate_tpu.models.transformer import draft_config, draft_params
from accelerate_tpu.serving import BucketLattice, ReplicaSpec, ServingEngine

CONFIG = LlamaConfig.tiny()
LATTICE = BucketLattice(slot_buckets=(2, 4), block_buckets=(4,),
                        prefill_buckets=(32,))


def _engine(params, **kw):
    kw.setdefault("lattice", LATTICE)
    return ServingEngine(
        params, CONFIG, num_blocks=33, block_size=8, max_slots=4,
        cache_dtype=jnp.float32, **kw,
    )


def _drive(engine, prompts, specs, *, seeds=None):
    reqs = [engine.submit(p, n, rng_seed=(seeds[i] if seeds else i))
            for i, (p, (_, n)) in enumerate(zip(prompts, specs))]
    engine.run()
    return [r.output_ids() for r in reqs]


@pytest.mark.smoke
def test_greedy_spec_decode_is_bitwise_identical():
    """The acceptance-criteria line: greedy output streams with speculation
    on are token-for-token identical to both the non-speculative engine and
    the single-stream ``greedy_generate`` reference — while actually
    accepting draft tokens (fewer engine steps than baseline)."""
    params = init_llama(CONFIG, jax.random.PRNGKey(0))
    rng = np.random.default_rng(7)
    specs = [(5, 7), (13, 11), (21, 5), (9, 9)]
    prompts = [rng.integers(0, CONFIG.vocab_size, (s,)).astype(np.int32)
               for s, _ in specs]

    base = _engine(params)
    base.warmup()
    out_base = _drive(base, prompts, specs)

    spec = _engine(params, spec_tokens=3, draft_layers=1)
    spec.warmup()
    out_spec = _drive(spec, prompts, specs)

    for i, (b, s) in enumerate(zip(out_base, out_spec)):
        assert np.array_equal(b, s), f"request {i} diverged under speculation"
        ref = greedy_generate(params, prompts[i][None], CONFIG,
                              max_new_tokens=specs[i][1])
        assert np.array_equal(np.asarray(ref[0]), s), f"request {i} vs reference"
    st = spec.stats()
    assert st["draft_proposed_tokens"] > 0
    assert st["draft_accepted_tokens"] > 0  # self-draft layer 0 agrees sometimes
    assert spec.steps < base.steps  # accepted drafts shortened the run


def test_sampled_spec_decode_is_bitwise_identical():
    """Bitwise-accept is sampling-safe: the verify step recomputes the exact
    fold_in key the non-speculative stream would use at each position, so
    temperature/top-k sampling with speculation matches the non-speculative
    engine stream-for-stream."""
    params = init_llama(CONFIG, jax.random.PRNGKey(0))
    rng = np.random.default_rng(10)
    specs = [(7, 8), (15, 6), (4, 10)]
    prompts = [rng.integers(0, CONFIG.vocab_size, (s,)).astype(np.int32)
               for s, _ in specs]
    sample_kw = dict(temperature=0.8, top_k=20)

    base = _engine(params, **sample_kw)
    base.warmup()
    out_base = _drive(base, prompts, specs, seeds=[11, 12, 13])

    spec = _engine(params, spec_tokens=2, draft_layers=1, **sample_kw)
    spec.warmup()
    out_spec = _drive(spec, prompts, specs, seeds=[11, 12, 13])

    for i, (b, s) in enumerate(zip(out_base, out_spec)):
        assert np.array_equal(b, s), f"sampled request {i} diverged"


def test_full_depth_draft_accepts_everything():
    """draft_layers == n_layers makes the draft the verifier itself: every
    greedy proposal must be accepted (accept rate 1.0) — the self-draft
    correctness canary (pool sharing, positions, fold indices all line up)."""
    params = init_llama(CONFIG, jax.random.PRNGKey(0))
    eng = _engine(params, spec_tokens=2, draft_layers=CONFIG.n_layers)
    eng.warmup()
    rng = np.random.default_rng(11)
    prompts = [rng.integers(0, CONFIG.vocab_size, (6,)).astype(np.int32)]
    _drive(eng, prompts, [(6, 8)])
    st = eng.stats()
    assert st["draft_proposed_tokens"] > 0
    assert st["spec_accept_rate"] == 1.0


def test_spec_decode_jit_caches_freeze_after_warmup():
    """Warmup covers draft + verify at every decode point: a full serve
    afterwards must add ZERO compiles to any cache (the no-recompile
    acceptance line, including the two new speculative functions)."""
    params = init_llama(CONFIG, jax.random.PRNGKey(0))
    eng = _engine(params, spec_tokens=3, draft_layers=1)
    warmed = eng.warmup()
    before = eng.jit_cache_sizes()
    assert before == warmed
    assert before["draft_compiles"] == len(LATTICE.decode_points())
    assert before["verify_compiles"] == len(LATTICE.decode_points())
    rng = np.random.default_rng(12)
    specs = [(5, 7), (13, 11), (21, 5), (9, 9), (12, 6)]
    prompts = [rng.integers(0, CONFIG.vocab_size, (s,)).astype(np.int32)
               for s, _ in specs]
    _drive(eng, prompts, specs)
    assert eng.jit_cache_sizes() == before, "post-warmup recompile"


def test_spec_decode_through_interpreted_kernels(monkeypatch):
    """Both ISSUE 18 features on at once: the draft's S=1 steps run the
    decode kernel and the S=k+1 verify runs the chunked-prefill kernel
    (interpreter mode on CPU — the same dataflow the TPU compiles). Outputs
    must still match the non-speculative engine bitwise and the jit caches
    must stay frozen after warmup."""
    monkeypatch.setenv("ACCELERATE_PAGED_KERNEL", "interpret")
    params = init_llama(CONFIG, jax.random.PRNGKey(0))
    rng = np.random.default_rng(7)
    specs = [(5, 7), (13, 11), (21, 5)]
    prompts = [rng.integers(0, CONFIG.vocab_size, (s,)).astype(np.int32)
               for s, _ in specs]

    base = _engine(params)
    base.warmup()
    out_base = _drive(base, prompts, specs)

    spec = _engine(params, spec_tokens=3, draft_layers=1)
    frozen = spec.warmup()
    out_spec = _drive(spec, prompts, specs)

    for i, (b, s) in enumerate(zip(out_base, out_spec)):
        assert np.array_equal(b, s), f"request {i} diverged under kernels"
    assert spec.jit_cache_sizes() == frozen, "post-warmup recompile"
    assert spec.stats()["draft_proposed_tokens"] > 0


def test_spec_accept_accounting():
    """proposed == accepted + rejected; the accept histogram's per-step
    counts weight-sum back to the accepted-token total; stats carries the
    config knobs."""
    params = init_llama(CONFIG, jax.random.PRNGKey(0))
    k = 3
    eng = _engine(params, spec_tokens=k, draft_layers=1)
    eng.warmup()
    rng = np.random.default_rng(13)
    specs = [(8, 9), (14, 12)]
    prompts = [rng.integers(0, CONFIG.vocab_size, (s,)).astype(np.int32)
               for s, _ in specs]
    _drive(eng, prompts, specs)
    st = eng.stats()
    assert st["spec_tokens"] == k and st["draft_layers"] == 1
    assert (st["draft_proposed_tokens"]
            == st["draft_accepted_tokens"] + st["draft_rejected_tokens"])
    hist = st["spec_accept_hist"]
    assert len(hist) == k + 1
    assert sum(i * c for i, c in enumerate(hist)) == st["draft_accepted_tokens"]
    assert st["spec_accept_rate"] == pytest.approx(
        st["draft_accepted_tokens"] / st["draft_proposed_tokens"], abs=1e-6)


def test_spec_config_validation():
    params = init_llama(CONFIG, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="spec_tokens"):
        _engine(params, spec_tokens=-1)
    with pytest.raises(ValueError, match="draft_layers"):
        _engine(params, spec_tokens=2)  # no draft_layers given
    with pytest.raises(ValueError, match="draft_layers"):
        _engine(params, spec_tokens=2, draft_layers=CONFIG.n_layers + 1)


def test_draft_params_and_config_truncate_layers():
    params = init_llama(CONFIG, jax.random.PRNGKey(0))
    d_cfg = draft_config(CONFIG, 1)
    assert d_cfg.n_layers == 1 and CONFIG.n_layers > 1  # original untouched
    dp = draft_params(params, 1)
    for leaf, full in zip(jax.tree_util.tree_leaves(dp["layers"]),
                          jax.tree_util.tree_leaves(params["layers"])):
        assert leaf.shape[0] == 1
        assert np.array_equal(np.asarray(leaf), np.asarray(full[:1]))
    assert dp["embed_tokens"] is params["embed_tokens"]  # shared, not copied
    with pytest.raises(ValueError, match="draft_layers"):
        draft_config(CONFIG, 0)
    with pytest.raises(ValueError, match="draft_layers"):
        draft_config(CONFIG, CONFIG.n_layers + 1)


def test_lattice_warmup_points_count_spec_functions():
    assert LATTICE.warmup_points() == LATTICE.size()
    assert (LATTICE.warmup_points(spec_decode=True)
            == LATTICE.size() + 2 * len(LATTICE.decode_points()))
    assert (LATTICE.warmup_points(prefix_cache=True, spec_decode=True)
            == LATTICE.size() + 1 + 2 * len(LATTICE.decode_points()))


def test_replica_spec_threads_spec_knobs_to_the_engine():
    spec = ReplicaSpec(
        model=dict(CONFIG.__dict__), num_blocks=33, block_size=8, max_slots=4,
        slot_buckets=(2, 4), block_buckets=(4,), prefill_buckets=(32,),
        param_dtype="float32", spec_tokens=2, draft_layers=1,
    )
    eng = spec.build_engine()
    assert eng.spec_tokens == 2 and eng.draft_layers == 1
    assert "draft_compiles" in eng.jit_cache_sizes()
