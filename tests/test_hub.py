"""Live observability hub (telemetry/hub.py): incremental file tailing
across growth / rotation / truncation / torn trailing lines, FleetModel
folding, the `top` dashboard rendering through the report CLI's own section
formatters (the shared-formatter invariant), and `report --follow`."""

import io
import json
import os

from accelerate_tpu.telemetry.anomaly import AnomalyEngine
from accelerate_tpu.telemetry.hub import (
    ANSI_CLEAR,
    HUB_STREAM,
    EventHub,
    FileTail,
    FleetModel,
    run_follow,
    run_top,
)
from accelerate_tpu.telemetry.report import (
    build_report,
    format_canary_section,
    format_report,
    main as report_main,
)


def _w(path, records, mode="a"):
    with open(path, mode) as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")


def _meta(run_id="hubtest", rank=0, n=1):
    return {"kind": "meta", "schema": 1, "run_id": run_id,
            "process_index": rank, "num_processes": n}


def _step(i, dur=0.01):
    return {"kind": "step", "step": i, "t": float(i), "dur_s": dur,
            "execute_s": dur}


# ---------------------------------------------------------------- FileTail --


def test_filetail_incremental_growth_and_torn_line(tmp_path):
    path = str(tmp_path / "events-rank0.jsonl")
    _w(path, [_meta(), _step(0)], mode="w")
    tail = FileTail(path)
    recs = tail.poll()
    assert [r["kind"] for r in recs] == ["meta", "step"]
    assert all(r["_file"] == "events-rank0.jsonl" for r in recs)
    assert tail.poll() == []                      # nothing new
    # a torn trailing line is buffered, not parsed and not lost
    with open(path, "a") as f:
        f.write(json.dumps(_step(1)) + "\n")
        f.write('{"kind": "step", "step": 2, "t"')
    recs = tail.poll()
    assert [r["step"] for r in recs] == [1]
    with open(path, "a") as f:
        f.write(': 2.0, "dur_s": 0.01}\n')        # the writer finishes it
    recs = tail.poll()
    assert [r["step"] for r in recs] == [2]       # parsed exactly once, whole
    assert tail.resets == 0


def test_filetail_rotation_detected_by_identity_not_size(tmp_path):
    path = str(tmp_path / "events-rank0.jsonl")
    _w(path, [_meta(run_id="old-run!!")], mode="w")
    tail = FileTail(path)
    assert tail.poll()[0]["run_id"] == "old-run!!"
    # rotate in a NEW file of the same byte length: only the inode changed
    side = str(tmp_path / "side.jsonl")
    _w(side, [_meta(run_id="new-run!!")], mode="w")
    assert os.path.getsize(side) == os.path.getsize(path)
    os.replace(side, path)
    recs = tail.poll()
    assert tail.resets == 1
    assert [r["run_id"] for r in recs] == ["new-run!!"]


def test_filetail_truncation_restarts_from_zero(tmp_path):
    path = str(tmp_path / "events-rank0.jsonl")
    _w(path, [_meta()] + [_step(i) for i in range(5)], mode="w")
    tail = FileTail(path)
    assert len(tail.poll()) == 6
    _w(path, [_meta(run_id="restarted")], mode="w")   # in-place truncation
    recs = tail.poll()
    assert tail.resets == 1
    assert [r["run_id"] for r in recs] == ["restarted"]


def test_filetail_skips_garbage_and_missing_file(tmp_path):
    path = str(tmp_path / "events-rank0.jsonl")
    tail = FileTail(path)
    assert tail.poll() == []                      # not written yet: no error
    with open(path, "w") as f:
        f.write("not json at all\n")
        f.write(json.dumps(_step(0)) + "\n")
        f.write("[1, 2, 3]\n")                    # parseable but not a dict
        f.write("\n")
    recs = tail.poll()
    assert [r["step"] for r in recs] == [0]


# -------------------------------------------------------------- FleetModel --


def test_fleet_model_folds_fixture_records():
    m = FleetModel()
    for rec in [
        _meta(),
        {"kind": "serving_replica", "replica": "r0", "state": "healthy", "t": 1.0},
        {"kind": "serving_replica", "replica": "r1", "state": "healthy", "t": 1.0},
        {"kind": "serving_replica", "replica": "r1", "state": "draining", "t": 2.0},
        {"kind": "router", "phase": "poll", "queued": 3, "inflight": 2,
         "completed": 7, "shed": 1, "failovers": 1, "t": 2.5},
        {"kind": "supervisor", "generation": 1, "processes": 2,
         "restarts_used": 1, "max_restarts": 2, "t": 3.0},
        {"kind": "restart", "generation": 1, "t": 3.1},
        {"kind": "slo_violation", "slo": "ttft_p95_s", "t": 3.2},
        {"kind": "anomaly", "detector": "step_latency", "t": 3.3},
        {"kind": "canary", "replica": "r0", "result": "match", "t": 3.4},
        {"kind": "canary", "replica": "r1", "result": "mismatch", "t": 3.5},
    ]:
        m.fold(rec)
    assert m.replicas["r1"]["state"] == "draining"     # last record wins
    assert m.replica_states() == {"draining": 1, "healthy": 1}
    assert m.router_poll["completed"] == 7
    assert m.supervisor["restarts_used"] == 1 and m.generation == 1
    assert m.restarts == 1 and m.slo_violations == 1
    assert m.anomaly_episodes == 1
    assert m.canary_probes == 2 and m.canary_failures == 1
    assert m.last_t == 3.5
    assert m.kinds["canary"] == 2
    # the snapshot defers to the report CLI's aggregation over the same fold
    snap = m.snapshot_report()
    assert snap["events"] == len(m.records)


def test_hub_discovers_streams_mid_run_and_injects_anomalies(tmp_path):
    """Replicas spawn mid-run: a stream that appears between polls must be
    picked up, and episodes fired by the engine must fold back as synthetic
    `anomaly` records on the hub's own stream marker."""
    d = str(tmp_path)
    _w(os.path.join(d, "events-rank0.jsonl"),
       [_meta()] + [_step(i) for i in range(30)], mode="w")
    hub = EventHub([d], anomaly=AnomalyEngine(emit_records=False))
    assert len(hub.poll()) == 31
    # a second stream appears after the first poll
    _w(os.path.join(d, "events-rank1.jsonl"),
       [_meta(rank=1, n=2)] + [_step(i, dur=0.9) for i in range(30, 33)],
       mode="w")
    new = hub.poll()
    kinds = [r["kind"] for r in new]
    assert kinds.count("step") == 3 and kinds.count("anomaly") == 1
    synth = [r for r in new if r["kind"] == "anomaly"]
    assert synth[0]["_file"] == HUB_STREAM
    assert synth[0]["detector"] == "step_latency"
    assert hub.model.anomaly_episodes == 1


# ------------------------------------------------------------ top / follow --


def _degraded_fleet_dir(tmp_path):
    d = str(tmp_path)
    recs = [_meta()] + [_step(i) for i in range(30)]
    recs += [_step(i, dur=0.3) for i in range(30, 34)]       # slow burst
    recs += [
        {"kind": "serving_replica", "replica": "good", "state": "healthy",
         "t": 40.0},
        {"kind": "serving_replica", "replica": "bad", "state": "draining",
         "t": 41.0},
        {"kind": "router", "phase": "poll", "queued": 0, "inflight": 0,
         "completed": 5, "shed": 0, "failovers": 0, "t": 41.5},
        {"kind": "supervisor", "generation": 1, "processes": 2,
         "restarts_used": 1, "max_restarts": 2, "t": 42.0},
        {"kind": "canary", "replica": "good", "rid": "canary-1",
         "golden": "golden0", "result": "match", "t": 43.0},
        {"kind": "canary", "replica": "bad", "rid": "canary-2",
         "golden": "golden1", "result": "mismatch", "t": 44.0},
        {"kind": "canary_failure", "replica": "bad", "rid": "canary-2",
         "golden": "golden1", "mismatch_index": 2, "expected_token": 17,
         "got_token": 4, "expected_len": 6, "got_len": 6, "drained": True,
         "t": 44.0},
    ]
    _w(os.path.join(d, "events-rank0.jsonl"), recs, mode="w")
    return d


def test_top_once_renders_degraded_fleet_via_shared_formatters(tmp_path):
    d = _degraded_fleet_dir(tmp_path)
    buf = io.StringIO()
    assert run_top([d], once=True, out=buf) == 0
    frame = buf.getvalue()
    assert ANSI_CLEAR not in frame                 # --once is pipe-safe
    assert "fleet top — run(s): hubtest" in frame
    assert "replicas: 2 (draining=1, healthy=1)" in frame
    assert "supervisor: generation 1, 2 process(es), restarts 1/2" in frame
    assert "ALERTS: 1 anomaly episode(s), 0 slo violation(s), " \
           "1 canary failure(s)" in frame
    assert "steps: 34" in frame
    # the live detector fired on the slow burst, with the cause attached
    assert "step_latency: 1 episode(s)" in frame
    assert "straggler or contended host" in frame
    # the shared-formatter invariant: the post-hoc report's canary section
    # appears in the live frame STRING-EXACT — same records, same code
    post = build_report([d])
    assert format_canary_section(post["canary"]) in frame
    assert "MISMATCH on bad: golden golden1 token 2 expected 17 got 4" in frame


def test_top_live_frames_clear_and_count(tmp_path):
    d = _degraded_fleet_dir(tmp_path)
    buf = io.StringIO()
    naps = []
    rc = run_top([d], max_ticks=2, interval_s=0.5, sleep=naps.append, out=buf)
    assert rc == 0
    out = buf.getvalue()
    assert out.count(ANSI_CLEAR) == 2
    assert "frame 1" in out and "frame 2" in out
    assert naps == [0.5]                           # no sleep after the last tick


def test_follow_mode_streams_report_increments(tmp_path):
    d = str(tmp_path)
    path = os.path.join(d, "events-rank0.jsonl")
    _w(path, [_meta()] + [_step(i) for i in range(3)], mode="w")

    def grow(_interval):                           # the writer races the tail
        _w(path, [_step(3), _step(4)])

    buf = io.StringIO()
    rc = run_follow([d], max_ticks=2, sleep=grow, out=buf)
    assert rc == 0
    out = buf.getvalue()
    assert "==== follow: +4 record(s), 4 total ====" in out
    assert "==== follow: +2 record(s), 6 total ====" in out
    # each increment re-renders the full post-hoc report text
    assert out.count("steps:") == 2
    assert format_report(build_report([d])) in out  # final render is exact


def test_follow_quiet_tick_prints_nothing(tmp_path):
    d = str(tmp_path)
    _w(os.path.join(d, "events-rank0.jsonl"), [_meta(), _step(0)], mode="w")
    buf = io.StringIO()
    rc = run_follow([d], max_ticks=3, sleep=lambda s: None, out=buf)
    assert rc == 0
    assert buf.getvalue().count("==== follow:") == 1  # ticks 2 & 3 were quiet


def test_cli_top_once_and_report_follow(tmp_path, capsys):
    d = _degraded_fleet_dir(tmp_path)
    assert report_main(["top", str(d), "--once"]) == 0
    out = capsys.readouterr().out
    assert "fleet top" in out and "canaries:" in out
    assert report_main(
        ["report", str(d), "--follow", "--follow-ticks", "1",
         "--interval", "0.01"]
    ) == 0
    out = capsys.readouterr().out
    assert "==== follow:" in out and "canaries:" in out
