"""Serving subsystem tests (ISSUE 11): paged KV cache + continuous batching.

The two acceptance lines these tests hold:

- paged decode through the engine is IDENTICAL to the single-stream
  ``generation`` decode for every admitted request — greedy and sampled
  (fixed key), including sequences whose blocks are non-contiguous in the
  pool and sequences that were preempted and resumed;
- admission/completion/eviction churn after bucket warmup never grows the
  jit caches (the telemetry recompile detector is the oracle).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.generation import _cached_attention, greedy_generate, sample_generate
from accelerate_tpu.models import LlamaConfig, init_llama
from accelerate_tpu.serving import (
    NULL_BLOCK,
    BlockAllocator,
    BlockAllocatorError,
    BlockPoolExhausted,
    BucketLattice,
    Request,
    RequestStatus,
    Scheduler,
    SchedulingError,
    ServingEngine,
    paged_attention,
)

CONFIG = LlamaConfig.tiny()
SMALL_LATTICE = BucketLattice(slot_buckets=(2, 4), block_buckets=(4,), prefill_buckets=(32,))


@pytest.fixture(scope="module")
def params():
    return jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16), init_llama(CONFIG, jax.random.PRNGKey(0))
    )


@pytest.fixture(scope="module")
def greedy_engine(params):
    engine = ServingEngine(
        params, CONFIG, num_blocks=33, block_size=8, max_slots=4, lattice=SMALL_LATTICE
    )
    engine.warmup()
    return engine


def _prompts(seed, lengths):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, CONFIG.vocab_size, (n,)).astype(np.int32) for n in lengths]


# ---------------------------------------------------------------------------
# block allocator


@pytest.mark.smoke
def test_allocator_lifecycle_and_accounting():
    alloc = BlockAllocator(num_blocks=9, block_size=4)
    assert alloc.usable_blocks == 8 and alloc.free_blocks == 8
    table = alloc.allocate("a", 6)  # 6 tokens -> 2 blocks
    assert len(table) == 2 and NULL_BLOCK not in table
    assert alloc.used_blocks == 2 and alloc.tokens("a") == 6
    # internal fragmentation: 8 allocated slots, 6 live tokens
    assert alloc.fragmentation() == pytest.approx(2 / 8)
    assert alloc.occupancy() == pytest.approx(2 / 8)
    # append within the last block allocates nothing; crossing allocates one
    assert alloc.append("a", 2) == []
    new = alloc.append("a", 1)
    assert len(new) == 1 and alloc.num_seq_blocks("a") == 3
    assert alloc.free("a") == 3
    assert alloc.free_blocks == 8 and alloc.stats()["live_tokens"] == 0


def test_allocator_free_list_reuse_and_nonmonotonic_tables():
    alloc = BlockAllocator(num_blocks=9, block_size=4)
    (x,) = alloc.allocate("x", 1)
    (y,) = alloc.allocate("y", 1)
    (z,) = alloc.allocate("z", 1)
    alloc.free("y")
    # LIFO free list: the just-freed block is handed out next...
    grown = alloc.append("z", 4)
    assert grown == [y]
    # ...which makes z's table non-monotonic in physical block ids
    table = alloc.block_table("z")
    assert table.tolist() == [z, y] and z > y
    # padding fills with the null block
    assert alloc.block_table("z", pad_to=4).tolist() == [z, y, NULL_BLOCK, NULL_BLOCK]


def test_allocator_errors():
    alloc = BlockAllocator(num_blocks=4, block_size=2)
    alloc.allocate("a", 2)
    with pytest.raises(BlockAllocatorError, match="already allocated"):
        alloc.allocate("a", 1)
    with pytest.raises(BlockPoolExhausted):
        alloc.allocate("big", 100)
    assert "big" not in alloc.live_sequences()  # all-or-nothing
    alloc.free("a")
    with pytest.raises(BlockAllocatorError, match="double free"):
        alloc.free("a")
    with pytest.raises(BlockAllocatorError, match="use-after-free"):
        alloc.append("a", 1)
    with pytest.raises(BlockAllocatorError, match="use-after-free"):
        alloc.block_table("a")


def test_allocator_exhaustion_leaves_sequence_unchanged():
    alloc = BlockAllocator(num_blocks=3, block_size=2)
    alloc.allocate("a", 2)
    alloc.allocate("b", 2)
    with pytest.raises(BlockPoolExhausted):
        alloc.append("a", 4)  # needs 2 more blocks, 0 free
    assert alloc.tokens("a") == 2 and alloc.num_seq_blocks("a") == 1


# ---------------------------------------------------------------------------
# bucket lattice


def test_bucket_lattice_rounding_and_limits():
    lat = BucketLattice.from_limits(max_slots=6, max_blocks_per_seq=5, max_prefill_len=48)
    assert lat.slot_buckets == (1, 2, 4, 6)
    assert lat.block_buckets == (1, 2, 4, 5)
    assert lat.prefill_buckets == (8, 16, 32, 48)
    assert lat.slot_bucket(3) == 4 and lat.slot_bucket(0) == 1
    assert lat.block_bucket(5) == 5
    assert lat.prefill_bucket(9) == 16
    with pytest.raises(ValueError, match="exceeds the bucket lattice"):
        lat.prefill_bucket(49)
    # every prefill point pairs with the single widest block bucket
    assert lat.prefill_points() == [(8, 5), (16, 5), (32, 5), (48, 5)]
    assert lat.size() == len(lat.decode_points()) + len(lat.prefill_points())


# ---------------------------------------------------------------------------
# paged attention parity (the bitwise micro-proof)


def test_paged_attention_bitwise_matches_contiguous_on_scrambled_blocks():
    """A sequence scattered over non-contiguous, out-of-order physical blocks
    must attend bitwise-identically to the same values in a contiguous cache
    — gather correctness plus exact-zero masking of null/stale slots."""
    rng = np.random.default_rng(0)
    B, S, H, D, Hkv = 1, 3, 4, 32, 2
    max_len, bs = 24, 8
    q = jnp.asarray(rng.normal(size=(B, S, H, D)).astype(np.float32)).astype(jnp.bfloat16)
    k_full = rng.normal(size=(B, max_len, Hkv, D)).astype(np.float32)
    v_full = rng.normal(size=(B, max_len, Hkv, D)).astype(np.float32)
    seq_len = 19
    q_positions = jnp.asarray([[seq_len - 3, seq_len - 2, seq_len - 1]], jnp.int32)
    ref = jax.jit(_cached_attention)(
        q,
        jnp.asarray(k_full).astype(jnp.bfloat16),
        jnp.asarray(v_full).astype(jnp.bfloat16),
        q_positions[0],
    )
    # scatter the 19 live tokens into scrambled blocks; garbage elsewhere
    nb = 6
    pool_k = rng.normal(size=(nb, bs, Hkv, D)).astype(np.float32)
    pool_v = rng.normal(size=(nb, bs, Hkv, D)).astype(np.float32)
    table = [5, 2, 4]  # logical block i -> scrambled physical id
    for i in range(seq_len):
        blk, off = divmod(i, bs)
        pool_k[table[blk], off] = k_full[0, i]
        pool_v[table[blk], off] = v_full[0, i]
    out = jax.jit(paged_attention)(
        q,
        jnp.asarray(pool_k).astype(jnp.bfloat16),
        jnp.asarray(pool_v).astype(jnp.bfloat16),
        jnp.asarray([table + [NULL_BLOCK]], jnp.int32),  # null-padded width 4
        q_positions,
    )
    assert np.array_equal(
        np.asarray(ref, np.float32), np.asarray(out, np.float32)
    ), "paged attention diverged from the contiguous cache"


# ---------------------------------------------------------------------------
# engine decode parity vs the single-stream reference


def test_engine_greedy_parity_with_noncontiguous_blocks(params, greedy_engine):
    engine = greedy_engine
    prompts = _prompts(0, (5, 13, 21, 9))
    max_new = (7, 11, 5, 9)
    reqs = [
        engine.submit(p, m, rng_seed=i) for i, (p, m) in enumerate(zip(prompts, max_new))
    ]
    # step until mid-flight, then prove at least one live sequence's blocks
    # are non-contiguous (concurrent growth interleaves the pool)
    noncontiguous = False
    for _ in range(4):
        engine.step()
        for req in engine.scheduler.running():
            table = engine.allocator.block_table(req.rid)
            if len(table) > 1 and np.any(np.diff(table) != 1):
                noncontiguous = True
    engine.run()
    assert noncontiguous, "concurrent requests never interleaved pool blocks"
    for i, (p, m) in enumerate(zip(prompts, max_new)):
        ref = greedy_generate(params, p[None], CONFIG, max_new_tokens=m)
        assert np.array_equal(np.asarray(ref[0]), reqs[i].output_ids()), f"request {i}"


def test_engine_chunked_prefill_parity_beyond_largest_bucket(params):
    """A prefix longer than the largest prefill bucket must chunk through it
    (length-bucketed chunked prefill) and still match the single-stream
    reference exactly."""
    engine = ServingEngine(
        params, CONFIG, num_blocks=17, block_size=8, max_slots=2,
        max_blocks_per_seq=8,
        lattice=BucketLattice(slot_buckets=(2,), block_buckets=(8,),
                              prefill_buckets=(16, 32)),
    )
    engine.warmup()
    prompt = _prompts(9, (45,))[0]  # 45 > the 32-wide largest prefill bucket
    req = engine.submit(prompt, 6, rng_seed=3)
    engine.run()
    ref = greedy_generate(params, prompt[None], CONFIG, max_new_tokens=6)
    assert np.array_equal(np.asarray(ref[0]), req.output_ids())
    # chunking stayed inside the warmed lattice: no new compiles
    assert engine.jit_cache_sizes() == {
        "prefill_compiles": 2, "decode_compiles": 1, "cow_compiles": 1
    }


def test_engine_sampled_parity_fixed_keys(params):
    knobs = dict(temperature=0.8, top_k=7, top_p=0.95)
    engine = ServingEngine(
        params, CONFIG, num_blocks=33, block_size=8, max_slots=4,
        lattice=SMALL_LATTICE, **knobs,
    )
    engine.warmup()
    prompts = _prompts(1, (6, 17, 11))
    max_new = (9, 6, 12)
    reqs = [
        engine.submit(p, m, rng_seed=100 + i)
        for i, (p, m) in enumerate(zip(prompts, max_new))
    ]
    engine.run()
    for i, (p, m) in enumerate(zip(prompts, max_new)):
        ref = sample_generate(
            params, p[None], CONFIG, max_new_tokens=m,
            rng_key=jax.random.PRNGKey(100 + i), **knobs,
        )
        assert np.array_equal(np.asarray(ref[0]), reqs[i].output_ids()), f"request {i}"


def test_engine_preemption_resumes_with_identical_output(params):
    """Pool pressure must evict the youngest request and resume it later with
    output identical to an uninterrupted single-stream run."""
    engine = ServingEngine(
        params, CONFIG, num_blocks=10, block_size=8, max_slots=4,
        max_blocks_per_seq=8,
        lattice=BucketLattice(slot_buckets=(1, 2, 4), block_buckets=(4, 8),
                              prefill_buckets=(32,)),
    )
    engine.warmup()
    prompts = _prompts(2, (16, 14, 15))
    reqs = [engine.submit(p, 16, rng_seed=i) for i, p in enumerate(prompts)]
    engine.run()
    assert engine.scheduler.preemption_count >= 1
    assert any(r.preemptions >= 1 for r in reqs)
    for i, p in enumerate(prompts):
        ref = greedy_generate(params, p[None], CONFIG, max_new_tokens=16)
        assert np.array_equal(np.asarray(ref[0]), reqs[i].output_ids()), f"request {i}"


def test_engine_eos_frees_slot_and_backfills(params, greedy_engine):
    """A request hitting eos stops early and its slot is backfilled by the
    queue at the next step (continuous batching's whole point)."""
    engine = greedy_engine
    prompts = _prompts(3, (8, 8, 8, 8, 8, 8))
    # learn what token the model actually emits first, then use it as eos
    probe = engine.submit(prompts[0], 2, rng_seed=0)
    engine.run()
    eos = probe.generated[0]
    reqs = [engine.submit(p, 12, eos_token_id=eos, rng_seed=i) for i, p in enumerate(prompts[1:])]
    done = engine.run()
    assert len(done) == len(reqs)
    for req in reqs:
        assert req.generated[-1] == eos or len(req.generated) == 12
        ref = greedy_generate(
            params, req.prompt[None], CONFIG, max_new_tokens=12, eos_token_id=eos
        )
        # reference pads with eos after finishing; the engine stops — compare
        # the engine's tokens against the reference prefix
        n = req.output_ids().size
        assert np.array_equal(np.asarray(ref[0])[:n], req.output_ids())


def test_engine_rejects_impossible_request(params):
    big = _prompts(4, (26,))[0]  # 26 + 4 tokens -> 4 blocks, cap is 2
    small = ServingEngine(
        params, CONFIG, num_blocks=3, block_size=8, max_slots=2,
        lattice=BucketLattice(slot_buckets=(2,), block_buckets=(2,), prefill_buckets=(32,)),
    )
    small.warmup()
    req = small.submit(big, 4)
    ok = small.submit(_prompts(5, (6,))[0], 3)
    done = small.run()
    # the impossible request is returned with a REJECTED status + reason,
    # never silently dropped; the queue behind it still drains
    assert req in done and req.status is RequestStatus.REJECTED
    assert req.generated == [] and "per-sequence cap" in req.error
    assert ok in done and len(ok.generated) == 3


def test_engine_rejects_request_outgrowing_the_block_lattice(params):
    """A request whose prompt fits but whose worst case (prompt + max_new)
    outgrows the lattice's widest block table must be rejected at ADMISSION
    — not crash the engine mid-decode with blocks leaked."""
    engine = ServingEngine(
        params, CONFIG, num_blocks=33, block_size=8, max_slots=2,
        lattice=BucketLattice(slot_buckets=(2,), block_buckets=(4,),
                              prefill_buckets=(16,)),
    )
    engine.warmup()
    # 10 + 30 = 40 tokens -> 5 blocks: fits the 32-block pool, NOT the
    # 4-wide table cap (the review finding's reproducer)
    doomed = engine.submit(_prompts(6, (10,))[0], 30)
    ok = engine.submit(_prompts(7, (10,))[0], 8)
    done = engine.run()
    assert doomed.status is RequestStatus.REJECTED and doomed in done
    assert ok in done and len(ok.generated) == 8
    assert engine.allocator.stats()["sequences"] == 0  # nothing leaked


def test_scheduler_static_mode_gang_admission():
    alloc = BlockAllocator(num_blocks=17, block_size=8)
    sched = Scheduler(alloc, max_slots=2, continuous=False)
    reqs = [Request(prompt=np.arange(4) + 1, max_new_tokens=4) for _ in range(3)]
    for r in reqs:
        sched.submit(r)
    first = sched.admissions()
    assert len(first) == 2  # gang of two
    # nothing admits while the gang is running — even with a free slot
    sched.complete(first[0], now=0.0)
    assert sched.admissions() == []
    sched.complete(first[1], now=0.0)
    assert sched.admissions() == [reqs[2]]  # only on a fully drained engine


def test_engine_rejects_request_beyond_rope_table(params):
    """Worst case (prefix + max_new) past config.max_seq_len must be rejected
    at admission: positions past the RoPE table would be silently clamped by
    the cos/sin gathers, corrupting output with no error."""
    engine = ServingEngine(
        params, CONFIG, num_blocks=40, block_size=8, max_slots=1,
        lattice=BucketLattice(slot_buckets=(1,), block_buckets=(39,),
                              prefill_buckets=(32,)),
    )
    # 30 + 250 = 280 tokens: fits the 39-block cap (35 blocks) but exceeds
    # tiny's max_seq_len of 256 — the token rule, not the block rule, fires
    doomed = engine.submit(_prompts(10, (30,))[0], 250)
    done = engine.run()
    assert doomed in done and doomed.status is RequestStatus.REJECTED
    assert "max_seq_len" in doomed.error


def test_scheduler_grow_error_is_a_guarded_backstop():
    """Admission's worst-case check makes grow()'s pool-exhaustion path
    unreachable through the engine, but the scheduler keeps it as a backstop:
    a sequence that somehow outgrows the pool with nothing left to evict
    raises a clear SchedulingError instead of a deep allocator error."""
    alloc = BlockAllocator(num_blocks=3, block_size=2)
    sched = Scheduler(alloc, max_slots=2)
    req = Request(prompt=np.arange(2) + 1, max_new_tokens=1)  # worst 3 tokens: admits
    sched.submit(req)
    assert sched.admissions() == [req]
    with pytest.raises(SchedulingError, match="no other sequence left to evict"):
        for _ in range(8):  # grown past its declared max_new, past the pool
            sched.grow(req)


# ---------------------------------------------------------------------------
# zero-recompile churn guard (telemetry recompile detector as the oracle)


def test_zero_recompiles_through_admission_churn(params):
    from accelerate_tpu.telemetry.step_profiler import RecompileWatcher

    engine = ServingEngine(
        params, CONFIG, num_blocks=17, block_size=4, max_slots=4,
        max_blocks_per_seq=8,
        lattice=BucketLattice(slot_buckets=(2, 4), block_buckets=(4, 8),
                              prefill_buckets=(16, 32)),
    )
    warmed = engine.warmup()
    assert warmed["decode_compiles"] == len(engine.lattice.decode_points())
    assert warmed["prefill_compiles"] == len(engine.lattice.prefill_points())
    watcher = RecompileWatcher()
    watcher.register("serving_prefill", engine.prefill_fn)
    watcher.register("serving_decode", engine.decode_fn)

    # churn across every bucket: light load (1 slot), full load (4 slots),
    # short and long prompts (both prefill buckets), sequences crossing the
    # 4->8 block-width boundary, eviction pressure, staggered arrivals
    rng = np.random.default_rng(7)
    lengths = [3, 14, 30, 9, 22, 5, 28, 12]
    news = [4, 9, 2, 14, 6, 11, 3, 8]
    reqs = []
    for i in range(0, len(lengths), 2):
        for j in (i, i + 1):
            prompt = rng.integers(0, CONFIG.vocab_size, (lengths[j],)).astype(np.int32)
            reqs.append(engine.submit(prompt, news[j], rng_seed=j))
        engine.step()
    engine.run()
    assert all(r.done for r in reqs)

    # the oracle: jit caches frozen at the warmed counts, watcher sees zero
    # cache misses after warmup
    assert engine.jit_cache_sizes() == warmed
    assert watcher.poll(emit=False) == {}


# ---------------------------------------------------------------------------
# telemetry + report


def test_serving_telemetry_and_report_section(params, tmp_path):
    from accelerate_tpu.telemetry import events as tel
    from accelerate_tpu.telemetry.report import build_report, format_report

    tel.enable(out_dir=str(tmp_path), run_id="serving-test")
    try:
        engine = ServingEngine(
            params, CONFIG, num_blocks=33, block_size=8, max_slots=4,
            lattice=SMALL_LATTICE,
        )
        engine.warmup()
        for i, (p, m) in enumerate(zip(_prompts(8, (5, 12, 9)), (6, 4, 8))):
            engine.submit(p, m, rng_seed=i)
        engine.run()
    finally:
        tel.disable()

    report = build_report([str(tmp_path)])
    serving = report["serving"]
    assert serving["steps"] == engine.steps
    assert serving["requests"]["completed"] == 3
    assert serving["requests"]["new_tokens"] == 6 + 4 + 8
    assert serving["decode_tokens"] == engine.decode_tokens
    assert serving["prefill_tokens"] == engine.prefill_tokens
    # prefix-cache schema fields are always present (zero for this
    # unshared workload) and mirror the engine's own counters
    assert serving["prefill_tokens_saved"] == engine.prefix_cached_tokens
    assert 0.0 <= serving["prefix_hit_rate"] <= 1.0
    assert serving["occupancy"]["max"] > 0.5  # batched, not serialized
    assert serving["requests"]["latency_s"]["count"] == 3
    text = format_report(report)
    assert "serving:" in text and "batch occupancy" in text and "requests: 3 completed" in text


def test_report_without_serving_records_omits_section(tmp_path):
    from accelerate_tpu.telemetry.report import build_report, format_report

    (tmp_path / "events-rank0.jsonl").write_text(
        '{"kind": "meta", "schema": 1, "run_id": "r", "process_index": 0, '
        '"num_processes": 1}\n'
    )
    report = build_report([str(tmp_path)])
    assert report["serving"] is None
    assert "serving:" not in format_report(report)


# ---------------------------------------------------------------------------
# prefix cache: refcounted block sharing + copy-on-write (ISSUE 14)


def test_prefix_allocator_shares_blocks_and_refcounts():
    alloc = BlockAllocator(num_blocks=17, block_size=4, prefix_caching=True)
    toks = np.arange(10, dtype=np.int32)  # 2 full blocks + a 2-token tail
    t_a = alloc.allocate_with_prefix("a", toks)
    assert t_a.cached_tokens == 0 and t_a.cow is None
    # same prefix, longer tail: the two full blocks are MAPPED, not copied
    t_b = alloc.allocate_with_prefix("b", np.concatenate([toks, toks[:3]]))
    assert t_b.cached_tokens == 8
    assert t_b.table[:2] == t_a.table[:2]
    assert t_b.table[2:] != t_a.table[2:]  # private tails
    assert alloc.shared_blocks() == 2
    # free one sharer: shared blocks stay live for the other (no
    # use-after-free); a's PARTIAL tail block is not content-indexed so it
    # goes straight back to the free list, while the full blocks stay
    # referenced by b (nothing parks in the LRU pool yet)
    free_before = alloc.free_blocks
    alloc.free("a")
    assert alloc.block_table("b")[0] == t_b.table[0]
    assert alloc.shared_blocks() == 0 and alloc.reclaimable_blocks == 0
    assert alloc.free_blocks == free_before + 1
    # a third request still matches the chain through b's references
    t_c = alloc.allocate_with_prefix("c", toks.copy())
    assert t_c.cached_tokens == 8 and t_c.table[:2] == t_b.table[:2]
    # freeing the LAST referents parks the registered blocks, matchable until
    # reclaimed
    alloc.free("b")
    alloc.free("c")
    assert alloc.reclaimable_blocks >= 2
    t_d = alloc.allocate_with_prefix("d", toks.copy())
    assert t_d.cached_tokens == 8


def test_prefix_allocator_full_match_is_copy_on_write():
    alloc = BlockAllocator(num_blocks=17, block_size=4, prefix_caching=True)
    toks = np.arange(8, dtype=np.int32)  # exactly 2 blocks: the aligned case
    t_a = alloc.allocate_with_prefix("a", toks)
    t_b = alloc.allocate_with_prefix("b", toks.copy())
    # all but the last position come from the cache; the last matched block
    # is replaced by a private copy target so no shared block is ever written
    assert t_b.cached_tokens == 7
    assert t_b.cow is not None
    src, dst = t_b.cow
    assert src == t_a.table[-1] and dst == t_b.table[-1] and dst != src
    assert t_b.table[0] == t_a.table[0]
    # the src pin: until the engine confirms the device copy, src must not be
    # reclaimable even though no live table holds it beyond a's
    alloc.free("a")
    free_before = alloc.free_blocks
    while alloc.free_blocks:  # drain the free list completely
        alloc.allocate(f"f{alloc.free_blocks}", alloc.block_size)
    with pytest.raises(BlockPoolExhausted):
        # the only reclaimable candidates are pinned/referenced: must refuse,
        # never hand out the COW source
        alloc.allocate("overflow", 10 * alloc.block_size)
    alloc.cow_done(src)
    assert alloc.reclaimable_blocks >= 1  # pin released: src parks in LRU
    assert free_before >= 0


def test_prefix_allocator_reclaims_lru_before_rejecting():
    alloc = BlockAllocator(num_blocks=9, block_size=4, prefix_caching=True)
    toks = np.arange(32, dtype=np.int32)  # all 8 usable blocks
    alloc.allocate_with_prefix("a", toks)
    alloc.free("a")  # every block cached + unreferenced (LRU pool)
    assert alloc.free_blocks == 0 and alloc.reclaimable_blocks == 8
    assert alloc.available_blocks == 8  # caching never shrinks capacity
    # a new unrelated allocation must reclaim from the LRU pool, not reject
    table = alloc.allocate_with_prefix("b", 100 + np.arange(12, dtype=np.int32))
    assert len(table.table) == 3 and alloc.reclaimed_blocks == 3
    # 3 reclaimed entries left the content index; b's 3 full blocks joined it
    assert alloc.stats()["cached_blocks"] == 8 - 3 + 3


def test_prefix_allocator_off_keeps_legacy_behavior():
    on = BlockAllocator(num_blocks=9, block_size=4, prefix_caching=False)
    toks = np.arange(8, dtype=np.int32)
    t1 = on.allocate_with_prefix("a", toks)
    assert t1.cached_tokens == 0 and t1.cow is None
    t2 = on.allocate_with_prefix("b", toks.copy())  # no index: no sharing
    assert set(t1.table).isdisjoint(t2.table)
    on.free("a")
    assert on.reclaimable_blocks == 0  # nothing parks: straight to free list
    plan = on.plan_prefix(toks)
    assert plan.fresh_blocks == 2 and not plan.matched


def test_prefix_plan_charges_lru_pinned_blocks():
    """A plan whose matched blocks sit in the LRU pool must charge them to
    admission (they count as available but this mapping pins them) — without
    the charge, admission green-lights an allocation that then throws."""
    alloc = BlockAllocator(num_blocks=5, block_size=4, prefix_caching=True)
    toks = np.arange(8, dtype=np.int32)
    alloc.allocate_with_prefix("a", toks)
    alloc.free("a")  # 2 cached blocks in LRU, 2 free
    plan = alloc.plan_prefix(np.concatenate([toks, np.arange(100, 112, dtype=np.int32)]))
    assert len(plan.matched) == 2 and plan.lru_pinned == 2
    # total charge = 3 fresh + 2 pinned = 5 > 4 available: inadmissible
    assert plan.fresh_blocks == 3
    assert plan.fresh_blocks + plan.lru_pinned > alloc.available_blocks
    with pytest.raises(BlockPoolExhausted):
        alloc.allocate_with_prefix("b", np.concatenate(
            [toks, np.arange(100, 112, dtype=np.int32)]
        ))


def test_engine_prefix_cache_bitwise_parity_and_savings(params):
    """Staggered requests sharing a long system prompt: the cached engine
    must produce BITWISE-identical outputs to the cache-off engine while
    skipping a large share of prefill work, with the jit caches frozen at
    the warmup counts (the zero-recompile oracle keeps holding)."""
    from accelerate_tpu.telemetry.step_profiler import RecompileWatcher

    rng = np.random.default_rng(11)
    shared = rng.integers(0, CONFIG.vocab_size, (24,)).astype(np.int32)
    suffixes = [rng.integers(0, CONFIG.vocab_size, (n,)).astype(np.int32)
                for n in (5, 9, 3, 7)]
    prompts = [np.concatenate([shared, s]) for s in suffixes]

    def run(prefix_cache):
        engine = ServingEngine(
            params, CONFIG, num_blocks=65, block_size=8, max_slots=4,
            lattice=BucketLattice(slot_buckets=(2, 4), block_buckets=(8,),
                                  prefill_buckets=(32,)),
            prefix_cache=prefix_cache,
        )
        warmed = engine.warmup()
        watcher = RecompileWatcher()
        watcher.register("prefill", engine.prefill_fn)
        watcher.register("decode", engine.decode_fn)
        reqs = [engine.submit(prompts[0], 8, rng_seed=0),
                engine.submit(prompts[1], 6, rng_seed=1)]
        for i in (2, 3):  # staggered: arrive after the first prefills landed
            engine.step()
            reqs.append(engine.submit(prompts[i], 5 + i, rng_seed=i))
        engine.run()
        assert engine.jit_cache_sizes() == warmed
        assert watcher.poll(emit=False) == {}
        return engine, [r.output_ids() for r in reqs]

    cached_engine, cached_out = run(True)
    plain_engine, plain_out = run(False)
    for i, (a, b) in enumerate(zip(cached_out, plain_out)):
        assert np.array_equal(a, b), f"request {i} diverged under prefix caching"
    stats = cached_engine.stats()
    assert stats["prefix_hit_rate"] > 0.3
    assert stats["prefill_tokens_saved"] >= 3 * 24 - 24  # later reqs skip the shared part
    assert "prefix_hit_rate" not in plain_engine.stats()


def test_engine_prefix_cache_cow_divergence_parity(params):
    """Block-aligned duplicate prompts hit the full-match COW path: each
    request recomputes its final position in a PRIVATE copy and decodes its
    own continuation — outputs bitwise-equal to unshared runs, shared blocks
    never written (proven by request 0 finishing first and request 1 still
    matching its reference afterwards)."""
    rng = np.random.default_rng(12)
    p32 = rng.integers(0, CONFIG.vocab_size, (32,)).astype(np.int32)  # 4 blocks

    def run(prefix_cache):
        engine = ServingEngine(
            params, CONFIG, num_blocks=65, block_size=8, max_slots=4,
            lattice=BucketLattice(slot_buckets=(2, 4), block_buckets=(8,),
                                  prefill_buckets=(32,)),
            prefix_cache=prefix_cache,
        )
        engine.warmup()
        a = engine.submit(p32, 4, rng_seed=0)
        engine.step()  # a prefilled + indexed before b arrives
        b = engine.submit(p32.copy(), 12, rng_seed=0)
        engine.run()
        return engine, a.output_ids(), b.output_ids()

    engine, a_cached, b_cached = run(True)
    _, a_plain, b_plain = run(False)
    assert np.array_equal(a_cached, a_plain)
    assert np.array_equal(b_cached, b_plain)
    assert engine.allocator.cow_copies == 1
    assert engine.stats()["cow_copies"] == 1
    # same seed + same prompt -> identical streams; the divergence point is
    # covered by kernel-level aliased-table tests (different seeds would
    # sample different tokens into the two PRIVATE last blocks)
    assert np.array_equal(a_cached, b_cached[: a_cached.size])


def test_engine_preemption_resume_rides_the_prefix_cache(params):
    """A preempted request's blocks park in the LRU pool; its resume re-plans
    and maps them back instead of re-prefilling — with output identical to
    the uninterrupted single-stream reference (the PR-13 failover waste the
    motivation names)."""
    engine = ServingEngine(
        params, CONFIG, num_blocks=10, block_size=8, max_slots=4,
        max_blocks_per_seq=8,
        lattice=BucketLattice(slot_buckets=(1, 2, 4), block_buckets=(4, 8),
                              prefill_buckets=(32,)),
    )
    engine.warmup()
    prompts = _prompts(2, (16, 14, 15))
    reqs = [engine.submit(p, 16, rng_seed=i) for i, p in enumerate(prompts)]
    engine.run()
    assert engine.scheduler.preemption_count >= 1
    for i, p in enumerate(prompts):
        ref = greedy_generate(params, p[None], CONFIG, max_new_tokens=16)
        assert np.array_equal(np.asarray(ref[0]), reqs[i].output_ids()), f"request {i}"
    # at least one resume found its own KV still cached
    assert engine.allocator.prefix_hit_tokens > 0


# ---------------------------------------------------------------------------
# multi-chip placement surface


def test_serving_shardings_places_kv_heads_on_tp():
    from jax.sharding import Mesh, PartitionSpec as P

    from accelerate_tpu.generation import serving_shardings

    devices = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devices, ("dp", "tp"))
    sharding = serving_shardings(mesh, CONFIG)  # tiny config: 2 kv heads % tp=2 == 0
    # CANONICAL form (trailing Nones trimmed): anything else re-specializes
    # the first warmed prefill bucket on its first steady-state call (the
    # PR 14 "4x2 recompile" — the dispatch cache compares specs, and GSPMD
    # hands back the canonical form on every step output)
    assert sharding.spec == P(None, None, None, "tp")
    # indivisible kv heads stay replicated
    import dataclasses

    odd = dataclasses.replace(CONFIG, n_heads=3, n_kv_heads=3)
    assert serving_shardings(mesh, odd).spec == P()


def test_zero_recompiles_through_churn_on_multidevice_mesh(params):
    """The 4x2-mesh churn regression (ISSUE 15 satellite): with the pool
    placed by ``serving_shardings`` on a multi-device mesh, post-warmup
    churn — including a prompt that CHUNKS past the largest prefill bucket
    and a small-bucket prefill against a steady-state pool (the exact shape
    that re-specialized before the canonicalization fix) — must keep every
    jit cache frozen at the warmed counts, with outputs bitwise-equal to
    the single-device single-stream reference."""
    from jax.sharding import Mesh

    from accelerate_tpu.telemetry.step_profiler import RecompileWatcher

    devices = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devices, ("dp", "tp"))
    engine = ServingEngine(
        params, CONFIG, num_blocks=33, block_size=8, max_slots=4,
        lattice=BucketLattice(slot_buckets=(2, 4), block_buckets=(8,),
                              prefill_buckets=(16, 32)),
        mesh=mesh,
    )
    warmed = engine.warmup()
    watcher = RecompileWatcher()
    watcher.register("mesh_prefill", engine.prefill_fn)
    watcher.register("mesh_decode", engine.decode_fn)
    rng = np.random.default_rng(21)
    reqs = []
    # (9, _) hits the SMALL prefill bucket against a steady-state pool;
    # (45, _) chunks past the largest (32) bucket; staggered arrivals churn
    # slot and width buckets
    for i, (n, new) in enumerate([(9, 4), (45, 6), (30, 4), (5, 8)]):
        prompt = rng.integers(0, CONFIG.vocab_size, (n,)).astype(np.int32)
        reqs.append(engine.submit(prompt, new, rng_seed=i))
        engine.step()
    engine.run()
    assert all(r.done for r in reqs)
    assert engine.jit_cache_sizes() == warmed
    assert watcher.poll(emit=False) == {}
    for i, r in enumerate(reqs):
        ref = greedy_generate(params, r.prompt[None], CONFIG,
                              max_new_tokens=r.max_new_tokens)
        assert np.array_equal(np.asarray(ref[0]), r.output_ids()), f"request {i}"
