"""Run every example end-to-end on the 8-device CPU mesh (reference
``tests/test_examples.py`` runs each ``examples/by_feature/*`` script). Runs
in-process with tiny sizes so the whole suite stays fast; each example's
``training_function``/``main_function`` returns metrics we can assert on."""

import importlib.util
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")
sys.path.insert(0, EXAMPLES)


def load_example(relpath):
    path = os.path.join(EXAMPLES, relpath)
    name = "example_" + relpath.replace("/", "_").removesuffix(".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def tiny_args(mod, relpath, **overrides):
    import argparse

    from example_utils import add_common_args

    parser = add_common_args(argparse.ArgumentParser())
    defaults = {
        "batch_size": 16, "epochs": 1, "train_size": 128, "eval_size": 64,
        "cpu": False,  # conftest already forces the cpu platform
    }
    defaults.update(overrides)
    ns, _ = parser.parse_known_args([])
    for k, v in defaults.items():
        setattr(ns, k, v)
    return ns


class TestCoreExamples:
    def test_nlp_example(self):
        # global batch = batch_size × 8-dev DP = 32 → 8 optimizer steps/epoch;
        # the keyword task reaches 1.0 accuracy by ~epoch 6 with this config
        mod = load_example("nlp_example.py")
        ns = tiny_args(mod, "nlp_example.py", batch_size=4, train_size=256, eval_size=64)
        ns.seq_len, ns.model_size, ns.lr = 32, "tiny", 3e-3
        ns.gradient_accumulation_steps, ns.project_dir = 1, None
        ns.dp, ns.fsdp, ns.tp = 0, 0, 1
        ns.epochs = 8
        out = mod.training_function(ns)
        assert out["eval_accuracy"] > 0.8

    def test_cv_example(self):
        mod = load_example("cv_example.py")
        ns = tiny_args(mod, "cv_example.py", batch_size=4, train_size=256,
                       eval_size=64, epochs=6, lr=3e-3)
        out = mod.training_function(ns)
        assert out["eval_accuracy"] > 0.8  # quadrant task reaches 1.0 by ~epoch 3

    def test_complete_nlp_example_with_resume(self, tmp_path):
        mod = load_example("complete_nlp_example.py")
        ns = tiny_args(mod, "complete_nlp_example.py", epochs=1)
        ns.seq_len, ns.gradient_accumulation_steps = 64, 1
        ns.project_dir = str(tmp_path)
        ns.with_tracking, ns.checkpointing_steps = True, "epoch"
        ns.resume_from_checkpoint, ns.early_stopping_patience = None, 0
        out = mod.training_function(ns)
        assert "eval_accuracy" in out
        ckpt = os.path.join(str(tmp_path), "checkpoints", "checkpoint_0")
        assert os.path.isdir(ckpt)
        # resume from it
        from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

        AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
        ns2 = tiny_args(mod, "complete_nlp_example.py", epochs=2)
        ns2.seq_len, ns2.gradient_accumulation_steps = 64, 1
        ns2.project_dir = str(tmp_path / "resumed")
        ns2.with_tracking, ns2.checkpointing_steps = False, None
        ns2.resume_from_checkpoint, ns2.early_stopping_patience = ckpt, 0
        out2 = mod.training_function(ns2)
        assert "eval_accuracy" in out2

    def test_torch_interop_nlp_example(self):
        # the north-star script: a torch/transformers training loop (reference
        # examples/nlp_example.py shape) bridged onto the jax core
        pytest.importorskip("torch")
        mod = load_example("torch_interop_nlp_example.py")
        ns = tiny_args(mod, "torch_interop_nlp_example.py", batch_size=4,
                       train_size=256, eval_size=64, epochs=5, lr=3e-3)
        ns.seq_len = 32
        out = mod.training_function(ns)
        assert out["eval_accuracy"] > 0.8
        assert out["final_loss"] < 0.2

    def test_nd_parallel(self):
        mod = load_example("nd_parallel.py")
        ns = tiny_args(mod, "nd_parallel.py")
        ns.seq_len, ns.dp_replicate, ns.fsdp, ns.tp, ns.cp = 64, 2, 2, 2, 1
        out = mod.training_function(ns)
        assert out["train_loss"] < 1.0


class TestByFeature:
    def _run(self, relpath, **overrides):
        mod = load_example(relpath)
        ns = tiny_args(mod, relpath, **overrides)
        return mod, ns

    def test_gradient_accumulation(self):
        mod, ns = self._run("by_feature/gradient_accumulation.py")
        ns.gradient_accumulation_steps = 2
        assert "eval_accuracy" in mod.training_function(ns)

    def test_automatic_gradient_accumulation(self):
        mod, ns = self._run("by_feature/automatic_gradient_accumulation.py")
        ns.target_global_batch = 64
        assert "eval_accuracy" in mod.training_function(ns)

    def test_checkpointing(self, tmp_path):
        mod, ns = self._run("by_feature/checkpointing.py", epochs=2)
        ns.output_dir = str(tmp_path)
        assert "eval_accuracy" in mod.training_function(ns)

    def test_early_stopping(self):
        mod, ns = self._run("by_feature/early_stopping.py", epochs=3)
        ns.patience = 1  # trip quickly
        out = mod.training_function(ns)
        assert "eval_accuracy" in out

    def test_local_sgd(self):
        mod, ns = self._run("by_feature/local_sgd.py")
        ns.local_sgd_steps = 4
        assert "eval_accuracy" in mod.training_function(ns)

    def test_sequence_packing(self):
        mod, ns = self._run("by_feature/sequence_packing.py")
        ns.seq_len, ns.num_docs = 48, 32
        out = mod.training_function(ns)
        assert out["train_loss"] < 5.0
        assert 0.3 < out["token_utilization"] <= 1.0

    def test_zero_offload(self):
        import warnings

        mod, ns = self._run("by_feature/zero_offload.py")
        with warnings.catch_warnings():
            # only the documented CPU-backend fallback warning is expected noise
            warnings.filterwarnings("ignore", message=".*host-offload.*")
            assert "eval_accuracy" in mod.training_function(ns)

    def test_memory(self):
        mod, ns = self._run("by_feature/memory.py")
        ns.starting_batch_size = 32
        assert "eval_accuracy" in mod.training_function(ns)

    def test_multi_process_metrics(self):
        mod, ns = self._run("by_feature/multi_process_metrics.py")
        out = mod.training_function(ns)
        assert out["eval_count"] == ns.eval_size

    def test_profiler(self, tmp_path):
        mod, ns = self._run("by_feature/profiler.py")
        ns.trace_dir = str(tmp_path / "trace")
        out = mod.training_function(ns)
        assert out["trace_written"]

    def test_tracking(self, tmp_path):
        mod, ns = self._run("by_feature/tracking.py")
        ns.project_dir = str(tmp_path)
        assert "eval_accuracy" in mod.training_function(ns)

    def test_fsdp_training(self):
        mod, ns = self._run("by_feature/fsdp_training.py")
        ns.fsdp = 0
        assert "eval_accuracy" in mod.training_function(ns)

    def test_megatron_lm_gpt_pretraining(self):
        mod, ns = self._run(
            "by_feature/megatron_lm_gpt_pretraining.py",
            epochs=3, batch_size=2, train_size=64,
        )
        ns.tp, ns.num_micro_batches, ns.seq_len, ns.lr = 2, 2, 64, 3e-3
        out = mod.training_function(ns)
        assert out["tp_sharded"] is True  # the plugin's tp degree reached the mesh
        assert out["train_loss"] < 6.0  # init ~log(512)=6.24, drops fast

    def test_fp8_training(self):
        mod, ns = self._run("by_feature/fp8_training.py")
        ns.steps = 30
        out = mod.training_function(ns)
        assert out["final_loss"] < out["first_loss"]

    def test_quantized_inference(self):
        mod, ns = self._run("by_feature/quantized_inference.py")
        ns.bits = 8
        out = mod.main_function(ns)
        assert out["compression"] > 2.0
        assert out["rel_err"] < 0.1


class TestInferenceExamples:
    def test_distributed_inference(self):
        mod = load_example("inference/distributed_inference.py")
        ns = tiny_args(mod, "inference/distributed_inference.py")
        out = mod.main_function(ns)
        assert out["num_results"] == 37

    def test_pipeline_inference(self):
        mod = load_example("inference/pipeline_inference.py")
        ns = tiny_args(mod, "inference/pipeline_inference.py")
        ns.pp, ns.microbatches = 4, 4
        out = mod.main_function(ns)
        assert out["max_err"] < 1e-4


class TestNewByFeature:
    def _run(self, relpath, **overrides):
        mod = load_example(relpath)
        ns = tiny_args(mod, relpath, **overrides)
        return mod, ns

    def test_schedule_free(self):
        mod, ns = self._run("by_feature/schedule_free.py", epochs=2)
        assert "eval_accuracy" in mod.training_function(ns)

    def test_deepspeed_with_config_support(self):
        mod, ns = self._run(
            "by_feature/deepspeed_with_config_support.py", epochs=3, train_size=512
        )
        ns.ds_config = os.path.join(
            EXAMPLES, "deepspeed_config_templates", "zero_stage1_config.json"
        )
        out = mod.training_function(ns)
        assert out["final_loss"] < out["first_loss"]

    def test_cross_validation(self):
        mod, ns = self._run("by_feature/cross_validation.py", epochs=1)
        ns.folds = 2
        out = mod.training_function(ns)
        assert 0.0 <= out["eval_accuracy"] <= 1.0

    def test_gradient_accumulation_for_autoregressive_models(self):
        mod, ns = self._run(
            "by_feature/gradient_accumulation_for_autoregressive_models.py",
            epochs=3, batch_size=2, train_size=128,
        )
        ns.seq_len, ns.gradient_accumulation_steps, ns.lr = 64, 2, 3e-3
        out = mod.training_function(ns)
        assert out["train_loss"] < 6.0  # init ~log(512)=6.24, drops fast

    def test_sequence_parallelism(self):
        mod = load_example("sequence_parallelism.py")
        ns = tiny_args(mod, "sequence_parallelism.py", epochs=3, batch_size=8, train_size=128)
        ns.seq_len, ns.sp, ns.dp_shard = 128, 4, 2
        out = mod.training_function(ns)
        assert out["train_loss"] < out["first_loss"]

    def test_complete_cv_example_with_resume(self, tmp_path):
        mod = load_example("complete_cv_example.py")
        ns = tiny_args(mod, "complete_cv_example.py", epochs=1, batch_size=4,
                       train_size=128, eval_size=64, lr=3e-3)
        ns.image_size, ns.project_dir = 32, str(tmp_path)
        ns.with_tracking, ns.checkpointing_steps = True, "epoch"
        ns.resume_from_checkpoint = None
        out = mod.training_function(ns)
        assert "eval_accuracy" in out
        ckpt = os.path.join(str(tmp_path), "checkpoints", "checkpoint_0")
        assert os.path.isdir(ckpt)
        from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

        AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
        ns2 = tiny_args(mod, "complete_cv_example.py", epochs=2, batch_size=4,
                        train_size=128, eval_size=64, lr=3e-3)
        ns2.image_size, ns2.project_dir = 32, str(tmp_path / "resumed")
        ns2.with_tracking, ns2.checkpointing_steps = False, None
        ns2.resume_from_checkpoint = ckpt
        out2 = mod.training_function(ns2)
        assert "eval_accuracy" in out2

    def test_gradient_compression(self):
        mod, ns = self._run("by_feature/gradient_compression.py", epochs=6,
                            batch_size=4, train_size=256, eval_size=64, lr=3e-3)
        ns.compress = "bf16"
        out = mod.training_function(ns)
        assert out["eval_accuracy"] > 0.8

    def test_fsdp_with_peak_mem_tracking(self):
        mod, ns = self._run("by_feature/fsdp_with_peak_mem_tracking.py", epochs=1)
        ns.fsdp = 8
        out = mod.training_function(ns)
        assert "planned" in out and out["planned"]["argument_bytes"] >= 0

    def test_seq2seq_example(self):
        mod = load_example("seq2seq_example.py")
        ns = tiny_args(mod, "seq2seq_example.py", epochs=15, batch_size=16,
                       train_size=2048, eval_size=64, lr=3e-3)
        ns.src_len = 12
        out = mod.training_function(ns)
        assert out["exact_match"] > 0.8, out
