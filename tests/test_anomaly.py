"""Online anomaly detectors (telemetry/anomaly.py): EWMA z-score math,
hysteresis (one record per episode), directionality, trend/leak detection,
record routing through the AnomalyEngine, anomaly record + counter emission,
and the disabled path touching zero state."""

import json

import pytest

from accelerate_tpu.telemetry import events as tel_events
from accelerate_tpu.telemetry import metrics
from accelerate_tpu.telemetry.anomaly import (
    ANOMALIES_TOTAL,
    AnomalyEngine,
    EwmaDetector,
    TrendDetector,
)


@pytest.fixture(autouse=True)
def _clean():
    yield
    tel_events.disable()
    metrics.disable()


# ------------------------------------------------------------ EwmaDetector --


def test_ewma_warmup_never_fires():
    """The first min_samples observations only train the estimate — a
    detector must never page off its own cold start, even on a wild series."""
    det = EwmaDetector("d", min_samples=16)
    fired = [det.observe(v) for v in [0.01, 100.0, -50.0, 0.01] * 4]
    assert all(f is None for f in fired)
    assert det.episodes == 0 and det.count == 16


def test_ewma_fires_on_high_outlier_with_context():
    det = EwmaDetector("lat", min_samples=16)
    for _ in range(30):
        assert det.observe(0.01) is None
    rec = det.observe(0.5, source="events-rank3.jsonl")
    assert rec is not None
    assert rec["detector"] == "lat" and rec["episode"] == 1
    assert rec["z"] >= det.z_enter and rec["value"] == 0.5
    assert rec["samples"] == 30 and rec["source"] == "events-rank3.jsonl"


def test_ewma_hysteresis_one_record_per_episode():
    """A sustained excursion is ONE episode: the entry fires, the plateau
    stays silent, recovery re-arms, and a second excursion fires again."""
    det = EwmaDetector("lat", min_samples=16, alpha=0.1)
    for _ in range(30):
        det.observe(0.01)
    fired = [det.observe(0.5) for _ in range(6)]          # excursion
    assert sum(f is not None for f in fired) == 1
    for _ in range(40):                                    # recovery re-arms
        det.observe(0.01)
    assert not det.in_episode
    fired2 = [det.observe(0.5) for _ in range(6)]          # second excursion
    assert sum(f is not None for f in fired2) == 1
    assert det.episodes == 2


def test_ewma_level_shift_becomes_the_new_normal():
    """The outlier feeds the estimate AFTER being scored, so a persistent
    level shift converges and the episode closes on its own."""
    det = EwmaDetector("lat", min_samples=16, alpha=0.2)
    for _ in range(30):
        det.observe(0.01)
    for _ in range(60):
        det.observe(0.5)
    assert det.episodes == 1 and not det.in_episode
    assert det.mean == pytest.approx(0.5, rel=0.05)


def test_ewma_direction_low_and_both():
    low = EwmaDetector("rate", min_samples=16, direction="low")
    for _ in range(40):
        low.observe(0.9)
    assert low.observe(0.0) is not None      # collapse fires
    spike = EwmaDetector("rate2", min_samples=16, direction="low")
    for _ in range(40):
        spike.observe(0.9)
    assert spike.observe(5.0) is None        # high excursion is fine for "low"
    both = EwmaDetector("skew", min_samples=16, direction="both")
    for _ in range(30):
        both.observe(0.0)
        both.observe(0.02)
    assert both.observe(-5.0) is not None    # either side fires
    with pytest.raises(ValueError):
        EwmaDetector("bad", direction="sideways")
    with pytest.raises(ValueError):
        EwmaDetector("bad", z_enter=2.0, z_exit=3.0)


def test_ewma_min_std_floors_flat_series():
    """A perfectly flat warmup must not turn the first jitter into an
    infinite z-score — min_std floors the variance, and the cause falls
    back to the detector's configured hypothesis."""
    det = EwmaDetector("flat", min_samples=4, min_std=0.05, cause="stock cause")
    for _ in range(60):
        det.observe(1.0)  # long enough for the EWMA variance to decay flat
    assert det.observe(1.01) is None         # 0.01 / 0.05 = z 0.2, in band
    rec = det.observe(2.0)                   # 1.0 / 0.05 = z 20, fires
    assert rec is not None and rec["cause"] == "stock cause"
    assert rec["std"] >= 0.05


def test_ewma_hypothesis_overrides_stock_cause():
    det = EwmaDetector("lat", min_samples=4, cause="stock cause")
    for _ in range(10):
        det.observe(0.01)
    rec = det.observe(9.0, hypothesis="recompilation")
    assert rec is not None and rec["cause"] == "recompilation"


# ----------------------------------------------------------- TrendDetector --


def test_trend_fires_on_sustained_drift_not_on_noise():
    """Block-pool leak signature: occupancy creeping up forever fires; a
    stationary noisy series never does."""
    leak = TrendDetector("leak", min_samples=30, slope_enter=0.002)
    fired = [leak.observe(0.3 + 0.005 * i) for i in range(60)]
    assert sum(f is not None for f in fired) == 1
    assert leak.episodes == 1 and leak.in_episode
    flat = TrendDetector("flat", min_samples=30, slope_enter=0.002)
    fired = [flat.observe(0.3 + 0.01 * (i % 2)) for i in range(120)]
    assert all(f is None for f in fired)


def test_trend_hysteresis_rearms_after_plateau():
    det = TrendDetector("leak", min_samples=10, slope_enter=0.01)
    for i in range(40):
        det.observe(0.1 + 0.02 * i)          # drift: one episode
    assert det.episodes == 1
    for _ in range(60):
        det.observe(0.9)                     # plateau: slope decays, re-arms
    assert not det.in_episode
    for i in range(40):
        det.observe(0.9 + 0.02 * i)          # second drift: second episode
    assert det.episodes == 2


# ----------------------------------------------------------- AnomalyEngine --


def _steps(n, dur, start=0):
    return [{"kind": "step", "step": start + i, "t": float(start + i),
             "dur_s": dur, "execute_s": dur} for i in range(n)]


def test_engine_routes_step_latency_with_hypothesis():
    eng = AnomalyEngine(emit_records=False)
    for rec in _steps(30, 0.01):
        assert eng.observe_record(rec) == []
    slow = {"kind": "step", "step": 30, "t": 30.0, "dur_s": 0.4,
            "execute_s": 0.1, "compile_s": 0.3, "_file": "events-rank0.jsonl"}
    fired = eng.observe_record(slow)
    assert len(fired) == 1
    assert fired[0]["detector"] == "step_latency"
    assert "recompilation" in fired[0]["cause"]
    assert fired[0]["source"] == "events-rank0.jsonl"


def test_engine_step_hypothesis_data_wait_and_fallback():
    eng = AnomalyEngine()
    stall = {"kind": "step", "dur_s": 0.4, "data_wait_s": 0.3}
    assert "input pipeline" in eng._step_hypothesis(stall)
    opaque = {"kind": "step", "dur_s": 0.4, "data_wait_s": 0.01}
    assert eng._step_hypothesis(opaque) is None  # falls back to stock cause


def test_engine_routes_ttft_spec_accept_heartbeat_and_leak():
    eng = AnomalyEngine(emit_records=False)
    # ttft: only finished router requests with a ttft feed the detector
    for _ in range(30):
        eng.observe_record({"kind": "router", "phase": "request",
                            "outcome": "finished", "ttft_s": 0.05,
                            "replica": "r0"})
    eng.observe_record({"kind": "router", "phase": "request",
                        "outcome": "failed", "ttft_s": 90.0})  # not routed
    fired = eng.observe_record({"kind": "router", "phase": "request",
                                "outcome": "finished", "ttft_s": 2.0,
                                "replica": "r1"})
    assert [f["detector"] for f in fired] == ["ttft"]
    assert fired[0]["source"] == "r1"
    # spec accept rate collapse (direction="low")
    for _ in range(30):
        eng.observe_record({"kind": "serving", "phase": "step",
                            "draft_proposed_tokens": 10,
                            "draft_accepted_tokens": 8})
    fired = eng.observe_record({"kind": "serving", "phase": "step",
                                "draft_proposed_tokens": 10,
                                "draft_accepted_tokens": 0})
    assert [f["detector"] for f in fired] == ["spec_accept_rate"]
    # heartbeat gap widening
    for _ in range(30):
        eng.observe_record({"kind": "serving_replica", "replica": "r0",
                            "heartbeat_age_s": 0.1})
    fired = eng.observe_record({"kind": "serving_replica", "replica": "r0",
                                "heartbeat_age_s": 6.0})
    assert [f["detector"] for f in fired] == ["heartbeat_gap"]
    # block-pool occupancy drifting up = leak
    fired_all = []
    for i in range(60):
        fired_all += eng.observe_record({"kind": "serving", "phase": "step",
                                         "block_occupancy": 0.2 + 0.005 * i})
    assert [f["detector"] for f in fired_all] == ["block_pool_leak"]
    assert eng.stats()["episodes"]["block_pool_leak"] == 1


def test_engine_emits_record_and_counter_per_episode(tmp_path):
    tel_events.enable(out_dir=str(tmp_path), run_id="anom")
    metrics.enable()
    eng = AnomalyEngine()
    for rec in _steps(30, 0.01):
        eng.observe_record(rec)
    for rec in _steps(6, 0.5, start=30):     # one sustained excursion
        eng.observe_record(rec)
    tel_events.disable()
    recs = [json.loads(l) for l in open(tmp_path / "events-rank0.jsonl")]
    anoms = [r for r in recs if r["kind"] == "anomaly"]
    assert len(anoms) == 1                   # hysteresis: one record
    assert anoms[0]["detector"] == "step_latency"
    reg = metrics.get_registry()
    fams = metrics.parse_prometheus_text(reg.render())
    samples = fams[ANOMALIES_TOTAL]["samples"]
    assert [(lab, val) for _, lab, val in samples] == [
        ({"detector": "step_latency"}, 1)
    ]


def test_engine_disabled_path_touches_no_state():
    eng = AnomalyEngine(enabled=False)
    for rec in _steps(50, 0.01) + _steps(10, 9.0, start=50):
        assert eng.observe_record(rec) == []
    assert eng.observed == 0 and eng.anomalies == []
    assert all(d.count == 0 and d.episodes == 0 for d in eng.detectors())


def test_engine_emit_records_off_still_detects():
    """The hub's in-process engines run with emit_records=False: episodes
    must still fire and accumulate without needing an armed event log."""
    eng = AnomalyEngine(emit_records=False)
    for rec in _steps(30, 0.01) + _steps(3, 0.5, start=30):
        eng.observe_record(rec)
    assert eng.stats()["anomalies"] == 1
    assert eng.step_latency.episodes == 1
