"""Mesh-sharded KV-cache decoding — the multi-chip leg of BASELINE config #5.

The reference shards big-model generate across devices via ``device_map``
dispatch (``/root/reference/src/accelerate/big_modeling.py:309`` +
``benchmarks/big_model_inference/README.md:27-37``); the TPU-native form is
GSPMD decode over a ``Mesh``: params TP-sharded by ``llama_shard_rules``, KV
cache head-sharded over ``tp`` and batch-sharded over ``dp``
(``generation.generation_shardings``). These tests pin (a) the placement
policy and (b) token parity between single-device and mesh-sharded decode.
"""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from accelerate_tpu.generation import (
    beam_generate,
    generation_shardings,
    greedy_generate,
    sample_generate,
)
from accelerate_tpu.models.transformer import LlamaConfig, init_llama, llama_shard_rules
from accelerate_tpu.parallel.sharding import shard_params


def _tiny_config():
    return LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, max_seq_len=128
    )


def _tiny_moe_config(**overrides):
    """Overriding a knob to None drops it so the dataclass default applies."""
    kwargs = dict(
        vocab_size=128, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
        max_seq_len=64, moe_experts=4, moe_top_k=2, moe_capacity_factor=8.0,
    )
    kwargs.update(overrides)
    return LlamaConfig(**{k: v for k, v in kwargs.items() if v is not None})


def _f32_params(config, seed):
    params = init_llama(config, jax.random.PRNGKey(seed))
    return jax.tree_util.tree_map(lambda x: x.astype(np.float32), params)


def _mesh_2x2():
    return Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))


class TestGenerationShardings:
    def test_batch_over_dp_heads_over_tp(self):
        mesh = _mesh_2x2()
        prompt_sh, cache_sh = generation_shardings(mesh, batch_size=4, config=_tiny_config())
        assert prompt_sh.spec == P("dp", None)
        assert cache_sh.spec == P(None, "dp", None, "tp", None)

    def test_indivisible_batch_stays_replicated(self):
        mesh = _mesh_2x2()
        prompt_sh, cache_sh = generation_shardings(mesh, batch_size=3, config=_tiny_config())
        assert prompt_sh.spec == P(None, None)
        assert cache_sh.spec == P(None, None, None, "tp", None)

    def test_indivisible_kv_heads_stay_replicated(self):
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(1, 4), ("dp", "tp"))
        # tp=4 does not divide n_kv_heads=2 -> head axis replicated
        _, cache_sh = generation_shardings(mesh, batch_size=4, config=_tiny_config())
        assert cache_sh.spec == P(None, None, None, None, None)

    def test_partial_data_axes_claimed_greedily(self):
        mesh = Mesh(
            np.array(jax.devices()).reshape(2, 2, 2), ("dp_replicate", "dp_shard", "tp")
        )
        # joint product 4 does not divide batch 2, but dp_replicate alone does
        prompt_sh, cache_sh = generation_shardings(mesh, batch_size=2, config=_tiny_config())
        assert prompt_sh.spec == P("dp_replicate", None)
        assert cache_sh.spec == P(None, "dp_replicate", None, "tp", None)

    def test_joint_data_axes(self):
        mesh = Mesh(
            np.array(jax.devices()).reshape(2, 2, 2), ("dp_replicate", "dp_shard", "tp")
        )
        prompt_sh, cache_sh = generation_shardings(mesh, batch_size=4, config=_tiny_config())
        assert prompt_sh.spec == P(("dp_replicate", "dp_shard"), None)
        assert cache_sh.spec == P(None, ("dp_replicate", "dp_shard"), None, "tp", None)


@pytest.mark.slow
class TestMoEDecode:
    """KV-cache decode for MoE configs must match full-forward recompute
    decoding token-for-token. ``moe_capacity_factor`` is set high enough that
    no token is capacity-dropped — with drops, prefill (S tokens per routing
    group) and decode (1 token per group) could legitimately diverge."""

    def test_moe_greedy_matches_full_forward_decode(self):
        import jax.numpy as jnp

        from accelerate_tpu.models.transformer import llama_forward

        config = _tiny_moe_config()
        params = _f32_params(config, 0)
        prompt = np.asarray(
            jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, config.vocab_size), np.int32
        )

        got = greedy_generate(params, prompt, config, max_new_tokens=5, cache_dtype=np.float32)

        # reference: recompute the full forward for every step (no cache)
        ids = prompt
        for _ in range(5):
            logits = llama_forward(params, ids, config, attention_impl="xla")
            nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1), np.int32)
            ids = np.concatenate([ids, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(ids, got)

    def test_moe_dispatched_decode_matches_resident(self):
        """The per-layer paged path (cpu_offload + generate_dispatched) routes
        MoE layers identically to resident decode."""
        from accelerate_tpu.big_modeling import cpu_offload
        from accelerate_tpu.generation import generate_dispatched, unstack_layer_params

        config = _tiny_moe_config()
        params = _f32_params(config, 0)
        prompt = np.asarray(
            jax.random.randint(jax.random.PRNGKey(5), (2, 4), 0, config.vocab_size), np.int32
        )
        ref = greedy_generate(params, prompt, config, max_new_tokens=4, cache_dtype=np.float32)
        disp = cpu_offload(unstack_layer_params(params, config))
        out = generate_dispatched(disp, prompt, config, max_new_tokens=4, cache_dtype=np.float32)
        np.testing.assert_array_equal(ref, out)

    def test_moe_decode_over_ep_mesh_matches_unsharded(self):
        """Expert-parallel decode: experts sharded over ``ep`` (llama_shard_rules
        moe entries), tokens replicated — same tokens as unsharded decode."""
        from jax.sharding import Mesh

        config = _tiny_moe_config()
        params = _f32_params(config, 0)
        prompt = np.asarray(
            jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, config.vocab_size), np.int32
        )
        ref = greedy_generate(params, prompt, config, max_new_tokens=5, cache_dtype=np.float32)

        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("ep", "tp"))
        sharded, specs = shard_params(params, mesh, rules=llama_shard_rules())
        assert specs["layers"]["moe"]["wi"]["kernel"] == P(None, "ep", None, "tp")
        got = greedy_generate(
            sharded, prompt, config, max_new_tokens=5, cache_dtype=np.float32, mesh=mesh
        )
        np.testing.assert_array_equal(ref, got)

    def test_decode_is_drop_free_at_default_capacity(self):
        """Single-token (S == 1) steps floor the capacity factor at E/top_k, so
        per-step routing never capacity-drops even with the training default
        cf — pinned by comparing against an explicitly no-drop config on a
        prompt of IDENTICAL tokens (maximal expert collision, the adversarial
        case for per-step capacity). The prompt is one token so prefill is
        itself a single-token step (longer prefills deliberately keep the
        training capacity — their routing group matches the full forward's)."""
        import dataclasses

        base = _tiny_moe_config(moe_experts=8, moe_capacity_factor=None)  # dataclass-default cf
        params = _f32_params(base, 2)
        prompt = np.full((4, 1), 7, np.int32)  # same token everywhere

        got_default = greedy_generate(params, prompt, base, max_new_tokens=4,
                                      cache_dtype=np.float32)
        high = dataclasses.replace(base, moe_capacity_factor=16.0)
        got_nodrop = greedy_generate(params, prompt, high, max_new_tokens=4,
                                     cache_dtype=np.float32)
        np.testing.assert_array_equal(got_default, got_nodrop)


class TestShardedDecodeParity:
    """Sharded decode must produce the same tokens as single-device decode
    (fp32 on the CPU mesh; GSPMD re-associates reductions, so logits match to
    tolerance and argmax/beam paths to exact tokens on these sizes)."""

    @pytest.fixture(scope="class")
    def setup(self):
        config = _tiny_config()
        params = init_llama(config, jax.random.PRNGKey(0))
        params = jax.tree_util.tree_map(lambda x: x.astype(np.float32), params)
        prompt = np.array(
            jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, config.vocab_size)
        ).astype(np.int32)
        mesh = _mesh_2x2()
        sharded, specs = shard_params(params, mesh, rules=llama_shard_rules())
        return config, params, prompt, mesh, sharded, specs

    def test_tp_specs_applied(self, setup):
        _, _, _, _, sharded, specs = setup
        assert specs["layers"]["wq"]["kernel"] == P(None, None, "tp")
        # canonical (trailing-None-trimmed) form — see sharding.canonicalize_spec
        assert specs["layers"]["wo"]["kernel"] == P(None, "tp")
        shard_shape = sharded["layers"]["wq"]["kernel"].sharding.shard_shape(
            sharded["layers"]["wq"]["kernel"].shape
        )
        assert shard_shape[2] == sharded["layers"]["wq"]["kernel"].shape[2] // 2

    def test_greedy_parity(self, setup):
        config, params, prompt, mesh, sharded, _ = setup
        ref = greedy_generate(params, prompt, config, max_new_tokens=6, cache_dtype=np.float32)
        got = greedy_generate(
            sharded, prompt, config, max_new_tokens=6, cache_dtype=np.float32, mesh=mesh
        )
        np.testing.assert_array_equal(ref, got)

    @pytest.mark.slow
    def test_sampled_parity_same_key(self, setup):
        """Exact sampled-token parity is only a *guaranteed* property when
        this backend's sharded forward is BITWISE-equal to the single-device
        one: `jax.random.categorical` is argmax(logits + gumbel), so any
        nonzero logits delta (GSPMD re-associating reductions — jaxlib- and
        core-count-dependent) can legitimately flip a near-tied sample while
        every probability stays correct to tolerance. Probe that capability
        first and skip with the measured delta when it is absent (greedy and
        beam parity above still assert exact tokens unconditionally)."""
        from accelerate_tpu.models.transformer import llama_forward

        config, params, prompt, mesh, sharded, _ = setup
        fwd = jax.jit(lambda p, ids: llama_forward(p, ids, config))
        ref_logits = np.asarray(fwd(params, prompt))
        got_logits = np.asarray(fwd(sharded, prompt))
        if not np.array_equal(ref_logits, got_logits):
            delta = float(np.max(np.abs(ref_logits - got_logits)))
            pytest.skip(
                "sharded forward is not bitwise-identical to single-device on "
                f"this jaxlib/backend (max |logits delta| = {delta:.3e}); a "
                "sampled near-tie inside the categorical gumbel can "
                "legitimately flip, so exact token equality is not a property "
                "of this environment — greedy/beam parity still pin exact "
                "tokens above"
            )
        kwargs = dict(
            max_new_tokens=6, temperature=0.7, top_k=8, cache_dtype=np.float32,
            rng_key=jax.random.PRNGKey(7),
        )
        ref = sample_generate(params, prompt, config, **kwargs)
        got = sample_generate(sharded, prompt, config, mesh=mesh, **kwargs)
        np.testing.assert_array_equal(ref, got)

    @pytest.mark.slow
    def test_beam_parity(self, setup):
        config, params, prompt, mesh, sharded, _ = setup
        ref, ref_s = beam_generate(
            params, prompt, config, num_beams=2, max_new_tokens=5,
            cache_dtype=np.float32, return_scores=True,
        )
        got, got_s = beam_generate(
            sharded, prompt, config, num_beams=2, max_new_tokens=5,
            cache_dtype=np.float32, return_scores=True, mesh=mesh,
        )
        np.testing.assert_array_equal(ref, got)
        np.testing.assert_allclose(ref_s, got_s, rtol=1e-4)

    def test_eos_freeze_under_mesh(self, setup):
        config, _, prompt, mesh, sharded, _ = setup
        out = greedy_generate(
            sharded, prompt, config, max_new_tokens=6, eos_token_id=5,
            cache_dtype=np.float32, mesh=mesh,
        )
        gen = out[:, prompt.shape[1]:]
        for row in gen:
            hits = np.where(row == 5)[0]
            if hits.size:
                assert (row[hits[0]:] == 5).all()
