"""Native C++ data-pipeline tests: correctness vs numpy, determinism, epoch
reshuffling, prefetch ordering under many workers."""

import numpy as np
import pytest

from accelerate_tpu.native import (
    NativeDataLoader,
    TokenDataset,
    gather_rows,
    is_native_available,
    parallel_collate,
)


@pytest.mark.smoke
def test_native_builds():
    # the build toolchain exists in CI/dev images; if this fails the fallback
    # path still works but we want to know
    assert is_native_available()


def test_parallel_collate_matches_stack():
    rng = np.random.default_rng(0)
    samples = [rng.normal(size=(128, 64)).astype(np.float32) for _ in range(32)]
    out = parallel_collate(samples)
    np.testing.assert_array_equal(out, np.stack(samples))
    assert out.dtype == np.float32


def test_parallel_collate_large_uses_threads():
    samples = [np.full((512, 512), i, np.float32) for i in range(16)]  # 16 MB
    out = parallel_collate(samples, num_threads=4)
    np.testing.assert_array_equal(out, np.stack(samples))


def test_parallel_collate_ragged_falls_back():
    samples = [np.zeros((3,)), np.zeros((3,))]
    out = parallel_collate(samples)
    assert out.shape == (2, 3)


def test_gather_rows():
    src = np.arange(1000, dtype=np.int64).reshape(100, 10)
    idx = np.asarray([5, 1, 99, 0, 5])
    np.testing.assert_array_equal(gather_rows(src, idx), src[idx])


@pytest.fixture
def token_file(tmp_path):
    rng = np.random.default_rng(42)
    tokens = rng.integers(0, 50000, size=(257 * 128,), dtype=np.uint16)
    path = tmp_path / "shard.bin"
    tokens.tofile(path)
    return str(path), tokens.reshape(257, 128)  # 257 records of seq 128


def test_token_dataset(token_file):
    path, ref = token_file
    ds = TokenDataset(path, seq_len=128)
    assert len(ds) == 257
    np.testing.assert_array_equal(ds[0], ref[0])
    np.testing.assert_array_equal(ds[256], ref[256])
    ds.close()


def test_loader_sequential(token_file):
    path, ref = token_file
    ds = TokenDataset(path, seq_len=128)
    dl = NativeDataLoader(ds, batch_size=32, shuffle=False, drop_last=True,
                          num_workers=4)
    assert len(dl) == 8
    batches = list(dl)
    assert len(batches) == 8
    got = np.concatenate(batches)
    np.testing.assert_array_equal(got, ref[:256])
    dl.close()
    ds.close()


def test_loader_shuffle_is_permutation_and_deterministic(tmp_path):
    # 256 records exactly: drop_last drops nothing, so epochs are permutations
    # of each other (257 would drop a different record each epoch)
    rng = np.random.default_rng(42)
    tokens = rng.integers(0, 50000, size=(256 * 128,), dtype=np.uint16)
    path = str(tmp_path / "even.bin")
    tokens.tofile(path)
    ref = tokens.reshape(256, 128)
    ds = TokenDataset(path, seq_len=128)
    dl1 = NativeDataLoader(ds, batch_size=16, shuffle=True, seed=7, drop_last=True,
                           num_workers=4)
    ep1 = np.concatenate(list(dl1))
    # same seed → identical epoch-0 order
    dl2 = NativeDataLoader(ds, batch_size=16, shuffle=True, seed=7, drop_last=True,
                           num_workers=2)
    np.testing.assert_array_equal(ep1, np.concatenate(list(dl2)))
    # all rows come from the dataset, no duplicates within the epoch
    seen = {r.tobytes() for r in ep1}
    all_rows = {r.tobytes() for r in ref}
    assert seen <= all_rows
    assert len(seen) == ep1.shape[0]  # rows are unique with high probability
    # epoch 1 reshuffles
    ep1b = np.concatenate(list(dl1))
    assert not np.array_equal(ep1, ep1b)
    np.testing.assert_array_equal(np.sort(ep1.reshape(-1)), np.sort(ep1b.reshape(-1)))
    dl1.close()
    dl2.close()
    ds.close()


def test_loader_wraparound_no_drop_last(token_file):
    path, ref = token_file
    ds = TokenDataset(path, seq_len=128)
    dl = NativeDataLoader(ds, batch_size=100, shuffle=False, drop_last=False,
                          num_workers=3)
    batches = list(dl)
    assert len(batches) == 3
    assert all(b.shape == (100, 128) for b in batches)
    # final batch wraps to the start (even_batches semantics)
    np.testing.assert_array_equal(batches[2][57:], ref[: 100 - 57])
    dl.close()
    ds.close()


def test_loader_many_workers_small_window(token_file):
    """Reorder-window stress: more workers than prefetch depth must not deadlock."""
    path, ref = token_file
    ds = TokenDataset(path, seq_len=128)
    dl = NativeDataLoader(ds, batch_size=8, shuffle=False, drop_last=True,
                          num_workers=8, prefetch_depth=2)
    got = np.concatenate(list(dl))
    np.testing.assert_array_equal(got, ref[: got.shape[0]])
    dl.close()
    ds.close()


def test_default_collate_uses_native_path():
    from accelerate_tpu.data_loader import default_collate

    samples = [{"x": np.full((600, 600), i, np.float32)} for i in range(4)]  # >1MB
    out = default_collate(samples)
    np.testing.assert_array_equal(out["x"][2], samples[2]["x"])


def test_parallel_collate_mixed_dtypes_promotes():
    out = parallel_collate([np.zeros(4, np.int64), np.full(4, 2.9)])
    np.testing.assert_allclose(out[1], 2.9)  # np.stack promotion, no truncation
    out2 = parallel_collate([np.zeros(4, np.float32), np.zeros(4, np.float64)])
    assert out2.dtype == np.float64


def test_gather_rows_bounds_and_negatives():
    src = np.arange(20.0).reshape(4, 5)
    np.testing.assert_array_equal(gather_rows(src, np.asarray([-1])), src[[-1]])
    with pytest.raises(IndexError):
        gather_rows(src, np.asarray([4]))
    assert gather_rows(src, np.asarray([], dtype=np.int64)).shape == (0, 5)


def test_loader_partial_iteration_restarts_epoch(token_file):
    path, ref = token_file
    ds = TokenDataset(path, seq_len=128)
    dl = NativeDataLoader(ds, batch_size=32, shuffle=False, drop_last=True,
                          num_workers=4)
    first = next(iter(dl))  # peek and abandon mid-epoch
    np.testing.assert_array_equal(first, ref[:32])
    batches = list(dl)  # must be a FULL epoch, not the leftover 7 batches
    assert len(batches) == 8
    np.testing.assert_array_equal(np.concatenate(batches), ref[:256])
    dl.close()
    ds.close()


class TestChunkIO:
    """Native checkpoint IO engine (src/io.cc via native/io.py)."""

    def _arrays(self):
        rng = np.random.default_rng(7)
        return [
            rng.standard_normal((64, 33)).astype(np.float32),
            np.arange(17, dtype=np.int64),
            rng.integers(0, 255, (5, 5, 5), dtype=np.uint8),
        ]

    def test_roundtrip_and_alignment(self, tmp_path):
        from accelerate_tpu.native import io as nio

        arrays = self._arrays()
        p = str(tmp_path / "c.bin")
        offs, sizes, crcs = nio.write_chunks(p, arrays)
        assert all(o % nio.ALIGN == 0 for o in offs)
        bufs = nio.read_chunks(p, offs, sizes, crcs)
        for a, b in zip(arrays, bufs):
            np.testing.assert_array_equal(np.frombuffer(b, a.dtype).reshape(a.shape), a)

    def test_crc_detects_corruption(self, tmp_path):
        from accelerate_tpu.native import io as nio

        arrays = self._arrays()
        p = str(tmp_path / "c.bin")
        offs, sizes, crcs = nio.write_chunks(p, arrays)
        with open(p, "r+b") as f:
            f.seek(offs[1] + 3)
            f.write(b"\xab")
        with pytest.raises(ValueError, match="CRC mismatch"):
            nio.read_chunks(p, offs, sizes, crcs)
        # without crcs the (corrupt) read still succeeds — caller's choice
        nio.read_chunks(p, offs, sizes, None)

    def test_python_fallback_writes_identical_format(self, tmp_path, monkeypatch):
        from accelerate_tpu.native import io as nio

        arrays = self._arrays()
        p_native = str(tmp_path / "n.bin")
        res_native = nio.write_chunks(p_native, arrays)
        monkeypatch.setattr(nio, "_lib", lambda: None)
        p_py = str(tmp_path / "p.bin")
        res_py = nio.write_chunks(p_py, arrays)
        assert res_native == res_py
        with open(p_native, "rb") as a, open(p_py, "rb") as b:
            assert a.read() == b.read()
        # cross-read: python-written file through python reader with native crcs
        bufs = nio.read_chunks(p_py, *res_py)
        for a, b in zip(arrays, bufs):
            np.testing.assert_array_equal(np.frombuffer(b, a.dtype).reshape(a.shape), a)
