"""Unit tests for the small utility surfaces the reference covers in
``tests/test_utils.py`` / ``test_imports.py`` / ``test_logging.py``:
environment parsing, env patching, capability probes, the rank-aware logging
adapter, the main-process-only tqdm/rich helpers, the public-API export
contracts, ``write_basic_config``, and the notebook/debug launchers."""

import logging
import os

import pytest

from accelerate_tpu.logging import MultiProcessAdapter, get_logger
from accelerate_tpu.utils import environment as env
from accelerate_tpu.utils import imports


class TestEnvironment:
    def test_str_to_bool(self):
        for s in ("1", "true", "True", "YES", "on"):
            assert env.str_to_bool(s) == 1
        for s in ("0", "false", "OFF", "no"):
            assert env.str_to_bool(s) == 0
        with pytest.raises(ValueError):
            env.str_to_bool("maybe")

    def test_parse_flag_from_env(self):
        with env.patch_environment(MY_FLAG="true"):
            assert env.parse_flag_from_env("MY_FLAG") is True
        with env.patch_environment(MY_FLAG="0"):
            assert env.parse_flag_from_env("MY_FLAG", default=True) is False
        assert env.parse_flag_from_env("MY_FLAG_UNSET", default=True) is True

    def test_parse_choice_and_int(self):
        with env.patch_environment(MP="bf16", N1="4"):
            assert env.parse_choice_from_env("MP") == "bf16"
            assert env.get_int_from_env(("N0", "N1"), 7) == 4
        assert env.get_int_from_env(("N0", "N1"), 7) == 7

    def test_patch_environment_restores_and_deletes(self):
        os.environ["KEEP_ME"] = "original"
        with env.patch_environment(KEEP_ME="patched", ADDED="x"):
            assert os.environ["KEEP_ME"] == "patched"
            assert os.environ["ADDED"] == "x"
        assert os.environ["KEEP_ME"] == "original"
        assert "ADDED" not in os.environ
        del os.environ["KEEP_ME"]

    def test_patch_environment_restores_on_exception(self):
        with pytest.raises(RuntimeError):
            with env.patch_environment(BOOM_VAR="1"):
                raise RuntimeError
        assert "BOOM_VAR" not in os.environ

    def test_are_libraries_initialized(self):
        assert "numpy" in env.are_libraries_initialized("numpy", "not_a_real_lib_xyz")


class TestImports:
    def test_probes_return_bool(self):
        for name in dir(imports):
            if name.startswith("is_") and name.endswith("_available"):
                assert isinstance(getattr(imports, name)(), bool), name

    def test_known_available(self):
        # baked into the environment (see repo instructions)
        assert imports.is_optax_available()
        assert imports.is_torch_available()
        assert imports.is_safetensors_available()

    def test_no_duplicate_probe_definitions(self):
        """A probe defined twice silently shadows the first: keep the module
        free of copy-paste duplicates."""
        import ast
        import inspect

        tree = ast.parse(inspect.getsource(imports))
        names = [n.name for n in tree.body if isinstance(n, ast.FunctionDef)]
        assert len(names) == len(set(names)), sorted(
            n for n in names if names.count(n) > 1
        )


class TestLogging:
    def test_main_process_logs(self, caplog):
        logger = get_logger("t_log_main")
        with caplog.at_level(logging.INFO, logger="t_log_main"):
            logger.info("hello %s", "world")
        assert "hello world" in caplog.text

    def test_level_from_env(self):
        with env.patch_environment(ACCELERATE_LOG_LEVEL="ERROR"):
            logger = get_logger("t_log_env")
            assert logger.logger.level == logging.ERROR

    def test_warning_once_dedupes(self, caplog):
        logger = get_logger("t_log_once")
        with caplog.at_level(logging.WARNING, logger="t_log_once"):
            logger.warning_once("repeat me")
            logger.warning_once("repeat me")
            logger.warning_once("another")
        assert caplog.text.count("repeat me") == 1
        assert caplog.text.count("another") == 1

    def test_in_order_single_process(self, caplog):
        logger = get_logger("t_log_order")
        with caplog.at_level(logging.INFO, logger="t_log_order"):
            logger.info("ordered", in_order=True, main_process_only=False)
        assert "ordered" in caplog.text

    def test_adapter_type(self):
        assert isinstance(get_logger("t_log_type"), MultiProcessAdapter)


class TestPublicAPI:
    def test_reference_top_level_names_resolve(self):
        """The reference's own top-level exports (its ``__init__.py``) must all
        exist here — incl. ``prepare_pippy``, aliased to the native
        ``prepare_pipeline`` (trainable, unlike PiPPy); the exhaustive sweep
        lives in test_api_parity.py."""
        import accelerate_tpu as at

        for name in ("Accelerator", "PartialState", "ParallelismConfig",
                     "notebook_launcher", "debug_launcher", "skip_first_batches",
                     "prepare_pippy"):
            assert getattr(at, name) is not None, name

    def test_all_exports_resolve(self):
        import accelerate_tpu as at

        for name in at.__all__:
            assert getattr(at, name) is not None, name

    def test_utils_namespace_parity(self):
        """Reference users spell `from accelerate.utils import gather,
        set_seed, send_to_device, ...` — the same names must resolve from
        accelerate_tpu.utils (lazily, to dodge the state import cycle)."""
        from accelerate_tpu import utils

        for name in sorted(utils._OPERATIONS | utils._RANDOM) + [
            "DistributedType", "ProjectConfiguration", "patch_environment", "str_to_bool",
        ]:
            assert getattr(utils, name) is not None, name
        # every __all__ entry must resolve (star-import contract) and be
        # visible to dir() (tab completion)
        for name in utils.__all__:
            assert getattr(utils, name) is not None, name
        assert set(utils.__all__) <= set(dir(utils))
        with pytest.raises(AttributeError):
            utils.not_a_real_name


class TestLaunchers:
    def test_debug_launcher_runs_on_virtual_mesh(self):
        import jax

        from accelerate_tpu import debug_launcher

        def fn(mult):
            assert os.environ.get("ACCELERATE_USE_CPU") == "yes"
            return len(jax.devices()) * mult

        # conftest already forced the 8-device CPU mesh; the launcher must run
        # the function under the accelerate env and hand back its return
        assert debug_launcher(fn, args=(2,)) == 16
        assert "ACCELERATE_USE_CPU" not in os.environ  # env restored

    def test_notebook_launcher_single_host(self):
        from accelerate_tpu import notebook_launcher

        def fn(x):
            assert os.environ.get("ACCELERATE_MIXED_PRECISION") == "bf16"
            return x + 1

        assert notebook_launcher(fn, args=(41,), mixed_precision="bf16") == 42

    def test_notebook_launcher_multinode_needs_master_addr(self):
        from accelerate_tpu import notebook_launcher

        with pytest.raises(ValueError):
            notebook_launcher(lambda: None, num_nodes=2)

    def test_notebook_launcher_multinode_sets_coordinator_env(self):
        from accelerate_tpu import notebook_launcher

        def fn():
            return (
                os.environ["ACCELERATE_COORDINATOR_ADDRESS"],
                os.environ["ACCELERATE_NUM_PROCESSES"],
                os.environ["ACCELERATE_PROCESS_ID"],
            )

        addr, n, rank = notebook_launcher(
            fn, master_addr="10.0.0.1", use_port="9999", num_nodes=2, node_rank=1
        )
        assert addr == "10.0.0.1:9999"
        assert (n, rank) == ("2", "1")
        assert "ACCELERATE_COORDINATOR_ADDRESS" not in os.environ


class TestWriteBasicConfig:
    def test_writes_default_and_refuses_clobber(self, tmp_path, capsys):
        from accelerate_tpu.commands.config import ClusterConfig
        from accelerate_tpu.utils import write_basic_config

        path = str(tmp_path / "cfg.yaml")
        out = write_basic_config("fp16", path)
        assert out == path
        cfg = ClusterConfig.load(path)
        assert cfg.mixed_precision == "fp16"
        assert write_basic_config("bf16", path) is False  # no clobber
        assert ClusterConfig.load(path).mixed_precision == "fp16"

    def test_rejects_unknown_precision(self, tmp_path):
        from accelerate_tpu.utils import write_basic_config

        with pytest.raises(ValueError):
            write_basic_config("tf32", str(tmp_path / "x.yaml"))

    def test_uppercase_precision_accepted(self, tmp_path):
        """Reference parity: accelerate lowercases before validating."""
        from accelerate_tpu.commands.config import ClusterConfig
        from accelerate_tpu.utils import write_basic_config

        path = str(tmp_path / "u.yaml")
        assert write_basic_config("BF16", path) == path
        assert ClusterConfig.load(path).mixed_precision == "bf16"


class TestRich:
    def test_console_singleton_and_print(self, capsys):
        pytest.importorskip("rich")
        from accelerate_tpu.utils.rich import get_console, rich_print

        assert get_console() is get_console()
        rich_print("hello rich")
        assert "hello rich" in capsys.readouterr().out

    def test_print_gates_on_main_process(self, capsys):
        pytest.importorskip("rich")
        from unittest.mock import PropertyMock, patch

        from accelerate_tpu.state import PartialState
        from accelerate_tpu.utils.rich import rich_print

        with patch.object(type(PartialState()), "is_main_process",
                          new_callable=PropertyMock, return_value=False):
            rich_print("suppressed")  # non-main + default main_process_only
            rich_print("forced", main_process_only=False)
        out = capsys.readouterr().out
        assert "suppressed" not in out
        assert "forced" in out


class TestTqdm:
    def test_main_process_enabled(self):
        from accelerate_tpu.utils.tqdm import tqdm

        bar = tqdm(range(3), main_process_only=True)
        # single process IS the main process: bar must not be disabled
        assert not bar.disable
        assert sum(1 for _ in bar) == 3

    def test_kwargs_passthrough(self):
        from accelerate_tpu.utils.tqdm import tqdm

        bar = tqdm(range(2), disable=True)
        assert bar.disable
        list(bar)
