"""HF-checkpoint → native-pytree converters: logit parity against transformers
models (the 'bring your pretrained weights to the native families' path —
reference counterpart: serving torch checkpoints directly,
``utils/modeling.py:1788`` lazy loading)."""



import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")

from accelerate_tpu.models import (
    BertConfig,
    LlamaConfig,
    T5Config,
    bert_forward,
    bert_params_from_hf,
    llama_forward,
    llama_params_from_hf,
    t5_forward,
    t5_params_from_hf,
)


class TestLlamaConversion:
    def _models(self, seed=0):
        from transformers import LlamaConfig as HFConfig, LlamaForCausalLM

        torch.manual_seed(seed)
        hf = LlamaForCausalLM(HFConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=64, rms_norm_eps=1e-6, rope_theta=10000.0,
            attention_dropout=0.0, tie_word_embeddings=False,
        )).eval()
        cfg = LlamaConfig(
            vocab_size=128, dim=32, ffn_dim=64, n_layers=2, n_heads=4,
            n_kv_heads=2, max_seq_len=64, norm_eps=1e-6,
        )
        return hf, cfg

    def test_logits_match_hf(self):
        hf, cfg = self._models()
        params = llama_params_from_hf(hf, cfg)
        ids = np.random.default_rng(0).integers(1, 128, (2, 10)).astype(np.int32)
        ours = llama_forward(params, jnp.asarray(ids), cfg, attention_impl="xla")
        with torch.no_grad():
            ref = hf(torch.from_numpy(ids.astype(np.int64))).logits.numpy()
        np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-4)

    def test_safetensors_source(self, tmp_path):
        from safetensors.torch import save_file

        hf, cfg = self._models(seed=1)
        path = str(tmp_path / "llama.safetensors")
        save_file({k: v.contiguous() for k, v in hf.state_dict().items()}, path)
        params_file = llama_params_from_hf(path, cfg)
        params_mod = llama_params_from_hf(hf, cfg)
        for a, b in zip(jax.tree_util.tree_leaves(params_file),
                        jax.tree_util.tree_leaves(params_mod)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestBertConversion:
    def test_logits_match_hf(self):
        from transformers import BertConfig as HFConfig, BertForSequenceClassification

        torch.manual_seed(0)
        hf = BertForSequenceClassification(HFConfig(
            vocab_size=100, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=64,
            max_position_embeddings=64, num_labels=3,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
            layer_norm_eps=1e-12,
        )).eval()
        cfg = BertConfig(
            vocab_size=100, dim=32, n_layers=2, n_heads=4, ffn_dim=64,
            max_seq_len=64, num_labels=3,
        )
        params = bert_params_from_hf(hf, cfg)
        rng = np.random.default_rng(0)
        ids = rng.integers(1, 100, (2, 12)).astype(np.int32)
        batch = {
            "input_ids": jnp.asarray(ids),
            "attention_mask": jnp.ones((2, 12), jnp.int32),
            "token_type_ids": jnp.zeros((2, 12), jnp.int32),
        }
        ours = bert_forward(params, batch, cfg, attention_impl="xla")
        with torch.no_grad():
            ref = hf(
                input_ids=torch.from_numpy(ids.astype(np.int64)),
                attention_mask=torch.ones(2, 12, dtype=torch.int64),
                token_type_ids=torch.zeros(2, 12, dtype=torch.int64),
            ).logits.numpy()
        np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-4)


class TestT5Conversion:
    def test_logits_match_hf(self):
        from transformers import T5Config as HFConfig, T5ForConditionalGeneration

        torch.manual_seed(0)
        hf = T5ForConditionalGeneration(HFConfig(
            vocab_size=128, d_model=32, d_kv=8, d_ff=64, num_layers=2,
            num_heads=4, relative_attention_num_buckets=8,
            relative_attention_max_distance=32, dropout_rate=0.0,
            tie_word_embeddings=True, feed_forward_proj="relu",
            decoder_start_token_id=0, eos_token_id=1, pad_token_id=0,
        )).eval()
        cfg = T5Config(
            vocab_size=128, dim=32, head_dim=8, ffn_dim=64, n_layers=2,
            n_heads=4, rel_pos_buckets=8, rel_pos_max_distance=32,
            tie_word_embeddings=True,
        )
        params = t5_params_from_hf(hf, cfg)
        rng = np.random.default_rng(0)
        enc = rng.integers(2, 128, (2, 9)).astype(np.int32)
        dec = rng.integers(2, 128, (2, 5)).astype(np.int32)
        dec[:, 0] = 0
        ours = t5_forward(
            params, {"input_ids": jnp.asarray(enc), "decoder_input_ids": jnp.asarray(dec)}, cfg
        )
        with torch.no_grad():
            ref = hf(
                input_ids=torch.from_numpy(enc.astype(np.int64)),
                decoder_input_ids=torch.from_numpy(dec.astype(np.int64)),
            ).logits.numpy()
        np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-5)


def test_t5_tied_checkpoint_into_untied_config_rescales(tmp_path):
    """A tied HF T5 checkpoint (no lm_head tensor) loaded into an untied
    config must fold the d^-0.5 tied-head rescale into the kernel, or every
    logit comes out sqrt(dim) too large."""
    from transformers import T5Config as HFConfig, T5ForConditionalGeneration

    torch.manual_seed(3)
    hf = T5ForConditionalGeneration(HFConfig(
        vocab_size=128, d_model=32, d_kv=8, d_ff=64, num_layers=2,
        num_heads=4, relative_attention_num_buckets=8,
        relative_attention_max_distance=32, dropout_rate=0.0,
        tie_word_embeddings=True, feed_forward_proj="relu",
        decoder_start_token_id=0, eos_token_id=1, pad_token_id=0,
    )).eval()
    base = dict(
        vocab_size=128, dim=32, head_dim=8, ffn_dim=64, n_layers=2,
        n_heads=4, rel_pos_buckets=8, rel_pos_max_distance=32,
    )
    rng = np.random.default_rng(3)
    enc = rng.integers(2, 128, (2, 7)).astype(np.int32)
    dec = np.zeros((2, 4), np.int32)
    batch = {"input_ids": jnp.asarray(enc), "decoder_input_ids": jnp.asarray(dec)}
    tied = t5_forward(
        t5_params_from_hf(hf, T5Config(tie_word_embeddings=True, **base)),
        batch, T5Config(tie_word_embeddings=True, **base),
    )
    untied = t5_forward(
        t5_params_from_hf(hf, T5Config(tie_word_embeddings=False, **base)),
        batch, T5Config(tie_word_embeddings=False, **base),
    )
    # rescale folded into the kernel vs applied to hidden states: same math,
    # different float op order
    np.testing.assert_allclose(np.asarray(untied), np.asarray(tied), rtol=2e-4, atol=1e-6)


def test_bf16_module_source():
    """Converting a bf16-loaded HF module must not crash (Tensor.numpy rejects
    BFloat16) and must preserve the bf16 dtype."""
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM

    torch.manual_seed(4)
    hf = LlamaForCausalLM(HFConfig(
        vocab_size=64, hidden_size=16, intermediate_size=32,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=32, tie_word_embeddings=False,
    )).to(torch.bfloat16).eval()
    cfg = LlamaConfig(vocab_size=64, dim=16, ffn_dim=32, n_layers=2, n_heads=2,
                      n_kv_heads=2, max_seq_len=32)
    params = llama_params_from_hf(hf, cfg)
    assert params["layers"]["wq"]["kernel"].dtype == jnp.bfloat16
    f32 = np.asarray(params["layers"]["wq"]["kernel"].astype(jnp.float32))
    ref = hf.model.layers[0].self_attn.q_proj.weight.detach().float().numpy().T
    np.testing.assert_array_equal(f32[0], ref)


def test_tied_config_refuses_distinct_head():
    """An untied checkpoint loaded into a tied config must raise, not silently
    drop the checkpoint's lm_head."""
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM

    torch.manual_seed(5)
    hf = LlamaForCausalLM(HFConfig(
        vocab_size=64, hidden_size=16, intermediate_size=32,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=32, tie_word_embeddings=False,
    )).eval()
    cfg = LlamaConfig(vocab_size=64, dim=16, ffn_dim=32, n_layers=2, n_heads=2,
                      n_kv_heads=2, max_seq_len=32, tie_embeddings=True)
    with pytest.raises(ValueError, match="distinct lm_head"):
        llama_params_from_hf(hf, cfg)


def test_beam_generate_matches_hf_beam_search():
    """Converted-weight beam search vs transformers generate(num_beams=K):
    pins our ranking/normalization against the INSTALLED HF version."""
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM

    from accelerate_tpu.generation import beam_generate

    torch.manual_seed(7)
    hf = LlamaForCausalLM(HFConfig(
        vocab_size=96, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False,
    )).eval()
    cfg = LlamaConfig(vocab_size=96, dim=32, ffn_dim=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, max_seq_len=64)
    params = llama_params_from_hf(hf, cfg)
    prompt = np.random.default_rng(7).integers(2, 96, (2, 6)).astype(np.int32)
    ours = beam_generate(params, prompt, cfg, num_beams=3, max_new_tokens=6,
                         cache_dtype=jnp.float32)
    hf.config.use_cache = True
    # eos disabled on BOTH sides so the comparison is well-defined (with eos,
    # HF pads finalized rows with pad_token while ours re-emits eos)
    ref = hf.generate(
        torch.from_numpy(prompt.astype(np.int64)), max_new_tokens=6,
        num_beams=3, do_sample=False, early_stopping=False, pad_token_id=0,
        length_penalty=1.0, eos_token_id=None,
    ).numpy()
    np.testing.assert_array_equal(ours, ref)
