"""Performance observatory (ISSUE 7): peak registry + MFU/roofline math
goldens, CPU-backend cost-analysis capture on a real jitted fn, xplane
fixture + real-trace parsing, overlap-ratio computation, automatic trace
windows, the report CLI's performance section, and the disabled-path
zero-cost smoke (mirrors test_forensics.py style)."""

import gzip
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, telemetry as tel
from accelerate_tpu.telemetry import perf, xplane
from accelerate_tpu.telemetry.report import build_report, format_report
from accelerate_tpu.utils.dataclasses import ProfileConfig


@pytest.fixture(autouse=True)
def _telemetry_clean(monkeypatch):
    for var in ("ACCELERATE_TELEMETRY", "ACCELERATE_TELEMETRY_DIR",
                "ACCELERATE_PERF_CAPTURE", "ACCELERATE_CPU_PEAK_FLOPS",
                "ACCELERATE_CPU_HBM_GBPS", "ACCELERATE_TRACE_EVERY",
                "ACCELERATE_TRACE_STEPS", "ACCELERATE_TRACE_AT",
                "ACCELERATE_TRACE_DIR"):
        monkeypatch.delenv(var, raising=False)
    yield
    tel.disable()


class _FakeDevice:
    def __init__(self, kind):
        self.device_kind = kind


# ------------------------------------------------------------ peak registry --


@pytest.mark.smoke
def test_peak_registry_table_and_fallbacks(monkeypatch):
    v5e = perf.peaks_for_device(_FakeDevice("TPU v5e"))
    assert v5e.flops == 197e12 and v5e.hbm_bytes_per_s == 819e9
    assert not v5e.nominal and v5e.source == "table"
    assert v5e.ridge_intensity == pytest.approx(197e12 / 819e9)
    # unknown TPU generations fall back to v5e instead of reporting nothing
    unknown = perf.peaks_for_device(_FakeDevice("TPU v99 mega"))
    assert unknown.flops == 197e12 and not unknown.nominal
    # non-TPU: nominal peaks keep MFU a usable relative signal on dev boxes
    cpu = perf.peaks_for_device(_FakeDevice(""))
    assert cpu.nominal and cpu.flops > 0 and cpu.source == "cpu-nominal"
    monkeypatch.setenv("ACCELERATE_CPU_PEAK_FLOPS", "2e12")
    monkeypatch.setenv("ACCELERATE_CPU_HBM_GBPS", "100")
    tuned = perf.peaks_for_device(_FakeDevice("cpu"))
    assert tuned.flops == 2e12 and tuned.hbm_bytes_per_s == 100e9
    assert tuned.nominal and tuned.source == "env"


def test_device_peak_helpers_gate_nominal_peaks():
    """bench.py omits MFU on dev boxes (no absolute peak exists); the
    telemetry path opts into the nominal stand-in explicitly."""
    cpu = _FakeDevice("cpu")
    assert perf.device_peak_flops(cpu) == 0.0
    assert perf.device_peak_flops(cpu, include_nominal=True) > 0
    assert perf.device_hbm_bandwidth(cpu) is None
    assert perf.device_hbm_bandwidth(cpu, include_nominal=True) > 0
    tpu = _FakeDevice("TPU v4")
    assert perf.device_peak_flops(tpu) == 275e12
    assert perf.device_hbm_bandwidth(tpu) == 1228e9


# ----------------------------------------------------------------- MFU math --


def test_mfu_and_intensity_goldens():
    assert perf.mfu(1e12, 1.0, 197e12) == pytest.approx(1e12 / 197e12)
    assert perf.mfu(5e11, 0.5, 1e12) == pytest.approx(1.0)
    assert perf.mfu(0.0, 1.0, 1e12) is None
    assert perf.mfu(1e12, 1.0, 0.0) is None
    assert perf.arithmetic_intensity(1e9, 1e7) == pytest.approx(100.0)
    assert perf.arithmetic_intensity(0.0, 1e7) is None


def test_roofline_bucket_straddles_ridge():
    peaks = perf.HardwarePeaks("TPU v5e", 197e12, 819e9)
    ridge = peaks.ridge_intensity  # ~240.5 FLOP/B
    assert perf.roofline_bucket(ridge * 2, peaks) == "compute-bound"
    assert perf.roofline_bucket(ridge, peaks) == "compute-bound"  # >= is compute
    assert perf.roofline_bucket(ridge / 2, peaks) == "hbm-bound"
    assert perf.roofline_bucket(None, peaks) is None
    no_bw = perf.HardwarePeaks("x", 1e12, None)
    assert perf.roofline_bucket(100.0, no_bw) is None


def test_train_flops_per_sample_golden():
    class Cfg:
        n_layers, dim = 4, 128

    n_params, seq = 1_000_000, 32
    expected = (6.0 * n_params + 12.0 * 4 * 128 * seq) * seq
    assert perf.train_flops_per_sample(Cfg, seq, n_params) == pytest.approx(expected)


def test_lm_train_mfu_gates_on_real_peak(monkeypatch):
    class Cfg:
        n_layers, dim = 2, 64

    # CPU backend: no absolute peak -> None (bench omits the field)
    assert perf.lm_train_mfu(1000.0, 10_000, Cfg, 16) is None
    monkeypatch.setattr(perf, "device_peak_flops", lambda d: 1e12)
    per_token = perf.train_flops_per_sample(Cfg, 16, 10_000) / 16
    assert perf.lm_train_mfu(1000.0, 10_000, Cfg, 16) == pytest.approx(
        round(1000.0 * per_token / 1e12, 4)
    )


# -------------------------------------------------------------- cost capture --


def _events(tmp_path):
    out = []
    for name in os.listdir(tmp_path):
        if name.endswith(".jsonl"):
            with open(os.path.join(tmp_path, name)) as f:
                out.extend(json.loads(line) for line in f if line.strip())
    return out


def test_capture_compiled_records_cost_and_memory(tmp_path):
    tel.enable(str(tmp_path))

    @jax.jit
    def step(x, y):
        return jnp.tanh(x @ y).sum()

    ones = jnp.ones((64, 64), jnp.float32)
    cost = perf.capture_compiled("my_step", step, (ones, ones))
    tel.disable()
    assert cost is not None and cost.flops > 0 and cost.bytes_accessed > 0
    assert cost.intensity == pytest.approx(cost.flops / cost.bytes_accessed)
    assert cost.roofline in ("compute-bound", "hbm-bound")
    assert cost.mfu(1.0) == pytest.approx(cost.flops / cost.peaks.flops)
    assert cost.memory and cost.memory["argument_bytes"] > 0
    events = _events(tmp_path)
    perf_recs = [e for e in events if e["kind"] == "perf"]
    assert len(perf_recs) == 1 and perf_recs[0]["fn"] == "my_step"
    assert perf_recs[0]["flops"] == cost.flops
    assert perf_recs[0]["roofline"] == cost.roofline
    assert any(e["kind"] == "memory_projection" for e in events)


def test_capture_kill_switch(tmp_path, monkeypatch):
    assert not perf.capture_enabled()  # telemetry off
    tel.enable(str(tmp_path))
    assert perf.capture_enabled()
    monkeypatch.setenv("ACCELERATE_PERF_CAPTURE", "0")
    assert not perf.capture_enabled()


def test_capture_tolerates_unlowerable_fn(tmp_path):
    tel.enable(str(tmp_path))
    assert perf.capture_compiled("eager", lambda x: x, (1,)) is None


def test_capture_compile_excluded_from_step_accounting(tmp_path):
    """The capture's AOT compile must not inflate step compile_s/compiles."""
    from accelerate_tpu.telemetry import step_profiler

    tel.enable(str(tmp_path))
    step_profiler.install_compile_listener()

    @jax.jit
    def fn(x):
        return x * 2 + 1

    ones = jnp.ones((8, 8))  # the array-creation compile is real training cost
    c0, s0 = step_profiler.compile_snapshot()
    perf.capture_compiled("fn", fn, (ones,))
    c1, s1 = step_profiler.compile_snapshot()
    assert c1 == c0  # the AOT compile was bracketed out
    assert s1 == pytest.approx(s0, abs=1e-6)


# ----------------------------------------------------- accelerator integration


def _tiny_train(tmp_path, steps=4, handlers=None):
    from accelerate_tpu.models import BertConfig, bert_loss, bert_shard_rules, init_bert
    import dataclasses

    config = dataclasses.replace(BertConfig.tiny(), max_seq_len=32)
    acc = Accelerator(mixed_precision="bf16", rng_seed=0, kwargs_handlers=handlers)
    params = init_bert(config, jax.random.PRNGKey(0))
    params, opt = acc.prepare(params, optax.adamw(1e-4), shard_rules=bert_shard_rules())
    step = acc.prepare_train_step(lambda p, b: bert_loss(p, b, config), opt)
    rng = np.random.default_rng(0)
    batch = {
        "input_ids": jnp.asarray(rng.integers(0, config.vocab_size, (8, 32)), jnp.int32),
        "attention_mask": jnp.ones((8, 32), jnp.int32),
        "token_type_ids": jnp.zeros((8, 32), jnp.int32),
        "labels": jnp.asarray(rng.integers(0, 2, (8,)), jnp.int32),
    }
    opt_state = opt.opt_state
    for _ in range(steps):
        params, opt_state, _m = step(params, opt_state, batch)
        # force completion inside the step so trace windows capture the
        # thunks (async dispatch would otherwise run them past stop_trace)
        float(np.asarray(_m["loss"]))
    acc.end_training()
    return acc


def test_accelerator_steps_carry_mfu_and_roofline(tmp_path):
    tel.enable(str(tmp_path))
    _tiny_train(tmp_path)
    tel.disable()
    events = _events(tmp_path)
    perfs = [e for e in events if e["kind"] == "perf"]
    assert len(perfs) == 1 and perfs[0]["fn"] == "train_step"
    steps = [e for e in events if e["kind"] == "step"]
    assert len(steps) == 4
    for s in steps:
        assert s["mfu"] > 0
        assert s["roofline"] in ("compute-bound", "hbm-bound")
        assert s["perf_fn"] == "train_step"
        assert s["arithmetic_intensity"] > 0
    # only the training path's jit compile lands in step records — the AOT
    # capture compile is excluded (one compile total, on the first step)
    assert sum(s["compiles"] for s in steps) == 1 and steps[0]["compiles"] == 1


def test_accelerator_capture_kill_switch(tmp_path, monkeypatch):
    monkeypatch.setenv("ACCELERATE_PERF_CAPTURE", "0")
    tel.enable(str(tmp_path))
    _tiny_train(tmp_path)
    tel.disable()
    events = _events(tmp_path)
    assert not [e for e in events if e["kind"] == "perf"]
    assert all(e.get("mfu") is None for e in events if e["kind"] == "step")


@pytest.mark.smoke
def test_perf_disabled_path_zero_cost(tmp_path, monkeypatch):
    """Telemetry off: no perf capture, no lowering, no trace window, no file
    — the wrapper's additions are flag checks (test_forensics style)."""
    monkeypatch.chdir(tmp_path)
    lowered = []

    real_capture = perf.capture_compiled
    monkeypatch.setattr(perf, "capture_compiled",
                        lambda *a, **k: lowered.append(a) or real_capture(*a, **k))
    acc = _tiny_train(tmp_path, steps=2)
    assert not lowered  # capture never invoked while telemetry is off
    assert acc._trace_windows is None  # no window driver without config/env
    assert not list(tmp_path.iterdir())  # nothing written anywhere


# ------------------------------------------------------------ xplane parsing --


def _varint(n):
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(fnum, wt):
    return _varint((fnum << 3) | wt)


def _ld(fnum, payload):
    return _tag(fnum, 2) + _varint(len(payload)) + payload


def _vi(fnum, value):
    return _tag(fnum, 0) + _varint(value)


def _encode_xspace(planes):
    """planes: [(plane_name, [(line_name, [(op, start_ms, dur_ms)]) |
    (line_name, line_ts_ms, [(op, start_ms, dur_ms)])])] — hand-built XSpace
    wire bytes, the parser's ground-truth fixture. Event starts are relative
    to their line's timestamp, exactly like the real schema."""
    space = b""
    for plane_name, lines in planes:
        meta_ids = {}
        plane = _ld(2, plane_name.encode())
        events_by_line = []
        for line in lines:
            line_name, line_ts_ms, events = line if len(line) == 3 else (line[0], 0.0, line[1])
            evs = b""
            for op, start_ms, dur_ms in events:
                mid = meta_ids.setdefault(op, len(meta_ids) + 1)
                # proto3 writers OMIT zero-valued varints: an event at the
                # line epoch has no offset field on the wire — encode the
                # same way so the fixture exercises the parser's default
                offset = b"" if start_ms == 0 else _vi(2, int(start_ms * 1e9))
                evs += _ld(4, _vi(1, mid) + offset + _vi(3, int(dur_ms * 1e9)))
            ts = b"" if line_ts_ms == 0 else _vi(3, int(line_ts_ms * 1e6))  # ns
            events_by_line.append(_ld(2, line_name.encode()) + ts + evs)
        for mid_name, mid in meta_ids.items():
            entry = _vi(1, mid) + _ld(2, _vi(1, mid) + _ld(2, mid_name.encode()))
            plane += _ld(4, entry)
        for line in events_by_line:
            plane += _ld(3, line)
        space += _ld(1, plane)
    return space


def _write_fixture(tmp_path, planes):
    d = tmp_path / "plugins" / "profile" / "2026_01_01"
    d.mkdir(parents=True)
    (d / "host.xplane.pb").write_bytes(_encode_xspace(planes))
    return str(tmp_path)


def test_xplane_fixture_overlap_and_topk(tmp_path):
    # device plane: compute [0,10]+[14,20]+[24,26] ms, collective [8,16] ms
    # -> collective 8ms, overlapped [8,10]+[14,16] = 4ms -> ratio 0.5;
    # busy union [0,20]+[24,26] = 22ms over a 26ms span -> idle 4ms
    trace_dir = _write_fixture(tmp_path, [
        ("/device:TPU:0", [
            ("stream1", [("fusion.1", 0.0, 10.0), ("fusion.2", 14.0, 6.0),
                         ("fusion.1", 24.0, 2.0)]),
            ("stream2", [("all-reduce.3", 8.0, 8.0)]),
        ]),
        # a host plane next to a device plane is ignored entirely
        ("/host:CPU", [("python", [("$train.py:1 step", 0.0, 26.0)])]),
    ])
    out = xplane.summarize_trace(trace_dir)
    assert out["events"] == 4 and out["ops"] == 3
    assert out["compute_s"] == pytest.approx(18e-3)
    assert out["collective_s"] == pytest.approx(8e-3)
    assert out["collective_overlap_s"] == pytest.approx(4e-3)
    assert out["comms_overlap_ratio"] == pytest.approx(0.5)
    assert out["busy_s"] == pytest.approx(22e-3)
    assert out["idle_s"] == pytest.approx(4e-3)
    assert out["span_s"] == pytest.approx(26e-3)
    top = out["top_ops"]
    assert top[0]["op"] == "fusion.1" and top[0]["count"] == 2
    assert top[0]["total_s"] == pytest.approx(12e-3)
    collective_ops = [t for t in top if t["collective"]]
    assert [t["op"] for t in collective_ops] == ["all-reduce.3"]


def test_xplane_lines_with_different_epochs_align(tmp_path):
    """Event offsets are relative to their LINE's timestamp_ns; lines
    (streams/queues) of one trace carry different epochs. The same physical
    intervals as test_xplane_fixture_overlap_and_topk, expressed with the
    collective line's epoch shifted by +8ms, must summarize identically —
    cross-line overlap is only meaningful after rebasing to absolute time."""
    trace_dir = _write_fixture(tmp_path, [
        ("/device:TPU:0", [
            ("stream1", 0.0, [("fusion.1", 0.0, 10.0), ("fusion.2", 14.0, 6.0),
                              ("fusion.1", 24.0, 2.0)]),
            # absolute [8,16]ms, written as offset 0 from an 8ms line epoch
            ("stream2", 8.0, [("all-reduce.3", 0.0, 8.0)]),
        ]),
    ])
    out = xplane.summarize_trace(trace_dir)
    assert out["collective_overlap_s"] == pytest.approx(4e-3)
    assert out["comms_overlap_ratio"] == pytest.approx(0.5)
    assert out["idle_s"] == pytest.approx(4e-3)


def test_xplane_device_envelope_lines_excluded(tmp_path):
    """Real TPU device planes carry 'Steps'/'XLA Modules' envelope lines
    whose events span whole steps — counting them as compute would cover
    every collective interval and fake comms_overlap_ratio ≈ 1.0. Only the
    op-level 'XLA Ops' (+ async) lines may feed the accounting."""
    trace_dir = _write_fixture(tmp_path, [
        ("/device:TPU:0", [
            # envelope lines: one event covering the whole 30ms step
            ("Steps", [("1", 0.0, 30.0)]),
            ("XLA Modules", [("jit_train_step(1)", 0.0, 30.0)]),
            # the real ops: compute [0,10], collective [12,20] — ZERO overlap
            ("XLA Ops", [("fusion.1", 0.0, 10.0)]),
            ("XLA Async Ops", [("all-reduce.2", 12.0, 8.0)]),
        ]),
    ])
    out = xplane.summarize_trace(trace_dir)
    assert out["events"] == 2  # envelopes excluded entirely
    assert out["compute_s"] == pytest.approx(10e-3)
    assert out["collective_s"] == pytest.approx(8e-3)
    assert out["comms_overlap_ratio"] == pytest.approx(0.0)  # not a fake 1.0
    assert {t["op"] for t in out["top_ops"]} == {"fusion.1", "all-reduce.2"}


def test_trace_windows_honors_both_triggers(tmp_path):
    """An env-seeded one-shot trace_at must not silently disable a periodic
    trace_every configured in code — both fire."""
    cfg = ProfileConfig(trace_every=4, trace_at=2, trace_steps=1)
    tw = xplane.TraceWindows(cfg, str(tmp_path))

    @jax.jit
    def fn(x):
        return x + 1

    x = jnp.ones((8,))
    for step in range(6):
        tw.on_step_start(step)
        fn(x).block_until_ready()
        tw.on_step_end(step)
    tw.close()
    assert [s["step_start"] for s in tw.summaries] == [2, 4]


def test_xplane_no_collectives_yields_null_ratio(tmp_path):
    trace_dir = _write_fixture(
        tmp_path, [("/device:TPU:0", [("s", [("dot.1", 0.0, 5.0)])])]
    )
    out = xplane.summarize_trace(trace_dir)
    assert out["collective_s"] == 0 and out["comms_overlap_ratio"] is None


def test_xplane_host_fallback_excludes_python_and_infra(tmp_path):
    trace_dir = _write_fixture(tmp_path, [
        ("/host:CPU", [
            ("python", [("PjitFunction(f)", 0.0, 50.0)]),
            ("tf_XLAEigen/1", [("dot.4", 0.0, 10.0),
                               ("ThunkExecutor::Execute", 0.0, 40.0),
                               ("$profiler.py:91 start_trace", 0.0, 99.0)]),
        ]),
    ])
    out = xplane.summarize_trace(trace_dir)
    assert out["events"] == 1  # only dot.4 is an op
    assert out["top_ops"][0]["op"] == "dot.4"


def test_chrome_trace_fallback(tmp_path):
    d = tmp_path / "plugins" / "profile" / "x"
    d.mkdir(parents=True)
    trace = {"traceEvents": [
        {"ph": "M", "pid": 1, "name": "process_name", "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 1, "tid": 2, "name": "thread_name", "args": {"name": "XLA Ops"}},
        {"ph": "X", "pid": 1, "tid": 2, "ts": 0.0, "dur": 1000.0, "name": "fusion.9"},
        {"ph": "X", "pid": 1, "tid": 2, "ts": 1000.0, "dur": 500.0, "name": "all-gather.2"},
    ]}
    with gzip.open(d / "host.trace.json.gz", "wt") as f:
        json.dump(trace, f)
    out = xplane.summarize_trace(str(tmp_path))
    assert out["events"] == 2
    assert out["compute_s"] == pytest.approx(1000e-6)
    assert out["collective_s"] == pytest.approx(500e-6)


def test_real_cpu_trace_parses_to_ops(tmp_path):
    """End-to-end against the real jax.profiler output on this backend."""

    @jax.jit
    def fn(x, y):
        return (x @ y).sum()

    x = jnp.ones((128, 128))
    fn(x, x).block_until_ready()
    jax.profiler.start_trace(str(tmp_path))
    for _ in range(3):
        fn(x, x).block_until_ready()
    jax.profiler.stop_trace()
    out = xplane.summarize_trace(str(tmp_path))
    assert out["files"] and out["events"] > 0 and out["busy_s"] > 0
    assert out["top_ops"]


# ------------------------------------------------------------- trace windows --


def test_trace_windows_every_n(tmp_path):
    tel.enable(str(tmp_path / "tel"))
    # 2-step windows: a 1-step CPU window can close before the XLA pool
    # threads flush their TraceMe buffers (the second step forces the flush)
    cfg = ProfileConfig(trace_every=3, trace_steps=2)
    tw = xplane.TraceWindows(cfg, str(tmp_path / "trace"))

    @jax.jit
    def fn(x):
        return (x @ x).sum()

    x = jnp.ones((64, 64))
    for step in range(8):
        tw.on_step_start(step)
        fn(x).block_until_ready()
        tw.on_step_end(step)
    tw.close()
    tel.disable()
    assert [s["step_start"] for s in tw.summaries] == [3, 6]
    assert [s["step_end"] for s in tw.summaries] == [4, 7]
    for s in tw.summaries:
        assert s["events"] > 0
        assert os.path.exists(os.path.join(s["trace_dir"], "summary.json"))
    traces = [e for e in _events(tmp_path / "tel") if e["kind"] == "trace"]
    assert len(traces) == 2 and all(t["top_ops"] for t in traces)


def test_trace_windows_one_shot(tmp_path):
    cfg = ProfileConfig(trace_at=3, trace_steps=1)
    tw = xplane.TraceWindows(cfg, str(tmp_path))

    @jax.jit
    def fn(x):
        return x + 1

    x = jnp.ones((8,))
    for step in range(6):
        tw.on_step_start(step)
        fn(x).block_until_ready()
        tw.on_step_end(step)
    tw.close()
    assert len(tw.summaries) == 1 and tw.summaries[0]["step_start"] == 3


def test_trace_windows_stand_down_when_profiler_busy(tmp_path):
    tel.enable(str(tmp_path / "tel"))
    jax.profiler.start_trace(str(tmp_path / "outer"))
    try:
        cfg = ProfileConfig(trace_every=1, trace_steps=1)
        tw = xplane.TraceWindows(cfg, str(tmp_path / "auto"))
        tw.on_step_start(1)
        assert tw.disabled and not tw.tracing
        tw.on_step_start(2)  # stays down, no retry storm
        assert tw.disabled
    finally:
        jax.profiler.stop_trace()
    tel.disable()
    errors = [e for e in _events(tmp_path / "tel")
              if e["kind"] == "trace" and e.get("error")]
    assert len(errors) == 1


def test_profile_config_env_seeding(monkeypatch):
    monkeypatch.setenv("ACCELERATE_TRACE_EVERY", "7")
    monkeypatch.setenv("ACCELERATE_TRACE_STEPS", "2")
    monkeypatch.setenv("ACCELERATE_TRACE_DIR", "/tmp/tracehere")
    cfg = ProfileConfig()
    assert cfg.trace_every == 7 and cfg.trace_steps == 2
    assert cfg.output_trace_dir == "/tmp/tracehere"
    assert cfg.windows_enabled
    monkeypatch.setenv("ACCELERATE_TRACE_EVERY", "garbage")
    assert ProfileConfig().trace_every == 0  # malformed env never crashes


def test_accelerator_trace_windows_emit_trace_events(tmp_path):
    tel.enable(str(tmp_path / "tel"))
    _tiny_train(
        tmp_path,
        steps=6,
        # 2-step window so the CPU pool threads flush into the session
        # before it closes (see test_trace_windows_every_n)
        handlers=[ProfileConfig(trace_every=3, trace_steps=2,
                                output_trace_dir=str(tmp_path / "prof"))],
    )
    tel.disable()
    traces = [e for e in _events(tmp_path / "tel") if e["kind"] == "trace"]
    assert len(traces) == 1  # one window spanning steps 3-4
    assert traces[0]["step_start"] == 3 and traces[0]["step_end"] == 4
    assert traces[0]["events"] > 0 and traces[0]["top_ops"]


# ---------------------------------------------------------- report section --


def _write_perf_stream(path, mfus=(0.4, 0.5, 0.6, 0.7), rank=0, with_trace=True):
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "meta", "schema": 1, "run_id": "r",
                            "process_index": rank, "num_processes": 1}) + "\n")
        f.write(json.dumps({
            "kind": "perf", "t": 0.0, "fn": "train_step", "flops": 2e9,
            "bytes_accessed": 4e7, "arithmetic_intensity": 50.0,
            "roofline": "hbm-bound", "peak_flops": 197e12,
            "peak_hbm_bytes_per_s": 819e9, "peak_source": "table",
            "device_kind": "TPU v5e"}) + "\n")
        for i, m in enumerate(mfus):
            f.write(json.dumps({
                "kind": "step", "step": i, "t": float(i), "dur_s": 0.01,
                "compile_s": 0.0, "execute_s": 0.01, "mfu": m,
                "arithmetic_intensity": 50.0, "roofline": "hbm-bound",
                "perf_fn": "train_step"}) + "\n")
        if with_trace:
            f.write(json.dumps({
                "kind": "trace", "t": 9.0, "events": 20, "ops": 4,
                "span_s": 0.1, "busy_s": 0.09, "idle_s": 0.01,
                "compute_s": 0.07, "collective_s": 0.02,
                "collective_overlap_s": 0.01, "comms_overlap_ratio": 0.5,
                "top_ops": [
                    {"op": "fusion.1", "total_s": 0.04, "count": 8,
                     "share": 0.5, "collective": False},
                    {"op": "all-reduce.7", "total_s": 0.02, "count": 4,
                     "share": 0.25, "collective": True},
                ]}) + "\n")


def test_report_performance_section_snapshot(tmp_path):
    _write_perf_stream(tmp_path / "events-rank0.jsonl")
    report = build_report([str(tmp_path)])
    p = report["performance"]
    assert p["mfu"]["count"] == 4 and p["mfu"]["p50"] == pytest.approx(0.5)
    assert p["mfu_trend"]["first_half_mean"] == pytest.approx(0.45)
    assert p["mfu_trend"]["second_half_mean"] == pytest.approx(0.65)
    assert p["mfu_trend"]["delta"] == pytest.approx(0.2)
    fn = p["by_fn"]["train_step"]
    assert fn["roofline"] == "hbm-bound" and fn["flops"] == 2e9
    assert fn["mfu"]["count"] == 4
    tr = p["trace"]
    assert tr["windows"] == 1 and tr["comms_overlap_ratio"] == pytest.approx(0.5)
    assert tr["top_ops"][0]["op"] == "fusion.1"
    text = format_report(report)
    assert "performance:" in text
    assert "MFU over 4 step(s)" in text
    assert "hbm-bound" in text
    assert "top op 1: fusion.1" in text
    assert "50.0% of collective time hidden" in text
    assert "[collective]" in text


def test_report_without_perf_records_omits_section(tmp_path):
    (tmp_path / "events-rank0.jsonl").write_text(
        json.dumps({"kind": "meta", "schema": 1, "run_id": "r", "process_index": 0}) + "\n"
        + json.dumps({"kind": "step", "step": 0, "dur_s": 0.01}) + "\n"
    )
    report = build_report([str(tmp_path)])
    assert report["performance"] is None
    assert "performance:" not in format_report(report)  # old logs still render


def test_report_by_rank_mfu_skew(tmp_path):
    _write_perf_stream(tmp_path / "events-rank0.jsonl", mfus=(0.6, 0.6), rank=0,
                       with_trace=False)
    _write_perf_stream(tmp_path / "events-rank1.jsonl", mfus=(0.3, 0.3), rank=1,
                       with_trace=False)
    report = build_report([str(tmp_path)], by_rank=True)
    ranks = report["ranks"]["per_rank"]
    assert ranks["0"]["mfu"]["p50"] == pytest.approx(0.6)
    assert ranks["1"]["mfu"]["p50"] == pytest.approx(0.3)
    text = format_report(report)
    assert "mfu p50=0.6000" in text and "mfu p50=0.3000" in text


# -------------------------------------------------------- memory projection --


def test_memory_projection_warns_on_overcommit(tmp_path, monkeypatch):
    from accelerate_tpu.telemetry import memory

    monkeypatch.setattr(
        memory, "device_memory_stats",
        lambda: [{"device": 0, "kind": "TPU v5e", "bytes_limit": 800}],
    )
    tel.enable(str(tmp_path))
    # args 600 + outputs 600 + temps 300 - aliased(donated) 600 = 900 > 800
    analysis = {"argument_bytes": 600, "output_bytes": 600, "temp_bytes": 300,
                "alias_bytes": 600}
    with pytest.warns(UserWarning, match="expect an OOM"):
        rec = memory.check_memory_fit("big_step", analysis)
    assert rec["projected_peak_bytes"] == 900 and rec["fits"] is False
    tel.disable()  # flush before reading the stream back
    events = _events(tmp_path)
    proj = [e for e in events if e["kind"] == "memory_projection"]
    assert proj and proj[0]["fn"] == "big_step"


def test_memory_projection_fits_no_warning(tmp_path, monkeypatch):
    import warnings as _warnings

    from accelerate_tpu.telemetry import memory

    monkeypatch.setattr(
        memory, "device_memory_stats",
        lambda: [{"device": 0, "kind": "TPU v5e", "bytes_limit": 10_000}],
    )
    tel.enable(str(tmp_path))
    analysis = {"argument_bytes": 600, "output_bytes": 600, "temp_bytes": 300,
                "alias_bytes": 600}
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        rec = memory.check_memory_fit("ok_step", analysis)
    assert rec["fits"] is True and rec["projected_peak_bytes"] == 900
    tel.disable()  # flush before reading the stream back
    events = _events(tmp_path)
    assert any(e["kind"] == "memory_projection" for e in events)
