"""jaxlint fixture: R1 seeded violations — host syncs inside traced code.

Parsed by tests/test_analysis.py, never imported. Every construct here is a
device→host sync inside a jit region; the twin (r1_clean.py) holds the
near-miss spellings that must NOT fire.
"""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def step_with_item(params, batch):
    loss = jnp.mean((batch["x"] @ params["w"]) ** 2)
    scalar = loss.item()  # R1: .item() inside traced code
    return scalar


@jax.jit
def step_with_float(params, batch):
    loss = jnp.mean(batch["x"] @ params["w"])
    lr_scale = float(loss)  # R1: float() on a tracer
    return lr_scale


@jax.jit
def step_with_branch(params, batch):
    loss = jnp.mean(batch["x"] @ params["w"])
    if loss > 0:  # R1: python `if` on a traced value
        loss = loss * 2
    return loss


@jax.jit
def step_with_asarray(params, batch):
    grads = jnp.ones_like(params["w"])
    host = np.asarray(grads)  # R1: np.asarray of a tracer
    return host


@jax.jit
def step_with_device_get(params, batch):
    out = jnp.sum(params["w"])
    return jax.device_get(out)  # R1: device_get inside traced code


def traced_helper(logits):
    """Reached from a jit root below — still traced code."""
    return logits.tolist()  # R1: .tolist() in a traced helper


@jax.jit
def step_calling_helper(params, batch):
    logits = batch["x"] @ params["w"]
    return traced_helper(logits)
