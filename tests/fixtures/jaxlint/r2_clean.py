"""jaxlint fixture: R2 clean twins — zero findings expected."""

import jax
import jax.numpy as jnp
from jax import lax

_BLOCK_SIZES = (128, 256)  # immutable ALL_CAPS constant: fine to close over


@jax.jit
def step_scan(params, batch):
    def body(carry, row):
        return carry + jnp.sum(row @ params["w"]), None

    total, _ = lax.scan(body, jnp.zeros(()), batch["x"])  # scan, not unroll
    return total


@jax.jit
def step_constant_closure(params, batch):
    pad = _BLOCK_SIZES[0]  # reads an immutable module constant
    return jnp.pad(batch["x"], ((0, 0), (0, pad))) @ params["w"]


@jax.jit
def step_static_range(params, batch, depth=4):
    x = batch["x"]
    for _ in range(depth):  # range() over a config int: static, no unroll hazard
        x = jax.nn.relu(x @ params["w"])
    return x


def _inner_step(x, config):
    return x * 2


compiled_static = jax.jit(_inner_step, static_argnums=(1,))


def call_with_hashable(x):
    return compiled_static(x, (4, 8))  # tuple static arg: hashable, cached once
