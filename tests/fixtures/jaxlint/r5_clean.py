"""jaxlint fixture: R5 clean twins — zero findings expected."""

import time

import jax
import jax.numpy as jnp


@jax.jit
def step_with_jax_random(params, batch, key=None):
    noise = jax.random.normal(key, ())  # explicit key: deterministic
    return jnp.mean(batch["x"] @ params["w"]) + noise


@jax.jit
def step_sorted_iteration(params, batch):
    total = jnp.zeros(())
    for name in sorted({"w", "b"}):  # sorted: stable order
        total = total + jnp.sum(params[name])
    return total


def build_sharding_specs(axis_names):
    specs = {}
    for axis in sorted(set(axis_names)):  # sorted before building specs
        specs[axis] = ("data", axis)
    return specs


def host_side_timing(fn, *args):
    t0 = time.monotonic()  # not traced: host-side timing is fine
    out = fn(*args)
    return out, time.monotonic() - t0
