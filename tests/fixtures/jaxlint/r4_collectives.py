"""jaxlint fixture: R4 seeded violations — rank-divergent collectives.

``save_metrics_deadlock`` is the canonical ``if is_main_process:
gather(...)`` shape from the issue; ``checkpoint_guarded`` is the subtler
early-return variant that real checkpoint code grows.
"""

from accelerate_tpu.utils.operations import broadcast, gather


def save_metrics_deadlock(state, metrics):
    if state.is_main_process:
        all_metrics = gather(metrics)  # R4: only rank 0 reaches the gather
        return all_metrics
    return None


def checkpoint_guarded(state, payload):
    if not state.is_main_process:
        return None  # rank filter...
    return gather(payload)  # R4: ...then a collective only main reaches


def _collect(tree):
    return gather(tree)  # collective via helper


def log_through_helper(state, metrics):
    if state.process_index == 0:
        return _collect(metrics)  # R4: collective-containing helper under rank guard
    return None


def ternary_gather(state, x):
    return gather(x) if state.is_main_process else None  # R4: one-arm collective


def shortcircuit_broadcast(state, x):
    return state.is_main_process and broadcast(x)  # R4: short-circuited


def asymmetric_branches(state, x):
    if state.is_main_process:
        y = gather(x)  # R4: branches disagree (gather vs broadcast)
    else:
        y = broadcast(x)  # R4: flagged with its sibling
    return y
