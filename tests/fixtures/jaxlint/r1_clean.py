"""jaxlint fixture: R1 clean twins — near-misses that must produce ZERO
findings. Each mirrors a violation in r1_host_sync.py with the legal
spelling."""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def step_identity_check(params, batch, aux=None):
    loss = jnp.mean(batch["x"] @ params["w"])
    if aux is not None:  # identity check resolves at trace time
        loss = loss + aux_weight(aux)
    return loss


def aux_weight(aux):
    return jnp.sum(aux)


@jax.jit
def step_config_branch(params, batch, use_bias=False, scale=1.0):
    out = batch["x"] @ params["w"]
    if use_bias:  # bool-default param: trace-time static
        out = out + params["b"]
    return out * float(scale)  # float() of a config value, not a tracer


@jax.jit
def step_dict_items(params, batch):
    total = jnp.zeros(())
    for name, leaf in params.items():  # dict .items(), not array .item()
        total = total + jnp.sum(leaf)
    return total


def host_side_metrics(arrays):
    """NOT reachable from any jit root: host-side syncs are fine here."""
    return [float(np.asarray(a).mean()) for a in arrays]


@jax.jit
def step_where(params, batch):
    loss = jnp.mean(batch["x"] @ params["w"])
    return jnp.where(loss > 0, loss * 2, loss)  # on-device select
