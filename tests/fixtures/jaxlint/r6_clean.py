"""jaxlint fixture: R6 clean near-miss twins — every explicit dot_general
pins its accumulator; operator matmuls and einsum are out of scope (their
policy lives in ``jax.default_matmul_precision``)."""

import jax
import jax.numpy as jnp


@jax.jit
def attn_scores_f32_accum(q, k):
    return jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )


@jax.jit
def mlp_block_f32_accum(x, w):
    h = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    return jax.nn.relu(h)


@jax.jit
def operator_matmul_out_of_scope(x, w):
    # `@` and einsum are governed by default_matmul_precision, not R6
    return jnp.einsum("bi,io->bo", x, w) + x @ w @ jnp.eye(w.shape[1], dtype=w.dtype)


def eager_helper_out_of_scope(x, w):
    # not traced, not an ops/ module: R6 stays quiet
    return jax.lax.dot_general(x, w, (((1,), (0,)), ((), ())))
