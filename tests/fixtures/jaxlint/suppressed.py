"""jaxlint fixture: inline-suppression semantics.

Each violation here is covered by a ``# jaxlint: disable`` comment; the
engine must report them as suppressed (not new). The final function carries
a real violation with a MISMATCHED rule id in the disable list — that one
must still fail.
"""

import jax
import jax.numpy as jnp


@jax.jit
def tolerated_sync(params, batch):
    loss = jnp.mean(batch["x"] @ params["w"])
    debug = float(loss)  # jaxlint: disable=R1
    return debug


@jax.jit
def tolerated_all(params, batch):
    loss = jnp.mean(batch["x"] @ params["w"])
    host = loss.item()  # jaxlint: disable
    return host


@jax.jit
def wrong_rule_listed(params, batch):
    loss = jnp.mean(batch["x"] @ params["w"])
    return loss.tolist()  # jaxlint: disable=R4
