"""jaxlint fixture: R3 clean twins — zero findings expected."""

import functools

import jax
import jax.numpy as jnp


def _update(params, opt_state, batch):
    grads = jax.grad(lambda p: jnp.mean((batch["x"] @ p["w"]) ** 2))(params)
    new_params = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, params, grads)
    return new_params, opt_state


donated_step = jax.jit(_update, donate_argnums=(0,))


def train_with_copied_state(params, batches):
    # the PR 3 fix shape: copy the leaves instead of aliasing the buffer
    opt_state = {"z": jax.tree_util.tree_map(jnp.copy, params), "count": 0}
    for batch in batches:
        params, opt_state = donated_step(params, opt_state, batch)
    return params


def train_rebinds(params, batches):
    for batch in batches:
        params, _ = donated_step(params, {"count": 0}, batch)  # rebound: fine
    return params


def wrapped_call_rebinds(params, opt_state, batch):
    # black-style wrapped call: the continuation-line argument names are the
    # call's own inputs, not post-donation reads
    new_params, new_opt = donated_step(
        params,
        opt_state,
        batch,
    )
    return new_params, new_opt


@functools.partial(jax.jit, donate_argnums=(0, 1))
def sgd_step_donated(params, opt_state, grads):
    params = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, params, grads)
    return params, opt_state


@jax.jit
def forward_only(params, batch):
    # returns a fresh value, not an updated param pytree: donation optional
    return jnp.mean(batch["x"] @ params["w"])
