"""jaxlint fixture: R3 seeded violations — donation bugs.

``train_with_aliased_state`` is a faithful reconstruction of the PR 3
schedule-free bug: the optimizer state holds ``z``, a plain alias of the
param buffer, and the step donates params — one physical buffer donated
while a live reference rides in another argument.
"""

import jax
import jax.numpy as jnp


def _update(params, opt_state, batch):
    grads = jax.grad(lambda p: jnp.mean((batch["x"] @ p["w"]) ** 2))(params)
    new_params = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, params, grads)
    return new_params, opt_state


donated_step = jax.jit(_update, donate_argnums=(0,))


def train_with_aliased_state(params, batches):
    z = params  # schedule-free z iterate: aliases the param buffer
    opt_state = {"z": z, "count": 0}
    for batch in batches:
        # R3: donated arg 0 (params) is aliased inside arg 1 (opt_state)
        params, opt_state = donated_step(params, opt_state, batch)
    return params


def eval_after_donate(params, batch):
    new_params, _ = donated_step(params, {"count": 0}, batch)
    return jnp.sum(params["w"])  # R3: read after donation deleted the buffer


def train_loop_no_rebind(params, batches):
    for batch in batches:
        donated_step(params, {"count": 0}, batch)  # R3: donated, never rebound
    return params


@jax.jit
def sgd_step_no_donate(params, grads):
    params = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, params, grads)
    return params, grads  # R3 (warning): updates params, no donate_argnums
