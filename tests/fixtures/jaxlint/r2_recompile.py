"""jaxlint fixture: R2 seeded violations — recompile hazards."""

import jax
import jax.numpy as jnp

_runtime_flags = {}  # mutable module global


@jax.jit
def step_shape_branch(params, batch):
    x = batch["x"]
    if x.shape[0] > 128:  # R2: shape-derived python branch
        x = x[:128]
    return x @ params["w"]


@jax.jit
def step_unrolled_loop(params, batch):
    total = jnp.zeros(())
    for row in batch["x"]:  # R2: python loop over a traced array unrolls
        total = total + jnp.sum(row @ params["w"])
    return total


@jax.jit
def step_mutable_global(params, batch):
    scale = _runtime_flags["loss_scale"]  # R2: closure over mutable global
    return jnp.mean(batch["x"] @ params["w"]) * scale


def _inner_step(x, config):
    return x * 2


compiled_static = jax.jit(_inner_step, static_argnums=(1,))


def call_with_unhashable(x):
    return compiled_static(x, [4, 8])  # R2: unhashable static arg (list)


def call_with_varying_static(x):
    outs = []
    for width in (8, 16, 32, 64):
        outs.append(compiled_static(x, width))  # R2: loop-varying static arg
    return outs


@jax.jit
def kernel_loop_over_kv_blocks(q, kv_blocks):
    # R2: the streaming-attention mistake — python-looping over a traced
    # [nkv, bs, d] array unrolls one matmul per block and recompiles per
    # block count (the kernel grid, not python, should walk the blocks)
    acc = jnp.zeros((q.shape[0], kv_blocks.shape[2]))
    for block in kv_blocks:
        acc = acc + q @ block
    return acc
