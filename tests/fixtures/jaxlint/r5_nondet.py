"""jaxlint fixture: R5 seeded violations — nondeterminism in traced code."""

import random
import time

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def step_with_clock(params, batch):
    seed = time.time()  # R5: baked at trace time, differs per rank
    return jnp.mean(batch["x"] @ params["w"]) + seed


@jax.jit
def step_with_python_random(params, batch):
    jitter = random.random()  # R5: one frozen draw per trace
    noise = np.random.normal(size=())  # R5: numpy entropy at trace time
    return jnp.mean(batch["x"] @ params["w"]) * jitter + noise


@jax.jit
def step_with_set_iteration(params, batch):
    total = jnp.zeros(())
    for name in {"w", "b"}:  # R5: set order is unspecified per process
        total = total + jnp.sum(params[name])
    return total


def build_sharding_specs(axis_names):
    specs = {}
    for axis in set(axis_names):  # R5: unordered axes feeding sharding specs
        specs[axis] = ("data", axis)
    return specs


@jax.jit
def kernel_block_permutation(q, kv):
    # R5: trace-time numpy entropy picks the block visit order — every rank
    # compiles a DIFFERENT schedule (the block lattice must be derived from
    # traced inputs, not host randomness)
    order = np.random.permutation(4)
    total = jnp.zeros(())
    for i in order:
        total = total + jnp.sum(q[i] @ kv[i])
    return total
