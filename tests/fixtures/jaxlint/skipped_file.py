# jaxlint: skip-file — vendored-fixture stand-in: whole file exempt
"""jaxlint fixture: file-level suppression."""

import jax
import jax.numpy as jnp


@jax.jit
def anything_goes(params, batch):
    loss = jnp.mean(batch["x"] @ params["w"])
    if loss > 0:  # would be R1 without the skip-file marker
        return float(loss)
    return 0.0
