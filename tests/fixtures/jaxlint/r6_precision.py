"""jaxlint fixture: R6 seeded violations — accumulator precision."""

import jax
import jax.numpy as jnp


@jax.jit
def attn_scores_default_accum(q, k):
    # R6: bf16 q/k accumulate in bf16 — the online-softmax drift source
    return jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))


@jax.jit
def mlp_block_default_accum(x, w):
    h = jax.lax.dot_general(x, w, (((1,), (0,)), ((), ())))  # R6
    return jax.nn.relu(h)


@jax.jit
def partial_fix_second_dot(x, w1, w2):
    h = jax.lax.dot_general(
        x, w1, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    # R6: the second dot dropped the annotation the first one carries
    return jax.lax.dot_general(h, w2, (((1,), (0,)), ((), ())))
