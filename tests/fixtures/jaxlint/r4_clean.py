"""jaxlint fixture: R4 clean twins — zero findings expected."""

from accelerate_tpu.utils.jax_compat import broadcast_one_to_all
from accelerate_tpu.utils.operations import gather


def gather_then_gate(state, metrics):
    all_metrics = gather(metrics)  # every rank participates...
    if state.is_main_process:
        _write(all_metrics)  # ...only the payload handling is gated
    return all_metrics


def source_as_argument(state, x):
    # the correct spelling of "main sends": rank identity is an ARGUMENT,
    # every rank enters the collective
    return broadcast_one_to_all(x, is_source=state.process_index == 0)


def symmetric_branches(state, x, big):
    if state.is_main_process:
        y = gather(x)
    else:
        y = gather(x)  # same op both arms: schedules match
    return y


def rank_gated_io_only(state, payload):
    if not state.is_main_process:
        return None
    _write(payload)  # file IO under a rank guard, no collective
    return payload


def _write(obj):
    pass
