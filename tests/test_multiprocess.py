"""Real multi-process (multi-host protocol) tests: spawn 2 OS processes with
``jax.distributed`` rendezvous on localhost CPU and run the bundled assertion
script — executing the code paths that the in-process 8-device mesh cannot
(process boundaries, object broadcast, coordinator rendezvous, per-process RNG
checkpointing). Reference pattern: ``tests/test_multidevice.py:50-101`` +
``test_utils/scripts/test_script.py`` (``training_check:449``)."""

import json
import os

import pytest

from accelerate_tpu.test_utils.testing import execute_multiprocess

SCRIPT = ["-m", "accelerate_tpu.test_utils.scripts.multihost_script"]


@pytest.fixture(scope="module")
def shared_tmpdir(tmp_path_factory):
    return str(tmp_path_factory.mktemp("multiproc"))


class TestTwoProcesses:
    def test_topology_and_ops(self, shared_tmpdir):
        outs = execute_multiprocess(
            SCRIPT + ["--scenario", "topology,ops,local_sgd", "--tmpdir", shared_tmpdir],
            num_processes=2,
        )
        for out in outs:
            assert "ALL OK" in out, out[-2000:]

    def test_dataloader_and_dispatcher(self, shared_tmpdir):
        outs = execute_multiprocess(
            SCRIPT + ["--scenario", "dataloader,dispatcher,dispatcher_ragged",
                      "--tmpdir", shared_tmpdir],
            num_processes=2,
        )
        for out in outs:
            assert "ALL OK" in out, out[-2000:]

    def test_training_and_checkpoint(self, shared_tmpdir):
        outs = execute_multiprocess(
            SCRIPT + ["--scenario", "training,checkpoint", "--tmpdir", shared_tmpdir],
            num_processes=2,
        )
        for out in outs:
            assert "ALL OK" in out, out[-2000:]

    def test_zigzag_cp_across_processes(self, shared_tmpdir):
        """Zig-zag ring attention's lane-exchange/rotation ppermutes across a
        REAL process boundary (the pod communication pattern)."""
        outs = execute_multiprocess(
            SCRIPT + ["--scenario", "zigzag", "--tmpdir", shared_tmpdir],
            num_processes=2,
        )
        for out in outs:
            assert "ALL OK" in out, out[-2000:]

    def test_ops_three_processes(self, shared_tmpdir):
        """np=3: odd process counts exercise uneven split/pad paths that np=2
        cannot (split_between_processes remainder, pad sizes 2/3/4)."""
        outs = execute_multiprocess(
            SCRIPT + ["--scenario", "topology,ops", "--tmpdir", shared_tmpdir],
            num_processes=3,
        )
        for out in outs:
            assert "ALL OK" in out, out[-2000:]

    def test_sharded_checkpoint(self, shared_tmpdir):
        """FSDP-sharded save where no host materializes the full state, reload
        onto a refactored mesh (2 devices/process → dim-1 sharding), resume to
        identical losses."""
        outs = execute_multiprocess(
            SCRIPT + ["--scenario", "sharded_checkpoint", "--tmpdir", shared_tmpdir],
            num_processes=2,
            devices_per_process=2,
        )
        for out in outs:
            assert "ALL OK" in out, out[-2000:]

    def test_three_process_ragged_dispatcher(self, shared_tmpdir):
        """3 OS processes: the dispatcher tensor fast-path (bs 6, ragged tail
        of 3) — odd world sizes catch divisibility slips the 2-process runs
        cannot (ops at np=3 is covered by test_ops_three_processes)."""
        outs = execute_multiprocess(
            SCRIPT + ["--scenario", "dispatcher_ragged", "--tmpdir", shared_tmpdir],
            num_processes=3,
        )
        for out in outs:
            assert "ALL OK" in out, out[-2000:]

    def test_hybrid_mesh_process_granule(self, shared_tmpdir):
        """2 procs x 2 local devices: the DCN-aware hybrid mesh places
        dp_replicate across process granules and a real sharded train step
        runs over it (the single-machine analogue of a 2-slice pod)."""
        outs = execute_multiprocess(
            SCRIPT + ["--scenario", "hybrid_mesh", "--tmpdir", shared_tmpdir],
            num_processes=2,
            devices_per_process=2,
        )
        for out in outs:
            assert "ALL OK" in out, out[-2000:]
            assert "hybrid mesh (process granule) train step OK" in out, out[-2000:]

    def test_sharded_generate(self, shared_tmpdir):
        """TP-sharded KV-cache decode across 2 processes: the row-parallel psum
        rides the cross-process collective backend inside the compiled decode
        scan; tokens match a single-device dense decode exactly."""
        outs = execute_multiprocess(
            SCRIPT + ["--scenario", "generate", "--tmpdir", shared_tmpdir],
            num_processes=2,
        )
        for out in outs:
            assert "ALL OK" in out, out[-2000:]

    def test_training_parity_across_process_counts(self, shared_tmpdir):
        """Same global batch, same init → same loss trajectory for 1 vs 2
        processes (the reference's training_check parity contract)."""
        execute_multiprocess(
            SCRIPT + ["--scenario", "training", "--tmpdir", shared_tmpdir],
            num_processes=1,
        )
        execute_multiprocess(
            SCRIPT + ["--scenario", "training", "--tmpdir", shared_tmpdir],
            num_processes=2,
        )
        with open(os.path.join(shared_tmpdir, "losses_np1.json")) as f:
            l1 = json.load(f)
        with open(os.path.join(shared_tmpdir, "losses_np2.json")) as f:
            l2 = json.load(f)
        assert len(l1) == len(l2)
        for a, b in zip(l1, l2):
            assert abs(a - b) < 1e-4, (l1, l2)
