"""Model family tests: shapes, init statistics, learning, TP rule coverage."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from accelerate_tpu.models import (
    BertConfig,
    LlamaConfig,
    bert_forward,
    bert_loss,
    bert_shard_rules,
    init_bert,
    init_llama,
    llama_forward,
    llama_loss,
    llama_shard_rules,
)


def test_llama_forward_shapes_and_init_loss():
    cfg = LlamaConfig.tiny()
    params = init_llama(cfg, jax.random.PRNGKey(0))
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 32)).astype(np.int32)
    logits = llama_forward(params, ids, cfg)
    assert logits.shape == (2, 32, cfg.vocab_size)
    loss = float(llama_loss(params, {"input_ids": ids}, cfg))
    assert abs(loss - np.log(cfg.vocab_size)) < 0.5  # ~uniform at init


def test_llama_loss_mask():
    cfg = LlamaConfig.tiny()
    params = init_llama(cfg, jax.random.PRNGKey(0))
    ids = np.ones((2, 16), np.int32)
    mask = np.zeros((2, 16), np.float32)
    loss = float(llama_loss(params, {"input_ids": ids, "loss_mask": mask}, cfg))
    assert loss == 0.0


def test_llama_overfits_single_batch():
    cfg = LlamaConfig.tiny()
    params = init_llama(cfg, jax.random.PRNGKey(0))
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 32)).astype(np.int32)
    opt = optax.adam(1e-2)
    st = opt.init(params)

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(lambda p: llama_loss(p, {"input_ids": ids}, cfg))(p)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, l

    for _ in range(30):
        params, st, loss = step(params, st)
    assert float(loss) < 1.0


def test_llama_tp_rules_cover_params():
    cfg = LlamaConfig.tiny()
    params = init_llama(cfg, jax.random.PRNGKey(0))
    rules = llama_shard_rules()
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    from accelerate_tpu.parallel.sharding import _path_str

    for path, leaf in flat:
        spec = rules.match(_path_str(path))
        if leaf.ndim >= 2:
            assert spec is not None, f"no TP rule for {_path_str(path)}"


def test_llama_gqa_heads():
    cfg = LlamaConfig(vocab_size=128, dim=64, n_layers=1, n_heads=4, n_kv_heads=2, max_seq_len=64)
    params = init_llama(cfg, jax.random.PRNGKey(0))
    assert params["layers"]["wk"]["kernel"].shape == (1, 64, 2 * 16)
    ids = np.zeros((1, 8), np.int32)
    assert llama_forward(params, ids, cfg).shape == (1, 8, 128)


def test_bert_forward_and_padding_mask():
    cfg = BertConfig.tiny()
    params = init_bert(cfg, jax.random.PRNGKey(0))
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 32)).astype(np.int32)
    full = {"input_ids": ids, "attention_mask": np.ones((2, 32), np.int32)}
    # padding tokens must not change the [CLS] logits
    padded_ids = ids.copy()
    padded_ids[:, 16:] = 0
    mask = np.ones((2, 32), np.int32)
    mask[:, 16:] = 0
    out_a = bert_forward(params, {"input_ids": padded_ids, "attention_mask": mask}, cfg)
    padded_ids2 = padded_ids.copy()
    padded_ids2[:, 16:] = 7  # different garbage in masked region
    out_b = bert_forward(params, {"input_ids": padded_ids2, "attention_mask": mask}, cfg)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b), atol=1e-5)


def test_bert_loss_finite():
    cfg = BertConfig.tiny()
    params = init_bert(cfg, jax.random.PRNGKey(0))
    batch = {
        "input_ids": np.ones((4, 16), np.int32),
        "attention_mask": np.ones((4, 16), np.int32),
        "labels": np.array([0, 1, 0, 1], np.int32),
    }
    loss = float(bert_loss(params, batch, cfg))
    assert np.isfinite(loss) and abs(loss - np.log(2)) < 0.3


def test_graft_entry_contract():
    import sys

    sys.path.insert(0, "/root/repo")
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == args[1].shape[0]


@pytest.mark.slow
def test_graft_dryrun_multichip():
    import sys

    sys.path.insert(0, "/root/repo")
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)
