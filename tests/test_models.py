"""Model family tests: shapes, init statistics, learning, TP rule coverage."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from accelerate_tpu.models import (
    BertConfig,
    LlamaConfig,
    bert_forward,
    bert_loss,
    bert_shard_rules,
    init_bert,
    init_llama,
    llama_forward,
    llama_loss,
    llama_shard_rules,
)


@pytest.mark.smoke
def test_llama_forward_shapes_and_init_loss():
    cfg = LlamaConfig.tiny()
    params = init_llama(cfg, jax.random.PRNGKey(0))
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 32)).astype(np.int32)
    logits = llama_forward(params, ids, cfg)
    assert logits.shape == (2, 32, cfg.vocab_size)
    loss = float(llama_loss(params, {"input_ids": ids}, cfg))
    assert abs(loss - np.log(cfg.vocab_size)) < 0.5  # ~uniform at init


def test_llama_remat_policies_same_loss_and_grads():
    """remat=False/True/'dots'/'dots_no_batch' are pure memory/recompute
    trades — loss AND grads must match bit-for-bit-ish."""
    cfg = LlamaConfig.tiny()
    params = init_llama(cfg, jax.random.PRNGKey(0))
    ids = np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 32)).astype(np.int32)
    batch = {"input_ids": ids}

    def lg(remat):
        return jax.value_and_grad(lambda p: llama_loss(p, batch, cfg, remat=remat))(params)

    ref_loss, ref_grads = lg(False)
    for remat in (True, "nothing", "dots", "dots_no_batch", "offload_dots"):
        loss, grads = lg(remat)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            grads, ref_grads,
        )
    import pytest

    with pytest.raises(ValueError):
        llama_loss(params, batch, cfg, remat="bogus")


def test_llama_loss_mask():
    cfg = LlamaConfig.tiny()
    params = init_llama(cfg, jax.random.PRNGKey(0))
    ids = np.ones((2, 16), np.int32)
    mask = np.zeros((2, 16), np.float32)
    loss = float(llama_loss(params, {"input_ids": ids, "loss_mask": mask}, cfg))
    assert loss == 0.0


def test_llama_overfits_single_batch():
    cfg = LlamaConfig.tiny()
    params = init_llama(cfg, jax.random.PRNGKey(0))
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 32)).astype(np.int32)
    opt = optax.adam(1e-2)
    st = opt.init(params)

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(lambda p: llama_loss(p, {"input_ids": ids}, cfg))(p)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, l

    for _ in range(30):
        params, st, loss = step(params, st)
    assert float(loss) < 1.0


def test_llama_tp_rules_cover_params():
    cfg = LlamaConfig.tiny()
    params = init_llama(cfg, jax.random.PRNGKey(0))
    rules = llama_shard_rules()
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    from accelerate_tpu.parallel.sharding import _path_str

    for path, leaf in flat:
        spec = rules.match(_path_str(path))
        if leaf.ndim >= 2:
            assert spec is not None, f"no TP rule for {_path_str(path)}"


def test_llama_gqa_heads():
    cfg = LlamaConfig(vocab_size=128, dim=64, n_layers=1, n_heads=4, n_kv_heads=2, max_seq_len=64)
    params = init_llama(cfg, jax.random.PRNGKey(0))
    assert params["layers"]["wk"]["kernel"].shape == (1, 64, 2 * 16)
    ids = np.zeros((1, 8), np.int32)
    assert llama_forward(params, ids, cfg).shape == (1, 8, 128)


def test_bert_forward_and_padding_mask():
    cfg = BertConfig.tiny()
    params = init_bert(cfg, jax.random.PRNGKey(0))
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 32)).astype(np.int32)
    full = {"input_ids": ids, "attention_mask": np.ones((2, 32), np.int32)}
    # padding tokens must not change the [CLS] logits
    padded_ids = ids.copy()
    padded_ids[:, 16:] = 0
    mask = np.ones((2, 32), np.int32)
    mask[:, 16:] = 0
    out_a = bert_forward(params, {"input_ids": padded_ids, "attention_mask": mask}, cfg)
    padded_ids2 = padded_ids.copy()
    padded_ids2[:, 16:] = 7  # different garbage in masked region
    out_b = bert_forward(params, {"input_ids": padded_ids2, "attention_mask": mask}, cfg)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b), atol=1e-5)


def test_bert_loss_finite():
    cfg = BertConfig.tiny()
    params = init_bert(cfg, jax.random.PRNGKey(0))
    batch = {
        "input_ids": np.ones((4, 16), np.int32),
        "attention_mask": np.ones((4, 16), np.int32),
        "labels": np.array([0, 1, 0, 1], np.int32),
    }
    loss = float(bert_loss(params, batch, cfg))
    assert np.isfinite(loss) and abs(loss - np.log(2)) < 0.3


def test_graft_entry_contract():
    import sys

    sys.path.insert(0, "/root/repo")
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == args[1].shape[0]


@pytest.mark.slow
def test_graft_dryrun_multichip():
    import sys

    sys.path.insert(0, "/root/repo")
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


class TestResNet:
    def test_forward_shapes_and_loss(self):
        from accelerate_tpu.models import ResNetConfig, init_resnet, resnet_forward, resnet_loss

        cfg = ResNetConfig.tiny()
        params = init_resnet(cfg, jax.random.PRNGKey(0))
        x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 32, 32, 3)), jnp.float32)
        logits = resnet_forward(params, x, cfg)
        assert logits.shape == (2, cfg.num_classes)
        loss = resnet_loss(params, {"pixels": x, "labels": jnp.asarray([0, 1])}, cfg)
        assert np.isfinite(float(loss))

    def test_resnet50_param_count_matches_torch(self):
        """25.56M — the torchvision ResNet-50 count (structure parity)."""
        from accelerate_tpu.models import ResNetConfig, init_resnet

        params = init_resnet(ResNetConfig.resnet50(), jax.random.PRNGKey(0))
        n = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))
        assert abs(n - 25_557_032) < 60_000, n

    def test_overfits_single_batch(self):
        import optax

        from accelerate_tpu.models import ResNetConfig, init_resnet, resnet_loss

        cfg = ResNetConfig.tiny()
        params = init_resnet(cfg, jax.random.PRNGKey(0))
        x = jnp.asarray(np.random.default_rng(1).normal(size=(8, 32, 32, 3)), jnp.float32)
        batch = {"pixels": x, "labels": jnp.asarray(np.arange(8) % cfg.num_classes)}
        opt = optax.adam(1e-3)
        state = opt.init(params)

        @jax.jit
        def step(p, s):
            loss, g = jax.value_and_grad(lambda p: resnet_loss(p, batch, cfg))(p)
            u, s = opt.update(g, s, p)
            return optax.apply_updates(p, u), s, loss

        first = None
        for _ in range(30):
            params, state, loss = step(params, state)
            if first is None:
                first = float(loss)
        assert float(loss) < first * 0.5, (first, float(loss))

    def test_shards_under_fsdp_tp(self):
        from accelerate_tpu import Accelerator, ParallelismConfig
        from accelerate_tpu.models import (
            ResNetConfig, init_resnet, resnet_loss, resnet_shard_rules,
        )
        import optax

        acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=4, tp_size=2))
        cfg = ResNetConfig.tiny()
        params = init_resnet(cfg, jax.random.PRNGKey(0))
        params, opt = acc.prepare(params, optax.sgd(0.1), shard_rules=resnet_shard_rules())
        step = acc.prepare_train_step(lambda p, b: resnet_loss(p, b, cfg), opt)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(8, 32, 32, 3)), jnp.float32)
        batch = {"pixels": x, "labels": jnp.asarray(np.zeros(8, np.int32))}
        params, opt_state, m = step(params, opt.opt_state, batch)
        assert np.isfinite(float(np.asarray(m["loss"])))
