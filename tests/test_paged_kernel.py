"""Pallas paged-attention decode kernel (ISSUE 14): interpret-mode parity.

The kernel (``ops.flash_attention.paged_attention_decode``) walks block
tables and streams KV blocks through VMEM with online softmax; the XLA
gather path (``serving.kv_pager.paged_attention``) is the reference
semantics. These tests drive the SAME kernel through the Pallas interpreter
on CPU — identical dataflow, no TPU required — and hold the line the
acceptance criteria name: parity across scrambled non-contiguous block
tables, GQA head ratios, ragged per-slot lengths, null-block rows, and
tables aliased at a copy-on-write divergence point; plus the
``ACCELERATE_PAGED_KERNEL`` dispatch/kill-switch contract.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.generation import greedy_generate
from accelerate_tpu.models import LlamaConfig, init_llama
from accelerate_tpu.ops.flash_attention import (
    paged_attention as dispatch_paged,
    paged_attention_decode,
    paged_kernel_mode,
)
from accelerate_tpu.serving import BucketLattice, ServingEngine
from accelerate_tpu.serving.kv_pager import NULL_BLOCK, paged_attention as gather_ref

CONFIG = LlamaConfig.tiny()


def _random_paged_case(seed, *, B, H, Hkv, D, bs, nb, W, lens):
    """A pool full of garbage with each row's live tokens scattered over a
    scrambled block table; returns (q, k_pool, v_pool, tables, lens)."""
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((B, 1, H, D)).astype(np.float32)
    k_pool = rng.standard_normal((nb, bs, Hkv, D)).astype(np.float32)
    v_pool = rng.standard_normal((nb, bs, Hkv, D)).astype(np.float32)
    # hand out distinct non-null physical blocks in a scrambled order
    perm = rng.permutation(np.arange(1, nb))
    tables = np.full((B, W), NULL_BLOCK, np.int32)
    used = 0
    for b, n in enumerate(lens):
        need = -(-int(n) // bs)
        tables[b, :need] = perm[used : used + need]
        used += need
    return q, k_pool, v_pool, tables, np.asarray(lens, np.int32)


def _assert_parity(q, k_pool, v_pool, tables, lens, tol=1e-6):
    qj = jnp.asarray(q)
    kj, vj = jnp.asarray(k_pool), jnp.asarray(v_pool)
    tj = jnp.asarray(tables)
    ref = gather_ref(qj, kj, vj, tj, jnp.asarray(lens - 1)[:, None])
    out = paged_attention_decode(qj, kj, vj, tj, jnp.asarray(lens), interpret=True)
    err = float(jnp.max(jnp.abs(ref.astype(jnp.float32) - out.astype(jnp.float32))))
    assert err <= tol, f"kernel diverged from gather reference by {err}"


@pytest.mark.smoke
def test_kernel_parity_scrambled_tables_ragged_lengths():
    case = _random_paged_case(
        0, B=4, H=8, Hkv=2, D=32, bs=8, nb=24, W=5, lens=[37, 10, 40, 1]
    )
    _assert_parity(*case)


@pytest.mark.parametrize("H,Hkv", [(4, 4), (8, 4), (8, 2), (8, 1)])
def test_kernel_parity_across_gqa_ratios(H, Hkv):
    case = _random_paged_case(
        1, B=2, H=H, Hkv=Hkv, D=16, bs=4, nb=16, W=4, lens=[13, 7]
    )
    _assert_parity(*case)


def test_kernel_parity_null_block_rows():
    """Inactive batch slots point every table entry at the null block with a
    1-token length — the kernel must produce exactly what the gather
    reference produces for them (the engine discards these rows, but a NaN
    would poison the batched output buffer)."""
    q, k_pool, v_pool, tables, lens = _random_paged_case(
        2, B=3, H=4, Hkv=2, D=16, bs=4, nb=9, W=3, lens=[9, 5, 11]
    )
    tables[1, :] = NULL_BLOCK  # dead slot
    lens[1] = 1
    out = paged_attention_decode(
        jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
        jnp.asarray(tables), jnp.asarray(lens), interpret=True,
    )
    assert bool(jnp.all(jnp.isfinite(out)))
    _assert_parity(q, k_pool, v_pool, tables, lens)


def test_kernel_parity_at_cow_divergence_point():
    """Two rows share every block except the last (the post-COW layout: a
    common cached prefix, then private diverged tails) — aliased physical
    blocks across tables must read identically for the shared part and
    independently past the divergence."""
    rng = np.random.default_rng(3)
    B, H, Hkv, D, bs, nb = 2, 4, 2, 16, 4, 10
    q = rng.standard_normal((B, 1, H, D)).astype(np.float32)
    k_pool = rng.standard_normal((nb, bs, Hkv, D)).astype(np.float32)
    v_pool = rng.standard_normal((nb, bs, Hkv, D)).astype(np.float32)
    tables = np.asarray([[3, 5, 7], [3, 5, 8]], np.int32)  # diverge at block 2
    lens = np.asarray([11, 12], np.int32)
    _assert_parity(q, k_pool, v_pool, tables, lens)


def test_kernel_parity_bf16_pools_within_one_ulp():
    """bf16 pools (the engine's cache dtype): the kernel computes the whole
    softmax in f32 while the reference rounds probabilities through bf16, so
    agreement is to bf16 resolution, not bitwise."""
    case = _random_paged_case(
        4, B=2, H=4, Hkv=2, D=32, bs=8, nb=12, W=3, lens=[20, 9]
    )
    q, k_pool, v_pool, tables, lens = case
    _assert_parity(
        q.astype(jnp.bfloat16), k_pool.astype(jnp.bfloat16),
        v_pool.astype(jnp.bfloat16), tables, lens, tol=2e-2,
    )


# ---------------------------------------------------------------------------
# dispatch + kill switch


def test_paged_kernel_mode_parsing(monkeypatch):
    monkeypatch.delenv("ACCELERATE_PAGED_KERNEL", raising=False)
    assert paged_kernel_mode() == "on"
    for raw, want in [("0", "off"), ("off", "off"), ("FALSE", "off"),
                      ("1", "on"), ("interpret", "interpret")]:
        monkeypatch.setenv("ACCELERATE_PAGED_KERNEL", raw)
        assert paged_kernel_mode() == want


def test_kill_switch_path_is_byte_identical_to_reference(monkeypatch):
    """``ACCELERATE_PAGED_KERNEL=0`` must route straight to the gather
    reference — byte-identical output, the pre-kernel engine exactly."""
    q, k_pool, v_pool, tables, lens = _random_paged_case(
        5, B=2, H=4, Hkv=2, D=16, bs=4, nb=8, W=3, lens=[9, 6]
    )
    args = (jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(tables), jnp.asarray(lens - 1)[:, None])
    monkeypatch.setenv("ACCELERATE_PAGED_KERNEL", "0")
    out = dispatch_paged(*args)
    ref = gather_ref(*args)
    assert np.array_equal(np.asarray(out, np.float32), np.asarray(ref, np.float32))


def test_prefill_shapes_dispatch_to_the_prefill_kernel(monkeypatch):
    """S > 1 (chunked prefill / k-verify) now routes to the Pallas
    chunked-prefill kernel under the same mode contract as decode (ISSUE 18
    extended the kernel family past S=1; ``tests/test_prefill_kernel.py``
    owns its parity matrix) — and still matches the gather reference."""
    monkeypatch.setenv("ACCELERATE_PAGED_KERNEL", "interpret")
    rng = np.random.default_rng(6)
    q = jnp.asarray(rng.standard_normal((1, 3, 4, 16)), jnp.float32)
    k_pool = jnp.asarray(rng.standard_normal((8, 4, 2, 16)), jnp.float32)
    v_pool = jnp.asarray(rng.standard_normal((8, 4, 2, 16)), jnp.float32)
    tables = jnp.asarray([[3, 5, 1]], jnp.int32)
    qpos = jnp.asarray([[8, 9, 10]], jnp.int32)
    import importlib

    fa = importlib.import_module("accelerate_tpu.ops.flash_attention")
    calls = []
    real_prefill = fa.paged_attention_prefill

    def spy(*args, **kwargs):
        calls.append(kwargs.get("interpret", False))
        return real_prefill(*args, **kwargs)

    monkeypatch.setattr(fa, "paged_attention_prefill", spy)
    out = fa.paged_attention(q, k_pool, v_pool, tables, qpos)
    ref = gather_ref(q, k_pool, v_pool, tables, qpos)
    assert calls == [True]  # S>1 hit the prefill kernel, interpreter mode
    assert float(jnp.max(jnp.abs(out - ref))) <= 1e-6


def test_tpu_backend_dispatches_the_kernel(monkeypatch):
    """On a TPU backend with the default mode, S=1 decode must route to the
    Pallas kernel (compiled, not interpreted) — asserted by stubbing the
    kernel entry point, since CI has no TPU to compile for."""
    import importlib

    # `ops.__init__` re-exports the `flash_attention` FUNCTION under the
    # submodule's name, so attribute-style import resolves to the function —
    # fetch the module itself
    fa = importlib.import_module("accelerate_tpu.ops.flash_attention")
    calls = []

    def fake_decode(q, k_pool, v_pool, tables, lens, scale=None, *, interpret=False):
        calls.append(interpret)
        return jnp.zeros_like(q)

    monkeypatch.setattr(fa, "paged_attention_decode", fake_decode)
    monkeypatch.setattr(fa.jax, "default_backend", lambda: "tpu")
    monkeypatch.delenv("ACCELERATE_PAGED_KERNEL", raising=False)
    q = jnp.zeros((1, 1, 4, 16))
    fa.paged_attention(
        q, jnp.zeros((4, 4, 2, 16)), jnp.zeros((4, 4, 2, 16)),
        jnp.zeros((1, 2), jnp.int32), jnp.asarray([[3]], jnp.int32),
    )
    assert calls == [False]  # kernel path, compiled (not interpret) mode


def test_engine_through_interpreted_kernel_matches_reference(monkeypatch):
    """The whole serving engine with decode dispatched through the Pallas
    kernel (interpreter mode) must still match the single-stream greedy
    reference token-for-token — the CPU stand-in for the TPU dispatch
    acceptance line. f32 end to end: the kernel keeps softmax probabilities
    in f32 where the reference rounds them through the cache dtype, so at
    bf16 a near-tie argmax can legitimately flip (the bf16 tolerance test
    above owns that envelope) — at f32 the paths agree to ~1e-7 and greedy
    token streams are identical."""
    monkeypatch.setenv("ACCELERATE_PAGED_KERNEL", "interpret")
    params = init_llama(CONFIG, jax.random.PRNGKey(0))
    engine = ServingEngine(
        params, CONFIG, num_blocks=33, block_size=8, max_slots=4,
        cache_dtype=jnp.float32,
        lattice=BucketLattice(slot_buckets=(2, 4), block_buckets=(4,),
                              prefill_buckets=(32,)),
    )
    engine.warmup()
    rng = np.random.default_rng(7)
    specs = [(5, 7), (13, 11), (21, 5)]
    prompts = [rng.integers(0, CONFIG.vocab_size, (s,)).astype(np.int32)
               for s, _ in specs]
    reqs = [engine.submit(p, n, rng_seed=i)
            for i, (p, (_, n)) in enumerate(zip(prompts, specs))]
    engine.run()
    for i, ((_, n), req) in enumerate(zip(specs, reqs)):
        ref = greedy_generate(params, prompts[i][None], CONFIG, max_new_tokens=n)
        assert np.array_equal(np.asarray(ref[0]), req.output_ids()), f"request {i}"


def test_kernel_rejects_multi_token_queries():
    with pytest.raises(ValueError, match="S=1"):
        paged_attention_decode(
            jnp.zeros((1, 2, 4, 16)), jnp.zeros((4, 4, 2, 16)),
            jnp.zeros((4, 4, 2, 16)), jnp.zeros((1, 2), jnp.int32),
            jnp.asarray([5]), interpret=True,
        )
