"""Pallas chunked-prefill paged-attention kernel (ISSUE 18): parity matrix.

``ops.flash_attention.paged_attention_prefill`` extends the S=1 decode
kernel (ISSUE 14, ``tests/test_paged_kernel.py``) to S>1 query chunks: same
grid walk over the block table, but each KV block is scored against the
whole chunk with a per-query causal mask ``kv_pos <= q_position``. The XLA
gather path (``serving.kv_pager.paged_attention``) remains the reference
semantics. These tests drive the kernel through the Pallas interpreter on
CPU — identical dataflow, no TPU required — across scrambled block tables,
ragged chunk start offsets, GQA ratios, null-block rows, COW-diverged
tables, and the in-chunk causality boundary, plus the dispatch contract
and the engine end-to-end (multi-chunk prefill + k-token verify both route
through this kernel).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.generation import greedy_generate
from accelerate_tpu.models import LlamaConfig, init_llama
from accelerate_tpu.ops.flash_attention import (
    paged_attention as dispatch_paged,
    paged_attention_prefill,
)
from accelerate_tpu.serving import BucketLattice, ServingEngine
from accelerate_tpu.serving.kv_pager import NULL_BLOCK, paged_attention as gather_ref

CONFIG = LlamaConfig.tiny()


def _random_prefill_case(seed, *, B, S, H, Hkv, D, bs, nb, W, starts):
    """A pool full of garbage; each row is a mid-prefill chunk: S queries at
    positions ``starts[b] + [0..S)`` whose KV (prefix + the chunk itself,
    already landed by the engine's write-before-attend order) is scattered
    over a scrambled block table. Returns (q, k_pool, v_pool, tables, qpos).
    """
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((B, S, H, D)).astype(np.float32)
    k_pool = rng.standard_normal((nb, bs, Hkv, D)).astype(np.float32)
    v_pool = rng.standard_normal((nb, bs, Hkv, D)).astype(np.float32)
    perm = rng.permutation(np.arange(1, nb))
    tables = np.full((B, W), NULL_BLOCK, np.int32)
    qpos = np.zeros((B, S), np.int32)
    used = 0
    for b, start in enumerate(starts):
        qpos[b] = int(start) + np.arange(S)
        need = -(-(int(start) + S) // bs)
        tables[b, :need] = perm[used : used + need]
        used += need
    return q, k_pool, v_pool, tables, qpos


def _assert_parity(q, k_pool, v_pool, tables, qpos, tol=2e-6):
    # tol is 2x the decode kernel's: S>1 rows reduce over longer contexts
    # (prefix + chunk) so accumulated f32 rounding runs slightly wider
    qj = jnp.asarray(q)
    kj, vj = jnp.asarray(k_pool), jnp.asarray(v_pool)
    tj, pj = jnp.asarray(tables), jnp.asarray(qpos)
    ref = gather_ref(qj, kj, vj, tj, pj)
    out = paged_attention_prefill(qj, kj, vj, tj, pj, interpret=True)
    err = float(jnp.max(jnp.abs(ref.astype(jnp.float32) - out.astype(jnp.float32))))
    assert err <= tol, f"prefill kernel diverged from gather reference by {err}"


@pytest.mark.smoke
def test_kernel_parity_scrambled_tables_ragged_starts():
    case = _random_prefill_case(
        0, B=3, S=5, H=8, Hkv=2, D=32, bs=8, nb=12, W=5, starts=[0, 11, 30]
    )
    _assert_parity(*case)


@pytest.mark.parametrize("H,Hkv", [(4, 4), (8, 4), (8, 2), (8, 1)])
def test_kernel_parity_across_gqa_ratios(H, Hkv):
    case = _random_prefill_case(
        1, B=2, S=4, H=H, Hkv=Hkv, D=16, bs=4, nb=16, W=6, starts=[3, 17]
    )
    _assert_parity(*case)


def test_in_chunk_causality_boundary():
    """Query j must not see KV at positions > start+j even though the whole
    chunk's KV is already in the pool (the engine scatter-writes the chunk
    before attending): perturbing the LAST chunk token's KV may only change
    the last query's output."""
    q, k_pool, v_pool, tables, qpos = _random_prefill_case(
        2, B=1, S=4, H=4, Hkv=2, D=16, bs=8, nb=4, W=2, starts=[0]
    )
    out = paged_attention_prefill(
        jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
        jnp.asarray(tables), jnp.asarray(qpos), interpret=True,
    )
    # position 3 lives at slot 3 of the row's first (and only live) block
    k2, v2 = k_pool.copy(), v_pool.copy()
    k2[tables[0, 0], 3] += 1.0
    v2[tables[0, 0], 3] -= 1.0
    out2 = paged_attention_prefill(
        jnp.asarray(q), jnp.asarray(k2), jnp.asarray(v2),
        jnp.asarray(tables), jnp.asarray(qpos), interpret=True,
    )
    assert np.array_equal(np.asarray(out[:, :3]), np.asarray(out2[:, :3]))
    assert not np.allclose(np.asarray(out[:, 3]), np.asarray(out2[:, 3]))


def test_kernel_parity_null_block_rows():
    """Inactive batch slots point every table entry at the null block — the
    kernel must stay finite and match the gather reference exactly as the
    decode kernel does (a NaN would poison the batched output buffer)."""
    q, k_pool, v_pool, tables, qpos = _random_prefill_case(
        3, B=3, S=4, H=4, Hkv=2, D=16, bs=4, nb=12, W=4, starts=[9, 0, 5]
    )
    tables[1, :] = NULL_BLOCK  # dead slot
    qpos[1] = np.arange(4)
    out = paged_attention_prefill(
        jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
        jnp.asarray(tables), jnp.asarray(qpos), interpret=True,
    )
    assert bool(jnp.all(jnp.isfinite(out)))
    _assert_parity(q, k_pool, v_pool, tables, qpos)


def test_kernel_parity_at_cow_divergence_point():
    """Two rows share every block except the one their chunk lands in (the
    post-COW layout): aliased physical blocks must read identically for the
    shared prefix and independently past the divergence."""
    rng = np.random.default_rng(4)
    B, S, H, Hkv, D, bs, nb = 2, 4, 4, 2, 16, 4, 10
    q = rng.standard_normal((B, S, H, D)).astype(np.float32)
    k_pool = rng.standard_normal((nb, bs, Hkv, D)).astype(np.float32)
    v_pool = rng.standard_normal((nb, bs, Hkv, D)).astype(np.float32)
    tables = np.asarray([[3, 5, 7], [3, 5, 8]], np.int32)  # diverge at block 2
    qpos = np.asarray([[8, 9, 10, 11], [8, 9, 10, 11]], np.int32)
    _assert_parity(q, k_pool, v_pool, tables, qpos)


def test_kernel_parity_bf16_pools_within_one_ulp():
    """bf16 pools (the engine's cache dtype): the kernel keeps the whole
    softmax in f32 while the reference rounds probabilities through bf16, so
    agreement is to bf16 resolution, not bitwise."""
    q, k_pool, v_pool, tables, qpos = _random_prefill_case(
        5, B=2, S=6, H=4, Hkv=2, D=32, bs=8, nb=12, W=4, starts=[14, 2]
    )
    _assert_parity(
        q.astype(jnp.bfloat16), k_pool.astype(jnp.bfloat16),
        v_pool.astype(jnp.bfloat16), tables, qpos, tol=2e-2,
    )


def test_kernel_rejects_single_token_queries():
    with pytest.raises(ValueError, match="S>1"):
        paged_attention_prefill(
            jnp.zeros((1, 1, 4, 16)), jnp.zeros((4, 4, 2, 16)),
            jnp.zeros((4, 4, 2, 16)), jnp.zeros((1, 2), jnp.int32),
            jnp.asarray([[5]], jnp.int32), interpret=True,
        )


# ---------------------------------------------------------------------------
# dispatch + kill switch


def test_kill_switch_path_is_byte_identical_to_reference(monkeypatch):
    """``ACCELERATE_PAGED_KERNEL=0`` routes S>1 straight to the gather
    reference — byte-identical output, the pre-kernel engine exactly."""
    q, k_pool, v_pool, tables, qpos = _random_prefill_case(
        6, B=2, S=3, H=4, Hkv=2, D=16, bs=4, nb=8, W=3, starts=[6, 1]
    )
    args = (jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(tables), jnp.asarray(qpos))
    monkeypatch.setenv("ACCELERATE_PAGED_KERNEL", "0")
    out = dispatch_paged(*args)
    ref = gather_ref(*args)
    assert np.array_equal(np.asarray(out, np.float32), np.asarray(ref, np.float32))


def test_tpu_backend_dispatches_the_prefill_kernel(monkeypatch):
    """On a TPU backend with the default mode, S>1 must route to the Pallas
    prefill kernel (compiled, not interpreted) — asserted by stubbing the
    kernel entry point, since CI has no TPU to compile for."""
    import importlib

    fa = importlib.import_module("accelerate_tpu.ops.flash_attention")
    calls = []

    def fake_prefill(q, k_pool, v_pool, tables, qpos, scale=None, *, interpret=False):
        calls.append(interpret)
        return jnp.zeros_like(q)

    monkeypatch.setattr(fa, "paged_attention_prefill", fake_prefill)
    monkeypatch.setattr(fa.jax, "default_backend", lambda: "tpu")
    monkeypatch.delenv("ACCELERATE_PAGED_KERNEL", raising=False)
    q = jnp.zeros((1, 3, 4, 16))
    fa.paged_attention(
        q, jnp.zeros((4, 4, 2, 16)), jnp.zeros((4, 4, 2, 16)),
        jnp.zeros((1, 2), jnp.int32), jnp.asarray([[3, 4, 5]], jnp.int32),
    )
    assert calls == [False]  # kernel path, compiled (not interpret) mode


def test_engine_multi_chunk_prefill_through_interpreted_kernel(monkeypatch):
    """The whole serving engine with CHUNKED prefill dispatched through the
    Pallas prefill kernel (interpreter mode) must match the single-stream
    greedy reference token-for-token. Prefill buckets are capped below the
    longest prompt so every long request runs multiple S>1 chunks, each
    attending back across earlier chunks' landed KV through the kernel."""
    monkeypatch.setenv("ACCELERATE_PAGED_KERNEL", "interpret")
    params = init_llama(CONFIG, jax.random.PRNGKey(0))
    engine = ServingEngine(
        params, CONFIG, num_blocks=33, block_size=8, max_slots=4,
        cache_dtype=jnp.float32,
        lattice=BucketLattice(slot_buckets=(2, 4), block_buckets=(4,),
                              prefill_buckets=(8, 16)),
    )
    engine.warmup()
    rng = np.random.default_rng(8)
    specs = [(21, 6), (5, 5), (17, 4)]  # 21 → chunks of 16 + 5; 17 → 16 + 1
    prompts = [rng.integers(0, CONFIG.vocab_size, (s,)).astype(np.int32)
               for s, _ in specs]
    reqs = [engine.submit(p, n, rng_seed=i)
            for i, (p, (_, n)) in enumerate(zip(prompts, specs))]
    engine.run()
    for i, ((_, n), req) in enumerate(zip(specs, reqs)):
        ref = greedy_generate(params, prompts[i][None], CONFIG, max_new_tokens=n)
        assert np.array_equal(np.asarray(ref[0]), req.output_ids()), f"request {i}"
