"""The bench's baseline-anchoring must never lose the driver's number: these
pin the pure bookkeeping (``bench.apply_baseline_anchors``) that runs between
measurement and the final JSON line."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import apply_baseline_anchors, sanitize_json


def _result(per_chip=1000.0):
    return {"per_chip": per_chip, "model": "bert-base", "backend": "tpu"}


class TestBaselineAnchors:
    def test_first_run_seeds_all_anchors(self, tmp_path):
        path = str(tmp_path / "b.json")
        configs = {"resnet_dp": {"value": 500.0}, "inference": {"value": 0.0}}
        ratio = apply_baseline_anchors(_result(), configs, path)
        assert ratio == 1.0
        saved = json.load(open(path))
        assert saved["per_chip"] == 1000.0
        assert saved["configs"] == {"resnet_dp": 500.0}  # zero values never anchor
        assert "vs_baseline" not in configs["resnet_dp"]

    def test_second_run_reports_ratios(self, tmp_path):
        path = str(tmp_path / "b.json")
        apply_baseline_anchors(_result(1000.0), {"resnet_dp": {"value": 500.0}}, path)
        configs = {"resnet_dp": {"value": 600.0}, "fsdp_lm": {"value": 70.0}}
        ratio = apply_baseline_anchors(_result(1500.0), configs, path)
        assert ratio == 1.5
        assert configs["resnet_dp"]["vs_baseline"] == 1.2
        # new config on a later run: anchored now, ratio next time
        saved = json.load(open(path))
        assert saved["configs"]["fsdp_lm"] == 70.0
        assert "vs_baseline" not in configs["fsdp_lm"]

    def test_remat_policy_mismatch_noted(self, tmp_path):
        """Self-tuning configs: anchor remembers the policy; a run that fell
        back to a different policy flags its ratio as non-comparable."""
        path = str(tmp_path / "b.json")
        apply_baseline_anchors(
            _result(), {"fsdp_lm": {"value": 100.0, "remat": "dots_no_batch"}}, path
        )
        saved = json.load(open(path))
        assert saved["configs_meta"]["fsdp_lm"] == {"remat": "dots_no_batch"}
        configs = {"fsdp_lm": {"value": 80.0, "remat": "True"}}
        apply_baseline_anchors(_result(), configs, path)
        assert configs["fsdp_lm"]["vs_baseline"] == 0.8
        assert "dots_no_batch" in configs["fsdp_lm"]["vs_baseline_note"]
        # same policy → no note
        configs = {"fsdp_lm": {"value": 110.0, "remat": "dots_no_batch"}}
        apply_baseline_anchors(_result(), configs, path)
        assert "vs_baseline_note" not in configs["fsdp_lm"]

    def test_legacy_headline_only_baseline(self, tmp_path):
        """Round-2's file has only per_chip; configs get added without
        touching the headline anchor."""
        path = str(tmp_path / "b.json")
        json.dump({"per_chip": 852.4, "model": "bert-base"}, open(path, "w"))
        configs = {"long_context": {"value": 22586.0}}
        ratio = apply_baseline_anchors(_result(1796.7), configs, path)
        assert round(ratio, 3) == round(1796.7 / 852.4, 3)
        saved = json.load(open(path))
        assert saved["per_chip"] == 852.4
        assert saved["configs"]["long_context"] == 22586.0

    def test_corrupt_baseline_reanchors_instead_of_crashing(self, tmp_path):
        path = str(tmp_path / "b.json")
        with open(path, "w") as f:
            f.write('{"per_chip": 10')  # truncated by a killed writer
        ratio = apply_baseline_anchors(_result(), {"resnet_dp": {"value": 5.0}}, path)
        assert ratio == 1.0
        assert json.load(open(path))["per_chip"] == 1000.0

    def test_sanitize_strips_non_finite(self):
        configs = {"a": {"final_loss": float("nan"), "value": 1.0,
                         "list": [float("inf"), 2.0]}}
        out = json.dumps(sanitize_json(configs), allow_nan=False)  # must not raise
        assert json.loads(out) == {"a": {"final_loss": None, "value": 1.0,
                                         "list": [None, 2.0]}}

    def test_nan_values_never_anchor_or_divide(self, tmp_path):
        path = str(tmp_path / "b.json")
        nan = float("nan")
        configs = {"fsdp_lm": {"value": nan}}
        ratio = apply_baseline_anchors(_result(nan), configs, path)
        assert ratio == 1.0
        saved = json.load(open(path)) if os.path.exists(path) else {}
        assert "per_chip" not in saved and saved.get("configs", {}) == {}
        # nan against an existing finite anchor: ratio 0, anchor untouched
        json.dump({"per_chip": 1000.0, "configs": {"fsdp_lm": 50.0}}, open(path, "w"))
        configs = {"fsdp_lm": {"value": nan}}
        apply_baseline_anchors(_result(), configs, path)
        assert configs["fsdp_lm"]["vs_baseline"] == 0.0
        assert json.load(open(path))["configs"]["fsdp_lm"] == 50.0

    def test_nan_headline_vs_real_anchor_is_failure_sentinel(self, tmp_path):
        path = str(tmp_path / "b.json")
        json.dump({"per_chip": 1000.0}, open(path, "w"))
        ratio = apply_baseline_anchors(_result(float("nan")), {}, path)
        assert ratio == 0.0  # failed run must not read as "at baseline"

    def test_malformed_env_knobs_fall_back(self, monkeypatch):
        from bench import _env_int

        monkeypatch.setenv("ACCELERATE_BENCH_RETRIES", "three")
        assert _env_int("ACCELERATE_BENCH_RETRIES", 4) == 4
        monkeypatch.setenv("ACCELERATE_BENCH_RETRIES", "")
        assert _env_int("ACCELERATE_BENCH_RETRIES", 4) == 4
        monkeypatch.setenv("ACCELERATE_BENCH_RETRIES", "2")
        assert _env_int("ACCELERATE_BENCH_RETRIES", 4) == 2

    def test_wrong_shaped_baseline_reanchors(self, tmp_path):
        path = str(tmp_path / "b.json")
        json.dump([1, 2, 3], open(path, "w"))  # valid JSON, wrong shape
        ratio = apply_baseline_anchors(_result(), {"resnet_dp": {"value": 5.0}}, path)
        assert ratio == 1.0
        assert json.load(open(path))["per_chip"] == 1000.0
        json.dump({"per_chip": 1000.0, "configs": "oops"}, open(path, "w"))
        configs = {"resnet_dp": {"value": 5.0}}
        apply_baseline_anchors(_result(), configs, path)
        assert json.load(open(path))["configs"] == {"resnet_dp": 5.0}

    def test_errored_config_entries_are_harmless(self, tmp_path):
        path = str(tmp_path / "b.json")
        configs = {"inference": {"metric": "inference", "value": 0.0, "error": "boom"}}
        apply_baseline_anchors(_result(), configs, path)
        saved = json.load(open(path))
        assert saved["configs"] == {}
        # and an errored run against an existing anchor reports ratio 0, not a crash
        json.dump({"per_chip": 1000.0, "configs": {"inference": 50.0}}, open(path, "w"))
        configs = {"inference": {"value": 0.0, "error": "boom"}}
        apply_baseline_anchors(_result(), configs, path)
        assert configs["inference"]["vs_baseline"] == 0.0


class TestAnchorNotes:
    def test_headline_batch_size_mismatch_noted(self, tmp_path):
        path = str(tmp_path / "b.json")
        json.dump({"per_chip": 800.0, "model": "bert-base", "batch_size": 64}, open(path, "w"))
        result = _result()
        result["batch_size"] = 256
        apply_baseline_anchors(result, {}, path)
        assert "batch size differs" in result.get("vs_baseline_note", "")

    def test_headline_anchor_seeds_batch_size(self, tmp_path):
        path = str(tmp_path / "b.json")
        result = _result()
        result["batch_size"] = 128
        apply_baseline_anchors(result, {}, path)
        assert json.load(open(path))["batch_size"] == 128

    def test_null_config_value_gives_null_ratio(self, tmp_path):
        path = str(tmp_path / "b.json")
        json.dump({"per_chip": 800.0, "configs": {"compile_time_llama1b": 5.0}}, open(path, "w"))
        configs = {"compile_time_llama1b": {"value": None, "note": "budget blown"}}
        apply_baseline_anchors(_result(), configs, path)
        assert configs["compile_time_llama1b"]["vs_baseline"] is None


class TestProbeRecovery:
    """Round-4 hardening: probe failure reasons are captured and the degraded
    path can adopt a recovered-TPU child run's output — but ONLY a real one."""

    def test_pick_tpu_json_line_accepts_real_tpu_result(self):
        from bench import _pick_tpu_json_line

        good = json.dumps({"value": 1250.0, "device_kind": "TPU v5 lite", "n_chips": 1})
        out = "\n".join(["progress noise", good])
        assert _pick_tpu_json_line(out) == json.loads(good)  # parsed dict

    def test_pick_tpu_json_line_rejects_cpu_degraded_and_cached(self):
        from bench import _pick_tpu_json_line

        cpu = json.dumps({"value": 49.0, "device_kind": "cpu"})
        degraded = json.dumps(
            {"value": 10.0, "device_kind": "TPU v5 lite", "degraded": "probe failed"}
        )
        # cached lines must not be re-presented as freshly measured (a child
        # that degraded and emitted the watcher cache would otherwise launder
        # an hours-old number)
        cached = json.dumps(
            {"value": 11.0, "device_kind": "TPU v5 lite", "cached": True}
        )
        assert _pick_tpu_json_line("\n".join([cpu, degraded, cached])) is None
        assert _pick_tpu_json_line("not json\n{broken") is None
        assert _pick_tpu_json_line("") is None
        # a partial (incremental) line is still usable — the picker's caller
        # strips the flag on promotion to final
        partial = json.dumps(
            {"value": 12.0, "device_kind": "TPU v5 lite", "partial": True}
        )
        assert _pick_tpu_json_line(partial)["value"] == 12.0

    def test_probe_subprocess_reports_detail(self):
        from bench import _probe_backend_subprocess

        # tiny timeout: the contract under test is the (ok, detail) shape, and
        # on a dead tunnel a long timeout just stalls the suite for its full
        # length (observed: this one test cost the core shard 60s)
        ok, detail = _probe_backend_subprocess(timeout=5)
        assert isinstance(ok, bool) and isinstance(detail, str)
        if not ok:
            assert detail  # a failed probe must say why


class TestPerConfigMfu:
    """VERDICT r04 item 2: every config must report utilization on TPU. The
    arithmetic is exercised here by faking the peak-FLOPs lookup (CPU reports
    no peak, so the fields gate on it). Since ISSUE 7 the lookup lives in the
    shared telemetry perf registry — bench-local call sites patch through
    ``bench.device_peak_flops``, the LM configs go through
    ``telemetry.perf.lm_train_mfu`` whose module global is patched instead."""

    def test_resnet_reports_mfu_when_peak_known(self, monkeypatch):
        import bench

        monkeypatch.setattr(bench, "device_peak_flops", lambda d: 1e12)
        out = bench.run_bench_resnet(on_tpu=False)
        assert out.get("mfu") is not None and out["mfu"] > 0
        # XLA reports bytes too: the conv step gets a roofline placement
        assert out.get("roofline") in ("compute-bound", "hbm-bound")
        assert out.get("arithmetic_intensity", 0) > 0

    def test_grad_accum_reports_mfu_when_peak_known(self, monkeypatch):
        import bench
        from accelerate_tpu.telemetry import perf

        monkeypatch.setattr(perf, "device_peak_flops", lambda d: 1e12)
        out = bench.run_bench_grad_accum(on_tpu=False)
        assert out.get("mfu") is not None and out["mfu"] > 0

    def test_inference_reports_mfu_and_roofline(self, monkeypatch):
        import bench

        monkeypatch.setattr(bench, "device_peak_flops", lambda d: 1e12)
        monkeypatch.setattr(bench, "device_hbm_bandwidth", lambda d: 819e9)
        out = bench.run_bench_inference(on_tpu=False)
        assert out.get("mfu") is not None and out["mfu"] > 0
        assert out.get("hbm_roofline_frac") is not None and out["hbm_roofline_frac"] > 0

    def test_bench_has_no_private_peak_table(self):
        """ISSUE 7 ratchet: bench.py must consume the shared telemetry/perf
        registry — a reintroduced private table could silently diverge."""
        import bench

        assert not hasattr(bench, "_PEAK_FLOPS")
        assert not hasattr(bench, "_HBM_BW")
        assert not hasattr(bench, "_lm_train_mfu")
        assert not hasattr(bench, "_peak_flops")
        assert not hasattr(bench, "_train_flops_per_sample")


class TestProbeLadderBudget:
    """Round-5 contract: probing can never starve the measurement phase
    (round-4 lost the round's data to an unbounded ladder)."""

    KNOBS = ("ACCELERATE_BENCH_RETRIES", "ACCELERATE_BENCH_PROBE_TIMEOUT",
             "ACCELERATE_BENCH_PROBE_BUDGET", "ACCELERATE_BENCH_BUDGET")

    def _fresh_bench(self, monkeypatch):
        import importlib.util
        import os as _os

        # inherited operator knobs (the watcher exports several) must not
        # skew the default-behavior assertions
        for knob in self.KNOBS:
            monkeypatch.delenv(knob, raising=False)
        spec = importlib.util.spec_from_file_location(
            "bench_fresh", _os.path.join(_os.path.dirname(_os.path.dirname(
                _os.path.abspath(__file__))), "bench.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_failed_probes_fall_back_within_bounded_attempts(self, monkeypatch):
        bench = self._fresh_bench(monkeypatch)
        calls, sleeps = [], []
        monkeypatch.setattr(bench, "_probe_backend_subprocess",
                            lambda t: (calls.append(t) or (False, "hung (fake)")))
        monkeypatch.setattr(bench.time, "sleep", lambda s: sleeps.append(s))
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        backend = bench._init_backend()
        assert backend == "cpu"  # degraded fallback, no exception
        assert bench._BACKEND_DEGRADED is not None
        assert len(calls) == 2  # default retries capped at 2 (was 8 in r4)
        assert sum(sleeps) <= 60  # no multi-minute backoff ladders
        assert all(t <= 150 for t in calls)  # per-probe timeout capped

    def test_probe_budget_caps_attempts_even_with_high_retries(self, monkeypatch):
        bench = self._fresh_bench(monkeypatch)
        # simulate a nearly-exhausted global budget: probe phase gets the floor
        monkeypatch.setattr(bench, "_remaining", lambda: 150.0)
        calls = []
        clock = {"now": 1000.0}

        def fake_probe(t):
            calls.append(t)
            clock["now"] += t  # each probe burns its full timeout
            return False, "hung (fake)"

        monkeypatch.setattr(bench, "_probe_backend_subprocess", fake_probe)
        monkeypatch.setattr(bench.time, "time", lambda: clock["now"])
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        monkeypatch.setenv("ACCELERATE_BENCH_RETRIES", "8")
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        bench._init_backend()
        # the ~60s probe floor admits one full-length probe, then the
        # budget-break path fires: attempts are CAPPED well below retries=8
        assert len(calls) < 8, calls
        assert all(t <= 60 for t in calls), calls
        assert any("probe budget exhausted" in h for h in bench._PROBE_HISTORY)

    def test_probe_history_records_reasons(self, monkeypatch):
        bench = self._fresh_bench(monkeypatch)
        monkeypatch.setattr(bench, "_probe_backend_subprocess",
                            lambda t: (False, "rc=1: tunnel down"))
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        bench._init_backend()
        assert any("tunnel down" in h for h in bench._PROBE_HISTORY)


@pytest.mark.slow
def test_degraded_bench_end_to_end_contract(tmp_path):
    """THE round-5 contract, end to end in a real subprocess: with the TPU
    unreachable and a tight budget, bench.py must still exit 0 within the
    budget, emit multiple cumulative JSON lines (a driver kill at any point
    keeps data), mark the run degraded with probe reasons, skip configs with
    budget notes instead of dying mid-flight, and finish with a non-partial
    parseable record."""
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(
        os.environ,
        JAX_PLATFORMS="tpu_nonexistent",  # deterministic probe failure
        ACCELERATE_BENCH_BUDGET="150",
        ACCELERATE_BENCH_RETRIES="1",
        ACCELERATE_BENCH_PROBE_TIMEOUT="20",
    )
    env.pop("ACCELERATE_BENCH_TRACE", None)
    res = subprocess.run(
        [_sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True, text=True, timeout=280, env=env, cwd=str(tmp_path),
    )
    assert res.returncode == 0, res.stderr[-1500:]
    lines = [l for l in res.stdout.splitlines() if l.startswith("{")]
    assert len(lines) >= 2, "must emit incrementally, not one final line"
    for line in lines:
        json.loads(line)  # every emitted line is parseable on its own
    final = json.loads(lines[-1])
    assert final.get("partial") is None  # the record is not marked superseded
    assert final["value"] > 0  # a real CPU measurement, not a zero sentinel
    assert final.get("degraded"), "TPU-unreachable run must be labelled"
    assert final.get("probe_history"), "the failure reasons must be recorded"
    notes = [c.get("note", "") for c in final["configs"].values()]
    assert any("budget exhausted" in n for n in notes), (
        "tight budget must skip configs with notes, not run past the deadline"
    )
