"""Tests for ``accelerate_tpu.analysis`` (jaxlint).

The fixture corpus under ``tests/fixtures/jaxlint/`` seeds violations per
rule (plus clean near-miss twins); the acceptance bar is **zero false
negatives on the seeded set and zero findings on the twins** — including a
reconstruction of the PR 3 donation-aliasing bug (r3_donation.py) and an
``if is_main_process: gather(...)`` deadlock (r4_collectives.py).

Also covers suppression/baseline semantics, the JSON output schema, the
flight-recorder collective-fingerprint cross-check for R4, and (smoke) that
``make lint`` passes on the repo itself.
"""

import json
import os
import shutil
import subprocess
import sys

import pytest

from accelerate_tpu.analysis import (
    Severity,
    build_package_index,
    discover_traced,
    run_lint,
    write_baseline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "jaxlint")


def _fixture(name: str) -> str:
    return os.path.join(FIXTURES, name)


def _lint(*names, rules=None):
    return run_lint([_fixture(n) for n in names], rules=rules, use_baseline=False)


def _symbols(result, rule):
    """Top-level function names carrying new findings of ``rule``."""
    return {
        f.symbol.split(".")[0]
        for f in result.new_findings
        if f.rule == rule and f.symbol
    }


# --------------------------------------------------------------- discovery --


def test_traced_region_discovery():
    pkg = build_package_index([FIXTURES])
    region = discover_traced(pkg)
    root_names = {q for (_m, q) in region.roots}
    # decorator form, call form, and partial form are all wrap points
    assert "step_with_item" in root_names  # @jax.jit
    assert "_update" in root_names  # jax.jit(_update, donate_argnums=...)
    assert "sgd_step_donated" in root_names  # @functools.partial(jax.jit, ...)
    # a helper only *called* from a root is traced but not a root
    traced_names = {q for (_m, q) in region.traced}
    assert "traced_helper" in traced_names
    assert ("r1_host_sync", "traced_helper") not in region.roots


def test_donation_spec_parsed():
    pkg = build_package_index([_fixture("r3_donation.py")])
    region = discover_traced(pkg)
    spec = region.roots[("r3_donation", "_update")]
    assert spec.donate_argnums == (0,)


def test_eager_call_to_raw_function_is_not_a_donated_site(tmp_path):
    """`f(...)` where `step = jax.jit(f, donate_argnums=...)` exists is an
    EAGER call — it donates nothing and must not trip use-after-donate."""
    (tmp_path / "m.py").write_text(
        "import jax\nimport jax.numpy as jnp\n\n"
        "def train_step(params, batch):\n"
        "    return params\n\n"
        "step = jax.jit(train_step, donate_argnums=(0,))\n\n"
        "def eager_debug(params, batch):\n"
        "    out = train_step(params, batch)\n"
        "    norm = jnp.sum(params['w'])\n"
        "    return out, norm\n"
    )
    result = run_lint([str(tmp_path)], use_baseline=False)
    assert [f for f in result.new_findings if f.rule == "R3"] == [], [
        f.message for f in result.new_findings
    ]


def test_tuple_of_names_donate_argnums_counts_as_donating(tmp_path):
    """`donate_argnums=(A, B)` with module constants still reads as
    configured donation."""
    (tmp_path / "m.py").write_text(
        "import jax\n\n"
        "A, B = 0, 1\n\n"
        "def train_step(params, opt_state, batch):\n"
        "    return params, opt_state\n\n"
        "step = jax.jit(train_step, donate_argnums=(A, B))\n"
    )
    result = run_lint([str(tmp_path)], use_baseline=False)
    assert [f for f in result.new_findings if f.rule == "R3"] == []


def test_non_literal_donate_argnums_counts_as_donating(tmp_path):
    """`donate_argnums=DONATE` (a variable) must not read as 'no donation' —
    R3's missing-donation warning would fail lint on correct code."""
    (tmp_path / "m.py").write_text(
        "import jax\nimport jax.numpy as jnp\n\n"
        "DONATE = (0, 1)\n\n"
        "def train_step(params, opt_state, batch):\n"
        "    params = jax.tree_util.tree_map(lambda p: p - 0.1, params)\n"
        "    return params, opt_state\n\n"
        "step = jax.jit(train_step, donate_argnums=DONATE)\n"
    )
    result = run_lint([str(tmp_path)], use_baseline=False)
    assert [f for f in result.new_findings if f.rule == "R3"] == []


def test_r4_order_swapped_and_elif_schedules_flagged(tmp_path):
    """Equal op *multisets* are not symmetry: order swaps and elif chains
    with no final else both deadlock and must be flagged."""
    (tmp_path / "m.py").write_text(
        "from accelerate_tpu.utils.operations import gather, reduce\n\n"
        "def order_swapped(state, x):\n"
        "    if state.is_main_process:\n"
        "        a = gather(x)\n"
        "        b = reduce(x)\n"
        "    else:\n"
        "        b = reduce(x)\n"
        "        a = gather(x)\n"
        "    return a, b\n\n"
        "def elif_no_else(state, x):\n"
        "    if state.process_index == 0:\n"
        "        return gather(x)\n"
        "    elif state.process_index == 1:\n"
        "        return gather(x)\n"
        "    return None\n\n"
        "def symmetric(state, x):\n"
        "    if state.is_main_process:\n"
        "        y = gather(x)\n"
        "    else:\n"
        "        y = gather(x)\n"
        "    return y\n"
    )
    result = run_lint([str(tmp_path)], use_baseline=False)
    assert {f.symbol for f in result.new_findings if f.rule == "R4"} == {
        "order_swapped",
        "elif_no_else",
    }


# ------------------------------------------------------- per-rule fixtures --


def test_r1_zero_false_negatives():
    result = _lint("r1_host_sync.py")
    assert _symbols(result, "R1") == {
        "step_with_item",
        "step_with_float",
        "step_with_branch",
        "step_with_asarray",
        "step_with_device_get",
        "traced_helper",
    }
    assert all(
        f.severity == Severity.ERROR for f in result.new_findings if f.rule == "R1"
    )


def test_r2_zero_false_negatives():
    result = _lint("r2_recompile.py")
    assert _symbols(result, "R2") == {
        "step_shape_branch",
        "step_unrolled_loop",
        "step_mutable_global",
        "call_with_unhashable",
        "call_with_varying_static",
        "kernel_loop_over_kv_blocks",
    }


def test_r3_zero_false_negatives_incl_pr3_reconstruction():
    result = _lint("r3_donation.py")
    assert _symbols(result, "R3") == {
        "train_with_aliased_state",
        "eval_after_donate",
        "train_loop_no_rebind",
        "sgd_step_no_donate",
    }
    # the PR 3 shape specifically: donated params aliased inside opt_state,
    # reported as an ERROR naming the shared buffer
    aliased = [
        f
        for f in result.new_findings
        if f.rule == "R3" and f.symbol == "train_with_aliased_state"
    ]
    assert len(aliased) == 1
    assert aliased[0].severity == Severity.ERROR
    assert "params" in aliased[0].message and "alias" in aliased[0].message


def test_r4_zero_false_negatives_incl_main_process_gather():
    result = _lint("r4_collectives.py")
    assert _symbols(result, "R4") == {
        "save_metrics_deadlock",
        "checkpoint_guarded",
        "log_through_helper",
        "ternary_gather",
        "shortcircuit_broadcast",
        "asymmetric_branches",
    }
    # the issue's canonical deadlock: `if is_main_process: gather(...)`
    canonical = [
        f
        for f in result.new_findings
        if f.rule == "R4" and f.symbol == "save_metrics_deadlock"
    ]
    assert canonical and canonical[0].severity == Severity.ERROR
    assert "gather" in canonical[0].message
    # the early-return variant names the guard line
    guarded = [
        f
        for f in result.new_findings
        if f.rule == "R4" and f.symbol == "checkpoint_guarded"
    ]
    assert guarded and "early return" in guarded[0].message


def test_r5_zero_false_negatives():
    result = _lint("r5_nondet.py")
    assert _symbols(result, "R5") == {
        "step_with_clock",
        "step_with_python_random",
        "step_with_set_iteration",
        "build_sharding_specs",
        "kernel_block_permutation",
    }


def test_r6_zero_false_negatives():
    """Every bare dot_general in the traced fixtures is flagged — including
    the partially-fixed function where only the FIRST dot carries the
    annotation (the review-pressure shape: the fix that only lands once)."""
    result = _lint("r6_precision.py")
    assert _symbols(result, "R6") == {
        "attn_scores_default_accum",
        "mlp_block_default_accum",
        "partial_fix_second_dot",
    }
    assert all(
        f.severity == Severity.WARNING
        for f in result.new_findings
        if f.rule == "R6"
    )
    # the partially-fixed fn yields exactly ONE finding (the annotated dot
    # must not be flagged)
    partial = [f for f in result.new_findings if f.symbol == "partial_fix_second_dot"]
    assert len(partial) == 1


@pytest.mark.parametrize(
    "twin",
    ["r1_clean.py", "r2_clean.py", "r3_clean.py", "r4_clean.py", "r5_clean.py",
     "r6_clean.py"],
)
def test_clean_twins_produce_zero_findings(twin):
    result = _lint(twin)
    assert result.new_findings == [], [
        (f.rule, f.location(), f.message) for f in result.new_findings
    ]


def test_rule_subset_selection():
    result = _lint("r1_host_sync.py", "r4_collectives.py", rules=["R4"])
    assert {f.rule for f in result.new_findings} == {"R4"}


def test_unknown_rule_id_is_an_error():
    """A --rules typo must not turn the lint into a vacuous pass."""
    with pytest.raises(ValueError, match="R9"):
        _lint("r1_host_sync.py", rules=["R9"])
    res = _run_cli("lint", _fixture("r1_host_sync.py"), "--no-baseline", "--rules", "R9")
    assert res.returncode == 2
    assert "unknown rule" in res.stderr


def test_module_level_jit_call_sites_checked(tmp_path):
    """An unhashable static arg at a MODULE-LEVEL call site is the same
    runtime TypeError as one inside a function — both must be flagged."""
    (tmp_path / "m.py").write_text(
        "import jax\n\n"
        "def _inner(x, config):\n"
        "    return x * 2\n\n"
        "step = jax.jit(_inner, static_argnums=(1,))\n\n"
        "def in_function(x):\n"
        "    return step(x, [4, 8])\n\n"
        "warmup = step(0, [4, 8])\n"
    )
    result = run_lint([str(tmp_path)], use_baseline=False)
    unhashable = [
        f for f in result.new_findings if f.rule == "R2" and "unhashable" in f.message
    ]
    assert len(unhashable) == 2, [(f.line, f.symbol) for f in unhashable]
    assert {f.symbol for f in unhashable} == {"in_function", ""}


def test_module_level_donated_call_site_checked(tmp_path):
    """The PR 3 aliasing shape at script level (scope None) must be caught."""
    (tmp_path / "m.py").write_text(
        "import jax\n\n"
        "def f(params, opt_state):\n"
        "    return params, opt_state\n\n"
        "step = jax.jit(f, donate_argnums=(0,))\n"
        "params = {'w': 1}\n"
        "out = step(params, {'z': params})\n"
    )
    result = run_lint([str(tmp_path)], use_baseline=False)
    aliased = [
        f for f in result.new_findings if f.rule == "R3" and "alias" in f.message
    ]
    assert len(aliased) == 1 and aliased[0].symbol == ""


def test_init_py_relative_imports_resolve(tmp_path):
    """`from .mod import helper` inside a package __init__ must resolve one
    level INTO the package, not above it — traced-region BFS depends on it."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "def helper(logits):\n"
        "    return logits.tolist()\n"
    )
    (pkg / "__init__.py").write_text(
        "import jax\nfrom .mod import helper\n\n"
        "@jax.jit\ndef step(params, batch):\n"
        "    return helper(batch['x'] @ params['w'])\n"
    )
    result = run_lint([str(tmp_path)], use_baseline=False)
    assert {f.symbol for f in result.new_findings if f.rule == "R1"} == {"helper"}


def test_r4_conditional_inside_arm_is_not_symmetric(tmp_path):
    """A sometimes-executed collective in one arm vs an unconditional one in
    the other deadlocks on the steps where the condition is false."""
    (tmp_path / "m.py").write_text(
        "from accelerate_tpu.utils.operations import gather\n\n"
        "def sometimes(state, step, metrics):\n"
        "    if state.is_main_process:\n"
        "        if step % 100 == 0:\n"
        "            gather(metrics)\n"
        "    else:\n"
        "        gather(metrics)\n\n"
        "def both_conditional(state, step, metrics):\n"
        "    if state.is_main_process:\n"
        "        if step % 100 == 0:\n"
        "            gather(metrics)\n"
        "    else:\n"
        "        if step % 100 == 0:\n"
        "            gather(metrics)\n"
    )
    result = run_lint([str(tmp_path)], use_baseline=False)
    assert {f.symbol for f in result.new_findings if f.rule == "R4"} == {"sometimes"}


def test_r2_loop_varying_static_arg_at_module_level(tmp_path):
    (tmp_path / "m.py").write_text(
        "import jax\n\n"
        "def step(x, width):\n"
        "    return x * 2\n\n"
        "jstep = jax.jit(step, static_argnums=(1,))\n"
        "for n in range(100):\n"
        "    jstep(1.0, n)\n"
    )
    result = run_lint([str(tmp_path)], use_baseline=False)
    varying = [
        f
        for f in result.new_findings
        if f.rule == "R2" and "loop variable" in f.message
    ]
    assert len(varying) == 1 and varying[0].symbol == ""


def test_same_named_files_all_scanned(tmp_path):
    """util.py in two non-package dirs must not shadow each other."""
    for sub in ("a", "b"):
        d = tmp_path / sub
        d.mkdir()
        (d / "util.py").write_text(
            "import jax\nimport jax.numpy as jnp\n\n"
            f"@jax.jit\ndef f_{sub}(params, batch):\n"
            "    return float(jnp.mean(params['w']))\n"
        )
    result = run_lint([str(tmp_path / "a"), str(tmp_path / "b")], use_baseline=False)
    assert result.stats["files"] == 2
    assert {f.symbol for f in result.new_findings} == {"f_a", "f_b"}


# ------------------------------------------------- suppressions + baseline --


def test_inline_suppressions():
    result = _lint("suppressed.py")
    suppressed = [f for f in result.findings if f.suppressed]
    assert {f.symbol.split(".")[0] for f in suppressed} == {
        "tolerated_sync",
        "tolerated_all",
    }
    # a disable listing the WRONG rule does not cover the finding
    assert _symbols(result, "R1") == {"wrong_rule_listed"}


def test_skip_file_suppresses_everything():
    result = _lint("skipped_file.py")
    assert result.new_findings == []
    assert any(f.suppressed for f in result.findings)


def test_baseline_roundtrip_and_ratchet(tmp_path):
    work = tmp_path / "pkg"
    work.mkdir()
    shutil.copy(_fixture("r1_host_sync.py"), work / "legacy.py")
    baseline = tmp_path / "jaxlint-baseline.json"

    first = run_lint([str(work)], use_baseline=False)
    n = len(first.new_findings)
    assert n > 0
    write_baseline(first.findings, str(baseline))

    # baselined run: everything covered, nothing new
    second = run_lint([str(work)], baseline_path=str(baseline))
    assert second.new_findings == []
    assert second.summary()["baselined"] == n

    # line moves don't invalidate the baseline (fingerprints are line-free)
    src = (work / "legacy.py").read_text()
    (work / "legacy.py").write_text("# moved\n# down\n\n" + src)
    third = run_lint([str(work)], baseline_path=str(baseline))
    assert third.new_findings == []

    # a NEW violation is not covered: the ratchet only goes down
    (work / "legacy.py").write_text(
        src
        + "\n\n@jax.jit\ndef fresh_bug(params, batch):\n"
        "    return float(jnp.mean(params['w']))\n"
    )
    fourth = run_lint([str(work)], baseline_path=str(baseline))
    assert len(fourth.new_findings) == 1
    assert fourth.new_findings[0].rule == "R1"
    assert fourth.new_findings[0].symbol == "fresh_bug"


def test_baseline_consumes_entries_per_duplicate(tmp_path):
    """Two identical new copies of one baselined bug: one entry covers one."""
    work = tmp_path / "pkg"
    work.mkdir()
    (work / "m.py").write_text(
        "import jax\nimport jax.numpy as jnp\n\n"
        "@jax.jit\ndef f(params, batch):\n"
        "    return float(jnp.mean(params['w']))\n"
    )
    baseline = tmp_path / "jaxlint-baseline.json"
    write_baseline(run_lint([str(work)], use_baseline=False).findings, str(baseline))
    (work / "m.py").write_text(
        "import jax\nimport jax.numpy as jnp\n\n"
        "@jax.jit\ndef f(params, batch):\n"
        "    return float(jnp.mean(params['w']))\n\n"
        "@jax.jit\ndef g(params, batch):\n"
        "    return float(jnp.mean(params['w']))\n"
    )
    res = run_lint([str(work)], baseline_path=str(baseline))
    assert len(res.new_findings) == 1  # g's copy is new; f's stays covered


# ------------------------------------------------------------ CLI surface --


def _run_cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.analysis", *args],
        capture_output=True,
        text=True,
        cwd=cwd,
        timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )


def test_cli_json_schema():
    res = _run_cli("lint", _fixture("r1_host_sync.py"), "--no-baseline", "--json")
    assert res.returncode == 1  # violations present
    payload = json.loads(res.stdout)
    assert payload["schema"] == 1
    assert set(payload) == {"schema", "summary", "stats", "findings"}
    assert {"total", "new", "errors", "warnings", "suppressed", "baselined", "by_rule"} <= set(
        payload["summary"]
    )
    assert payload["summary"]["errors"] >= 6
    for f in payload["findings"]:
        assert {
            "rule",
            "severity",
            "path",
            "line",
            "col",
            "message",
            "symbol",
            "line_content",
            "suppressed",
            "baselined",
        } <= set(f)
        assert f["rule"] in {"R1", "R2", "R3", "R4", "R5", "R6"}
        assert f["severity"] in {"error", "warning", "note"}


def test_cli_exit_codes():
    assert _run_cli("lint", _fixture("r1_clean.py"), "--no-baseline").returncode == 0
    assert _run_cli("lint", _fixture("r1_host_sync.py"), "--no-baseline").returncode == 1


def test_cli_rules_catalog():
    res = _run_cli("rules")
    assert res.returncode == 0
    for rule_id in ("R1", "R2", "R3", "R4", "R5", "R6"):
        assert rule_id in res.stdout


def test_cli_write_baseline(tmp_path):
    work = tmp_path / "pkg"
    work.mkdir()
    shutil.copy(_fixture("r5_nondet.py"), work / "m.py")
    baseline = tmp_path / "bl.json"
    res = _run_cli("lint", str(work), "--baseline", str(baseline), "--write-baseline")
    assert res.returncode == 0, res.stdout + res.stderr
    data = json.loads(baseline.read_text())
    assert data["version"] == 1 and len(data["findings"]) >= 4
    res = _run_cli("lint", str(work), "--baseline", str(baseline))
    assert res.returncode == 0


@pytest.mark.smoke
def test_repo_lints_clean():
    """The acceptance gate: `make lint` (the CLI over accelerate_tpu/ with
    the shipped baseline) exits 0 at HEAD."""
    res = _run_cli("lint", "accelerate_tpu/")
    assert res.returncode == 0, res.stdout[-4000:] + res.stderr[-2000:]


# ------------------------------------- R4 runtime cross-check (satellite) --


def test_collective_fingerprint_rolls_and_matches():
    from accelerate_tpu.telemetry.flight_recorder import FlightRecorder

    a, b = FlightRecorder(capacity=8), FlightRecorder(capacity=8)
    for rec in (a, b):
        rec.record_collective("gather", "(8, 4)/float32")
        rec.record_collective("reduce:mean", "(8,)/float32")
    assert a.collective_hash == b.collective_hash and a.collective_count == 2
    b.record_collective("gather", "(8, 4)/float32")
    assert a.collective_hash != b.collective_hash


def test_gather_feeds_fingerprint():
    import numpy as np

    from accelerate_tpu.telemetry import flight_recorder
    from accelerate_tpu.utils.operations import gather

    rec = flight_recorder.get_recorder()
    before = rec.collective_count
    gather({"x": np.ones((4, 2), np.float32)})
    assert rec.collective_count == before + 1
    assert rec.collective_recent[-1]["op"] == "gather"
    # single-process: op recorded, payload walk skipped (no peer to diverge
    # from); multiprocess signatures are covered by _collective_signature's
    # own test below
    assert rec.collective_recent[-1]["sig"] == "local"


def test_collective_signature_multiprocess_shapes(monkeypatch):
    import numpy as np

    from accelerate_tpu.utils import operations

    class _FakeState:
        num_processes = 2

    monkeypatch.setattr(operations, "PartialState", lambda: _FakeState())
    sig = operations._collective_signature(
        {"a": np.ones((8, 2), np.float32), "b": [np.zeros((3,), np.int32)]}
    )
    assert "(8, 2)/float32" in sig and "(3,)/int32" in sig


def test_pad_across_processes_feeds_fingerprint():
    import numpy as np

    from accelerate_tpu.telemetry import flight_recorder
    from accelerate_tpu.utils.operations import pad_across_processes

    rec = flight_recorder.get_recorder()
    before = rec.collective_count
    pad_across_processes({"x": np.ones((3, 2), np.float32)})
    assert rec.collective_count == before + 1
    # op-only signature: pad's whole job is rank-VARYING shapes, which must
    # not poison the cross-rank fingerprint on healthy ragged batches
    assert rec.collective_recent[-1]["op"] == "pad_across_processes"
    assert rec.collective_recent[-1]["sig"] == "ragged"


def test_by_rank_report_rank_with_no_collectives_is_prefix_skew(tmp_path):
    """A rank dumped before its first collective has an (empty) prefix of
    every schedule — skew, not a divergence banner."""
    from accelerate_tpu.telemetry.flight_recorder import FlightRecorder
    from accelerate_tpu.telemetry.report import build_report

    for rank, ops in ((0, ["gather", "reduce:mean"]), (1, [])):
        rec = FlightRecorder(capacity=16)
        for op in ops:
            rec.record_collective(op, "(8,)/float32")
        (tmp_path / f"flight-rank{rank}.json").write_text(
            json.dumps(
                {
                    "kind": "flight_record",
                    "reason": "test",
                    "meta": {"process_index": rank},
                    "collective_schedule": rec.collective_schedule(),
                }
            )
        )
    div = build_report([str(tmp_path)], by_rank=True)["ranks"]["collective_divergence"]
    assert div["diverged"] is False
    assert div["prefix_skew"] == {"0": 2, "1": 0}


def test_by_rank_report_confirms_divergent_schedule(tmp_path):
    """Statically-flagged divergence (R4) confirmed at runtime: rank 1 skips
    one gather; the --by-rank report names the first differing call."""
    from accelerate_tpu.telemetry.flight_recorder import FlightRecorder
    from accelerate_tpu.telemetry.report import build_report, format_rank_section

    plans = {
        # rank 0 took the rank-conditional extra gather; rank 1 moved on to
        # the barrier — the dumps disagree at call #3, not just in length
        0: ["gather", "reduce:mean", "gather", "barrier"],
        1: ["gather", "reduce:mean", "barrier"],
    }
    for rank, ops in plans.items():
        rec = FlightRecorder(capacity=16)
        for op in ops:
            rec.record_collective(op, "(8, 4)/float32")
        (tmp_path / f"flight-rank{rank}.json").write_text(
            json.dumps(
                {
                    "kind": "flight_record",
                    "reason": "test",
                    "meta": {"process_index": rank},
                    "collective_schedule": rec.collective_schedule(),
                }
            )
        )
    report = build_report([str(tmp_path)], by_rank=True)
    div = report["ranks"]["collective_divergence"]
    assert div["diverged"] is True
    assert div["count_skew"] == {"0": 4, "1": 3}
    assert div["first_divergence"]["seq"] == 3
    assert div["first_divergence"]["calls"]["0"]["op"] == "gather"
    assert div["first_divergence"]["calls"]["1"]["op"] == "barrier"
    text = format_rank_section(report["ranks"])
    assert "COLLECTIVE SCHEDULE DIVERGENCE" in text
    assert "call #3" in text


def _write_sched(tmp_path, rank, sched):
    (tmp_path / f"flight-rank{rank}.json").write_text(
        json.dumps(
            {
                "kind": "flight_record",
                "reason": "test",
                "meta": {"process_index": rank},
                "collective_schedule": sched,
            }
        )
    )


def test_by_rank_divergence_proven_at_min_count_despite_window_rotation(tmp_path):
    """The differing call rotated out of every window, but the cumulative
    hashes at the minimum common count disagree — that is proof, not
    'indeterminate'."""
    from accelerate_tpu.telemetry.report import build_report

    _write_sched(
        tmp_path,
        0,
        {
            "count": 100,
            "hash": "cccccccc",
            "recent": [
                {"seq": s, "op": "gather", "sig": "x", "hash": "aaaaaaaa"}
                for s in range(90, 101)
            ],
        },
    )
    _write_sched(
        tmp_path,
        1,
        {
            "count": 90,
            "hash": "bbbbbbbb",
            "recent": [{"seq": 90, "op": "gather", "sig": "x", "hash": "bbbbbbbb"}],
        },
    )
    div = build_report([str(tmp_path)], by_rank=True)["ranks"]["collective_divergence"]
    assert div["diverged"] is True and div["first_divergence"] is None


def test_by_rank_window_outrun_count_skew_is_indeterminate(tmp_path):
    """Counts differ and no window reaches the minimum common count: timing
    skew and divergence are indistinguishable — no deadlock banner."""
    from accelerate_tpu.telemetry.report import build_report, format_rank_section

    _write_sched(
        tmp_path,
        0,
        {
            "count": 200,
            "hash": "cccccccc",
            "recent": [
                {"seq": s, "op": "gather", "sig": "x", "hash": "aaaaaaaa"}
                for s in range(190, 201)
            ],
        },
    )
    _write_sched(
        tmp_path,
        1,
        {
            "count": 100,
            "hash": "bbbbbbbb",
            "recent": [
                {"seq": s, "op": "gather", "sig": "x", "hash": "bbbbbbbb"}
                for s in range(95, 101)
            ],
        },
    )
    report = build_report([str(tmp_path)], by_rank=True)
    div = report["ranks"]["collective_divergence"]
    assert div["diverged"] is False and div.get("indeterminate") is True
    assert "INDETERMINATE" in format_rank_section(report["ranks"])


def test_by_rank_report_consistent_schedule(tmp_path):
    from accelerate_tpu.telemetry.flight_recorder import FlightRecorder
    from accelerate_tpu.telemetry.report import build_report, format_rank_section

    for rank in (0, 1):
        rec = FlightRecorder(capacity=16)
        for op in ("gather", "reduce:mean"):
            rec.record_collective(op, "(8, 4)/float32")
        (tmp_path / f"flight-rank{rank}.json").write_text(
            json.dumps(
                {
                    "kind": "flight_record",
                    "reason": "test",
                    "meta": {"process_index": rank},
                    "collective_schedule": rec.collective_schedule(),
                }
            )
        )
    report = build_report([str(tmp_path)], by_rank=True)
    div = report["ranks"]["collective_divergence"]
    assert div["diverged"] is False
    assert "consistent across ranks" in format_rank_section(report["ranks"])


def test_by_rank_report_prefix_skew_is_not_divergence(tmp_path):
    """A healthy run dumped mid-step: rank 0 is one call ahead with an
    identical common prefix — dump-timing skew, not a deadlock banner."""
    from accelerate_tpu.telemetry.flight_recorder import FlightRecorder
    from accelerate_tpu.telemetry.report import build_report, format_rank_section

    plans = {0: ["gather", "reduce:mean", "gather"], 1: ["gather", "reduce:mean"]}
    for rank, ops in plans.items():
        rec = FlightRecorder(capacity=16)
        for op in ops:
            rec.record_collective(op, "(8, 4)/float32")
        (tmp_path / f"flight-rank{rank}.json").write_text(
            json.dumps(
                {
                    "kind": "flight_record",
                    "reason": "test",
                    "meta": {"process_index": rank},
                    "collective_schedule": rec.collective_schedule(),
                }
            )
        )
    report = build_report([str(tmp_path)], by_rank=True)
    div = report["ranks"]["collective_divergence"]
    assert div["diverged"] is False
    assert div["prefix_skew"] == {"0": 1, "1": 0}
    text = format_rank_section(report["ranks"])
    assert "dump-timing skew" in text and "DIVERGENCE" not in text
