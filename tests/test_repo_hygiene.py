"""Repo hygiene guards: compiled artifacts must never be tracked in git.

A previous seed committed ``accelerate_tpu/telemetry/__pycache__`` with no
matching source — stale bytecode that shadows nothing and confuses everyone.
This guard fails the suite if any ``__pycache__``/``.pyc`` ever lands in the
index again.
"""

import os
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _git_ls_files():
    try:
        res = subprocess.run(
            ["git", "ls-files"], cwd=REPO, capture_output=True, text=True, timeout=60
        )
    except (OSError, subprocess.TimeoutExpired):
        pytest.skip("git unavailable")
    if res.returncode != 0:
        pytest.skip("not a git checkout")
    return res.stdout.splitlines()


def test_no_compiled_artifacts_tracked():
    tracked = _git_ls_files()
    bad = [
        path
        for path in tracked
        if "__pycache__" in path or path.endswith((".pyc", ".pyo", ".pyd"))
    ]
    assert bad == [], f"compiled artifacts tracked in git: {bad}"


def test_pycache_is_gitignored():
    gitignore = os.path.join(REPO, ".gitignore")
    assert os.path.exists(gitignore)
    patterns = [line.strip() for line in open(gitignore)]
    assert "__pycache__/" in patterns and "*.pyc" in patterns


# --------------------------------------------------------------- jaxlint --
# The static-analysis baseline (jaxlint-baseline.json) is a ratchet: entries
# exist only to grandfather findings that predate the linter, and the count
# may only ever go DOWN. Fixing debt removes entries; new findings must be
# fixed or inline-suppressed with a justification comment, never baselined.
# PR 6 shipped with zero entries — keep it that way (or lower, if a future
# PR ever has to add one and then pays it off).

MAX_JAXLINT_BASELINE_ENTRIES = 0


def test_jaxlint_baseline_only_shrinks():
    import json

    path = os.path.join(REPO, "jaxlint-baseline.json")
    assert os.path.exists(path), "jaxlint-baseline.json missing from repo root"
    with open(path) as f:
        data = json.load(f)
    assert data.get("version") == 1
    entries = data.get("findings")
    assert isinstance(entries, list)
    assert len(entries) <= MAX_JAXLINT_BASELINE_ENTRIES, (
        f"jaxlint baseline grew to {len(entries)} entr(ies) — the baseline "
        "only ratchets down. Fix the new finding or add an inline "
        "`# jaxlint: disable=Rn` with a justification comment, then (only "
        "if unavoidable) raise MAX_JAXLINT_BASELINE_ENTRIES in the same "
        "review that approves the debt."
    )
    for entry in entries:
        assert {"rule", "path", "symbol", "line_content"} <= set(entry)
