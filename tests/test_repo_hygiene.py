"""Repo hygiene guards: compiled artifacts must never be tracked in git.

A previous seed committed ``accelerate_tpu/telemetry/__pycache__`` with no
matching source — stale bytecode that shadows nothing and confuses everyone.
This guard fails the suite if any ``__pycache__``/``.pyc`` ever lands in the
index again.
"""

import os
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _git_ls_files():
    try:
        res = subprocess.run(
            ["git", "ls-files"], cwd=REPO, capture_output=True, text=True, timeout=60
        )
    except (OSError, subprocess.TimeoutExpired):
        pytest.skip("git unavailable")
    if res.returncode != 0:
        pytest.skip("not a git checkout")
    return res.stdout.splitlines()


def test_no_compiled_artifacts_tracked():
    tracked = _git_ls_files()
    bad = [
        path
        for path in tracked
        if "__pycache__" in path or path.endswith((".pyc", ".pyo", ".pyd"))
    ]
    assert bad == [], f"compiled artifacts tracked in git: {bad}"


def test_pycache_is_gitignored():
    gitignore = os.path.join(REPO, ".gitignore")
    assert os.path.exists(gitignore)
    patterns = [line.strip() for line in open(gitignore)]
    assert "__pycache__/" in patterns and "*.pyc" in patterns
