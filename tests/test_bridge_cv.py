"""CV-family torch bridge tests (VERDICT r03 item 4; reference acceptance
surface ``/root/reference/examples/cv_example.py`` — ResNet-50 through
``prepare``).

Covers the ATen lowerings for convolution (strided/dilated/grouped/transposed,
1d/2d), batch-norm (eval running-stats, train batch-stats + running-stat
updates through the BUFFER_MUTATION channel), max/avg/adaptive pooling
(ceil_mode, count_include_pad, non-divisible adaptive windows), and
interpolate (nearest, nearest-exact, bilinear both align_corners modes) — each
verified against torch eager; plus a ResNet-style block with forward AND grad
parity and a BridgedModule training e2e where running stats stay live."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
nn = torch.nn


def _lower(m, inputs, train=False):
    from accelerate_tpu.bridge.aten_lowering import lower_module_aten

    return lower_module_aten(m, inputs, train_mode=train)


def _op_parity(module, x, atol=1e-5):
    """Export `module` wrapping a single op, run both ways, compare."""
    module = module.eval()
    with torch.no_grad():
        expected = module(torch.from_numpy(x)).numpy()
    fn, params, buffers = _lower(module, {"x": x})
    got = np.asarray(fn(params, buffers, {"x": x}, train=False))
    np.testing.assert_allclose(got, expected, atol=atol, rtol=1e-5)


class _Op(nn.Module):
    def __init__(self, f):
        super().__init__()
        self.f = f

    def forward(self, x):
        return self.f(x)


def _img(shape=(2, 3, 16, 16), seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


class TestConvLowering:
    @pytest.mark.smoke
    def test_conv2d_stride_padding(self):
        torch.manual_seed(0)
        _op_parity(_Op(nn.Conv2d(3, 8, 3, stride=2, padding=1)), _img())

    def test_conv2d_no_bias_dilated(self):
        torch.manual_seed(1)
        _op_parity(_Op(nn.Conv2d(3, 8, 3, padding=2, dilation=2, bias=False)), _img())

    def test_conv2d_grouped(self):
        torch.manual_seed(2)
        _op_parity(_Op(nn.Conv2d(8, 8, 3, padding=1, groups=4)), _img((2, 8, 12, 12)))

    def test_conv2d_asymmetric_kernel(self):
        torch.manual_seed(3)
        _op_parity(_Op(nn.Conv2d(3, 4, (1, 5), padding=(0, 2))), _img())

    def test_conv1d(self):
        torch.manual_seed(4)
        _op_parity(_Op(nn.Conv1d(4, 8, 3, stride=2, padding=1)), _img((2, 4, 32)))

    def test_conv_transpose2d(self):
        torch.manual_seed(5)
        _op_parity(
            _Op(nn.ConvTranspose2d(4, 6, 3, stride=2, padding=1, output_padding=1)),
            _img((2, 4, 8, 8)),
        )

    def test_conv_transpose2d_grouped(self):
        torch.manual_seed(6)
        _op_parity(
            _Op(nn.ConvTranspose2d(4, 8, 4, stride=2, padding=1, groups=2)),
            _img((2, 4, 8, 8)),
        )


class TestPoolingLowering:
    def test_max_pool2d_basic(self):
        _op_parity(_Op(lambda x: nn.functional.max_pool2d(x, 3, 2, 1)), _img())

    def test_max_pool2d_ceil_mode(self):
        _op_parity(
            _Op(lambda x: nn.functional.max_pool2d(x, 3, 2, 1, ceil_mode=True)),
            _img((2, 3, 15, 15)),
        )

    def test_max_pool2d_dilation(self):
        _op_parity(
            _Op(lambda x: nn.functional.max_pool2d(x, 2, 2, 0, dilation=2)), _img()
        )

    def test_avg_pool2d_basic(self):
        _op_parity(_Op(lambda x: nn.functional.avg_pool2d(x, 2)), _img())

    def test_avg_pool2d_padding_count_include(self):
        _op_parity(
            _Op(lambda x: nn.functional.avg_pool2d(x, 3, 2, 1, count_include_pad=True)),
            _img(),
        )

    def test_avg_pool2d_padding_count_exclude(self):
        _op_parity(
            _Op(lambda x: nn.functional.avg_pool2d(x, 3, 2, 1, count_include_pad=False)),
            _img(),
        )

    def test_avg_pool2d_ceil_mode(self):
        _op_parity(
            _Op(lambda x: nn.functional.avg_pool2d(x, 3, 2, 1, ceil_mode=True)),
            _img((2, 3, 15, 15)),
        )

    def test_adaptive_avg_pool2d_one(self):
        _op_parity(_Op(lambda x: nn.functional.adaptive_avg_pool2d(x, 1)), _img())

    def test_adaptive_avg_pool2d_divisible(self):
        _op_parity(_Op(lambda x: nn.functional.adaptive_avg_pool2d(x, (4, 8))), _img())

    def test_adaptive_avg_pool2d_non_divisible(self):
        _op_parity(
            _Op(lambda x: nn.functional.adaptive_avg_pool2d(x, (5, 7))),
            _img((2, 3, 13, 17)),
        )


class TestGroupNorm:
    def test_group_norm_parity(self):
        torch.manual_seed(7)
        gn = nn.GroupNorm(4, 8)
        with torch.no_grad():
            gn.weight.mul_(1.3).add_(0.1)
            gn.bias.add_(0.2)
        _op_parity(_Op(gn), _img((2, 8, 6, 6)), atol=1e-5)

    def test_group_norm_unet_block_with_grads(self):
        """GroupNorm + silu + conv (the UNet-family block shape): forward and
        grad parity vs torch — GroupNorm is batch-independent so train==eval."""
        import jax

        torch.manual_seed(8)

        class Block(nn.Module):
            def __init__(self):
                super().__init__()
                self.conv = nn.Conv2d(3, 8, 3, padding=1)
                self.gn = nn.GroupNorm(2, 8)
                self.up = nn.ConvTranspose2d(8, 4, 4, stride=2, padding=1)

            def forward(self, pixel_values, labels=None):
                h = nn.functional.silu(self.gn(self.conv(pixel_values)))
                out = {"logits": self.up(h)}
                if labels is not None:
                    out["loss"] = nn.functional.mse_loss(out["logits"], labels)
                return out

        m = Block().eval()
        x = _img((2, 3, 8, 8), seed=8)
        y = _img((2, 4, 16, 16), seed=9)
        batch = {"pixel_values": x, "labels": y}
        fn, params, buffers = _lower(m, batch)
        out = fn(params, buffers, batch, train=False)
        tout = m(torch.from_numpy(x), torch.from_numpy(y))
        np.testing.assert_allclose(
            float(np.asarray(out["loss"])), float(tout["loss"]), atol=1e-5
        )
        grads = jax.grad(lambda p: fn(p, buffers, batch, train=False)["loss"])(params)
        tout["loss"].backward()
        for name, p in m.named_parameters():
            np.testing.assert_allclose(
                np.asarray(grads[name]), p.grad.numpy(), atol=2e-4, err_msg=name
            )


class TestLossLowerings:
    def test_smooth_l1_beta_zero_is_l1_with_finite_grads(self):
        import jax
        import jax.numpy as jnp

        from accelerate_tpu.bridge.aten_lowering import _aten_handlers

        h = _aten_handlers()["aten.smooth_l1_loss.default"]
        p = jnp.asarray(np.random.default_rng(0).normal(size=(4,)).astype(np.float32))
        t = jnp.zeros((4,))
        assert abs(float(h(None, p, t, 1, 0.0)) - float(jnp.mean(jnp.abs(p)))) < 1e-6
        g = jax.grad(lambda p: h(None, p, t, 1, 0.0))(p)
        assert bool(jnp.all(jnp.isfinite(g)))  # /beta NaN-grad guard

    def test_loss_reduction_none_keeps_input_dtype(self):
        import jax.numpy as jnp

        from accelerate_tpu.bridge.aten_lowering import _aten_handlers

        h = _aten_handlers()
        p = jnp.ones((4,), jnp.bfloat16)
        t = jnp.zeros((4,), jnp.bfloat16)
        for op in ("aten.mse_loss.default", "aten.l1_loss.default"):
            assert h[op](None, p, t, 0).dtype == jnp.bfloat16
            assert h[op](None, p, t, 1).dtype == jnp.float32  # scalar stays f32

    def test_smooth_l1_matches_torch(self):
        import jax.numpy as jnp

        from accelerate_tpu.bridge.aten_lowering import _aten_handlers

        h = _aten_handlers()["aten.smooth_l1_loss.default"]
        p = np.random.default_rng(2).normal(size=(8,)).astype(np.float32)
        got = float(h(None, jnp.asarray(p), jnp.zeros((8,)), 1, 0.5))
        ref = float(nn.functional.smooth_l1_loss(
            torch.from_numpy(p.copy()), torch.zeros(8), beta=0.5))
        assert abs(got - ref) < 1e-6

    def test_native_group_norm_returns_real_stats(self):
        import jax.numpy as jnp

        from accelerate_tpu.bridge.aten_lowering import _aten_handlers

        h = _aten_handlers()["aten.native_group_norm.default"]
        x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 8, 4, 4)).astype(np.float32))
        out, mean, rstd = h(None, x, None, None, 2, 8, 16, 4, 1e-5)
        assert mean.shape == (2, 4) and rstd.shape == (2, 4)
        ref = nn.functional.group_norm(torch.from_numpy(np.asarray(x)), 4)
        np.testing.assert_allclose(np.asarray(out), ref.numpy(), atol=1e-5)


class TestInterpolateLowering:
    def test_nearest_scale2(self):
        _op_parity(
            _Op(lambda x: nn.functional.interpolate(x, scale_factor=2, mode="nearest")),
            _img((2, 3, 7, 9)),
        )

    def test_nearest_downscale(self):
        _op_parity(
            _Op(lambda x: nn.functional.interpolate(x, size=(5, 6), mode="nearest")),
            _img(),
        )

    def test_nearest_exact(self):
        _op_parity(
            _Op(lambda x: nn.functional.interpolate(x, scale_factor=2, mode="nearest-exact")),
            _img((2, 3, 7, 9)),
        )

    def test_bilinear_half_pixel(self):
        _op_parity(
            _Op(lambda x: nn.functional.interpolate(
                x, size=(13, 11), mode="bilinear", align_corners=False)),
            _img(),
            atol=1e-4,
        )

    def test_bilinear_align_corners(self):
        _op_parity(
            _Op(lambda x: nn.functional.interpolate(
                x, size=(31, 3), mode="bilinear", align_corners=True)),
            _img(),
            atol=1e-4,
        )

    def test_bilinear_align_corners_to_size_one(self):
        # torch clamps the align_corners scale to 0 for output size 1
        _op_parity(
            _Op(lambda x: nn.functional.interpolate(
                x, size=(1, 1), mode="bilinear", align_corners=True)),
            _img(),
            atol=1e-4,
        )


def _mini_resnet(num_classes=4, seed=0):
    """Hand-written ResNet block stack (torchvision absent in this image):
    stem conv/bn/maxpool + residual block with downsample + avgpool + fc —
    the op mix of the reference's ResNet-50 acceptance example."""

    class MiniResNet(nn.Module):
        def __init__(self):
            super().__init__()
            self.stem = nn.Conv2d(3, 8, 7, stride=2, padding=3, bias=False)
            self.bn0 = nn.BatchNorm2d(8)
            self.pool = nn.MaxPool2d(3, stride=2, padding=1)
            self.conv1 = nn.Conv2d(8, 16, 3, stride=2, padding=1, bias=False)
            self.bn1 = nn.BatchNorm2d(16)
            self.conv2 = nn.Conv2d(16, 16, 3, padding=1, bias=False)
            self.bn2 = nn.BatchNorm2d(16)
            self.down = nn.Conv2d(8, 16, 1, stride=2, bias=False)
            self.bnd = nn.BatchNorm2d(16)
            self.fc = nn.Linear(16, num_classes)

        def forward(self, pixel_values, labels=None):
            x = self.pool(torch.relu(self.bn0(self.stem(pixel_values))))
            idn = self.bnd(self.down(x))
            x = torch.relu(self.bn1(self.conv1(x)))
            x = self.bn2(self.conv2(x))
            x = torch.relu(x + idn)
            x = nn.functional.adaptive_avg_pool2d(x, (1, 1)).flatten(1)
            logits = self.fc(x)
            out = {"logits": logits}
            if labels is not None:
                out["loss"] = nn.functional.cross_entropy(logits, labels)
            return out

    torch.manual_seed(seed)
    return MiniResNet()


def _cv_batch(n=4, side=32, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "pixel_values": rng.normal(size=(n, 3, side, side)).astype(np.float32),
        "labels": rng.integers(0, classes, (n,)).astype(np.int64),
    }


class TestResNetBlockParity:
    def test_eval_forward_matches_torch(self):
        m = _mini_resnet().eval()
        batch = _cv_batch()
        fn, params, buffers = _lower(m, batch)
        out = fn(params, buffers, batch, train=False)
        with torch.no_grad():
            tout = m(torch.from_numpy(batch["pixel_values"]), torch.from_numpy(batch["labels"]))
        np.testing.assert_allclose(
            np.asarray(out["logits"]), tout["logits"].numpy(), atol=1e-4
        )

    def test_train_forward_uses_batch_stats_and_grads_match(self):
        import jax

        m = _mini_resnet().train()
        batch = _cv_batch(seed=1)
        fn, params, buffers = _lower(m, batch, train=True)
        assert fn.mutated_buffers  # BN running stats surface as mutations
        out, buf_updates = fn(params, buffers, batch, train=True, with_buffer_updates=True)
        tout = m(torch.from_numpy(batch["pixel_values"]), torch.from_numpy(batch["labels"]))
        np.testing.assert_allclose(
            float(np.asarray(out["loss"])), float(tout["loss"]), atol=1e-4
        )
        grads = jax.grad(lambda p: fn(p, buffers, batch, train=True)["loss"])(params)
        tout["loss"].backward()
        for name, p in m.named_parameters():
            if p.grad is None:
                continue
            np.testing.assert_allclose(
                np.asarray(grads[name]), p.grad.numpy(), atol=2e-4,
                err_msg=f"grad mismatch at {name}",
            )
        # torch's forward above also updated ITS running stats: ours must agree
        tbuf = dict(m.named_buffers())
        for k, v in buf_updates.items():
            if "num_batches" in k:
                continue
            np.testing.assert_allclose(
                np.asarray(v), tbuf[k].detach().numpy(), atol=1e-4, err_msg=k
            )

    def test_bridged_module_training_updates_running_stats(self):
        from accelerate_tpu.bridge.module import BridgedModule

        m = _mini_resnet(seed=2)
        bm = BridgedModule(m).train()
        batch = _cv_batch(seed=2)
        before = {k: np.asarray(v).copy() for k, v in bm.buffers.items()
                  if "running_mean" in k}
        out = bm(**batch)
        assert np.isfinite(float(out["loss"]))
        after = {k: np.asarray(v) for k, v in bm.buffers.items() if "running_mean" in k}
        moved = [k for k in before if not np.allclose(before[k], after[k])]
        assert moved, "BN running stats did not update across a train step"
        # eval after training uses the live stats without error
        bm.eval()
        eval_out = bm(**{"pixel_values": batch["pixel_values"]})
        assert np.asarray(eval_out["logits"]).shape == (4, 4)
        # sync_to_torch must carry the LIVE buffers (not just params) so a
        # torch-side state_dict save reflects training
        torch_mod = bm.sync_to_torch()
        tstats = dict(torch_mod.named_buffers())
        for k in moved:
            np.testing.assert_allclose(
                tstats[k].detach().numpy(), after[k], atol=1e-6,
                err_msg=f"{k} not synced back to torch",
            )


    def test_train_forward_without_labels_updates_running_stats(self):
        # torch updates BN running stats on ANY train-mode forward, labels or
        # not — a mid-training logits probe must not desynchronize stats
        from accelerate_tpu.bridge.module import BridgedModule

        m = _mini_resnet(seed=4)
        bm = BridgedModule(m).train()
        batch = _cv_batch(seed=4)
        before = {k: np.asarray(v).copy() for k, v in bm.buffers.items()
                  if "running_mean" in k}
        out = bm(pixel_values=batch["pixel_values"])  # no labels
        assert np.asarray(out["logits"]).shape == (4, 4)
        after = {k: np.asarray(v) for k, v in bm.buffers.items() if "running_mean" in k}
        moved = [k for k in before if not np.allclose(before[k], after[k])]
        assert moved, "label-less train forward did not update running stats"

    def test_bf16_policy_keeps_running_stats_fp32(self):
        # the momentum blend must see fp32 stats even under a bf16 compute
        # policy (torch keeps BN stats fp32 under autocast)
        import jax.numpy as jnp

        from accelerate_tpu import Accelerator
        from accelerate_tpu.bridge.module import BridgedModule
        from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        acc = Accelerator(mixed_precision="bf16", rng_seed=0)
        bm = BridgedModule(_mini_resnet(seed=5), accelerator=acc).train()
        batch = _cv_batch(seed=5)
        for _ in range(3):
            bm(**batch)
        stats = {k: v for k, v in bm.buffers.items() if "running_" in k}
        assert stats
        for k, v in stats.items():
            assert v.dtype == jnp.float32, f"{k} degraded to {v.dtype}"
        # at least one stat value must carry sub-bf16 precision — proof the
        # blend ran in fp32, not on bf16-quantized inputs
        vals = np.concatenate([np.asarray(v).ravel() for v in stats.values()])
        requantized = vals.astype(jnp.bfloat16).astype(np.float32)
        assert not np.array_equal(vals, requantized), (
            "running stats sit exactly on the bf16 grid — blend was quantized"
        )


class TestCvTrainingE2E:
    def test_loss_decreases_with_bridged_optimizer(self):
        """The reference cv_example training shape: torch module + torch
        optimizer through Accelerator.prepare, loop is plain torch style."""
        from accelerate_tpu import Accelerator

        from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        acc = Accelerator(rng_seed=0)
        m = _mini_resnet(seed=3)
        opt = torch.optim.SGD(m.parameters(), lr=0.05, momentum=0.9)
        model, opt = acc.prepare(m, opt)
        model.train()
        batch = _cv_batch(n=8, seed=3)
        losses = []
        for _ in range(8):
            out = model(**batch)
            acc.backward(out["loss"])
            opt.step()
            opt.zero_grad()
            losses.append(float(out["loss"]))
        assert losses[-1] < losses[0] * 0.7, f"no learning: {losses}"
