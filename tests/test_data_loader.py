"""Data pipeline tests — modeled on the reference's exhaustive BatchSamplerShard
index-math suite (``/root/reference/tests/test_data_loader.py``, 913 LoC)."""

import threading
import time

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from accelerate_tpu import AcceleratorState, GradientState, ParallelismConfig
from accelerate_tpu.data_loader import (
    BatchSampler,
    BatchSamplerShard,
    DataLoader,
    DataLoaderShard,
    GlobalBatchAssembler,
    IterableDatasetShard,
    SeedableRandomSampler,
    SequentialSampler,
    SkipBatchSampler,
    default_collate,
    prepare_data_loader,
    skip_first_batches,
)


def make_batch_sampler(n, batch_size, drop_last=False, shuffle=False):
    sampler = SeedableRandomSampler(n, seed=0) if shuffle else SequentialSampler(n)
    return BatchSampler(sampler, batch_size, drop_last)


class TestBatchSamplerShard:
    def check(self, n, batch_size, num_shards, drop_last=False, even_batches=True, split_batches=False):
        inner = make_batch_sampler(n, batch_size, drop_last)
        shards = [
            BatchSamplerShard(
                make_batch_sampler(n, batch_size, drop_last),
                num_shards,
                i,
                split_batches=split_batches,
                even_batches=even_batches,
            )
            for i in range(num_shards)
        ]
        results = [list(s) for s in shards]
        return results

    def test_even_split(self):
        # 24 samples, bs=3, 4 shards → 8 batches, 2 rounds, no remainder
        results = self.check(24, 3, 4)
        assert [len(r) for r in results] == [2, 2, 2, 2]
        seen = sorted(i for r in results for b in r for i in b)
        assert seen == list(range(24))

    def test_wraparound_even_batches(self):
        # 22 samples, bs=3, 4 shards → 8 batches, last is short (1 sample)
        results = self.check(22, 3, 4)
        sizes = {len(b) for r in results for b in r}
        assert sizes == {3}, f"all batches must be full-size, got {sizes}"
        counts = [len(r) for r in results]
        assert len(set(counts)) == 1, "all shards must see same number of batches"

    def test_drop_last(self):
        results = self.check(22, 3, 4, drop_last=True)
        # 7 full batches → 1 full round of 4; trailing 3 dropped
        assert [len(r) for r in results] == [1, 1, 1, 1]

    def test_uneven_no_even_batches(self):
        results = self.check(22, 3, 4, even_batches=False)
        total = sum(len(r) for r in results)
        assert total == 8  # all batches distributed, shards uneven

    def test_split_batches(self):
        results = self.check(24, 8, 4, split_batches=True)
        for r in results:
            assert all(len(b) == 2 for b in r)
        assert [len(r) for r in results] == [3, 3, 3]  + [3]

    def test_split_batches_requires_divisible(self):
        with pytest.raises(ValueError):
            BatchSamplerShard(make_batch_sampler(24, 6), 4, 0, split_batches=True)

    def test_len_matches_iteration(self):
        for n in (16, 17, 22, 24):
            for bs in (2, 3):
                for num in (2, 4):
                    shard = BatchSamplerShard(make_batch_sampler(n, bs), num, 0)
                    assert len(list(shard)) == len(shard), (n, bs, num)


class TestBatchSamplerShardGrid:
    """Exhaustive sweep over drop_last × even_batches × split_batches ×
    uneven-tail sizes — the counterpart of the reference's 913-LoC index-math
    suite (``/root/reference/tests/test_data_loader.py``), plus a stronger
    invariant the reference doesn't hold: ``len() == sum(1 for _)`` in EVERY
    mode, including split_batches (reference split ``__len__`` is nominal)."""

    @staticmethod
    def _grid():
        for n in range(1, 19):
            for bs in (1, 2, 3, 4):
                for num_shards in (1, 2, 3, 4):
                    for drop_last in (False, True):
                        for even_batches in (False, True):
                            for split in (False, True):
                                if split and bs % num_shards != 0:
                                    continue
                                yield n, bs, num_shards, drop_last, even_batches, split

    def test_full_grid_invariants(self):
        for n, bs, num_shards, drop_last, even_batches, split in self._grid():
            shards = [
                BatchSamplerShard(
                    make_batch_sampler(n, bs, drop_last),
                    num_shards,
                    i,
                    split_batches=split,
                    even_batches=even_batches,
                )
                for i in range(num_shards)
            ]
            results = [list(s) for s in shards]
            cfg = dict(n=n, bs=bs, shards=num_shards, drop=drop_last,
                       even=even_batches, split=split)

            # 1. len() is EXACT in every mode
            for i, (s, r) in enumerate(zip(shards, results)):
                assert len(s) == len(r), (cfg, i, len(s), len(r))

            all_indices = [i for r in results for b in r for i in b]
            assert all(0 <= i < n for i in all_indices), cfg

            if even_batches:
                # 2. every shard sees the same number of batches...
                counts = {len(r) for r in results}
                assert len(counts) == 1, (cfg, [len(r) for r in results])
                # ...and every batch is the same (full) size
                sizes = {len(b) for r in results for b in r}
                assert len(sizes) <= 1, (cfg, sizes)
                if not drop_last:
                    # 3. full coverage (wraparound may duplicate, never skip)
                    assert set(all_indices) == set(range(n)), cfg
            else:
                if not drop_last:
                    # 4. exact partition: every sample exactly once, none dropped
                    assert sorted(all_indices) == list(range(n)), (cfg, sorted(all_indices))

            if drop_last:
                # 5. never duplicates with drop_last
                assert len(all_indices) == len(set(all_indices)), cfg

    def test_split_slice_size_is_nominal(self):
        """Dataset smaller than one batch: each shard's slice must still be
        batch_size // num_shards (reference ``batch_length`` :198), not shrunk."""
        shards = [
            BatchSamplerShard(make_batch_sampler(2, 4), 2, i, split_batches=True)
            for i in range(2)
        ]
        for s in shards:
            batches = list(s)
            assert all(len(b) == 2 for b in batches), batches


def test_iterable_dataset_shard_grid():
    """Exhaustive sweep mirroring the reference's iterable-shard tests
    (``/root/reference/tests/test_data_loader.py``): every (length, batch_size,
    num_shards, drop_last, even_batches) cell must satisfy the invariants —
    all shards yield the SAME count; full windows are exact round-robin
    slices; the tail is dropped, padded from the stream head (even_batches),
    or truncated (neither); and every yielded item comes from the dataset."""
    from accelerate_tpu.data_loader import IterableDatasetShard

    for length in range(0, 26):
        data = list(range(length))
        for batch_size in (1, 2, 3):
            for num_shards in (2, 3):
                window = batch_size * num_shards
                for drop_last in (False, True):
                    for even_batches in (False, True):
                        shards = [
                            list(
                                IterableDatasetShard(
                                    data, batch_size, num_shards, i,
                                    drop_last=drop_last, even_batches=even_batches,
                                )
                            )
                            for i in range(num_shards)
                        ]
                        cell = (length, batch_size, num_shards, drop_last, even_batches)
                        n_full = length // window
                        tail = length % window
                        # same yield count on every shard
                        if drop_last or tail == 0:
                            expect = [n_full * batch_size] * num_shards
                        elif even_batches:
                            expect = [(n_full + 1) * batch_size] * num_shards
                        else:
                            # last partial window truncates: shard i gets its
                            # slice of the tail items
                            expect = [
                                max(0, min(batch_size, tail - i * batch_size))
                                + n_full * batch_size
                                for i in range(num_shards)
                            ]
                        assert [len(s) for s in shards] == expect, cell
                        # full windows: exact round-robin partition
                        flat_full = [x for w in range(n_full) for i in range(num_shards)
                                     for x in shards[i][w * batch_size:(w + 1) * batch_size]]
                        assert flat_full == data[: n_full * window], cell
                        # every yielded element exists in the stream
                        for s in shards:
                            assert set(s) <= set(data), cell
                        # even_batches tail pad comes from the FIRST window
                        if tail and not drop_last and even_batches and length:
                            first_window = data[:window] if length >= window else data
                            padded = [x for s in shards for x in s[n_full * batch_size:]]
                            for x in padded[tail:]:
                                assert x in first_window, cell


def test_iterable_dataset_shard():
    data = list(range(22))
    shards = [
        IterableDatasetShard(data, batch_size=3, num_shards=2, shard_index=i) for i in range(2)
    ]
    out = [list(s) for s in shards]
    # full windows of 6: 3 windows cover 18 items; tail of 4 padded from start
    assert len(out[0]) == len(out[1]) == 12
    assert out[0][:3] == [0, 1, 2] and out[1][:3] == [3, 4, 5]


def test_seedable_sampler_epoch_reshuffle():
    s = SeedableRandomSampler(10, seed=1)
    first = list(s)
    s.set_epoch(1)
    second = list(s)
    assert first != second
    s.set_epoch(0)
    assert list(s) == first


@pytest.mark.smoke
def test_default_collate_nested():
    samples = [{"x": np.ones(2), "y": (1, 2)}, {"x": np.zeros(2), "y": (3, 4)}]
    batch = default_collate(samples)
    assert batch["x"].shape == (2, 2)
    assert batch["y"][0].shape == (2,)


class RangeDataset:
    def __init__(self, n, feat=4):
        self.x = np.arange(n * feat, dtype=np.float32).reshape(n, feat)
        self.y = np.arange(n, dtype=np.int32)

    def __len__(self):
        return len(self.y)

    def __getitem__(self, i):
        return {"x": self.x[i], "y": self.y[i]}


def test_global_batch_assembler_single_process():
    pc = ParallelismConfig(dp_shard_size=4, tp_size=2)
    mesh = pc.build_mesh()
    asm = GlobalBatchAssembler(mesh, pc)
    assert asm.dp_size == 4
    assert asm.local_dp_rows() == [0, 1, 2, 3]
    block = {"x": np.arange(8 * 3, dtype=np.float32).reshape(8, 3)}
    out = asm.to_global(block)
    arr = out["x"]
    assert isinstance(arr, jax.Array)
    assert arr.shape == (8, 3)
    assert arr.sharding.spec == P(("dp_replicate", "dp_shard"))
    np.testing.assert_array_equal(np.asarray(arr), block["x"])


def test_global_batch_assembler_cp_shards_sequence():
    pc = ParallelismConfig(dp_shard_size=2, cp_size=4)
    mesh = pc.build_mesh()
    asm = GlobalBatchAssembler(mesh, pc)
    block = {"ids": np.arange(4 * 8, dtype=np.int32).reshape(4, 8)}
    out = asm.to_global(block)["ids"]
    assert out.shape == (4, 8)
    assert out.sharding.spec == P(("dp_replicate", "dp_shard"), "cp")
    np.testing.assert_array_equal(np.asarray(out), block["ids"])


def test_prepare_data_loader_end_to_end():
    # Reference semantics: user batch_size is per-dp-row; global batch = 16*8=128
    # (reference keeps per-process batch size, prepare_data_loader:996)
    state = AcceleratorState(parallelism_config=ParallelismConfig(dp_shard_size=8))
    ds = RangeDataset(256)
    dl = DataLoader(ds, batch_size=16, shuffle=False)
    prepared = prepare_data_loader(dl, state=state)
    batches = list(prepared)
    assert len(batches) == 2
    for b in batches:
        assert b["x"].shape == (128, 4)
        assert b["x"].sharding.spec == P(("dp_replicate", "dp_shard"))
    # all 256 samples seen exactly once
    ys = np.concatenate([np.asarray(b["y"]) for b in batches])
    assert sorted(ys.tolist()) == list(range(256))


def test_prepared_loader_end_of_dataloader_flag():
    state = AcceleratorState(parallelism_config=ParallelismConfig(dp_shard_size=8))
    ds = RangeDataset(256)
    prepared = prepare_data_loader(DataLoader(ds, batch_size=16), state=state)
    gs = GradientState()
    flags = []
    for _ in prepared:
        flags.append(gs.end_of_dataloader)
    assert flags == [False, True]
    assert not gs.in_dataloader


def test_prepared_loader_remainder_uneven():
    state = AcceleratorState(parallelism_config=ParallelismConfig(dp_shard_size=8))
    ds = RangeDataset(200)  # 200 % 128 = 72 real samples in final global batch
    prepared = prepare_data_loader(DataLoader(ds, batch_size=16), state=state)
    gs = GradientState()
    rems = []
    shapes = []
    for b in prepared:
        rems.append(gs.remainder)
        shapes.append(b["x"].shape)
    assert rems[-1] == 72
    # even_batches wraparound: shapes identical every step (no recompiles)
    assert len(set(shapes)) == 1 and shapes[0] == (128, 4)


def test_skip_first_batches():
    state = AcceleratorState(parallelism_config=ParallelismConfig(dp_shard_size=8))
    ds = RangeDataset(512)
    prepared = prepare_data_loader(DataLoader(ds, batch_size=16), state=state)
    skipped = skip_first_batches(prepared, 2)
    batches = list(skipped)
    assert len(batches) == 2
    ys = np.concatenate([np.asarray(b["y"]) for b in batches])
    assert sorted(ys.tolist()) == list(range(256, 512))


def test_skip_batch_sampler():
    sampler = SkipBatchSampler(make_batch_sampler(20, 4), skip_batches=2)
    assert len(sampler) == 3
    assert list(sampler)[0] == [8, 9, 10, 11]


def test_state_dict_resume():
    state = AcceleratorState(parallelism_config=ParallelismConfig(dp_shard_size=8))
    ds = RangeDataset(512)  # 32 inner batches → 4 global steps
    dl = DataLoader(ds, batch_size=16, shuffle=True, seed=7)
    prepared = prepare_data_loader(dl, state=state)
    it = iter(prepared)
    first = next(it)
    second = next(it)
    sd = prepared.state_dict()
    assert sd["batches_seen"] == 2
    # fresh loader, load state, should resume with the last 2 global batches
    dl2 = DataLoader(RangeDataset(512), batch_size=16, shuffle=True, seed=7)
    prepared2 = prepare_data_loader(dl2, state=state)
    prepared2.load_state_dict(sd)
    remaining = list(prepared2)
    rest = list(it)
    assert len(remaining) == len(rest) == 2
    np.testing.assert_array_equal(np.asarray(remaining[0]["y"]), np.asarray(rest[0]["y"]))


def test_torch_dataloader_interop():
    torch = pytest.importorskip("torch")
    import torch.utils.data as tud

    class TorchDS(tud.Dataset):
        def __len__(self):
            return 128

        def __getitem__(self, i):
            return {"x": torch.ones(4) * i, "y": torch.tensor(i)}

    state = AcceleratorState(parallelism_config=ParallelismConfig(dp_shard_size=8))
    tdl = tud.DataLoader(TorchDS(), batch_size=8)
    prepared = prepare_data_loader(tdl, state=state)
    batches = list(prepared)
    assert len(batches) == 2  # 16 inner batches / 8 dp-rows
    assert isinstance(batches[0]["x"], jax.Array)
    assert batches[0]["x"].shape == (64, 4)  # global batch = 8 * 8
    ys = np.concatenate([np.asarray(b["y"]) for b in batches])
    assert sorted(ys.tolist()) == list(range(128))


# ---------------------------------------------- stateful inner loaders --------


class _FakeStatefulDataLoader:
    """torchdata-StatefulDataLoader-shaped: iterates a range of batches and
    records its own position in an opaque state dict, replaying the remainder
    after load_state_dict — the contract our wrapper must PRESERVE."""

    def __init__(self, n_batches=6, batch_size=2):
        self.n_batches = n_batches
        self.batch_size = batch_size
        self._pos = 0

    def __len__(self):
        return self.n_batches

    def __iter__(self):
        start = self._pos
        self._pos = 0  # torchdata: a loaded state applies to the NEXT iter only
        for i in range(start, self.n_batches):
            self._yielded = i + 1
            yield {"x": np.full((self.batch_size, 2), i, dtype=np.float32)}

    def state_dict(self):
        return {"_num_yielded": getattr(self, "_yielded", 0)}

    def load_state_dict(self, state):
        # torchdata contract: a finished-iterator state means the NEXT epoch
        # starts fresh (with advanced sampler RNG); mid-epoch states resume
        if state.get("_iterator_finished"):
            self._pos = 0
        else:
            self._pos = state["_num_yielded"]


class TestStatefulInnerLoader:
    def test_snapshot_lags_prefetch_by_one(self):
        """The wrapper prefetches one ahead; the served state must reflect what
        the USER consumed, not what the prefetch pulled (reference
        adjust_state_dict_for_prefetch semantics, data_loader.py:463-497)."""
        from accelerate_tpu.data_loader import DataLoaderShard

        inner = _FakeStatefulDataLoader()
        dl = DataLoaderShard(inner)
        it = iter(dl)
        next(it)  # user consumed batch 0 (inner already pulled batch 1)
        state = dl.state_dict()
        assert state["_num_yielded"] == 1, state  # NOT 2
        assert state["_iterator_finished"] is False
        next(it)
        assert dl.state_dict()["_num_yielded"] == 2

    def test_resume_replays_unconsumed_batches(self):
        from accelerate_tpu.data_loader import DataLoaderShard

        dl = DataLoaderShard(_FakeStatefulDataLoader())
        it = iter(dl)
        consumed = [float(next(it)["x"][0, 0]) for _ in range(3)]
        mid_state = dl.state_dict()
        # fresh loader + load_state_dict: must see exactly batches 3..5
        dl2 = DataLoaderShard(_FakeStatefulDataLoader())
        dl2.load_state_dict(mid_state)
        rest = [float(b["x"][0, 0]) for b in dl2]
        assert consumed == [0.0, 1.0, 2.0] and rest == [3.0, 4.0, 5.0]
        # loading a MID-epoch state after a completed epoch clears the
        # wrapper's end-of-epoch bookkeeping: the state must not re-serve as
        # finished (which would resume as a fresh epoch and skip batches)
        dl2.load_state_dict(mid_state)
        assert dl2.state_dict()["_iterator_finished"] is False

    def test_finished_epoch_is_tagged(self):
        from accelerate_tpu.data_loader import DataLoaderShard

        dl = DataLoaderShard(_FakeStatefulDataLoader(n_batches=2))
        assert [b for b in dl] and dl.state_dict()["_iterator_finished"] is True

    def test_prepare_preserves_stateful_torch_loader(self):
        """A torch DataLoader subclass carrying state machinery is wrapped
        as-is — prepare() must keep ITS state_dict working, not rebuild."""
        import torch
        import torch.utils.data as tud

        from accelerate_tpu import Accelerator

        class StatefulTorchDL(tud.DataLoader):
            def __init__(self, dataset, **kw):
                super().__init__(dataset, **kw)
                self._resume_from = 0

            def __iter__(self):
                it = super().__iter__()
                for _ in range(self._resume_from):
                    next(it)
                self._it_yielded = self._resume_from
                self._resume_from = 0
                for batch in it:
                    self._it_yielded += 1
                    yield batch

            def state_dict(self):
                return {"yielded": getattr(self, "_it_yielded", 0)}

            def load_state_dict(self, state):
                self._resume_from = state["yielded"]

        # batch of 8 rows: divides the 8 dp-rows of the virtual mesh (the
        # stateful path treats each yielded batch as the per-host block)
        data = torch.arange(48, dtype=torch.float32).reshape(24, 2)
        dl = StatefulTorchDL(tud.TensorDataset(data), batch_size=8)
        acc = Accelerator(cpu=True)
        prepared = acc.prepare(dl)
        it = iter(prepared)
        next(it)
        state = prepared.state_dict()
        assert state["yielded"] == 1 and "_iterator_finished" in state
        prepared.load_state_dict({"yielded": 2, "_iterator_finished": False})
        remaining = list(prepared)
        assert len(remaining) == 1  # 3 local batches total, resumed past 2

    def test_use_stateful_dataloader_flag_gates_plain_loaders(self):
        import torch
        import torch.utils.data as tud

        from accelerate_tpu import Accelerator
        from accelerate_tpu.utils import DataLoaderConfiguration

        acc = Accelerator(
            cpu=True,
            dataloader_config=DataLoaderConfiguration(use_stateful_dataloader=True),
        )
        plain = tud.DataLoader(
            tud.TensorDataset(torch.zeros(4, 2)), batch_size=2
        )
        with pytest.raises(ImportError, match="torchdata"):
            acc.prepare(plain)
        # the native loader is stateful out of the box: flag is satisfied
        from accelerate_tpu.data_loader import DataLoader as NativeDL

        class DS:
            def __len__(self):
                return 4

            def __getitem__(self, i):
                return {"x": np.float32(i)}

        prepared = acc.prepare(NativeDL(DS(), batch_size=2))
        assert hasattr(prepared, "state_dict")

    def test_save_state_handles_tensorful_inner_state(self, tmp_path):
        """A torchdata-like inner state carrying tensors is not JSON-friendly;
        save_state must pickle it and load_state must restore it."""
        import torch

        from accelerate_tpu import Accelerator

        class TensorStateDL(_FakeStatefulDataLoader):
            def state_dict(self):
                return {
                    "_num_yielded": getattr(self, "_yielded", 0),
                    "_generator": torch.tensor([1, 2, 3]),  # non-JSON leaf
                }

            def load_state_dict(self, state):
                assert isinstance(state["_generator"], torch.Tensor)
                self._pos = state["_num_yielded"]

        acc = Accelerator(cpu=True)
        from accelerate_tpu.data_loader import DataLoaderShard

        dl = DataLoaderShard(TensorStateDL(n_batches=4, batch_size=8))
        acc._dataloaders.append(dl)
        it = iter(dl)
        next(it)
        out = acc.save_state(str(tmp_path / "ckpt"))
        import os as _os

        files = _os.listdir(out)
        assert any(f.startswith("dataloader") and f.endswith(".pkl") for f in files), files
        dl2 = DataLoaderShard(TensorStateDL(n_batches=4, batch_size=8))
        acc._dataloaders[0] = dl2
        acc.load_state(out)
        assert dl2.base_dataloader._pos == 1
        assert len(list(dl2)) == 3  # resumes past the consumed batch

    def test_epoch_boundary_resume_replays_full_fresh_epoch(self):
        """A checkpoint taken AFTER a completed epoch must resume at the next
        epoch's first batch — loading the exhausted inner position would
        silently yield an empty epoch."""
        from accelerate_tpu.data_loader import DataLoaderShard

        dl = DataLoaderShard(_FakeStatefulDataLoader(n_batches=2))
        assert len(list(dl)) == 2  # complete the epoch
        state = dl.state_dict()
        assert state["_iterator_finished"] is True
        dl2 = DataLoaderShard(_FakeStatefulDataLoader(n_batches=2))
        dl2.load_state_dict(state)
        assert len(list(dl2)) == 2  # fresh full epoch, not zero batches
        # and a mid-epoch checkpoint right after still reports unfinished
        it = iter(dl2)
        next(it)
        assert dl2.state_dict()["_iterator_finished"] is False

    def test_stateful_inner_state_always_pickled(self, tmp_path):
        """Opaque inner states must never round-trip through json: int dict
        keys would coerce to strings and mangle worker-state maps."""
        from accelerate_tpu import Accelerator
        from accelerate_tpu.data_loader import DataLoaderShard

        class IntKeyStateDL(_FakeStatefulDataLoader):
            def state_dict(self):
                return {"_num_yielded": getattr(self, "_yielded", 0),
                        "workers": {0: "a", 1: "b"}}  # int keys

            def load_state_dict(self, state):
                assert 0 in state["workers"], state  # keys must survive as ints
                self._pos = state["_num_yielded"]

        acc = Accelerator(cpu=True)
        dl = DataLoaderShard(IntKeyStateDL(n_batches=4, batch_size=8))
        acc._dataloaders.append(dl)
        it = iter(dl)
        next(it)
        out = acc.save_state(str(tmp_path / "ckpt"))
        import os as _os

        assert any(f.endswith(".pkl") and f.startswith("dataloader")
                   for f in _os.listdir(out))
        dl2 = DataLoaderShard(IntKeyStateDL(n_batches=4, batch_size=8))
        acc._dataloaders[0] = dl2
        acc.load_state(out)  # would KeyError on '0' if json had mangled keys
        assert len(list(dl2)) == 3


# ---------------------------------------------- async prefetch pipeline -------


class SleepyDataset:
    """Map-style dataset whose every item costs ``delay`` seconds of host IO —
    the overlap tests' stand-in for tokenization/disk reads."""

    def __init__(self, n, feat=4, delay=0.002):
        self.n = n
        self.feat = feat
        self.delay = delay

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        time.sleep(self.delay)
        return {"x": np.full((self.feat,), i, dtype=np.float32), "y": np.int32(i)}


class TestPrefetchPipeline:
    def test_batch_order_and_values_match_sync_path(self):
        state = AcceleratorState(parallelism_config=ParallelismConfig(dp_shard_size=8))
        # 200 rows: uneven tail exercises remainder bookkeeping in both modes
        sync = prepare_data_loader(
            DataLoader(RangeDataset(200), batch_size=16), state=state, prefetch_depth=0
        )
        pref = prepare_data_loader(
            DataLoader(RangeDataset(200), batch_size=16), state=state, prefetch_depth=3
        )
        gs = GradientState()
        sync_batches, sync_flags = [], []
        for b in sync:
            sync_batches.append(b)
            sync_flags.append((gs.end_of_dataloader, gs.remainder))
        pref_batches, pref_flags = [], []
        for b in pref:
            pref_batches.append(b)
            pref_flags.append((gs.end_of_dataloader, gs.remainder))
        assert len(sync_batches) == len(pref_batches)
        assert sync_flags == pref_flags
        for a, b in zip(sync_batches, pref_batches):
            np.testing.assert_array_equal(np.asarray(a["x"]), np.asarray(b["x"]))
            np.testing.assert_array_equal(np.asarray(a["y"]), np.asarray(b["y"]))
            assert a["x"].sharding.spec == b["x"].sharding.spec

    def test_prepared_resume_round_trip_with_prefetch(self):
        """Mid-epoch state_dict/load_state_dict with prefetch_depth>1: the
        producer running ahead must not leak into the recorded position."""
        state = AcceleratorState(parallelism_config=ParallelismConfig(dp_shard_size=8))
        dl = DataLoader(RangeDataset(512), batch_size=16, shuffle=True, seed=7)
        prepared = prepare_data_loader(dl, state=state, prefetch_depth=3)
        it = iter(prepared)
        next(it)
        next(it)
        sd = prepared.state_dict()
        assert sd["batches_seen"] == 2  # user consumed 2, producer was ahead
        dl2 = DataLoader(RangeDataset(512), batch_size=16, shuffle=True, seed=7)
        prepared2 = prepare_data_loader(dl2, state=state, prefetch_depth=3)
        prepared2.load_state_dict(sd)
        remaining = list(prepared2)
        rest = list(it)
        assert len(remaining) == len(rest) == 2
        for a, b in zip(remaining, rest):
            np.testing.assert_array_equal(np.asarray(a["y"]), np.asarray(b["y"]))

    def test_stateful_inner_resume_with_prefetch(self):
        from accelerate_tpu.data_loader import DataLoaderShard

        dl = DataLoaderShard(_FakeStatefulDataLoader(n_batches=6), prefetch_depth=3)
        it = iter(dl)
        consumed = [float(next(it)["x"][0, 0]) for _ in range(3)]
        mid_state = dl.state_dict()
        # the snapshot reflects the 3 CONSUMED batches, not the prefetched ones
        assert mid_state["_num_yielded"] == 3
        assert mid_state["_iterator_finished"] is False
        dl2 = DataLoaderShard(_FakeStatefulDataLoader(n_batches=6), prefetch_depth=3)
        dl2.load_state_dict(mid_state)
        rest = [float(b["x"][0, 0]) for b in dl2]
        assert consumed == [0.0, 1.0, 2.0] and rest == [3.0, 4.0, 5.0]

    def test_producer_exception_propagates(self):
        from accelerate_tpu.data_loader import DataLoaderShard

        class BoomDataset:
            def __len__(self):
                return 32

            def __getitem__(self, i):
                if i == 19:
                    raise ValueError("boom at item 19")
                return {"x": np.float32(i)}

        dl = DataLoaderShard(DataLoader(BoomDataset(), batch_size=4), prefetch_depth=2)
        got = []
        with pytest.raises(ValueError, match="boom at item 19"):
            for b in dl:
                got.append(b)
        assert len(got) <= 4  # batches before the poisoned one
        # the epoch's producer thread wound down with the iterator
        assert not [
            t for t in threading.enumerate() if t.name == "accelerate-tpu-prefetch"
        ]
        assert not GradientState().in_dataloader

    def test_abandoned_iterator_stops_producer(self):
        from accelerate_tpu.data_loader import DataLoaderShard

        dl = DataLoaderShard(DataLoader(RangeDataset(256), batch_size=8), prefetch_depth=2)
        it = iter(dl)
        next(it)
        it.close()  # user breaks out of the loop
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and [
            t for t in threading.enumerate() if t.name == "accelerate-tpu-prefetch"
        ]:
            time.sleep(0.01)
        assert not [
            t for t in threading.enumerate() if t.name == "accelerate-tpu-prefetch"
        ]

    def test_prefetch_overlap_beats_sync_wall_time_and_stall(self, tmp_path):
        """Acceptance: a dataset that sleeps per item must not inflate per-step
        wall time once prefetching overlaps it with (simulated) device compute
        — both the telemetry-reported per-step data wait and the 10-step wall
        time must be strictly below the synchronous path."""
        from accelerate_tpu.data_loader import DataLoaderShard
        from accelerate_tpu.telemetry import events as tel
        from accelerate_tpu.telemetry.report import build_report
        from accelerate_tpu.telemetry.step_profiler import StepTelemetry

        steps = 10
        compute_s = 0.02  # the "jitted step" the input pipeline should hide under

        def run(depth: int, out_dir) -> float:
            tel.enable(str(out_dir))
            # 2ms/item × batch 8 = ~16ms of host fetch per step
            dl = DataLoaderShard(
                DataLoader(SleepyDataset(8 * steps, delay=0.002), batch_size=8),
                prefetch_depth=depth,
            )
            st = StepTelemetry()
            t0 = time.monotonic()
            it = iter(dl)
            for _ in range(steps):
                batch = next(it)
                with st.step():
                    assert batch["x"].shape == (8, 4)
                    time.sleep(compute_s)
            wall = time.monotonic() - t0
            it.close()
            tel.disable()
            return wall

        wall_sync = run(0, tmp_path / "sync")
        wall_pref = run(2, tmp_path / "pref")
        rep_sync = build_report([str(tmp_path / "sync")])
        rep_pref = build_report([str(tmp_path / "pref")])
        # per-step data wait: sync pays the full fetch, prefetch only the stall
        assert rep_sync["steps"]["count"] == rep_pref["steps"]["count"] == steps
        assert (
            rep_pref["steps"]["data_wait_s"]["mean"]
            < rep_sync["steps"]["data_wait_s"]["mean"]
        )
        assert (
            rep_pref["data_pipeline"]["critical_wait_s"]
            < rep_sync["data_pipeline"]["critical_wait_s"]
        )
        assert wall_pref < wall_sync
        # the report attributes the phases: sync has no stall, prefetch does
        assert "stall" not in rep_sync["data_pipeline"]["phases"]
        assert rep_pref["data_pipeline"]["phases"]["stall"]["count"] >= steps
        assert rep_pref["data_pipeline"]["prefetch"]["overlap_ratio"] > 0.5

    def test_skip_batches_with_prefetch(self):
        state = AcceleratorState(parallelism_config=ParallelismConfig(dp_shard_size=8))
        prepared = prepare_data_loader(
            DataLoader(RangeDataset(512), batch_size=16), state=state, prefetch_depth=3
        )
        skipped = skip_first_batches(prepared, 2)
        batches = list(skipped)
        assert len(batches) == 2
        ys = np.concatenate([np.asarray(b["y"]) for b in batches])
        assert sorted(ys.tolist()) == list(range(256, 512))
