"""Example-corpus drift protection (reference ``tests/test_examples.py``
``ExampleDifferenceTests``: the ``by_feature`` one-feature scripts are diffed
against the ``complete_*`` examples so docs and examples cannot drift apart).

The native spelling of that property: the set of ``accelerator.<api>`` calls
(and ``Accelerator(...)`` kwargs) a by_feature script introduces BEYOND the
base ``nlp_example.py`` must appear in the corresponding ``complete_*``
example. If someone strips ``save_state`` from the complete example while the
checkpointing lesson still teaches it, this fails.
"""

import ast
import pathlib

import pytest

EXAMPLES = pathlib.Path(__file__).resolve().parent.parent / "examples"


def api_surface(path: pathlib.Path) -> "tuple[set, set]":
    """(accelerator.<attr> call/attribute names, Accelerator(...) kwarg names)."""
    tree = ast.parse(path.read_text())
    attrs, kwargs = set(), set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "accelerator"
        ):
            attrs.add(node.attr)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) and node.func.id == "Accelerator":
            kwargs |= {k.arg for k in node.keywords if k.arg}
    return attrs, kwargs


# by_feature lesson -> the complete example that must demonstrate it.
# Deliberately NOT mapped:
# - engine-flavored lessons (fsdp_training, zero_offload, fp8_training,
#   quantized_inference, sequence_packing, gradient_compression,
#   deepspeed_with_config_support, fsdp_with_peak_mem_tracking): they
#   configure the mesh/plugins rather than new Accelerator APIs, and
#   tests/test_examples.py runs them end-to-end;
# - auxiliary-utility lessons (memory + cross_validation -> free_memory,
#   profiler -> profile, local_sgd/schedule_free/automatic_gradient_
#   accumulation/gradient_accumulation_for_autoregressive_models): they teach
#   utilities the complete examples deliberately do not demonstrate (a
#   complete example with profiling/OOM-retry would obscure its own lesson).
# Every other lesson must be covered by a complete example, asserted below.
FEATURE_TO_COMPLETE = {
    "checkpointing.py": "complete_nlp_example.py",
    "early_stopping.py": "complete_nlp_example.py",
    "tracking.py": "complete_nlp_example.py",
    "gradient_accumulation.py": "complete_nlp_example.py",
    "multi_process_metrics.py": "complete_nlp_example.py",
}


@pytest.mark.parametrize("feature,complete", sorted(FEATURE_TO_COMPLETE.items()))
def test_complete_examples_cover_by_feature_lessons(feature, complete):
    base_attrs, base_kwargs = api_surface(EXAMPLES / "nlp_example.py")
    feat_attrs, feat_kwargs = api_surface(EXAMPLES / "by_feature" / feature)
    comp_attrs, comp_kwargs = api_surface(EXAMPLES / complete)
    missing_attrs = (feat_attrs - base_attrs) - comp_attrs
    missing_kwargs = (feat_kwargs - base_kwargs) - comp_kwargs
    assert not missing_attrs, (
        f"{complete} no longer demonstrates accelerator.{sorted(missing_attrs)} "
        f"taught by by_feature/{feature}"
    )
    assert not missing_kwargs, (
        f"{complete} no longer passes Accelerator({sorted(missing_kwargs)}) "
        f"taught by by_feature/{feature}"
    )


def test_every_by_feature_script_keeps_the_base_skeleton():
    """Each lesson stays a variation of the base training loop (reference
    ExampleDifferenceTests' premise): constructs Accelerator, prepares, and
    drives a train step through one of the supported spellings."""
    step_spellings = {
        "prepare_train_step", "prepare_train_loop", "_build_train_step",
        "backward", "accumulate",
    }
    # inference-only lessons legitimately skip the Accelerator training loop
    # (the reference's big-model-inference lessons do the same)
    inference_lessons = {"quantized_inference.py"}
    for script in sorted((EXAMPLES / "by_feature").glob("*.py")):
        if script.name in inference_lessons:
            continue
        attrs, _ = api_surface(script)
        # some lessons (memory/automatic accumulation) rebuild objects inside a
        # retry decorator and only touch prepare_train_step — any prepare*
        # spelling counts as "prepares through the Accelerator"
        assert any(a.startswith("prepare") for a in attrs), (
            f"{script.name} never prepares through the Accelerator"
        )
        assert attrs & step_spellings, (
            f"{script.name} drives no train step (none of {sorted(step_spellings)})"
        )


def test_complete_examples_superset_of_base():
    """complete_* must remain a strict superset of the base example's API use."""
    base_attrs, _ = api_surface(EXAMPLES / "nlp_example.py")
    comp_attrs, _ = api_surface(EXAMPLES / "complete_nlp_example.py")
    assert base_attrs <= comp_attrs | {"print"}, sorted(base_attrs - comp_attrs)
