"""Sequence packing: fixed-shape packed batches must be EXACTLY equivalent to
running each document alone (attention isolation, per-segment rope, loss
boundary masking). ``utils/packing.py`` + ``llama_forward(segment_ids=...)``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.models import LlamaConfig, init_llama, llama_forward, llama_loss
from accelerate_tpu.utils.packing import pack_sequences, unpack_logits


def test_pack_sequences_layout():
    ids, segs = pack_sequences([[1, 2, 3], [4, 5], [6, 7, 8, 9], [10]], seq_len=8)
    assert ids.shape == segs.shape and ids.shape[1] == 8
    # every token present exactly once, segments contiguous, padding = 0
    flat = ids[segs > 0]
    assert sorted(flat.tolist()) == list(range(1, 11))
    for r in range(segs.shape[0]):
        nz = segs[r][segs[r] > 0]
        assert (np.diff(nz) >= 0).all()  # segment numbers non-decreasing


def test_pack_sequences_long_doc_chunks_or_raises():
    ids, segs = pack_sequences([list(range(1, 12))], seq_len=4)
    assert (ids[segs > 0] > 0).sum() == 11
    with pytest.raises(ValueError):
        pack_sequences([list(range(1, 12))], seq_len=4, split_long=False)


def test_pack_sequences_rejects_empty_docs():
    with pytest.raises(ValueError, match="empty"):
        pack_sequences([[1, 2], [], [3]], seq_len=8)


@pytest.mark.slow
def test_packed_forward_matches_separate_docs():
    """Logits of each packed document == logits of that document run alone."""
    cfg = LlamaConfig.tiny()
    params = init_llama(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    docs = [rng.integers(1, cfg.vocab_size, size=n).tolist() for n in (12, 7, 9)]
    ids, segs = pack_sequences(docs, seq_len=20)
    packed = llama_forward(params, jnp.asarray(ids), cfg, segment_ids=jnp.asarray(segs),
                           attention_impl="xla")
    per_doc = unpack_logits(packed, segs)
    for doc, got in zip(docs, per_doc):
        alone = llama_forward(
            params, jnp.asarray(np.asarray(doc)[None, :]), cfg, attention_impl="xla"
        )[0]
        np.testing.assert_allclose(np.asarray(got), np.asarray(alone), rtol=2e-4, atol=2e-4)


def test_packed_loss_matches_separate_docs():
    """Packed LM loss == token-weighted mean of the separate per-doc losses."""
    cfg = LlamaConfig.tiny()
    params = init_llama(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    docs = [rng.integers(1, cfg.vocab_size, size=n).tolist() for n in (10, 6)]
    ids, segs = pack_sequences(docs, seq_len=16)
    assert ids.shape[0] == 1  # both fit one row — the interesting case
    packed_loss = float(llama_loss(
        params, {"input_ids": jnp.asarray(ids), "segment_ids": jnp.asarray(segs)}, cfg,
        attention_impl="xla",
    ))
    total, weight = 0.0, 0
    for doc in docs:
        l = float(llama_loss(
            params, {"input_ids": jnp.asarray(np.asarray(doc)[None, :])}, cfg,
            attention_impl="xla",
        ))
        total += l * (len(doc) - 1)  # doc contributes len-1 next-token targets
        weight += len(doc) - 1
    np.testing.assert_allclose(packed_loss, total / weight, rtol=2e-5)


def test_loss_masks_apply_with_kwarg_segment_ids():
    """segment_ids passed as a forward KWARG (not in batch) must still engage
    the boundary/padding loss masking — both spellings give the same loss."""
    cfg = LlamaConfig.tiny()
    params = init_llama(cfg, jax.random.PRNGKey(0))
    docs = [np.random.default_rng(2).integers(1, cfg.vocab_size, size=n).tolist() for n in (9, 5)]
    ids, segs = pack_sequences(docs, seq_len=16)
    via_batch = float(llama_loss(
        params, {"input_ids": jnp.asarray(ids), "segment_ids": jnp.asarray(segs)}, cfg,
        attention_impl="xla",
    ))
    via_kwarg = float(llama_loss(
        params, {"input_ids": jnp.asarray(ids)}, cfg,
        segment_ids=jnp.asarray(segs), attention_impl="xla",
    ))
    assert via_batch == via_kwarg


def test_pack_order_preserved_and_unpack_aligns():
    """Shelf packing must keep input order even when first-fit would reorder
    (lengths 12, 9, 7 with seq_len 20: first-fit would pack [a, c][b])."""
    rng = np.random.default_rng(3)
    docs = [rng.integers(1, 90, size=n).tolist() for n in (12, 9, 7)]
    ids, segs = pack_sequences(docs, seq_len=20)
    back = unpack_logits(ids[..., None], segs)  # unpack the ids themselves
    assert [b[:, 0].tolist() for b in back] == docs


def test_packed_rope_positions_restart():
    from accelerate_tpu.models.transformer import llama_forward as fwd

    cfg = LlamaConfig.tiny()
    params = init_llama(cfg, jax.random.PRNGKey(0))
    doc = np.arange(1, 9)  # 8 tokens
    # same doc packed at an OFFSET must produce identical logits (positions
    # restart per segment, attention isolated)
    ids = np.zeros((1, 20), np.int32)
    segs = np.zeros((1, 20), np.int32)
    ids[0, :5] = 7  # filler doc
    segs[0, :5] = 1
    ids[0, 5:13] = doc
    segs[0, 5:13] = 2
    out = fwd(params, jnp.asarray(ids), cfg, segment_ids=jnp.asarray(segs), attention_impl="xla")
    alone = fwd(params, jnp.asarray(doc[None, :]), cfg, attention_impl="xla")
    np.testing.assert_allclose(
        np.asarray(out[0, 5:13]), np.asarray(alone[0]), rtol=2e-4, atol=2e-4
    )


def test_segment_ids_with_attention_fn_rejected():
    from accelerate_tpu import ParallelismConfig
    from accelerate_tpu.parallel import make_context_parallel_attention

    cfg = LlamaConfig.tiny()
    params = init_llama(cfg, jax.random.PRNGKey(0))
    mesh = ParallelismConfig(cp_size=8).build_mesh()
    attn = make_context_parallel_attention(mesh, strategy="ring")
    with pytest.raises(ValueError, match="segment_ids"):
        llama_forward(
            params, jnp.ones((1, 16), jnp.int32), cfg,
            segment_ids=jnp.ones((1, 16), jnp.int32), attention_fn=attn,
        )
