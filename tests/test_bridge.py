"""torch-interop bridge tests (the north star; reference contract:
``src/accelerate/accelerator.py:1735 prepare_model`` + ``:2770 backward`` driving
``examples/nlp_example.py``'s torch loop).

Covers: fx→JAX lowering parity vs torch eager (forward, loss, gradients), the
full torch-style training loop through ``Accelerator.prepare`` /
``accelerator.backward`` / ``optimizer.step`` / torch LR scheduler, gradient
accumulation semantics, and DLPack round-trips."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")


def _tiny_bert(num_labels=2, seed=0):
    from transformers import BertConfig, BertForSequenceClassification

    torch.manual_seed(seed)
    cfg = BertConfig(
        vocab_size=100,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=2,
        intermediate_size=64,
        max_position_embeddings=64,
        problem_type="single_label_classification",
        num_labels=num_labels,
        hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
    )
    return BertForSequenceClassification(cfg)


def _batch(n=4, seq=16, vocab=100, num_labels=2, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(10, vocab, (n, seq)).astype(np.int64)
    # learnable: label = f(planted keyword), same shape as the nlp example task
    keywords = rng.integers(2, 10, n)
    ids[:, 1] = keywords
    ids[:, 2] = keywords
    return {
        "input_ids": ids,
        "attention_mask": np.ones((n, seq), np.int64),
        "token_type_ids": np.zeros((n, seq), np.int64),
        "labels": (keywords >= 6).astype(np.int64),
    }


@pytest.mark.smoke
def test_slice_scatter_negative_end_matches_aten():
    # end=-1 means size-1 in ATen slice semantics (ADVICE r03)
    import jax.numpy as jnp

    from accelerate_tpu.bridge.aten_lowering import _aten_handlers

    h = _aten_handlers()["aten.slice_scatter.default"]
    base = torch.arange(12, dtype=torch.float32).reshape(3, 4)
    src = torch.full((3, 2), -1.0)
    expected = torch.slice_scatter(base, src, dim=1, start=1, end=-1)
    got = h(None, jnp.asarray(base.numpy()), jnp.asarray(src.numpy()), 1, 1, -1)
    np.testing.assert_array_equal(np.asarray(got), expected.numpy())
    # end below -size clamps to 0 => empty window, base unchanged (ATen clamp)
    empty = torch.empty((3, 0))
    expected2 = torch.slice_scatter(base, empty, dim=1, start=1, end=-5)
    got2 = h(None, jnp.asarray(base.numpy()), jnp.asarray(empty.numpy()), 1, 1, -5)
    np.testing.assert_array_equal(np.asarray(got2), expected2.numpy())


class TestLoweringParity:
    def test_forward_loss_logits_match_torch(self):
        from accelerate_tpu.bridge import lower_module

        model = _tiny_bert().eval()
        fn, params, buffers = lower_module(
            model, ["input_ids", "attention_mask", "token_type_ids", "labels"]
        )
        batch = _batch()
        out = fn(params, buffers, batch, train=False)
        tout = model(**{k: torch.from_numpy(v) for k, v in batch.items()})
        assert abs(float(np.asarray(out["loss"])) - float(tout.loss)) < 1e-4
        np.testing.assert_allclose(
            np.asarray(out["logits"]), tout.logits.detach().numpy(), atol=1e-4
        )

    def test_grads_match_torch_autograd(self):
        import jax

        from accelerate_tpu.bridge import lower_module

        model = _tiny_bert().eval()
        fn, params, buffers = lower_module(
            model, ["input_ids", "attention_mask", "token_type_ids", "labels"]
        )
        batch = _batch()

        grads = jax.grad(lambda p: fn(p, buffers, batch, train=False)["loss"])(params)
        tout = model(**{k: torch.from_numpy(v) for k, v in batch.items()})
        tout.loss.backward()
        for name, p in model.named_parameters():
            if p.grad is None:
                continue
            np.testing.assert_allclose(
                np.asarray(grads[name]), p.grad.numpy(), atol=2e-4,
                err_msg=f"grad mismatch at {name}",
            )


class TestTorchStyleLoop:
    def _make(self, accelerator, n=64, lr=5e-3, step_size=100_000):
        from accelerate_tpu import DataLoader

        model = _tiny_bert()
        optimizer = torch.optim.AdamW(model.parameters(), lr=lr)
        scheduler = torch.optim.lr_scheduler.StepLR(optimizer, step_size=step_size, gamma=0.5)
        data = _batch(n=n, seed=1)

        class DS:
            def __len__(self):
                return n

            def __getitem__(self, i):
                return {k: v[i] for k, v in data.items()}

        dl = DataLoader(DS(), batch_size=8)
        return accelerator.prepare(model, optimizer, dl, scheduler)

    def test_training_loop_reduces_loss(self):
        from accelerate_tpu import Accelerator

        accelerator = Accelerator(mixed_precision="no", rng_seed=0)
        model, optimizer, dl, scheduler = self._make(accelerator)
        model.train()
        losses = []
        for epoch in range(8):
            for batch in dl:
                outputs = model(**batch)
                loss = outputs.loss
                accelerator.backward(loss)
                optimizer.step()
                scheduler.step()
                optimizer.zero_grad()
                losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])

    def test_eval_mode_and_metrics_gather(self):
        from accelerate_tpu import Accelerator

        accelerator = Accelerator(mixed_precision="no", rng_seed=0)
        model, optimizer, dl, scheduler = self._make(accelerator)
        model.eval()
        total = 0
        for batch in dl:
            outputs = model(**batch)
            predictions = outputs.logits.argmax(dim=-1)
            g = accelerator.gather_for_metrics({"p": predictions, "l": batch["labels"]})
            assert np.asarray(g["p"]).shape[0] == np.asarray(g["l"]).shape[0]
            total += np.asarray(g["p"]).shape[0]
        assert total == 64

    def test_torch_scheduler_drives_bridged_lr(self):
        from accelerate_tpu import Accelerator

        accelerator = Accelerator(mixed_precision="no", rng_seed=0)
        model, optimizer, dl, scheduler = self._make(accelerator, lr=1e-2, step_size=100)
        model.train()
        # StepLR(step_size=100): after 100 scheduler advances lr halves; our
        # AcceleratedScheduler advances num_processes (=8) per step → 13 steps
        batch = next(iter(dl))
        for _ in range(13):
            out = model(**batch)
            accelerator.backward(out.loss)
            optimizer.step()
            scheduler.step()
            optimizer.zero_grad()
        assert optimizer.param_groups[0]["lr"] == pytest.approx(5e-3)

    def test_grad_accumulation_matches_large_batch(self):
        """Two backwards + one step == one step on the concatenated batch."""
        import jax

        from accelerate_tpu import Accelerator

        def run(split):
            from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

            AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
            accelerator = Accelerator(mixed_precision="no", rng_seed=0)
            model = _tiny_bert(seed=3)
            optimizer = torch.optim.SGD(model.parameters(), lr=1e-1)
            model, optimizer = accelerator.prepare(model, optimizer)
            model.train()
            big = _batch(n=16, seed=2)
            if split:
                for half in (slice(0, 8), slice(8, 16)):
                    out = model(**{k: v[half] for k, v in big.items()})
                    accelerator.backward(out.loss)
            else:
                out = model(**big)
                accelerator.backward(out.loss)
            optimizer.step()
            optimizer.zero_grad()
            return {k: np.asarray(jax.device_get(v)) for k, v in model.params.items()}

        p_split = run(True)
        p_whole = run(False)
        for k in p_whole:
            np.testing.assert_allclose(p_split[k], p_whole[k], atol=1e-5, err_msg=k)


class TestDLPack:
    def test_roundtrip(self):
        from accelerate_tpu.bridge import jax_to_torch, torch_to_jax

        t = torch.arange(12, dtype=torch.float32).reshape(3, 4)
        j = torch_to_jax(t)
        assert j.shape == (3, 4)
        t2 = jax_to_torch(j)
        assert torch.equal(t, t2)

    def test_write_back(self):
        import jax.numpy as jnp

        from accelerate_tpu.bridge import write_back_to_module

        lin = torch.nn.Linear(4, 2)
        new_w = jnp.ones((2, 4))
        write_back_to_module(lin, {"weight": new_w})
        assert torch.equal(lin.weight.detach(), torch.ones(2, 4))

    def test_sync_to_torch_after_training(self):
        from accelerate_tpu import Accelerator

        accelerator = Accelerator(mixed_precision="no", rng_seed=0)
        tm = _tiny_bert()
        before = {n: p.detach().clone() for n, p in tm.named_parameters()}
        model, optimizer = accelerator.prepare(
            tm, torch.optim.SGD(tm.parameters(), lr=1e-1)
        )
        model.train()
        out = model(**_batch())
        accelerator.backward(out.loss)
        optimizer.step()
        model.sync_to_torch()
        changed = any(
            not torch.equal(before[n], p.detach()) for n, p in tm.named_parameters()
        )
        assert changed


def _tiny_gpt2(seed=0):
    from transformers import GPT2Config, GPT2LMHeadModel

    torch.manual_seed(seed)
    cfg = GPT2Config(
        vocab_size=100, n_positions=64, n_embd=32, n_layer=2, n_head=2,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0, use_cache=False,
    )
    return GPT2LMHeadModel(cfg)


def _tiny_llama(seed=0):
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(seed)
    cfg = LlamaConfig(
        vocab_size=100, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, intermediate_size=64, max_position_embeddings=64,
        use_cache=False,
    )
    return LlamaForCausalLM(cfg)


def _lm_batch(n=2, seq=16, vocab=100, seed=0):
    ids = np.random.default_rng(seed).integers(1, vocab, (n, seq)).astype(np.int64)
    return {"input_ids": ids, "labels": ids.copy()}


class TestDecoderBridge:
    """Decoder families through the torch.export/ATen path (round-2 verdict
    item 4: transformers.utils.fx no longer traces GPT-2/Llama)."""

    @pytest.mark.parametrize("make_model", [_tiny_gpt2, _tiny_llama])
    def test_forward_loss_matches_torch(self, make_model):
        from accelerate_tpu.bridge.aten_lowering import lower_module_aten

        model = make_model().eval()
        batch = _lm_batch()
        fn, params, buffers = lower_module_aten(model, batch)
        out = fn(params, buffers, batch, train=False)
        tout = model(**{k: torch.from_numpy(v) for k, v in batch.items()})
        assert abs(float(np.asarray(out["loss"])) - float(tout.loss)) < 1e-4
        np.testing.assert_allclose(
            np.asarray(out["logits"]), tout.logits.detach().numpy(), atol=1e-4
        )

    @pytest.mark.parametrize("make_model", [_tiny_gpt2, _tiny_llama])
    def test_grads_match_torch_autograd(self, make_model):
        import jax

        from accelerate_tpu.bridge.aten_lowering import lower_module_aten

        model = make_model().eval()
        batch = _lm_batch(seed=1)
        fn, params, buffers = lower_module_aten(model, batch)
        grads = jax.grad(lambda p: fn(p, buffers, batch, train=False)["loss"])(params)
        tout = model(**{k: torch.from_numpy(v) for k, v in batch.items()})
        tout.loss.backward()
        # tied weights: jax grads accumulate on the canonical (first-seen) name
        for name, p in model.named_parameters():
            if p.grad is None or name not in grads:
                continue
            np.testing.assert_allclose(
                np.asarray(grads[name]), p.grad.numpy(), atol=3e-4,
                err_msg=f"grad mismatch at {name}",
            )

    def test_gpt2_generate_matches_hf_greedy(self):
        from accelerate_tpu.bridge import BridgedModule

        model = _tiny_gpt2(seed=2)
        prompt = np.random.default_rng(2).integers(1, 100, (2, 8)).astype(np.int64)
        bridged = BridgedModule(model)
        ours = bridged.generate(prompt, max_new_tokens=6)

        model.config.use_cache = True
        ref = model.generate(
            torch.from_numpy(prompt), max_new_tokens=6, do_sample=False,
            pad_token_id=0,
        ).numpy()
        np.testing.assert_array_equal(ours, ref)

    def test_gpt2_generate_ragged_prompts(self):
        """Right-padded ragged batch with attention_mask: each row must match
        generating its own unpadded prompt alone (pads never attended)."""
        from accelerate_tpu.bridge import BridgedModule

        model = _tiny_gpt2(seed=2)
        rng = np.random.default_rng(5)
        row0 = rng.integers(1, 100, (5,)).astype(np.int64)
        row1 = rng.integers(1, 100, (8,)).astype(np.int64)
        ids = np.zeros((2, 8), np.int64)
        ids[0, :5], ids[1] = row0, row1
        mask = np.zeros((2, 8), np.int64)
        mask[0, :5], mask[1] = 1, 1
        bridged = BridgedModule(model)
        out = bridged.generate(ids, max_new_tokens=4, attention_mask=mask)
        ref0 = bridged.generate(row0[None], max_new_tokens=4)[0]
        ref1 = bridged.generate(row1[None], max_new_tokens=4)[0]
        np.testing.assert_array_equal(out[0, : ref0.shape[0]], ref0)
        np.testing.assert_array_equal(out[1, : ref1.shape[0]], ref1)

    def test_gpt2_training_loop_through_accelerator(self):
        """torch-style loop: prepared GPT-2 trains (loss drops) through
        accelerator.backward / optimizer.step with the ATen-lowered forward."""
        from accelerate_tpu import Accelerator, DataLoader

        accelerator = Accelerator(mixed_precision="no", rng_seed=0)
        model = _tiny_gpt2(seed=3)
        optimizer = torch.optim.AdamW(model.parameters(), lr=1e-2)
        data = _lm_batch(n=16, seq=16, seed=3)

        class DS:
            def __len__(self):
                return 16

            def __getitem__(self, i):
                return {k: v[i] for k, v in data.items()}

        model, optimizer, dl = accelerator.prepare(model, optimizer, DataLoader(DS(), batch_size=8))
        model.train()
        losses = []
        for epoch in range(12):
            for batch in dl:
                outputs = model(**batch)
                accelerator.backward(outputs.loss)
                optimizer.step()
                optimizer.zero_grad()
                losses.append(float(outputs.loss))
        assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def _tiny_t5(seed=0):
    from transformers import T5Config, T5ForConditionalGeneration

    torch.manual_seed(seed)
    cfg = T5Config(
        vocab_size=100, d_model=32, d_kv=8, d_ff=64, num_layers=2, num_heads=4,
        dropout_rate=0.0, decoder_start_token_id=0, use_cache=False,
    )
    return T5ForConditionalGeneration(cfg)


def _seq2seq_batch(n=2, src=16, tgt=8, vocab=100, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "input_ids": rng.integers(1, vocab, (n, src)).astype(np.int64),
        "attention_mask": np.ones((n, src), np.int64),
        "labels": rng.integers(1, vocab, (n, tgt)).astype(np.int64),
    }


class TestEncoderDecoderBridge:
    """T5 (encoder-decoder) through the torch.export path. Exercises the
    mutation-functionalization route: T5's ``_shift_right`` writes labels
    through a slice view (``aten.copy_`` on ``aten.slice``), which forces
    ``run_decompositions`` and the slice_scatter/select_scatter/copy/fill
    handlers."""

    def test_forward_loss_matches_torch(self):
        from accelerate_tpu.bridge.aten_lowering import lower_module_aten

        model = _tiny_t5().eval()
        batch = _seq2seq_batch()
        fn, params, buffers = lower_module_aten(model, batch)
        out = fn(params, buffers, batch, train=False)
        tout = model(**{k: torch.from_numpy(v) for k, v in batch.items()})
        assert abs(float(np.asarray(out["loss"])) - float(tout.loss)) < 1e-4
        np.testing.assert_allclose(
            np.asarray(out["logits"]), tout.logits.detach().numpy(), atol=1e-4
        )

    def test_grads_match_torch_autograd(self):
        import jax

        from accelerate_tpu.bridge.aten_lowering import lower_module_aten

        model = _tiny_t5().eval()
        batch = _seq2seq_batch(seed=1)
        fn, params, buffers = lower_module_aten(model, batch)
        grads = jax.grad(lambda p: fn(p, buffers, batch, train=False)["loss"])(params)
        tout = model(**{k: torch.from_numpy(v) for k, v in batch.items()})
        tout.loss.backward()
        for name, p in model.named_parameters():
            if p.grad is None or name not in grads:
                continue
            np.testing.assert_allclose(
                np.asarray(grads[name]), p.grad.numpy(), atol=3e-4,
                err_msg=f"grad mismatch at {name}",
            )

    def test_t5_generate_matches_hf_greedy(self):
        from transformers import T5Config, T5ForConditionalGeneration

        from accelerate_tpu.bridge import BridgedModule

        torch.manual_seed(1)
        # large init scale → diverse argmax tokens (default tiny init degenerates
        # to a constant token, which would vacuously pass)
        cfg = T5Config(
            vocab_size=100, d_model=32, d_kv=8, d_ff=64, num_layers=2, num_heads=4,
            dropout_rate=0.0, decoder_start_token_id=0, use_cache=False,
            initializer_factor=20.0,
        )
        model = T5ForConditionalGeneration(cfg).eval()
        bm = BridgedModule(model)
        ids = np.random.default_rng(1).integers(2, 100, (2, 12)).astype(np.int64)
        got = bm.generate(ids, max_new_tokens=6)
        model.config.use_cache = True
        ref = model.generate(
            torch.from_numpy(ids), max_new_tokens=6, do_sample=False, num_beams=1
        ).numpy()
        width = min(got.shape[1], ref.shape[1])
        np.testing.assert_array_equal(got[:, :width], ref[:, :width])
        assert len(set(got.flatten().tolist())) > 3  # non-degenerate decode

    def test_eos_list_and_config_pad_handling(self):
        from accelerate_tpu.bridge.module import _is_eos

        tok = np.asarray([1, 2, 3, 5])
        np.testing.assert_array_equal(_is_eos(tok, [1, 2]), [True, True, False, False])
        np.testing.assert_array_equal(_is_eos(tok, 5), [False, False, False, True])
        # B == len(eos_list): membership, not positional broadcasting
        np.testing.assert_array_equal(_is_eos(np.asarray([2, 1]), [1, 2]), [True, True])

    def test_bridged_module_trains(self):
        model = _tiny_t5()
        batch = {k: torch.from_numpy(v) for k, v in _seq2seq_batch(n=4).items()}
        losses = []
        import torch.optim as topt

        from accelerate_tpu import Accelerator

        acc = Accelerator(cpu=True)
        bm2, opt = acc.prepare(model, topt.AdamW(model.parameters(), lr=5e-3))
        for _ in range(12):
            out = bm2(**batch)
            acc.backward(out.loss)
            opt.step()
            opt.zero_grad()
            losses.append(float(out.loss))
        assert losses[-1] < losses[0]


class TestNativeGeneration:
    def test_cached_greedy_matches_full_forward(self):
        import jax
        import jax.numpy as jnp

        from accelerate_tpu.generation import greedy_generate
        from accelerate_tpu.models import LlamaConfig, init_llama
        from accelerate_tpu.models.transformer import llama_forward

        cfg = LlamaConfig.tiny()
        params = init_llama(cfg, jax.random.PRNGKey(0))
        prompt = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 8)).astype(np.int32)

        ids = jnp.asarray(prompt)
        for _ in range(5):
            logits = llama_forward(params, ids, cfg)
            ids = jnp.concatenate(
                [ids, jnp.argmax(logits[:, -1], axis=-1)[:, None].astype(ids.dtype)], axis=1
            )
        ref = np.asarray(ids)
        out = greedy_generate(params, prompt, cfg, max_new_tokens=5, cache_dtype=jnp.float32)
        np.testing.assert_array_equal(out, ref)

    def test_dispatched_generate_with_cpu_offload(self):
        import jax
        import jax.numpy as jnp

        from accelerate_tpu.big_modeling import cpu_offload
        from accelerate_tpu.generation import (
            generate_dispatched,
            greedy_generate,
            unstack_layer_params,
        )
        from accelerate_tpu.models import LlamaConfig, init_llama

        cfg = LlamaConfig.tiny()
        params = init_llama(cfg, jax.random.PRNGKey(1))
        prompt = np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 8)).astype(np.int32)
        ref = greedy_generate(params, prompt, cfg, max_new_tokens=5, cache_dtype=jnp.float32)

        dp = cpu_offload(unstack_layer_params(params, cfg))
        out, stats = generate_dispatched(
            dp, prompt, cfg, max_new_tokens=5, cache_dtype=jnp.float32, return_stats=True
        )
        np.testing.assert_array_equal(out, ref)
        assert stats["decode_tokens_per_sec"] > 0


def test_gpt2_generate_eos_parity_mixed_finish():
    """Rows that finish at different steps: positions after a row's first eos
    must be pad_token_id, matching HF greedy semantics."""
    from accelerate_tpu.bridge import BridgedModule

    model = _tiny_gpt2(seed=4)
    prompt = np.random.default_rng(4).integers(1, 100, (3, 8)).astype(np.int64)
    bridged = BridgedModule(model)
    # pick the token the model actually emits first for row 0 as the "eos" so
    # rows finish at different times
    probe = bridged.generate(prompt, max_new_tokens=4)
    eos = int(probe[0, 8])
    ours = bridged.generate(prompt, max_new_tokens=6, eos_token_id=eos, pad_token_id=0)

    model.config.use_cache = True
    ref = model.generate(
        torch.from_numpy(prompt), max_new_tokens=6, do_sample=False,
        eos_token_id=eos, pad_token_id=0,
    ).numpy()
    np.testing.assert_array_equal(ours[:, : ref.shape[1]], ref)


class TestSampledGeneration:
    """sample_generate: HF do_sample-style temperature/top-k/top-p decoding."""

    def _setup(self):
        import dataclasses

        import jax
        from accelerate_tpu.models import LlamaConfig, init_llama

        cfg = dataclasses.replace(LlamaConfig.tiny(), n_layers=2)
        params = init_llama(cfg, jax.random.PRNGKey(0))
        prompt = np.random.default_rng(0).integers(2, cfg.vocab_size, (2, 6)).astype(np.int32)
        return cfg, params, prompt

    def test_temperature_zero_equals_greedy(self):
        import jax
        import jax.numpy as jnp
        from accelerate_tpu.generation import greedy_generate, sample_generate

        cfg, params, prompt = self._setup()
        ref = greedy_generate(params, prompt, cfg, max_new_tokens=5, cache_dtype=jnp.float32)
        out = sample_generate(params, prompt, cfg, max_new_tokens=5, temperature=0.0,
                              rng_key=jax.random.PRNGKey(3), cache_dtype=jnp.float32)
        np.testing.assert_array_equal(out, ref)

    def test_top_k_one_equals_greedy(self):
        import jax
        import jax.numpy as jnp
        from accelerate_tpu.generation import greedy_generate, sample_generate

        cfg, params, prompt = self._setup()
        ref = greedy_generate(params, prompt, cfg, max_new_tokens=5, cache_dtype=jnp.float32)
        out = sample_generate(params, prompt, cfg, max_new_tokens=5, temperature=1.0,
                              top_k=1, rng_key=jax.random.PRNGKey(3), cache_dtype=jnp.float32)
        np.testing.assert_array_equal(out, ref)

    def test_deterministic_per_key_and_varies_across_keys(self):
        import jax
        import jax.numpy as jnp
        from accelerate_tpu.generation import sample_generate

        cfg, params, prompt = self._setup()
        kw = dict(max_new_tokens=8, temperature=1.5, cache_dtype=jnp.float32)
        a1 = sample_generate(params, prompt, cfg, rng_key=jax.random.PRNGKey(1), **kw)
        a2 = sample_generate(params, prompt, cfg, rng_key=jax.random.PRNGKey(1), **kw)
        b = sample_generate(params, prompt, cfg, rng_key=jax.random.PRNGKey(2), **kw)
        np.testing.assert_array_equal(a1, a2)
        assert not np.array_equal(a1, b)  # hot sampling; 2^-? collision odds ~0

    def test_sample_token_logits_masks(self):
        import jax
        import jax.numpy as jnp
        from accelerate_tpu.generation import sample_token_logits

        # one dominant token: top_p=0.5 must keep only it -> always sampled
        logits = jnp.asarray([[10.0, 0.0, 0.0, 0.0]])
        for seed in range(5):
            tok = sample_token_logits(logits, jax.random.PRNGKey(seed),
                                      temperature=1.0, top_p=0.5)
            assert int(tok[0]) == 0
        # top_k=2 on known order: only indices {3, 2} can appear
        logits = jnp.asarray([[0.0, 1.0, 2.0, 3.0]])
        seen = {int(sample_token_logits(logits, jax.random.PRNGKey(s),
                                        temperature=2.0, top_k=2)[0])
                for s in range(30)}
        assert seen <= {2, 3} and seen, seen


class TestBeamSearch:
    def _setup(self):
        import dataclasses

        import jax
        from accelerate_tpu.models import LlamaConfig, init_llama

        cfg = dataclasses.replace(LlamaConfig.tiny(), n_layers=2)
        params = init_llama(cfg, jax.random.PRNGKey(0))
        prompt = np.random.default_rng(1).integers(2, cfg.vocab_size, (2, 5)).astype(np.int32)
        return cfg, params, prompt

    def test_beam_one_equals_greedy(self):
        import jax.numpy as jnp
        from accelerate_tpu.generation import beam_generate, greedy_generate

        cfg, params, prompt = self._setup()
        ref = greedy_generate(params, prompt, cfg, max_new_tokens=5, cache_dtype=jnp.float32)
        out = beam_generate(params, prompt, cfg, num_beams=1, max_new_tokens=5,
                            cache_dtype=jnp.float32)
        np.testing.assert_array_equal(out, ref)

    def test_matches_numpy_reference_beam(self):
        """Exact check vs a brute-force numpy beam search driven by the
        full (uncached) forward."""
        import jax
        import jax.numpy as jnp
        from accelerate_tpu.generation import beam_generate
        from accelerate_tpu.models import llama_forward

        cfg, params, prompt = self._setup()
        K, N = 3, 4
        out, scores = beam_generate(params, prompt, cfg, num_beams=K,
                                    max_new_tokens=N, cache_dtype=jnp.float32,
                                    return_scores=True)

        def logp_all(ids):  # ids [n, S] -> last-position log-probs [n, V]
            logits = llama_forward(params, jnp.asarray(ids), cfg, attention_impl="xla")
            return np.asarray(jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), axis=-1))

        for b in range(prompt.shape[0]):
            beams = [(list(prompt[b]), 0.0)]
            for _ in range(N):
                cands = []
                lp = logp_all(np.asarray([s for s, _ in beams], np.int32))
                for (seq, sc), row in zip(beams, lp):
                    top = np.argsort(row)[::-1][: K]
                    for t in top:
                        cands.append((seq + [int(t)], sc + float(row[t])))
                cands.sort(key=lambda x: -x[1])
                beams = cands[:K]
            best_seq, best_score = beams[0]
            np.testing.assert_array_equal(out[b], np.asarray(best_seq))
            # modern-HF normalization: generated length only
            expected = best_score / N
            assert abs(scores[b] - expected) < 1e-4, (scores[b], expected)

    def test_beam_finds_higher_likelihood_than_greedy(self):
        import jax
        import jax.numpy as jnp
        from accelerate_tpu.generation import beam_generate, greedy_generate
        from accelerate_tpu.models import llama_forward

        cfg, params, prompt = self._setup()
        N = 6

        def seq_logp(full):  # sum of chosen-token log-probs over the generated tail
            logits = llama_forward(params, jnp.asarray(full[:, :-1]), cfg, attention_impl="xla")
            lp = np.asarray(jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1))
            S = prompt.shape[1]
            tot = 0.0
            for b in range(full.shape[0]):
                for i in range(N):
                    tot += lp[b, S - 1 + i, full[b, S + i]]
            return tot

        g = greedy_generate(params, prompt, cfg, max_new_tokens=N, cache_dtype=jnp.float32)
        bm = beam_generate(params, prompt, cfg, num_beams=4, max_new_tokens=N,
                           cache_dtype=jnp.float32)
        assert seq_logp(np.asarray(bm)) >= seq_logp(np.asarray(g)) - 1e-5
