"""ZeRO-1 as a GSPMD sharding: params replicated, optimizer state sharded
across the dp_replicate axis (``parallel.sharding.zero1_state_specs``;
technique of arXiv:2004.13336 — XLA partitions the elementwise update math).

Reference counterpart: DeepSpeed stage-1 (`DeepSpeedPlugin(zero_stage=1)`),
whose engine shards the Adam state across DP ranks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, DeepSpeedPlugin


def _kinds(tree):
    return {
        str(x.sharding.spec)
        for x in jax.tree_util.tree_leaves(tree)
        if hasattr(x, "sharding") and hasattr(x.sharding, "spec")
    }


def test_zero1_shards_opt_state_not_params():
    acc = Accelerator(cpu=True, deepspeed_plugin=DeepSpeedPlugin(zero_stage=1))
    assert acc._zero1_axis == "dp_replicate"
    assert acc.mesh.shape["dp_replicate"] == 8
    params = {"w": jnp.ones((64, 16)), "b": jnp.ones((16,))}
    params, opt = acc.prepare(params, optax.adam(1e-2))
    # params replicated (all spec axes None)
    for x in jax.tree_util.tree_leaves(params):
        assert all(ax is None for ax in tuple(x.sharding.spec)), x.sharding
    # the fused bucketed path engaged on this pure-DP mesh, with the adam
    # moments (now 1-D buckets) sharded over dp_replicate
    assert opt.fused_zero1
    specs = _kinds(opt.opt_state)
    assert any("dp_replicate" in s for s in specs), specs


def test_zero1_state_memory_is_split():
    acc = Accelerator(cpu=True, deepspeed_plugin=DeepSpeedPlugin(zero_stage=1))
    params, opt = acc.prepare({"w": jnp.ones((64, 16))}, optax.adam(1e-2))
    # fused ZeRO-1 stores adam moments as 1-D buckets; each device holds 1/8
    mu = opt.opt_state[0].mu
    assert set(mu) == {"b000"}  # one bucket for this tiny tree
    bucket = mu["b000"]
    assert bucket.shape == (64 * 16,)
    shard = next(iter(bucket.addressable_shards))
    assert shard.data.shape == (64 * 16 // 8,)


def test_zero1_training_matches_unsharded_baseline():
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    def run(plugin):
        AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
        acc = Accelerator(cpu=True, deepspeed_plugin=plugin)
        params, opt = acc.prepare({"w": jnp.ones((32, 8), jnp.float32)}, optax.adam(1e-2))

        def loss_fn(p, b):
            return jnp.mean((b["x"] @ p["w"]) ** 2)

        step = acc.prepare_train_step(loss_fn, opt)
        s = opt.opt_state
        rng = np.random.default_rng(0)
        for _ in range(5):
            b = {"x": jnp.asarray(rng.normal(size=(16, 32)), jnp.float32)}
            params, s, m = step(params, s, b)
        return np.asarray(jax.device_get(params["w"])), float(m["loss"])

    w0, l0 = run(DeepSpeedPlugin(zero_stage=0))   # replicated everything
    w1, l1 = run(DeepSpeedPlugin(zero_stage=1))   # sharded optimizer state
    np.testing.assert_array_equal(w1, w0)  # weights bit-identical on the CPU mesh
    assert abs(l0 - l1) < 1e-5  # loss reduction order differs in the last ulps


def test_deepspeed_env_protocol_builds_plugin(tmp_path):
    """accelerate-tpu launch --use_deepspeed ... → ACCELERATE_DEEPSPEED_* env →
    Accelerator() builds the plugin (reference utils/launch.py:557-577)."""
    import argparse
    import json

    from accelerate_tpu.commands.launch import deepspeed_env
    from accelerate_tpu.utils import patch_environment

    ns = argparse.Namespace(
        use_deepspeed=True, zero_stage=1, offload_optimizer_device="cpu",
        offload_param_device=None, gradient_clipping=0.5, deepspeed_config_file=None,
    )
    env = deepspeed_env(ns)
    assert env["ACCELERATE_USE_DEEPSPEED"] == "true"
    assert env["ACCELERATE_DEEPSPEED_ZERO_STAGE"] == "1"
    assert env["ACCELERATE_GRADIENT_CLIPPING"] == "0.5"
    assert "ACCELERATE_DEEPSPEED_OFFLOAD_PARAM_DEVICE" not in env

    # flags absent entirely → no DS env at all
    assert deepspeed_env(argparse.Namespace()) == {}

    ds_file = tmp_path / "ds.json"
    ds_file.write_text(json.dumps({"zero_optimization": {"stage": 2}, "gradient_clipping": 1.5}))
    with patch_environment(
        ACCELERATE_USE_DEEPSPEED="true",
        ACCELERATE_DEEPSPEED_CONFIG_FILE=str(ds_file),
    ):
        plugin = DeepSpeedPlugin.from_env()
        assert plugin.zero_stage == 2
        assert plugin.gradient_clipping == 1.5
        # and a fresh Accelerator picks the plugin up from env
        acc = Accelerator(cpu=True)
        assert acc._plugin_grad_clip == 1.5
        assert acc.mesh.shape["dp_shard"] == 8  # stage 2 → FSDP mesh

    with patch_environment(
        ACCELERATE_USE_DEEPSPEED="true",
        ACCELERATE_DEEPSPEED_ZERO_STAGE="1",
        ACCELERATE_DEEPSPEED_OFFLOAD_OPTIMIZER_DEVICE="cpu",
    ):
        plugin = DeepSpeedPlugin.from_env()
        assert plugin.zero_stage == 1 and plugin.offload_optimizer_device == "cpu"


def test_explicit_plugin_flags_beat_ds_config():
    """--zero_stage 1 + ds.json stage 2 → explicit wins, with a warning
    (the reference errors on flag/config mismatches)."""
    with pytest.warns(UserWarning, match="disagrees"):
        p = DeepSpeedPlugin(zero_stage=1, hf_ds_config={"zero_optimization": {"stage": 2}})
    assert p.zero_stage == 1
    # defaults still filled from config, no warning
    p = DeepSpeedPlugin(hf_ds_config={"zero_optimization": {"stage": 3}})
    assert p.zero_stage == 3


def test_aux_flags_alone_do_not_activate_deepspeed(capsys):
    import argparse

    from accelerate_tpu.commands.launch import deepspeed_env

    ns = argparse.Namespace(
        use_deepspeed=False, zero_stage=None, offload_optimizer_device="none",
        offload_param_device=None, gradient_clipping=1.0, deepspeed_config_file=None,
    )
    assert deepspeed_env(ns) == {}
    assert "ignoring DeepSpeed flags" in capsys.readouterr().err


def test_zero1_specs_leave_sharded_and_scalar_leaves_alone():
    from jax.sharding import Mesh, PartitionSpec as P

    from accelerate_tpu.parallel import zero1_state_specs

    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("dp_replicate", "tp"))
    state = {
        "mu": jnp.ones((8, 4)),      # replicated → shard dim0
        "count": jnp.int32(0),        # scalar → stays replicated
        "odd": jnp.ones((5, 4)),      # 5 % 4 != 0 → stays replicated
        "tp_leaf": jnp.ones((8, 4)),  # already tp-sharded → untouched
    }
    specs = {"mu": P(), "count": P(), "odd": P(), "tp_leaf": P(None, "tp")}
    out = zero1_state_specs(state, specs, mesh)
    assert out["mu"] == P("dp_replicate")
    assert out["count"] == P()
    assert out["odd"] == P()
    assert out["tp_leaf"] == P(None, "tp")
