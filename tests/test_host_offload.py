"""Optimizer-state host offload (ZeRO-Offload / FSDP cpu_offload parity via
XLA memory kinds — ``parallel/sharding.py`` host-offload section).

The CPU emulation backend cannot COMPILE memory-kind annotated programs, so on
CPU we test placement + sharding plumbing + the documented warning fallback;
the full compiled round-trip runs on real TPU (gated).
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, DeepSpeedPlugin, FullyShardedDataParallelPlugin
from accelerate_tpu.parallel import sharding as shlib


def _is_tpu():
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# Capability probe (parallel/sharding.offload_memory_kinds): offload needs the
# backend to expose BOTH a host-RAM tier (pinned_host on TPU, unpinned_host on
# some CPU builds) and a distinct "device" tier. The CPU emulation backend
# addresses ONLY unpinned_host — host RAM *is* its device memory, so there is
# no second tier to stage from and the three placement tests below are
# structurally impossible there, not merely failing.
_KINDS = shlib.offload_memory_kinds()
needs_memory_tiers = pytest.mark.skipif(
    _KINDS is None,
    reason=(
        "backend exposes no separate host/device memory tiers "
        "(CPU emulation addresses only unpinned_host — nothing to offload from)"
    ),
)


@needs_memory_tiers
def test_offload_tree_shardings_kinds():
    host_kind, device_kind = _KINDS
    tree = {"m": jnp.ones((8,)), "v": jnp.ones((8,))}
    host, dev = shlib.offload_tree_shardings(tree)
    assert all(s.memory_kind == host_kind for s in jax.tree_util.tree_leaves(host))
    assert all(s.memory_kind == device_kind for s in jax.tree_util.tree_leaves(dev))


@needs_memory_tiers
def test_offload_to_host_places_pinned():
    tree = {"m": jnp.arange(8.0)}
    out = shlib.offload_to_host(tree)
    assert out["m"].sharding.memory_kind == shlib.host_memory_kind()
    np.testing.assert_array_equal(np.asarray(out["m"]), np.arange(8.0))


def test_offload_without_memory_tiers_raises_clearly():
    """On a single-tier backend the offload helpers must say WHY instead of
    surfacing jax's 'Could not find memory addressable' from deep inside."""
    if _KINDS is not None:
        pytest.skip("backend has real memory tiers; nothing to assert here")
    with pytest.raises(RuntimeError, match="memory tiers"):
        shlib.offload_tree_shardings({"m": jnp.ones((4,))})


def test_plugin_sets_offload_intent():
    acc = Accelerator(cpu=True, deepspeed_plugin=DeepSpeedPlugin(
        zero_stage=2, offload_optimizer_device="cpu"))
    assert acc._offload_optimizer
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    acc2 = Accelerator(cpu=True, fsdp_plugin=FullyShardedDataParallelPlugin(cpu_offload=True))
    assert acc2._offload_optimizer
    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    acc3 = Accelerator(cpu=True)
    assert not acc3._offload_optimizer


def test_unsupported_backend_falls_back_with_warning(monkeypatch):
    """On backends without memory-kind compilation the step must still train,
    with the documented warning."""
    monkeypatch.setattr(shlib, "_host_offload_support", False)
    acc = Accelerator(cpu=True, deepspeed_plugin=DeepSpeedPlugin(
        zero_stage=2, offload_optimizer_device="cpu"))
    params, opt = acc.prepare({"w": jnp.ones((4,))}, optax.adam(0.1))

    def loss_fn(p, b):
        return jnp.sum((p["w"] * b["x"]) ** 2)

    with pytest.warns(UserWarning, match="host-offload"):
        step = acc.prepare_train_step(loss_fn, opt)
    batch = {"x": jnp.ones((4,))}
    p2, s2, m = step(params, opt.opt_state, batch)
    assert float(m["loss"]) > 0
    assert not np.allclose(np.asarray(p2["w"]), 1.0)


def test_nvme_degrades_to_host_ram_with_warning():
    with pytest.warns(UserWarning, match="nvme"):
        acc = Accelerator(cpu=True, deepspeed_plugin=DeepSpeedPlugin(
            zero_stage=2, offload_optimizer_device="nvme"))
    assert acc._offload_optimizer


def test_disable_jit_offload_warns(monkeypatch):
    from accelerate_tpu.utils import JitConfig

    monkeypatch.setattr(shlib, "_host_offload_support", True)
    acc = Accelerator(cpu=True, jit_config=JitConfig(disable_jit=True))
    params, opt = acc.prepare({"w": jnp.ones((2,))}, optax.sgd(0.1))
    with pytest.warns(UserWarning, match="jit is disabled"):
        acc.prepare_train_step(lambda p, b: jnp.sum(p["w"] ** 2), opt, offload_optimizer=True)


def test_train_loop_warns_when_offload_configured(monkeypatch):
    monkeypatch.setattr(shlib, "_host_offload_support", False)
    acc = Accelerator(cpu=True, deepspeed_plugin=DeepSpeedPlugin(
        zero_stage=2, offload_optimizer_device="cpu"))
    params, opt = acc.prepare({"w": jnp.ones((2,))}, optax.sgd(0.1))
    with pytest.warns(UserWarning, match="scanned train loop"):
        acc.prepare_train_loop(lambda p, b: jnp.sum((p["w"] * b["x"]) ** 2), opt)


def test_single_tier_backend_is_definitively_unsupported(monkeypatch):
    """No host/device tier split -> support is False and CACHED (the topology
    cannot change mid-process; no point re-probing)."""
    monkeypatch.setattr(shlib, "_host_offload_support", None)
    monkeypatch.setattr(shlib, "offload_memory_kinds", lambda: None)
    assert shlib.host_offload_supported() is False
    assert shlib._host_offload_support is False


def _arm_fake_tiers(monkeypatch):
    """Pretend the host/device tiers exist so host_offload_supported reaches
    its COMPILE probe (on the CPU backend the kind probe short-circuits, and
    even SingleDeviceSharding(pinned_host) construction raises)."""
    import jax as _jax

    class FakeSharding:
        def __init__(self, device, memory_kind=None):
            self.memory_kind = memory_kind

    monkeypatch.setattr(shlib, "offload_memory_kinds", lambda: ("pinned_host", "device"))
    monkeypatch.setattr(_jax.sharding, "SingleDeviceSharding", FakeSharding)
    monkeypatch.setattr(_jax, "device_put", lambda x, s=None: x)


def test_probe_does_not_cache_transient_failures(monkeypatch):
    def boom(*a, **k):
        raise RuntimeError("RESOURCE_EXHAUSTED: transient")

    import jax as _jax

    monkeypatch.setattr(shlib, "_host_offload_support", None)
    _arm_fake_tiers(monkeypatch)
    monkeypatch.setattr(_jax, "jit", boom)
    assert shlib.host_offload_supported() is False
    assert shlib._host_offload_support is None  # transient -> not cached
    monkeypatch.undo()
    shlib._host_offload_support = None
    # definitive signature -> cached False
    def boom2(*a, **k):
        raise RuntimeError("No registered implementation for untyped custom call to annotate_device_placement")

    monkeypatch.setattr(shlib, "_host_offload_support", None)
    _arm_fake_tiers(monkeypatch)
    monkeypatch.setattr(_jax, "jit", boom2)
    assert shlib.host_offload_supported() is False
    assert shlib._host_offload_support is False


def test_offload_requires_live_opt_state(monkeypatch):
    monkeypatch.setattr(shlib, "_host_offload_support", True)
    acc = Accelerator(cpu=True)
    opt = acc.prepare(optax.adam(0.1))
    with pytest.raises(ValueError, match="live optimizer state"):
        acc.prepare_train_step(lambda p, b: jnp.float32(0.0), opt, offload_optimizer=True)


@pytest.mark.skipif(not _is_tpu(), reason="memory-kind compilation needs real TPU")
def test_host_offloaded_step_trains_on_tpu():  # pragma: no cover - TPU only
    acc = Accelerator(deepspeed_plugin=DeepSpeedPlugin(zero_stage=2, offload_optimizer_device="cpu"))
    params, opt = acc.prepare({"w": jnp.ones((64,))}, optax.adam(0.05))

    def loss_fn(p, b):
        return jnp.sum((p["w"] * b["x"]) ** 2)

    step = acc.prepare_train_step(loss_fn, opt)
    assert all(
        getattr(x.sharding, "memory_kind", None) == "pinned_host"
        for x in jax.tree_util.tree_leaves(opt.opt_state)
        if hasattr(x, "sharding")
    )
    params_s, state = params, opt.opt_state
    batch = {"x": jnp.ones((64,))}
    losses = []
    for _ in range(10):
        params_s, state, m = step(params_s, state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    # state still host-resident after compiled steps
    assert all(
        getattr(x.sharding, "memory_kind", None) == "pinned_host"
        for x in jax.tree_util.tree_leaves(state)
        if hasattr(x, "sharding")
    )
