"""Optimizer-state host offload (ZeRO-Offload / FSDP cpu_offload parity via
XLA memory kinds — ``parallel/sharding.py`` host-offload section).

The CPU emulation backend cannot COMPILE memory-kind annotated programs, so on
CPU we test placement + sharding plumbing + the documented warning fallback;
the full compiled round-trip runs on real TPU (gated).
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, DeepSpeedPlugin, FullyShardedDataParallelPlugin
from accelerate_tpu.parallel import sharding as shlib


def _is_tpu():
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def test_offload_tree_shardings_kinds():
    tree = {"m": jnp.ones((8,)), "v": jnp.ones((8,))}
    host, dev = shlib.offload_tree_shardings(tree)
    assert all(s.memory_kind == "pinned_host" for s in jax.tree_util.tree_leaves(host))
    assert all(s.memory_kind == "device" for s in jax.tree_util.tree_leaves(dev))


def test_offload_to_host_places_pinned():
    tree = {"m": jnp.arange(8.0)}
    out = shlib.offload_to_host(tree)
    assert out["m"].sharding.memory_kind == "pinned_host"
    np.testing.assert_array_equal(np.asarray(out["m"]), np.arange(8.0))


def test_plugin_sets_offload_intent():
    acc = Accelerator(cpu=True, deepspeed_plugin=DeepSpeedPlugin(
        zero_stage=2, offload_optimizer_device="cpu"))
    assert acc._offload_optimizer
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    acc2 = Accelerator(cpu=True, fsdp_plugin=FullyShardedDataParallelPlugin(cpu_offload=True))
    assert acc2._offload_optimizer
    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    acc3 = Accelerator(cpu=True)
    assert not acc3._offload_optimizer


def test_unsupported_backend_falls_back_with_warning(monkeypatch):
    """On backends without memory-kind compilation the step must still train,
    with the documented warning."""
    monkeypatch.setattr(shlib, "_host_offload_support", False)
    acc = Accelerator(cpu=True, deepspeed_plugin=DeepSpeedPlugin(
        zero_stage=2, offload_optimizer_device="cpu"))
    params, opt = acc.prepare({"w": jnp.ones((4,))}, optax.adam(0.1))

    def loss_fn(p, b):
        return jnp.sum((p["w"] * b["x"]) ** 2)

    with pytest.warns(UserWarning, match="host-offload"):
        step = acc.prepare_train_step(loss_fn, opt)
    batch = {"x": jnp.ones((4,))}
    p2, s2, m = step(params, opt.opt_state, batch)
    assert float(m["loss"]) > 0
    assert not np.allclose(np.asarray(p2["w"]), 1.0)


def test_nvme_degrades_to_host_ram_with_warning():
    with pytest.warns(UserWarning, match="nvme"):
        acc = Accelerator(cpu=True, deepspeed_plugin=DeepSpeedPlugin(
            zero_stage=2, offload_optimizer_device="nvme"))
    assert acc._offload_optimizer


def test_disable_jit_offload_warns(monkeypatch):
    from accelerate_tpu.utils import JitConfig

    monkeypatch.setattr(shlib, "_host_offload_support", True)
    acc = Accelerator(cpu=True, jit_config=JitConfig(disable_jit=True))
    params, opt = acc.prepare({"w": jnp.ones((2,))}, optax.sgd(0.1))
    with pytest.warns(UserWarning, match="jit is disabled"):
        acc.prepare_train_step(lambda p, b: jnp.sum(p["w"] ** 2), opt, offload_optimizer=True)


def test_train_loop_warns_when_offload_configured(monkeypatch):
    monkeypatch.setattr(shlib, "_host_offload_support", False)
    acc = Accelerator(cpu=True, deepspeed_plugin=DeepSpeedPlugin(
        zero_stage=2, offload_optimizer_device="cpu"))
    params, opt = acc.prepare({"w": jnp.ones((2,))}, optax.sgd(0.1))
    with pytest.warns(UserWarning, match="scanned train loop"):
        acc.prepare_train_loop(lambda p, b: jnp.sum((p["w"] * b["x"]) ** 2), opt)


def test_probe_does_not_cache_transient_failures(monkeypatch):
    calls = []

    def boom(*a, **k):
        calls.append(1)
        raise RuntimeError("RESOURCE_EXHAUSTED: transient")

    monkeypatch.setattr(shlib, "_host_offload_support", None)
    import jax as _jax

    monkeypatch.setattr(_jax, "jit", boom)
    assert shlib.host_offload_supported() is False
    assert shlib._host_offload_support is None  # transient -> not cached
    monkeypatch.undo()
    shlib._host_offload_support = None
    # definitive signature -> cached False
    def boom2(*a, **k):
        raise RuntimeError("No registered implementation for untyped custom call to annotate_device_placement")

    monkeypatch.setattr(shlib, "_host_offload_support", None)
    monkeypatch.setattr(_jax, "jit", boom2)
    assert shlib.host_offload_supported() is False
    assert shlib._host_offload_support is False


def test_offload_requires_live_opt_state(monkeypatch):
    monkeypatch.setattr(shlib, "_host_offload_support", True)
    acc = Accelerator(cpu=True)
    opt = acc.prepare(optax.adam(0.1))
    with pytest.raises(ValueError, match="live optimizer state"):
        acc.prepare_train_step(lambda p, b: jnp.float32(0.0), opt, offload_optimizer=True)


@pytest.mark.skipif(not _is_tpu(), reason="memory-kind compilation needs real TPU")
def test_host_offloaded_step_trains_on_tpu():  # pragma: no cover - TPU only
    acc = Accelerator(deepspeed_plugin=DeepSpeedPlugin(zero_stage=2, offload_optimizer_device="cpu"))
    params, opt = acc.prepare({"w": jnp.ones((64,))}, optax.adam(0.05))

    def loss_fn(p, b):
        return jnp.sum((p["w"] * b["x"]) ** 2)

    step = acc.prepare_train_step(loss_fn, opt)
    assert all(
        getattr(x.sharding, "memory_kind", None) == "pinned_host"
        for x in jax.tree_util.tree_leaves(opt.opt_state)
        if hasattr(x, "sharding")
    )
    params_s, state = params, opt.opt_state
    batch = {"x": jnp.ones((64,))}
    losses = []
    for _ in range(10):
        params_s, state, m = step(params_s, state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    # state still host-resident after compiled steps
    assert all(
        getattr(x.sharding, "memory_kind", None) == "pinned_host"
        for x in jax.tree_util.tree_leaves(state)
        if hasattr(x, "sharding")
    )
