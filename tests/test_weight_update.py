"""Fused cross-replica (ZeRO-1) weight update — ``parallel/weight_update.py``
+ the unified sharding plan surface (``parallel/sharding.py``, ISSUE 9).

Runs on the virtual 8-device CPU mesh (conftest sets
``--xla_force_host_platform_device_count=8``). The parity bar matches the
MULTICHIP dryrun tolerance (1.5e-7); on this deterministic backend the fused
step is in fact bitwise-identical to the replicated baseline, because the
update region runs under shard_map and leaks no sharding constraint into the
forward/backward graph.
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from accelerate_tpu import Accelerator, DeepSpeedPlugin
from accelerate_tpu.parallel.sharding import (
    ShardingPlan,
    canonicalize_spec,
    make_sharding_plan,
)
from accelerate_tpu.parallel.weight_update import (
    FusedZero1Incompatible,
    build_bucket_plan,
    hlo_collective_bytes,
)
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.utils import patch_environment

MULTICHIP_TOL = 1.5e-7


def _reset():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def _zero1_accelerator(**kwargs):
    _reset()
    return Accelerator(
        cpu=True, deepspeed_plugin=DeepSpeedPlugin(zero_stage=1), rng_seed=0, **kwargs
    )


def _mlp_params(scale=0.1):
    return {
        "w1": jnp.asarray(np.random.default_rng(1).normal(size=(64, 32)) * scale, jnp.float32),
        "b1": jnp.zeros((32,), jnp.float32),
        "w2": jnp.asarray(np.random.default_rng(2).normal(size=(32, 8)) * scale, jnp.float32),
    }


def _mlp_loss(p, b):
    return jnp.mean((jnp.tanh(b["x"] @ p["w1"] + p["b1"]) @ p["w2"]) ** 2)


def _batches(n, bs=16, dim=64, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {"x": jnp.asarray(rng.normal(size=(bs, dim)), jnp.float32)} for _ in range(n)
    ]


def _run_training(plugin_stage, steps=5, accum=1):
    _reset()
    acc = Accelerator(
        cpu=True,
        deepspeed_plugin=DeepSpeedPlugin(zero_stage=plugin_stage),
        gradient_accumulation_steps=accum,
        rng_seed=0,
    )
    params, opt = acc.prepare(_mlp_params(), optax.adam(1e-3))
    step = acc.prepare_train_step(_mlp_loss, opt)
    s = opt.opt_state
    losses = []
    for b in _batches(steps):
        params, s, m = step(params, s, b)
        losses.append(float(m["loss"]))
    return acc, opt, params, losses


# ------------------------------------------------------------- bucket plan --
def test_bucket_plan_layout_and_roundtrip():
    params = {
        "a": jnp.ones((40, 3), jnp.float32),   # 120 elems
        "b": jnp.ones((7,), jnp.float32),      # forces padding (127 total f32)
        "c": jnp.ones((16,), jnp.bfloat16),    # separate dtype bucket
    }
    plan = build_bucket_plan(params, "dp_replicate", 8, bucket_bytes=1 << 20)
    assert plan.num_buckets == 2  # one f32, one bf16
    for size in plan.bucket_sizes.values():
        assert size % 8 == 0
    assert plan.collective_bytes == sum(plan.bucket_nbytes.values())
    buckets = plan.bucket_tree(params)
    rebuilt = plan.unbucket_tree(buckets)
    for k in params:
        np.testing.assert_array_equal(np.asarray(rebuilt[k]), np.asarray(params[k]))


def test_bucket_plan_respects_size_bound():
    # 4 leaves of 1 KiB each with a 1 KiB bucket bound -> one bucket per leaf
    params = {f"w{i}": jnp.ones((256,), jnp.float32) for i in range(4)}
    plan = build_bucket_plan(params, "dp_replicate", 8, bucket_bytes=1024)
    assert plan.num_buckets == 4


def test_bucket_plan_rejects_integer_leaves():
    with pytest.raises(ValueError, match="floating"):
        build_bucket_plan({"i": jnp.ones((8,), jnp.int32)}, "dp_replicate", 8)


# ------------------------------------------------------ parity + memory ------
def test_fused_zero1_matches_replicated_baseline():
    """The ISSUE 9 acceptance bar: fused ZeRO-1 loss trajectory matches the
    replicated (stage-0) baseline to the MULTICHIP tolerance on 8 devices."""
    _, opt0, params0, losses0 = _run_training(plugin_stage=0)
    assert not opt0.fused_zero1
    _, opt1, params1, losses1 = _run_training(plugin_stage=1)
    assert opt1.fused_zero1
    for l0, l1 in zip(losses0, losses1):
        assert abs(l1 - l0) / max(abs(l0), 1e-12) < MULTICHIP_TOL, (losses0, losses1)
    for k in params0:
        np.testing.assert_allclose(
            np.asarray(params1[k]), np.asarray(params0[k]), rtol=MULTICHIP_TOL
        )


def test_opt_state_bytes_per_replica_is_one_nth():
    acc, opt, _, _ = _run_training(plugin_stage=1, steps=1)
    n = acc.mesh.shape["dp_replicate"]
    assert n == 8
    dev0 = jax.devices()[0]
    global_bytes = 0
    dev0_bytes = 0
    sharded_leaves = 0
    for leaf in jax.tree_util.tree_leaves(opt.opt_state):
        if not hasattr(leaf, "addressable_shards"):
            continue
        global_bytes += leaf.nbytes
        for shard in leaf.addressable_shards:
            if shard.device == dev0:
                dev0_bytes += shard.data.nbytes
        if any(ax is not None for ax in tuple(leaf.sharding.spec)):
            sharded_leaves += 1
    assert sharded_leaves >= 2  # adam mu + nu buckets
    # scalars (count) stay replicated; the moment buckets dominate
    assert dev0_bytes < global_bytes / n * 1.1, (dev0_bytes, global_bytes)


def test_grad_accumulation_multisteps_interaction():
    """optax.MultiSteps wraps the fused update: micro-step grads accumulate in
    SHARDED bucket buffers, boundary updates match the unfused baseline."""
    _, opt0, params0, losses0 = _run_training(plugin_stage=0, steps=4, accum=2)
    _, opt1, params1, losses1 = _run_training(plugin_stage=1, steps=4, accum=2)
    assert opt1.fused_zero1
    from accelerate_tpu.optimizer import _find_multisteps_state

    ms = _find_multisteps_state(opt1.opt_state)
    assert ms is not None and int(ms.gradient_step) == 2  # 4 micro / accum 2
    # the accumulator rides the bucketed layout, sharded 1/N
    acc_leaves = [
        x for x in jax.tree_util.tree_leaves(ms.acc_grads)
        if hasattr(x, "sharding")
    ]
    assert acc_leaves and all(
        any(ax is not None for ax in tuple(x.sharding.spec)) for x in acc_leaves
    )
    for l0, l1 in zip(losses0, losses1):
        assert abs(l1 - l0) / max(abs(l0), 1e-12) < MULTICHIP_TOL
    for k in params0:
        np.testing.assert_allclose(
            np.asarray(params1[k]), np.asarray(params0[k]), rtol=MULTICHIP_TOL
        )


# ------------------------------------------------------------- checkpoints --
def test_sharded_checkpoint_roundtrip_under_fused_specs(tmp_path):
    """Save the bucketed 1/N state sharded, resume, and take an identical next
    step — the crash-resume contract under the new spec surface."""
    from accelerate_tpu.sharded_checkpoint import (
        load_sharded_pytree,
        save_sharded_pytree,
    )

    acc, opt, params, _ = _run_training(plugin_stage=1, steps=2)
    step = acc.prepare_train_step(_mlp_loss, opt)
    state = opt.opt_state
    save_sharded_pytree(state, str(tmp_path), prefix="optimizer")
    save_sharded_pytree(params, str(tmp_path), prefix="model")
    next_batch = _batches(1, seed=99)[0]
    p_ref, s_ref, m_ref = step(params, state, next_batch)
    ref_loss = float(m_ref["loss"])

    # resume into freshly-initialized (bucketed, sharded) templates
    _reset()
    acc2 = _zero1_accelerator()
    params2, opt2 = acc2.prepare(_mlp_params(), optax.adam(1e-3))
    assert opt2.fused_zero1
    params2 = load_sharded_pytree(params2, str(tmp_path), prefix="model")
    opt2.opt_state = load_sharded_pytree(opt2.opt_state, str(tmp_path), prefix="optimizer")
    step2 = acc2.prepare_train_step(_mlp_loss, opt2)
    _, _, m2 = step2(params2, opt2.opt_state, next_batch)
    assert float(m2["loss"]) == pytest.approx(ref_loss, rel=MULTICHIP_TOL)


def test_plan_restores_shape_struct_templates(tmp_path):
    """ShardingPlan as the checkpoint consumer: a ShapeDtypeStruct template
    (no live arrays yet) restores onto plan-derived shardings recorded in the
    shard index."""
    from accelerate_tpu.sharded_checkpoint import (
        load_sharded_pytree,
        save_sharded_pytree,
    )

    acc, opt, params, _ = _run_training(plugin_stage=1, steps=1)
    plan = acc._sharding_plan
    assert isinstance(plan, ShardingPlan) and plan.fused_zero1
    save_sharded_pytree(opt.opt_state, str(tmp_path), prefix="optimizer")
    template = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), opt.opt_state
    )
    restored = load_sharded_pytree(template, str(tmp_path), prefix="optimizer", plan=plan)
    for saved, back in zip(
        jax.tree_util.tree_leaves(opt.opt_state), jax.tree_util.tree_leaves(restored)
    ):
        np.testing.assert_array_equal(np.asarray(saved), np.asarray(back))
        if hasattr(saved, "sharding"):
            assert back.sharding.spec == saved.sharding.spec


# ---------------------------------------------------------------- fallbacks --
def test_shape_dependent_transform_falls_back_with_warning():
    """adafactor materializes factored (non-bucket-shaped) moments: the plan
    demotes itself to annotation-mode ZeRO-1 and training still works."""
    acc = _zero1_accelerator()
    with pytest.warns(UserWarning, match="not elementwise-bucketable"):
        params, opt = acc.prepare(_mlp_params(), optax.adafactor(1e-3))
    assert not opt.fused_zero1
    step = acc.prepare_train_step(_mlp_loss, opt)
    _, _, m = step(params, opt.opt_state, _batches(1)[0])
    assert np.isfinite(float(m["loss"]))


def test_env_kill_switch_disables_fused_path():
    with patch_environment(ACCELERATE_ZERO1_FUSED="0"):
        acc = _zero1_accelerator()
        params, opt = acc.prepare(_mlp_params(), optax.adam(1e-3))
    assert not opt.fused_zero1
    # annotation-mode ZeRO-1 still shards the (param-shaped) moments
    specs = {
        str(x.sharding.spec)
        for x in jax.tree_util.tree_leaves(opt.opt_state)
        if hasattr(x, "sharding")
    }
    assert any("dp_replicate" in s for s in specs), specs


def test_blocked_fused_path_demotes_plan_to_annotation_mode():
    """An optimizer that opts out of bucketing (the fp8 label-routed shape)
    must still get annotation-mode ZeRO-1 sharding, and the plan must stop
    advertising fused collective bytes (no phantom telemetry)."""
    acc = _zero1_accelerator()
    opt = acc.prepare(optax.adam(1e-3))
    opt._allow_fused_zero1 = False
    # prepare(params) late-binds opt.init with the plan; the blocked optimizer
    # must demote it (plan.zero1 was populated by make_sharding_plan first)
    params = acc.prepare(_mlp_params())
    assert not opt.fused_zero1
    assert not acc._sharding_plan.fused_zero1  # demoted
    assert acc._sharding_plan.zero1_collective_bytes() is None
    # annotation-mode still shards the moments over the replicate axis
    specs = {
        str(x.sharding.spec)
        for x in jax.tree_util.tree_leaves(opt.opt_state)
        if hasattr(x, "sharding")
    }
    assert any("dp_replicate" in s for s in specs), specs


def test_explicit_param_specs_are_canonicalized():
    """User-supplied specs take the same canonical form as inferred ones —
    padded/size-1-axis forms must neither re-specialize the step nor read as
    'sharded' and wrongly disable the fused path."""
    from jax.sharding import PartitionSpec as P

    acc = _zero1_accelerator()
    padded = {
        "w1": P(None, None), "b1": P(None),
        "w2": P(None, "tp"),  # tp has size 1 on this pure-DP mesh
    }
    params, opt = acc.prepare(_mlp_params(), optax.adam(1e-3), shard_rules=None)
    # rebuild through prepare_model with explicit specs
    _reset()
    acc = _zero1_accelerator()
    params = acc.prepare_model(_mlp_params(), specs=padded)
    assert all(
        s == P() for s in jax.tree_util.tree_leaves(acc._param_specs)
    ), acc._param_specs
    assert acc._sharding_plan.fused_zero1  # still recognized as pure-DP


def test_hlo_collective_bytes_parses_variadic_ops():
    text = (
        "  %ag = f32[2048]{0} all-gather(f32[256]{0} %p), dimensions={0}\n"
        "  %combined = (f32[2048]{0}, bf16[512]{0}) all-gather(%a, %b)\n"
        "  %ar = (f32[64]{0}) all-reduce(%g)\n"
    )
    out = hlo_collective_bytes(text)
    assert out["all-gather"] == 2048 * 4 + (2048 * 4 + 512 * 2)
    assert out["all-reduce"] == 64 * 4


# ---------------------------------------------------------------- telemetry --
def test_compiled_collective_bytes_are_counted(tmp_path):
    from accelerate_tpu import telemetry

    _reset()
    telemetry.enable(str(tmp_path / "tel"))
    try:
        acc = Accelerator(
            cpu=True, deepspeed_plugin=DeepSpeedPlugin(zero_stage=1), rng_seed=0
        )
        params, opt = acc.prepare(_mlp_params(), optax.adam(1e-3))
        assert opt.fused_zero1
        plan_bytes = acc._sharding_plan.zero1_collective_bytes()
        step = acc.prepare_train_step(_mlp_loss, opt)
        s = opt.opt_state
        for b in _batches(3):
            params, s, _ = step(params, s, b)
        telemetry.get_event_log().hard_flush()
        import json

        events = [
            json.loads(line)
            for line in open(next((tmp_path / "tel").glob("events-rank*.jsonl")))
        ]
        comms = [e for e in events if e.get("kind") == "comm"]
        for op in ("compiled:reduce_scatter", "compiled:all_gather"):
            mine = [e for e in comms if e["op"] == op]
            assert len(mine) == 3, (op, comms)  # one per step
            assert all(e["bytes"] == plan_bytes[op.split(":")[1]] for e in mine)
            assert all(e["wire"] for e in mine)  # device-fabric traffic
    finally:
        telemetry.disable()


# ----------------------------------------------------- canonical spec forms --
def test_canonicalize_spec_forms():
    from jax.sharding import PartitionSpec as P

    sizes = {"dp_shard": 8, "tp": 1, "cp": 2}
    assert canonicalize_spec(P(None, None)) == P()
    assert canonicalize_spec(P("dp_shard", None), sizes) == P("dp_shard")
    assert canonicalize_spec(P(None, "tp"), sizes) == P()  # size-1 axis drops
    assert canonicalize_spec(P(("dp_shard", "cp"), None), sizes) == P(("dp_shard", "cp"))
    assert canonicalize_spec(P(("dp_shard", "tp")), sizes) == P("dp_shard")
    assert canonicalize_spec(None) == P()


def test_prepared_step_never_respecializes():
    """Regression for the bert-tiny 'cache 1→2 at step 1' signal (PR 7's known
    issue): canonical placed specs == GSPMD output specs, so the compiled
    step's dispatch cache must stay at ONE entry across steps."""
    _reset()
    from accelerate_tpu.parallel.sharding import ShardingRules
    from jax.sharding import PartitionSpec as P

    acc = Accelerator(rng_seed=0)
    captured = {}
    orig = acc._track_step

    def spy(fn, opt, kind="train_step"):
        captured["fn"] = fn
        return orig(fn, opt, kind=kind)

    acc._track_step = spy
    # tp rules on a tp=1 mesh: exactly the padded/size-1-axis spec shapes that
    # used to re-specialize
    rules = ShardingRules([(r"w1", P(None, "tp")), (r"w2", P("tp", None))])
    params, opt = acc.prepare(_mlp_params(), optax.adam(1e-3), shard_rules=rules)
    step = acc.prepare_train_step(_mlp_loss, opt)
    s = opt.opt_state
    sizes = []
    for b in _batches(3):
        params, s, _ = step(params, s, b)
        sizes.append(captured["fn"]._cache_size())
    assert sizes == [1, 1, 1], sizes


# ------------------------------------------------------------- compiled HLO --
def test_fused_step_hlo_contains_collectives():
    """The compiled fused step must actually communicate: nonzero collective
    bytes in the HLO (the doctor's in-CI twin)."""
    acc, opt, params, _ = _run_training(plugin_stage=1, steps=1)
    train_step = acc._build_train_step(_mlp_loss, opt, False, False)
    lowered = jax.jit(train_step, donate_argnums=(0, 1)).lower(
        params, opt.opt_state, _batches(1)[0]
    )
    found = hlo_collective_bytes(lowered.compile().as_text())
    assert sum(found.values()) > 0, found
    assert "all-gather" in found  # updated param chunks reassemble every step
