"""`accelerate_tpu.utils` import-spelling parity + the generic helpers in
utils/other.py (reference ``utils/other.py`` + ``utils/__init__.py`` exports).
"""

import os
from collections import namedtuple

import numpy as np
import pytest

import accelerate_tpu.utils as u


def test_reference_utils_spellings_resolve():
    for name in [
        # constants (reference utils/constants.py:20-33)
        "MODEL_NAME", "OPTIMIZER_NAME", "SCHEDULER_NAME", "SAMPLER_NAME", "RNG_NAME",
        # modeling
        "infer_auto_device_map", "find_tied_parameters", "retie_parameters",
        "compute_module_sizes", "get_balanced_memory", "get_max_memory",
        "dtype_byte_size", "convert_file_size_to_int", "load_state_dict",
        # offload
        "OffloadedWeightsLoader", "PrefixedDataset", "offload_weight",
        "load_offloaded_weight", "offload_state_dict", "save_offload_index",
        # memory
        "find_executable_batch_size", "release_memory", "clear_device_cache",
        # quantization
        "load_and_quantize_model", "BnbQuantizationConfig",
        # misc
        "convert_bytes", "merge_dicts", "is_port_in_use", "honor_type",
        "listify", "find_device", "convert_to_fp32", "convert_outputs_to_fp32",
        "clean_state_dict_for_safetensors", "save", "load", "check_os_kernel",
        "get_pretty_name", "recursive_getattr", "extract_model_from_parallel",
        "merge_fsdp_weights", "wait_for_everyone", "tqdm",
    ]:
        assert getattr(u, name) is not None
        assert name in dir(u)  # introspection sees lazy names


def test_bnb_quantization_config_is_native_config():
    from accelerate_tpu.utils.quantization import QuantizationConfig

    assert u.BnbQuantizationConfig is QuantizationConfig


def test_convert_bytes():
    assert u.convert_bytes(512) == "512 bytes"
    assert u.convert_bytes(1024) == "1.0 KB"
    assert u.convert_bytes(1024**2 * 1.5) == "1.5 MB"
    assert u.convert_bytes(1024**3) == "1.0 GB"


def test_merge_dicts_recursive_non_mutating():
    dst = {"a": {"c": 2}, "d": 3}
    out = u.merge_dicts({"a": {"b": 1}}, dst)
    assert out == {"a": {"b": 1, "c": 2}, "d": 3}
    assert dst == {"a": {"c": 2}, "d": 3}


def test_honor_type_and_listify():
    NT = namedtuple("NT", "x y")
    assert u.honor_type(NT(1, 2), iter([3, 4])) == NT(3, 4)
    assert u.honor_type((1, 2), iter([3, 4])) == (3, 4)
    assert u.is_namedtuple(NT(1, 2)) and not u.is_namedtuple((1, 2))
    out = u.listify({"a": np.arange(3), "b": [np.float32(1.5), "s"], "c": None})
    assert out == {"a": [0, 1, 2], "b": [1.5, "s"], "c": None}


def test_convert_to_fp32():
    import jax.numpy as jnp

    tree = {"x": jnp.ones((2,), jnp.bfloat16), "i": jnp.ones((2,), jnp.int32)}
    out = u.convert_to_fp32(tree)
    assert out["x"].dtype == jnp.float32
    assert out["i"].dtype == jnp.int32  # non-float untouched


def test_find_device():
    import jax
    import jax.numpy as jnp

    dev = u.find_device({"a": [1, 2], "b": jnp.ones((2,))})
    assert dev in jax.devices()
    assert u.find_device({"a": [1, 2]}) is None


def test_clean_state_dict_dedups_tied():
    w = np.ones((2, 2), np.float32)
    clean = u.clean_state_dict_for_safetensors({"w": w, "tied": w, "other": np.zeros(2)})
    assert len(clean) == 2  # one of w/tied dropped, other kept


@pytest.mark.smoke
def test_save_load_round_trip(tmp_path):
    tree = {"layer": {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}}
    npz = str(tmp_path / "s.npz")
    u.save(tree, npz)
    back = u.load(npz)
    np.testing.assert_array_equal(back["layer/w"], tree["layer"]["w"])
    st = str(tmp_path / "s.safetensors")
    u.save(tree, st, safe_serialization=True)
    np.testing.assert_array_equal(u.load(st)["layer/w"], tree["layer"]["w"])


def test_save_respects_exact_path_without_npz_extension(tmp_path):
    # np.savez on a bare path appends ".npz"; save() must write EXACTLY the
    # path given so load() finds it again (ADVICE r03)
    tree = {"w": np.arange(4, dtype=np.float32)}
    path = str(tmp_path / "model.bin")
    u.save(tree, path)
    assert os.path.exists(path) and not os.path.exists(path + ".npz")
    np.testing.assert_array_equal(u.load(path)["w"], tree["w"])


def test_compare_versions_prerelease_ordering():
    from accelerate_tpu.utils.versions import compare_versions

    # PEP 440: a dev build PRECEDES its release (ADVICE r03)
    assert compare_versions("0.5.0.dev0", "<", "0.5.0")
    assert not compare_versions("0.5.0.dev0", ">=", "0.5.0")
    assert compare_versions("1.2.0rc1", "<", "1.2.0")
    assert compare_versions("0.7", "==", "0.7.0")
    assert compare_versions("1.10.2", ">", "1.9.9")
    # ordering among pre-releases themselves (fallback parser must agree even
    # when packaging is installed, so exercise it directly)
    from accelerate_tpu.utils.versions import _fallback_compare as fc

    assert fc("1.0rc2", ">", "1.0rc1")
    assert fc("1.0.dev0", "<", "1.0a1") and fc("1.0a1", "<", "1.0b1")
    assert fc("1.0b1", "<", "1.0rc1") and fc("1.0rc1", "<", "1.0")
    assert fc("1.0.post1", ">", "1.0")
    assert fc("1.0.0-beta", "<", "1.0.0")
    assert fc("0.7", "==", "0.7.0")
    # local-version / platform suffixes are NOT pre-releases
    assert fc("0.4.30+cuda12", ">=", "0.4.30")
    assert fc("1.0-arm64", ">=", "1.0")
    # deep release tuples are not truncated
    assert fc("1.2.3.4.5.1", "<", "1.2.3.4.5.2")


def test_purge_accelerate_environment_preserves_classmethods():
    os.environ["ACCELERATE_SCRATCH4"] = "v"

    @u.purge_accelerate_environment
    class T:
        @classmethod
        def test_cm(cls):
            return "ACCELERATE_SCRATCH4" not in os.environ

        @staticmethod
        def test_sm():
            return "ACCELERATE_SCRATCH4" not in os.environ

    try:
        assert T.test_cm() is True
        assert T().test_cm() is True  # instance access must still bind cls
        assert T.test_sm() is True
    finally:
        os.environ.pop("ACCELERATE_SCRATCH4", None)


def test_purge_accelerate_environment_covers_inherited_methods():
    os.environ["ACCELERATE_SCRATCH3"] = "v"

    class Base:
        def test_inherited(self):
            return "ACCELERATE_SCRATCH3" not in os.environ

    @u.purge_accelerate_environment
    class Child(Base):
        pass

    try:
        assert Child().test_inherited() is True  # inherited method purged too
        assert Base().test_inherited() is False  # base class untouched
    finally:
        os.environ.pop("ACCELERATE_SCRATCH3", None)


def test_is_port_in_use():
    import socket

    s = socket.socket()
    s.bind(("localhost", 0))
    s.listen(1)
    port = s.getsockname()[1]
    try:
        assert u.is_port_in_use(port)
    finally:
        s.close()
    assert not u.is_port_in_use(port)


def test_get_pretty_name_and_recursive_getattr():
    assert u.get_pretty_name(test_convert_bytes) == "test_convert_bytes"
    assert u.get_pretty_name(3.5) == "float"

    class A:
        pass

    a = A()
    a.b = A()
    a.b.c = 7
    assert u.recursive_getattr(a, "b.c") == 7


def test_check_os_kernel_no_warning_on_modern_kernel(recwarn):
    # pin the release: the suite must not depend on the host's own kernel
    u.check_os_kernel(release="5.15.0-1052-gcp")
    assert not [w for w in recwarn.list if "kernel" in str(w.message)]


def test_check_os_kernel_warns_on_old_kernel():
    import platform

    if platform.system() != "Linux":
        pytest.skip("kernel check is Linux-only")
    with pytest.warns(UserWarning, match="kernel 4.4.0"):
        u.check_os_kernel(release="4.4.0")


def test_merge_fsdp_weights_is_shard_merge():
    from accelerate_tpu.sharded_checkpoint import merge_sharded_checkpoint

    assert u.merge_fsdp_weights is merge_sharded_checkpoint


def test_reference_precision_and_engine_probes():
    from accelerate_tpu.utils import (
        is_bf16_available,
        is_bnb_available,
        is_cuda_available,
        is_deepspeed_available,
        is_fp8_available,
        is_fp16_available,
        is_mps_available,
    )

    assert is_bf16_available() is True  # native TPU dtype (signature parity)
    assert is_bf16_available(ignore_tpu=True) is True
    assert is_fp16_available() is True
    assert is_fp8_available() is True  # jax float8 dtypes exist
    assert is_cuda_available() is False  # tpu/cpu image
    assert is_mps_available() is False
    # torch-engine probes are plain package probes — consistent with the
    # actual environment, whatever it has installed
    from accelerate_tpu.utils.imports import _package_available

    assert is_deepspeed_available() == _package_available("deepspeed")
    assert is_bnb_available() == _package_available("bitsandbytes")


# ------------------------------------------------------- environment utils --


def test_convert_dict_to_env_variables():
    # key case preserved: http_proxy and HTTP_PROXY are different variables
    assert u.convert_dict_to_env_variables({"http_proxy": "p", "BAR": 1}) == [
        "http_proxy=p",
        "BAR=1",
    ]
    with pytest.raises(ValueError):
        u.convert_dict_to_env_variables({"evil": "a;rm -rf"})
    with pytest.raises(ValueError):
        u.convert_dict_to_env_variables({"evil": "a\nb"})
    with pytest.raises(ValueError):
        u.convert_dict_to_env_variables({"bad=key": "v"})


def test_clear_environment_restores_even_on_exception():
    os.environ["_SCRATCH_TEST_VAR"] = "1"
    try:
        with pytest.raises(RuntimeError):
            with u.clear_environment():
                assert "_SCRATCH_TEST_VAR" not in os.environ
                os.environ["LEAKED"] = "y"
                raise RuntimeError
        assert os.environ.get("_SCRATCH_TEST_VAR") == "1"
        assert "LEAKED" not in os.environ
    finally:
        os.environ.pop("_SCRATCH_TEST_VAR", None)


def test_purge_accelerate_environment():
    os.environ["ACCELERATE_SCRATCH"] = "outer"

    @u.purge_accelerate_environment
    def fn():
        assert "ACCELERATE_SCRATCH" not in os.environ
        os.environ["ACCELERATE_INNER"] = "x"  # must not leak out
        return 42

    try:
        assert fn() == 42
        assert os.environ.get("ACCELERATE_SCRATCH") == "outer"
        assert "ACCELERATE_INNER" not in os.environ
    finally:
        os.environ.pop("ACCELERATE_SCRATCH", None)


def test_purge_accelerate_environment_on_class():
    os.environ["ACCELERATE_SCRATCH2"] = "v"

    @u.purge_accelerate_environment
    class T:
        def test_m(self):
            return "ACCELERATE_SCRATCH2" not in os.environ

    try:
        assert T().test_m() is True
    finally:
        os.environ.pop("ACCELERATE_SCRATCH2", None)
