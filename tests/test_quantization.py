"""Quantization tests (reference ``tests/test_quantization.py`` asserts
memory-footprint reduction, skip-module handling, and generation quality; here:
round-trip error bounds, footprint, pytree/jit transparency, int8 MXU matmul
accuracy, quantized end-to-end forward)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.ops.quantization import (
    QuantizationConfig,
    QuantizedArray,
    dequantize_params,
    int8_dynamic_matmul,
    quantize,
    quantize_blockwise_4bit,
    quantize_blockwise_int8,
    quantize_int8_matmul_weight,
    quantize_params,
    quantized_byte_size,
)


def _rand(shape, seed=0, scale=1.0):
    return jax.random.normal(jax.random.PRNGKey(seed), shape) * scale


class TestBlockwise:
    @pytest.mark.parametrize("kind", ["int8", "nf4"])
    def test_zero_blocks_stay_finite(self, kind):
        # an all-zero block has absmax 0: the scale math must not divide by
        # zero, and mixed zero/nonzero blocks must round-trip the nonzero part
        cfg = QuantizationConfig(**{f"load_in_{'8bit' if kind == 'int8' else '4bit'}": True},
                                 block_size=64)
        w = jnp.zeros((64, 128), jnp.float32)
        back = quantize(w, cfg).dequantize(jnp.float32)
        assert bool(jnp.all(jnp.isfinite(back))) and float(jnp.abs(back).max()) == 0.0
        mixed = jnp.concatenate([jnp.zeros((64, 64)), jnp.ones((64, 64))], axis=1)
        backm = quantize(mixed, cfg).dequantize(jnp.float32)
        assert bool(jnp.all(jnp.isfinite(backm)))
        assert float(jnp.abs(backm[:, 64:] - 1).max()) < 0.1

    def test_non_divisible_block_size(self):
        w = jnp.full((10, 100), 0.5, jnp.float32)
        q = quantize(w, QuantizationConfig(load_in_8bit=True, block_size=64))
        assert float(jnp.abs(q.dequantize(jnp.float32) - 0.5).max()) < 1e-2

    @pytest.mark.smoke
    def test_int8_roundtrip_error(self):
        w = _rand((128, 256))
        cfg = QuantizationConfig(load_in_8bit=True, block_size=64)
        q = quantize(w, cfg)
        err = jnp.abs(q.dequantize(jnp.float32) - w)
        # absmax int8: error bounded by scale/2 = absmax/254 per block
        assert float(err.max()) < float(jnp.abs(w).max()) / 100
        rel = float(jnp.linalg.norm(err) / jnp.linalg.norm(w))
        assert rel < 0.01

    def test_nf4_roundtrip_error(self):
        w = _rand((128, 256))
        cfg = QuantizationConfig(load_in_4bit=True, quant_type="nf4", block_size=64)
        q = quantize(w, cfg)
        rel = float(jnp.linalg.norm(q.dequantize(jnp.float32) - w) / jnp.linalg.norm(w))
        assert rel < 0.12  # 4-bit: ~8% typical for gaussian weights

    def test_nf4_beats_fp4_on_gaussian(self):
        w = _rand((256, 256))
        e = {}
        for qt in ("nf4", "fp4"):
            cfg = QuantizationConfig(load_in_4bit=True, quant_type=qt)
            q = quantize(w, cfg)
            e[qt] = float(jnp.linalg.norm(q.dequantize(jnp.float32) - w))
        assert e["nf4"] < e["fp4"]

    def test_non_divisible_block(self):
        w = _rand((7, 9))  # 63 elems, block 64 → padding path
        cfg = QuantizationConfig(load_in_8bit=True, block_size=64, min_size=1)
        q = quantize(w, cfg)
        assert q.dequantize().shape == (7, 9)

    def test_exact_zero_block(self):
        codes, scales = quantize_blockwise_int8(jnp.zeros((64,)), 64)
        assert float(jnp.abs(codes).max()) == 0
        packed, scales4 = quantize_blockwise_4bit(jnp.zeros((64,)), 64)
        assert np.isfinite(np.asarray(scales4)).all()


class TestQuantizedArray:
    def test_footprint(self):
        w = _rand((256, 256))
        q8 = quantize(w, QuantizationConfig(load_in_8bit=True))
        q4 = quantize(w, QuantizationConfig(load_in_4bit=True))
        dense = 256 * 256 * 4
        assert q8.nbytes_quantized < dense / 3  # int8 + scales < 1/3 fp32
        assert q4.nbytes_quantized < dense / 6

    def test_jax_array_protocol(self):
        """x @ q works unchanged — the bnb 'replace linear layer' moment."""
        w = _rand((64, 32))
        x = _rand((8, 64), seed=1)
        q = quantize(w, QuantizationConfig(load_in_8bit=True))
        out = x @ q
        np.testing.assert_allclose(np.asarray(out), np.asarray(x @ w), atol=0.1, rtol=0.1)

    def test_pytree_through_jit(self):
        """Quantized leaves cross the jit boundary as int8 — no host dequant."""
        w = _rand((64, 64))
        q = quantize(w, QuantizationConfig(load_in_8bit=True))

        @jax.jit
        def f(q, x):
            return x @ q

        x = _rand((4, 64), seed=2)
        out = f(q, x)
        assert out.shape == (4, 64)
        leaves = jax.tree_util.tree_leaves(q)
        assert any(l.dtype == jnp.int8 for l in leaves)


class TestQuantizeParams:
    def _params(self):
        return {
            "embed": {"embedding": _rand((512, 64))},
            "layer": {"wq": {"kernel": _rand((64, 64), 1)},
                      "norm": {"scale": jnp.ones((64,))}},
            "lm_head": {"kernel": _rand((64, 512), 2)},
        }

    def test_skip_modules_and_small_leaves(self):
        cfg = QuantizationConfig(load_in_8bit=True, min_size=1024)
        q = quantize_params(self._params(), cfg)
        assert isinstance(q["layer"]["wq"]["kernel"], QuantizedArray)
        assert not isinstance(q["embed"]["embedding"], QuantizedArray)  # skip "embed"
        assert not isinstance(q["lm_head"]["kernel"], QuantizedArray)   # skip lm_head
        assert not isinstance(q["layer"]["norm"]["scale"], QuantizedArray)  # small

    def test_dequantize_params_roundtrip(self):
        cfg = QuantizationConfig(load_in_8bit=True, min_size=1024)
        p = self._params()
        d = dequantize_params(quantize_params(p, cfg), jnp.float32)
        np.testing.assert_allclose(np.asarray(d["layer"]["wq"]["kernel"]),
                                   np.asarray(p["layer"]["wq"]["kernel"]),
                                   atol=0.05)

    def test_nothing_quantized_raises(self):
        cfg = QuantizationConfig(load_in_8bit=True, min_size=10**9)
        with pytest.raises(ValueError, match="nothing was quantized"):
            quantize_params(self._params(), cfg)

    def test_byte_size_accounting(self):
        cfg = QuantizationConfig(load_in_8bit=True, min_size=1024)
        p = self._params()
        q = quantize_params(p, cfg)
        from accelerate_tpu.utils.modeling import total_byte_size

        assert quantized_byte_size(q) < total_byte_size(p)


class TestInt8Matmul:
    def test_kblock_matmul_close_to_dense(self):
        w = _rand((256, 128))
        x = _rand((16, 256), seed=3)
        qw = quantize_int8_matmul_weight(w, block_size=64)
        out = int8_dynamic_matmul(x, qw, preferred_dtype=jnp.float32)
        ref = x @ w
        rel = float(jnp.linalg.norm(out - ref) / jnp.linalg.norm(ref))
        assert rel < 0.02

    def test_kblock_dequantize(self):
        w = _rand((100, 40))  # k not divisible by block
        qw = quantize_int8_matmul_weight(w, block_size=64)
        rel = float(jnp.linalg.norm(qw.dequantize(jnp.float32) - w) / jnp.linalg.norm(w))
        assert rel < 0.01

    def test_fallback_for_flat_layout(self):
        w = _rand((64, 32))
        q = quantize(w, QuantizationConfig(load_in_8bit=True))
        out = int8_dynamic_matmul(_rand((4, 64), 5), q)
        assert out.shape == (4, 32)


@pytest.mark.slow
class TestEndToEnd:
    def test_quantized_llama_forward(self):
        from accelerate_tpu.models import LlamaConfig, init_llama, llama_forward

        config = LlamaConfig.tiny()
        params = init_llama(config, jax.random.PRNGKey(0))
        ids = np.zeros((2, 16), dtype=np.int32)
        ref = np.asarray(llama_forward(params, ids, config, attention_impl="xla"),
                         dtype=np.float32)
        for kw in ({"load_in_8bit": True}, {"load_in_4bit": True}):
            cfg = QuantizationConfig(min_size=4096, **kw)
            qparams = quantize_params(params, cfg)
            # quantized leaves feed the forward DIRECTLY (stacked layers are
            # scanned — children slice per layer, __jax_array__ dequantizes)
            out = llama_forward(qparams, ids, config, attention_impl="xla")
            jout = jax.jit(
                lambda p, i: llama_forward(p, i, config, attention_impl="xla")
            )(qparams, ids)
            assert out.shape == ref.shape
            out = np.asarray(out, dtype=np.float32)
            assert np.isfinite(out).all()
            rel = np.linalg.norm(out - ref) / np.linalg.norm(ref)
            assert rel < (0.1 if kw.get("load_in_8bit") else 0.5)
            np.testing.assert_allclose(np.asarray(jout, np.float32), out, atol=1e-2)

    def test_load_and_quantize_model(self, tmp_path):
        from accelerate_tpu.checkpointing import save_model
        from accelerate_tpu.utils.quantization import load_and_quantize_model

        params = {"blk": {"w": _rand((128, 128))}, "norm": {"s": jnp.ones((8,))}}
        save_model(params, str(tmp_path))
        template = jax.eval_shape(lambda: params)
        cfg = QuantizationConfig(load_in_8bit=True, min_size=1024)
        q, offload_index = load_and_quantize_model(template, cfg, checkpoint=str(tmp_path))
        assert offload_index == {}
        assert isinstance(q["blk"]["w"], QuantizedArray)
        np.testing.assert_allclose(np.asarray(q["blk"]["w"].dequantize(jnp.float32)),
                                   np.asarray(params["blk"]["w"]), atol=0.05)


class TestStackedLeaves:
    """Stacked per-layer leaves must stay scannable after quantization
    (lax.scan slices pytree children along dim 0; static shape aux can't follow)."""

    def test_stacked_2d_vector_scan(self):
        L, D = 4, 2048
        stacked = {"kern": _rand((L, 64, 64)), "vec": _rand((L, D), seed=9)}
        cfg = QuantizationConfig(load_in_8bit=True, min_size=1024)
        q = quantize_params({"layers": stacked}, cfg)["layers"]
        assert isinstance(q["vec"], QuantizedArray)

        def layer(carry, p):
            return carry + jnp.sum(jnp.asarray(p["vec"])) + jnp.sum(jnp.asarray(p["kern"])), None

        total, _ = jax.lax.scan(layer, jnp.float32(0), q)
        ref = float(jnp.sum(stacked["vec"]) + jnp.sum(stacked["kern"]))
        np.testing.assert_allclose(float(total), ref, rtol=0.02)

    def test_stacked_4d_scan_dequant(self):
        L = 3
        w = _rand((L, 8, 16, 33))  # per-layer 4224 elems, not block-multiple
        cfg = QuantizationConfig(load_in_8bit=True, min_size=1024)
        q = quantize_params({"w": w}, cfg)["w"]

        def layer(carry, p):
            return carry, p["w"].dequantize(jnp.float32)

        _, per_layer = jax.lax.scan(layer, 0, {"w": q})
        np.testing.assert_allclose(np.asarray(per_layer), np.asarray(w), atol=0.05)

    def test_none_and_host_leaves_pass_through(self):
        import numpy as onp

        params = {"a": {"w": _rand((128, 128))}, "disk": {"w": None},
                  "host": {"w": onp.zeros((8, 8), onp.float32)}}
        cfg = QuantizationConfig(load_in_8bit=True, min_size=1024)
        q = quantize_params(params, cfg)
        assert q["disk"]["w"] is None
        assert isinstance(q["host"]["w"], onp.ndarray)  # untouched, not device_put
        assert isinstance(q["a"]["w"], QuantizedArray)


class TestStructurePreservation:
    def test_list_nodes_survive(self):
        params = {"layers": [_rand((64, 64), 0), _rand((64, 64), 1)]}
        cfg = QuantizationConfig(load_in_8bit=True, min_size=1024)
        q = quantize_params(params, cfg)
        assert isinstance(q["layers"], list)  # NOT converted to a dict
        assert isinstance(q["layers"][0], QuantizedArray)

    def test_single_layer_stack_scans(self):
        w = {"w": _rand((1, 64, 64))}  # L=1 stacked model
        cfg = QuantizationConfig(load_in_8bit=True, min_size=1024)
        q = quantize_params(w, cfg)

        def layer(c, p):
            return c + jnp.sum(jnp.asarray(p["w"])), None

        total, _ = jax.lax.scan(layer, jnp.float32(0), q)
        np.testing.assert_allclose(float(total), float(jnp.sum(w["w"])), rtol=0.02)

    def test_cast_to_compute_preserves_scales(self):
        from accelerate_tpu.utils.dataclasses import MixedPrecisionPolicy

        cfg = QuantizationConfig(load_in_8bit=True, min_size=1024)
        q = quantize_params({"w": _rand((64, 64))}, cfg)
        policy = MixedPrecisionPolicy.from_precision("bf16")
        cast = policy.cast_to_compute(q)
        assert cast["w"].scales.dtype == jnp.float32  # NOT truncated to bf16
