"""Reference API-surface parity: compat shims, kwargs handlers, offload hooks,
state-hook registration, lomo fused update.

Reference points: ``utils/dataclasses.py`` (DDP kwargs :155, FSDP plugin :1566,
DeepSpeed plugin :1113), ``big_modeling.py`` (``cpu_offload_with_hook:219``),
``accelerator.py`` (``register_save_state_pre_hook:3497``,
``register_load_state_pre_hook:3664``, ``lomo_backward:4265``).
"""

import os

import numpy as np
import pytest

import accelerate_tpu as atpu
from accelerate_tpu import Accelerator


# ---------------------------------------------------------------- exports --


def test_reference_export_names_resolve():
    # every name the reference exports at top level that has a TPU-native
    # counterpart must resolve from the package root
    for name in [
        "Accelerator",
        "AutocastKwargs",
        "DDPCommunicationHookType",
        "DeepSpeedPlugin",
        "DistributedDataParallelKwargs",
        "FullyShardedDataParallelPlugin",
        "GradScalerKwargs",
        "InitProcessGroupKwargs",
        "ProfileKwargs",
        "cpu_offload",
        "cpu_offload_with_hook",
        "dispatch_model",
        "is_rich_available",
        "load_checkpoint_in_model",
        "prepare_pipeline",
        "synchronize_rng_states",
        "notebook_launcher",
        "debug_launcher",
        "skip_first_batches",
        "init_empty_weights",
        "load_checkpoint_and_dispatch",
        "infer_auto_device_map",
        "find_executable_batch_size",
        "prepare_pippy",
        "rich",
        "init_on_device",
        "disk_offload",
        "load_checkpoint_in_model",
    ]:
        assert getattr(atpu, name) is not None


def test_reference_top_level_exports_complete_and_introspectable():
    """EVERY name `from accelerate import X` resolves (parsed from the
    reference's __init__) must resolve from accelerate_tpu AND appear in
    dir() — lazy loading must not hide the public surface."""
    import ast
    import pathlib

    ref_init = pathlib.Path("/root/reference/src/accelerate/__init__.py")
    if not ref_init.exists():
        pytest.skip("reference checkout not mounted")
    names = set()
    for node in ast.walk(ast.parse(ref_init.read_text())):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                names.add(a.asname or a.name)
    listing = dir(atpu)
    for name in sorted(names):
        assert getattr(atpu, name, None) is not None, f"missing export: {name}"
        assert name in listing, f"{name} resolves but is invisible to dir()"


def test_utils_reference_surface_resolves_broadly():
    """The reference's ``accelerate.utils`` exports: everything with a
    TPU-native meaning must resolve (engine/vendor internals — Megatron
    wrappers, TE/MSAMP recipes, device-vendor probes — are N/A by design)."""
    import accelerate_tpu.utils as u

    for name in [
        # new this round: enums/configs
        "ComputeEnvironment", "SageMakerDistributedType", "DynamoBackend",
        "CustomDtype", "TorchDynamoPlugin", "TorchContextParallelConfig",
        "TorchTensorParallelConfig", "TorchTensorParallelPlugin",
        "DeepSpeedSequenceParallelConfig", "DummyOptim", "DummyScheduler",
        # constants
        "SAFE_WEIGHTS_NAME", "SAFE_WEIGHTS_INDEX_NAME", "WEIGHTS_NAME",
        "RNG_STATE_NAME", "SCALER_NAME", "PROFILE_PATTERN_NAME",
        # ops/others
        "ignorant_find_batch_size", "TensorInformation", "is_tensor_information",
        "gather_across_data_parallel_groups", "avg_losses_across_data_parallel_group",
        "is_compiled_module", "is_torch_tensor", "is_torch_version",
        # module helpers + ckpt spellings
        "named_module_tensors", "set_module_tensor_to_device",
        "align_module_device", "has_offloaded_params", "id_tensor_storage",
        "load_offloaded_weights", "save_fsdp_model", "load_fsdp_model",
        "save_fsdp_optimizer", "load_fsdp_optimizer", "PrepareForLaunch",
        "ParallelismConfig", "load_checkpoint_in_model",
    ]:
        assert getattr(u, name, None) is not None, name
        assert name in dir(u), f"{name} invisible to dir()"


def test_module_level_reference_spellings():
    from accelerate_tpu.big_modeling import attach_layerwise_casting_hooks
    from accelerate_tpu.data_loader import SkipDataLoader, get_sampler
    from accelerate_tpu.tracking import get_available_trackers

    assert callable(attach_layerwise_casting_hooks)
    assert "jsonl" in get_available_trackers()
    from accelerate_tpu.data_loader import DataLoader

    class DS:
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return {"x": np.float32(i)}

    dl = DataLoader(DS(), batch_size=2)
    skipper = SkipDataLoader(dl, skip_batches=1)
    assert len(skipper) == 3
    assert len(list(skipper)) == 3
    assert len(skipper) == 3  # len stays consistent AFTER an epoch too
    assert len(list(skipper)) == 3  # reference: skips EVERY epoch, not once
    # a checkpoint resume takes precedence for one epoch, then persistent skip
    skipper.load_state_dict({"batches_seen": 3, "iteration": 0})
    assert len(skipper) == 1
    assert len(list(skipper)) == 1
    assert len(list(skipper)) == 3  # back to the persistent every-epoch skip
    # an EPOCH-BOUNDARY checkpoint (batches_seen=0) still honors the
    # persistent skip — it applies every epoch
    skipper.load_state_dict({"batches_seen": 0, "iteration": 1})
    assert len(list(skipper)) == 3
    # skip_first_batches on a SkipDataLoader is honored (not silently reset)
    from accelerate_tpu.data_loader import skip_first_batches

    assert len(list(skip_first_batches(skipper, 3))) == 1
    assert len(list(skipper)) == 3  # one-shot, then persistent again
    assert get_sampler(dl) is not None


def test_get_sampler_reaches_innermost_stateful_sampler():
    from accelerate_tpu.data_loader import DataLoader, get_sampler

    class DS:
        def __len__(self):
            return 16

        def __getitem__(self, i):
            return {"x": np.float32(i)}

    acc = Accelerator(cpu=True)
    dl = acc.prepare(DataLoader(DS(), batch_size=2, shuffle=True, seed=7))
    sampler = get_sampler(dl)
    assert hasattr(sampler, "state_dict"), type(sampler)  # the REAL sampler
    assert sampler.state_dict().get("seed") == 7


def test_ds_config_precision_conflicts():
    from accelerate_tpu.utils import DeepSpeedPlugin

    plugin = DeepSpeedPlugin(hf_ds_config={"fp16": {"enabled": True},
                                           "zero_optimization": {"stage": 2}})
    # constructor conflict: hard error (reference fill_match parity)
    with pytest.raises(ValueError, match="disagrees"):
        Accelerator(cpu=True, mixed_precision="bf16", deepspeed_plugin=plugin)
    # launcher env is NOT explicit (always set): config wins with a warning
    from accelerate_tpu.utils import patch_environment

    with patch_environment(ACCELERATE_MIXED_PRECISION="bf16"):
        with pytest.warns(UserWarning, match="ds config wins"):
            acc = Accelerator(cpu=True, deepspeed_plugin=plugin)
    assert acc.mixed_precision == "fp16"


def test_shim_configs_map_to_native_semantics():
    from accelerate_tpu.utils import (
        DynamoBackend,
        TorchContextParallelConfig,
        TorchDynamoPlugin,
        TorchTensorParallelPlugin,
    )

    assert TorchContextParallelConfig(cp_comm_strategy="allgather").cp_rotate_method == "allgather"
    assert TorchContextParallelConfig(cp_comm_strategy="alltoall").cp_rotate_method == "zigzag"
    with pytest.raises(ValueError):
        TorchContextParallelConfig(cp_comm_strategy="bogus")
    assert TorchDynamoPlugin(backend=DynamoBackend.EAGER).to_jit_config().disable_jit
    assert not TorchDynamoPlugin().to_jit_config().disable_jit
    pc = TorchTensorParallelPlugin(tp_size=2).to_parallelism_config()
    assert pc.tp_size == 2 and pc.dp_shard_size == -1


def test_dummy_optim_and_scheduler_through_prepare():
    """Reference DeepSpeed flow: DummyOptim/DummyScheduler placeholders become
    a real optimizer + warmup-decay schedule at prepare() time."""
    import jax.numpy as jnp

    from accelerate_tpu.utils import DummyOptim, DummyScheduler

    acc = Accelerator(cpu=True)
    params = {"w": jnp.ones((4, 4))}
    dummy_opt = DummyOptim(lr=1e-2)
    dummy_sched = DummyScheduler(dummy_opt, total_num_steps=10, warmup_num_steps=2)
    params, opt, sched = acc.prepare(params, dummy_opt, dummy_sched)
    from accelerate_tpu.optimizer import AcceleratedOptimizer
    from accelerate_tpu.scheduler import AcceleratedScheduler

    assert isinstance(opt, AcceleratedOptimizer)
    assert isinstance(sched, AcceleratedScheduler)
    # warmup then decay shape
    fn = sched.schedule_fn
    assert float(fn(0)) < float(fn(1)) <= 1e-2  # warming up
    assert float(fn(9)) < float(fn(2))  # decaying
    # the schedule must drive the REAL update, not just get_last_lr: adam's
    # normalized step magnitude tracks lr, so warmup deltas grow step-on-step
    import numpy as np_

    step = acc.prepare_train_step(lambda p, b: jnp.sum((p["w"] * b["x"]) ** 2), opt)
    batch = {"x": jnp.ones((4, 4))}
    p0 = np_.asarray(params["w"])
    params1, opt_state, _ = step(params, opt.opt_state, batch)
    p1 = np_.asarray(params1["w"])
    params2, opt_state, _ = step(params1, opt_state, batch)
    p2 = np_.asarray(params2["w"])
    d0 = np_.abs(p1 - p0).mean()
    d1 = np_.abs(p2 - p1).mean()
    assert d1 > d0 * 1.5, (d0, d1)  # lr(1)=2*lr(0) during the 2-step warmup


def test_dummy_scheduler_warmuplr_holds_after_warmup():
    """No total_num_steps = DS WarmupLR: hold base lr after warmup, never
    decay to zero."""
    import jax.numpy as jnp

    from accelerate_tpu.utils import DummyOptim, DummyScheduler

    acc = Accelerator(cpu=True)
    do = DummyOptim(lr=1e-2)
    ds = DummyScheduler(do, warmup_num_steps=2)  # no total
    fn = acc._dummy_schedule_fn(ds)
    assert float(fn(0)) < 1e-2
    assert float(fn(2)) == pytest.approx(1e-2)
    assert float(fn(5000)) == pytest.approx(1e-2)  # holds, no decay-to-zero

    # unpaired scheduler picks up the co-prepared DummyOptim's lr
    do2 = DummyOptim(lr=1e-5)
    ds2 = DummyScheduler(total_num_steps=100, warmup_num_steps=10)
    params, opt, sched = acc.prepare({"w": jnp.ones((2,))}, do2, ds2)
    assert ds2.optimizer is do2
    assert float(sched.schedule_fn(50)) <= 1e-5  # scaled by the REAL base lr


def test_dummy_scheduler_alone_warns_about_unbaked_lr():
    from accelerate_tpu.utils import DummyScheduler

    acc = Accelerator(cpu=True)
    with pytest.warns(UserWarning, match="cannot be baked"):
        acc.prepare(DummyScheduler(total_num_steps=10))


def test_dummy_scheduler_callable_receives_optimizer():
    from accelerate_tpu.utils import DummyOptim, DummyScheduler

    acc = Accelerator(cpu=True)
    seen = {}

    class FakeSched:
        def step(self):
            pass

    def make(optimizer):
        seen["opt"] = optimizer
        return FakeSched()

    do = DummyOptim(lr=1e-3)
    import jax.numpy as jnp

    with pytest.warns(UserWarning, match="cannot modulate"):
        params, opt, sched = acc.prepare(
            {"w": jnp.ones((2,))}, do, DummyScheduler(do, lr_scheduler_callable=make)
        )
    assert seen["opt"] is do
    # callable-built schedulers follow the same once-per-optimizer-step rule
    assert sched.num_processes == 1


def test_ds_config_drives_dummy_hyperparams_and_precision():
    """The ds config's optimizer/scheduler/bf16 sections are the source of
    truth for placeholders (reference deepspeed_with_config_support flow)."""
    from accelerate_tpu.utils import DeepSpeedPlugin, DummyOptim, DummyScheduler

    ds = {
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "AdamW", "params": {
            "lr": 5e-4, "betas": [0.9, 0.95], "eps": 1e-6, "weight_decay": 0.05}},
        "scheduler": {"type": "WarmupDecayLR", "params": {
            "warmup_num_steps": 3, "total_num_steps": "auto"}},
    }
    plugin = DeepSpeedPlugin(hf_ds_config=ds)
    assert plugin.mixed_precision == "bf16"
    assert plugin.dummy_optim_kwargs() == {
        "lr": 5e-4, "betas": (0.9, 0.95), "eps": 1e-6, "weight_decay": 0.05
    }
    assert plugin.dummy_scheduler_kwargs() == {"warmup_num_steps": 3}  # auto omitted

    acc = Accelerator(cpu=True, deepspeed_plugin=plugin)
    assert acc.mixed_precision == "bf16"  # config set it, user didn't
    do = DummyOptim(lr=9.0)  # placeholder value loses to the config
    dsc = DummyScheduler(do, total_num_steps=10, warmup_num_steps=99)
    import jax.numpy as jnp

    params, opt, sched = acc.prepare({"w": jnp.ones((2, 2))}, do, dsc)
    assert do.lr == 5e-4 and do.kwargs["betas"] == (0.9, 0.95)
    assert dsc.warmup_num_steps == 3 and dsc.total_num_steps == 10  # auto kept user value
    # ds schedulers advance once per optimizer step (no num_processes scaling)
    assert sched.num_processes == 1


def test_fsdp_ckpt_spellings_round_trip(tmp_path):
    import jax.numpy as jnp

    from accelerate_tpu.utils import load_fsdp_model, save_fsdp_model

    params = {"layer": {"w": jnp.arange(8.0).reshape(2, 4)}}
    save_fsdp_model(None, None, params, str(tmp_path))
    zeros = {"layer": {"w": jnp.zeros((2, 4))}}
    back = load_fsdp_model(None, None, zeros, str(tmp_path))
    np.testing.assert_array_equal(np.asarray(back["layer"]["w"]), np.asarray(params["layer"]["w"]))


def test_torch_module_helper_spellings():
    torch = pytest.importorskip("torch")

    from accelerate_tpu.utils import (
        align_module_device,
        has_offloaded_params,
        id_tensor_storage,
        named_module_tensors,
        set_module_tensor_to_device,
    )

    m = torch.nn.Linear(3, 2)
    m.register_buffer("buf", torch.zeros(2))
    names = [n for n, _ in named_module_tensors(m)]
    assert set(names) == {"weight", "bias", "buf"}
    set_module_tensor_to_device(m, "bias", "cpu", value=torch.ones(2))
    assert torch.equal(m.bias, torch.ones(2))
    assert not has_offloaded_params(m)
    a, b = m.weight, m.weight.view(-1)
    assert id_tensor_storage(a) == id_tensor_storage(b)  # views share storage
    with align_module_device(m, "cpu"):
        pass  # no crash; devices unchanged on exit
    assert m.weight.device.type == "cpu"


def test_kwargs_aliases_are_the_native_classes():
    from accelerate_tpu.utils import (
        AutocastConfig,
        AutocastKwargs,
        GradScalerConfig,
        GradScalerKwargs,
        ProfileConfig,
        ProfileKwargs,
    )

    assert AutocastKwargs is AutocastConfig
    assert GradScalerKwargs is GradScalerConfig
    assert ProfileKwargs is ProfileConfig


# ---------------------------------------------------------------- versions --


def test_compare_versions():
    from accelerate_tpu.utils import compare_versions, is_jax_version

    assert compare_versions("1.2.3", ">=", "1.2")
    assert compare_versions("1.2.3", "<", "1.10")  # numeric, not lexicographic
    assert not compare_versions("2.0", "==", "2.1")
    assert compare_versions("jax", ">", "0.1")
    assert is_jax_version(">=", "0.3")
    # PEP-440-style padding: X.Y.0 == X.Y
    assert compare_versions("0.7.0", "==", "0.7")
    assert compare_versions("0.7.0", "<=", "0.7")
    assert not compare_versions("0.7.1", "==", "0.7")
    with pytest.raises(ValueError):
        compare_versions("1.0", "~=", "1.0")


# ------------------------------------------------------------ plugin shims --


def test_fsdp_plugin_strategy_spellings():
    P = atpu.FullyShardedDataParallelPlugin
    assert P(sharding_strategy="full_shard").sharding_strategy == "FULL_SHARD"
    assert P(sharding_strategy=1).sharding_strategy == "FULL_SHARD"
    assert P(sharding_strategy="ShardingStrategy.SHARD_GRAD_OP").sharding_strategy == "SHARD_GRAD_OP"
    with pytest.raises(ValueError):
        P(sharding_strategy="BOGUS")
    with pytest.raises(ValueError):
        P(sharding_strategy=5)  # unknown int codes must not silently FULL_SHARD


def test_fsdp_plugin_activation_checkpointing_maps_to_remat():
    P = atpu.FullyShardedDataParallelPlugin
    assert P().remat is False
    assert P(activation_checkpointing=True).remat == "dots_no_batch"
    # and the mapped policy is accepted by the model forward
    import jax

    from accelerate_tpu.models import LlamaConfig, init_llama
    from accelerate_tpu.models.transformer import llama_loss

    cfg = LlamaConfig.tiny()
    params = init_llama(cfg, jax.random.PRNGKey(0))
    ids = np.ones((1, 16), np.int32)
    loss = float(llama_loss(params, {"input_ids": ids}, cfg,
                            remat=P(activation_checkpointing=True).remat))
    assert np.isfinite(loss)


def test_lomo_cache_is_bounded():
    import jax.numpy as jnp

    from accelerate_tpu.accelerator import _LOMO_CACHE_SIZE

    acc = Accelerator(cpu=True)
    params = {"w": jnp.ones((2,))}
    for i in range(_LOMO_CACHE_SIZE + 4):
        # fresh lambda per call — the documented misuse; cache must stay bounded
        _, params = acc.lomo_backward(lambda p: (p["w"] ** 2).sum(), params, learning_rate=0.01)
    assert len(acc._lomo_steps) <= _LOMO_CACHE_SIZE


def test_fsdp_plugin_to_parallelism_config():
    pc = atpu.FullyShardedDataParallelPlugin().to_parallelism_config(num_devices=8)
    assert pc.dp_shard_size == -1
    pc = atpu.FullyShardedDataParallelPlugin(sharding_strategy="NO_SHARD").to_parallelism_config(num_devices=8)
    assert pc.dp_replicate_size == 8 and pc.dp_shard_size == 1
    with pytest.raises(ValueError):
        atpu.FullyShardedDataParallelPlugin(sharding_strategy="HYBRID_SHARD").to_parallelism_config(8)
    pc = atpu.FullyShardedDataParallelPlugin(sharding_strategy="HYBRID_SHARD").to_parallelism_config(
        8, dp_replicate_size=2
    )
    assert pc.dp_replicate_size == 2


def test_deepspeed_plugin_mines_ds_config():
    p = atpu.DeepSpeedPlugin(
        hf_ds_config={
            "zero_optimization": {"stage": 3, "offload_param": {"device": "nvme"}},
            "gradient_accumulation_steps": 4,
            "gradient_clipping": 0.5,
        }
    )
    assert p.zero_stage == 3
    assert p.gradient_accumulation_steps == 4
    assert p.gradient_clipping == 0.5
    assert p.offload_param_device == "nvme"
    assert p.to_parallelism_config().dp_shard_size == -1
    assert atpu.DeepSpeedPlugin(zero_stage=0).to_parallelism_config(4).dp_replicate_size == 4
    # "auto" values are left at defaults, as the reference's fill_match does
    p = atpu.DeepSpeedPlugin(hf_ds_config={"zero_optimization": {"stage": "auto"}})
    assert p.zero_stage == 2
    with pytest.raises(ValueError):
        atpu.DeepSpeedPlugin(zero_stage=7)


def test_ddp_kwargs_comm_hook_dtype():
    K, H = atpu.DistributedDataParallelKwargs, atpu.DDPCommunicationHookType
    assert K().gradient_compression_dtype() is None
    assert K(comm_hook=H.FP16).gradient_compression_dtype() == "float16"
    assert K(comm_hook="bf16").gradient_compression_dtype() == "bfloat16"
    with pytest.warns(UserWarning):
        assert K(comm_hook=H.POWER_SGD).gradient_compression_dtype() == "bfloat16"


def test_accelerator_accepts_fsdp_plugin():
    acc = Accelerator(cpu=True, fsdp_plugin=atpu.FullyShardedDataParallelPlugin())
    assert acc.mesh.shape["dp_shard"] == 8  # -1 inferred at mesh build


def test_accelerator_accepts_deepspeed_plugin_with_accum():
    acc = Accelerator(
        cpu=True, deepspeed_plugin=atpu.DeepSpeedPlugin(zero_stage=2, gradient_accumulation_steps=4)
    )
    assert acc.gradient_accumulation_steps == 4
    assert acc.mesh.shape["dp_shard"] == 8


def test_deepspeed_plugin_gradient_clipping_applies():
    """ds_config gradient_clipping must actually clip in the prepared step."""
    import jax.numpy as jnp
    import optax

    clip = 0.01
    acc = Accelerator(cpu=True, deepspeed_plugin=atpu.DeepSpeedPlugin(zero_stage=2, gradient_clipping=clip))
    params, opt = acc.prepare({"w": jnp.full((4,), 100.0)}, optax.sgd(1.0))

    def loss_fn(p, batch):
        return jnp.sum(p["w"] * batch["x"])  # grad = x (norm >> clip)

    step = acc.prepare_train_step(loss_fn, opt)
    batch = {"x": jnp.full((4,), 10.0)}
    params2, _, _ = step(params, opt.opt_state, batch)
    # update magnitude bounded by lr * clip
    delta = np.abs(np.asarray(params2["w"]) - 100.0)
    assert float(delta.max()) <= clip + 1e-6


def test_lomo_backward_fp16_scaled_and_overflow_safe():
    import jax.numpy as jnp

    acc = Accelerator(cpu=True, mixed_precision="fp16")
    params = {"w": jnp.asarray([2.0, -1.0], jnp.float32)}

    def loss_fn(p):
        return jnp.sum(p["w"] ** 2)

    for _ in range(30):
        loss, params = acc.lomo_backward(loss_fn, params, learning_rate=0.1)
    assert float(loss) < 0.1  # converges despite fp16 compute

    # overflow: fp16 forward inf → update skipped, params unchanged, no NaN
    big = {"w": jnp.asarray([60000.0, 60000.0], jnp.float32)}  # fp16 max ~65504

    def sq(p):
        return jnp.sum(p["w"] * p["w"])  # fp16 square overflows

    loss, out = acc.lomo_backward(sq, big, learning_rate=0.1)
    assert np.all(np.isfinite(np.asarray(out["w"])))
    np.testing.assert_array_equal(np.asarray(out["w"]), np.asarray([60000.0, 60000.0]))


def test_accelerator_rejects_both_plugins_and_non_plugins():
    with pytest.raises(ValueError):
        Accelerator(cpu=True, fsdp_plugin=atpu.FullyShardedDataParallelPlugin(),
                    deepspeed_plugin=atpu.DeepSpeedPlugin())
    with pytest.raises(TypeError):
        Accelerator(cpu=True, fsdp_plugin=object())


# ------------------------------------------------------- kwargs_handlers --


def test_accelerator_kwargs_handlers_routing():
    from accelerate_tpu.utils import DistributedDataParallelKwargs, GradScalerKwargs

    scaler = GradScalerKwargs(init_scale=64.0)
    ddp = DistributedDataParallelKwargs(comm_hook="bf16")
    acc = Accelerator(cpu=True, kwargs_handlers=[scaler, ddp])
    assert acc.grad_scaler_config.init_scale == 64.0
    assert acc.ddp_handler is ddp


def test_accelerator_kwargs_handlers_rejects_duplicates_and_unknown():
    from accelerate_tpu.utils import GradScalerKwargs

    with pytest.raises(ValueError):
        Accelerator(cpu=True, kwargs_handlers=[GradScalerKwargs(), GradScalerKwargs()])
    with pytest.raises(ValueError):
        Accelerator(cpu=True, kwargs_handlers=[object()])


def test_comm_hook_compression_applies_in_train_step():
    """bf16-compressed grads step must still train (values bounded to bf16)."""
    import jax.numpy as jnp
    import optax

    from accelerate_tpu.utils import DistributedDataParallelKwargs

    acc = Accelerator(cpu=True, kwargs_handlers=[DistributedDataParallelKwargs(comm_hook="bf16")])
    params, opt = acc.prepare({"w": jnp.ones((4,), jnp.float32)}, optax.sgd(0.5))

    def loss_fn(p, batch):
        return jnp.sum((p["w"] * batch["x"] - batch["y"]) ** 2)

    step = acc.prepare_train_step(loss_fn, opt)
    batch = {"x": jnp.ones((4,)), "y": jnp.zeros((4,))}
    params2, _, metrics = step(params, opt.opt_state, batch)
    assert float(metrics["loss"]) > 0
    assert not np.allclose(np.asarray(params2["w"]), 1.0)


# ------------------------------------------------------------ offload hook --


def test_cpu_offload_with_hook_round_trip():
    params = {"w": np.arange(8, dtype=np.float32).reshape(2, 4)}
    dev_params, hook = atpu.cpu_offload_with_hook(params)
    import jax

    assert isinstance(dev_params["w"], jax.Array)
    np.testing.assert_array_equal(np.asarray(dev_params["w"]), params["w"])
    hook.offload()
    # host copy survives; reload pages it back
    again = hook.load()
    np.testing.assert_array_equal(np.asarray(again["w"]), params["w"])
    hook.remove()


def test_cpu_offload_with_hook_chaining_offloads_previous():
    a = {"w": np.ones((2,), np.float32)}
    b = {"w": np.full((2,), 2.0, np.float32)}
    _, hook_a = atpu.cpu_offload_with_hook(a)
    _, hook_b = atpu.cpu_offload_with_hook(b, prev_module_hook=hook_a)
    # loading b must have paged a off the device (chaining is one-directional,
    # matching the reference: each hook offloads only its prev_module_hook)
    assert hook_a._on_device is None
    assert hook_b._on_device is not None
    np.testing.assert_array_equal(np.asarray(hook_a.params["w"]), a["w"])


# ---------------------------------------------------------- state prehooks --


def test_save_and_load_state_pre_hooks(tmp_path):
    import jax.numpy as jnp

    acc = Accelerator(cpu=True, project_dir=str(tmp_path))
    calls = []
    h1 = acc.register_save_state_pre_hook(lambda models, d: calls.append(("save", d)))
    h2 = acc.register_load_state_pre_hook(lambda models, d: calls.append(("load", d)))
    params = {"w": jnp.ones((2,))}
    out = acc.save_state(str(tmp_path / "ck"), params=params)
    acc.load_state(out, params=params)
    assert [c[0] for c in calls] == ["save", "load"]
    h1.remove()
    h2.remove()
    acc.save_state(str(tmp_path / "ck2"), params=params)
    assert len(calls) == 2  # removed hook did not fire


def test_save_state_pre_hook_sees_resolved_dir(tmp_path):
    """With automatic checkpoint naming the hook must receive the real
    ``checkpoint_<i>`` directory, not the raw (None) argument."""
    import jax.numpy as jnp

    from accelerate_tpu.utils import ProjectConfiguration

    acc = Accelerator(
        cpu=True,
        project_config=ProjectConfiguration(
            project_dir=str(tmp_path), automatic_checkpoint_naming=True
        ),
    )
    seen = []
    acc.register_save_state_pre_hook(lambda models, d: seen.append(d))
    out = acc.save_state(params={"w": jnp.ones((2,))})
    assert seen == [out]
    assert os.path.basename(out).startswith("checkpoint_")


def test_autocast_disable_builds_full_precision_step():
    """AutocastKwargs(enabled=False) must make steps BUILT inside the context
    compute in full precision despite the bf16 session policy."""
    import jax.numpy as jnp
    import optax

    from accelerate_tpu.utils import AutocastKwargs

    acc = Accelerator(cpu=True, mixed_precision="bf16")
    params, opt = acc.prepare({"w": jnp.ones((4,), jnp.float32)}, optax.sgd(0.1))
    seen = {}

    def loss_fn(p, batch):
        seen["dtype"] = p["w"].dtype
        return jnp.sum((p["w"] * batch["x"]) ** 2)

    batch = {"x": jnp.ones((4,))}
    with acc.autocast(AutocastKwargs(enabled=False)):
        step32 = acc.prepare_train_step(loss_fn, opt)
        params, opt_state, _ = step32(params, opt.opt_state, batch)  # donated: rebind
        assert seen["dtype"] == jnp.float32
    step16 = acc.prepare_train_step(loss_fn, opt)
    step16(params, opt_state, batch)
    assert seen["dtype"] == jnp.bfloat16


def test_profile_handler_routed_from_kwargs(tmp_path):
    from accelerate_tpu.utils import ProfileKwargs

    handler = ProfileKwargs(output_trace_dir=str(tmp_path / "tr"))
    acc = Accelerator(cpu=True, kwargs_handlers=[handler])
    assert acc.profile_handler is handler


def test_step_windowed_profile_schedule(tmp_path):
    """Reference ProfileKwargs(wait/warmup/active/repeat/skip_first) schedule
    (``utils/dataclasses.py:484-599``): only the active windows are traced,
    one trace dir per cycle, per rank."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.utils import ProfileKwargs

    acc = Accelerator(cpu=True)
    cfg = ProfileKwargs(
        output_trace_dir=str(tmp_path), skip_first=1, wait=1, warmup=1, active=2, repeat=2
    )
    f = jax.jit(lambda x: jnp.sin(x) * 2)
    x = jnp.ones((8,))
    with acc.profile(cfg) as prof:
        assert prof is not None
        for _ in range(12):
            x = f(x)
            x.block_until_ready()
            prof.step()
        # repeat=2 exhausted: tracing must be off even mid-loop
        assert not prof.tracing
    assert len(prof.trace_dirs) == 2
    for d in prof.trace_dirs:
        assert os.path.isdir(d) and any(os.scandir(d)), d
    # cycle dirs live under the per-rank dir
    assert all(f"rank{acc.process_index}" in d for d in prof.trace_dirs)


def test_step_windowed_profile_schedule_math():
    from accelerate_tpu.accelerator import StepProfiler
    from accelerate_tpu.utils.dataclasses import ProfileConfig

    cfg = ProfileConfig(skip_first=2, wait=1, warmup=1, active=2, repeat=0)
    prof = StepProfiler(cfg, "/tmp/unused")
    # step() is called AFTER each work step; work step k is traced iff the
    # profiler is tracing between calls k and k+1
    traced_work_steps = []
    import unittest.mock as mock

    with mock.patch("jax.profiler.start_trace"), mock.patch("jax.profiler.stop_trace"), \
         mock.patch("os.makedirs"):
        for k in range(14):
            prof.step()
            if prof.tracing:
                traced_work_steps.append(k + 1)  # the upcoming work step
        prof.close()
    # skip_first=2, cycle = wait 1 + warmup 1 + active 2: active work steps are
    # 4,5 then 8,9 then 12,13 ...
    assert traced_work_steps == [4, 5, 8, 9, 12, 13], traced_work_steps


def test_step_profiler_traces_first_step_and_splits_cycles():
    import unittest.mock as mock

    from accelerate_tpu.accelerator import StepProfiler
    from accelerate_tpu.utils.dataclasses import ProfileConfig

    with mock.patch("jax.profiler.start_trace") as start, \
         mock.patch("jax.profiler.stop_trace") as stop, mock.patch("os.makedirs"):
        # active window starting at position 0: the FIRST work step is traced
        prof = StepProfiler(ProfileConfig(active=1, repeat=1), "/tmp/unused")
        assert prof.tracing  # tracing from context entry, before any step()
        prof.step()
        assert not prof.tracing
        prof.close()
        assert start.call_count == 1 and stop.call_count == 1
        assert len(prof.trace_dirs) == 1

        # back-to-back active windows (wait=warmup=0) split per cycle
        start.reset_mock(), stop.reset_mock()
        prof = StepProfiler(ProfileConfig(active=2, repeat=3), "/tmp/unused")
        for _ in range(8):
            prof.step()
        prof.close()
        assert len(prof.trace_dirs) == 3, prof.trace_dirs
        assert [d.rsplit("cycle", 1)[1] for d in prof.trace_dirs] == ["0", "1", "2"]
        assert start.call_count == 3 and stop.call_count == 3


# ------------------------------------------------------------------- lomo --


def test_lomo_backward_fused_sgd_converges():
    import jax.numpy as jnp

    acc = Accelerator(cpu=True)
    params = {"w": jnp.asarray([3.0, -2.0])}

    def loss_fn(p):
        return jnp.sum(p["w"] ** 2)

    losses = []
    for _ in range(40):
        loss, params = acc.lomo_backward(loss_fn, params, learning_rate=0.1)
        losses.append(float(loss))
    assert losses[-1] < 1e-2 * losses[0]
    assert len(acc._lomo_steps) == 1  # jitted once, reused


# ------------------------------------------------------- prepare_pipeline --


def test_prepare_pipeline_matches_sequential():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from accelerate_tpu.parallel import prepare_pipeline

    devs = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("pp",))
    rng = np.random.default_rng(0)
    layer_params = [
        {"w": jnp.asarray(rng.normal(size=(8, 8)) / 8, jnp.float32)} for _ in range(8)
    ]

    def stage_fn(stage_params, x):
        # stage_params: layers stacked [L/pp, ...] — scan over the slice
        def body(h, lp):
            return jnp.tanh(h @ lp["w"]), None

        out, _ = jax.lax.scan(body, x, stage_params)
        return out

    stacked, forward = prepare_pipeline(layer_params, stage_fn, mesh)
    x = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)
    got = forward(stacked, x)

    ref = x
    for lp in layer_params:
        ref = jnp.tanh(ref @ lp["w"])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_optimizer_module_spellings():
    import jax
    import jax.numpy as jnp
    import optax

    from accelerate_tpu.optimizer import (
        AcceleratedOptimizer,
        move_to_device,
        patch_optimizer_step,
    )

    opt = AcceleratedOptimizer(optax.adam(0.1))  # adam: REAL moment leaves
    opt.init({"w": jnp.ones((2,))})
    target = jax.devices()[0]
    moved = move_to_device(opt.opt_state, target)
    assert jax.tree_util.tree_structure(moved) == jax.tree_util.tree_structure(opt.opt_state)
    array_leaves = [l for l in jax.tree_util.tree_leaves(moved) if hasattr(l, "devices")]
    assert array_leaves  # placement assertion must not be vacuous
    for leaf in array_leaves:
        assert leaf.devices() == {target}  # placement really happened
    # reference contract: returns a wrapped method flagging the optimizer
    calls = []
    patched = patch_optimizer_step(opt, lambda *a: calls.append(a))
    assert opt._accelerate_step_called is False  # initialized like the reference
    patched("g", "p")
    assert opt._accelerate_step_called and calls == [("g", "p")]


# ------------------------------------------------------- pinned utils boundary --


def test_utils_reference_boundary_is_closed():
    """EVERY name the reference's ``accelerate.utils`` exports either resolves
    from ``accelerate_tpu.utils`` or appears in ``EXCLUDED_REFERENCE_UTILS``
    with a reason — and never both. The boundary is pinned: a reference name
    can neither be silently missing nor excluded while also implemented
    (VERDICT r04 item 6)."""
    import ast
    import pathlib

    import accelerate_tpu.utils as u

    ref_init = pathlib.Path("/root/reference/src/accelerate/utils/__init__.py")
    if not ref_init.exists():
        pytest.skip("reference checkout not mounted")
    names = set()
    for node in ast.walk(ast.parse(ref_init.read_text())):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                names.add(a.asname or a.name)
    resolved = {n for n in names if getattr(u, n, None) is not None}
    excluded = set(u.EXCLUDED_REFERENCE_UTILS)
    assert not (resolved & excluded), f"both implemented and excluded: {sorted(resolved & excluded)}"
    assert not (excluded - names), f"excluding names the reference no longer exports: {sorted(excluded - names)}"
    unaccounted = names - resolved - excluded
    assert not unaccounted, f"neither implemented nor excluded-with-reason: {sorted(unaccounted)}"
    for name, reason in u.EXCLUDED_REFERENCE_UTILS.items():
        assert isinstance(reason, str) and len(reason) > 20, f"{name}: reason too thin"


def test_new_parity_names_function():
    """The round-5 additions do real work, not just resolve."""
    import numpy as np

    from accelerate_tpu import utils as u

    tree = {
        "embed": {"w": np.zeros((64, 8), np.float32)},
        "layers": {"a": {"k": np.zeros((4, 8, 8), np.float32)},
                   "b": {"w": np.zeros((4, 8, 16), np.float32)}},
    }
    total, (largest, names) = u.calculate_maximum_sizes(tree)
    assert total == 64 * 8 * 4 + 4 * (8 * 8 + 8 * 16) * 4
    assert largest == 64 * 8 * 4 and names == ["embed"]  # scan stack counts per-slice
    per_slice, _ = u.get_max_layer_size({"layers": tree["layers"]})
    assert per_slice == (8 * 8 + 8 * 16) * 4
    u.check_device_map(tree, {"embed": 0, "layers": "cpu"})
    with pytest.raises(ValueError):
        u.check_device_map(tree, {"embed": 0})
    assert u.extract_submodules_state_dict({"x/w": 1, "y/w": 2}, ["x"]) == {"w": 1}

    # megatron shim configures the native mesh
    plugin = u.MegatronLMPlugin(tp_degree=2, pp_degree=2, expert_model_parallel_size=2)
    pc = plugin.to_parallelism_config()
    assert (pc.tp_size, pc.pp_size, pc.ep_size, pc.dp_shard_size) == (2, 2, 2, -1)
    # Megatron sequence_parallelism is a flag on the tp group, NOT a Ulysses
    # axis: it must consume no extra devices (tp_degree=4 + SP fits 4 chips)
    sp_pc = u.MegatronLMPlugin(tp_degree=4, sequence_parallelism=True).to_parallelism_config()
    assert sp_pc.sp_size == 1 and sp_pc.total_size(num_devices=4) == 4

    # fp8 recipe kwargs map onto the native recipe
    recipe = u.TERecipeKwargs(amax_history_len=8).to_native()
    assert recipe.amax_history_len == 8 and u.TERecipeKwargs().backend == "TE"
    assert u.AORecipeKwargs().backend == "AO" and u.MSAMPRecipeKwargs().backend == "MSAMP"

    # ds-surface spellings
    ds = u.HfDeepSpeedConfig({"zero_optimization": {"stage": 3}})
    assert ds.is_zero3() and not ds.is_zero2() and not ds.is_offload()
    with pytest.raises(ValueError):
        u.get_active_deepspeed_plugin(object())

    # regional compilation public API
    from accelerate_tpu.models import LlamaConfig

    regional = u.compile_regions(LlamaConfig.tiny())
    assert regional.unroll_layers is False and u.has_compiled_regions(regional)
    fn = u.compile_regions(lambda x: x * 2)
    assert fn(3) == 6 and u.has_compiled_regions(fn)

    # probes are honest on this image
    assert u.is_xpu_available() is False and u.is_hpu_available() is False
    assert u.is_transformer_engine_available() is False
    assert u.is_peft_model(object()) is False and u.model_has_dtensor(object()) is False

    # env/launch spellings
    assert u.get_cpu_distributed_information()["world_size"] >= 1
    env = u.prepare_multi_gpu_env(type("A", (), {"mixed_precision": "bf16"})())
    assert env["ACCELERATE_MIXED_PRECISION"] == "bf16"  # key must actually exist

    # fsdp ram-efficient toggles supply the DEFAULT; explicit args win
    u.disable_fsdp_ram_efficient_loading()
    try:
        assert u.FullyShardedDataParallelPlugin().cpu_ram_efficient_loading is False
        assert u.FullyShardedDataParallelPlugin(
            cpu_ram_efficient_loading=True
        ).cpu_ram_efficient_loading is True  # explicit beats env
        u.enable_fsdp_ram_efficient_loading()
        assert u.FullyShardedDataParallelPlugin().cpu_ram_efficient_loading is True
    finally:
        os.environ.pop("FSDP_CPU_RAM_EFFICIENT_LOADING", None)

    # fp8 recipe validation is as strict as the native recipe
    with pytest.raises(ValueError):
        u.FP8RecipeKwargs(fp8_format="E5M2")

    # ragged leaves warn (and pass through) instead of failing silently
    import warnings as _warnings

    from accelerate_tpu.utils.operations import CannotPadNestedTensorWarning, pad_across_processes

    ragged = {"x": np.array([[1, 2], [3]], dtype=object)}
    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        out = pad_across_processes(ragged)
    assert any(issubclass(w.category, CannotPadNestedTensorWarning) for w in caught)
    assert out["x"] is ragged["x"]


def test_conflicting_fp8_handlers_raise():
    from accelerate_tpu.utils import AORecipeKwargs, TERecipeKwargs

    with pytest.raises(ValueError):
        Accelerator(kwargs_handlers=[TERecipeKwargs(), AORecipeKwargs()], cpu=True)


def test_accelerator_accepts_megatron_and_dynamo_plugins():
    """MegatronLMPlugin degrees define the mesh; TorchDynamoPlugin's one
    actionable XLA knob (eager) reaches JitConfig; fp8 recipe kwargs land as
    the native recipe (VERDICT r04 item 7)."""
    from accelerate_tpu import ParallelismConfig
    from accelerate_tpu.utils import MegatronLMPlugin, TERecipeKwargs, TorchDynamoPlugin
    from accelerate_tpu.utils.dataclasses import JitConfig

    acc = Accelerator(
        megatron_lm_plugin=MegatronLMPlugin(tp_degree=2, num_micro_batches=4),
        kwargs_handlers=[TERecipeKwargs(amax_history_len=8)],
        cpu=True,
    )
    assert acc.parallelism_config.tp_size == 2
    assert acc.gradient_accumulation_steps == 4  # micro-batches = accumulation
    assert acc.fp8_recipe.amax_history_len == 8
    assert acc.fp8_recipe_handler.backend == "TE"

    with pytest.raises(ValueError):
        Accelerator(megatron_lm_plugin=MegatronLMPlugin(),
                    parallelism_config=ParallelismConfig())
    with pytest.raises(ValueError):
        Accelerator(dynamo_plugin=TorchDynamoPlugin(), jit_config=JitConfig())


def test_dynamo_plugin_eager_reaches_jit_config():
    from accelerate_tpu.utils import TorchDynamoPlugin

    acc = Accelerator(dynamo_plugin=TorchDynamoPlugin(backend="EAGER"), cpu=True)
    assert acc.jit_config.disable_jit is True
