"""Telemetry subsystem: event-log round-trip, recompile counting, report CLI
aggregation, disabled-mode zero-write behavior, comms counters, dataloader
data-wait + reshard routing, and the tracker bridge."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, DataLoader, telemetry as tel
from accelerate_tpu.telemetry import events as tel_events
from accelerate_tpu.telemetry.report import build_report, format_report, main as report_main
from accelerate_tpu.telemetry.step_profiler import RecompileWatcher, StepTelemetry
from accelerate_tpu.utils import operations as ops

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _telemetry_clean(monkeypatch):
    monkeypatch.delenv("ACCELERATE_TELEMETRY", raising=False)
    monkeypatch.delenv("ACCELERATE_TELEMETRY_DIR", raising=False)
    monkeypatch.delenv("ACCELERATE_RUN_ID", raising=False)
    yield
    tel.disable()
    ops.reset_comm_counters()


# ---------------------------------------------------------------- event log --


def test_event_log_round_trip(tmp_path):
    log = tel.enable(str(tmp_path), run_id="run-test")
    log.emit("custom", payload=42)
    with tel.span("region", tag="a"):
        pass
    tel.set_step(7)
    tel.counter("items", 3)
    tel.gauge("temp", 1.5)
    tel.disable()

    files = os.listdir(tmp_path)
    assert files == ["events-rank0.jsonl"]
    records = [json.loads(line) for line in open(tmp_path / files[0])]
    meta, rest = records[0], records[1:]
    assert meta["kind"] == "meta"
    assert meta["schema"] == tel_events.TELEMETRY_SCHEMA_VERSION
    assert meta["run_id"] == "run-test"
    assert meta["process_index"] == 0 and meta["num_processes"] >= 1
    kinds = [r["kind"] for r in rest]
    assert kinds == ["custom", "span", "counter", "gauge"]
    assert all(isinstance(r["t"], float) for r in rest)
    assert rest[1]["name"] == "region" and rest[1]["dur_s"] >= 0 and rest[1]["tag"] == "a"
    # step rides along once set
    assert rest[2]["step"] == 7 and rest[3]["step"] == 7
    assert "step" not in rest[0]


def test_disabled_mode_writes_nothing(tmp_path, monkeypatch):
    monkeypatch.setenv(tel_events.TELEMETRY_DIR_ENV_VAR, str(tmp_path / "t"))
    assert not tel.is_enabled()
    assert tel.maybe_enable_from_env() is None  # kill switch: env unset
    tel.emit("x", a=1)
    tel.counter("c", 1)
    tel.gauge("g", 1)
    tel.set_step(3)
    # the disabled span is one shared null object — no per-call allocation
    assert tel.span("a") is tel.span("b")
    with tel.span("a"):
        pass
    assert not (tmp_path / "t").exists()
    assert tel.get_event_log() is None


def test_kill_switch_enables_from_env(tmp_path, monkeypatch):
    monkeypatch.setenv(tel_events.TELEMETRY_ENV_VAR, "1")
    monkeypatch.setenv(tel_events.TELEMETRY_DIR_ENV_VAR, str(tmp_path / "out"))
    log = tel.maybe_enable_from_env()
    assert log is not None and tel.is_enabled()
    tel.emit("ping")
    tel.disable()
    assert (tmp_path / "out" / "events-rank0.jsonl").exists()


def test_enabled_but_silent_run_creates_no_file(tmp_path):
    tel.enable(str(tmp_path / "quiet"))
    tel.disable()  # nothing emitted -> nothing opened
    assert not (tmp_path / "quiet").exists()


# ---------------------------------------------------- recompile detection ----


def test_recompile_watcher_counts_cache_misses_per_function():
    fn = jax.jit(lambda x: x * 2)
    watcher = RecompileWatcher()
    watcher.register("double", fn)
    fn(jnp.ones((2, 2)))
    # first entry is the expected initial compile, not a recompile
    assert watcher.poll(emit=False) == {"double": 0}
    fn(jnp.ones((2, 2)))
    assert watcher.poll(emit=False) == {}
    fn(jnp.ones((3, 3)))  # reshape -> cache miss
    assert watcher.poll(emit=False) == {"double": 1}
    assert watcher.recompile_total() >= 1


def test_step_telemetry_records_compile_execute_split(tmp_path):
    tel.enable(str(tmp_path))
    st = StepTelemetry(memory_every=1)
    fn = jax.jit(lambda x: jnp.sum(x * 2))
    st.register_compiled("fn", fn)
    for shape in ((4,), (4,), (5,)):
        with st.step():
            fn(jnp.ones(shape)).block_until_ready()
    tel.disable()
    records = [json.loads(l) for l in open(tmp_path / "events-rank0.jsonl")]
    steps = [r for r in records if r["kind"] == "step"]
    assert len(steps) == 3
    assert steps[0]["compile_s"] > 0  # first call compiles
    assert steps[1]["compiles"] == 0 and steps[1]["recompiles"] == 0
    assert steps[2]["recompiles"] == 1  # the reshape
    for s in steps:
        assert s["dur_s"] >= s["execute_s"] >= 0
    misses = [r for r in records if r["kind"] == "jit_cache_miss"]
    assert [m["first"] for m in misses] == [True, False]
    memory = [r for r in records if r["kind"] == "memory"]
    assert len(memory) == 3 and memory[0]["host_rss_bytes"] > 0


# ------------------------------------------------------------ comms counters --


def test_comm_counters_on_cpu_backend(tmp_path):
    tel.enable(str(tmp_path))
    ops.reset_comm_counters()
    ops.gather({"a": jnp.ones((4, 2), jnp.float32)})
    ops.reduce(np.ones((8,), np.float32), "mean")
    ops.broadcast(np.ones((2,), np.float32))
    ops.gather_object({"k": 1})
    ops.broadcast_object_list([1, 2, 3])
    counters = ops.get_comm_counters()
    tel.disable()
    assert counters["gather"]["calls"] == 1 and counters["gather"]["bytes"] == 4 * 2 * 4
    assert counters["reduce"]["bytes"] == 8 * 4
    assert counters["broadcast"]["bytes"] == 2 * 4
    assert counters["gather_object"]["bytes"] > 0
    assert counters["broadcast_object_list"]["bytes"] > 0
    records = [json.loads(l) for l in open(tmp_path / "events-rank0.jsonl")]
    comm = [r for r in records if r["kind"] == "comm"]
    assert sorted({c["op"] for c in comm}) == [
        "broadcast", "broadcast_object_list", "gather", "gather_object", "reduce",
    ]


def test_comm_counters_idle_when_disabled():
    ops.reset_comm_counters()
    ops.gather(jnp.ones((4,)))
    ops.reduce(np.ones((4,)), "sum")
    assert ops.get_comm_counters() == {}


# ------------------------------------------------------- dataloader hookup ---


def test_dataloader_emits_data_wait(tmp_path):
    tel.enable(str(tmp_path))
    acc = Accelerator()
    data = [{"x": np.ones((4,), np.float32)} for _ in range(64)]
    dl = acc.prepare(DataLoader(data, batch_size=8))
    for _ in dl:
        pass
    tel.disable()
    records = [json.loads(l) for l in open(tmp_path / "events-rank0.jsonl")]
    waits = [r for r in records if r["kind"] == "data_wait"]
    # async prefetch (the default): producer-side fetch/transfer are emitted
    # off the critical path, the consumer's queue-pop stall is the only
    # critical wait
    assert waits and {w["phase"] for w in waits} == {"fetch", "transfer", "stall"}
    assert all(not w["critical"] for w in waits if w["phase"] in ("fetch", "transfer"))
    assert all(w["critical"] for w in waits if w["phase"] == "stall")
    occupancy = [r for r in records if r["kind"] == "gauge" and r["name"] == "prefetch_queue"]
    assert occupancy and all(0 <= g["value"] <= g["capacity"] for g in occupancy)
    summary = [r for r in records if r["kind"] == "prefetch_summary"]
    assert len(summary) == 1 and summary[0]["batches"] == 1 and summary[0]["depth"] == 2
    reshard = [r for r in records if r["kind"] == "dataloader_reshard"]
    assert reshard and reshard[0]["decision"] == "native_sampler_sharded"
    assert reshard[0]["prefetch_depth"] == 2


def test_dataloader_sync_path_data_wait(tmp_path):
    """prefetch_depth=0: the synchronous path charges fetch + transfer to the
    critical path (pre-prefetch behavior)."""
    from accelerate_tpu.utils import DataLoaderConfiguration

    tel.enable(str(tmp_path))
    acc = Accelerator(dataloader_config=DataLoaderConfiguration(prefetch_depth=0))
    data = [{"x": np.ones((4,), np.float32)} for _ in range(64)]
    dl = acc.prepare(DataLoader(data, batch_size=8))
    for _ in dl:
        pass
    tel.disable()
    records = [json.loads(l) for l in open(tmp_path / "events-rank0.jsonl")]
    waits = [r for r in records if r["kind"] == "data_wait"]
    assert waits and {w["phase"] for w in waits} == {"fetch", "transfer"}
    assert all(w["critical"] for w in waits)
    assert not [r for r in records if r["kind"] == "prefetch_summary"]


def test_stateful_loader_under_dp_routes_to_dispatcher(tmp_path):
    import torch.utils.data as tud

    from accelerate_tpu.data_loader import DataLoaderDispatcher, prepare_data_loader
    from accelerate_tpu.state import AcceleratorState

    class _TorchStateful(tud.DataLoader):
        def state_dict(self):
            return {}

        def load_state_dict(self, state):
            pass

    tel.enable(str(tmp_path))
    state = AcceleratorState()  # default: dp over all 8 virtual devices
    loader = _TorchStateful(list(range(64)), batch_size=8)
    with pytest.warns(UserWarning, match="routing through DataLoaderDispatcher"):
        prepared = prepare_data_loader(loader, state=state)
    assert isinstance(prepared, DataLoaderDispatcher)
    # explicitly refusing the dispatcher is a hard error, not silent duplication
    with pytest.raises(ValueError, match="duplicate data"):
        prepare_data_loader(loader, state=state, dispatch_batches=False)
    tel.disable()
    records = [json.loads(l) for l in open(tmp_path / "events-rank0.jsonl")]
    decisions = [r["decision"] for r in records if r["kind"] == "dataloader_reshard"]
    assert "stateful_to_dispatcher" in decisions


def test_use_stateful_dataloader_raises_only_without_torchdata(monkeypatch, tmp_path):
    import torch.utils.data as tud

    from accelerate_tpu.utils.dataclasses import DataLoaderConfiguration

    acc = Accelerator(dataloader_config=DataLoaderConfiguration(use_stateful_dataloader=True))
    plain = tud.DataLoader(list(range(16)), batch_size=4)
    # torchdata absent in this container: the ImportError path
    if "torchdata" not in sys.modules:
        with pytest.raises(ImportError, match="torchdata"):
            acc.prepare_data_loader(plain)
    # with torchdata>=0.8.0 importable the loader is rebuilt, not rejected
    import types

    class _StatefulDataLoader(tud.DataLoader):
        def state_dict(self):
            return {"pos": 0}

        def load_state_dict(self, state):
            pass

    torchdata = types.ModuleType("torchdata")
    torchdata.__version__ = "0.11.0"
    sdl_mod = types.ModuleType("torchdata.stateful_dataloader")
    sdl_mod.StatefulDataLoader = _StatefulDataLoader
    torchdata.stateful_dataloader = sdl_mod
    monkeypatch.setitem(sys.modules, "torchdata", torchdata)
    monkeypatch.setitem(sys.modules, "torchdata.stateful_dataloader", sdl_mod)
    with pytest.warns(UserWarning):  # dp>1: rebuilt loader routes to dispatcher
        prepared = acc.prepare_data_loader(plain)
    assert isinstance(prepared.base_dataloader, _StatefulDataLoader)
    assert prepared.base_dataloader.dataset is plain.dataset
    # a too-old torchdata is the same as absent
    torchdata.__version__ = "0.7.1"
    with pytest.raises(ImportError, match="torchdata"):
        acc.prepare_data_loader(tud.DataLoader(list(range(8)), batch_size=4))


# ------------------------------------------------------------------- report --


def _run_training_with_telemetry(tmp_path, steps=5):
    tel.enable(str(tmp_path))
    acc = Accelerator()
    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    optimizer = optax.sgd(1e-2)
    n_samples = steps * 8 * acc.partial_state.num_devices
    data = [
        {"x": np.random.default_rng(i).standard_normal(4).astype(np.float32),
         "y": np.float32(1.0)}
        for i in range(n_samples)
    ]
    dl = DataLoader(data, batch_size=8)
    params, optimizer, dl = acc.prepare(params, optimizer, dl)

    def loss_fn(p, batch):
        pred = batch["x"] @ p["w"] + p["b"]
        return jnp.mean((jnp.sum(pred, -1) - batch["y"]) ** 2)

    step = acc.prepare_train_step(loss_fn, optimizer)
    opt_state = optimizer.opt_state
    for batch in dl:
        params, opt_state, metrics = step(params, opt_state, batch)
    # forced reshape -> the compiled step recompiles
    reshaped = {"x": jnp.ones((4, 4)), "y": jnp.ones((4,))}
    params, opt_state, metrics = step(params, opt_state, reshaped)
    ops.gather(metrics["loss"])  # comms traffic
    tel.get_event_log().flush()
    return acc


def test_training_loop_report_end_to_end(tmp_path):
    """The acceptance scenario: 5-step CPU loop -> JSONL -> report with
    step percentiles, >=1 detected recompile, and comms byte totals."""
    _run_training_with_telemetry(tmp_path)
    tel.disable()
    report = build_report([str(tmp_path)])
    assert report["steps"]["count"] >= 5
    assert report["steps"]["wall_s"]["p50"] > 0
    assert set(report["steps"]["wall_s"]) >= {"p50", "p90", "p99", "mean", "max"}
    assert report["recompiles"]["total"] >= 1
    assert any(n >= 1 for n in report["recompiles"]["by_fn"].values())
    assert report["comms"]["total_bytes"] > 0
    assert report["comms"]["by_op"]["gather"]["bytes"] > 0
    assert report["memory"]["live_array_peak_bytes"] > 0
    assert report["data_wait_events"] > 0
    text = format_report(report)
    assert "p50" in text and "recompile" in text and "comms" in text


def test_report_cli_main(tmp_path, capsys):
    tel.enable(str(tmp_path))
    with tel.span("warm"):
        pass
    tel.emit("step", dur_s=0.01, data_wait_s=0.001, compile_s=0.0, execute_s=0.009,
             compiles=0, recompiles=0)
    tel.disable()
    assert report_main(["report", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "telemetry report" in out and "p50" in out
    assert report_main(["report", str(tmp_path), "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["steps"]["count"] == 1


@pytest.mark.slow
def test_report_cli_subprocess(tmp_path):
    tel.enable(str(tmp_path))
    tel.emit("step", dur_s=0.5, data_wait_s=0.0, compile_s=0.1, execute_s=0.4,
             compiles=1, recompiles=0)
    tel.disable()
    res = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.telemetry", "report", str(tmp_path)],
        capture_output=True, text=True, cwd=REPO, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert "p50" in res.stdout


def test_report_tolerates_torn_and_foreign_lines(tmp_path):
    path = tmp_path / "events-rank0.jsonl"
    path.write_text(
        json.dumps({"kind": "meta", "schema": 1, "run_id": "r", "process_index": 0}) + "\n"
        + json.dumps({"kind": "step", "dur_s": 1.0}) + "\n"
        + "{\"kind\": \"step\", \"dur_s\":"  # torn tail from a killed run
    )
    report = build_report([str(tmp_path)])
    assert report["steps"]["count"] == 1


# ----------------------------------------------------------- tracker bridge --


def test_tracker_bridge_mirrors_summary(tmp_path):
    from accelerate_tpu.telemetry.tracker_bridge import mirror_to_trackers, summary_metrics

    tel.enable(str(tmp_path / "t"))
    tel.emit("step", dur_s=0.02, data_wait_s=0.0, compile_s=0.0, execute_s=0.02,
             compiles=0, recompiles=2)
    tel.emit("jit_cache_miss", fn="train_step#0", count=2, recompiles=2, first=False)
    tel.emit("comm", op="gather", bytes=1024)
    tel.get_event_log().flush()
    summary = summary_metrics()
    assert summary["telemetry/steps"] == 1
    assert summary["telemetry/recompiles"] == 2
    assert summary["telemetry/comm_bytes"] == 1024
    logged = {}

    class _Recorder:
        name = "rec"

        def log(self, values, step=None, **kwargs):
            logged.update(values)

    assert mirror_to_trackers([_Recorder()], summary=summary) == summary
    assert logged == summary
    tel.disable()
    # disabled + no dir: bridge degrades to a no-op
    assert summary_metrics() == {}


def test_accelerator_end_training_mirrors_into_trackers(tmp_path, monkeypatch):
    from accelerate_tpu.utils.dataclasses import ProjectConfiguration

    monkeypatch.setenv(tel_events.TELEMETRY_ENV_VAR, "1")
    monkeypatch.setenv(tel_events.TELEMETRY_DIR_ENV_VAR, str(tmp_path / "t"))
    acc = Accelerator(
        log_with="jsonl",
        project_config=ProjectConfiguration(project_dir=str(tmp_path), logging_dir=str(tmp_path)),
    )
    acc.init_trackers("proj")
    tel.emit("step", dur_s=0.01, data_wait_s=0.0, compile_s=0.0, execute_s=0.01,
             compiles=0, recompiles=0)
    acc.end_training()
    lines = [json.loads(l) for l in open(tmp_path / "proj.jsonl")]
    tele_lines = [l for l in lines if any(k.startswith("telemetry/") for k in l)]
    assert tele_lines and tele_lines[-1]["telemetry/steps"] == 1


# ------------------------------------------------------------------- memory --


def test_memory_monitor_watermarks():
    from accelerate_tpu.telemetry.memory import MemoryMonitor, live_array_bytes

    keep = jnp.ones((128, 128))  # noqa: F841 - held live on purpose
    monitor = MemoryMonitor()
    first = monitor.sample(emit=False)
    assert first["live_array_bytes"] >= 128 * 128 * 4
    assert first["host_rss_bytes"] > 0
    marks = monitor.watermarks()
    assert marks["live_array_peak_bytes"] >= first["live_array_bytes"] or marks[
        "live_array_peak_bytes"
    ] >= 128 * 128 * 4
    assert live_array_bytes() >= 128 * 128 * 4


# -------------------------------------------------------------- environment --


def test_local_world_size_follows_partial_state(monkeypatch):
    from accelerate_tpu.state import PartialState
    from accelerate_tpu.utils.environment import get_cpu_distributed_information

    monkeypatch.setenv("LOCAL_WORLD_SIZE", "8")
    PartialState._reset_state()
    # env-only (no live state): the env value is served as-is
    assert get_cpu_distributed_information()["local_world_size"] == 8
    PartialState()  # single process
    info = get_cpu_distributed_information()
    assert info["world_size"] == 1
    # a live single-process state overrides the stale env value
    assert info["local_world_size"] == 1


def test_partial_state_run_id(monkeypatch):
    from accelerate_tpu.state import PartialState

    monkeypatch.setenv("ACCELERATE_RUN_ID", "launcher-run-7")
    PartialState._reset_state()
    assert PartialState().run_id == "launcher-run-7"
    PartialState._reset_state()
    monkeypatch.delenv("ACCELERATE_RUN_ID")
    assert PartialState().run_id.startswith("run-")
