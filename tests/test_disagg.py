"""Disaggregated prefill/decode serving tests (ISSUE 16).

The acceptance lines these tests hold:

- **handoff integrity**: the content-addressed KV handoff (paged block
  content + the prefix-hash chain as the transfer unit) round-trips its
  wire form losslessly, and the verify step catches payload corruption,
  hash tampering and prompt mismatch — a damaged handoff is NEVER landed;
- **decode admission gating**: a decode engine admits a request only once
  its KV blocks have landed; a handoff that can never land (pool
  exhausted, nothing running) is dropped and the request falls back to a
  full re-prefill — correct either way, bitwise;
- **bitwise parity**: the two-tier path (prefill hop → handoff → decode
  hop) produces output identical to the monolithic engine for greedy AND
  sampled decoding, including preempt/resume under pool pressure,
  prefill/decode replica death mid-handoff, and corrupt-handoff re-runs —
  each request finishing EXACTLY once;
- **autoscaler hysteresis**: on a synthetic clock the policy scales up
  only while the ttft objective is violating, holds one pending join at a
  time, shrinks only after sustained idleness, and never flaps inside the
  cooldown window; pre-shipping pushes exactly the joiner's warmup
  lattice and nothing else.

Host-side policy logic runs against fakes (microseconds); the parity and
failover lines run against real thread-backed engines in tier-1 and real
subprocess replicas with real SIGKILL in the slow-marked e2e.
"""

import dataclasses
import json
import os
import time
from types import SimpleNamespace

import numpy as np
import pytest

from accelerate_tpu.generation import greedy_generate
from accelerate_tpu.models import LlamaConfig
from accelerate_tpu.resilience import chaos
from accelerate_tpu.resilience.chaos import ChaosSchedule, Fault
from accelerate_tpu.serving import (
    AutoscalerPolicy,
    BlockPoolExhausted,
    BucketLattice,
    DecodeEngine,
    DisaggRouter,
    KVHandoff,
    LocalReplica,
    PrefillEngine,
    ProcessReplica,
    ReplicaSpec,
    ReplicaState,
    RouterRequestStatus,
    ServingRouter,
    lattice_fns,
)
from accelerate_tpu.serving.disagg import corrupt_wire

CONFIG = LlamaConfig.tiny()


def _spec(**kw) -> ReplicaSpec:
    base = dict(
        model=dataclasses.asdict(CONFIG), num_blocks=33, block_size=8,
        max_slots=2, slot_buckets=(2,), block_buckets=(4,), prefill_buckets=(32,),
    )
    base.update(kw)
    return ReplicaSpec(**base)


def _params():
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.models import init_llama

    return jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16),
        init_llama(CONFIG, jax.random.PRNGKey(0)),
    )


def _lattice():
    return BucketLattice(
        slot_buckets=(2,), block_buckets=(4,), prefill_buckets=(32,)
    )


def _prompts(seed, lengths):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, CONFIG.vocab_size, (n,)).astype(np.int32)
            for n in lengths]


def _pack_one(params, prompt, max_new, rng_seed=0):
    """One request through a PrefillEngine; returns its handoff wire dict."""
    eng = PrefillEngine(params, CONFIG, num_blocks=33, block_size=8,
                        max_slots=2, lattice=_lattice())
    eng.warmup()
    req = eng.submit(prompt, max_new, rng_seed=rng_seed)
    eng.step()
    handoffs = eng.pop_handoffs()
    assert len(handoffs) == 1 and handoffs[0][0] is req
    assert eng.handoffs_packed == 1
    return handoffs[0][1]


# ---------------------------------------------------------------------------
# handoff integrity
# ---------------------------------------------------------------------------


def test_handoff_wire_roundtrip_and_verify():
    params = _params()
    (prompt,) = _prompts(0, [20])  # 2 full blocks + a 4-token tail
    wire = _pack_one(params, prompt, 4)
    ho, problems = KVHandoff.verify_wire(wire, prompt=prompt)
    assert problems == [] and ho is not None
    assert ho.n_blocks == 2 and len(ho.hashes) == 2
    assert ho.block_size == 8
    assert np.array_equal(ho.prompt, prompt)
    # the chain hashes are recomputable from the prompt alone — content
    # addressing, not positional bookkeeping
    re_wire = ho.to_wire()
    ho2, problems2 = KVHandoff.verify_wire(re_wire, prompt=prompt)
    assert problems2 == [] and ho2.crc == ho.crc

    # payload corruption: one flipped byte in the k content must be caught
    bad = corrupt_wire({**wire})
    _, problems = KVHandoff.verify_wire(bad, prompt=prompt)
    assert problems, "corrupted payload passed verification"

    # hash tampering: a forged chain hash must fail the prompt recompute
    forged = dict(wire)
    forged["hashes"] = ["00" * 16] + list(wire["hashes"][1:])
    _, problems = KVHandoff.verify_wire(forged, prompt=prompt)
    assert problems

    # prompt mismatch: a handoff delivered against the wrong request
    other = np.roll(prompt, 1)
    _, problems = KVHandoff.verify_wire(wire, prompt=other)
    assert problems


def test_handoff_empty_prompt_shorter_than_block():
    """Prompts under one block ship zero KV blocks — the handoff still
    carries tok0 and verifies; decode re-prefills the whole (tiny) prompt."""
    params = _params()
    (prompt,) = _prompts(1, [5])
    wire = _pack_one(params, prompt, 3)
    ho, problems = KVHandoff.verify_wire(wire, prompt=prompt)
    assert problems == [] and ho.n_blocks == 0
    # empty-payload corruption flips the crc instead
    bad = corrupt_wire(dict(wire))
    _, problems = KVHandoff.verify_wire(bad, prompt=prompt)
    assert problems


# ---------------------------------------------------------------------------
# decode admission gating
# ---------------------------------------------------------------------------


def test_decode_gates_until_handoff_lands_then_reuses_blocks():
    params = _params()
    (prompt,) = _prompts(2, [20])
    max_new = 6
    wire = _pack_one(params, prompt, max_new)
    dec = DecodeEngine(params, CONFIG, num_blocks=33, block_size=8,
                       max_slots=2, lattice=_lattice())
    dec.warmup()
    req = dec.submit(prompt, max_new, rng_seed=0,
                     generated=[int(wire["first_token"])], handoff=wire)
    # gated: the admission gate holds the request while its KV is in flight
    assert req.rid in dec._awaiting
    while not dec.scheduler.idle():
        dec.step()
    assert dec.handoffs_landed == 1 and dec.handoff_blocks == 2
    assert not dec._awaiting
    # the landed blocks were REUSED (prefix hit), not re-prefilled
    assert req.cached_tokens >= 8
    ref = greedy_generate(params, prompt[None], CONFIG, max_new_tokens=max_new)
    assert np.array_equal(np.asarray(ref[0]), req.output_ids())


def test_decode_drops_unlandable_handoff_and_reprefills():
    """A handoff that can never land (pool exhausted with nothing running)
    is dropped: the gate opens and the request full-re-prefills — slower,
    still bitwise-correct. The deadlock-escape path."""
    params = _params()
    (prompt,) = _prompts(3, [20])
    max_new = 5
    wire = _pack_one(params, prompt, max_new)
    dec = DecodeEngine(params, CONFIG, num_blocks=33, block_size=8,
                       max_slots=2, lattice=_lattice())
    dec.warmup()

    class _NeverLands:
        def pack(self, engine, req):  # pragma: no cover - decode side only
            raise AssertionError("decode engines do not pack")

        def deliver(self, handoff, engine):
            raise BlockPoolExhausted("no room, ever")

    dec.transport = _NeverLands()
    req = dec.submit(prompt, max_new, rng_seed=0,
                     generated=[int(wire["first_token"])], handoff=wire)
    while not dec.scheduler.idle():
        dec.step()
    assert dec.handoffs_landed == 0
    assert not dec._awaiting  # dropped, not wedged
    ref = greedy_generate(params, prompt[None], CONFIG, max_new_tokens=max_new)
    assert np.array_equal(np.asarray(ref[0]), req.output_ids())


def test_delivery_is_idempotent_per_hash():
    """Re-delivering the same handoff dedups on the content hash — the
    at-least-once transport retry cannot strand or duplicate blocks."""
    params = _params()
    (prompt,) = _prompts(4, [24])
    wire = _pack_one(params, prompt, 4)
    dec = DecodeEngine(params, CONFIG, num_blocks=33, block_size=8,
                       max_slots=2, lattice=_lattice())
    dec.warmup()
    ho, problems = KVHandoff.verify_wire(wire, prompt=prompt)
    assert problems == []
    first = dec.transport.deliver(ho, dec)
    again = dec.transport.deliver(ho, dec)
    assert first["landed"] == 3 and first["dedup"] == 0
    assert again["landed"] == 0 and again["dedup"] == 3


# ---------------------------------------------------------------------------
# bitwise parity vs the monolith (router level)
# ---------------------------------------------------------------------------


def _run_router(router, workload, *, seeds=None, timeout_s=300):
    router.wait_ready(timeout_s=timeout_s)
    reqs = [
        router.submit(prompt, max_new,
                      rng_seed=(seeds[i] if seeds else i))
        for i, (prompt, max_new) in enumerate(workload)
    ]
    router.run(timeout_s=timeout_s)
    return reqs


def _disagg_fleet(spec, n_prefill=1, n_decode=1, **kw):
    pspec = dataclasses.replace(spec, role="prefill")
    dspec = dataclasses.replace(spec, role="decode")
    return DisaggRouter(
        [LocalReplica(f"p{i}", pspec) for i in range(n_prefill)],
        [LocalReplica(f"d{i}", dspec) for i in range(n_decode)],
        **kw,
    )


def test_disagg_bitwise_parity_greedy():
    spec = _spec()
    prompts = _prompts(5, [4, 11, 20, 24, 9, 17])
    workload = [(p, 3 + (i % 5)) for i, p in enumerate(prompts)]
    router = _disagg_fleet(spec, n_prefill=1, n_decode=2)
    try:
        reqs = _run_router(router, workload)
        params = spec.build_params()
        for (prompt, max_new), req in zip(workload, reqs):
            assert req.status is RouterRequestStatus.FINISHED, req.error
            ref = greedy_generate(params, prompt[None], CONFIG,
                                  max_new_tokens=max_new)
            assert np.array_equal(np.asarray(ref[0]), req.output_ids())
        assert router.handoffs == len(workload)
        assert router.completed == len(workload)
    finally:
        router.close()


def test_disagg_bitwise_parity_sampled_vs_monolith():
    """Sampled decoding (temperature + top-k) through the two-tier path vs
    the SAME spec monolith: tok0 sampled at fold 0 on the prefill engine,
    every later token at its fold on the decode engine — identical streams,
    or the handoff broke the fold-index bookkeeping."""
    spec = _spec(temperature=0.8, top_k=4)
    prompts = _prompts(6, [6, 14, 22, 10])
    workload = [(p, 4 + i) for i, p in enumerate(prompts)]
    mono = ServingRouter([LocalReplica("m0", spec)])
    try:
        mono_reqs = _run_router(mono, workload)
    finally:
        mono.close()
    router = _disagg_fleet(spec, n_prefill=1, n_decode=1)
    try:
        reqs = _run_router(router, workload)
        for m, d in zip(mono_reqs, reqs):
            assert m.status is RouterRequestStatus.FINISHED
            assert d.status is RouterRequestStatus.FINISHED, d.error
            assert m.generated == d.generated
    finally:
        router.close()


def test_disagg_parity_under_pool_pressure_preempt_resume():
    """A tight decode pool forces preemption/resume mid-decode; the two-tier
    path must stay bitwise-identical to the SAME-spec monolith under the
    same pressure (the monolith is the reference the ISSUE names — under
    this much pool churn its preempt/resume schedule differs from the
    unconstrained single-stream decode, identically on both paths)."""
    spec = _spec(num_blocks=17)  # 16 usable blocks across 2 slots
    prompts = _prompts(7, [18, 22, 20, 16])
    workload = [(p, 10) for p in prompts]
    mono = ServingRouter([LocalReplica("m0", spec)])
    try:
        mono_reqs = _run_router(mono, workload)
    finally:
        mono.close()
    router = _disagg_fleet(spec, n_prefill=1, n_decode=1)
    try:
        reqs = _run_router(router, workload)
        for m, d in zip(mono_reqs, reqs):
            assert m.status is RouterRequestStatus.FINISHED
            assert d.status is RouterRequestStatus.FINISHED, d.error
            assert m.generated == d.generated
    finally:
        router.close()


def test_disagg_prefill_death_reruns_exactly_once():
    """A chaos crash at the kv_handoff point kills one prefill replica after
    prefilling but before its handoff ships — the router must wipe the
    sampled tok0 (fold 0 re-runs on the survivor) and finish every request
    exactly once, bitwise."""
    spec = _spec()
    prompts = _prompts(8, [9, 16, 21, 12, 24])
    workload = [(p, 6) for p in prompts]
    chaos.arm(ChaosSchedule(
        faults=[Fault(kind="crash", point="kv_handoff", step=1)]
    ))
    router = _disagg_fleet(spec, n_prefill=2, n_decode=1,
                           health_timeout_s=10.0)
    try:
        reqs = _run_router(router, workload)
        params = spec.build_params()
        for (prompt, max_new), req in zip(workload, reqs):
            assert req.status is RouterRequestStatus.FINISHED, req.error
            ref = greedy_generate(params, prompt[None], CONFIG,
                                  max_new_tokens=max_new)
            assert np.array_equal(np.asarray(ref[0]), req.output_ids())
        dead = [n for n, r in router.replicas.items()
                if r.state is ReplicaState.DEAD]
        assert len(dead) == 1 and dead[0].startswith("p")
        assert router.completed == len(workload)
    finally:
        router.close()
        chaos.arm(None)


def test_disagg_decode_death_fails_over_across_handoff():
    """Killing a decode replica mid-decode fails its requests over to the
    surviving decode replica with the streamed progress intact — the resume
    crosses the handoff boundary (the survivor re-prefills prompt +
    generated-so-far; the original handoff blocks are gone with the dead
    engine) and stays token-exact."""
    spec = _spec()
    prompts = _prompts(9, [8, 15, 19, 23, 11, 14])
    workload = [(p, 9) for p in prompts]
    router = _disagg_fleet(spec, n_prefill=1, n_decode=2,
                           health_timeout_s=10.0)
    try:
        router.wait_ready(timeout_s=300)
        reqs = [router.submit(p, m, rng_seed=i)
                for i, (p, m) in enumerate(workload)]
        t0 = time.monotonic()
        killed = False
        while not all(r.status.terminal for r in reqs):
            router.poll()
            if not killed and any(
                r.status is RouterRequestStatus.FINISHED for r in reqs
            ):
                router.replicas["d0"].kill()
                killed = True
            time.sleep(0.001)
            assert time.monotonic() - t0 < 300, "wedged"
        assert killed
        params = spec.build_params()
        for (prompt, max_new), req in zip(workload, reqs):
            assert req.status is RouterRequestStatus.FINISHED, req.error
            ref = greedy_generate(params, prompt[None], CONFIG,
                                  max_new_tokens=max_new)
            assert np.array_equal(np.asarray(ref[0]), req.output_ids())
        assert router.completed == len(workload)
    finally:
        router.close()


def test_disagg_corrupt_handoff_detected_and_rerun():
    """A chaos 'corrupt' fault damages one handoff in flight: the router's
    wire verify must catch it (never landing damaged KV), re-run the
    prefill, and still finish bitwise-exact."""
    spec = _spec()
    prompts = _prompts(10, [13, 18, 25, 10])
    workload = [(p, 5) for p in prompts]
    chaos.arm(ChaosSchedule(
        faults=[Fault(kind="corrupt", point="kv_handoff", step=1)]
    ))
    router = _disagg_fleet(spec, n_prefill=1, n_decode=1)
    try:
        reqs = _run_router(router, workload)
        params = spec.build_params()
        for (prompt, max_new), req in zip(workload, reqs):
            assert req.status is RouterRequestStatus.FINISHED, req.error
            ref = greedy_generate(params, prompt[None], CONFIG,
                                  max_new_tokens=max_new)
            assert np.array_equal(np.asarray(ref[0]), req.output_ids())
        assert router.handoff_corrupt >= 1
        assert router.completed == len(workload)
    finally:
        router.close()
        chaos.arm(None)


@pytest.mark.slow  # 4 subprocess replicas each paying jax import + warmup,
# plus a real SIGKILL on the prefill tier mid-load
def test_process_replica_disagg_sigkill_parity():
    spec = _spec()
    pspec = dataclasses.replace(spec, role="prefill")
    dspec = dataclasses.replace(spec, role="decode")
    prompts = _prompts(11, [9, 17, 22, 13, 20, 15])
    workload = [(p, 8) for p in prompts]
    router = DisaggRouter(
        [ProcessReplica(f"p{i}", pspec) for i in range(2)],
        [ProcessReplica(f"d{i}", dspec) for i in range(2)],
        health_timeout_s=30.0,
    )
    try:
        router.wait_ready(timeout_s=600)
        reqs = [router.submit(p, m, rng_seed=i)
                for i, (p, m) in enumerate(workload)]
        t0 = time.monotonic()
        killed = False
        while not all(r.status.terminal for r in reqs):
            router.poll()
            if not killed and router.handoffs >= 2:
                router.replicas["p0"].kill()  # real SIGKILL mid-handoff
                killed = True
            time.sleep(0.001)
            assert time.monotonic() - t0 < 600, "wedged"
        assert killed
        params = spec.build_params()
        for (prompt, max_new), req in zip(workload, reqs):
            assert req.status is RouterRequestStatus.FINISHED, req.error
            ref = greedy_generate(params, prompt[None], CONFIG,
                                  max_new_tokens=max_new)
            assert np.array_equal(np.asarray(ref[0]), req.output_ids())
        assert router.completed == len(workload)
    finally:
        router.close()


# ---------------------------------------------------------------------------
# autoscaler hysteresis (synthetic clock, fake router)
# ---------------------------------------------------------------------------


class _FakeReplica:
    def __init__(self, name, role="decode", state=ReplicaState.HEALTHY):
        self.name = name
        self.role = role
        self.state = state
        self.ready_info = {}
        self.stopped = False

    def stop(self):
        self.stopped = True


class _FakeRouter:
    def __init__(self, replicas):
        self.replicas = {r.name: r for r in replicas}
        self.last_slo_results = []
        self.admission = SimpleNamespace(depth=0)
        self._inflight = {}
        self.added = []
        self.drained = []

    def add_replica(self, rep):
        self.replicas[rep.name] = rep
        self.added.append(rep.name)

    def drain(self, name):
        self.replicas[name].state = ReplicaState.DRAINING
        self.drained.append(name)

    def _outstanding(self, name):
        return []


_BURN = {"slo": "ttft", "violating": True, "fast_burn": 20.0,
         "burn_threshold": 14.4}


def _policy(**kw):
    base = dict(
        spawn=lambda name, spec: _FakeReplica(name,
                                              state=ReplicaState.STARTING),
        min_decode=1, max_decode=3, cooldown_s=30.0, idle_shrink_after_s=10.0,
    )
    base.update(kw)
    return AutoscalerPolicy(_spec(), **base)


def test_autoscaler_grows_on_burn_once_then_cools_down():
    router = _FakeRouter([_FakeReplica("p0", role="prefill"),
                          _FakeReplica("d0")])
    pol = _policy()
    router.last_slo_results = [_BURN]
    assert pol.maybe_act(router, now=0.0) is True
    assert router.added == ["scale1"]
    assert pol.scale_ups == 1
    # still burning: the pending join vetoes a second spawn
    assert pol.maybe_act(router, now=1.0) is False
    assert router.added == ["scale1"]
    # the joiner warms up: join_ready books the warm join off ready_info
    joiner = router.replicas["scale1"]
    joiner.state = ReplicaState.HEALTHY
    joiner.ready_info = {"cache_hit": 6}
    assert pol.maybe_act(router, now=5.0) is True
    join = [e for e in pol.events if e["action"] == "join_ready"]
    assert len(join) == 1
    assert join[0]["warm"] is True and join[0]["join_compiles"] == 0
    assert join[0]["time_to_ready_s"] == 5.0
    # join resolved but the cooldown window still vetoes a second spawn
    assert pol.maybe_act(router, now=6.0) is False
    assert pol.maybe_act(router, now=31.0) is True  # cooldown over: grow again
    assert router.added == ["scale1", "scale2"]


def test_autoscaler_respects_max_decode():
    router = _FakeRouter([_FakeReplica("d0"), _FakeReplica("d1"),
                          _FakeReplica("d2")])
    pol = _policy(max_decode=3)
    router.last_slo_results = [_BURN]
    assert pol.maybe_act(router, now=0.0) is False
    assert pol.scale_ups == 0 and router.added == []


def test_autoscaler_shrinks_after_sustained_idle_no_flapping():
    router = _FakeRouter([_FakeReplica("p0", role="prefill"),
                          _FakeReplica("d0"), _FakeReplica("scale9")])
    pol = _policy()
    # idle but not yet sustained: nothing happens
    assert pol.maybe_act(router, now=0.0) is False
    assert pol.maybe_act(router, now=9.0) is False
    # a burst of activity resets the idle clock
    router._inflight = {1: object()}
    assert pol.maybe_act(router, now=9.5) is False
    router._inflight = {}
    assert pol.maybe_act(router, now=10.0) is False
    # sustained idle: retire the NEWEST joiner (name_prefix match), once
    assert pol.maybe_act(router, now=20.5) is True
    assert router.drained == ["scale9"]
    assert router.replicas["scale9"].stopped
    assert pol.scale_downs == 1
    # cooldown + min_decode: continued idleness cannot flap the fleet
    assert pol.maybe_act(router, now=25.0) is False
    assert pol.maybe_act(router, now=200.0) is False  # d0 is the floor
    assert pol.scale_downs == 1 and router.drained == ["scale9"]


def test_autoscaler_burn_beats_shrink_and_alternation_respects_cooldown():
    router = _FakeRouter([_FakeReplica("d0")])
    pol = _policy(idle_shrink_after_s=5.0)
    router.last_slo_results = [_BURN]
    assert pol.maybe_act(router, now=0.0) is True  # scale_up
    router.replicas["scale1"].state = ReplicaState.HEALTHY
    assert pol.maybe_act(router, now=1.0) is True  # join_ready
    # burn clears, idleness starts — but the cooldown window holds
    router.last_slo_results = []
    assert pol.maybe_act(router, now=2.0) is False
    assert pol.maybe_act(router, now=8.0) is False  # idle 6s > 5s, cooldown
    assert pol.maybe_act(router, now=31.0) is True  # cooldown over: shrink
    assert pol.scale_ups == 1 and pol.scale_downs == 1
    actions = [e["action"] for e in pol.events]
    assert actions == ["scale_up", "join_ready", "scale_down"]


def test_autoscaler_join_failure_releases_pending_slot():
    router = _FakeRouter([_FakeReplica("d0")])
    pol = _policy()
    router.last_slo_results = [_BURN]
    assert pol.maybe_act(router, now=0.0) is True
    router.replicas["scale1"].state = ReplicaState.DEAD
    assert pol.maybe_act(router, now=1.0) is True  # join_failed booked
    assert [e["action"] for e in pol.events][-1] == "join_failed"
    assert not pol.stats()["pending_joins"]
    # after cooldown the next burn may retry with a fresh joiner
    assert pol.maybe_act(router, now=31.0) is True
    assert router.added == ["scale1", "scale2"]


def test_autoscaler_validates_bounds():
    with pytest.raises(ValueError):
        _policy(min_decode=0)
    with pytest.raises(ValueError):
        _policy(min_decode=3, max_decode=2)


# ---------------------------------------------------------------------------
# compile-cache pre-shipping
# ---------------------------------------------------------------------------


def _fake_entry(cache_dir, name, fn, payload=b"x" * 64):
    d = os.path.join(cache_dir, name)
    os.makedirs(d)
    with open(os.path.join(d, "exec.bin"), "wb") as f:
        f.write(payload)
    with open(os.path.join(d, "MANIFEST.json"), "w") as f:
        json.dump({"fn": fn}, f)


def test_lattice_fns_is_the_warmup_set():
    spec = _spec()
    fns = lattice_fns(spec)
    lat = spec.lattice()
    assert fns == (
        {f"serving_prefill[{S}x{W}]" for S, W in lat.prefill_points()}
        | {f"serving_decode[{B}x{W}]" for B, W in lat.decode_points()}
        | {"serving_cow", "serving_land"}
    )
    # the default power-of-two lattice path (no pinned buckets) also resolves
    fns_default = lattice_fns(_spec(slot_buckets=None, block_buckets=None,
                                    prefill_buckets=None))
    assert {"serving_cow", "serving_land"} <= fns_default


def test_preship_ships_only_lattice_relevant_entries(tmp_path):
    from accelerate_tpu.compile_cache import preship

    spec = _spec()
    fns = sorted(lattice_fns(spec))
    src = tmp_path / "src"
    dst = tmp_path / "dst"
    os.makedirs(src)
    for i, fn in enumerate(fns):
        _fake_entry(str(src), f"rel{i}", fn)
    # irrelevant: another model's lattice point and a training fn
    _fake_entry(str(src), "other0", "serving_prefill[999x99]")
    _fake_entry(str(src), "other1", "train_step")
    out = preship(str(src), str(dst), fns=set(fns))
    assert out["shipped"] == len(fns)
    assert out["skipped"] == 2
    assert out["already"] == 0
    assert out["bytes"] > 0
    shipped = sorted(os.listdir(dst))
    assert shipped == [f"rel{i}" for i in range(len(fns))]
    # idempotent: a second push copies nothing
    again = preship(str(src), str(dst), fns=set(fns))
    assert again["shipped"] == 0 and again["already"] == len(fns)


def test_preship_default_prefix_filter(tmp_path):
    from accelerate_tpu.compile_cache import preship

    src = tmp_path / "src"
    dst = tmp_path / "dst"
    os.makedirs(src)
    _fake_entry(str(src), "a", "serving_prefill[16x2]")
    _fake_entry(str(src), "b", "serving_land")
    _fake_entry(str(src), "c", "train_step")
    out = preship(str(src), str(dst))
    assert out["shipped"] == 2 and out["skipped"] == 1
    assert sorted(os.listdir(dst)) == ["a", "b"]


def test_warm_join_end_to_end_zero_compiles(tmp_path):
    """The acceptance invariant wired through real engines: a decode joiner
    whose cache dir was pre-shipped from a warm source boots with ZERO
    compiles — every warmup point (prefill/decode lattice, COW, land) is a
    cache hit, visible in its ready event."""
    from accelerate_tpu.compile_cache import preship

    warm_dir = str(tmp_path / "warm")
    join_dir = str(tmp_path / "joiner")
    spec = _spec(role="decode", compile_cache_dir=warm_dir)
    # a founding decode replica warms the source cache
    founder = LocalReplica("d0", spec)
    router = ServingRouter([founder])
    try:
        router.wait_ready(timeout_s=300)
    finally:
        router.close()
    shipped = preship(warm_dir, join_dir, fns=lattice_fns(spec))
    assert shipped["shipped"] > 0
    joiner = LocalReplica(
        "scale1", dataclasses.replace(spec, compile_cache_dir=join_dir)
    )
    router2 = ServingRouter([joiner])
    try:
        router2.wait_ready(timeout_s=300)
        info = joiner.ready_info or {}
        compiles = sum(int(info.get(k, 0)) for k in
                       ("cache_miss", "cache_uncached", "cache_error"))
        assert compiles == 0, info
        assert int(info.get("cache_hit", 0)) > 0
    finally:
        router2.close()
