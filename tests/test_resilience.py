"""Elastic preemption-tolerant training (ISSUE 10): chaos schedules, cohort
membership, the restart supervisor, cross-topology checkpoint re-sharding,
and the subprocess e2e — SIGKILL mid-epoch, auto-resume, bitwise parity."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from accelerate_tpu.resilience import (
    ChaosFaultError,
    ChaosSchedule,
    CheckpointTopologyError,
    CohortSpec,
    Fault,
    MembershipError,
    RestartPolicy,
    Supervisor,
    check_topology,
    classify_exit,
    negotiate_membership,
    replan_data_assignment,
    topology_matches,
)
from accelerate_tpu.resilience import chaos as chaos_mod
from accelerate_tpu.resilience import membership as membership_mod
from accelerate_tpu.sharded_checkpoint import (
    read_saved_mesh,
    resize_padded_bucket,
    save_sharded_pytree,
    load_sharded_pytree,
)
from accelerate_tpu.telemetry.report import build_report, format_report

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _child_env(**extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("ACCELERATE_CHAOS_SCHEDULE", None)
    env.pop("ACCELERATE_RESTART_GENERATION", None)
    env.pop("ACCELERATE_RESUME_FROM_CHECKPOINT", None)
    env.pop("ACCELERATE_ELASTIC_RESUME", None)
    # children run on a single virtual device: batch math stays trivial
    env.pop("XLA_FLAGS", None)
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _toy_cmd(project_dir, steps=6, save_every=2, **flags):
    cmd = [
        sys.executable, "-m", "accelerate_tpu.resilience._toy_train",
        "--project-dir", str(project_dir), "--steps", str(steps),
        "--save-every", str(save_every), "--global-batch", "8",
    ]
    for k, v in flags.items():
        cmd += [f"--{k.replace('_', '-')}", str(v)]
    return cmd


# ---------------------------------------------------------------------------
# chaos schedules


@pytest.mark.smoke
def test_chaos_schedule_seeded_is_deterministic():
    a = ChaosSchedule.seeded(42, steps=20, n_faults=3)
    b = ChaosSchedule.seeded(42, steps=20, n_faults=3)
    c = ChaosSchedule.seeded(43, steps=20, n_faults=3)
    assert a.to_json() == b.to_json()
    assert a.to_json() != c.to_json()
    # round-trips through json and @file indirection
    assert ChaosSchedule.from_json(a.to_json()) == a


def test_chaos_schedule_file_indirection(tmp_path):
    sched = ChaosSchedule(faults=[Fault(kind="hang", step=3, duration_s=1.0)])
    path = tmp_path / "sched.json"
    path.write_text(sched.to_json())
    assert ChaosSchedule.from_json(f"@{path}") == sched


def test_fault_matching_filters():
    f = Fault(kind="sigkill", point="train_step", step=5, rank=1, generation=0)
    assert f.matches("train_step", 5, rank=1, generation=0)
    assert not f.matches("collective", 5, 1, 0)
    assert not f.matches("train_step", 4, 1, 0)
    assert not f.matches("train_step", 5, 0, 0)
    assert not f.matches("train_step", 5, 1, 1)  # generation-pinned
    anyf = Fault(kind="slow", point="any")
    assert anyf.matches("prefetch", None, 3, 7)
    with pytest.raises(ValueError):
        Fault(kind="meteor")
    with pytest.raises(ValueError):
        Fault(kind="hang", point="nowhere")


def test_maybe_inject_crash_fault_and_once_semantics():
    chaos_mod.arm(ChaosSchedule(faults=[Fault(kind="crash", point="any")]))
    try:
        with pytest.raises(ChaosFaultError):
            chaos_mod.maybe_inject("train_step", step=0)
        # once=True: the same fault does not re-fire
        chaos_mod.maybe_inject("train_step", step=1)
    finally:
        chaos_mod.arm(None)


def test_maybe_inject_slow_fault_repeats():
    chaos_mod.arm(ChaosSchedule(
        faults=[Fault(kind="slow", point="prefetch", duration_s=0.05, once=False)]
    ))
    try:
        t0 = time.monotonic()
        chaos_mod.maybe_inject("prefetch")
        chaos_mod.maybe_inject("prefetch")
        assert time.monotonic() - t0 >= 0.1  # fired both times
    finally:
        chaos_mod.arm(None)


def test_replan_data_assignment_straggler_and_exclusion():
    healthy = replan_data_assignment({0: 1.0, 1: 1.0, 2: 1.0})
    assert healthy["stragglers"] == [] and set(healthy["weights"].values()) == {1.0}
    skew = replan_data_assignment({0: 1.0, 1: 1.0, 2: 2.0, 3: 4.0}, slow_factor=1.5)
    assert skew["stragglers"] == [2, 3]
    assert skew["weights"][2] == 0.5 and skew["weights"][0] == 1.0
    assert skew["exclude"] == [3]  # 4x median > 2*slow_factor
    assert replan_data_assignment({}) == {"weights": {}, "stragglers": [], "exclude": []}


# ---------------------------------------------------------------------------
# membership


def test_negotiate_membership_shrinks_dp_replicate():
    spec = negotiate_membership([0, 2], 4, generation=1,
                                prev_axis_sizes={"dp_replicate": 4})
    assert spec.num_processes == 2 and spec.members == [0, 2]
    assert spec.dp_replicate_size == 2
    env = spec.to_env(new_rank=1)
    assert env["ACCELERATE_NUM_PROCESSES"] == "2"
    assert env["ACCELERATE_PROCESS_ID"] == "1"
    assert env["PARALLELISM_CONFIG_DP_REPLICATE_SIZE"] == "2"
    assert env["ACCELERATE_RESTART_GENERATION"] == "1"
    assert env["ACCELERATE_ELASTIC_RESUME"] == "1"
    assert env["ACCELERATE_RESUME_FROM_CHECKPOINT"] == "latest"


def test_negotiate_membership_rejects_bad_shrinks():
    with pytest.raises(MembershipError):  # 4*3/4 = 3: fine; 4*3 % 4 != 0 -> no
        negotiate_membership([0, 1, 2], 4, generation=1,
                             prev_axis_sizes={"dp_replicate": 2})
    with pytest.raises(MembershipError):  # model-parallel axes cannot absorb
        negotiate_membership([0], 2, generation=1, prev_axis_sizes={"tp": 2})
    with pytest.raises(MembershipError):
        negotiate_membership([], 2, generation=1)


def test_roster_handshake(tmp_path, monkeypatch):
    roster_dir = str(tmp_path / "cohort")
    monkeypatch.setenv("ACCELERATE_RESTART_GENERATION", "2")
    monkeypatch.setenv("ACCELERATE_PROCESS_ID", "3")
    membership_mod.announce_membership(roster_dir)
    roster = membership_mod.read_roster(roster_dir, 2)
    assert 3 in roster and roster[3]["generation"] == 2
    assert membership_mod.read_roster(roster_dir, 1) == {}  # namespaced by gen
    spec = CohortSpec(generation=2, num_processes=1, members=[3])
    membership_mod.publish_cohort_spec(roster_dir, spec)
    assert membership_mod.load_cohort_spec(roster_dir, 2) == spec
    assert membership_mod.load_cohort_spec(roster_dir, 9) is None


# ---------------------------------------------------------------------------
# supervisor mechanics (fast children — no jax import)


def test_classify_exit_reserved_codes():
    assert classify_exit(0) == ("clean", False)
    assert classify_exit(101) == ("stall_abort", True)  # reserved: stall abort
    assert classify_exit(-9) == ("killed", True)
    assert classify_exit(-15) == ("terminated", True)
    assert classify_exit(-11) == ("signal:11", True)
    assert classify_exit(3) == ("crash", True)


def test_restart_policy_backoff_bounded():
    p = RestartPolicy(backoff_base_s=1.0, backoff_factor=2.0, backoff_max_s=5.0)
    assert [p.backoff(i) for i in (1, 2, 3, 4, 5)] == [1.0, 2.0, 4.0, 5.0, 5.0]


def test_supervisor_clean_exit_needs_no_restart(tmp_path):
    sup = Supervisor([[sys.executable, "-c", "pass"]],
                     telemetry_dir=str(tmp_path),
                     policy=RestartPolicy(max_restarts=3, backoff_base_s=0.01))
    assert sup.run() == 0
    assert sup.restarts_used == 0 and sup.incidents == []


def test_supervisor_budget_exhaustion(tmp_path):
    """A child that always crashes burns the budget, then the supervisor gives
    up, propagates the exit code, and records the exhaustion."""
    sup = Supervisor([[sys.executable, "-c", "import sys; sys.exit(3)"]],
                     telemetry_dir=str(tmp_path),
                     policy=RestartPolicy(max_restarts=1, backoff_base_s=0.01,
                                          poison_threshold=0))
    rc = sup.run()
    assert rc == 3
    assert sup.restarts_used == 1
    assert [i.cause for i in sup.incidents] == ["crash", "crash"]
    events = [json.loads(l) for l in
              open(tmp_path / "events-supervisor.jsonl") if l.strip()]
    gave_up = [e for e in events if e.get("kind") == "restart" and e.get("gave_up")]
    assert gave_up and gave_up[0]["budget_exhausted"]


def test_supervisor_restarts_sigkilled_child(tmp_path):
    """SIGKILL (the preemption signature) in generation 0; generation 1 runs
    clean — the supervisor classifies, restarts once, and finishes 0."""
    marker = tmp_path / "DONE"
    child = (
        "import os, signal\n"
        "if os.environ['ACCELERATE_RESTART_GENERATION'] == '0':\n"
        "    os.kill(os.getpid(), signal.SIGKILL)\n"
        f"open({str(marker)!r}, 'w').write('ok')\n"
    )
    sup = Supervisor([[sys.executable, "-c", child]],
                     telemetry_dir=str(tmp_path),
                     policy=RestartPolicy(max_restarts=2, backoff_base_s=0.01))
    assert sup.run() == 0
    assert sup.restarts_used == 1
    assert sup.incidents[0].cause == "killed"
    assert marker.is_file()


def test_supervisor_poison_step_diagnosis(tmp_path, capsys):
    """Repeated crash at the SAME step is a deterministic bug, not a
    preemption: the supervisor must stop with a diagnosis instead of burning
    the whole budget re-dying."""
    child = (
        "import json, os, sys\n"
        f"d = {str(tmp_path)!r}\n"
        "json.dump({'kind': 'flight_record', 'step': 7, 'events': []},\n"
        "          open(os.path.join(d, 'flight-rank0.json'), 'w'))\n"
        "sys.exit(1)\n"
    )
    sup = Supervisor([[sys.executable, "-c", child]],
                     telemetry_dir=str(tmp_path),
                     policy=RestartPolicy(max_restarts=10, backoff_base_s=0.01,
                                          poison_threshold=2))
    rc = sup.run()
    assert rc == 1
    # stopped after threshold same-step crashes, NOT after 10 restarts
    assert sup.restarts_used == 1
    assert "poison step" in capsys.readouterr().err
    events = [json.loads(l) for l in
              open(tmp_path / "events-supervisor.jsonl") if l.strip()]
    poison = [e for e in events if e.get("cause") == "poison_step"]
    assert poison and poison[0]["step"] == 7 and poison[0]["gave_up"]


def test_supervisor_heartbeat_gap_detection(tmp_path):
    """A child that hangs without ever touching its heartbeat file trips the
    mtime watch — the hang class exit codes cannot report."""
    child = "import time\ntime.sleep(600)\n"
    sup = Supervisor([[sys.executable, "-c", child]],
                     telemetry_dir=str(tmp_path),
                     policy=RestartPolicy(max_restarts=0, backoff_base_s=0.01,
                                          heartbeat_timeout_s=0.5,
                                          grace_period_s=0.5))
    t0 = time.monotonic()
    rc = sup.run()
    assert rc == 1  # budget 0: the gap exhausts it immediately
    assert time.monotonic() - t0 < 30
    assert sup.incidents[0].cause == "heartbeat_gap"


def test_supervisor_resets_heartbeat_file_on_respawn(tmp_path):
    """A stale heartbeat mtime left by the dead generation must not re-trip
    the gap watch before the new child can arm its watchdog: the supervisor
    deletes the file at every spawn."""
    child = (
        "import os, sys, time\n"
        "hb = os.environ['ACCELERATE_HEARTBEAT_FILE']\n"
        "open(hb, 'w').write('beat')\n"
        "if os.environ['ACCELERATE_RESTART_GENERATION'] == '0':\n"
        "    time.sleep(600)\n"   # silent hang: the gap watch must end gen 0
        "for _ in range(10):\n"   # gen 1 beats healthily, outliving the
        "    open(hb, 'w').write('beat')\n"  # leftover gen-0 mtime age
        "    time.sleep(0.2)\n"
        "sys.exit(0)\n"
    )
    sup = Supervisor([[sys.executable, "-c", child]],
                     telemetry_dir=str(tmp_path),
                     policy=RestartPolicy(max_restarts=2, backoff_base_s=0.01,
                                          heartbeat_timeout_s=1.0,
                                          grace_period_s=0.5))
    assert sup.run() == 0
    assert sup.restarts_used == 1  # only the real gen-0 hang tripped
    assert [i.cause for i in sup.incidents] == ["heartbeat_gap"]


def test_heartbeat_watch_ignores_cleanly_exited_ranks(tmp_path):
    """A rank that finished (rc 0) stops touching its heartbeat file — that
    natural staleness must not tear down the still-healthy cohort."""
    fast = (
        "import os\n"
        "open(os.environ['ACCELERATE_HEARTBEAT_FILE'], 'w').write('beat')\n"
    )
    slow = (
        "import os, time\n"
        "hb = os.environ['ACCELERATE_HEARTBEAT_FILE']\n"
        "for _ in range(15):\n"
        "    open(hb, 'w').write('beat')\n"
        "    time.sleep(0.2)\n"
    )
    sup = Supervisor(
        [[sys.executable, "-c", fast], [sys.executable, "-c", slow]],
        telemetry_dir=str(tmp_path),
        policy=RestartPolicy(max_restarts=0, backoff_base_s=0.01,
                             heartbeat_timeout_s=1.0, grace_period_s=0.5),
    )
    assert sup.run() == 0  # no spurious heartbeat_gap from the finished rank
    assert sup.incidents == []


def test_single_child_supervision_preserves_launcher_world_size(tmp_path):
    """Supervising ONE child (which may be a rank of a multi-host job) must
    not clobber the launcher's ACCELERATE_NUM_PROCESSES/PROCESS_ID."""
    out = tmp_path / "env.json"
    child = (
        "import json, os\n"
        f"json.dump({{k: os.environ.get(k) for k in ('ACCELERATE_NUM_PROCESSES',"
        f" 'ACCELERATE_PROCESS_ID', 'ACCELERATE_RESTART_GENERATION')}},"
        f" open({str(out)!r}, 'w'))\n"
    )
    env = dict(os.environ, ACCELERATE_NUM_PROCESSES="4", ACCELERATE_PROCESS_ID="2")
    sup = Supervisor([[sys.executable, "-c", child]], env=env,
                     telemetry_dir=str(tmp_path),
                     policy=RestartPolicy(max_restarts=0, backoff_base_s=0.01))
    assert sup.run() == 0
    seen = json.loads(out.read_text())
    assert seen["ACCELERATE_NUM_PROCESSES"] == "4"
    assert seen["ACCELERATE_PROCESS_ID"] == "2"
    assert seen["ACCELERATE_RESTART_GENERATION"] == "0"


def test_launch_elastic_honors_explicit_zero_restarts(tmp_path):
    """`--elastic --max_restarts 0` means supervise-but-never-restart; the
    elastic default of 3 applies only when the flag is absent."""
    import accelerate_tpu.commands.launch as L

    captured = {}

    def fake_supervise(cmd, env=None, policy=None, telemetry_dir=None,
                       axis_sizes=None):
        captured["policy"] = policy
        return 0

    parser = L.launch_command_parser()
    real = L.__dict__.get("elastic_launcher")
    import accelerate_tpu.resilience.supervisor as S
    orig = S.__dict__["supervise_command"]
    try:
        S.supervise_command = fake_supervise
        args = parser.parse_args(["--cpu", "--elastic", "--max_restarts", "0", "x.py"])
        assert L.launch_command(args) == 0
        assert captured["policy"].max_restarts == 0
        args = parser.parse_args(["--cpu", "--elastic", "x.py"])
        assert L.launch_command(args) == 0
        assert captured["policy"].max_restarts == 3
    finally:
        S.supervise_command = orig
    assert real is not None  # sanity: the launcher exists


def test_restarts_section_renders_for_reshard_only_runs(tmp_path):
    """A manual elastic reshard (no supervisor) must still show up in the
    formatted report."""
    with open(tmp_path / "events-rank0.jsonl", "w") as f:
        f.write(json.dumps({"kind": "meta", "schema": 1, "run_id": "r",
                            "process_index": 0}) + "\n")
        f.write(json.dumps({"kind": "elastic", "phase": "reshard",
                            "saved_mesh": {"dp_replicate": 4},
                            "current_mesh": {"dp_replicate": 2}}) + "\n")
    text = format_report(build_report([str(tmp_path)]))
    assert "elastic reshard" in text


def test_watchdog_touches_heartbeat_file(tmp_path, monkeypatch):
    from accelerate_tpu.telemetry.watchdog import Watchdog

    hb = tmp_path / "heartbeat-rank0"
    monkeypatch.setenv("ACCELERATE_HEARTBEAT_FILE", str(hb))
    wd = Watchdog(timeout=30.0, interval=0.05, out_dir=str(tmp_path)).start()
    try:
        assert hb.is_file()  # created at start
        first = hb.stat().st_mtime
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and hb.stat().st_mtime == first:
            time.sleep(0.05)
        assert hb.stat().st_mtime > first  # ticked
    finally:
        wd.stop()


# ---------------------------------------------------------------------------
# cross-topology re-sharding


def test_resize_padded_bucket_semantics():
    v = np.array([1.0, 2.0, 3.0, 0.0], np.float32)  # fill=3, padded to 4
    grown = resize_padded_bucket(v, 6)
    np.testing.assert_array_equal(grown, [1, 2, 3, 0, 0, 0])
    shrunk = resize_padded_bucket(grown, 3)
    np.testing.assert_array_equal(shrunk, [1, 2, 3])
    assert resize_padded_bucket(v, 4) is v  # no-op passthrough
    with pytest.raises(ValueError, match="nonzero"):
        resize_padded_bucket(v, 2)  # would drop real data


def test_topology_matching_and_guard():
    assert topology_matches({"dp_replicate": 4, "tp": 1}, {"dp_replicate": 4})
    assert topology_matches(None, {"dp_replicate": 4})  # legacy: unknown passes
    assert not topology_matches({"dp_replicate": 4}, {"dp_replicate": 2})
    # same topology -> no resharding
    assert check_topology({"dp_replicate": 4}, {"dp_replicate": 4}) is False
    # pure refactorization (global shapes invariant) passes WITHOUT elastic —
    # the coordinate loader has always handled fsdp=8 -> fsdp=4xtp=2
    assert check_topology({"dp_shard": 8}, {"dp_shard": 4, "tp": 2}) is False
    # a dp_replicate width change is shape-affecting (ZeRO-1 bucket padding):
    # blocked without elastic, re-pad with
    with pytest.raises(CheckpointTopologyError) as err:
        check_topology({"dp_replicate": 4}, {"dp_replicate": 2})
    assert "dp_replicate=4" in str(err.value) and "dp_replicate=2" in str(err.value)
    assert check_topology({"dp_replicate": 4}, {"dp_replicate": 2}, elastic=True)
    # dp change composed with other axis changes still goes through elastically
    assert check_topology({"dp_replicate": 2, "tp": 2}, {"dp_replicate": 4},
                          elastic=True)


def _fused_zero1_setup(n_dev, params_host, bucket_bytes=1 << 20):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from accelerate_tpu.parallel.weight_update import (
        build_bucket_plan,
        init_bucketed_opt_state,
        make_fused_zero1_update,
    )

    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("dp_replicate",))
    repl = NamedSharding(mesh, P())
    params = jax.device_put(params_host, repl)
    plan = build_bucket_plan(params, "dp_replicate", n_dev, bucket_bytes)
    tx = optax.adam(1e-2)
    state, specs = init_bucketed_opt_state(tx, params, plan, mesh)
    fused = make_fused_zero1_update(tx, plan, mesh, specs)

    def loss_fn(p, b):
        return jnp.mean((b @ p["w"]) ** 2)

    def step(p, st, b):
        loss, grads = jax.value_and_grad(loss_fn)(p, b)
        new_p, new_st = fused(grads, st, p)
        return new_p, new_st, loss

    batch = jax.device_put(jnp.ones((4, 19), jnp.float32), repl)
    return mesh, params, state, jax.jit(step), batch


def test_fused_zero1_dp4_to_dp2_reshard_parity(tmp_path):
    """The in-process re-shard core: a fused-ZeRO-1 state saved at dp=4
    (buckets padded to 1048) restores at dp=2 (padded to 1046) via the
    elastic loader, and continued training matches the dp=4 continuation
    bitwise. 1045 elements were chosen so the paddings actually differ."""
    params_host = {"w": np.linspace(-1, 1, 19 * 55, dtype=np.float32).reshape(19, 55)}
    mesh4, p4, s4, step4, batch4 = _fused_zero1_setup(4, params_host)
    for _ in range(2):
        p4, s4, _ = step4(p4, s4, batch4)
    d = str(tmp_path / "ck")
    save_sharded_pytree(s4, d, prefix="optimizer")
    save_sharded_pytree(p4, d, prefix="model")
    assert read_saved_mesh(d, "optimizer") == {"dp_replicate": 4}
    saved_mu = np.asarray(jax.device_get(s4[0].mu["b000"]))
    assert saved_mu.shape == (1048,)

    mesh2, p2_init, s2_template, step2, batch2 = _fused_zero1_setup(2, params_host)
    assert s2_template[0].mu["b000"].shape == (1046,)
    # non-elastic load refuses the shape change
    with pytest.raises(ValueError, match="shape mismatch"):
        load_sharded_pytree(s2_template, d, prefix="optimizer")
    s2 = load_sharded_pytree(s2_template, d, prefix="optimizer", elastic=True)
    p2 = load_sharded_pytree(p2_init, d, prefix="model", elastic=True)
    loaded_mu = np.asarray(jax.device_get(s2[0].mu["b000"]))
    np.testing.assert_array_equal(loaded_mu[:1045], saved_mu[:1045])
    assert not loaded_mu[1045:].any()  # re-pad, not data

    # continue one step on each topology: identical math, bitwise params
    p4b, _, _ = step4(p4, s4, batch4)
    p2b, _, _ = step2(p2, s2, batch2)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(p4b["w"])), np.asarray(jax.device_get(p2b["w"]))
    )


def test_load_state_topology_error_names_both_shapes(tmp_path):
    """Accelerator.load_state onto a different mesh factorization fails with
    CheckpointTopologyError up front — not a deep jax shape error — and the
    elastic path refuses model-parallel changes too."""
    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.state import AcceleratorState
    from accelerate_tpu.utils.dataclasses import ProjectConfiguration

    acc = Accelerator(
        project_config=ProjectConfiguration(project_dir=str(tmp_path),
                                            automatic_checkpoint_naming=True),
        parallelism_config=ParallelismConfig(dp_replicate_size=8),
    )
    params = {"w": np.ones((32, 8), np.float32)}
    out = acc.save_state(params=params)
    manifest = json.load(open(os.path.join(out, "_COMMITTED")))
    assert manifest["mesh"]["dp_replicate"] == 8
    acc.end_training()

    AcceleratorState._reset_state()
    acc2 = Accelerator(
        project_config=ProjectConfiguration(project_dir=str(tmp_path),
                                            automatic_checkpoint_naming=True),
        parallelism_config=ParallelismConfig(dp_shard_size=8),
    )
    with pytest.raises(CheckpointTopologyError) as err:
        acc2.load_state(out, params=params)
    assert "dp_replicate=8" in str(err.value) and "dp_shard=8" in str(err.value)
    # elastic: params have topology-invariant global shapes — loads fine onto
    # the refactorized mesh
    restored = acc2.load_state(out, params=params, elastic=True)
    np.testing.assert_array_equal(np.asarray(restored["w"]), params["w"])
    acc2.end_training()


# ---------------------------------------------------------------------------
# restarts telemetry -> report


def test_restarts_report_section(tmp_path):
    with open(tmp_path / "events-supervisor.jsonl", "w") as f:
        f.write(json.dumps({"kind": "meta", "schema": 1, "run_id": "r",
                            "role": "supervisor"}) + "\n")
        f.write(json.dumps({"kind": "elastic", "phase": "start",
                            "processes": 2}) + "\n")
        f.write(json.dumps({"kind": "restart", "generation": 1, "attempt": 1,
                            "cause": "killed", "exit_code": -9, "step": 4,
                            "dump": "flight-rank0.json",
                            "downtime_s": 2.5}) + "\n")
        f.write(json.dumps({"kind": "restart", "generation": 2, "attempt": 2,
                            "cause": "stall_abort", "exit_code": 101,
                            "downtime_s": 1.5}) + "\n")
        f.write(json.dumps({"kind": "elastic", "phase": "reshard",
                            "saved_mesh": {"dp_replicate": 4},
                            "current_mesh": {"dp_replicate": 2}}) + "\n")
        f.write(json.dumps({"kind": "elastic", "phase": "done",
                            "generation": 2, "restarts": 2}) + "\n")
    rep = build_report([str(tmp_path)])
    rs = rep["restarts"]
    assert rs["count"] == 2 and rs["generations"] == 2
    assert rs["downtime_s"] == 4.0
    assert rs["causes"] == {"killed": 1, "stall_abort": 1}
    assert rs["completed"] and rs["dumps"] == ["flight-rank0.json"]
    assert rs["reshards"][0]["saved_mesh"] == {"dp_replicate": 4}
    text = format_report(rep)
    assert "restarts: 2 restart(s) over 3 generation(s)" in text
    assert "cause killed: 1" in text and "elastic reshard" in text


# ---------------------------------------------------------------------------
# subprocess e2e: the acceptance scenario


@pytest.mark.slow  # full launch-CLI chaos acceptance: ~6 sequential child
# processes (reference run + 3 supervised generations), each paying a jax
# import — minutes on a loaded box; tier-1's 870s window can't afford it
# (pre-PR-11 HEAD measured rc=124 here). `make chaos` and doctor check 11
# keep the fast auto-resume signal in the timed lane.
def test_e2e_sigkill_and_hang_autoresume_bitwise_parity(tmp_path):
    """The headline acceptance e2e: under a seeded SIGKILL + hang fault
    schedule, `accelerate-tpu launch --elastic` finishes training with final
    params BITWISE-identical to the fault-free run. Generation 0 is
    preempted (SIGKILL) mid-epoch; generation 1 wedges in a chaos hang the
    watchdog turns into a 101 stall-abort; generation 2 runs clean — every
    resume comes off the last committed checkpoint, and the restart
    telemetry attributes both causes."""
    ref_dir = tmp_path / "ref"
    chaos_dir = tmp_path / "chaos"
    tel_dir = chaos_dir / "telemetry"
    for d in (ref_dir, chaos_dir, tel_dir):
        d.mkdir(parents=True)

    # the reference must see the same 8-virtual-device topology `launch --cpu`
    # gives the supervised run: reduction order is part of bitwise parity
    ref = subprocess.run(
        _toy_cmd(ref_dir),
        env=_child_env(XLA_FLAGS="--xla_force_host_platform_device_count=8",
                       ACCELERATE_USE_CPU="true"),
        capture_output=True, text=True, timeout=300,
    )
    assert ref.returncode == 0, ref.stderr[-2000:]

    schedule = ChaosSchedule(
        faults=[
            Fault(kind="sigkill", point="train_step", step=3, generation=0),
            Fault(kind="hang", point="train_step", step=1, generation=1,
                  duration_s=None),  # forever: only the watchdog ends it
        ],
        seed=7,
    )
    env = _child_env(
        ACCELERATE_CHAOS_SCHEDULE=schedule.to_json(),
        ACCELERATE_TELEMETRY_DIR=str(tel_dir),
        ACCELERATE_WATCHDOG_TIMEOUT="2",  # launch --elastic defaults ABORT=1
    )
    r = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.launch",
         "--cpu", "--elastic", "--max_restarts", "3",
         "--monitor_interval", "0.1", "-m",
         "accelerate_tpu.resilience._toy_train",
         "--project-dir", str(chaos_dir), "--steps", "6",
         "--save-every", "2", "--global-batch", "8"],
        env=env, capture_output=True, text=True, timeout=420,
    )
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-3000:])
    # the committed checkpoint the first resume came from predates the kill
    assert (chaos_dir / "checkpoints" / "checkpoint_0" / "_COMMITTED").is_file()

    ref_params = dict(np.load(ref_dir / "final_params.npz"))
    chaos_params = dict(np.load(chaos_dir / "final_params.npz"))
    assert set(ref_params) == set(chaos_params)
    for k in ref_params:
        np.testing.assert_array_equal(ref_params[k], chaos_params[k])

    rep = build_report([str(tel_dir)])
    rs = rep["restarts"]
    assert rs["count"] == 2 and rs["completed"] and rs["generations"] == 2
    assert rs["causes"] == {"killed": 1, "stall_abort": 1}
    assert rs["dumps"]  # the stall abort dumped a flight record and linked it
    text = format_report(rep)
    assert "restarts: 2 restart(s) over 3 generation(s)" in text


@pytest.mark.slow
def test_e2e_dp4_to_dp2_elastic_resume_full_stack(tmp_path):
    """Full-stack cross-topology resume: train+checkpoint at dp=4 (fused
    ZeRO-1), resume the same project dir on a dp=2 device set with the elastic
    env the supervisor injects, and match an uninterrupted dp=2 run bitwise
    (loss-curve continuity at full precision)."""
    a_dir, ref_dir = tmp_path / "a", tmp_path / "ref"
    a_dir.mkdir(), ref_dir.mkdir()

    def run(project_dir, n_dev, **extra_env):
        env = _child_env(
            XLA_FLAGS=f"--xla_force_host_platform_device_count={n_dev}",
            **extra_env,
        )
        return subprocess.run(
            _toy_cmd(project_dir, steps=6, save_every=2, zero_stage=1),
            env=env, capture_output=True, text=True, timeout=300,
        )

    r = run(a_dir, 4)
    assert r.returncode == 0, r.stderr[-2000:]
    # pretend the run died after checkpoint_0 committed (mid-epoch)
    for stale in ("checkpoint_1", "checkpoint_2"):
        p = a_dir / "checkpoints" / stale
        if p.is_dir():
            import shutil

            shutil.rmtree(p)
    (a_dir / "final_params.npz").unlink()

    r = run(a_dir, 2, ACCELERATE_RESUME_FROM_CHECKPOINT="latest",
            ACCELERATE_ELASTIC_RESUME="1", ACCELERATE_RESTART_GENERATION="1")
    assert r.returncode == 0, r.stderr[-2000:]
    resumed = json.loads(r.stdout.strip().splitlines()[-1])
    assert resumed["resumed_from_iteration"] == 0

    r = run(ref_dir, 2)
    assert r.returncode == 0, r.stderr[-2000:]

    a = dict(np.load(a_dir / "final_params.npz"))
    ref = dict(np.load(ref_dir / "final_params.npz"))
    for k in ref:
        np.testing.assert_array_equal(a[k], ref[k])


@pytest.mark.slow  # launch-CLI e2e: two child generations through the real
# `accelerate-tpu launch --elastic` entry point; the supervisor unit tests
# above cover the same restart path in-process for the timed lane
def test_launch_elastic_flag_supervises(tmp_path):
    """`accelerate-tpu launch --elastic` routes through the supervisor: a
    script that SIGKILLs itself in generation 0 and succeeds in generation 1
    must leave rc 0 and a restart record."""
    script = tmp_path / "train.py"
    marker = tmp_path / "DONE"
    script.write_text(
        "import os, signal\n"
        "if os.environ.get('ACCELERATE_RESTART_GENERATION', '0') == '0':\n"
        "    os.kill(os.getpid(), signal.SIGKILL)\n"
        f"open({str(marker)!r}, 'w').write('ok')\n"
    )
    tel_dir = tmp_path / "telemetry"
    env = _child_env(ACCELERATE_TELEMETRY_DIR=str(tel_dir))
    r = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.launch",
         "--cpu", "--elastic", "--max_restarts", "2", "--monitor_interval", "0.1",
         str(script)],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, (r.stdout, r.stderr[-2000:])
    assert marker.is_file()
    events = [json.loads(l) for l in
              open(tel_dir / "events-supervisor.jsonl") if l.strip()]
    restarts = [e for e in events if e["kind"] == "restart"]
    assert len(restarts) == 1 and restarts[0]["cause"] == "killed"
