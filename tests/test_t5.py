"""T5 encoder-decoder family: HF weight-conversion logit parity, seq2seq
training, TP/FSDP sharded step, greedy generation (reference acceptance
surface: T0pp/T5 in the big-model-inference table,
``benchmarks/big_model_inference/README.md:27-37``)."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from accelerate_tpu.models import (
    T5Config,
    init_t5,
    t5_forward,
    t5_greedy_generate,
    t5_loss,
    t5_shard_rules,
)


def _hf_t5(seed=0):
    torch = pytest.importorskip("torch")
    from transformers import T5Config as HFConfig, T5ForConditionalGeneration

    torch.manual_seed(seed)
    hf_cfg = HFConfig(
        vocab_size=128, d_model=32, d_kv=8, d_ff=64, num_layers=2, num_heads=4,
        relative_attention_num_buckets=8, relative_attention_max_distance=32,
        dropout_rate=0.0, tie_word_embeddings=True, feed_forward_proj="relu",
        decoder_start_token_id=0, eos_token_id=1, pad_token_id=0,
    )
    model = T5ForConditionalGeneration(hf_cfg).eval()
    cfg = T5Config(
        vocab_size=128, dim=32, head_dim=8, ffn_dim=64, n_layers=2, n_heads=4,
        rel_pos_buckets=8, rel_pos_max_distance=32, tie_word_embeddings=True,
    )
    return model, cfg


def _convert_hf_weights(model, cfg: T5Config) -> dict:
    from accelerate_tpu.models import t5_params_from_hf

    return t5_params_from_hf(model, cfg)


class TestHFParity:
    def test_logits_match_hf(self):
        torch = pytest.importorskip("torch")
        model, cfg = _hf_t5()
        params = _convert_hf_weights(model, cfg)
        rng = np.random.default_rng(0)
        enc_ids = rng.integers(2, 128, (2, 9)).astype(np.int32)
        dec_ids = rng.integers(2, 128, (2, 5)).astype(np.int32)
        dec_ids[:, 0] = 0
        ours = t5_forward(
            params,
            {"input_ids": jnp.asarray(enc_ids), "decoder_input_ids": jnp.asarray(dec_ids)},
            cfg,
        )
        with torch.no_grad():
            ref = model(
                input_ids=torch.from_numpy(enc_ids.astype(np.int64)),
                decoder_input_ids=torch.from_numpy(dec_ids.astype(np.int64)),
            ).logits.numpy()
        np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-5)

    def test_logits_match_hf_with_padding_mask(self):
        torch = pytest.importorskip("torch")
        model, cfg = _hf_t5(seed=1)
        params = _convert_hf_weights(model, cfg)
        rng = np.random.default_rng(1)
        enc_ids = rng.integers(2, 128, (2, 8)).astype(np.int32)
        mask = np.ones((2, 8), np.int32)
        mask[0, 5:] = 0
        enc_ids[0, 5:] = 0
        dec_ids = np.zeros((2, 4), np.int32)
        ours = t5_forward(
            params,
            {"input_ids": jnp.asarray(enc_ids), "decoder_input_ids": jnp.asarray(dec_ids),
             "attention_mask": jnp.asarray(mask)},
            cfg,
        )
        with torch.no_grad():
            ref = model(
                input_ids=torch.from_numpy(enc_ids.astype(np.int64)),
                attention_mask=torch.from_numpy(mask.astype(np.int64)),
                decoder_input_ids=torch.from_numpy(dec_ids.astype(np.int64)),
            ).logits.numpy()
        np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-5)

    def test_greedy_generate_matches_hf(self):
        torch = pytest.importorskip("torch")
        model, cfg = _hf_t5(seed=2)
        params = _convert_hf_weights(model, cfg)
        rng = np.random.default_rng(2)
        enc_ids = rng.integers(2, 128, (2, 7)).astype(np.int32)
        ours = t5_greedy_generate(
            params, enc_ids, cfg, max_new_tokens=6,
            decoder_start_token_id=0, eos_token_id=1,
        )
        ref = model.generate(
            torch.from_numpy(enc_ids.astype(np.int64)), max_new_tokens=6,
            do_sample=False, num_beams=1,
        ).numpy()
        width = min(ours.shape[1], ref.shape[1])
        np.testing.assert_array_equal(np.asarray(ours)[:, :width], ref[:, :width])


class TestTraining:
    def _copy_task(self, n, se, st, vocab, seed=0):
        """Learnable seq2seq task: target = first (st-1) source tokens."""
        rng = np.random.default_rng(seed)
        src = rng.integers(2, vocab, (n, se)).astype(np.int32)
        tgt = src[:, : st - 1]
        dec_in = np.concatenate([np.zeros((n, 1), np.int32), tgt[:, :-1]], axis=1)
        labels = tgt.astype(np.int32)
        return {"input_ids": src, "decoder_input_ids": dec_in, "labels": labels}

    def test_overfits_copy_task(self):
        cfg = T5Config.tiny()
        params = init_t5(cfg, jax.random.PRNGKey(0))
        batch = {k: jnp.asarray(v) for k, v in self._copy_task(16, 10, 6, cfg.vocab_size).items()}
        opt = optax.adam(3e-3)
        state = opt.init(params)

        @jax.jit
        def step(p, s):
            l, g = jax.value_and_grad(lambda p: t5_loss(p, batch, cfg))(p)
            u, s = opt.update(g, s, p)
            return optax.apply_updates(p, u), s, l

        first = None
        for i in range(60):
            params, state, loss = step(params, state)
            if first is None:
                first = float(loss)
        assert float(loss) < first * 0.25, (first, float(loss))

    def test_sharded_train_step(self):
        from accelerate_tpu import Accelerator, ParallelismConfig

        from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

        AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
        pc = ParallelismConfig(dp_shard_size=4, tp_size=2)
        acc = Accelerator(parallelism_config=pc, rng_seed=0)
        cfg = T5Config.tiny()
        params = init_t5(cfg, jax.random.PRNGKey(0))
        params, opt = acc.prepare(params, optax.adam(1e-3), shard_rules=t5_shard_rules())
        step = acc.prepare_train_step(lambda p, b: t5_loss(p, b, cfg), opt)
        batch = {k: jnp.asarray(v) for k, v in self._copy_task(8, 10, 6, cfg.vocab_size).items()}
        s = opt.opt_state
        p2, s, m1 = step(params, s, batch)
        p2, s, m2 = step(p2, s, batch)
        assert float(m2["loss"]) < float(m1["loss"])
        # TP rule applied to the stacked attention kernels (out-dim over tp),
        # composed with the FSDP in-dim shard
        spec = p2["encoder"]["layers"]["attn"]["wq"]["kernel"].sharding.spec
        assert spec == P(None, "dp_shard", "tp"), spec
