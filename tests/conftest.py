"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's multi-process-without-a-cluster strategy
(``/root/reference/tests`` + SURVEY.md §4) but better: XLA's
``--xla_force_host_platform_device_count`` gives a real 8-device mesh in ONE
process, so sharding/collective semantics are tested without subprocesses.
"""

import os

# Must run before jax initializes its backends.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_singletons():
    """Each test gets fresh state singletons (reference tests use _reset_state
    too) and an unchanged global jax config: a test exercising
    ``JitConfig(disable_jit=True)`` must not leave the WHOLE remaining suite
    running eager (observed: the dryrun's shard_map PP leg needs a jit
    context and failed suite-only)."""
    prev_disable_jit = bool(jax.config.jax_disable_jit)
    yield
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    if bool(jax.config.jax_disable_jit) != prev_disable_jit:
        jax.config.update("jax_disable_jit", prev_disable_jit)
