"""Ring/allgather CP and Ulysses SP attention must match single-device attention
bit-for-bit-ish, forward AND backward (the reference's CP/SP numerical-parity
expectation, docs/source/concept_guides/context_parallelism.md)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from accelerate_tpu import AcceleratorState, ParallelismConfig
from accelerate_tpu.ops.attention import dot_product_attention
from accelerate_tpu.parallel.long_context import make_context_parallel_attention


def _make_qkv(B=2, S=64, H=4, Hkv=4, D=16, seed=0):
    rng = np.random.default_rng(seed)
    q = rng.normal(size=(B, S, H, D)).astype(np.float32)
    k = rng.normal(size=(B, S, Hkv, D)).astype(np.float32)
    v = rng.normal(size=(B, S, Hkv, D)).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


def _shard(x, mesh, spec):
    return jax.device_put(x, NamedSharding(mesh, spec))


@pytest.mark.parametrize("strategy,axis", [("ring", "cp"), ("zigzag", "cp"), ("allgather", "cp"), ("ulysses", "sp")])
@pytest.mark.parametrize("causal", [True, False])
def test_cp_sp_matches_reference(strategy, axis, causal):
    # ulysses shards heads (H=4) so sp must divide H; ring/allgather scale past H
    pc = ParallelismConfig(cp_size=8) if axis == "cp" else ParallelismConfig(sp_size=4)
    mesh = pc.build_mesh()
    q, k, v = _make_qkv()
    ref = dot_product_attention(q, k, v, causal=causal, impl="xla")
    attn = make_context_parallel_attention(mesh, strategy=strategy)
    spec = P(("dp_replicate", "dp_shard"), axis, None, None)
    qs, ks, vs = (_shard(x, mesh, spec) for x in (q, k, v))
    out = jax.jit(lambda a, b, c: attn(a, b, c, causal=causal))(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("strategy", ["ring", "zigzag", "ulysses"])
def test_cp_sp_gradients_match(strategy):
    axis = "sp" if strategy == "ulysses" else "cp"
    pc = ParallelismConfig(cp_size=8) if axis == "cp" else ParallelismConfig(sp_size=4)
    mesh = pc.build_mesh()
    q, k, v = _make_qkv(S=32)
    attn = make_context_parallel_attention(mesh, strategy=strategy)

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True, impl="xla") ** 2)

    def loss_cp(q, k, v):
        return jnp.sum(attn(q, k, v, causal=True) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    spec = P(("dp_replicate", "dp_shard"), axis, None, None)
    qs, ks, vs = (_shard(x, mesh, spec) for x in (q, k, v))
    g_cp = jax.jit(jax.grad(loss_cp, argnums=(0, 1, 2)))(qs, ks, vs)
    for a, b in zip(g_ref, g_cp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=5e-4, atol=5e-5)


@pytest.mark.smoke
def test_ring_with_gqa():
    pc = ParallelismConfig(cp_size=4, dp_shard_size=2)
    mesh = pc.build_mesh()
    q, k, v = _make_qkv(B=4, S=32, H=8, Hkv=2)
    ref = dot_product_attention(q, k, v, causal=True, impl="xla")
    attn = make_context_parallel_attention(mesh, strategy="ring")
    spec = P(("dp_replicate", "dp_shard"), "cp", None, None)
    qs, ks, vs = (_shard(x, mesh, spec) for x in (q, k, v))
    out = jax.jit(lambda a, b, c: attn(a, b, c, causal=True))(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("strategy", ["ring", "zigzag", "allgather"])
def test_cp_strategies_extreme_gqa_non_causal(strategy):
    # H=8 down to ONE kv head, non-causal: the repeat-kv folding and the
    # non-causal block schedules must agree with dense attention exactly
    pc = ParallelismConfig(cp_size=4, dp_shard_size=2)
    mesh = pc.build_mesh()
    q, k, v = _make_qkv(B=2, S=32, H=8, Hkv=1)
    ref = dot_product_attention(q, k, v, causal=False, impl="xla")
    attn = make_context_parallel_attention(mesh, strategy=strategy)
    spec = P(("dp_replicate", "dp_shard"), "cp", None, None)
    qs, ks, vs = (_shard(x, mesh, spec) for x in (q, k, v))
    out = jax.jit(lambda a, b, c: attn(a, b, c, causal=False))(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_zigzag_with_gqa_and_dp():
    pc = ParallelismConfig(cp_size=4, dp_shard_size=2)
    mesh = pc.build_mesh()
    q, k, v = _make_qkv(B=4, S=32, H=8, Hkv=2)
    ref = dot_product_attention(q, k, v, causal=True, impl="xla")
    attn = make_context_parallel_attention(mesh, strategy="zigzag")
    spec = P(("dp_replicate", "dp_shard"), "cp", None, None)
    qs, ks, vs = (_shard(x, mesh, spec) for x in (q, k, v))
    out = jax.jit(lambda a, b, c: attn(a, b, c, causal=True))(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_zigzag_non_causal_falls_back_to_ring():
    """Non-causal zigzag = plain ring (balanced placement buys nothing)."""
    pc = ParallelismConfig(cp_size=8)
    mesh = pc.build_mesh()
    q, k, v = _make_qkv()
    ref = dot_product_attention(q, k, v, causal=False, impl="xla")
    attn = make_context_parallel_attention(mesh, strategy="zigzag")
    spec = P(("dp_replicate", "dp_shard"), "cp", None, None)
    qs, ks, vs = (_shard(x, mesh, spec) for x in (q, k, v))
    out = jax.jit(lambda a, b, c: attn(a, b, c, causal=False))(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_zigzag_in_llama_end_to_end():
    """Llama forward with ZIGZAG attention over cp matches the plain forward
    (exercises the exchange through rope'd q/k inside the real model)."""
    from accelerate_tpu.models import LlamaConfig, init_llama, llama_forward
    from accelerate_tpu.parallel.sharding import replicate

    cfg = LlamaConfig(vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, max_seq_len=64)
    params = init_llama(cfg, jax.random.PRNGKey(0))
    ids = np.random.default_rng(0).integers(0, 128, (2, 64)).astype(np.int32)
    ref = llama_forward(params, ids, cfg, attention_impl="xla")

    pc = ParallelismConfig(cp_size=4, dp_shard_size=2)
    mesh = pc.build_mesh()
    attn = make_context_parallel_attention(mesh, strategy="zigzag")
    params_r = replicate(params, mesh)
    ids_s = jax.device_put(
        jnp.asarray(ids), NamedSharding(mesh, P(("dp_replicate", "dp_shard"), "cp"))
    )
    out = jax.jit(lambda p, i: llama_forward(p, i, cfg, attention_fn=attn))(params_r, ids_s)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=5e-4, atol=5e-4)


def test_cp_in_llama_end_to_end():
    """Llama forward with ring attention over cp matches the plain forward."""
    from accelerate_tpu.models import LlamaConfig, init_llama, llama_forward

    cfg = LlamaConfig(vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=4, max_seq_len=64)
    params = init_llama(cfg, jax.random.PRNGKey(0))
    ids = np.random.default_rng(0).integers(0, 128, (2, 64)).astype(np.int32)
    ref = llama_forward(params, ids, cfg, attention_impl="xla")

    pc = ParallelismConfig(cp_size=4, dp_shard_size=2)
    mesh = pc.build_mesh()
    attn = make_context_parallel_attention(mesh, strategy="ring")
    from accelerate_tpu.parallel.sharding import replicate

    params_r = replicate(params, mesh)
    ids_s = jax.device_put(
        jnp.asarray(ids), NamedSharding(mesh, P(("dp_replicate", "dp_shard"), "cp"))
    )
    out = jax.jit(lambda p, i: llama_forward(p, i, cfg, attention_fn=attn))(params_r, ids_s)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=5e-4, atol=5e-4)
