"""The user-runnable benchmarks/ scripts stay runnable and emit parseable
JSON (reference ships standalone benchmark dirs; ours must not rot)."""

import json
import os
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent


def run_script(rel, *args, timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    res = subprocess.run(
        [sys.executable, str(REPO / rel), *args],
        capture_output=True, text=True, timeout=timeout, env=env,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    last = res.stdout.strip().splitlines()[-1]
    return json.loads(last)


@pytest.mark.slow
def test_fp8_benchmark_emits_parity_json():
    out = run_script("benchmarks/fp8/run.py", "--steps", "5")
    assert {"bf16_final_loss", "fp8_final_loss", "bf16_step_ms", "fp8_step_ms"} <= set(out)


@pytest.mark.slow
def test_long_context_benchmark_honors_seq_knob():
    out = run_script("benchmarks/long_context/run.py", "--seq", "512")
    assert out["unit"] == "tokens/sec/chip" and out["value"] > 0
    assert out["seq_len"] == 512  # the CLI knob actually reached the workload


def test_input_pipeline_benchmark_smoke():
    """Fast tier-1 smoke: the sync-vs-prefetch microbench runs and emits the
    contract keys (overlap correctness itself is asserted by
    test_data_loader's acceptance test; a loaded CI box makes speedup-margin
    assertions here flaky)."""
    out = run_script(
        "benchmarks/input_pipeline/run.py",
        "--steps", "6", "--item-delay-ms", "1", "--compute-ms", "5",
    )
    assert out["bench"] == "input_pipeline"
    assert out["unit"] == "speedup(prefetch/sync)" and out["value"] > 0
    assert out["sync"]["samples_per_s"] > 0
    assert out["prefetch"]["samples_per_s"] > 0
    assert out["prefetch_depth"] == 2


def test_checkpoint_benchmark_smoke():
    """Fast tier-1 smoke: the sync-vs-async checkpoint microbench runs and
    emits the contract keys (the zero-stall margin itself is asserted by
    test_async_checkpoint's timing tests; wall-clock ratio assertions here
    would be flaky on a loaded CI box)."""
    out = run_script(
        "benchmarks/checkpoint/run.py",
        "--steps", "9", "--compute-ms", "10", "--every", "3", "--mb", "2",
    )
    assert out["bench"] == "checkpoint"
    assert out["unit"] == "exposed_stall_ratio(async/sync)"
    assert out["value"] >= 0
    for variant in ("baseline", "sync", "async"):
        assert out[variant]["p95_step_ms"] > 0
    assert out["sync"]["saves"] == out["async"]["saves"] == 3
    assert out["baseline"]["saves"] == 0


def test_perf_benchmark_smoke():
    """Fast tier-1 smoke: the performance-observatory microbench (ISSUE 7)
    runs the bench train step under telemetry + a trace window and emits the
    contract keys with a non-zero MFU (absolute MFU margins on a loaded CI
    box are asserted nowhere — CPU peaks are nominal by design)."""
    out = run_script("benchmarks/perf/run.py", "--steps", "5", "--trace-every", "2")
    assert out["bench"] == "perf"
    assert out["unit"] == "mfu(p50)" and out["value"] > 0
    assert out["roofline"] in ("compute-bound", "hbm-bound")
    assert out["arithmetic_intensity"] > 0 and out["flops_per_step"] > 0
    assert out["trace_windows"] >= 1
    assert out["top_ops"] and all(op["total_s"] > 0 for op in out["top_ops"])
    # single-process CPU run traces no collectives: the ratio must be an
    # honest null, not a fake 1.0
    assert out["overlap_ratio"] is None


def test_weight_update_benchmark_smoke():
    """Fast tier-1 smoke: the fused-vs-annotation ZeRO-1 microbench (ISSUE 9)
    runs on the 8-virtual-device CPU mesh and emits the contract keys. CPU
    step-time ratios are emulation artifacts (see the README), so only
    structure + the memory/parity facts are asserted; the step-time and
    overlap numbers become meaningful on TPU hardware runs."""
    out = run_script(
        "benchmarks/weight_update/run.py",
        "--steps", "5", "--dim", "64", "--layers", "2", "--trace-every", "3",
    )
    assert out["bench"] == "weight_update"
    assert out["unit"] == "step_time_ratio(fused/unfused)" and out["value"] > 0
    assert out["n_devices"] == 8
    assert out["fused"]["fused"] is True  # the fused path actually engaged
    assert out["unfused"]["fused"] is False
    for leg in ("fused", "unfused"):
        assert out[leg]["step_ms"] > 0
        assert out[leg]["opt_state_bytes_per_replica"] > 0
    # one replica holds ~1/8 of the state (scalar count leaves ride on top)
    assert out["fused"]["opt_state_fraction"] < 0.2
    # both legs compute the same training: loss parity to float32 print width
    assert out["fused"]["final_loss"] == pytest.approx(
        out["unfused"]["final_loss"], rel=1e-6
    )
    # compiled-collective accounting flowed through telemetry
    assert out["collective_bytes_per_step"] > 0


def test_serving_benchmark_smoke():
    """Fast tier-1 smoke: the continuous-vs-static serving microbench
    (ISSUE 11) runs at a reduced workload and emits the contract keys with a
    continuous win. The full ≥1.5x acceptance margin is asserted on the
    default workload by `make bench-serve` (margin assertions at reduced
    scale on a loaded CI box would be flaky); here the bar is ratio > 1.0
    plus real batching evidence (occupancy) and latency percentiles."""
    out = run_script(
        "benchmarks/serving/run.py",
        "--requests", "12", "--rate", "2.0", "--max-slots", "4",
        "--replicated-requests", "8", "--prefix-requests", "10",
        "--disagg-requests", "8", "--spec-requests", "8",
        timeout=600,
    )
    assert out["bench"] == "serving"
    assert out["unit"] == "throughput_ratio(continuous/static)"
    assert out["value"] > 1.0  # continuous must beat static even reduced
    for leg in ("continuous", "static"):
        assert out[leg]["completed"] == 12
        assert out[leg]["rejected"] == 0  # whole workload actually measured
        assert out[leg]["tokens_per_s"] > 0
        assert out[leg]["p99_latency_ms"] >= out[leg]["p50_latency_ms"] > 0
    # same workload -> same useful tokens; only the schedule differs
    assert out["continuous"]["tokens"] == out["static"]["tokens"]
    assert out["continuous"]["mean_occupancy"] > out["static"]["mean_occupancy"]
    assert out["p99_latency_ms"] == out["continuous"]["p99_latency_ms"]
    # replicated router leg (ISSUE 12): no scaling-margin bar at reduced
    # scale, but the robustness invariants are absolute — nothing lost, the
    # kill run's outputs bitwise-equal to the unkilled run, failover fired
    rep = out["replicated"]
    assert rep["bench"] == "serving_replicated" and rep["value"] > 0
    for leg in ("one_replica", "replicated", "replica_kill"):
        assert rep[leg]["completed"] == 8
        assert rep[leg]["lost"] == 0
        assert rep[leg]["tokens_per_s"] > 0
    assert rep["replica_kill"]["failovers"] >= 1
    assert rep["kill_outputs_match_unkilled"] is True
    assert rep["replica_kill"]["p99_latency_ms"] >= rep["replica_kill"]["p50_latency_ms"]
    # observability leg (ISSUE 15): tracing ON over the same kill workload —
    # outputs still bitwise-identical, and 100% of completions carry a
    # gap-free span tree (failover hops included)
    traced = rep["replica_kill_traced"]
    assert traced["completed"] == 8 and traced["lost"] == 0
    assert traced["span_trees_complete"] is True
    assert traced["broken_span_trees"] == 0
    assert rep["traced_outputs_match_unkilled"] is True
    assert rep["tracing_tokens_per_s_ratio"] > 0
    # shared-prefix leg (ISSUE 14): the deterministic invariants hold even at
    # reduced scale — prefill-token reduction is a token COUNT, not a wall
    # clock, so the ≥40% acceptance bar is assertable here; the wall-clock
    # tok/s and ttft improvements are asserted by `make bench-serve` at full
    # scale and only sanity-checked (> 0) under CI load
    pc = out["prefix_cache"]
    assert pc["bench"] == "serving_prefix_cache"
    assert pc["value"] >= 0.4  # prefill tokens cut by at least 40%
    assert pc["prefix_hit_rate"] > 0
    assert pc["prefill_tokens_saved"] > 0
    assert pc["outputs_match"] is True  # bitwise parity between the legs
    assert pc["zero_recompiles"] is True
    assert pc["cached"]["completed"] == pc["uncached"]["completed"] == 10
    assert pc["cached"]["rejected"] == pc["uncached"]["rejected"] == 0
    assert pc["tokens_per_s_ratio"] > 0 and pc["ttft_p50_ratio"] > 0
    # disaggregated leg (ISSUE 16): no throughput bar at reduced scale on a
    # loaded box, but the correctness invariants are absolute — bitwise
    # parity with the monolith, zero lost requests, ≥1 autoscaler scale-up
    # under the tight ttft objective, and a WARM join (every warmup point
    # pre-shipped: zero compiles on the joiner)
    dg = out["disagg"]
    assert dg["bench"] == "serving_disagg" and dg["value"] > 0
    assert dg["outputs_match_monolith"] is True
    assert dg["zero_lost"] is True
    assert dg["monolith"]["completed"] == dg["disagg"]["completed"] == 8
    assert dg["disagg"]["handoffs"] >= 8
    assert dg["scale_up_fired"] is True
    assert dg["join_compiles"] == 0 and dg["warm_join"] is True
    tr = dg["disagg"]["transition"]
    # the burn trigger fires on ttft OBSERVATIONS (first tokens), not
    # completions, so neither phase has a guaranteed minimum on a loaded
    # box — but the cut must partition every completion, and whichever
    # phase is populated must carry real percentiles
    assert tr["pre_scale"]["completed"] + tr["post_scale"]["completed"] == 8
    assert any(
        tr[ph]["completed"] > 0 and tr[ph]["p99_ttft_ms"] > 0
        for ph in ("pre_scale", "post_scale")
    )
    # speculative-decoding leg (ISSUE 18): no latency bar on CPU (the
    # truncated-layer draft only pays on TPU, where draft+verify beat k+1
    # sequential decode steps), but bitwise-accept makes the correctness
    # invariants absolute — outputs identical to the plain decode loop,
    # zero post-warmup recompiles with draft+verify watched, and the step
    # count must not grow (accepted drafts can only shorten the run)
    sd = out["spec_decode"]
    assert sd["bench"] == "serving_spec_decode"
    assert sd["outputs_match"] is True
    assert sd["zero_recompiles"] is True
    assert sd["speculative"]["completed"] == sd["baseline"]["completed"] == 8
    assert sd["speculative"]["rejected"] == sd["baseline"]["rejected"] == 0
    assert sd["speculative"]["tokens"] == sd["baseline"]["tokens"]
    assert sd["speculative"]["engine_steps"] <= sd["baseline"]["engine_steps"]
    assert sd["speculative"]["draft_proposed_tokens"] > 0
    assert 0.0 <= sd["spec_accept_rate"] <= 1.0
    assert sum(sd["speculative"]["spec_accept_hist"]) > 0
    # prefill-kernel chunk microbench rode along: gather column is always
    # compiled; the kernel column is compiled on TPU, interpreted on CPU
    pk = sd["prefill_kernel"]
    assert pk["gather_us_per_token"] > 0 and pk["kernel_us_per_token"] > 0
    assert pk["kernel_mode"] == ("compiled" if sd["on_tpu"] else "interpret")


def test_attention_benchmark_smoke():
    """Fast tier-1 smoke for `make bench-attn` (ISSUE 20): the kernel grid
    runs on CPU shapes (xla path — interpret mode is a correctness tool, not
    a perf signal), every cell lands without error, and the payload carries
    the roofline numbers plus the regression-guarded block. The fp8 leg's
    loss parity is absolute even at CPU scale; step-time margins are TPU
    facts and asserted nowhere here."""
    out = run_script("benchmarks/attention/run.py", "--steps", "2", timeout=600)
    assert out["unit"] == "us/token" and out["value"] > 0
    assert out["grid"] and all("error" not in g for g in out["grid"])
    for g in out["grid"]:
        assert g["us_per_token"] > 0
        assert g["achieved_tflops"] > 0
        assert 0 < g["fraction_of_peak"]
    # every sparsity leg actually ran (the block-skip comparison needs all 3)
    assert {g["sparsity"] for g in out["grid"]} == {"dense", "causal", "window"}
    fp8 = out["fp8_train_step"]
    assert fp8["bf16_step_ms"] > 0 and fp8["fp8_step_ms"] > 0
    assert fp8["loss_rel_delta"] < 0.05  # fp8 recipe parity envelope
    g = out["guarded"]
    assert g["attn_kernel_us_per_token"] == out["value"]
    assert g["fp8_step_ms"] == fp8["fp8_step_ms"]
    assert 0 < g["attn_mfu_best_fraction"]


def test_compile_time_restart_benchmark_smoke():
    """Fast tier-1 smoke for `make bench-compile` (ISSUE 13): the train leg
    only (two subprocess generations against one cache) — the payload must
    carry cold/warm seconds plus the cache-event counts, and the warm
    generation must actually HIT (miss>0 there would be a silent recompile
    masquerading as a warm start). Speedup-margin assertions live in the
    chaos/compile-cache suites; wall-clock ratios here would flake on a
    loaded CI box."""
    out = run_script("benchmarks/compile_time/run.py", "--modes", "train", timeout=360)
    assert out["bench"] == "compile_time_restart"
    assert out["unit"].startswith("speedup")
    leg = out["train"]
    assert leg["metric"] == "restart_to_first_step_s"
    assert leg["cold_s"] > 0 and leg["warm_s"] > 0 and leg["speedup"] > 0
    assert leg["cold_cache_events"].get("store", 0) >= 1
    assert leg["warm_cache_events"].get("hit", 0) >= 1
    assert leg["warm_cache_events"].get("miss", 0) == 0


def _regress_cli(tmp_path, *args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.telemetry", "regress", *args],
        capture_output=True, text=True, timeout=120, env=env, cwd=str(REPO),
    )


def _bench_payload(value):
    return {
        "metric": "tok_per_sec", "value": value, "mfu": 0.4,
        "env": {"device_kind": "cpu", "device_count": 1, "jaxlib": "x"},
    }


def test_bench_check_flags_synthetic_regression(tmp_path):
    """The `make bench-check` gate, tier-1: a synthetic 20% tok/s regression
    must exit nonzero and NAME the regressed metric."""
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(_bench_payload(100.0)))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(_bench_payload(80.0)))
    res = _regress_cli(tmp_path, "--scan", str(tmp_path))
    assert res.returncode == 1, res.stdout + res.stderr
    assert "REGRESSION" in res.stdout and "tok_per_sec" in res.stdout


def test_bench_check_waiver_buys_exit_code_not_silence(tmp_path):
    """`--waive` flips the exit code for a known regression, but the
    REGRESSION row still prints, the WAIVED marker carries the reason, and
    the verdict line names the waiver again — silence is the one thing a
    waiver must never buy."""
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(_bench_payload(100.0)))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(_bench_payload(80.0)))
    res = _regress_cli(tmp_path, "--scan", str(tmp_path),
                       "--waive", "*tok_per_sec*=cpu runner flake")
    assert res.returncode == 0, res.stdout + res.stderr
    assert "REGRESSION" in res.stdout          # the row survives the waiver
    assert "^ WAIVED" in res.stdout and "cpu runner flake" in res.stdout
    assert "regress verdict: OK with 1 regression(s) WAIVED" in res.stdout


def test_bench_check_waiver_file_autoloads_in_scan_mode(tmp_path):
    """Scan mode picks up BENCH_WAIVERS next to the payloads (the committed
    path `make bench-check` uses) and announces the load."""
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(_bench_payload(100.0)))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(_bench_payload(80.0)))
    (tmp_path / "BENCH_WAIVERS").write_text(
        "# known CPU variance\n*tok_per_sec*  # runner variance at boundary\n"
    )
    res = _regress_cli(tmp_path, "--scan", str(tmp_path))
    assert res.returncode == 0, res.stdout + res.stderr
    assert "regress: loaded 1 waiver(s)" in res.stdout
    assert "runner variance at boundary" in res.stdout


def test_bench_check_unmatched_waiver_does_not_apply(tmp_path):
    """A waiver that names some OTHER metric must not buy this regression's
    exit code."""
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(_bench_payload(100.0)))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(_bench_payload(80.0)))
    res = _regress_cli(tmp_path, "--scan", str(tmp_path),
                       "--waive", "configs.some_other_bench=nope")
    assert res.returncode == 1, res.stdout + res.stderr
    assert "REGRESSION" in res.stdout and "^ WAIVED" not in res.stdout


def _attn_guarded_payload(us=100.0, fp8_ms=30.0, mfu=0.4):
    p = _bench_payload(100.0)
    p["configs"] = {
        "attention": {
            "metric": "attention fwd+bwd µs/token", "value": us,
            "guarded": {
                "attn_kernel_us_per_token": us,
                "fp8_step_ms": fp8_ms,
                "attn_mfu_best_fraction": mfu,
            },
        }
    }
    return p


@pytest.mark.parametrize(
    "kwargs, name",
    [
        ({"us": 130.0}, "attn_kernel_us_per_token"),      # 30% slower kernel
        ({"fp8_ms": 39.0}, "fp8_step_ms"),                # 30% slower fp8 step
        ({"mfu": 0.28}, "attn_mfu_best_fraction"),        # 30% roofline drop
    ],
)
def test_bench_check_flags_attention_guarded_regressions(tmp_path, kwargs, name):
    """ISSUE 20 acceptance: a synthetic regression on each guarded attention
    metric (30% — past the 10% spec band even after the 2x CPU-fingerprint
    widening) must fail `make bench-check` and NAME the metric — the specs
    give the kernel time and fp8 step ms lower-is-better direction and the
    mfu fraction higher-is-better (a generic catch-all would read a slower
    kernel as an 'improvement')."""
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(_attn_guarded_payload()))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(_attn_guarded_payload(**kwargs)))
    res = _regress_cli(tmp_path, "--scan", str(tmp_path))
    assert res.returncode == 1, res.stdout + res.stderr
    assert "REGRESSION" in res.stdout and name in res.stdout


def test_bench_check_accepts_unchanged_attention_guarded_payload(tmp_path):
    for fname in ("BENCH_r01.json", "BENCH_r02.json"):
        (tmp_path / fname).write_text(json.dumps(_attn_guarded_payload()))
    res = _regress_cli(tmp_path, "--scan", str(tmp_path))
    assert res.returncode == 0, res.stdout + res.stderr


def test_bench_check_accepts_identical_payloads(tmp_path):
    for name in ("BENCH_r01.json", "BENCH_r02.json"):
        (tmp_path / name).write_text(json.dumps(_bench_payload(100.0)))
    res = _regress_cli(tmp_path, "--scan", str(tmp_path))
    assert res.returncode == 0, res.stdout + res.stderr
    assert "regress verdict: OK" in res.stdout


def test_bench_check_refuses_cross_fingerprint(tmp_path):
    a = _bench_payload(100.0)
    b = _bench_payload(100.0)
    b["env"] = {"device_kind": "TPU v5 lite", "device_count": 8, "jaxlib": "x"}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(a))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(b))
    res = _regress_cli(tmp_path, "--scan", str(tmp_path))
    assert res.returncode == 2, res.stdout + res.stderr
    assert "REFUSING" in res.stdout


def test_hub_dashboard_render_stays_under_overhead_budget(tmp_path):
    """Tier-1 guard for the live plane (ISSUE 19): tailing + folding a
    ~2000-record stream and rendering one `top` frame — detectors armed —
    must finish well inside a fixed budget. The dashboard watches the
    fleet; it must never cost like one."""
    import time

    from accelerate_tpu.telemetry.anomaly import AnomalyEngine
    from accelerate_tpu.telemetry.hub import EventHub, render_top

    path = tmp_path / "events-rank0.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "meta", "schema": 1, "run_id": "bench",
                            "process_index": 0, "num_processes": 1}) + "\n")
        for i in range(2000):
            f.write(json.dumps({"kind": "step", "step": i, "t": float(i),
                                "dur_s": 0.01 + 0.0001 * (i % 7),
                                "execute_s": 0.01}) + "\n")
    hub = EventHub([str(tmp_path)], anomaly=AnomalyEngine(emit_records=False))
    t0 = time.perf_counter()
    hub.poll()
    frame = render_top(hub.model)
    elapsed = time.perf_counter() - t0
    assert len(hub.model.records) >= 2001
    assert "steps: 2000" in frame
    # generous for a loaded single-core CI box; a regression that makes the
    # live plane quadratic or per-record-expensive blows straight past it
    assert elapsed < 3.0, f"hub poll+fold+render took {elapsed:.2f}s"


def test_benchmark_dirs_are_documented():
    dirs = [p for p in (REPO / "benchmarks").iterdir() if p.is_dir() and p.name != "__pycache__"]
    assert len(dirs) >= 5
    for d in dirs:
        assert (d / "README.md").exists(), f"{d.name} lacks a README"
        assert (d / "run.py").exists(), f"{d.name} lacks run.py"
