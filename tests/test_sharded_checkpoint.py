"""Sharded checkpoint I/O: per-process chunk files, mesh-refactorization reload,
offline consolidation (reference ``utils/fsdp_utils.py:103-414`` — DCP sharded
save/load + ``merge_fsdp_weights``)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from accelerate_tpu.sharded_checkpoint import (
    consolidate_sharded,
    is_sharded_checkpoint,
    load_sharded_pytree,
    merge_sharded_checkpoint,
    save_sharded_pytree,
)


def _mesh(shape, names):
    devices = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devices, names)


@pytest.fixture
def params():
    rng = np.random.default_rng(0)
    return {
        "layer": {
            "w": rng.normal(size=(16, 8)).astype(np.float32),
            "b": rng.normal(size=(8,)).astype(np.float32),
        },
        "head": rng.normal(size=(8, 4)).astype(np.float32),
        "step": np.int32(7),
    }


def _shard(params, mesh, w_spec, head_spec):
    return {
        "layer": {
            "w": jax.device_put(params["layer"]["w"], NamedSharding(mesh, w_spec)),
            "b": jax.device_put(params["layer"]["b"], NamedSharding(mesh, P())),
        },
        "head": jax.device_put(params["head"], NamedSharding(mesh, head_spec)),
        "step": params["step"],
    }


class TestShardedSaveLoad:
    def test_roundtrip_same_mesh(self, params, tmp_path):
        mesh = _mesh((8,), ("fsdp",))
        live = _shard(params, mesh, P("fsdp"), P("fsdp", None))
        save_sharded_pytree(live, str(tmp_path), prefix="model")
        assert is_sharded_checkpoint(str(tmp_path), "model")

        template = jax.tree_util.tree_map(
            lambda x: jnp.zeros_like(x) if isinstance(x, jax.Array) else x, live
        )
        restored = load_sharded_pytree(template, str(tmp_path), prefix="model")
        np.testing.assert_allclose(np.asarray(restored["layer"]["w"]), params["layer"]["w"])
        np.testing.assert_allclose(np.asarray(restored["head"]), params["head"])
        assert int(restored["step"]) == 7

    def test_reload_on_refactored_mesh(self, params, tmp_path):
        """Save on fsdp=8, reload on fsdp=4×tp=2 with 2-D sharding — the
        coordinate-based assembly reshards without any gather."""
        mesh_a = _mesh((8,), ("fsdp",))
        live = _shard(params, mesh_a, P("fsdp"), P("fsdp"))
        save_sharded_pytree(live, str(tmp_path), prefix="model")

        mesh_b = _mesh((4, 2), ("fsdp", "tp"))
        template = {
            "layer": {
                "w": jax.device_put(
                    jnp.zeros((16, 8)), NamedSharding(mesh_b, P("fsdp", "tp"))
                ),
                "b": jax.device_put(jnp.zeros((8,)), NamedSharding(mesh_b, P("tp"))),
            },
            "head": jax.device_put(jnp.zeros((8, 4)), NamedSharding(mesh_b, P(None, "tp"))),
            "step": np.int32(0),
        }
        restored = load_sharded_pytree(template, str(tmp_path), prefix="model")
        np.testing.assert_allclose(np.asarray(restored["layer"]["w"]), params["layer"]["w"])
        np.testing.assert_allclose(np.asarray(restored["layer"]["b"]), params["layer"]["b"])
        np.testing.assert_allclose(np.asarray(restored["head"]), params["head"])
        # and the restored arrays actually carry the new shardings
        assert restored["layer"]["w"].sharding.spec == P("fsdp", "tp")

    def test_each_region_written_once(self, params, tmp_path):
        """Replicated leaves must not be duplicated across chunk files: total
        stored elements == total model elements."""
        mesh = _mesh((4, 2), ("fsdp", "tp"))
        live = _shard(params, mesh, P("fsdp", "tp"), P(None, "tp"))
        save_sharded_pytree(live, str(tmp_path), prefix="model")
        import json

        stored = n_chunks = 0
        for name in os.listdir(tmp_path):
            if name.endswith(".index.json"):
                with open(os.path.join(tmp_path, name)) as f:
                    index = json.load(f)
                for meta in index["leaves"].values():
                    n_chunks += len(meta["chunks"])
                    for chunk in meta["chunks"]:
                        stored += int(np.prod([
                            e - s for s, e in zip(chunk["start"], chunk["stop"])
                        ] or [1]))
        expected = sum(np.asarray(v).size for v in jax.tree_util.tree_leaves(params))
        assert stored == expected, (stored, expected)
        # and the BYTES physically on disk agree (the index is self-reported;
        # a writer that stored full arrays while recording slice coords would
        # pass the count above) — all leaves here are f32, plus ≤64B alignment
        # slack per chunk
        disk = sum(
            os.path.getsize(os.path.join(tmp_path, n))
            for n in os.listdir(tmp_path) if n.endswith((".bin", ".npz"))
        )
        assert disk <= expected * 4 + n_chunks * 64 + 1024, (disk, expected * 4, n_chunks)

    def test_consolidate_and_merge_cli(self, params, tmp_path):
        mesh = _mesh((8,), ("fsdp",))
        live = _shard(params, mesh, P("fsdp"), P("fsdp"))
        save_sharded_pytree(live, str(tmp_path), prefix="model")

        flat = consolidate_sharded(str(tmp_path), "model")
        np.testing.assert_allclose(flat["layer/w"], params["layer"]["w"])
        np.testing.assert_allclose(flat["head"], params["head"])

        out = merge_sharded_checkpoint(str(tmp_path), str(tmp_path / "merged"))
        from safetensors.numpy import load_file

        merged = load_file(out)
        np.testing.assert_allclose(merged["layer/w"], params["layer"]["w"])

    def test_missing_leaf_raises(self, params, tmp_path):
        mesh = _mesh((8,), ("fsdp",))
        live = _shard(params, mesh, P("fsdp"), P("fsdp"))
        save_sharded_pytree(live, str(tmp_path), prefix="model")
        template = dict(live)
        template["extra"] = jnp.zeros((3,))
        with pytest.raises(KeyError):
            load_sharded_pytree(template, str(tmp_path), prefix="model")


class TestAcceleratorShardedState:
    def test_save_state_sharded_roundtrip(self, tmp_path):
        """save_state(sharded=True) writes shard files (no model.npz) and
        load_state restores through the sharded reader."""
        import optax

        from accelerate_tpu import Accelerator

        accelerator = Accelerator()
        mesh = _mesh((8,), ("fsdp",))
        params = {
            "w": jax.device_put(
                np.arange(32, dtype=np.float32).reshape(16, 2),
                NamedSharding(mesh, P("fsdp")),
            )
        }
        opt = optax.adam(1e-3)
        opt_state = opt.init(params)
        ckpt = str(tmp_path / "ckpt")
        accelerator.save_state(ckpt, params=params, opt_state=opt_state, sharded=True)
        assert not os.path.exists(os.path.join(ckpt, "model.npz"))
        assert is_sharded_checkpoint(ckpt, "model")
        assert is_sharded_checkpoint(ckpt, "optimizer")

        zeros = jax.tree_util.tree_map(
            lambda x: jax.device_put(jnp.zeros_like(x), x.sharding)
            if isinstance(x, jax.Array)
            else x,
            params,
        )
        opt_zeros = jax.tree_util.tree_map(
            lambda x: jnp.zeros_like(x) if isinstance(x, jax.Array) else x, opt_state
        )
        restored, restored_opt = accelerator.load_state(ckpt, params=zeros, opt_state=opt_zeros)
        np.testing.assert_allclose(np.asarray(restored["w"]), np.asarray(params["w"]))
        # adam mu buffer restored too
        flat_a = jax.tree_util.tree_leaves(restored_opt)
        flat_b = jax.tree_util.tree_leaves(opt_state)
        for a, b in zip(flat_a, flat_b):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))


@pytest.mark.smoke
def test_checkpoint_dir_reuse_scrubs_stale_format(tmp_path):
    """A reused output_dir must not leave the previous save's format behind:
    load prefers model.npz, so a sharded save over an old npz save (or vice
    versa) would silently restore stale weights without the scrub."""
    import optax

    from accelerate_tpu import Accelerator

    accelerator = Accelerator()
    ckpt = str(tmp_path / "reused")
    mesh = _mesh((8,), ("fsdp",))

    params_old = {"w": np.full((16, 2), 1.0, np.float32)}
    accelerator.save_state(ckpt, params=params_old, opt_state=optax.sgd(0.1).init(params_old))
    assert os.path.exists(os.path.join(ckpt, "model.npz"))

    params_new = {
        "w": jax.device_put(
            np.full((16, 2), 2.0, np.float32), NamedSharding(mesh, P("fsdp"))
        )
    }
    accelerator.save_state(
        ckpt, params=params_new, opt_state=optax.sgd(0.1).init(params_new), sharded=True
    )
    # the stale npz must be gone, and load must restore the NEW values
    assert not os.path.exists(os.path.join(ckpt, "model.npz"))
    restored = accelerator.load_state(
        ckpt,
        params={"w": jax.device_put(jnp.zeros((16, 2)), NamedSharding(mesh, P("fsdp")))},
    )
    np.testing.assert_allclose(np.asarray(restored["w"]), 2.0)


def test_legacy_npz_shard_set_still_loads(params, tmp_path, monkeypatch):
    """A shard dir written with ACCELERATE_TPU_CKPT_FORMAT=npz (the pre-native
    container) must load through the default bin-aware reader."""
    mesh = _mesh((8,), ("fsdp",))
    live = _shard(params, mesh, P("fsdp"), P("fsdp"))
    monkeypatch.setenv("ACCELERATE_TPU_CKPT_FORMAT", "npz")
    save_sharded_pytree(live, str(tmp_path), prefix="model")
    monkeypatch.delenv("ACCELERATE_TPU_CKPT_FORMAT")
    assert any(n.endswith(".npz") for n in os.listdir(tmp_path))
    restored = load_sharded_pytree(live, str(tmp_path), prefix="model")
    np.testing.assert_allclose(np.asarray(restored["layer"]["w"]), params["layer"]["w"])
    np.testing.assert_allclose(np.asarray(restored["head"]), params["head"])


def test_stale_other_format_file_does_not_misroute(params, tmp_path, monkeypatch):
    """A stale .bin left in the dir must not hijack chunk routing when a fresh
    npz-format save (public API, no accelerator scrub) overwrites the index."""
    mesh = _mesh((8,), ("fsdp",))
    live = _shard(params, mesh, P("fsdp"), P("fsdp"))
    save_sharded_pytree(live, str(tmp_path), prefix="model")  # writes .bin
    assert any(n.endswith(".bin") for n in os.listdir(tmp_path))
    monkeypatch.setenv("ACCELERATE_TPU_CKPT_FORMAT", "npz")
    save_sharded_pytree(live, str(tmp_path), prefix="model")  # overwrites index
    monkeypatch.delenv("ACCELERATE_TPU_CKPT_FORMAT")
    restored = load_sharded_pytree(live, str(tmp_path), prefix="model")
    np.testing.assert_allclose(np.asarray(restored["layer"]["w"]), params["layer"]["w"])
