"""End-to-end Accelerator tests — the TPU twin of the reference's
``training_check`` parity suite (``test_utils/scripts/test_script.py:449``):
distributed runs must match the single-device baseline bit-for-bit-ish."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from accelerate_tpu import Accelerator, AcceleratorState, GradientState, ParallelismConfig, PartialState
from accelerate_tpu.data_loader import DataLoader
from accelerate_tpu.parallel.sharding import ShardingRules


RNG = np.random.default_rng(0)
W_TRUE = RNG.normal(size=(16, 4)).astype(np.float32)
X_ALL = RNG.normal(size=(256, 16)).astype(np.float32)
Y_ALL = X_ALL @ W_TRUE


class RegressionDS:
    def __len__(self):
        return len(X_ALL)

    def __getitem__(self, i):
        return {"x": X_ALL[i], "y": Y_ALL[i]}


def loss_fn(p, batch):
    pred = batch["x"].astype(p["w"].dtype) @ p["w"] + p["b"]
    return jnp.mean((pred - batch["y"].astype(pred.dtype)) ** 2)


def fresh_params():
    return {"w": np.zeros((16, 4), np.float32), "b": np.zeros(4, np.float32)}


def run_training(pc, batch_size, epochs=2, accum=1, precision="no", lr=1e-2):
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    acc = Accelerator(
        mixed_precision=precision, gradient_accumulation_steps=accum, parallelism_config=pc
    )
    params, opt, dl = acc.prepare(
        fresh_params(), optax.sgd(lr), DataLoader(RegressionDS(), batch_size=batch_size)
    )
    step = acc.prepare_train_step(loss_fn, opt)
    opt_state = opt.opt_state
    for _ in range(epochs):
        for batch in dl:
            params, opt_state, metrics = step(params, opt_state, batch)
    return jax.tree_util.tree_map(np.asarray, params), float(metrics["loss"])


@pytest.mark.smoke
def test_dp_parity_with_single_device():
    """8-way DP on global batch 64 == single-device on batch 64 (same samples,
    same order, sequential sampler)."""
    params_dp, _ = run_training(ParallelismConfig(dp_replicate_size=8), batch_size=8)
    params_1, _ = run_training(ParallelismConfig(dp_replicate_size=1), batch_size=64)
    np.testing.assert_allclose(params_dp["w"], params_1["w"], rtol=2e-5, atol=2e-6)


def test_fsdp_parity_with_single_device():
    params_fsdp, _ = run_training(
        ParallelismConfig(dp_shard_size=8), batch_size=8, epochs=1
    )
    params_1, _ = run_training(ParallelismConfig(dp_replicate_size=1), batch_size=64, epochs=1)
    np.testing.assert_allclose(params_fsdp["w"], params_1["w"], rtol=2e-5, atol=2e-6)


def test_grad_accumulation_parity():
    """accum=4 on batch 16 == no-accum on batch 64 for SGD (mean-of-means with
    equal micro sizes)."""
    pc = ParallelismConfig(dp_replicate_size=8)
    params_acc, _ = run_training(pc, batch_size=2, accum=4, epochs=1)
    params_big, _ = run_training(pc, batch_size=8, accum=1, epochs=1)
    np.testing.assert_allclose(params_acc["w"], params_big["w"], rtol=2e-5, atol=2e-6)


def test_bf16_training_converges():
    params, loss = run_training(
        ParallelismConfig(dp_replicate_size=8), batch_size=8, epochs=20, precision="bf16", lr=1e-1
    )
    assert loss < 0.5


@pytest.mark.parametrize("precision", ["no", "bf16", "fp16"])
def test_train_loop_matches_per_step_calls(precision):
    """prepare_train_loop (K scanned steps / one dispatch) must be update-for-
    update identical to K prepare_train_step calls — incl. fp16 dynamic loss
    scaling state threading through the scan carry."""
    from accelerate_tpu.utils.operations import stack_batches

    def make(n_batches=4, bs=8):
        return [
            {
                "x": X_ALL[i * bs : (i + 1) * bs],
                "y": Y_ALL[i * bs : (i + 1) * bs],
            }
            for i in range(n_batches)
        ]

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    acc = Accelerator(mixed_precision=precision)
    params, opt = acc.prepare(fresh_params(), optax.sgd(1e-2))
    step = acc.prepare_train_step(loss_fn, opt)
    p1, s1 = params, opt.opt_state
    step_losses = []
    for b in make():
        p1, s1, m = step(p1, s1, b)
        step_losses.append(float(m["loss"]))

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    acc2 = Accelerator(mixed_precision=precision)
    params2, opt2 = acc2.prepare(fresh_params(), optax.sgd(1e-2))
    loop = acc2.prepare_train_loop(loss_fn, opt2)
    p2, s2, m2 = loop(params2, opt2.opt_state, stack_batches(make()))
    loop_losses = [float(x) for x in np.asarray(m2["loss"])]

    np.testing.assert_allclose(step_losses, loop_losses, rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(a, np.float32), np.asarray(b, np.float32), rtol=1e-6)
    # write-back tracking: optimizer sees the post-loop state (checkpointable)
    assert opt2.opt_state is s2


def test_prepare_assigns_shardings():
    acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=8))
    big = {"w": np.zeros((128, 64), np.float32), "tiny": np.zeros(4, np.float32)}
    prepared = acc.prepare_model(big)
    # canonical (trailing-None-trimmed) spec — the form GSPMD returns on step
    # outputs, so placed inputs never re-specialize the compiled step
    assert prepared["w"].sharding.spec == P("dp_shard")
    # small params stay replicated
    assert prepared["tiny"].sharding.spec == P()


def test_prepare_with_tp_rules():
    acc = Accelerator(
        parallelism_config=ParallelismConfig(dp_shard_size=4, tp_size=2),
        shard_rules=ShardingRules([(r"w/kernel", P(None, "tp"))]),
    )
    params = acc.prepare_model({"w": {"kernel": np.zeros((64, 64), np.float32)}})
    spec = params["w"]["kernel"].sharding.spec
    assert spec == P("dp_shard", "tp")


def test_optimizer_state_sharded_like_params():
    acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=8))
    params, opt = acc.prepare({"w": np.zeros((128, 8), np.float32)}, optax.adam(1e-3))
    mu = opt.opt_state[0].mu["w"]
    assert mu.sharding.spec == P("dp_shard")


def test_clip_grad_norm():
    acc = Accelerator()
    grads = {"w": jnp.full((4,), 10.0)}
    clipped, norm = acc.clip_grad_norm_(grads, max_norm=1.0)
    assert float(norm) == pytest.approx(20.0)
    assert float(optax.global_norm(clipped)) == pytest.approx(1.0, rel=1e-4)


def test_gather_for_metrics_trims_remainder():
    acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=8))
    ds_len = 200  # 200 % 128 = 72
    class DS:
        def __len__(self):
            return ds_len
        def __getitem__(self, i):
            return {"y": np.int32(i)}
    dl = acc.prepare_data_loader(DataLoader(DS(), batch_size=16))
    seen = []
    for batch in dl:
        gathered = acc.gather_for_metrics(batch["y"])
        seen.extend(np.asarray(gathered).tolist())
    assert sorted(seen) == list(range(ds_len))


def test_accumulate_context_and_scheduler():
    acc = Accelerator(gradient_accumulation_steps=2)
    schedule = optax.linear_schedule(1.0, 0.0, 100)
    sched = acc.prepare_scheduler(schedule)
    sync_flags = []
    for i in range(4):
        with acc.accumulate():
            sync_flags.append(acc.sync_gradients)
            sched.step()
    assert sync_flags == [False, True, False, True]
    # stepped only on sync steps, num_devices x each
    assert sched._step_count == 2 * PartialState().num_devices


def test_trigger_roundtrip():
    acc = Accelerator()
    assert acc.check_trigger() is False
    acc.set_trigger()
    assert acc.check_trigger() is True
    assert acc.check_trigger() is False


def test_save_load_state_roundtrip(tmp_path):
    acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=8))
    params, opt, dl = acc.prepare(
        fresh_params(), optax.adam(1e-2), DataLoader(RegressionDS(), batch_size=8)
    )
    step = acc.prepare_train_step(loss_fn, opt)
    opt_state = opt.opt_state
    for batch in dl:
        params, opt_state, _ = step(params, opt_state, batch)
    opt.opt_state = opt_state
    saved_w = np.asarray(params["w"])
    out = acc.save_state(str(tmp_path / "ckpt"), params=params)
    # perturb, then load back (reference test_state_checkpointing pattern)
    perturbed = jax.tree_util.tree_map(lambda x: x * 0 + 1.0, params)
    restored = acc.load_state(out, params=perturbed)
    np.testing.assert_allclose(np.asarray(restored["w"]), saved_w)
    assert restored["w"].sharding.spec == perturbed["w"].sharding.spec
    # optimizer state round-trips
    mu = np.asarray(opt.opt_state[0].mu["w"])
    assert np.isfinite(mu).all()


def test_save_model_safetensors(tmp_path):
    pytest.importorskip("safetensors")
    acc = Accelerator()
    params = {"layer": {"kernel": np.ones((8, 4), np.float32)}}
    files = acc.save_model(params, str(tmp_path / "export"))
    assert any(f.endswith(".safetensors") for f in files)
    from accelerate_tpu.checkpointing import load_checkpoint_in_model

    loaded = load_checkpoint_in_model(
        {"layer": {"kernel": np.zeros((8, 4), np.float32)}}, str(tmp_path / "export")
    )
    np.testing.assert_allclose(loaded["layer"]["kernel"], params["layer"]["kernel"])


def test_checkpoint_rotation(tmp_path):
    from accelerate_tpu.utils.dataclasses import ProjectConfiguration

    acc = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(tmp_path), automatic_checkpoint_naming=True, total_limit=2
        )
    )
    params = {"w": np.zeros(4, np.float32)}
    import os

    for _ in range(4):
        acc.save_state(params=params)
    ckpts = sorted(os.listdir(tmp_path / "checkpoints"))
    assert ckpts == ["checkpoint_2", "checkpoint_3"]


def test_custom_object_checkpointing(tmp_path):
    class Counter:
        def __init__(self):
            self.n = 0
        def state_dict(self):
            return {"n": np.int64(self.n)}
        def load_state_dict(self, sd):
            self.n = int(sd["n"])

    acc = Accelerator()
    c = Counter()
    c.n = 7
    acc.register_for_checkpointing(c)
    out = acc.save_state(str(tmp_path / "ck"), params={"w": np.zeros(2, np.float32)})
    c.n = 0
    acc.load_state(out, params={"w": np.zeros(2, np.float32)})
    assert c.n == 7


def test_train_loop_on_sharded_mesh_with_dataloader():
    """prepare_train_loop over stacked SHARDED global batches (the bench hot
    path): FSDPxTP mesh, stack_batches of prepared-DataLoader output, loss
    falls, and state write-back stays live for checkpointing."""
    from accelerate_tpu.utils.operations import stack_batches

    AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
    acc = Accelerator(
        mixed_precision="bf16",
        parallelism_config=ParallelismConfig(dp_shard_size=4, tp_size=2),
    )
    params, opt, dl = acc.prepare(
        fresh_params(), optax.adam(1e-2),
        DataLoader(RegressionDS(), batch_size=4),
        shard_rules=ShardingRules([(r"w", P("dp_shard", "tp")), (r"b", P())]),
    )
    loop = acc.prepare_train_loop(loss_fn, opt)
    batches = list(dl)[:4]
    stacked = stack_batches(batches)
    p, s = params, opt.opt_state
    p, s, m1 = loop(p, s, stacked)
    p, s, m2 = loop(p, s, stacked)
    losses1 = np.asarray(m1["loss"]); losses2 = np.asarray(m2["loss"])
    assert losses1.shape == (4,)
    assert float(losses2[-1]) < float(losses1[0])
    assert opt.opt_state is s  # write-back for save_state
    # params stayed sharded through the scan
    assert p["w"].sharding.spec == P("dp_shard", "tp")
