"""Fleet goodput/badput ledger + perf-regression sentinel tests (ISSUE 17).

The acceptance lines these tests hold:

- every wall-clock second of a hand-authored run with KNOWN attribution
  (a chaos-killed generation; a serving run with failover re-prefills)
  lands in the right taxonomy bucket, with the remainder reported honestly
  as ``unattributed``;
- the report's restarts section and the ledger's restart stats are ONE
  computation (``goodput.restart_stats``) — they agree by construction;
- the rendered ``goodput`` report section is byte-deterministic (golden);
- the sentinel's verdict matrix: noise inside tolerance, regression and
  improvement outside it, hard bars, and the cross-environment REFUSAL
  (exit code 2, never a fake verdict);
- disabled path: with telemetry off the live meter is a no-op — no state,
  no files, no threads.
"""

import glob
import json
import os
import threading

import numpy as np
import pytest

from accelerate_tpu.telemetry import events as tel
from accelerate_tpu.telemetry import goodput, metrics, regress
from accelerate_tpu.telemetry.report import (
    build_report,
    format_goodput_section,
    format_report,
)

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")


@pytest.fixture(autouse=True)
def _clean_goodput_state():
    goodput._reset_for_tests()
    metrics.disable()
    tel.disable()
    yield
    goodput._reset_for_tests()
    metrics.disable()
    tel.disable()


# ---------------------------------------------------------------------------
# hand-authored fixtures with known attribution


def _training_events() -> "list[dict]":
    """A chaos-killed rank-0 stream: generation 0 does two steps (one
    carrying a 1.2s compile, one behind a 0.5s loader stall) and a 0.4s
    blocking checkpoint, then dies; generation 1 reruns clean. The
    supervisor measured 2.0s of downtime over a 2-process cohort."""
    rank = [
        # --- generation 0 (meta ordinal 0) ---
        {"kind": "meta", "process_index": 0, "t": 100.0},
        # first step starts at t0 exactly: no init time
        {"kind": "step", "t": 102.0, "dur_s": 2.0, "compile_s": 1.2,
         "execute_s": 0.8, "data_wait_s": 0.0},
        # starts at 102.5 — the 0.5s gap is the loader stall it drained
        {"kind": "step", "t": 103.5, "dur_s": 1.0, "compile_s": 0.0,
         "execute_s": 1.0, "data_wait_s": 0.5},
        {"kind": "checkpoint", "t": 104.0, "phase": "snapshot",
         "dur_s": 0.4, "hidden": False},
        {"kind": "checkpoint", "t": 104.0, "phase": "write",
         "dur_s": 0.3, "hidden": True},  # async writer time: NOT a stall
        # --- generation 1 (meta ordinal 1, post-restart) ---
        {"kind": "meta", "process_index": 0, "t": 110.0},
        {"kind": "step", "t": 111.0, "dur_s": 1.0, "compile_s": 0.0,
         "execute_s": 1.0, "data_wait_s": 0.0},
        {"kind": "step", "t": 112.0, "dur_s": 1.0, "compile_s": 0.0,
         "execute_s": 1.0, "data_wait_s": 0.0},
    ]
    sup = [
        {"kind": "meta", "role": "supervisor", "t": 100.0},
        {"kind": "restart", "t": 108.0, "generation": 1, "attempt": 1,
         "cause": "killed", "downtime_s": 2.0, "processes": 2},
    ]
    for e in rank:
        e["_file"] = "events-rank0.jsonl"
    for e in sup:
        e["_file"] = "events-supervisor.jsonl"
    return rank + sup


def _serving_events() -> "list[dict]":
    """A serving stream with every token-waste cause represented: a warmup,
    two engine steps (one carrying preemption re-prefills, one carrying a
    failover resume re-prefill), an evidenced idle gap, an abandoned
    request, a shed request, and a dropped KV handoff."""
    evs = [
        {"kind": "meta", "process_index": 0, "t": 200.0},
        {"kind": "serving", "phase": "warmup", "t": 201.0, "dur_s": 0.8},
        {"kind": "serving", "phase": "step", "t": 201.5, "dur_s": 0.4,
         "prefill_tokens": 100, "decode_tokens": 50,
         "preempt_reprefill_tokens": 20, "resume_reprefill_tokens": 0},
        {"kind": "serving", "phase": "idle", "t": 202.0, "dur_s": 0.5},
        {"kind": "serving", "phase": "step", "t": 202.5, "dur_s": 0.4,
         "prefill_tokens": 60, "decode_tokens": 40,
         "preempt_reprefill_tokens": 0, "resume_reprefill_tokens": 30},
        {"kind": "router", "phase": "request", "rid": "r1",
         "outcome": "finished", "prompt_tokens": 50, "new_tokens": 10},
        # dispatched (has a replica) then failed: its compute is abandoned
        {"kind": "router", "phase": "request", "rid": "r2",
         "outcome": "failed", "replica": "rep0",
         "prompt_tokens": 40, "new_tokens": 5},
        # shed before dispatch: zero compute wasted, counted separately
        {"kind": "router", "phase": "request", "rid": "r3",
         "outcome": "shed", "replica": None,
         "prompt_tokens": 30, "new_tokens": 0},
        {"kind": "kv_handoff", "rid": "r1", "outcome": "dropped",
         "t": 202.2, "blocks": 4},
    ]
    for e in evs:
        e["_file"] = "events-rank0.jsonl"
    return evs


class TestLedgerAttribution:
    def test_chaos_killed_training_run_attributes_every_cause(self):
        ledger = goodput.build_ledger(_training_events(), by_rank=True)
        # gen0 wall 4.0 + gen1 wall 2.0 + 2.0s downtime x 2 processes
        assert ledger["wall_s"] == pytest.approx(10.0)
        assert ledger["good_s"] == pytest.approx(3.8)  # 0.8 + 1.0 + 1.0 + 1.0
        assert ledger["goodput_fraction"] == pytest.approx(0.38)
        bad = ledger["badput_s"]
        assert bad["compile"] == pytest.approx(1.2)
        assert bad["data_wait"] == pytest.approx(0.5)  # charged to the gap
        assert bad["checkpoint_stall"] == pytest.approx(0.4)  # hidden excluded
        assert bad["restart_downtime"] == pytest.approx(4.0)  # chip-seconds
        assert ledger["top_badput"]["cause"] == "restart_downtime"
        assert ledger["top_badput"]["fraction"] == pytest.approx(0.4)
        # only the 0.1s the fixture deliberately leaves dark is unattributed
        assert ledger["unattributed_s"] == pytest.approx(0.1)
        assert ledger["unattributed_fraction"] < 0.05
        assert not ledger["overattributed"]

    def test_by_generation_attributes_downtime_to_the_generation_it_spawned(self):
        ledger = goodput.build_ledger(_training_events())
        gens = ledger["by_generation"]
        assert gens["0"]["restart_downtime_s"] == 0.0
        assert gens["0"]["good_s"] == pytest.approx(1.8)
        assert gens["1"]["restart_downtime_s"] == pytest.approx(4.0)
        assert gens["1"]["wall_s"] == pytest.approx(6.0)  # 2.0 run + 4.0 down

    def test_data_wait_is_charged_in_step_when_there_is_no_gap(self):
        """Back-to-back steps (no inter-step gap): the drained wait must come
        out of execute time, not inflate productive seconds."""
        evs = [
            {"kind": "meta", "process_index": 0, "t": 0.0,
             "_file": "events-rank0.jsonl"},
            {"kind": "step", "t": 1.0, "dur_s": 1.0, "compile_s": 0.0,
             "execute_s": 1.0, "data_wait_s": 0.0,
             "_file": "events-rank0.jsonl"},
            {"kind": "step", "t": 2.0, "dur_s": 1.0, "compile_s": 0.0,
             "execute_s": 1.0, "data_wait_s": 0.3,
             "_file": "events-rank0.jsonl"},
        ]
        ledger = goodput.build_ledger(evs)
        assert ledger["badput_s"]["data_wait"] == pytest.approx(0.3)
        assert ledger["good_s"] == pytest.approx(1.7)

    def test_cold_compile_is_distinguished_by_cache_evidence(self):
        evs = [
            {"kind": "meta", "process_index": 0, "t": 0.0,
             "_file": "events-rank0.jsonl"},
            {"kind": "compile_cache", "event": "miss", "t": 0.5,
             "_file": "events-rank0.jsonl"},
            {"kind": "step", "t": 2.0, "dur_s": 2.0, "compile_s": 1.5,
             "execute_s": 0.5, "data_wait_s": 0.0,
             "_file": "events-rank0.jsonl"},
        ]
        ledger = goodput.build_ledger(evs)
        assert ledger["badput_s"]["compile_cold"] == pytest.approx(1.5)
        assert "compile" not in ledger["badput_s"]

    def test_serving_run_attributes_wall_and_tokens(self):
        ledger = goodput.build_ledger(_serving_events())
        assert ledger["wall_s"] == pytest.approx(2.5)
        bad = ledger["badput_s"]
        assert bad["warmup"] == pytest.approx(0.8)
        assert bad["idle"] == pytest.approx(0.5)
        assert bad["init"] == pytest.approx(0.2)  # meta -> warmup start
        assert ledger["good_by_category"]["serving_execute"] == pytest.approx(0.8)
        tok = ledger["tokens"]
        assert tok["computed_tokens"] == 250
        waste = tok["waste_by_cause"]
        assert waste["preemption_reprefill"] == 20
        assert waste["failover_reprefill"] == 30
        assert waste["abandoned"] == 45  # r2: 40 prompt + 5 generated
        assert waste["handoff_rerun"] == 50  # r1's prompt re-prefilled
        assert tok["wasted_tokens"] == 145
        assert tok["useful_tokens"] == 105
        assert tok["token_goodput_fraction"] == pytest.approx(0.42)
        assert tok["shed_requests"] == 1
        assert tok["handoff_reruns"] == 1

    def test_no_evidence_means_no_ledger(self):
        assert goodput.build_ledger([]) is None
        # a supervisor-only stream has no rank wall-clock and no restarts
        sup = [{"kind": "meta", "role": "supervisor", "t": 0.0,
                "_file": "events-supervisor.jsonl"}]
        assert goodput.build_ledger(sup) is None

    def test_by_rank_skew(self):
        evs = _training_events()
        straggler = [
            {"kind": "meta", "process_index": 1, "t": 100.0},
            {"kind": "step", "t": 104.0, "dur_s": 4.0, "compile_s": 0.0,
             "execute_s": 4.0, "data_wait_s": 3.0},  # 3s behind the loader
        ]
        for e in straggler:
            e["_file"] = "events-rank1.jsonl"
        ledger = goodput.build_ledger(evs + straggler, by_rank=True)
        assert set(ledger["by_rank"]) == {"0", "1"}
        assert ledger["rank_skew"] > 0.3  # rank1 is mostly data_wait


class TestRestartStatsUnification:
    def test_report_restarts_and_ledger_agree_by_construction(self, tmp_path):
        """The satellite: ONE downtime/cause computation. The report's
        restarts section and the ledger's restart stats must be numerically
        identical on the same stream."""
        events = _training_events()
        for e in events:
            path = tmp_path / e.pop("_file")
            with open(path, "a") as f:
                f.write(json.dumps(e) + "\n")
        rep = build_report([str(tmp_path)])
        rs = rep["restarts"]
        gp = rep["goodput"]
        assert rs["count"] == gp["restarts"]["count"] == 1
        assert rs["downtime_s"] == gp["restarts"]["downtime_s"] == 2.0
        assert rs["causes"] == gp["restarts"]["causes"] == {"killed": 1}
        # and the ledger's fleet wall carries the chip-weighted variant
        assert gp["restarts"]["chip_downtime_s"] == 4.0
        text = format_report(rep)
        assert "restarts: 1 restart(s)" in text
        assert "goodput: goodput " in text


class TestGoodputSectionRender:
    def test_goodput_section_matches_golden(self):
        events = _training_events() + _serving_events()
        ledger = goodput.build_ledger(events, by_rank=True)
        section = format_goodput_section(ledger) + "\n"
        golden = open(os.path.join(GOLDEN, "goodput_report.txt")).read()
        assert section == golden


class TestServingRunAttribution:
    def test_router_driven_run_accounts_95_percent_of_wall(self, tmp_path):
        """The serving-side acceptance bar: a real router-driven run's event
        stream (warmup + steps + evidenced idle, all carrying dur_s) must
        leave <5% of wall-clock unattributed in the ledger."""
        import dataclasses

        from accelerate_tpu.models import LlamaConfig
        from accelerate_tpu.serving import (
            AdmissionController,
            LocalReplica,
            ReplicaSpec,
            RouterRequestStatus,
            ServingRouter,
        )

        tel.enable(out_dir=str(tmp_path), run_id="gp-serve")
        spec = ReplicaSpec(
            model=dataclasses.asdict(LlamaConfig.tiny()), num_blocks=33,
            block_size=8, max_slots=2, slot_buckets=(2,), block_buckets=(6,),
            prefill_buckets=(16,),
        )
        router = ServingRouter(
            [LocalReplica("r0", spec)],
            admission=AdmissionController(max_queue=8),
            health_timeout_s=300.0,
        )
        try:
            router.wait_ready(timeout_s=600)
            reqs = [
                router.submit(np.arange(1, 8, dtype=np.int32), 4, rng_seed=i)
                for i in range(3)
            ]
            router.run(timeout_s=600)
        finally:
            router.close()
        tel.disable()
        from accelerate_tpu.telemetry.report import load_events

        ledger = goodput.build_ledger(load_events([str(tmp_path)]))
        assert all(r.status is RouterRequestStatus.FINISHED for r in reqs)
        assert ledger is not None
        assert ledger["good_by_category"].get("serving_execute", 0.0) > 0
        assert ledger["unattributed_fraction"] < 0.05, ledger
        assert ledger["tokens"]["computed_tokens"] > 0


# ---------------------------------------------------------------------------
# live meter


class TestLiveMeter:
    def test_disabled_path_is_zero_cost(self, tmp_path):
        """Telemetry off: notes are dropped, nothing is emitted, no files or
        threads appear."""
        before = set(glob.glob(str(tmp_path / "*")))
        threads_before = threading.active_count()
        goodput.note("data_wait", 1.0)
        goodput.note_step(1.0, 0.5, 0.1)
        goodput.note_serving_step(0.3, computed_tokens=10, wasted_tokens=2)
        assert goodput.maybe_emit() is False
        assert goodput.emit_now() is None
        assert goodput._SECONDS == {}
        assert goodput._TOKENS == {"computed": 0, "wasted": 0}
        assert set(glob.glob(str(tmp_path / "*"))) == before
        assert threading.active_count() == threads_before

    def test_emit_now_writes_record_and_gauges(self, tmp_path):
        tel.enable(out_dir=str(tmp_path), run_id="gp")
        reg = metrics.enable()
        goodput.note_step(execute_s=2.0, compile_s=0.5, data_wait_s=0.5)
        goodput.note_serving_step(1.0, computed_tokens=100, wasted_tokens=25)
        goodput.note("checkpoint_stall", 0.25)
        fields = goodput.emit_now(final=True)
        tel.disable()
        assert fields["good_s"] == pytest.approx(2.5)  # 1.5 exec + 1.0 serve
        assert fields["badput_s"] == pytest.approx(1.25)
        assert fields["token_goodput_fraction"] == pytest.approx(0.75)
        assert fields["final"] is True
        recs = [json.loads(l) for l in open(tmp_path / "events-rank0.jsonl")]
        snaps = [r for r in recs if r["kind"] == "goodput"]
        assert len(snaps) == 1
        assert snaps[0]["by_category"]["checkpoint_stall"] == 0.25
        text = reg.render()
        assert metrics.GOODPUT_FRACTION_GAUGE in text
        assert metrics.TOKEN_GOODPUT_FRACTION_GAUGE in text
        assert metrics.BADPUT_SECONDS_GAUGE in text

    def test_maybe_emit_is_throttled(self, tmp_path):
        tel.enable(out_dir=str(tmp_path), run_id="gp")
        goodput.note("compile", 1.0)
        assert goodput.maybe_emit(now=1e9) is True
        assert goodput.maybe_emit(now=1e9 + 1.0) is False  # inside interval
        assert goodput.maybe_emit(now=1e9 + goodput._EMIT_INTERVAL_S + 1) is True
        tel.disable()


# ---------------------------------------------------------------------------
# perf-regression sentinel


def _payload(value=100.0, mfu=0.5, kind="cpu", count=1, **configs):
    return {
        "metric": "throughput", "value": value, "mfu": mfu,
        "env": {"device_kind": kind, "device_count": count, "jaxlib": "x"},
        "configs": {k: {"value": v} for k, v in configs.items()},
    }


class TestSentinelVerdicts:
    def test_noise_inside_tolerance(self):
        # cpu fingerprint doubles the 5% catch-all to 10%; -3% is noise
        vs = regress.compare_metrics(_payload(100.0), _payload(97.0))
        v = next(v for v in vs if v["metric"] == "throughput")
        assert v["verdict"] == regress.NOISE

    def test_regression_and_improvement_outside_tolerance(self):
        vs = regress.compare_metrics(_payload(100.0), _payload(80.0))
        v = next(v for v in vs if v["metric"] == "throughput")
        assert v["verdict"] == regress.REGRESSION
        vs = regress.compare_metrics(_payload(100.0), _payload(130.0))
        v = next(v for v in vs if v["metric"] == "throughput")
        assert v["verdict"] == regress.IMPROVED

    def test_lower_is_better_metrics_invert(self):
        base = _payload(100.0, ckpt_stall_s=1.0)
        cand = _payload(100.0, ckpt_stall_s=2.0)  # stall doubled: regression
        vs = regress.compare_metrics(base, cand)
        v = next(v for v in vs if v["metric"] == "configs.ckpt_stall_s")
        assert v["direction"] == "lower"
        assert v["verdict"] == regress.REGRESSION

    def test_dead_run_trips_the_hard_bar_even_vs_dead_baseline(self):
        base = {"metric": "x y", "value": 0.0,
                "env": {"device_kind": "cpu", "device_count": 1}}
        cand = {"metric": "x y", "value": 0.0,
                "env": {"device_kind": "cpu", "device_count": 1}}
        vs = regress.compare_metrics(base, cand)
        v = next(v for v in vs if v["metric"] == "headline")
        assert v["verdict"] == regress.REGRESSION
        assert "hard bar" in v["reason"]

    def test_cpu_noise_doubling(self):
        # -8% on a TPU fingerprint: past the 5% band -> REGRESSION;
        # the same delta on CPU sits inside the doubled 10% band -> NOISE
        tpu = regress.compare_metrics(
            _payload(100.0, kind="TPU v5"), _payload(92.0, kind="TPU v5"))
        cpu = regress.compare_metrics(_payload(100.0), _payload(92.0))
        assert next(v for v in tpu if v["metric"] == "throughput")["verdict"] \
            == regress.REGRESSION
        assert next(v for v in cpu if v["metric"] == "throughput")["verdict"] \
            == regress.NOISE

    def test_fingerprint_refusal(self):
        a = regress.fingerprint(_payload(kind="cpu"))
        b = regress.fingerprint(_payload(kind="TPU v5 lite"))
        assert not regress.comparable(a, b)
        # unknown on either side is also a refusal, never a guess
        assert not regress.comparable(a, {"device_kind": None})

    def test_fingerprint_falls_back_to_payload_device_fields(self):
        fp = regress.fingerprint({"device_kind": "TPU v4", "n_chips": 8})
        assert fp == {"device_kind": "TPU v4", "device_count": 8,
                      "jaxlib": None}


class TestSentinelCLI:
    def _write(self, tmp_path, name, payload):
        p = tmp_path / name
        p.write_text(json.dumps(payload))
        return str(p)

    def test_identical_payloads_exit_clean(self, tmp_path, capsys):
        a = self._write(tmp_path, "BENCH_r01.json", _payload(100.0))
        b = self._write(tmp_path, "BENCH_r02.json", _payload(100.0))
        assert regress.run_regress([a, b]) == 0
        assert "verdict: OK" in capsys.readouterr().out

    def test_synthetic_regression_exits_one_and_names_the_metric(
            self, tmp_path, capsys):
        self._write(tmp_path, "BENCH_r01.json", _payload(100.0))
        self._write(tmp_path, "BENCH_r02.json", _payload(80.0))  # -20% tok/s
        assert regress.run_regress([], scan=str(tmp_path)) == 1
        out = capsys.readouterr().out
        assert "REGRESSION" in out and "throughput" in out

    def test_cross_fingerprint_exits_two_with_refusal(self, tmp_path, capsys):
        a = self._write(tmp_path, "BENCH_r01.json", _payload(kind="cpu"))
        b = self._write(tmp_path, "BENCH_r02.json",
                        _payload(kind="TPU v5 lite", count=8))
        assert regress.run_regress([a, b]) == 2
        assert "REFUSING" in capsys.readouterr().out

    def test_driver_wrapper_payloads_unwrap(self, tmp_path):
        wrapped = {"n": 1, "cmd": "python bench.py", "rc": 0, "tail": "",
                   "parsed": _payload(50.0)}
        p = self._write(tmp_path, "BENCH_r03.json", wrapped)
        loaded = regress.load_payload(p)
        assert loaded["value"] == 50.0

    def test_scan_skips_unusable_payloads(self, tmp_path, capsys):
        (tmp_path / "BENCH_r01.json").write_text("not json at all")
        self._write(tmp_path, "BENCH_r02.json", _payload(100.0))
        self._write(tmp_path, "BENCH_r03.json", _payload(101.0))
        assert regress.run_regress([], scan=str(tmp_path)) == 0
        assert "skipping" in capsys.readouterr().out
