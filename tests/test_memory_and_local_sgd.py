"""Memory-retry utilities + LocalSGD (reference ``tests/test_memory_utils.py``
pattern: fake OOM-raising callables; LocalSGD convergence on the virtual mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.local_sgd import (
    LocalSGD,
    make_local_sgd_train_step,
    replicate_for_local_sgd,
    unstack_local_sgd,
)
from accelerate_tpu.utils.memory import (
    find_executable_batch_size,
    release_memory,
    should_reduce_batch_size,
)


class FakeOOM(RuntimeError):
    pass


class TestFindExecutableBatchSize:
    @pytest.mark.smoke
    def test_halves_until_fit(self):
        sizes = []

        @find_executable_batch_size(starting_batch_size=128)
        def train(batch_size):
            sizes.append(batch_size)
            if batch_size > 16:
                raise FakeOOM("RESOURCE_EXHAUSTED: out of memory allocating")
            return batch_size

        assert train() == 16
        assert sizes == [128, 64, 32, 16]

    def test_non_oom_errors_propagate(self):
        @find_executable_batch_size(starting_batch_size=8)
        def train(batch_size):
            raise ValueError("unrelated")

        with pytest.raises(ValueError, match="unrelated"):
            train()

    def test_reaching_zero_raises(self):
        @find_executable_batch_size(starting_batch_size=4)
        def train(batch_size):
            raise FakeOOM("OOM")

        with pytest.raises(RuntimeError, match="No executable batch size"):
            train()

    def test_signature_check(self):
        @find_executable_batch_size(starting_batch_size=4)
        def train(not_batch):
            return 1

        with pytest.raises(TypeError, match="batch_size"):
            train()

    def test_custom_reduce_fn(self):
        sizes = []

        @find_executable_batch_size(starting_batch_size=10, reduce_batch_size_fn=lambda b: b - 3)
        def train(batch_size):
            sizes.append(batch_size)
            if batch_size > 4:
                raise MemoryError()
            return batch_size

        assert train() == 4
        assert sizes == [10, 7, 4]

    def test_should_reduce_markers(self):
        assert should_reduce_batch_size(MemoryError())
        assert should_reduce_batch_size(RuntimeError("RESOURCE_EXHAUSTED: hbm"))
        assert not should_reduce_batch_size(ValueError("shape mismatch"))

    def test_release_memory(self):
        a, b = np.ones(4), np.ones(4)
        a, b = release_memory(a, b)
        assert a is None and b is None


class TestLocalSGDImperative:
    def test_single_process_noop(self):
        acc = Accelerator()
        params = {"w": jnp.ones((2,))}
        with LocalSGD(acc, model=params, local_sgd_steps=2) as ls:
            for _ in range(4):
                out = ls.step(params)
        assert out is params or np.allclose(np.asarray(out["w"]), 1.0)

    def test_sync_flag_restored(self):
        acc = Accelerator()
        with LocalSGD(acc, local_sgd_steps=2):
            pass
        assert acc.gradient_state.sync_gradients


class TestLocalSGDCompiled:
    def test_replicas_diverge_then_converge(self):
        pc = ParallelismConfig(dp_shard_size=8)
        acc = Accelerator(parallelism_config=pc)
        mesh = acc.mesh
        k = 4

        def loss_fn(params, batch):
            pred = batch["x"] @ params["w"]
            return jnp.mean((pred - batch["y"]) ** 2)

        opt = optax.sgd(0.1)
        params = {"w": jnp.zeros((4, 1))}
        opt_state = opt.init(params)
        params_stack = replicate_for_local_sgd(params, mesh)
        opt_stack = replicate_for_local_sgd(opt_state, mesh)

        step = make_local_sgd_train_step(loss_fn, opt, mesh, local_sgd_steps=k)

        rng = np.random.default_rng(0)
        w_true = rng.normal(size=(4, 1)).astype(np.float32)
        losses = []
        for i in range(2 * k):
            x = rng.normal(size=(16, 4)).astype(np.float32)
            batch = {"x": jnp.asarray(x), "y": jnp.asarray(x @ w_true)}
            params_stack, opt_stack, loss = step(params_stack, opt_stack, batch, i)
            losses.append(float(loss))
            ws = np.asarray(params_stack["w"])
            equal_across = all(np.allclose(ws[0], ws[j]) for j in range(1, 8))
            if (i + 1) % k == 0:
                assert equal_across, f"replicas should be averaged at step {i}"
            else:
                # each replica saw a different data shard → they drift
                assert not equal_across, f"replicas should differ at step {i}"
        assert losses[-1] < losses[0]

    def test_unstack(self):
        pc = ParallelismConfig(dp_shard_size=8)
        acc = Accelerator(parallelism_config=pc)
        stack = replicate_for_local_sgd({"w": jnp.arange(3.0)}, acc.mesh)
        one = unstack_local_sgd(stack)
        np.testing.assert_allclose(np.asarray(one["w"]), [0, 1, 2])
