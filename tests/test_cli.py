"""CLI tests (reference ``tests/test_cli.py``: runs accelerate {config,launch,env,
estimate} against config fixtures)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(*argv, **kw):
    env = kw.pop("env", None) or {**os.environ, "PYTHONPATH": REPO}
    return subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli", *argv],
        capture_output=True, text=True, env=env, timeout=300, **kw,
    )


class TestArrowKeyMenu:
    """reference ``commands/menu/`` counterpart: cursor-key selection with a
    numbered non-TTY fallback."""

    def test_key_decoding(self):
        import io

        from accelerate_tpu.commands.menu import _CANCEL, _DOWN, _ENTER, _UP, _read_key

        assert _read_key(io.StringIO("\x1b[A")) == _UP
        assert _read_key(io.StringIO("\x1b[B")) == _DOWN
        assert _read_key(io.StringIO("\r")) == _ENTER
        assert _read_key(io.StringIO("\n")) == _ENTER
        assert _read_key(io.StringIO("q")) == _CANCEL
        assert _read_key(io.StringIO("\x1b")) == _CANCEL  # bare Esc
        assert _read_key(io.StringIO("k")) == _UP
        assert _read_key(io.StringIO("j")) == _DOWN
        assert _read_key(io.StringIO("3")) == "3"
        assert _read_key(io.StringIO("")) == _CANCEL  # EOF
        assert _read_key(io.StringIO("x")) == ""  # ignored

    def test_cursor_arithmetic_wraps(self):
        from accelerate_tpu.commands.menu import _DOWN, _UP, _next_index

        assert _next_index(_DOWN, 0, 3) == 1
        assert _next_index(_DOWN, 2, 3) == 0  # wrap
        assert _next_index(_UP, 0, 3) == 2  # wrap
        assert _next_index("2", 0, 3) == 1  # digit jump (1-based)
        assert _next_index("9", 1, 3) == 1  # out of range: stay
        assert _next_index("", 1, 3) == 1

    def test_non_tty_fallback(self, monkeypatch):
        from accelerate_tpu.commands import menu

        monkeypatch.setattr("builtins.input", lambda *_: "2")
        assert menu.select("pick", ["a", "b", "c"]) == "b"
        monkeypatch.setattr("builtins.input", lambda *_: "")
        assert menu.select("pick", ["a", "b", "c"], default="c") == "c"
        monkeypatch.setattr("builtins.input", lambda *_: "nope")
        assert menu.select("pick", ["a", "b"], default="b") == "b"

    def test_arrow_keys_on_a_real_pty(self):
        """Down + Enter over a pty must select the second option — guards the
        buffered-stdin regression where an arrow press read as bare Esc."""
        import pty
        import time

        pid, fd = pty.fork()
        if pid == 0:  # child
            try:
                # pytest's capture machinery replaced sys.stdin/stdout with
                # non-tty objects; rebind them to the pty fds
                sys.stdin = os.fdopen(0, "r")
                sys.stdout = os.fdopen(1, "w", buffering=1)
                from accelerate_tpu.commands.menu import select

                choice = select("pick", ["alpha", "beta", "gamma"], default="alpha")
                os.write(1, f"CHOSEN={choice}".encode())
            except BaseException as e:  # surface child failures to the parent
                os.write(1, f"CHILD-ERROR {type(e).__name__}: {e}".encode())
            finally:
                os._exit(0)
        time.sleep(1.0)
        os.write(fd, b"\x1b[B")
        time.sleep(0.3)
        os.write(fd, b"\r")
        out = b""
        t0 = time.time()
        while time.time() - t0 < 15 and b"CHOSEN=" not in out:
            try:
                chunk = os.read(fd, 4096)
            except OSError:
                break
            if not chunk:
                break
            out += chunk
        os.waitpid(pid, 0)
        assert b"CHOSEN=beta" in out, out[-500:]

    def test_ask_with_choices_uses_fallback_off_tty(self, monkeypatch):
        from accelerate_tpu.commands.config import _ask

        monkeypatch.setattr("builtins.input", lambda *_: "")
        assert _ask("Mixed precision", "bf16", str, ("no", "bf16", "fp16")) == "bf16"


def test_estimate_memory_from_config_json(tmp_path):
    """Hub-style estimation (reference commands/estimate.py:316): architecture
    built on the meta device from a config.json alone — works offline on a
    local model directory, and on any Hub id when network exists."""
    import json as _json

    cfgdir = tmp_path / "tiny-bert"
    cfgdir.mkdir()
    (cfgdir / "config.json").write_text(_json.dumps({
        "model_type": "bert",
        "vocab_size": 128,
        "hidden_size": 32,
        "num_hidden_layers": 2,
        "num_attention_heads": 2,
        "intermediate_size": 64,
        "max_position_embeddings": 64,
    }))
    r = run_cli("estimate-memory", str(cfgdir), "--json")
    assert r.returncode == 0, r.stderr
    import json

    out = json.loads(r.stdout.strip().splitlines()[-1])
    n_f32 = out["float32"]["inference_bytes"]
    assert n_f32 > 0 and out["bfloat16"]["inference_bytes"] == n_f32 // 2
    assert out["float32"]["adam_training_bytes"] == n_f32 * 4
    # reference table's largest-layer column (device-map planning)
    assert 0 < out["float32"]["largest_layer_bytes"] <= n_f32


def test_estimate_memory_unreachable_hub_id_fails_cleanly():
    # HF_HUB_OFFLINE makes the failure deterministic and instant (no network
    # retry cycle in sandboxes where outbound traffic hangs)
    env = {**os.environ, "PYTHONPATH": REPO, "HF_HUB_OFFLINE": "1"}
    r = run_cli("estimate-memory", "no-such-org/no-such-model", env=env)
    assert r.returncode != 0
    assert "could not load a config" in (r.stderr + r.stdout)


def test_config_default_roundtrip(tmp_path):
    path = tmp_path / "cfg.yaml"
    r = run_cli("config", "--default", "--config_file", str(path))
    assert r.returncode == 0, r.stderr
    from accelerate_tpu.commands.config import ClusterConfig

    cfg = ClusterConfig.load(str(path))
    assert cfg.mixed_precision == "bf16"
    # all-1 mesh = "not configured" → launch emits no PARALLELISM_CONFIG_* and
    # the runtime default (pure DP) applies
    assert cfg.dp_shard_size == 1


def test_config_rejects_unknown_keys(tmp_path):
    path = tmp_path / "bad.yaml"
    path.write_text("mixed_precision: bf16\nnot_a_real_key: 3\n")
    from accelerate_tpu.commands.config import ClusterConfig

    with pytest.raises(ValueError, match="not_a_real_key"):
        ClusterConfig.load(str(path))


def test_env_probe_outcomes():
    """The env diagnostic's JAX probe must yield a single-line field for every
    outcome: healthy JSON, failed import, and a hung backend."""
    import subprocess as sp
    from types import SimpleNamespace
    from unittest.mock import patch

    from accelerate_tpu.commands.env import _probe_jax

    healthy = SimpleNamespace(
        returncode=0,
        # a stray structured-log line AFTER the blob must not be mistaken for it
        stdout='{"JAX version": "0.9", "JAX backend": "tpu"}\n{"level": "info"}\n42\n',
        stderr="",
    )
    with patch.object(sp, "run", return_value=healthy):
        assert _probe_jax()["JAX backend"] == "tpu"

    broken = SimpleNamespace(
        returncode=1, stdout="",
        stderr="Traceback ...\nModuleNotFoundError: No module named 'jax'\n",
    )
    with patch.object(sp, "run", return_value=broken):
        out = _probe_jax()["JAX"]
        assert out == "unavailable (ModuleNotFoundError: No module named 'jax')"
        assert "\n" not in out

    with patch.object(sp, "run", side_effect=sp.TimeoutExpired("cmd", 5)):
        assert "HUNG" in _probe_jax(timeout=5)["JAX"]


@pytest.mark.smoke
def test_env_command(monkeypatch):
    # keep the JAX backend probe short: on a hung TPU tunnel the killable
    # subprocess waits out its budget before reporting the outage
    monkeypatch.setenv("ACCELERATE_ENV_PROBE_TIMEOUT", "20")
    r = run_cli("env")
    assert r.returncode == 0, r.stderr
    assert "accelerate-tpu" in r.stdout
    assert "JAX" in r.stdout


def test_estimate_memory_builtin():
    r = run_cli("estimate-memory", "llama", "--json",
                "--hidden_size", "1024", "--num_layers", "4", "--num_heads", "8",
                "--vocab_size", "1000")
    assert r.returncode == 0, r.stderr
    sizes = json.loads(r.stdout.strip().splitlines()[-1])
    assert sizes["bfloat16"]["inference_bytes"] * 2 == sizes["float32"]["inference_bytes"]
    assert sizes["float32"]["adam_training_bytes"] == 4 * sizes["float32"]["inference_bytes"]


def test_estimate_memory_checkpoint_dir(tmp_path):
    np.savez(tmp_path / "model.npz", w=np.zeros((10, 10), np.float32))
    r = run_cli("estimate-memory", str(tmp_path), "--json")
    assert r.returncode == 0, r.stderr
    sizes = json.loads(r.stdout.strip().splitlines()[-1])
    assert sizes["float32"]["inference_bytes"] == 400


def test_merge_weights(tmp_path):
    # build a sharded safetensors dir in-process (CPU platform via conftest)
    from accelerate_tpu.checkpointing import save_model

    params = {"a": {"w": np.ones((64, 64), np.float32)},
              "b": {"w": np.full((32,), 7.0, np.float32)}}
    shard_dir = tmp_path / "shards"
    written = save_model(params, str(shard_dir), max_shard_size="10KB")
    assert len(written) > 1  # actually sharded
    out_dir = tmp_path / "merged"
    r = run_cli("merge-weights", str(shard_dir), str(out_dir))
    assert r.returncode == 0, r.stderr
    from safetensors.numpy import load_file

    merged = load_file(out_dir / "model.safetensors")
    np.testing.assert_allclose(merged["a/w"], np.ones((64, 64)))
    np.testing.assert_allclose(merged["b/w"], np.full((32,), 7.0))


def test_launch_env_protocol(tmp_path):
    """launch must write the env-var channel the runtime reads."""
    probe = tmp_path / "probe.py"
    probe.write_text(
        "import os, json\n"
        "print(json.dumps({k: v for k, v in os.environ.items()\n"
        "                  if k.startswith(('ACCELERATE_', 'PARALLELISM_'))}))\n"
    )
    r = run_cli("launch", "--cpu", "--num_processes", "4", "--mixed_precision", "bf16",
                "--dp_shard_size", "2", "--tp_size", "2",
                "--gradient_accumulation_steps", "3", "--debug", str(probe))
    assert r.returncode == 0, r.stderr
    env = json.loads(r.stdout.strip().splitlines()[-1])
    assert env["ACCELERATE_MIXED_PRECISION"] == "bf16"
    assert env["ACCELERATE_USE_CPU"] == "true"
    assert env["ACCELERATE_GRADIENT_ACCUMULATION_STEPS"] == "3"
    assert env["ACCELERATE_DEBUG_MODE"] == "true"
    assert env["PARALLELISM_CONFIG_DP_SHARD_SIZE"] == "2"
    assert env["PARALLELISM_CONFIG_TP_SIZE"] == "2"


def test_launch_module_mode(tmp_path):
    r = run_cli("launch", "--cpu", "-m", "json.tool", "--help")
    assert r.returncode == 0


@pytest.mark.slow
def test_bundled_test_script():
    r = run_cli("test", "--cpu", "--num_processes", "8")
    assert r.returncode == 0, r.stderr + r.stdout
    assert "All tests passed!" in r.stdout


def test_launch_no_mesh_flags_emits_no_parallelism_env(tmp_path):
    """A plain launch must not flip the runtime into FSDP (all-1 mesh = unset)."""
    probe = tmp_path / "probe.py"
    probe.write_text(
        "import os, json\n"
        "print(json.dumps([k for k in os.environ if k.startswith('PARALLELISM_')]))\n"
    )
    r = run_cli("launch", "--cpu", str(probe))
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout.strip().splitlines()[-1]) == []


def test_empty_config_file_is_defaults(tmp_path):
    path = tmp_path / "empty.yaml"
    path.write_text("# nothing here\n")
    from accelerate_tpu.commands.config import ClusterConfig

    cfg = ClusterConfig.load(str(path))
    assert cfg.mixed_precision == "bf16"


def test_tpu_pod_machine_rank_precedes_script(monkeypatch):
    """--machine_rank must be injected before the script positional, or argparse
    REMAINDER swallows it and every worker runs rank 0."""
    import accelerate_tpu.commands.launch as L

    captured = {}

    def fake_run(cmd, **kw):
        captured["cmd"] = cmd

        class R:
            returncode = 0

        return R()

    monkeypatch.setattr(L.subprocess, "run", fake_run)
    parser = L.launch_command_parser()
    args = parser.parse_args([
        "--tpu_pod", "--tpu_name", "t", "--num_machines", "2",
        "--main_process_ip", "10.0.0.2", "train.py", "--lr", "1e-3",
    ])
    L.launch_command(args)
    remote = next(a for a in captured["cmd"] if a.startswith("--command="))
    assert "--machine_rank=$RANK train.py" in remote
    # and the re-parsed inner command assigns the rank to launch, not the script
    inner = remote.split("; ", 1)[1].replace("$RANK", "3").split()
    assert inner[:2] == ["accelerate-tpu", "launch"]
    inner_args = parser.parse_args(inner[2:])
    assert inner_args.machine_rank == 3
    assert inner_args.training_script == "train.py"


def test_tpu_pod_restart_refans_whole_pod(monkeypatch):
    """Pod elastic restart re-runs the WHOLE fan-out (per-worker restart could
    not rejoin the running collective) and injects resume hints on retry."""
    import accelerate_tpu.commands.launch as L

    calls = []

    def fake_run(cmd, **kw):
        calls.append(cmd)

        class R:
            returncode = 1 if len(calls) == 1 else 0

        return R()

    monkeypatch.setattr(L.subprocess, "run", fake_run)
    parser = L.launch_command_parser()
    args = parser.parse_args([
        "--tpu_pod", "--tpu_name", "t", "--num_machines", "2",
        "--main_process_ip", "10.0.0.2", "--max_restarts", "2",
        "--monitor_interval", "0", "train.py",
    ])
    rc = L.launch_command(args)
    assert rc == 0
    assert len(calls) == 2
    first = next(a for a in calls[0] if a.startswith("--command="))
    second = next(a for a in calls[1] if a.startswith("--command="))
    assert "--max_restarts" not in first  # workers must NOT self-restart
    assert "ACCELERATE_RESUME_FROM_CHECKPOINT=latest" in second
    assert "ACCELERATE_RESTART_COUNT=1" in second


def test_launch_max_restarts_supervision(tmp_path):
    """Elastic supervision: the script fails on attempt 0, succeeds on attempt 1;
    the restart must carry ACCELERATE_RESTART_COUNT and the resume hint."""
    marker = tmp_path / "attempts.txt"
    script = tmp_path / "flaky.py"
    script.write_text(
        "import os, sys\n"
        f"marker = {str(marker)!r}\n"
        "count = int(os.environ['ACCELERATE_RESTART_COUNT'])\n"
        "with open(marker, 'a') as f:\n"
        "    f.write(f\"{count}:{os.environ.get('ACCELERATE_RESUME_FROM_CHECKPOINT', '')}\\n\")\n"
        "sys.exit(1 if count == 0 else 0)\n"
    )
    r = run_cli("launch", "--cpu", "--max_restarts", "2", "--monitor_interval", "0",
                str(script))
    assert r.returncode == 0, r.stderr
    lines = marker.read_text().strip().splitlines()
    assert lines == ["0:", "1:latest"], lines


def test_launch_max_restarts_exhausted(tmp_path):
    script = tmp_path / "always_fails.py"
    script.write_text("import sys; sys.exit(3)\n")
    r = run_cli("launch", "--cpu", "--max_restarts", "1", "--monitor_interval", "0",
                str(script))
    assert r.returncode == 3
    assert "restart 1/1" in r.stderr


def test_tpu_pod_fanout_executes_through_real_transport(tmp_path, monkeypatch):
    """Mock-TRANSPORT pod fan-out (VERDICT r04 weak item 6: the SSH path was
    only ever tested via monkeypatched argv assembly). A fake `gcloud`
    executable on PATH records every invocation and fails the first fan-out,
    so this exercises the REAL subprocess boundary: PATH resolution, argv
    quoting survival, rc propagation, and the whole-pod elastic re-fan-out
    with resume hints."""
    import accelerate_tpu.commands.launch as L

    log = tmp_path / "gcloud_calls.log"
    state = tmp_path / "gcloud_state"
    fake = tmp_path / "bin" / "gcloud"
    fake.parent.mkdir()
    fake.write_text(
        "#!/bin/bash\n"
        # one argv per line, NUL-free; %q survives embedded quotes/spaces
        f'printf "%q " "$@" >> "{log}"; echo >> "{log}"\n'
        f'if [ ! -f "{state}" ]; then touch "{state}"; exit 17; fi\n'  # fail 1st
        "exit 0\n"
    )
    fake.chmod(0o755)
    monkeypatch.setenv("PATH", f"{fake.parent}:{os.environ['PATH']}")

    parser = L.launch_command_parser()
    args = parser.parse_args([
        "--tpu_pod", "--tpu_name", "pod-1", "--tpu_zone", "us-central2-b",
        "--num_machines", "4", "--main_process_ip", "10.0.0.2",
        "--max_restarts", "2", "--monitor_interval", "0",
        "train.py", "--lr", "1e-3",
    ])
    rc = L.launch_command(args)
    assert rc == 0
    calls = [line for line in log.read_text().splitlines() if line.strip()]
    assert len(calls) == 2  # first fan-out failed (rc 17), one re-fan-out
    first, second = calls
    for call in (first, second):
        assert "compute tpus tpu-vm ssh pod-1" in call.replace("\\", "")
        assert "--worker=all" in call
        assert "--zone=us-central2-b" in call
        assert "machine_rank" in call and "train.py" in call
        assert "agent-worker-number" in call  # metadata-server rank probe
    assert "ACCELERATE_RESTART_COUNT=1" not in first
    assert "ACCELERATE_RESTART_COUNT=1" in second  # resume hint on retry only
    assert "ACCELERATE_RESUME_FROM_CHECKPOINT=latest" in second


def test_to_fsdp2_is_an_explained_noop(capsys):
    """The reference's to-fsdp2 config migrator has nothing to migrate here
    (FSDP1/2 collapse under GSPMD); the subcommand exists and says so instead
    of being an unknown command."""
    from accelerate_tpu.commands.accelerate_cli import main

    import sys as _sys

    old = _sys.argv
    _sys.argv = ["accelerate-tpu", "to-fsdp2", "--config_file", "x.yaml"]
    try:
        with pytest.raises(SystemExit) as e:
            main()
        assert e.value.code == 0
    finally:
        _sys.argv = old
    out = capsys.readouterr().out
    assert "collapse" in out and "fsdp_gspmd" in out
