"""Pipeline (GPipe over pp axis) + expert-parallel MoE tests on the virtual mesh.

Parity model: reference ``tests`` exercise PiPPy via subprocess launches
(``examples/inference/pippy``); here pipelined vs sequential execution is
asserted numerically in-process, including gradients (which the reference's
inference-only PP cannot do at all).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.parallel.moe import init_moe_ffn, moe_ffn, moe_shard_rules
from accelerate_tpu.parallel.pipeline import (
    make_pipeline_forward,
    merge_microbatches,
    split_into_stages,
    split_microbatches,
)


def make_layers(n_layers, d, key):
    keys = jax.random.split(key, n_layers)
    return [
        {"w": jax.random.normal(k, (d, d)) / np.sqrt(d), "b": jnp.zeros((d,))} for k in keys
    ]


def stage_fn(stage_params, x):
    """One pipeline stage: scan over its slice of layers."""

    def layer(x, p):
        return jnp.tanh(x @ p["w"] + p["b"]), None

    out, _ = jax.lax.scan(layer, x, stage_params)
    return out


def sequential_forward(layers, x):
    for p in layers:
        x = jnp.tanh(x @ p["w"] + p["b"])
    return x


class TestMicrobatching:
    def test_split_merge_roundtrip(self):
        batch = {"x": jnp.arange(24.0).reshape(12, 2)}
        split = split_microbatches(batch, 4)
        assert split["x"].shape == (4, 3, 2)
        merged = merge_microbatches(split)
        np.testing.assert_array_equal(np.asarray(merged["x"]), np.asarray(batch["x"]))

    def test_indivisible_raises(self):
        with pytest.raises(ValueError):
            split_microbatches(jnp.zeros((10, 2)), 4)

    def test_split_into_stages(self):
        layers = make_layers(8, 4, jax.random.PRNGKey(0))
        stacked = split_into_stages(layers, 4)
        assert stacked["w"].shape == (4, 2, 4, 4)
        with pytest.raises(ValueError):
            split_into_stages(layers, 3)


class TestPipelineForward:
    @pytest.mark.parametrize("pp,n_layers,micro", [(2, 4, 4), (4, 8, 8), (8, 8, 4)])
    def test_matches_sequential(self, pp, n_layers, micro):
        pc = ParallelismConfig(pp_size=pp, dp_shard_size=8 // pp)
        acc = Accelerator(parallelism_config=pc)
        d, B = 8, 16
        layers = make_layers(n_layers, d, jax.random.PRNGKey(0))
        stacked = split_into_stages(layers, pp)
        x = jax.random.normal(jax.random.PRNGKey(1), (B, d))

        fwd = make_pipeline_forward(stage_fn, acc.mesh, num_microbatches=micro)
        out = jax.jit(fwd)(stacked, x)
        expected = sequential_forward(layers, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-5)

    def test_trivial_single_stage(self):
        pc = ParallelismConfig(dp_shard_size=8)
        acc = Accelerator(parallelism_config=pc)
        layers = make_layers(4, 8, jax.random.PRNGKey(0))
        stacked = split_into_stages(layers, 1)
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 8))
        fwd = make_pipeline_forward(stage_fn, acc.mesh, num_microbatches=2)
        np.testing.assert_allclose(
            np.asarray(fwd(stacked, x)), np.asarray(sequential_forward(layers, x)), rtol=1e-5
        )

    def test_gradients_flow_through_pipeline(self):
        """Training through the pipeline: grads match the sequential model."""
        pp, n_layers, micro = 2, 4, 2
        pc = ParallelismConfig(pp_size=pp, dp_shard_size=4)
        acc = Accelerator(parallelism_config=pc)
        d, B = 4, 8
        layers = make_layers(n_layers, d, jax.random.PRNGKey(0))
        stacked = split_into_stages(layers, pp)
        x = jax.random.normal(jax.random.PRNGKey(1), (B, d))

        fwd = make_pipeline_forward(stage_fn, acc.mesh, num_microbatches=micro)

        def loss_pipe(sp):
            return jnp.mean(fwd(sp, x) ** 2)

        def loss_seq(ls):
            return jnp.mean(sequential_forward(ls, x) ** 2)

        g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
        g_seq = jax.grad(loss_seq)(layers)
        g_seq_stacked = split_into_stages(g_seq, pp)
        np.testing.assert_allclose(
            np.asarray(g_pipe["w"]), np.asarray(g_seq_stacked["w"]), rtol=1e-4, atol=1e-5
        )


class TestMoE:
    def test_output_shape_and_aux(self):
        params = init_moe_ffn(jax.random.PRNGKey(0), d_model=8, d_ff=16, num_experts=4)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 8))
        y, aux = moe_ffn(params, x, top_k=2, capacity_factor=2.0)
        assert y.shape == x.shape
        assert np.isfinite(float(aux))
        # balanced router at init → aux loss near 1 (E * sum(1/E * 1/E) * E = 1)
        assert 0.5 < float(aux) < 2.0

    def test_ample_capacity_matches_dense_topk(self):
        """With capacity >= N every token is routed; y = Σ_k gate_k · expert_k(x)."""
        E, D, F = 4, 8, 16
        params = init_moe_ffn(jax.random.PRNGKey(0), D, F, E)
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 6, D))
        y, _ = moe_ffn(params, x, top_k=2, capacity_factor=float(E))  # capacity = N*2

        logits = np.asarray(x.reshape(-1, D) @ params["router"]["kernel"])
        probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
        order = np.argsort(-probs, axis=-1)[:, :2]
        expected = np.zeros((6, D), np.float32)
        for n in range(6):
            g = probs[n, order[n]]
            g = g / g.sum()
            for k in range(2):
                e = order[n, k]
                h = np.asarray(
                    jax.nn.gelu(np.asarray(x.reshape(-1, D))[n] @ params["wi"]["kernel"][e])
                )
                expected[n] += g[k] * (h @ params["wo"]["kernel"][e])
        np.testing.assert_allclose(np.asarray(y.reshape(-1, D)), expected, rtol=1e-4, atol=1e-4)

    def test_capacity_drops_tokens(self):
        params = init_moe_ffn(jax.random.PRNGKey(0), 8, 16, 2)
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 16, 8))
        y_small, _ = moe_ffn(params, x, top_k=1, capacity_factor=0.25)
        y_big, _ = moe_ffn(params, x, top_k=1, capacity_factor=4.0)
        # tighter capacity must change (zero-out) some outputs
        assert not np.allclose(np.asarray(y_small), np.asarray(y_big))

    @pytest.mark.slow
    def test_ep_sharded_matches_unsharded(self):
        pc = ParallelismConfig(ep_size=8)
        acc = Accelerator(parallelism_config=pc)
        params = init_moe_ffn(jax.random.PRNGKey(0), 8, 16, 8)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 8))
        y_ref, aux_ref = moe_ffn(params, x, top_k=2, capacity_factor=2.0)

        sharded = acc.prepare(params, shard_rules=moe_shard_rules())

        @jax.jit
        def f(p, x):
            return moe_ffn(p, x, top_k=2, capacity_factor=2.0, mesh=acc.mesh)

        y, aux = f(sharded, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)

    def test_gradients(self):
        params = init_moe_ffn(jax.random.PRNGKey(0), 8, 16, 4)
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 8))

        def loss(p):
            y, aux = moe_ffn(p, x, top_k=2, capacity_factor=2.0)
            return jnp.mean(y**2) + 0.01 * aux

        grads = jax.grad(loss)(params)
        for leaf in jax.tree_util.tree_leaves(grads):
            assert np.isfinite(np.asarray(leaf)).all()
        # router must receive gradient through the combine weights
        assert float(jnp.abs(grads["router"]["kernel"]).sum()) > 0


class Test1F1B:
    """1F1B training schedule vs GPipe-forward + autodiff: identical loss and
    gradients, strictly smaller compiled temp memory at large M."""

    def _setup(self, pp=4, n_layers=8, micro=8, d=8, bs=16):
        from accelerate_tpu.parallel.pipeline import make_pipeline_train_step_1f1b

        acc = Accelerator(parallelism_config=ParallelismConfig(pp_size=pp, dp_shard_size=8 // pp), cpu=True)
        layers = make_layers(n_layers, d, jax.random.PRNGKey(0))
        stages = split_into_stages(layers, pp)
        x = jax.random.normal(jax.random.PRNGKey(1), (bs, d))
        targets = jax.random.normal(jax.random.PRNGKey(2), (bs, d))

        def loss_fn(y, t):
            return jnp.mean((y - t) ** 2)

        step = make_pipeline_train_step_1f1b(
            stage_fn, loss_fn, acc.mesh, num_microbatches=micro
        )
        return acc, layers, stages, x, targets, loss_fn, step

    @pytest.mark.parametrize("pp,micro", [(2, 6), (4, 24), (2, 3), (4, 6)])
    def test_grads_match_single_device_autodiff_uneven_microbatches(self, pp, micro):
        """M >> pp (steady-state 1F1B interleave) and M NOT a multiple of pp
        ((2,3), (4,6)): gradients must equal single-device autodiff exactly."""
        from accelerate_tpu.parallel.pipeline import make_pipeline_train_step_1f1b

        acc = Accelerator(
            parallelism_config=ParallelismConfig(pp_size=pp, dp_shard_size=8 // pp), cpu=True
        )
        d, bs = 8, 24
        layers = make_layers(8, d, jax.random.PRNGKey(0))
        stages = split_into_stages(layers, pp)
        x = jax.random.normal(jax.random.PRNGKey(1), (bs, d))
        targets = jax.random.normal(jax.random.PRNGKey(2), (bs, d))

        def loss_fn(y, t):
            return jnp.mean((y - t) ** 2)

        full = jax.tree_util.tree_map(
            lambda s: s.reshape((-1,) + s.shape[2:]), split_into_stages(layers, 1)
        )

        def full_loss(stack):
            return loss_fn(stage_fn(stack, x), targets)

        ref_grads = jax.grad(full_loss)(full)
        step = make_pipeline_train_step_1f1b(stage_fn, loss_fn, acc.mesh, num_microbatches=micro)
        loss, grads = step(stages, x, targets)
        assert abs(float(loss) - float(full_loss(full))) < 1e-5
        for g, r in zip(jax.tree_util.tree_leaves(grads), jax.tree_util.tree_leaves(ref_grads)):
            np.testing.assert_allclose(
                np.asarray(g).reshape(np.asarray(r).shape), np.asarray(r), atol=1e-5
            )

    def test_loss_and_grads_match_gpipe_autodiff(self):
        acc, layers, stages, x, targets, loss_fn, step = self._setup()
        micro = 8

        loss_1f1b, grads_1f1b = step(stages, x, targets)

        # reference: GPipe forward + jax.grad straight through the schedule,
        # with the same per-microbatch mean-loss weighting
        fwd = make_pipeline_forward(stage_fn, acc.mesh, num_microbatches=micro)

        def gpipe_loss(stages, x, t):
            y = fwd(stages, x)
            ym = split_microbatches(y, micro)
            tm = split_microbatches(t, micro)
            return jnp.mean(jax.vmap(loss_fn)(ym, tm))

        loss_ref, grads_ref = jax.jit(jax.value_and_grad(gpipe_loss))(stages, x, targets)
        assert abs(float(loss_1f1b) - float(loss_ref)) < 1e-5, (loss_1f1b, loss_ref)
        for a, b in zip(
            jax.tree_util.tree_leaves(grads_1f1b), jax.tree_util.tree_leaves(grads_ref)
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    def test_single_stage_degenerates(self):
        from accelerate_tpu.parallel.pipeline import make_pipeline_train_step_1f1b

        acc = Accelerator(cpu=True)  # pp absent → 1
        layers = make_layers(4, 8, jax.random.PRNGKey(0))
        stages = split_into_stages(layers, 1)
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 8))
        t = jax.random.normal(jax.random.PRNGKey(2), (8, 8))

        def loss_fn(y, tt):
            return jnp.mean((y - tt) ** 2)

        step = make_pipeline_train_step_1f1b(stage_fn, loss_fn, acc.mesh, num_microbatches=4)
        loss, grads = step(stages, x, t)
        ref = jnp.mean((sequential_forward(layers, x) - t) ** 2)
        assert abs(float(loss) - float(ref)) < 1e-5

    def test_memory_smaller_than_gpipe(self):
        """The point of 1F1B: compiled temp memory stays bounded by the
        pipeline depth, not the microbatch count."""
        from accelerate_tpu.parallel.pipeline import make_pipeline_train_step_1f1b

        pp, micro, d, bs = 4, 32, 64, 128
        acc = Accelerator(parallelism_config=ParallelismConfig(pp_size=pp, dp_shard_size=8 // pp), cpu=True)
        layers = make_layers(8, d, jax.random.PRNGKey(0))
        stages = split_into_stages(layers, pp)
        x = jax.random.normal(jax.random.PRNGKey(1), (bs, d))
        targets = jax.random.normal(jax.random.PRNGKey(2), (bs, d))

        def loss_fn(y, t):
            return jnp.mean((y - t) ** 2)

        step = make_pipeline_train_step_1f1b(stage_fn, loss_fn, acc.mesh, num_microbatches=micro)
        fwd = make_pipeline_forward(stage_fn, acc.mesh, num_microbatches=micro)

        def gpipe_loss(stages, x, t):
            y = fwd(stages, x)
            ym = split_microbatches(y, micro)
            tm = split_microbatches(t, micro)
            return jnp.mean(jax.vmap(loss_fn)(ym, tm))

        lowered_1f1b = jax.jit(step).lower(stages, x, targets).compile()
        lowered_gpipe = jax.jit(jax.value_and_grad(gpipe_loss)).lower(stages, x, targets).compile()
        mem_1f1b = lowered_1f1b.memory_analysis().temp_size_in_bytes
        mem_gpipe = lowered_gpipe.memory_analysis().temp_size_in_bytes
        assert mem_1f1b < mem_gpipe, (mem_1f1b, mem_gpipe)


@pytest.mark.slow
class TestMoEInModel:
    """MoE wired into the Llama family (LlamaConfig.moe_experts > 0)."""

    def _cfg(self):
        import dataclasses

        from accelerate_tpu.models import LlamaConfig

        return dataclasses.replace(
            LlamaConfig.tiny(), moe_experts=4, n_layers=2, unroll_layers=False
        )

    def test_moe_llama_trains(self):
        import optax

        from accelerate_tpu.models import init_llama, llama_loss

        cfg = self._cfg()
        params = init_llama(cfg, jax.random.PRNGKey(0))
        assert params["layers"]["moe"]["wi"]["kernel"].shape[:2] == (2, 4)
        rng = np.random.default_rng(0)
        ids = np.tile(rng.integers(2, cfg.vocab_size, (8, 4)).astype(np.int32), (1, 16))
        batch = {"input_ids": jnp.asarray(ids)}
        opt = optax.adam(3e-3)
        s = opt.init(params)

        @jax.jit
        def step(p, s):
            l, g = jax.value_and_grad(lambda p: llama_loss(p, batch, cfg))(p)
            u, s = opt.update(g, s, p)
            return optax.apply_updates(p, u), s, l

        params, s, l = step(params, s)
        first = float(l)
        for _ in range(40):
            params, s, l = step(params, s)
        assert float(l) < first * 0.5, (first, float(l))

    def test_moe_llama_ep_sharded_step(self):
        import optax

        from accelerate_tpu import Accelerator, ParallelismConfig
        from accelerate_tpu.models import init_llama, llama_loss, llama_shard_rules
        from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

        AcceleratorState._reset_state(); GradientState._reset_state(); PartialState._reset_state()
        pc = ParallelismConfig(dp_shard_size=2, ep_size=2, tp_size=2)
        acc = Accelerator(parallelism_config=pc, rng_seed=0)
        cfg = self._cfg()
        params = init_llama(cfg, jax.random.PRNGKey(0))
        params, opt = acc.prepare(params, optax.adam(1e-3), shard_rules=llama_shard_rules())
        # experts sharded over ep, expert matmuls over tp
        spec = params["layers"]["moe"]["wi"]["kernel"].sharding.spec
        assert spec[1] == "ep" and spec[3] == "tp", spec
        step = acc.prepare_train_step(lambda p, b: llama_loss(p, b, cfg), opt)
        ids = np.tile(np.random.default_rng(0).integers(2, cfg.vocab_size, (8, 4)).astype(np.int32), (1, 16))
        batch = {"input_ids": jnp.asarray(ids)}
        s = opt.opt_state
        p, s, m1 = step(params, s, batch)
        p, s, m2 = step(p, s, batch)
        assert float(m2["loss"]) < float(m1["loss"])
