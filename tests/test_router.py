"""Fault-tolerant serving router tests (ISSUE 12).

The acceptance lines these tests hold:

- **no lost or duplicated requests**: a replica SIGKILLed or wedged forever
  mid-decode loses nothing — every admitted request completes EXACTLY once,
  with tokens bitwise-equal to the single-stream ``greedy_generate``
  reference (failover resumes from the streamed ``generated``-so-far via the
  scheduler's preempt/resume state, so the retry is token-exact);
- **graceful overload**: the token bucket and bounded priority queues shed
  with a distinct ``SHED`` status (by priority: batch displaced before
  interactive), deadlines expire queued work instead of decoding it late,
  and the router never wedges — it fails requests loudly when no replica
  can ever run them.

Host-side dispatch/health/failover logic runs against in-test FakeReplicas
(microseconds); the token-exact failover line runs against real
thread-backed engines in tier-1 and against real subprocess replicas with
real SIGKILL / wedge-forever chaos in the slow-marked e2e.
"""

import dataclasses
import json
import time
from types import SimpleNamespace

import numpy as np
import pytest

from accelerate_tpu.generation import greedy_generate
from accelerate_tpu.models import LlamaConfig
from accelerate_tpu.resilience import chaos
from accelerate_tpu.resilience.chaos import ChaosFaultError, ChaosSchedule, Fault
from accelerate_tpu.serving import (
    PRIORITY_BATCH,
    PRIORITY_INTERACTIVE,
    AdmissionController,
    CanaryGolden,
    CanaryProbe,
    LocalReplica,
    ProcessReplica,
    ReplicaSpec,
    ReplicaState,
    RouterRequestStatus,
    ServingRouter,
    TokenBucket,
    precompute_goldens,
)

CONFIG = LlamaConfig.tiny()


def _spec(**kw) -> ReplicaSpec:
    base = dict(
        model=dataclasses.asdict(CONFIG), num_blocks=33, block_size=8,
        max_slots=2, slot_buckets=(2,), block_buckets=(4,), prefill_buckets=(32,),
    )
    base.update(kw)
    return ReplicaSpec(**base)


def _prompts(seed, lengths):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, CONFIG.vocab_size, (n,)).astype(np.int32) for n in lengths]


class FakeReplica:
    """Scriptable replica: the router's dispatch/health/failover logic under
    test without paying an engine."""

    transport = "fake"

    def __init__(self, name, max_slots=4):
        self.name = name
        self.state = ReplicaState.HEALTHY
        self.spec = SimpleNamespace(max_slots=max_slots)
        self.submitted = []
        self._events = []
        self._alive = True

    def submit(self, payload):
        self.submitted.append(payload)

    def drain_events(self):
        ev, self._events = self._events, []
        return ev

    def alive(self):
        return self._alive

    def kill(self):
        self._alive = False

    def stop(self):
        pass

    def close(self, timeout=0.0):
        self._alive = False

    # test helpers
    def push(self, **ev):
        self._events.append(ev)

    def die(self):
        self._alive = False


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# admission control


@pytest.mark.smoke
def test_token_bucket_refill_and_all_or_nothing():
    clock = FakeClock()
    bucket = TokenBucket(rate_per_s=10.0, burst=30.0, clock=clock)
    assert bucket.take(30)  # starts full
    assert not bucket.take(1)  # empty, all-or-nothing
    clock.t += 2.0  # +20 tokens
    assert bucket.available() == pytest.approx(20.0)
    assert not bucket.take(25)
    assert bucket.take(20)
    clock.t += 100.0  # refill caps at burst
    assert bucket.available() == pytest.approx(30.0)
    with pytest.raises(ValueError):
        TokenBucket(rate_per_s=0, burst=10)


def test_admission_priority_order_and_requeue_front():
    ctl = AdmissionController(max_queue=8, clock=FakeClock())
    reqs = [SimpleNamespace(priority=p, rid=i) for i, p in enumerate([1, 0, 1, 0])]
    for r in reqs:
        assert ctl.try_admit(r, cost=1).admitted
    # interactive (0) drains before batch (1), FIFO within a class
    assert [r.rid for r in ctl.queued()] == [1, 3, 0, 2]
    popped = ctl.pop_next()
    assert popped.rid == 1
    # a failover requeue goes back to the FRONT of its class
    ctl.requeue_front(popped)
    assert [ctl.pop_next().rid for _ in range(4)] == [1, 3, 0, 2]
    assert ctl.pop_next() is None


def test_admission_queue_full_sheds_lowest_priority():
    ctl = AdmissionController(max_queue=2, clock=FakeClock())
    b1 = SimpleNamespace(priority=PRIORITY_BATCH, rid="b1")
    b2 = SimpleNamespace(priority=PRIORITY_BATCH, rid="b2")
    assert ctl.try_admit(b1, 1).admitted and ctl.try_admit(b2, 1).admitted
    # an interactive newcomer displaces the most recent batch request...
    hi = SimpleNamespace(priority=PRIORITY_INTERACTIVE, rid="hi")
    verdict = ctl.try_admit(hi, 1)
    assert verdict.admitted and [v.rid for v in verdict.evicted] == ["b2"]
    # ...but a batch newcomer cannot displace its own class or better
    b3 = SimpleNamespace(priority=PRIORITY_BATCH, rid="b3")
    verdict = ctl.try_admit(b3, 1)
    assert not verdict.admitted and verdict.reason == "queue-full"
    assert ctl.depth == 2 and ctl.depth_by_priority() == {0: 1, 1: 1}


def test_admission_never_evicts_failover_requeues():
    """A failover re-queue (retries > 0) is ALREADY-ADMITTED, partially
    decoded work: priority eviction must pass over it — shedding it would
    lose a request the router promised to finish — and fall back to the
    newest never-dispatched victim, or shed the newcomer."""
    ctl = AdmissionController(max_queue=2, clock=FakeClock())
    fresh = SimpleNamespace(priority=PRIORITY_BATCH, rid="fresh", retries=0)
    resumed = SimpleNamespace(priority=PRIORITY_BATCH, rid="resumed", retries=1)
    assert ctl.try_admit(fresh, 1).admitted
    ctl.requeue_front(resumed)
    # the newest batch entry is `fresh`... but even if the requeue were
    # newest, it must be skipped: evict `fresh`, the only retries==0 victim
    hi = SimpleNamespace(priority=PRIORITY_INTERACTIVE, rid="hi")
    verdict = ctl.try_admit(hi, 1)
    assert verdict.admitted and [v.rid for v in verdict.evicted] == ["fresh"]
    # queue now holds only the resumed request below interactive: a second
    # interactive newcomer finds NO evictable victim and is shed itself
    hi2 = SimpleNamespace(priority=PRIORITY_INTERACTIVE, rid="hi2")
    verdict = ctl.try_admit(hi2, 1)
    assert not verdict.admitted and verdict.reason == "queue-full"
    assert resumed in ctl.queued()  # the admitted work survived overload


# ---------------------------------------------------------------------------
# router: shed / deadline / dispatch (FakeReplica, host-only)


def test_router_sheds_with_distinct_status_and_reports(tmp_path):
    from accelerate_tpu.telemetry import events as tel
    from accelerate_tpu.telemetry.report import build_report, format_report

    clock = FakeClock()
    # replicas still warming: nothing dispatches, the queues fill honestly
    rep = FakeReplica("r0")
    rep.state = ReplicaState.STARTING
    tel.enable(out_dir=str(tmp_path), run_id="router-shed")
    try:
        router = ServingRouter(
            [rep],
            admission=AdmissionController(
                max_queue=2, rate_tokens_per_s=10.0, burst_tokens=40.0, clock=clock
            ),
            clock=clock,
        )
        prompt = np.arange(4, dtype=np.int32) + 1
        ok1 = router.submit(prompt, 8, priority=PRIORITY_BATCH)  # cost 12
        ok2 = router.submit(prompt, 8, priority=PRIORITY_BATCH)  # cost 12
        # bucket now holds 16: a 20-cost request is rate-shed
        rate_shed = router.submit(prompt, 16, priority=PRIORITY_BATCH)
        # queue is full (2): interactive displaces the newest batch request,
        # another batch request sheds outright
        displacing = router.submit(prompt, 4, priority=PRIORITY_INTERACTIVE)
        full_shed = router.submit(prompt, 4, priority=PRIORITY_BATCH)
        router.poll()
    finally:
        tel.disable()

    assert ok1.status is RouterRequestStatus.QUEUED
    assert rate_shed.status is RouterRequestStatus.SHED
    assert "rate-limited" in rate_shed.error
    assert displacing.status is RouterRequestStatus.QUEUED
    assert ok2.status is RouterRequestStatus.SHED  # displaced victim
    assert "displaced" in ok2.error
    assert full_shed.status is RouterRequestStatus.SHED
    assert "queue-full" in full_shed.error
    # every submitted request has exactly one definite state; nothing vanished
    assert router.stats()["shed"] == 3
    assert router.stats()["shed_by_reason"] == {
        "rate-limited": 1, "displaced by higher-priority admission": 1, "queue-full": 1,
    }
    report = build_report([str(tmp_path)])
    section = report["router"]
    assert section["shed"] == 3
    assert section["shed_reasons"]["rate-limited"] == 1
    assert section["outcomes"]["shed"] == 3
    text = format_report(report)
    assert "router:" in text and "shed 3" in text


def test_router_deadline_expires_queued_work():
    clock = FakeClock()
    rep = FakeReplica("r0")
    rep.state = ReplicaState.STARTING  # nothing dispatches yet
    router = ServingRouter([rep], clock=clock)
    prompt = np.arange(3, dtype=np.int32) + 1
    doomed = router.submit(prompt, 4, deadline_s=5.0)
    safe = router.submit(prompt, 4)  # no deadline
    clock.t += 6.0
    done = router.poll()
    assert doomed.status is RouterRequestStatus.EXPIRED
    assert "deadline" in doomed.error and doomed in done
    assert safe.status is RouterRequestStatus.QUEUED
    assert rep.submitted == []  # the expired request never reached a replica
    assert router.stats()["expired"] == 1


def test_router_dispatches_by_least_outstanding_tokens():
    r0, r1 = FakeReplica("r0"), FakeReplica("r1")
    router = ServingRouter([r0, r1])
    big = router.submit(np.arange(10, dtype=np.int32) + 1, 10)  # 20 tokens
    small = router.submit(np.arange(2, dtype=np.int32) + 1, 2)  # 4 tokens
    third = router.submit(np.arange(2, dtype=np.int32) + 1, 2)
    router.poll()
    # big -> r0 (tie broken by order), small -> r1 (0 < 20), third -> r1 (4 < 20)
    assert big.replica == "r0" and small.replica == "r1" and third.replica == "r1"
    assert router.outstanding_tokens("r0") == 20
    assert router.outstanding_tokens("r1") == 8
    # progress shrinks the owed budget: streamed tokens reduce the load metric
    r1.push(event="step", step=1, progress={small.rid: [5]})
    router.poll()
    assert router.outstanding_tokens("r1") == 5  # 4-token req: prefill paid, 1 left


def test_router_failover_resumes_with_progress_exactly_once():
    r0, r1 = FakeReplica("r0"), FakeReplica("r1")
    router = ServingRouter([r0, r1], max_retries=3)
    req = router.submit(np.asarray([1, 2, 3], np.int32), 5)
    router.poll()
    assert req.replica == "r0" and req.status is RouterRequestStatus.DISPATCHED
    r0.push(event="step", step=1, progress={req.rid: [7, 8]})
    router.poll()
    assert req.generated == [7, 8] and req.first_token_t is not None
    r0.die()
    router.poll()
    # dead replica's work re-dispatched WITH its streamed progress, same poll
    assert r0.state is ReplicaState.DEAD
    assert req.replica == "r1" and req.retries == 1
    assert r1.submitted[-1]["generated"] == [7, 8]
    assert router.failovers == 1
    # the survivor owes the FULL re-prefill (prompt 3 + resumed 2) plus the
    # remaining budget (3): a freshly burdened survivor must not look light
    assert router.outstanding_tokens("r1") == 3 + 2 + 3
    # a zombie's late completion must not double-complete the request
    r0.push(event="done", rid=req.rid, status="finished", tokens=[7, 8, 0, 0, 0])
    router.poll()
    assert req.status is RouterRequestStatus.DISPATCHED  # still r1's to finish
    r1.push(event="done", rid=req.rid, status="finished",
            tokens=[7, 8, 9, 10, 11], preemptions=0)
    r1.push(event="done", rid=req.rid, status="finished",
            tokens=[7, 8, 9, 10, 11], preemptions=0)  # duplicate: ignored
    done = router.poll()
    assert req.status is RouterRequestStatus.FINISHED
    assert req.generated == [7, 8, 9, 10, 11]
    assert router.completed == 1 and len(done) == 1


def test_router_hang_detection_uses_heartbeat_staleness():
    clock = FakeClock()
    r0, r1 = FakeReplica("r0"), FakeReplica("r1")
    router = ServingRouter([r0, r1], health_timeout_s=2.0, clock=clock)
    req = router.submit(np.asarray([1, 2], np.int32), 4)
    router.poll()
    assert req.replica == "r0"
    # r0 stays alive() but silent WITH work in flight -> stalled -> DEAD;
    # r1 is just as silent but idle, so it is NOT declared dead
    clock.t += 3.0
    router.poll()
    assert r0.state is ReplicaState.DEAD and "stale" in r0.reason
    assert not r0.alive()  # the router reaps what it declares dead
    assert r1.state is ReplicaState.HEALTHY
    assert req.replica == "r1" and req.retries == 1


def test_router_finalizes_fully_streamed_request_on_death():
    r0 = FakeReplica("r0")
    router = ServingRouter([r0])
    req = router.submit(np.asarray([1, 2], np.int32), 3)
    router.poll()
    r0.push(event="step", step=1, progress={req.rid: [4, 5, 6]})  # all 3 streamed
    router.poll()
    r0.die()
    done = router.poll()
    # nothing left to decode: the death only lost the done event, not work
    assert req.status is RouterRequestStatus.FINISHED
    assert req.generated == [4, 5, 6] and req in done
    assert router.completed == 1


def test_router_bounds_retries_and_fails_without_replicas():
    r0 = FakeReplica("r0")
    # per-replica outstanding bound of 1: the second request must WAIT — the
    # bounded-dispatch backpressure, and the setup for the no-replicas path
    router = ServingRouter([r0], max_retries=0, max_outstanding_per_replica=1)
    inflight = router.submit(np.asarray([1, 2], np.int32), 4)
    queued = router.submit(np.asarray([1, 2], np.int32), 4)
    router.poll()
    assert queued.status is RouterRequestStatus.QUEUED  # backpressure held it
    assert inflight.status is RouterRequestStatus.DISPATCHED
    r0.die()
    done = router.poll()
    # the in-flight request exhausted its retry budget; the queued one can
    # never run (no live replicas) — both FAILED loudly, nothing wedged
    assert inflight.status is RouterRequestStatus.FAILED
    assert "replica deaths" in inflight.error
    assert queued.status is RouterRequestStatus.FAILED
    assert "no live replicas" in queued.error
    assert set(done) == {inflight, queued}


def test_router_drain_stops_dispatch_but_finishes_inflight():
    r0, r1 = FakeReplica("r0"), FakeReplica("r1")
    router = ServingRouter([r0, r1])
    first = router.submit(np.asarray([1], np.int32), 2)
    router.poll()
    assert first.replica == "r0"
    router.drain("r0")
    assert r0.state is ReplicaState.DRAINING
    later = router.submit(np.asarray([1], np.int32), 2)
    router.poll()
    assert later.replica == "r1"  # draining replicas get nothing new
    r0.push(event="done", rid=first.rid, status="finished", tokens=[9, 9])
    router.poll()
    assert first.status is RouterRequestStatus.FINISHED  # in-flight finished
    # draining the WHOLE fleet with work still queued must fail that work
    # loudly (DRAINING never returns to HEALTHY) — not wedge until timeout
    router.drain("r1")  # `later` stays in flight on r1 and still finishes
    stranded = router.submit(np.asarray([1], np.int32), 2)
    done = router.poll()
    assert stranded.status is RouterRequestStatus.FAILED and stranded in done
    assert "draining" in stranded.error
    r1.push(event="done", rid=later.rid, status="finished", tokens=[8, 8])
    router.poll()
    assert later.status is RouterRequestStatus.FINISHED  # drain kept its word


# ---------------------------------------------------------------------------
# chaos + watchdog integration


def test_chaos_serving_decode_point():
    schedule = ChaosSchedule.seeded(
        7, steps=10, kinds=("sigkill",), n_faults=1, point="serving_decode"
    )
    assert schedule.faults[0].point == "serving_decode"
    assert schedule.to_json() == ChaosSchedule.seeded(
        7, steps=10, kinds=("sigkill",), n_faults=1, point="serving_decode"
    ).to_json()
    chaos.arm(ChaosSchedule(faults=[Fault(kind="crash", point="serving_decode", step=2)]))
    try:
        chaos.maybe_inject("serving_decode", step=1)  # wrong step: no fire
        chaos.maybe_inject("train_step", step=2)  # wrong point: no fire
        with pytest.raises(ChaosFaultError):
            chaos.maybe_inject("serving_decode", step=2)
        chaos.maybe_inject("serving_decode", step=2)  # once: spent
    finally:
        chaos.arm(None)


def test_watchdog_stall_names_replica_source(tmp_path):
    import json

    from accelerate_tpu.telemetry import watchdog

    wd = watchdog.start(timeout=0.3, interval=0.1, out_dir=str(tmp_path))
    try:
        ServingRouter([FakeReplica("wedged")])
        # registered at router construction; never beaten -> a stall dump
        # that NAMES the replica, same forensics as a stuck train step
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not wd.dump_paths:
            time.sleep(0.05)
        assert wd.dump_paths, "no stall dump within 5s"
        with open(wd.dump_paths[0]) as f:
            reason = json.load(f)["reason"]
        assert "serving_replica:wedged" in reason
    finally:
        watchdog.stop()


# ---------------------------------------------------------------------------
# real engines: token-exact failover (tier-1: thread replicas)


def test_local_replica_failover_bitwise_parity(tmp_path):
    """Kill one of two thread-backed replicas mid-decode: every request must
    finish exactly once with output bitwise-equal to the single-stream
    reference — the resumed requests continue from their streamed progress,
    not from scratch blindly trusted."""
    from accelerate_tpu.telemetry import events as tel
    from accelerate_tpu.telemetry.report import build_report, format_report

    spec = _spec()
    tel.enable(out_dir=str(tmp_path), run_id="router-failover")
    router = None
    try:
        router = ServingRouter(
            [LocalReplica(f"r{i}", spec) for i in range(2)], health_timeout_s=5.0
        )
        router.wait_ready(timeout_s=300)
        prompts = _prompts(1, (5, 13, 9, 16, 7, 11))
        reqs = [router.submit(p, 12, rng_seed=i) for i, p in enumerate(prompts)]
        # let tokens flow until r0 holds partially decoded work, then kill it
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            router.poll()
            if any(
                r.replica == "r0" and len(r.generated) >= 2 and not r.status.terminal
                for r in reqs
            ):
                break
            time.sleep(0.002)
        victims = [r.rid for r in reqs if r.replica == "r0" and not r.status.terminal]
        assert victims, "r0 never held in-flight work"
        router.replicas["r0"].kill()
        done = router.run(timeout_s=240)
    finally:
        if router is not None:
            router.close()
        tel.disable()

    assert router.replicas["r0"].state is ReplicaState.DEAD
    assert router.failovers >= 1
    # exactly once: every request terminal exactly one time, none duplicated
    assert sorted(r.rid for r in done) == sorted(r.rid for r in reqs)
    params = spec.build_params()
    for i, (p, req) in enumerate(zip(_prompts(1, (5, 13, 9, 16, 7, 11)), reqs)):
        assert req.status is RouterRequestStatus.FINISHED, (i, req.status, req.error)
        ref = greedy_generate(params, p[None], CONFIG, max_new_tokens=12)
        assert np.array_equal(np.asarray(ref[0]), req.output_ids()), f"request {i}"
    assert any(r.retries >= 1 for r in reqs)  # failover actually resumed work
    report = build_report([str(tmp_path)])
    section = report["router"]
    assert section["completed"] == len(reqs)
    assert section["failovers"] == router.failovers
    assert section["replicas"]["r0"]["state"] == "dead"
    assert section["requests"]["retried"] >= 1
    text = format_report(report)
    assert "router:" in text and "r0: dead" in text


def test_engine_resume_submit_is_token_exact():
    """The failover resume primitive in isolation: engine B continuing a
    request from engine A's generated-so-far produces the same tokens as one
    uninterrupted run — across DIFFERENT engine instances, which is exactly
    the cross-replica case."""
    spec = _spec(slot_buckets=(1,), block_buckets=(4,), prefill_buckets=(32,), max_slots=1)
    engine_a = spec.build_engine()
    engine_a.warmup()
    prompt = _prompts(3, (9,))[0]
    partial = engine_a.submit(prompt, 4, rng_seed=5)
    engine_a.run()
    assert len(partial.generated) == 4
    engine_b = spec.build_engine()
    engine_b.warmup()
    resumed = engine_b.submit(prompt, 10, rng_seed=5, generated=list(partial.generated))
    engine_b.run()
    ref = greedy_generate(spec.build_params(), prompt[None], CONFIG, max_new_tokens=10)
    assert np.array_equal(np.asarray(ref[0]), resumed.output_ids())
    with pytest.raises(ValueError, match="nothing left to decode"):
        engine_b.submit(prompt, 4, generated=[1, 2, 3, 4])


def test_engine_step_beats_watchdog_serving_decode(tmp_path):
    from accelerate_tpu.telemetry import watchdog

    spec = _spec(slot_buckets=(1,), block_buckets=(4,), prefill_buckets=(32,), max_slots=1)
    engine = spec.build_engine(heartbeat_name="serving_decode:solo")
    engine.warmup()
    wd = watchdog.start(timeout=60, interval=5, out_dir=str(tmp_path))
    try:
        engine.submit(_prompts(4, (5,))[0], 5)
        engine.step()  # request still live after this step -> source beats
        sources = wd.sources()
        assert "serving_decode:solo" in sources  # beats per step, with the step
        assert sources["serving_decode:solo"]["step"] == engine.steps
        engine.run()
        # drained-to-idle engines deregister: a quiet traffic window must
        # never read as a decode stall (or 101-abort a serving process)
        assert "serving_decode:solo" not in wd.sources()
    finally:
        watchdog.stop()


# ---------------------------------------------------------------------------
# the chaos e2e: real processes, real SIGKILL, real wedge-forever hang


@pytest.mark.slow  # 3 subprocess replicas each paying jax import + warmup
def test_process_replica_sigkill_and_hang_chaos_poisson_parity():
    """ISSUE 12 acceptance: seeded chaos (replica SIGKILL + wedge-forever
    hang, both mid-decode) under a Poisson open-loop load — every admitted
    request completes exactly once, bitwise-equal to its single-stream
    reference; the two chaos'd replicas die, the survivor absorbs the
    failovers."""
    import os

    spec = _spec()
    sigkill = ChaosSchedule(
        faults=[Fault(kind="sigkill", point="serving_decode", step=3)]
    ).to_json()
    hang = ChaosSchedule(
        faults=[Fault(kind="hang", point="serving_decode", step=4, duration_s=None)]
    ).to_json()
    # children inherit env verbatim (no implicit platform pinning) — pin CPU
    # here so the test is hermetic even when the runner didn't export it
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    router = None
    try:
        router = ServingRouter(
            [
                ProcessReplica("r0", spec, chaos_schedule=sigkill, env=env),
                ProcessReplica("r1", spec, chaos_schedule=hang, env=env),
                ProcessReplica("r2", spec, env=env),
            ],
            health_timeout_s=3.0,
        )
        router.wait_ready(timeout_s=300)
        # seeded Poisson open loop: exponential inter-arrival gaps, submitted
        # on the router's wall clock while it polls
        rng = np.random.default_rng(42)
        n = 10
        gaps = rng.exponential(0.03, n)
        lengths = rng.integers(4, 20, n)
        prompts = [
            rng.integers(0, CONFIG.vocab_size, (int(s),)).astype(np.int32)
            for s in lengths
        ]
        reqs = []
        done = []  # every poll's terminal requests — exactly-once needs ALL
        for i in range(n):
            t0 = time.monotonic()
            while time.monotonic() - t0 < gaps[i]:
                done.extend(router.poll())
                time.sleep(0.001)
            reqs.append(router.submit(prompts[i], 10, rng_seed=i))
        done.extend(router.run(timeout_s=300))
    finally:
        if router is not None:
            router.close()

    dead = {n for n, r in router.replicas.items() if r.state is ReplicaState.DEAD}
    assert dead == {"r0", "r1"}, f"chaos'd replicas should both be dead: {dead}"
    assert router.replicas["r2"].state is ReplicaState.HEALTHY
    assert router.failovers >= 2
    # exactly once, nothing lost, nothing duplicated
    assert sorted(r.rid for r in done) == sorted(r.rid for r in reqs)
    assert router.completed == len(reqs)
    params = spec.build_params()
    for i, (p, req) in enumerate(zip(prompts, reqs)):
        assert req.status is RouterRequestStatus.FINISHED, (i, req.status, req.error)
        ref = greedy_generate(params, p[None], CONFIG, max_new_tokens=10)
        assert np.array_equal(np.asarray(ref[0]), req.output_ids()), f"request {i}"


def test_router_report_absent_without_records(tmp_path):
    from accelerate_tpu.telemetry.report import build_report, format_report

    (tmp_path / "events-rank0.jsonl").write_text(
        '{"kind": "meta", "schema": 1, "run_id": "r", "process_index": 0, '
        '"num_processes": 1}\n'
    )
    report = build_report([str(tmp_path)])
    assert report["router"] is None
    assert "router:" not in format_report(report)


# ---------------------------------------------------------------------------
# router self-healing (ISSUE 13 satellite): a chaos-killed fleet heals back
# to N via respawn-from-spec under a bounded budget with backoff


class RespawnableFake(FakeReplica):
    """FakeReplica that can be respawned from itself (generation counted)."""

    def __init__(self, name, max_slots=4, generation=0):
        super().__init__(name, max_slots=max_slots)
        self.generation = generation

    def respawn(self):
        return RespawnableFake(
            self.name, max_slots=self.spec.max_slots, generation=self.generation + 1
        )


def test_router_self_heal_respawns_within_budget_then_gives_up():
    clock = FakeClock()
    rep = RespawnableFake("r0")
    router = ServingRouter(
        [rep], clock=clock, self_heal=True, max_respawns_per_replica=1,
        respawn_backoff_base_s=0.0,
    )
    rep.die()
    router.poll()
    healed = router.replicas["r0"]
    assert healed is not rep and healed.generation == 1
    assert healed.state is ReplicaState.HEALTHY
    assert router.respawns == 1
    assert router.stats()["per_replica"]["r0"]["respawns"] == 1
    # budget exhausted: the second death stays dead, and queued work fails
    # loudly instead of waiting for a heal that can never come
    healed.die()
    router.poll()
    assert router.replicas["r0"].state is ReplicaState.DEAD
    req = router.submit(np.arange(4, dtype=np.int32), 4)
    router.poll()
    assert req.status is RouterRequestStatus.FAILED
    assert "no live replicas" in req.error


def test_router_self_heal_backoff_defers_second_respawn():
    clock = FakeClock()
    rep = RespawnableFake("r0")
    router = ServingRouter(
        [rep], clock=clock, self_heal=True, max_respawns_per_replica=3,
        respawn_backoff_base_s=10.0,
    )
    rep.die()
    router.poll()  # first respawn is immediate
    assert router.replicas["r0"].generation == 1
    router.replicas["r0"].die()
    router.poll()  # second respawn gated behind the backoff window
    assert router.replicas["r0"].state is ReplicaState.DEAD
    # queued work WAITS (budget remains) instead of failing
    req = router.submit(np.arange(4, dtype=np.int32), 4)
    router.poll()
    assert req.status is RouterRequestStatus.QUEUED
    clock.t += 10.1
    router.poll()
    assert router.replicas["r0"].generation == 2
    assert router.replicas["r0"].state is ReplicaState.HEALTHY


def test_router_self_heal_ignores_replicas_without_spec():
    clock = FakeClock()
    rep = FakeReplica("r0")  # no respawn()
    router = ServingRouter([rep], clock=clock, self_heal=True)
    rep.die()
    router.poll()
    assert router.replicas["r0"] is rep
    assert router.replicas["r0"].state is ReplicaState.DEAD
    assert router.respawns == 0


def test_router_self_heals_killed_fleet_back_to_n_bitwise(tmp_path):
    """The e2e: one of two thread-backed replicas is killed mid-decode. The
    router must (a) fail the work over with bitwise parity, (b) respawn the
    dead replica from its stored spec — warm-booted from the compile cache —
    and (c) end with the fleet back at N serving bitwise-identical output
    from the RESPAWNED replica."""
    spec = _spec(compile_cache_dir=str(tmp_path / "cache"))
    router = None
    try:
        router = ServingRouter(
            [LocalReplica(f"r{i}", spec) for i in range(2)],
            health_timeout_s=5.0, self_heal=True, max_respawns_per_replica=2,
            respawn_backoff_base_s=0.05,
        )
        router.wait_ready(timeout_s=300)
        prompts = _prompts(1, (5, 13, 9, 16, 7, 11))
        reqs = [router.submit(p, 12, rng_seed=i) for i, p in enumerate(prompts)]
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            router.poll()
            if any(
                r.replica == "r0" and len(r.generated) >= 2 and not r.status.terminal
                for r in reqs
            ):
                break
            time.sleep(0.002)
        assert any(r.replica == "r0" and not r.status.terminal for r in reqs)
        router.replicas["r0"].kill()
        done = router.run(timeout_s=240)
        assert sorted(r.rid for r in done) == sorted(r.rid for r in reqs)
        params = spec.build_params()
        for i, (p, req) in enumerate(zip(prompts, reqs)):
            assert req.status is RouterRequestStatus.FINISHED, (i, req.status, req.error)
            ref = greedy_generate(params, p[None], CONFIG, max_new_tokens=12)
            assert np.array_equal(np.asarray(ref[0]), req.output_ids()), f"request {i}"
        # the fleet heals back to N: the replacement boots (warm), goes ready
        assert router.respawns >= 1
        router.wait_ready(timeout_s=300)
        assert all(
            r.state is ReplicaState.HEALTHY for r in router.replicas.values()
        ), {n: r.state for n, r in router.replicas.items()}
        healed = router.replicas["r0"]
        # warm boot: the respawned engine loaded its whole lattice from cache
        # (incl. the prefix-cache COW point, one extra warmed shape)
        assert healed._worker is not None
        assert healed._worker.engine.cache_stats["hit"] == spec.lattice().warmup_points(
            prefix_cache=True
        )
        # drain the survivor so the next request MUST run on the respawned
        # replica — and its output must still be bitwise-correct
        router.drain("r1")
        p_new = _prompts(9, (8,))[0]
        req_new = router.submit(p_new, 8, rng_seed=42)
        done = router.run(timeout_s=240)
        assert req_new.status is RouterRequestStatus.FINISHED, req_new.error
        assert req_new.replica == "r0"
        ref = greedy_generate(params, p_new[None], CONFIG, max_new_tokens=8)
        assert np.array_equal(np.asarray(ref[0]), req_new.output_ids())
    finally:
        if router is not None:
            router.close()


def test_router_self_heal_never_resurrects_drained_replica():
    """drain() is a requested scale-down: a drained replica that then dies
    must stay dead — self-heal respawning it would undo the operator's
    decommission."""
    clock = FakeClock()
    reps = [RespawnableFake("r0"), RespawnableFake("r1")]
    router = ServingRouter(
        reps, clock=clock, self_heal=True, max_respawns_per_replica=3,
        respawn_backoff_base_s=0.0,
    )
    router.drain("r0")
    reps[0].die()
    router.poll()
    assert router.replicas["r0"] is reps[0]  # not replaced
    assert router.replicas["r0"].state is ReplicaState.DEAD
    assert router.respawns == 0
    # a CRASHED (never drained) replica still heals
    reps[1].die()
    router.poll()
    assert router.replicas["r1"].generation == 1
    # and queued work does not wait on the decommissioned one once the
    # healthy survivor exists
    req = router.submit(np.arange(4, dtype=np.int32), 4)
    router.poll()
    assert req.status is RouterRequestStatus.DISPATCHED


# ---------------------------------------------------------------------------
# bitwise correctness canaries (ISSUE 19, serving/canary.py)


def _canary_probe(**kw):
    golden = CanaryGolden(name="g0", prompt=(1, 2, 3), max_new_tokens=3,
                          expected=(7, 8, 9), rng_seed=5)
    kw.setdefault("interval_s", 1000.0)
    return CanaryProbe([golden], **kw)


def test_canary_probe_check_names_first_mismatch():
    g = CanaryGolden("g", (1,), 4, expected=(7, 8, 9, 10))
    assert CanaryProbe.check(g, [7, 8, 9, 10]) is None
    m = CanaryProbe.check(g, [7, 99, 9, 10])
    assert (m["mismatch_index"], m["expected_token"], m["got_token"]) == (1, 8, 99)
    short = CanaryProbe.check(g, [7, 8, 9])     # wrong length IS a mismatch
    assert short["mismatch_index"] == 3 and short["got_token"] is None
    assert (short["expected_len"], short["got_len"]) == (4, 3)


def test_canary_mismatch_drains_replica_and_match_does_not(tmp_path):
    """A scripted fleet: 'bad' answers the golden with a corrupted token,
    'good' answers bitwise-exact. The mismatch must emit canary +
    canary_failure records naming the differing token, drain the bad
    replica, and leave zero false positives on the healthy one — all
    invisible to the user-facing request counters."""
    from accelerate_tpu.telemetry import events as tel
    from accelerate_tpu.telemetry.report import build_report, format_report

    clock = FakeClock()
    bad, good = FakeReplica("bad"), FakeReplica("good")
    probe = _canary_probe()
    tel.enable(out_dir=str(tmp_path), run_id="canary")
    try:
        router = ServingRouter([bad, good], canary=probe, clock=clock)
        router.poll()
        # round-robin over sorted targets: the first probe lands on 'bad'
        assert bad.submitted and bad.submitted[0]["rid"] == "canary-1"
        assert bad.submitted[0]["prompt"] == [1, 2, 3]
        assert bad.submitted[0]["rng_seed"] == 5
        bad.push(event="done", rid="canary-1", status="finished",
                 tokens=[7, 99, 9])
        router.poll()
        assert bad.state is ReplicaState.DRAINING
        # next due probe can only target the healthy survivor
        clock.t += 1001.0
        router.poll()
        assert good.submitted and good.submitted[0]["rid"] == "canary-2"
        good.push(event="done", rid="canary-2", status="finished",
                  tokens=[7, 8, 9])
        router.poll()
        assert good.state is ReplicaState.HEALTHY
    finally:
        tel.disable()

    assert probe.stats() == {
        "probes": 2, "failures": 1,
        "by_replica": {"bad": {"probes": 1, "failures": 1},
                       "good": {"probes": 1, "failures": 0}},
    }
    stats = router.stats()
    assert stats["canary"]["failed_replicas"] == ["bad"]
    # canaries are invisible to the user-facing ledgers
    assert stats["completed"] == 0 and stats["shed"] == 0 and stats["failed"] == 0
    assert router.admission.depth == 0
    report = build_report([str(tmp_path)])
    sec = report["canary"]
    assert sec["probes"] == 2 and sec["failures"] == 1
    (mm,) = sec["mismatches"]
    assert mm["replica"] == "bad" and mm["mismatch_index"] == 1
    assert mm["expected_token"] == 8 and mm["got_token"] == 99 and mm["drained"]
    text = format_report(report)
    assert "canaries: 2 probe(s), 1 MISMATCH(ES)" in text
    assert "MISMATCH on bad: golden g0 token 1 expected 8 got 99" in text
    # router section shows the drained replica
    assert any("bad: draining" in line for line in text.splitlines())


def test_canary_failed_replica_loses_dispatch_ties():
    """With drain_on_failure=False the failed replica stays HEALTHY but
    joins the DRAINING-pressure set: user work prefers clean replicas at
    equal load, exactly like an SLO-burning replica."""
    clock = FakeClock()
    bad, good = FakeReplica("a-bad"), FakeReplica("b-good")
    probe = _canary_probe(drain_on_failure=False)
    router = ServingRouter([bad, good], canary=probe, clock=clock)
    router.poll()
    bad.push(event="done", rid="canary-1", status="finished", tokens=[0, 0, 0])
    router.poll()
    assert bad.state is ReplicaState.HEALTHY        # kept serving...
    req = router.submit(np.asarray([1, 2], np.int32), 2)
    router.poll()
    assert req.replica == "b-good"                  # ...but loses the tie
    assert router.stats()["canary"]["failed_replicas"] == ["a-bad"]


def test_canary_dropped_not_failed_over_on_replica_death(tmp_path):
    """A probe's job is to test THIS replica: when the replica dies with the
    probe inflight, the probe is dropped as inconclusive — never re-dispatched
    (failover would launder the evidence) and never counted as a mismatch."""
    from accelerate_tpu.telemetry import events as tel

    clock = FakeClock()
    r0, r1 = FakeReplica("r0"), FakeReplica("r1")
    probe = _canary_probe()
    tel.enable(out_dir=str(tmp_path), run_id="canary-drop")
    try:
        router = ServingRouter([r0, r1], canary=probe, clock=clock)
        router.poll()
        assert r0.submitted and r0.submitted[0]["rid"] == "canary-1"
        r0.die()
        router.poll()
        assert r0.state is ReplicaState.DEAD
    finally:
        tel.disable()
    assert router.failovers == 0 and r1.submitted == []
    assert router.canary_inconclusive == 1
    assert probe.stats()["probes"] == 0             # no verdict recorded
    recs = [json.loads(l) for l in open(tmp_path / "events-rank0.jsonl")]
    assert not [r for r in recs if r["kind"] == "canary_failure"]


def test_canary_engine_rejection_is_inconclusive(tmp_path):
    """A probe the engine rejects (pool/lattice cap) says nothing about
    token correctness: inconclusive, no verdict against the replica."""
    from accelerate_tpu.telemetry import events as tel

    clock = FakeClock()
    r0 = FakeReplica("r0")
    probe = _canary_probe()
    tel.enable(out_dir=str(tmp_path), run_id="canary-rej")
    try:
        router = ServingRouter([r0], canary=probe, clock=clock)
        router.poll()
        r0.push(event="done", rid="canary-1", status="rejected",
                error="prompt too long")
        router.poll()
    finally:
        tel.disable()
    assert r0.state is ReplicaState.HEALTHY
    assert router.canary_inconclusive == 1
    assert probe.stats()["probes"] == 0
    recs = [json.loads(l) for l in open(tmp_path / "events-rank0.jsonl")]
    (canary_rec,) = [r for r in recs if r["kind"] == "canary"]
    assert canary_rec["result"] == "inconclusive"


def test_canary_real_fleet_corrupt_weights_drained_bitwise(tmp_path):
    """End to end against real thread-backed engines: the bad replica shares
    the spec but builds its params from a different seed — deterministic
    init makes that genuinely corrupt weights, so its canary answers diverge
    bitwise while the healthy replica's match (zero false positives)."""
    from accelerate_tpu.telemetry import events as tel

    spec = _spec()
    goldens = precompute_goldens(spec, max_new_tokens=4)
    assert goldens and all(len(g.expected) == 4 for g in goldens)
    probe = CanaryProbe(goldens, interval_s=0.05)
    tel.enable(out_dir=str(tmp_path), run_id="canary-real")
    router = None
    try:
        router = ServingRouter(
            [
                LocalReplica("good", spec),
                LocalReplica("bad", dataclasses.replace(spec, param_seed=1234)),
            ],
            canary=probe,
            health_timeout_s=10.0,
        )
        router.wait_ready(timeout_s=300)
        deadline = time.monotonic() + 300
        while (probe.by_replica.get("bad", {}).get("failures", 0) < 1
               or probe.by_replica.get("good", {}).get("probes", 0) < 1
               or router._inflight):
            router.poll()
            if time.monotonic() > deadline:
                raise AssertionError(f"canary probes stalled: {probe.stats()}")
            time.sleep(0.002)
    finally:
        if router is not None:
            router.close()
        tel.disable()
    assert router.replicas["bad"].state is ReplicaState.DRAINING
    assert probe.by_replica["bad"]["failures"] >= 1
    assert probe.by_replica["good"]["failures"] == 0
