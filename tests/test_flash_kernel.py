"""In-tree blocked flash attention: interpret-mode parity matrix on CPU tier-1.

The kernel (``ops.flash_attention``) streams KV blocks through VMEM with f32
online softmax over a ``(B·H, q_blocks, kv_blocks)`` grid, broadcasts GQA
heads in-kernel via the k/v index maps, and skips fully-masked
(q_block, kv_block) tiles through a scalar-prefetch block lattice.
``ACCELERATE_FLASH_KERNEL=interpret`` runs the IDENTICAL kernel through the
Pallas interpreter, so these tests drive the exact TPU dataflow — including
the custom_vjp backward — in CPU CI:

- fwd parity vs the einsum reference at dtype-appropriate tolerance
  (f32 near machine-eps, bf16 within the documented envelope);
- bwd grads vs ``jax.grad`` of the reference;
- four GQA ratios (the kv index maps, not an HBM repeat, do the broadcast);
- sliding-window + packed-segment block-skip correctness: NaN-poison a
  skipped block and the unaffected rows must come out bitwise unchanged
  (a streamed-but-masked block would still poison the online max);
- the ``ACCELERATE_FLASH_KERNEL=0`` kill switch is byte-identical to the
  einsum reference;
- the fwd+bwd HLO materializes neither an [B,H,S,S] score tensor nor a
  repeated-KV broadcast.
"""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.ops.attention import (
    _xla_attention,
    dot_product_attention,
    segment_mask,
)
from accelerate_tpu.ops.flash_attention import (
    _block_lattice,
    _FlashConfig,
    flash_attention,
    flash_kernel_mode,
)

BQ = BKV = 32  # small blocks: several grid steps per axis even at S=128


@pytest.fixture
def interpret_mode(monkeypatch):
    monkeypatch.setenv("ACCELERATE_FLASH_KERNEL", "interpret")


def _qkv(b=2, s=128, h=4, hkv=None, d=16, dtype=jnp.float32, seed=0):
    hkv = h if hkv is None else hkv
    keys = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(keys[0], (b, s, h, d), dtype)
    k = jax.random.normal(keys[1], (b, s, hkv, d), dtype)
    v = jax.random.normal(keys[2], (b, s, hkv, d), dtype)
    return q, k, v


def _packed_seg(b=2, s=128):
    # two packed documents + a padded tail, block-aligned at 32
    return jnp.asarray(np.repeat([[1] * 64 + [2] * 40 + [0] * 24], b, 0), jnp.int32)


def _reference(q, k, v, *, causal=False, segment_ids=None, window=None):
    mask = segment_mask(segment_ids) if segment_ids is not None else None
    return _xla_attention(q, k, v, causal=causal, mask=mask, scale=None, window=window)


MASK_CASES = [
    ("dense", {}),
    ("causal", dict(causal=True)),
    ("window", dict(causal=True, window=40)),
    ("packed", dict(segment_ids="packed")),
    ("all", dict(causal=True, window=50, segment_ids="packed")),
]


def _resolve(kw, b=2, s=128):
    kw = dict(kw)
    if kw.get("segment_ids") == "packed":
        kw["segment_ids"] = _packed_seg(b, s)
    return kw


class TestForwardParity:
    @pytest.mark.parametrize("name,kw", MASK_CASES)
    def test_f32_parity_tight(self, interpret_mode, name, kw):
        """f32: the kernel's online softmax reorders the reduction, so exact
        bitwise equality vs the two-pass einsum is not defined — but both
        accumulate in f32, so parity holds to a few ulps of the row sums.
        (Bitwise equality is the KILL SWITCH's contract, tested below.)"""
        q, k, v = _qkv()
        kw = _resolve(kw)
        out = flash_attention(q, k, v, block_q=BQ, block_kv=BKV, **kw)
        ref = _reference(q, k, v, **kw)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6, rtol=0)

    @pytest.mark.parametrize("name,kw", MASK_CASES)
    def test_bf16_parity_envelope(self, interpret_mode, name, kw):
        """bf16: inputs and the PV operands are bf16 (f32 accumulate), same
        as the reference einsum — the documented envelope is 2e-2."""
        q, k, v = _qkv(dtype=jnp.bfloat16)
        kw = _resolve(kw)
        out = flash_attention(q, k, v, block_q=BQ, block_kv=BKV, **kw)
        ref = _reference(q, k, v, **kw)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=2e-2
        )

    def test_rectangular_blocks(self, interpret_mode):
        q, k, v = _qkv()
        out = flash_attention(q, k, v, causal=True, block_q=32, block_kv=64)
        ref = _reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)


class TestBackwardParity:
    @pytest.mark.parametrize("name,kw", MASK_CASES)
    def test_grads_match_reference(self, interpret_mode, name, kw):
        q, k, v = _qkv()
        kw = _resolve(kw)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, block_q=BQ, block_kv=BKV, **kw) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(_reference(q, k, v, **kw) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name_, a, b in zip("qkv", gf, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-5, err_msg=f"d{name_} ({name})"
            )


class TestGQA:
    @pytest.mark.parametrize("h,hkv", [(4, 4), (4, 2), (8, 2), (8, 1)])
    def test_gqa_ratios_fwd_and_bwd(self, interpret_mode, h, hkv):
        """The GQA broadcast lives in the kv BlockSpec index maps (fwd/dq) and
        the group-member walk of the dk/dv kernel — every ratio must match
        the reference's explicit head repetition."""
        q, k, v = _qkv(h=h, hkv=hkv)
        out = flash_attention(q, k, v, causal=True, block_q=BQ, block_kv=BKV)
        ref = _reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)

        gf = jax.grad(
            lambda a, b, c: jnp.sum(
                flash_attention(a, b, c, causal=True, block_q=BQ, block_kv=BKV) ** 2
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        gr = jax.grad(
            lambda a, b, c: jnp.sum(_reference(a, b, c, causal=True) ** 2),
            argnums=(0, 1, 2),
        )(q, k, v)
        for name, a, b in zip("qkv", gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4,
                                       err_msg=f"d{name} H={h} Hkv={hkv}")


class TestBlockSkip:
    """Skipped blocks are never streamed: NaN-poisoning one must leave every
    row that does not attend into it bitwise unchanged. A kernel that streamed
    the block and merely masked it would propagate the NaN through the online
    max/exp."""

    def test_sliding_window_skips_out_of_band_blocks(self, interpret_mode):
        q, k, v = _qkv(b=1, h=2, hkv=2)
        # window=32, blocks of 32: query rows >= 64 never touch kv block 0
        kbad = k.at[:, :32].set(jnp.nan)
        vbad = v.at[:, :32].set(jnp.nan)
        out = flash_attention(q, k, v, causal=True, window=32, block_q=BQ, block_kv=BKV)
        outbad = flash_attention(
            q, kbad, vbad, causal=True, window=32, block_q=BQ, block_kv=BKV
        )
        assert bool(jnp.all(out[:, 64:] == outbad[:, 64:]))
        assert bool(jnp.all(jnp.isfinite(outbad[:, 64:])))

    def test_packed_segments_skip_cross_document_blocks(self, interpret_mode):
        q, k, v = _qkv(b=1, h=2, hkv=2)
        seg = jnp.asarray([[1] * 64 + [2] * 64], jnp.int32)
        kbad = k.at[:, :64].set(jnp.nan)
        out = flash_attention(q, k, v, segment_ids=seg, block_q=BQ, block_kv=BKV)
        outbad = flash_attention(q, kbad, v, segment_ids=seg, block_q=BQ, block_kv=BKV)
        assert bool(jnp.all(out[:, 64:] == outbad[:, 64:]))

    def test_backward_also_skips(self, interpret_mode):
        """dq of in-band rows must ignore poisoned out-of-band KV blocks —
        the dq kernel walks the same lattice as the forward."""
        q, k, v = _qkv(b=1, h=2, hkv=2)
        kbad = k.at[:, :32].set(jnp.nan)
        vbad = v.at[:, :32].set(jnp.nan)

        def dq_of(kk, vv):
            return jax.grad(
                lambda a: jnp.sum(
                    flash_attention(
                        a, kk, vv, causal=True, window=32, block_q=BQ, block_kv=BKV
                    )[:, 64:]
                    ** 2
                )
            )(q)

        assert bool(jnp.all(dq_of(k, v)[:, 64:] == dq_of(kbad, vbad)[:, 64:]))

    def test_lattice_counts_scale_with_sparsity(self):
        """The lattice itself: causal halves the active tiles, a window
        caps them per row, and padding tails drop out entirely."""
        seg = jnp.ones((1, 128), jnp.int32)
        base = dict(scale=1.0, block_q=32, block_kv=32, h=1, hkv=1,
                    use_seg=False, interpret=True)
        dense = _block_lattice(seg, _FlashConfig(causal=False, window=None, **base))
        causal = _block_lattice(seg, _FlashConfig(causal=True, window=None, **base))
        window = _block_lattice(seg, _FlashConfig(causal=True, window=32, **base))
        assert int(dense[1].sum()) == 16  # 4x4 all active
        assert int(causal[1].sum()) == 10  # lower triangle of 4x4
        assert int(window[1].sum()) == 7  # diagonal + one band below
        # packed docs: block-aligned documents never cross
        seg2 = jnp.asarray([[1] * 64 + [2] * 64], jnp.int32)
        packed = _block_lattice(
            seg2,
            _FlashConfig(causal=False, window=None, scale=1.0, block_q=32,
                         block_kv=32, h=1, hkv=1, use_seg=True, interpret=True),
        )
        assert int(packed[1].sum()) == 8  # two 2x2 diagonal blocks


class TestKillSwitch:
    def test_off_mode_is_byte_identical_to_einsum(self, monkeypatch):
        monkeypatch.setenv("ACCELERATE_FLASH_KERNEL", "0")
        assert flash_kernel_mode() == "off"
        q, k, v = _qkv()
        seg = _packed_seg()
        out = flash_attention(q, k, v, causal=True, segment_ids=seg)
        ref = _reference(q, k, v, causal=True, segment_ids=seg)
        assert bool(jnp.all(out == ref))

    def test_mode_parsing(self, monkeypatch):
        for raw, want in [("1", "on"), ("0", "off"), ("off", "off"),
                          ("false", "off"), ("interpret", "interpret")]:
            monkeypatch.setenv("ACCELERATE_FLASH_KERNEL", raw)
            assert flash_kernel_mode() == want
        monkeypatch.delenv("ACCELERATE_FLASH_KERNEL", raising=False)
        assert flash_kernel_mode() == "on"

    def test_untileable_shapes_fall_back(self, interpret_mode):
        # cross-attention (Sq != Skv) is reference territory
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 4, 16))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 128, 4, 16))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 128, 4, 16))
        out = flash_attention(q, k, v, causal=True)
        ref = _xla_attention(q, k, v, causal=True, mask=None, scale=None)
        assert bool(jnp.all(out == ref))


def _broadcast_blowups(hlo: str):
    """(operand_numel, result_numel) for every non-scalar broadcast in the
    lowered text — a repeated-KV materialization shows up as numel × groups."""
    out = []
    for line in hlo.splitlines():
        if "broadcast" not in line:
            continue
        shapes = re.findall(r"tensor<([0-9x]+)x[a-z0-9]+>", line)
        if len(shapes) >= 2:
            nums = [int(np.prod([int(d) for d in s.split("x")])) for s in shapes]
            out.append((nums[0], nums[-1]))
    return out


class TestHLO:
    B, S, H, HKV, D = 2, 256, 8, 2, 64

    def _grad_hlo(self, fn):
        q, k, v = _qkv(b=self.B, s=self.S, h=self.H, hkv=self.HKV, d=self.D)
        grad = jax.grad(lambda a, b, c: jnp.sum(fn(a, b, c) ** 2), argnums=(0, 1, 2))
        return jax.jit(grad).lower(q, k, v).as_text()

    def test_no_score_tensor_and_no_repeated_kv(self, interpret_mode):
        hlo = self._grad_hlo(
            lambda q, k, v: flash_attention(q, k, v, causal=True, block_q=64, block_kv=64)
        )
        # no [.., S, S] score tensor anywhere in fwd+bwd
        assert f"x{self.S}x{self.S}x" not in hlo
        # no broadcast inflating a KV-sized tensor to q-head size
        kv_numel = self.B * self.S * self.HKV * self.D
        q_numel = self.B * self.S * self.H * self.D
        blowups = [p for p in _broadcast_blowups(hlo) if p == (kv_numel, q_numel)]
        assert not blowups, blowups

    def test_reference_does_materialize_both(self):
        """Sanity: the detector fires on the einsum reference, which builds
        the [B,H,S,S] scores and repeats KV across the GQA groups."""
        hlo = self._grad_hlo(
            lambda q, k, v: _xla_attention(q, k, v, causal=True, mask=None, scale=None)
        )
        assert f"x{self.S}x{self.S}x" in hlo
        kv_numel = self.B * self.S * self.HKV * self.D
        q_numel = self.B * self.S * self.H * self.D
        assert any(p == (kv_numel, q_numel) for p in _broadcast_blowups(hlo))


class TestDispatch:
    def test_window_requires_causal(self):
        q, k, v = _qkv(s=32)
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, window=8)
        with pytest.raises(ValueError, match="causal"):
            dot_product_attention(q, k, v, window=8, impl="xla")

    def test_fused_rejects_window(self):
        q, k, v = _qkv(s=32)
        with pytest.raises(ValueError, match="window"):
            dot_product_attention(q, k, v, causal=True, window=8, impl="fused")

    def test_xla_window_band(self):
        """The xla path's band mask equals an explicit additive window mask."""
        q, k, v = _qkv(s=32)
        out = dot_product_attention(q, k, v, causal=True, window=8, impl="xla")
        i = np.arange(32)[:, None]
        j = np.arange(32)[None, :]
        allow = (j <= i) & (i - j < 8)
        ref = dot_product_attention(
            q, k, v, mask=jnp.asarray(allow)[None, None], impl="xla"
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)

    def test_auto_crossover_consults_table_off_tpu(self):
        """Off-TPU auto must stay on the einsum path regardless of S — the
        crossover table only applies where the kernel can run natively."""
        from accelerate_tpu.ops.attention import _flash_supported

        q, k, v = _qkv(s=512, d=64)
        assert not _flash_supported(q, k, causal=True)
        out = dot_product_attention(q, k, v, causal=True, impl="auto")
        ref = _xla_attention(q, k, v, causal=True, mask=None, scale=None)
        assert bool(jnp.all(out == ref))

    def test_crossover_table_orders_sparsity(self):
        """Sparser masks cross over earlier: the block lattice drops tiles, so
        the kernel's streamed work shrinks while the einsum path does not."""
        from accelerate_tpu.ops.attention import ATTN_CROSSOVER_S

        for dkey in ("bf16", "f32"):
            assert (
                ATTN_CROSSOVER_S[(dkey, "window")]
                <= ATTN_CROSSOVER_S[(dkey, "causal")]
                <= ATTN_CROSSOVER_S[(dkey, "dense")]
            )

    def test_dot_product_attention_window_through_flash(self, interpret_mode):
        q, k, v = _qkv()
        out = dot_product_attention(q, k, v, causal=True, window=40, impl="flash")
        ref = _reference(q, k, v, causal=True, window=40)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)


# ---------------------------------------------------------------------------
# FP8 end-to-end: dtype_recipe="fp8" must keep the fused ZeRO-1 path ENGAGED
# (meta leaves ride as passthrough slots in the bucket plan instead of
# demoting the whole optimizer to the annotation path).


class TestFp8FusedZero1:
    def _reset(self):
        from accelerate_tpu.state import (
            AcceleratorState,
            GradientState,
            PartialState,
        )

        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()

    def _params(self):
        from accelerate_tpu.ops.fp8 import fp8_dense_init

        k = jax.random.split(jax.random.PRNGKey(0), 2)
        return {"l1": fp8_dense_init(k[0], 16, 32), "l2": fp8_dense_init(k[1], 32, 1)}

    @staticmethod
    def _loss(p, b):
        from accelerate_tpu.ops.fp8 import fp8_dense_apply

        h = jax.nn.relu(fp8_dense_apply(p["l1"], b["x"]))
        return jnp.mean((fp8_dense_apply(p["l2"], h) - b["y"]) ** 2)

    def _run(self, stage, steps=3, accum=1):
        import optax

        from accelerate_tpu import Accelerator, DeepSpeedPlugin

        self._reset()
        acc = Accelerator(
            cpu=True,
            mixed_precision="fp8",
            gradient_accumulation_steps=accum,
            deepspeed_plugin=DeepSpeedPlugin(zero_stage=stage),
            rng_seed=0,
        )
        params, opt = acc.prepare(self._params(), optax.adam(1e-2))
        step = acc.prepare_train_step(self._loss, opt)
        rng = np.random.default_rng(0)
        X = rng.normal(size=(64, 16)).astype(np.float32)
        batch = {
            "x": jnp.asarray(X),
            "y": jnp.asarray((X @ rng.normal(size=(16, 1))).astype(np.float32)),
        }
        s = opt.opt_state
        losses = []
        for _ in range(steps):
            params, s, m = step(params, s, batch)
            losses.append(float(m["loss"]))
        opt.opt_state = s
        return acc, opt, params, losses

    def test_plan_not_demoted_and_advertises_collectives(self):
        """The acceptance bar: fp8 meta must NOT clear the fused path. The
        plan keeps its bucket layout (meta leaves as passthrough slots) and
        still reports per-step collective bytes for telemetry."""
        acc, opt, params, _ = self._run(stage=1, steps=1)
        assert opt.fused_zero1
        plan = acc._sharding_plan
        assert plan.fused_zero1
        assert plan.zero1_collective_bytes() is not None
        assert plan.zero1.passthrough_indices  # the 6 meta history leaves
        assert len(plan.zero1.passthrough_indices) == 6

    def test_opt_state_is_one_over_n(self):
        acc, opt, _, _ = self._run(stage=1, steps=1)
        n = acc.mesh.shape["dp_replicate"]
        assert n == 8
        bucket_leaves = [
            x
            for x in jax.tree_util.tree_leaves(opt.opt_state)
            if hasattr(x, "addressable_shards")
            and getattr(x, "ndim", 0) == 1
            and any(ax is not None for ax in tuple(x.sharding.spec))
        ]
        assert bucket_leaves  # adam mu/nu buckets
        for leaf in bucket_leaves:
            shard = next(iter(leaf.addressable_shards))
            assert shard.data.size == leaf.size // n

    def test_parity_vs_unfused_baseline_and_meta_replacement(self):
        """Fused fp8 step vs the stage-0 (replicated, label-partitioned)
        baseline: same losses, params within the multichip tolerance, meta
        histories BITWISE equal (both sides install the same cotangent)."""
        from accelerate_tpu.ops.fp8 import META_KEY

        _, opt0, p0, l0 = self._run(stage=0)
        assert not opt0.fused_zero1
        _, opt1, p1, l1 = self._run(stage=1)
        assert opt1.fused_zero1
        for a, b in zip(l0, l1):
            assert abs(a - b) / max(abs(a), 1e-12) < 1.5e-7, (l0, l1)
        for name in ("l1", "l2"):
            np.testing.assert_allclose(
                np.asarray(p1[name]["kernel"]),
                np.asarray(p0[name]["kernel"]),
                atol=1e-7,
            )
            for hist in ("x_hist", "w_hist", "g_hist"):
                np.testing.assert_array_equal(
                    np.asarray(p1[name][META_KEY][hist]),
                    np.asarray(p0[name][META_KEY][hist]),
                )
            # histories actually rolled (replace-with-cotangent, not zeros)
            assert float(jnp.max(p1[name][META_KEY]["x_hist"])) > 0

    def test_accumulation_boundaries_under_fused_fp8(self):
        """MultiSteps wraps the BUCKETED inner tx: 4 micro-steps / accum 2 →
        2 optimizer steps, meta still rolling every micro-step."""
        from accelerate_tpu.optimizer import _find_multisteps_state
        from accelerate_tpu.ops.fp8 import META_KEY

        _, opt, params, _ = self._run(stage=1, steps=4, accum=2)
        assert opt.fused_zero1
        ms = _find_multisteps_state(opt.opt_state)
        assert ms is not None and int(ms.gradient_step) == 2
        assert float(jnp.max(params["l1"][META_KEY]["x_hist"])) > 0

    def test_llama_dtype_recipe_plan(self):
        """Model-level knob: a dtype_recipe='fp8' llama tree plans fused
        ZeRO-1 with every fp8_meta leaf passthrough, none bucketed."""
        from dataclasses import replace

        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from accelerate_tpu.models.transformer import LlamaConfig, init_llama
        from accelerate_tpu.ops.fp8 import META_KEY
        from accelerate_tpu.parallel.sharding import make_sharding_plan

        cfg = replace(LlamaConfig.tiny(), dtype_recipe="fp8")
        params = init_llama(cfg, jax.random.PRNGKey(0))
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("dp_replicate",))
        params = jax.device_put(params, NamedSharding(mesh, P()))
        plan = make_sharding_plan(params, mesh, zero1_axis="dp_replicate")
        assert plan.fused_zero1
        # 7 fp8 projections × 3 histories = 21 passthrough leaves
        assert len(plan.zero1.passthrough_indices) == 21
        paths, _ = jax.tree_util.tree_flatten_with_path(params)
        for i in plan.zero1.passthrough_indices:
            assert any(getattr(p, "key", None) == META_KEY for p in paths[i][0])
        bucketed = {s.leaf_index for s in plan.zero1.slots}
        assert not bucketed & set(plan.zero1.passthrough_indices)
